"""Fleet-scale serve resilience (ISSUE 16; docs/SERVING.md "Running a
fleet", docs/ROBUSTNESS.md "Fleet failures").

The contract under test:

* **placement** — rendezvous hashing: the same session key always
  lands on the same replica under a stable ring, and a replica
  join/leave moves only the minimal key share (keys on the departed
  replica / keys won by the new one);
* **health** — the HEALTHY -> SUSPECT -> DEAD machine with
  hysteresis: soft evidence (supervisor rebuild) suspends placement
  but never kills, only consecutive HARD evidence (missed scrapes,
  scheduler wedge) reaches DEAD, and DEAD is sticky;
* **merge exactness** — fleet-merged metrics equal what one process
  observing every request would have recorded (the PR-15 histogram
  contract), so fleet p99s are real percentiles, not averages of
  averages;
* **migration** — a session whose replica dies mid-stream resumes on
  a survivor from the shared journal + the router's tail buffer, with
  outputs parity-equal (<= 1e-4) to an uninterrupted run and zero
  lost or duplicated frames (the slow subprocess canary SIGKILLs a
  replica under a live client);
* **admission + autoscaling** — the fleet-wide watermark rejects new
  sessions 429-style with a predicted-wait hint, and the autoscaler
  spawns on backlog / drains on idle under a cooldown.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.config import CorrectorConfig
from kcmc_tpu.obs.latency import SegmentLatencies
from kcmc_tpu.serve.client import ServeClient, ServeError
from kcmc_tpu.serve.fleet import (
    DEAD,
    HEALTHY,
    SUSPECT,
    Replica,
    ReplicaHealth,
    merge_fleet_metrics,
    place,
    predicted_wait_s,
    rank,
)
from kcmc_tpu.serve.journal import journal_path, load_session_journal
from kcmc_tpu.serve.router import FleetRouter
from kcmc_tpu.serve.server import ServeServer
from kcmc_tpu.utils.faults import FatalFaultError, FaultPlan
from kcmc_tpu.utils.synthetic import make_drift_stack

TOL = 1e-4
MC_KW = dict(
    model="translation", backend="numpy", batch_size=8,
    max_keypoints=64, n_hypotheses=32,
)


def _stack(n=24, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


# -- fault grammar: the fleet surface ---------------------------------------


def test_fleet_fault_surface_grammar():
    plan = FaultPlan.from_spec("fleet:step=1:raise, fleet:stall=2")
    plan.maybe_fail("fleet", 0)
    with pytest.raises(FatalFaultError):
        plan.maybe_fail("fleet", 1)
    # stall clauses are consumed (scrape-stall injection)
    assert plan.take_stall("fleet", 5) == 2.0
    assert plan.take_stall("fleet", 6) == 0.0


# -- rendezvous placement ---------------------------------------------------


def test_placement_deterministic_and_order_independent():
    rids = [f"10.0.0.{i}:7733" for i in range(4)]
    keys = [f"sess-{i}" for i in range(64)]
    got = {k: place(k, rids) for k in keys}
    assert got == {k: place(k, list(reversed(rids))) for k in keys}
    # every replica should win SOME keys (64 keys over 4 replicas)
    assert set(got.values()) == set(rids)


def test_placement_leave_moves_only_departed_share():
    rids = [f"10.0.0.{i}:7733" for i in range(4)]
    keys = [f"sess-{i}" for i in range(200)]
    before = {k: place(k, rids) for k in keys}
    after = {k: place(k, rids[:3]) for k in keys}
    for k in keys:
        if before[k] != rids[3]:
            # keys NOT on the departed replica must not move
            assert after[k] == before[k]
        else:
            assert after[k] in rids[:3]


def test_placement_join_moves_only_won_share():
    rids = [f"10.0.0.{i}:7733" for i in range(4)]
    keys = [f"sess-{i}" for i in range(200)]
    before = {k: place(k, rids) for k in keys}
    after = {k: place(k, rids + ["10.0.0.9:7733"]) for k in keys}
    moved = [k for k in keys if after[k] != before[k]]
    assert moved, "a join should win some keys"
    assert all(after[k] == "10.0.0.9:7733" for k in moved)
    # roughly 1/5 of keys should move, never a wholesale reshuffle
    assert len(moved) < len(keys) // 2


def test_rank_is_a_full_deterministic_order():
    rids = [f"r{i}" for i in range(5)]
    order = rank("some-session", rids)
    assert sorted(order) == sorted(rids)
    assert order == rank("some-session", list(reversed(rids)))


# -- replica health machine -------------------------------------------------


def test_health_hard_evidence_ladder_and_hysteresis():
    h = ReplicaHealth(suspect_probes=2, dead_probes=3)
    assert h.state == HEALTHY
    h.observe(False, hard=True)
    assert h.state == HEALTHY  # one bad scrape is not evidence
    h.observe(False, hard=True)
    assert h.state == SUSPECT
    # recovery needs suspect_probes consecutive GOOD scrapes
    h.observe(True)
    assert h.state == SUSPECT
    h.observe(True)
    assert h.state == HEALTHY
    # and a single good scrape resets the bad streak
    h.observe(False, hard=True)
    h.observe(True)
    h.observe(True)
    for _ in range(3):
        h.observe(False, hard=True)
    assert h.state == DEAD
    h.observe(True)
    assert h.state == DEAD  # sticky: a dead replica never self-heals


def test_health_soft_evidence_never_kills():
    h = ReplicaHealth(suspect_probes=2, dead_probes=3)
    for _ in range(20):
        h.observe(False, hard=False)
    # a replica mid-rebuild is suspended from placement, not killed
    assert h.state == SUSPECT


# -- merge exactness --------------------------------------------------------


def test_merge_fleet_metrics_is_exact():
    """Merging per-replica exports must reproduce what ONE process
    observing every request would have recorded — summaries equal to
    the digit."""
    rng = np.random.default_rng(3)
    a, b, union = (
        SegmentLatencies(), SegmentLatencies(), SegmentLatencies(),
    )
    for i, v in enumerate(rng.uniform(1e-4, 0.5, size=200)):
        (a if i % 2 else b).observe("request.total", float(v))
        union.observe("request.total", float(v))
    merged = merge_fleet_metrics(
        {
            "r-a:1": {"plane": {"histograms": a.hist_dicts()},
                      "counters": {"frames_done": 100},
                      "gauges": {"queued_frames": 3}},
            "r-b:2": {"plane": {"histograms": b.hist_dicts()},
                      "counters": {"frames_done": 50},
                      "gauges": {"queued_frames": 4}},
        }
    )
    want = union.report()["totals"]["request.total"]
    assert merged["plane"]["totals"]["request.total"] == want
    assert merged["counters"]["frames_done"] == 150
    assert merged["gauges"]["queued_frames"] == 7
    assert merged["fleet"]["n_replicas"] == 2
    assert merged["fleet"]["n_healthy"] == 2


def test_predicted_wait_hint():
    sl = SegmentLatencies()
    for _ in range(10):
        sl.observe("request.total", 0.1)
    merged = merge_fleet_metrics(
        {"r:1": {"plane": {"histograms": sl.hist_dicts()}}}
    )
    hint = predicted_wait_s(merged, queued=100, capacity=100)
    assert hint is not None and hint > 0.1  # scaled by backlog
    assert predicted_wait_s({}, 10, 100) is None  # no history -> None
    assert predicted_wait_s(merged, 10, 0) is None


def test_top_renders_fleet_block():
    from kcmc_tpu.obs.top import _merge_stats, render

    sl = SegmentLatencies()
    sl.observe("request.total", 0.02)
    merged = merge_fleet_metrics(
        {"10.0.0.1:7733": {"plane": {"histograms": sl.hist_dicts()},
                           "gauges": {"sessions_open": 2}}},
        states={"10.0.0.1:7733": HEALTHY, "10.0.0.2:7733": DEAD},
    )
    out = render(merged, _merge_stats({}), "fleet(2)")
    assert "fleet: 2 replicas, 1 healthy" in out
    assert "10.0.0.2:7733" in out and "DEAD" in out


# -- in-process fleet: proxying + migration ---------------------------------


def _inproc_fleet(tmp_path, n=2, **cfg_kw):
    jdir = str(tmp_path / "journals")
    servers = []
    for _ in range(n):
        mc = MotionCorrector(
            serve_journal_dir=jdir, serve_journal_every=4, **MC_KW
        )
        servers.append(ServeServer(mc, port=0).start())
    reps = [Replica("127.0.0.1", s.port) for s in servers]
    cfg = CorrectorConfig(
        fleet_probe_interval_s=cfg_kw.pop("probe_interval", 0.2),
        **cfg_kw,
    )
    router = FleetRouter(reps, port=0, config=cfg, journal_dir=jdir).start()
    return servers, reps, router, jdir


def test_router_proxies_a_full_stream_parity_exact(tmp_path):
    stack = _stack(24, seed=4)
    truth = MotionCorrector(**MC_KW).correct(stack)
    servers, reps, router, _ = _inproc_fleet(tmp_path, n=2)
    try:
        with ServeClient(port=router.port) as c:
            assert c.ping()
            sid = c.open_session(tenant="t", session_id="P1")
            c.submit(sid, stack[:12])
            c.submit(sid, stack[12:])
            out = c.close_session(sid)
            m = c.metrics()
            st = c.stats()
        assert out["frames"] == 24
        assert np.abs(out["transforms"] - truth.transforms).max() < TOL
        assert st["router"] is True and st["sessions_routed"] == 1
        assert m["fleet"]["n_replicas"] == 2
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_migration_on_replica_death_is_parity_exact(tmp_path):
    """Kill (gracefully drain, which journals) the bound replica
    mid-stream; the router must migrate the session to the survivor
    on the next forward and the stream finishes parity-exact with
    zero client-visible errors."""
    stack = _stack(24, seed=5)
    truth = MotionCorrector(**MC_KW).correct(stack)
    servers, reps, router, jdir = _inproc_fleet(tmp_path, n=2)
    by_rid = {r.rid: s for r, s in zip(reps, servers)}
    try:
        with ServeClient(port=router.port) as c:
            sid = c.open_session(tenant="t", session_id="M1")
            c.submit(sid, stack[:12])
            jp = journal_path(jdir, sid)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30.0:
                if os.path.exists(jp):
                    got = load_session_journal(jp)
                    if got and int(got[0]["done"]) >= 4:
                        break
                time.sleep(0.05)
            else:
                raise AssertionError("journal never became durable")
            victim_rid = router.stats()["sessions"][sid]
            by_rid[victim_rid].stop()  # drains + journals, then dies
            c.submit(sid, stack[12:])  # forward fails -> migrate
            out = c.close_session(sid)
        st = router.stats()
        assert out["frames"] == 24
        assert np.abs(out["transforms"] - truth.transforms).max() < TOL
        assert st["migrations_total"] == 1
        assert st["sessions"] == {}  # closed sessions unbind
        # the migration span reached the router's own telemetry
        mig = router.fleet_metrics()["plane"]["totals"]["fleet.migrate"]
        assert mig["count"] == 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_admission_watermark_rejects_with_hint():
    r1 = Replica("127.0.0.1", 1, ready={"queue_depth": 64})
    sl = SegmentLatencies()
    sl.observe("request.total", 0.2)
    r1.last_metrics = {
        "plane": {"histograms": sl.hist_dicts()},
        "gauges": {"queued_frames": 60},
    }
    router = FleetRouter(
        [r1], port=0,
        config=CorrectorConfig(fleet_queue_watermark=0.5),
    )
    try:
        resp = router._admission_reject()
        assert resp is not None and resp["code"] == 429
        assert resp["queued"] == 60 and resp["limit"] == 32
        assert resp["predicted_wait_s"] > 0
        assert router.stats()["sessions_rejected"] == 1
        # watermark 1.0 disables admission control entirely
        router.config = CorrectorConfig(fleet_queue_watermark=1.0)
        assert router._admission_reject() is None
    finally:
        router._tcp.server_close()  # never started; close the socket


def test_client_budget_caps_whole_round_trip(tmp_path):
    """The metrics/stats `timeout=` satellite: the budget bounds the
    WHOLE verb round-trip (reconnect attempts included), so a prober
    can never be held past its scrape budget by a dead replica."""
    mc = MotionCorrector(**MC_KW)
    srv = ServeServer(mc, port=0).start()
    c = ServeClient(port=srv.port, reconnect_backoff_s=2.0)
    try:
        assert c.metrics(timeout=5.0)["schema"] == "kcmc_metrics/1"
        srv.stop()
        c.disconnect()  # force the reconnect path against a dead addr
        t0 = time.monotonic()
        with pytest.raises(ServeError) as ei:
            c.metrics(timeout=0.5)
        assert ei.value.code == 503
        # budget 0.5s must beat the un-budgeted backoff schedule (2s+)
        assert time.monotonic() - t0 < 2.0
    finally:
        c.close()
        srv.stop()


# -- autoscaler -------------------------------------------------------------


class _FakeRouter:
    def __init__(self):
        self.config = CorrectorConfig()
        self.load = {
            "queued_frames": 0, "capacity": 100, "n_live": 1,
            "n_owned": 1, "e2e_p99_s": None,
        }
        self.added: list = []
        self.drained: list = []

    def fleet_load(self):
        return dict(self.load)

    def add_replica(self, r):
        self.added.append(r)
        self.load["n_live"] += 1
        self.load["n_owned"] += 1

    def drain_replica(self, rid):
        self.drained.append(rid)
        self.load["n_live"] -= 1
        self.load["n_owned"] -= 1
        return {"replica": rid, "migrated": [], "failed": []}

    def stats(self):
        return {
            "replicas": {
                f"r{i}": {
                    "spawned": True, "state": HEALTHY, "sessions": i
                }
                for i in range(self.load["n_owned"])
            }
        }


def test_autoscaler_spawns_on_backlog_drains_on_idle():
    from types import SimpleNamespace

    from kcmc_tpu.serve.autoscale import Autoscaler

    router = _FakeRouter()
    scaler = Autoscaler(
        router, spawn_fn=lambda: SimpleNamespace(rid="new"),
        min_replicas=1, max_replicas=2, cooldown_s=0.0,
    )
    router.load["queued_frames"] = 80  # 0.8 > scale_up_at=0.5
    act = scaler.tick()
    assert act["action"] == "spawn" and len(router.added) == 1
    # at the ceiling: hot load does nothing more
    router.load["queued_frames"] = 90
    assert scaler.tick() is None
    # idle: drain the emptiest spawned replica, down to the floor
    router.load["queued_frames"] = 0
    act = scaler.tick()
    assert act["action"] == "drain" and router.drained == ["r0"]
    assert scaler.tick() is None  # at the floor


def test_autoscaler_cooldown_blocks_flapping():
    from types import SimpleNamespace

    from kcmc_tpu.serve.autoscale import Autoscaler

    router = _FakeRouter()
    scaler = Autoscaler(
        router, spawn_fn=lambda: SimpleNamespace(rid="new"),
        min_replicas=1, max_replicas=4, cooldown_s=300.0,
    )
    router.load["queued_frames"] = 80
    assert scaler.tick() is not None
    assert scaler.tick() is None  # cooldown armed
    assert len(router.added) == 1


def test_autoscaler_validates_bounds():
    from kcmc_tpu.serve.autoscale import Autoscaler

    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(_FakeRouter(), spawn_fn=None, min_replicas=3,
                   max_replicas=2)
    with pytest.raises(ValueError, match="scale_down_at"):
        Autoscaler(_FakeRouter(), spawn_fn=None, scale_up_at=0.2,
                   scale_down_at=0.3)


# -- fleet config knobs -----------------------------------------------------


def test_fleet_config_validation():
    CorrectorConfig(fleet_probe_interval_s=0.5, fleet_suspect_probes=1,
                    fleet_dead_probes=1)
    with pytest.raises(ValueError, match="fleet_probe_interval_s"):
        CorrectorConfig(fleet_probe_interval_s=0.0)
    with pytest.raises(ValueError, match="fleet_dead_probes"):
        CorrectorConfig(fleet_suspect_probes=3, fleet_dead_probes=2)
    with pytest.raises(ValueError, match="fleet_queue_watermark"):
        CorrectorConfig(fleet_queue_watermark=1.5)
    with pytest.raises(ValueError, match="fleet_scale_cooldown_s"):
        CorrectorConfig(fleet_scale_cooldown_s=-1.0)


# -- subprocess canary: SIGKILL under a live client -------------------------


def _spawn_replica_proc(jdir):
    from kcmc_tpu.serve.fleet import spawn_replica

    return spawn_replica(
        [
            "--port", "0", "--backend", "numpy",
            "--batch-size", "8", "--max-keypoints", "64",
            "--hypotheses", "32",
            "--journal-dir", jdir, "--journal-every", "4",
        ],
        env={"JAX_PLATFORMS": "cpu"},
    )


@pytest.mark.slow
def test_kill9_replica_mid_stream_migrates_parity_exact(tmp_path):
    """THE fleet acceptance canary: SIGKILL 1 of 3 replicas while a
    client is mid-stream through the router. The router must detect
    the death, migrate the session to a survivor (journal + tail
    replay), and the stream finishes with zero lost/duplicated frames
    and parity <= 1e-4 against an uninterrupted run — the client sees
    only a bounded retry."""
    stack = _stack(24, seed=10)
    truth = MotionCorrector(**MC_KW).correct(stack)
    jdir = str(tmp_path / "journals")
    os.makedirs(jdir, exist_ok=True)
    replicas = [_spawn_replica_proc(jdir) for _ in range(3)]
    router = FleetRouter(replicas, port=0, journal_dir=jdir).start()
    try:
        with ServeClient(port=router.port) as c:
            sid = c.open_session(tenant="canary", session_id="K1")
            c.submit(sid, stack[:16])
            jp = journal_path(jdir, sid)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 60.0:
                if os.path.exists(jp):
                    got = load_session_journal(jp)
                    if got and int(got[0]["done"]) >= 4:
                        break
                time.sleep(0.05)
            else:
                raise AssertionError("journal never became durable")
            victim_rid = router.stats()["sessions"][sid]
            victim = next(r for r in replicas if r.rid == victim_rid)
            os.kill(victim.proc.pid, signal.SIGKILL)
            victim.proc.wait(timeout=30)
            c.submit(sid, stack[16:])
            delivered = 0
            while delivered < 24:
                span = c.results(sid, timeout=60.0)
                assert span is not None
                assert int(span["first_frame"]) == delivered, (
                    "lost or duplicated frames across the migration"
                )
                delivered += int(span["n"])
            out = c.close_session(sid)
        st = router.stats()
        assert out["frames"] == 24
        assert np.abs(out["transforms"] - truth.transforms).max() < TOL
        assert st["migrations_total"] >= 1
        assert st["migration_failures"] == 0
    finally:
        router.stop(stop_owned=True)


@pytest.mark.slow
def test_router_cli_ready_line_and_clean_shutdown(tmp_path):
    """`kcmc_tpu router --spawn 2` boots a fleet, prints a machine-
    readable ready line, serves a stream end to end, and SIGTERM
    drains to a final `{"routed": true}` record."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kcmc_tpu", "router",
            "--port", "0", "--spawn", "2",
            "--journal-dir", str(tmp_path / "journals"),
            "--serve-args",
            "--backend numpy --batch-size 8 "
            "--max-keypoints 64 --hypotheses 32",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=env,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["routing"] is True and len(ready["replicas"]) == 2
        stack = _stack(16, seed=11)
        with ServeClient(port=ready["port"]) as c:
            sid = c.open_session(tenant="cli", session_id="C1")
            c.submit(sid, stack)
            out = c.close_session(sid)
            assert out["frames"] == 16
        proc.send_signal(signal.SIGTERM)
        final = json.loads(proc.stdout.readline())
        assert final["routed"] is True
        assert final["stats"]["sessions_routed"] == 1
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
