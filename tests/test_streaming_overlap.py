"""Zero-stall streaming: device-resident rolling templates, overlapped
writeback, and pipeline-stall telemetry (round 6).

Contracts under test:

* with a backend implementing the `update_reference` seam, segment
  boundaries neither flush the in-flight dispatch window nor round-trip
  the template through host numpy — `prepare_reference` (the host seam)
  runs exactly once per run and the pipeline drains exactly once;
* device-path rolling results match the legacy host blend path within
  float32 reduction-order tolerance (bit-identical on the numpy
  backend, whose seam mirrors the host math exactly);
* output writeback runs on a bounded background thread: ordered,
  backpressured, exception-surfacing, and checkpoint-synchronized
  (kill/resume output stays byte-identical);
* `timing` and the CLI summary report the per-seam stall accounting.
"""

import json
import time

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import AsyncBatchWriter, ChunkedStackLoader
from kcmc_tpu.io.tiff import TiffWriter, write_stack
from kcmc_tpu.utils import synthetic

SHAPE = (64, 64)
T = 32
E = 8  # template_update_every


@pytest.fixture(scope="module")
def drifting():
    rng = np.random.default_rng(7)
    scene = synthetic.render_scene(rng, SHAPE, n_blobs=60)
    drift = np.cumsum(rng.uniform(-0.8, 0.8, size=(T, 2)), axis=0)
    mats = np.tile(np.eye(3, dtype=np.float32), (T, 1, 1))
    mats[:, :2, 2] = drift
    frames = [synthetic._warp_scene(scene, m) for m in mats]
    return np.stack(frames).astype(np.float32), mats


def mk(backend="jax", **kw):
    return MotionCorrector(
        model="translation", backend=backend, batch_size=4,
        template_update_every=E, template_window=8, **kw,
    )


# -- device-resident rolling templates ----------------------------------


def test_boundaries_skip_host_prepare_and_pipeline_flush(drifting):
    """The zero-stall acceptance counters: ONE host prepare_reference
    (the initial template), one update_reference per interior boundary,
    ONE pipeline drain-flush for the whole run (the final one)."""
    stack, _ = drifting
    mc = mk()
    host_prepares, updates = [], []
    orig_prep = mc.backend.prepare_reference
    orig_up = mc.backend.update_reference

    def spy_prep(frame):
        if isinstance(frame, np.ndarray):  # host template round trip
            host_prepares.append(1)
        return orig_prep(frame)

    def spy_up(*a, **kw):
        updates.append(1)
        return orig_up(*a, **kw)

    mc.backend.prepare_reference = spy_prep
    mc.backend.update_reference = spy_up
    res = mc.correct(stack)
    assert len(host_prepares) == 1
    assert len(updates) == T // E - 1
    pipe = res.timing["pipeline"]
    assert pipe["device_templates"] is True
    assert pipe["template_updates"] == T // E - 1
    assert pipe["drain_flushes"] == 1


def test_host_path_flushes_every_segment(drifting):
    stack, _ = drifting
    res = mk(device_templates=False).correct(stack)
    pipe = res.timing["pipeline"]
    assert pipe["device_templates"] is False
    assert pipe["template_updates"] == T // E - 1
    assert pipe["drain_flushes"] == T // E  # legacy: drain per segment


def test_device_path_matches_host_blend(drifting):
    stack, mats = drifting
    dev = mk().correct(stack)
    host = mk(device_templates=False).correct(stack)
    np.testing.assert_allclose(
        dev.transforms, host.transforms, atol=1e-3
    )


def test_numpy_backend_seam_is_bit_identical(drifting):
    """NumpyBackend.update_reference mirrors the legacy host blend
    exactly — same math, same order — so routing through the seam must
    not move a single bit."""
    stack, _ = drifting
    a = mk(backend="numpy").correct(stack)
    b = mk(backend="numpy", device_templates=False).correct(stack)
    np.testing.assert_array_equal(a.transforms, b.transforms)


def test_streaming_matches_memory_on_device_path(drifting, tmp_path):
    stack, _ = drifting
    path = tmp_path / "in.tif"
    write_stack(path, stack)
    mem = mk().correct(stack)
    stream = mk().correct_file(path, chunk_size=16)
    np.testing.assert_allclose(stream.transforms, mem.transforms, atol=1e-5)
    st = stream.timing["stalls_s"]
    assert "template_update" in st and "drain_sync" in st
    assert stream.timing["pipeline"]["device_templates"] is True


def test_registration_only_rolling_device_path(drifting, tmp_path):
    """emit_frames=False + device templates: the averaging window feeds
    the device tail (never materialized on host) and transforms match
    the frame-emitting run."""
    stack, _ = drifting
    path = tmp_path / "in.tif"
    write_stack(path, stack)
    full = mk().correct_file(path, chunk_size=16)
    reg = mk().correct_file(path, chunk_size=16, emit_frames=False)
    assert reg.corrected.shape[0] == 0
    np.testing.assert_allclose(reg.transforms, full.transforms, atol=1e-5)


def test_template_window_must_cover_a_frame():
    with pytest.raises(ValueError, match="template_window"):
        MotionCorrector(template_window=0)


# -- overlapped writeback ------------------------------------------------


class _StubWriter:
    """Minimal TiffWriter-protocol stub with optional slowness/failure."""

    def __init__(self, delay=0.0, fail_at=None):
        self.pages = []
        self.delay = delay
        self.fail_at = fail_at
        self.closed = False
        self.n_pages = 0

    def append_batch(self, frames, n_threads=0):
        if self.delay:
            time.sleep(self.delay)
        if self.fail_at is not None and self.n_pages >= self.fail_at:
            raise OSError("disk full (simulated)")
        self.pages.append(np.array(frames))
        self.n_pages += len(frames)

    def checkpoint_state(self):
        return {"n_pages": self.n_pages}

    def close(self):
        self.closed = True


def test_async_writer_ordered_and_checkpoint_synchronized():
    inner = _StubWriter(delay=0.005)
    w = AsyncBatchWriter(inner, depth=2)
    batches = [np.full((2, 4, 4), i, np.float32) for i in range(6)]
    for b in batches:
        w.append_batch(b)
    # checkpoint_state flushes: the state IS the durable high-water mark
    assert w.checkpoint_state() == {"n_pages": 12}
    w.close()
    np.testing.assert_array_equal(
        np.concatenate(inner.pages), np.concatenate(batches)
    )
    assert inner.closed
    assert w.stats()["batches"] == 6


def test_async_writer_backpressure_bounded_and_recorded():
    inner = _StubWriter(delay=0.03)
    w = AsyncBatchWriter(inner, depth=1)
    for _ in range(4):
        w.append_batch(np.zeros((1, 2, 2), np.float32))
    w.close()
    assert inner.n_pages == 4
    assert w.stats()["backpressure_s"] > 0


def test_async_writer_surfaces_worker_exception():
    inner = _StubWriter(fail_at=2)
    w = AsyncBatchWriter(inner, depth=2)
    with pytest.raises(OSError, match="disk full"):
        for _ in range(10):
            w.append_batch(np.zeros((2, 2, 2), np.float32))
            time.sleep(0.01)
    w.close()  # already-surfaced failure: close is clean
    assert inner.closed


def test_correct_file_surfaces_write_failure(drifting, tmp_path, monkeypatch):
    stack, _ = drifting
    path = tmp_path / "in.tif"
    write_stack(path, stack)

    def boom(self, frames, n_threads=0):
        raise OSError("no space left (simulated)")

    monkeypatch.setattr(TiffWriter, "append_batch", boom)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    with pytest.raises(OSError, match="no space left"):
        mc.correct_file(path, output=str(tmp_path / "out.tif"))


class _PoisonAfter:
    def __init__(self, allow):
        self.allow = allow
        self.calls = 0

    def __call__(self, orig, loader, lo, hi):
        self.calls += 1
        if self.calls > self.allow:
            raise RuntimeError("simulated kill")
        return orig(loader, lo, hi)


@pytest.mark.slow
def test_slow_writer_kill_resume_byte_identical(
    drifting, tmp_path, monkeypatch
):
    """Backpressured background writer + mid-run kill + resume: the
    resumed output must stay byte-identical (the checkpoint can only
    claim frames the writer made durable)."""
    stack, _ = drifting
    u16 = np.clip(stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)
    orig_append = TiffWriter.append_batch
    monkeypatch.setattr(
        TiffWriter, "append_batch",
        lambda self, frames, n_threads=0: (
            time.sleep(0.01),
            orig_append(self, frames, n_threads=n_threads),
        )[1],
    )
    orig_read = ChunkedStackLoader._read

    def run(output, checkpoint=None, poison=None):
        mc = mk()
        if poison is not None:
            monkeypatch.setattr(
                ChunkedStackLoader, "_read",
                lambda self, lo, hi: poison(orig_read, self, lo, hi),
            )
        else:
            monkeypatch.setattr(ChunkedStackLoader, "_read", orig_read)
        return mc.correct_file(
            str(src), output=str(output), chunk_size=8,
            checkpoint=checkpoint and str(checkpoint), checkpoint_every=8,
        )

    ref = run(tmp_path / "ref.tif")
    assert "writer_backpressure" in ref.timing["stalls_s"]
    ckpt = tmp_path / "run.ckpt.npz"
    out = tmp_path / "out.tif"
    with pytest.raises(RuntimeError, match="simulated kill"):
        run(out, checkpoint=ckpt, poison=_PoisonAfter(2))
    res = run(out, checkpoint=ckpt)
    assert (tmp_path / "ref.tif").read_bytes() == out.read_bytes()
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-6)


@pytest.mark.slow
def test_mid_segment_saves_pair_the_governing_template(
    drifting, tmp_path, monkeypatch
):
    """Zero-stall runs reach checkpoint saves while the CURRENT template
    is already a segment ahead of the drained cursor; the save must pair
    the cursor with the template that governs a resume there
    (corrector._tmpl_at_cursor). W < E opens mid-segment save windows."""
    stack, _ = drifting
    u16 = np.clip(stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)
    orig_read = ChunkedStackLoader._read

    def run(output, checkpoint=None, poison=None):
        mc = MotionCorrector(
            model="translation", backend="jax", batch_size=2,
            template_update_every=E, template_window=4,
        )
        if poison is not None:
            monkeypatch.setattr(
                ChunkedStackLoader, "_read",
                lambda self, lo, hi: poison(orig_read, self, lo, hi),
            )
        else:
            monkeypatch.setattr(ChunkedStackLoader, "_read", orig_read)
        return mc.correct_file(
            str(src), output=str(output), chunk_size=4,
            checkpoint=checkpoint and str(checkpoint), checkpoint_every=2,
        )

    ref = run(tmp_path / "ref.tif")
    ckpt = tmp_path / "run.ckpt.npz"
    out = tmp_path / "out.tif"
    with pytest.raises(RuntimeError, match="simulated kill"):
        run(out, checkpoint=ckpt, poison=_PoisonAfter(3))
    res = run(out, checkpoint=ckpt)
    assert (tmp_path / "ref.tif").read_bytes() == out.read_bytes()
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-6)


# -- telemetry surfacing -------------------------------------------------


def test_cli_summary_reports_stalls(drifting, tmp_path, capsys):
    stack, _ = drifting
    src = tmp_path / "in.tif"
    write_stack(src, np.clip(stack * 40000, 0, 65535).astype(np.uint16))
    from kcmc_tpu.__main__ import main

    rc = main([
        "correct", str(src), "-o", str(tmp_path / "o.tif"),
        "--batch-size", "4", "--template-update", str(E),
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "stalls_s" in summary
    assert "writer_backpressure" in summary["stalls_s"]
    assert summary["pipeline"]["device_templates"] is True
