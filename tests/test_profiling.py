"""Profiling utilities (no mesh needed)."""


def test_profiling_stage_breakdown_cpu():
    from kcmc_tpu.utils.profiling import honest_time, stage_breakdown

    import jax.numpy as jnp
    import jax

    t = honest_time(jax.jit(lambda x: (x * 2).sum()), jnp.ones((64, 64)), iters=3)
    assert t >= 0.0
    rep = stage_breakdown(shape=(96, 96), batch_size=4, iters=2, max_keypoints=64)
    assert set(rep) == {"detect", "describe", "match", "consensus", "full (+warp)",
                        "frames_per_sec"}
    assert rep["frames_per_sec"] > 0


def test_honest_time_forces_execution():
    """honest_time must return a sane per-call cost for a jitted fn."""
    import jax
    import jax.numpy as jnp

    from kcmc_tpu.utils.profiling import honest_time

    f = jax.jit(lambda x: jnp.sum(x * 2.0))
    x = jnp.ones((256, 256))
    t = honest_time(f, x, iters=4, min_warmup_s=0.05)
    assert 0 < t < 5.0


def test_stage_breakdown_structure():
    """stage_breakdown reports cumulative+incremental ms per stage and a
    throughput figure; cumulative must be nondecreasing-ish and the full
    program must dominate single stages."""
    from kcmc_tpu.utils.profiling import stage_breakdown

    rep = stage_breakdown(
        model="translation", shape=(96, 96), batch_size=4, iters=2,
        max_keypoints=64,
    )
    stages = ["detect", "describe", "match", "consensus", "full (+warp)"]
    for s in stages:
        assert set(rep[s]) == {"cumulative_ms", "incremental_ms"}
        assert rep[s]["cumulative_ms"] > 0
    assert rep["frames_per_sec"] > 0
    # prefix programs are supersets: describe includes detect etc.
    # (clock noise can wobble single measurements; assert the big
    # relation only: the full pipeline costs at least half the
    # detect-only prefix — a sanity floor, not a microbenchmark)
    assert rep["full (+warp)"]["cumulative_ms"] > 0.5 * rep["detect"]["cumulative_ms"]


def test_stage_breakdown_rejects_non_matrix_models():
    import pytest

    from kcmc_tpu.utils.profiling import stage_breakdown

    with pytest.raises(ValueError, match="piecewise"):
        stage_breakdown(model="piecewise")
