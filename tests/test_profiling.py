"""Profiling utilities (no mesh needed)."""


def test_profiling_stage_breakdown_cpu():
    from kcmc_tpu.utils.profiling import honest_time, stage_breakdown

    import jax.numpy as jnp
    import jax

    t = honest_time(jax.jit(lambda x: (x * 2).sum()), jnp.ones((64, 64)), iters=3)
    assert t >= 0.0
    rep = stage_breakdown(shape=(96, 96), batch_size=4, iters=2, max_keypoints=64)
    assert set(rep) == {"detect", "describe", "match", "consensus", "full (+warp)",
                        "frames_per_sec"}
    assert rep["frames_per_sec"] > 0
