"""Rolling template updates (template_update_every): long recordings
whose scene slowly changes.

Contract under test: the template tracks the scene (registration keeps
working where a frozen frame-0 template loses its matches), updates
happen at ABSOLUTE frame boundaries (results independent of batch size
and of the memory vs streaming path), and checkpoint resume restores
the evolving template for byte-identical streaming output.
"""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import ChunkedStackLoader
from kcmc_tpu.io.tiff import write_stack
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (128, 128)
T = 48


def _morphing_stack(seed=3):
    """Scene A cross-fades to a completely different scene B while the
    whole stack drifts — frame-0 keypoints no longer exist by the end."""
    rng = np.random.default_rng(seed)
    a = synthetic.render_scene(rng, SHAPE, n_blobs=120)
    b = synthetic.render_scene(rng, SHAPE, n_blobs=120)
    drift = np.cumsum(rng.uniform(-1.2, 1.2, size=(T, 2)), axis=0)
    mats = np.tile(np.eye(3, dtype=np.float32), (T, 1, 1))
    mats[:, :2, 2] = drift
    frames = []
    for t in range(T):
        w = t / (T - 1)
        frames.append(synthetic._warp_scene((1 - w) * a + w * b, mats[t]))
    return np.stack(frames).astype(np.float32), mats


@pytest.fixture(scope="module")
def morphing():
    return _morphing_stack()


def _rmse(transforms, mats):
    return transform_rmse(transforms, relative_transforms(mats), SHAPE)


def test_rolling_template_tracks_scene_change(morphing):
    stack, mats = morphing
    static = MotionCorrector(
        model="translation", backend="jax", batch_size=8
    ).correct(stack)
    rolling = MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        template_update_every=8, template_window=8,
        template_update_alpha=0.7,
    ).correct(stack)
    # By the cross-fade's end the frozen template has lost its scene;
    # the rolling template still matches it.
    tail = np.s_[T - 8 :]
    static_tail = np.asarray(static.diagnostics["n_inliers"][tail])
    rolling_tail = np.asarray(rolling.diagnostics["n_inliers"][tail])
    assert rolling_tail.min() > 2 * max(static_tail.min(), 1)
    assert _rmse(rolling.transforms, mats) < 0.25
    assert _rmse(rolling.transforms, mats) < 0.5 * _rmse(
        static.transforms, mats
    )


def test_update_boundaries_are_batch_size_invariant(morphing):
    stack, mats = morphing
    mk = lambda B: MotionCorrector(
        model="translation", backend="jax", batch_size=B,
        template_update_every=8, template_window=8,
    ).correct(stack)
    np.testing.assert_allclose(
        mk(4).transforms, mk(8).transforms, atol=1e-5
    )


def test_correct_file_matches_in_memory(morphing, tmp_path):
    stack, mats = morphing
    path = tmp_path / "morph.tif"
    write_stack(path, stack)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        template_update_every=8, template_window=8,
    )
    mem = mk().correct(stack)
    stream = mk().correct_file(path, chunk_size=16)
    np.testing.assert_allclose(stream.transforms, mem.transforms, atol=1e-5)


def test_window_not_batch_aligned_paths_agree(morphing, tmp_path):
    """template_window smaller than (and unaligned with) the batch:
    the streaming tail buffer trims at batch granularity but the blend
    must slice frame-exactly, or memory/streaming templates diverge."""
    stack, _ = morphing
    path = tmp_path / "morph.tif"
    write_stack(path, stack)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        template_update_every=8, template_window=6,
    )
    mem = mk().correct(stack)
    stream = mk().correct_file(path, chunk_size=16)
    np.testing.assert_allclose(stream.transforms, mem.transforms, atol=1e-5)


def test_transforms_independent_of_output_dtype(morphing, tmp_path):
    """The rolling template must blend unrounded float32 pixels: a
    uint16 output format must not perturb the recovered transforms."""
    stack, _ = morphing
    u16 = np.clip(stack * 40000, 0, 65535).astype(np.uint16)
    path = tmp_path / "morph16.tif"
    write_stack(path, u16)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        template_update_every=8, template_window=8,
    )
    as_u16 = mk().correct_file(
        path, output=str(tmp_path / "o16.tif"), output_dtype="input"
    )
    as_f32 = mk().correct_file(
        path, output=str(tmp_path / "of.tif"), output_dtype="float32"
    )
    np.testing.assert_allclose(
        as_u16.transforms, as_f32.transforms, atol=1e-6
    )
    mem = mk().correct(u16)  # default float32 output
    np.testing.assert_allclose(mem.transforms, as_f32.transforms, atol=1e-5)


def test_registration_only_composes_with_rolling_updates(morphing, tmp_path):
    """emit_frames=False + rolling updates: identical transforms to the
    frame-emitting run (only the averaging windows transfer), with no
    corrected frames returned."""
    stack, _ = morphing
    path = tmp_path / "m.tif"
    write_stack(path, stack)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        template_update_every=8, template_window=8,
    )
    full = mk().correct_file(path, chunk_size=16)
    reg = mk().correct_file(path, chunk_size=16, emit_frames=False)
    assert reg.corrected.shape[0] == 0
    np.testing.assert_allclose(reg.transforms, full.transforms, atol=1e-5)


def test_sharded_rolling_matches_single_device(morphing):
    """Rolling updates re-prepare the reference mid-run; the sharded
    path must re-shard it and keep reproducing single-device results."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from kcmc_tpu.parallel import make_mesh

    stack, mats = morphing
    mk = lambda mesh: MotionCorrector(
        model="translation", backend="jax", batch_size=8, mesh=mesh,
        template_update_every=8, template_window=8,
    )
    r1 = mk(None).correct(stack)
    r8 = mk(make_mesh(8)).correct(stack)
    np.testing.assert_allclose(r8.transforms, r1.transforms, atol=1e-4)
    assert _rmse(r8.transforms, mats) < 0.25


def test_constructor_validation():
    with pytest.raises(ValueError, match="template_update_every"):
        MotionCorrector(template_update_every=-1)
    with pytest.raises(ValueError, match="template_update_alpha"):
        MotionCorrector(template_update_alpha=0.0)


class _PoisonAfter:
    def __init__(self, allow):
        self.allow = allow
        self.calls = 0

    def __call__(self, orig, loader, lo, hi):
        self.calls += 1
        if self.calls > self.allow:
            raise RuntimeError("simulated kill")
        return orig(loader, lo, hi)


def test_rolling_resume_byte_identical(morphing, tmp_path, monkeypatch):
    """Kill mid-run + resume with rolling updates on: the checkpoint
    restores the evolving template, and the resumed output TIFF is
    byte-identical to an uninterrupted run's."""
    from kcmc_tpu.utils.checkpoint import load_stream_checkpoint

    stack, _ = morphing
    u16 = np.clip(stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)
    orig = ChunkedStackLoader._read

    def run(output, checkpoint=None, poison=None):
        mc = MotionCorrector(
            model="translation", backend="jax", batch_size=4,
            template_update_every=8, template_window=8,
        )
        if poison is not None:
            monkeypatch.setattr(
                ChunkedStackLoader, "_read",
                lambda self, lo, hi: poison(orig, self, lo, hi),
            )
        else:
            monkeypatch.setattr(ChunkedStackLoader, "_read", orig)
        return mc.correct_file(
            str(src), output=str(output), chunk_size=8,
            checkpoint=checkpoint and str(checkpoint),
            # boundary saves honor the requested cadence (they no
            # longer fire unconditionally at every template boundary)
            checkpoint_every=8,
        )

    ref = run(tmp_path / "ref.tif")

    ckpt = tmp_path / "run.ckpt.npz"
    out = tmp_path / "out.tif"
    with pytest.raises(RuntimeError, match="simulated kill"):
        run(out, checkpoint=ckpt, poison=_PoisonAfter(2))
    meta, _ = load_stream_checkpoint(str(ckpt))
    assert 0 < meta["done"] < T
    assert meta["done"] % 8 == 0  # saves snap to update boundaries
    assert meta["arrays"]["template"].shape == SHAPE

    res = run(out, checkpoint=ckpt)
    assert (tmp_path / "ref.tif").read_bytes() == out.read_bytes()
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-6)


def test_boundary_saves_honor_checkpoint_every(morphing, tmp_path):
    """With small template_update_every and a large checkpoint_every,
    boundary saves are gated on the requested cadence instead of firing
    at every boundary (T/E part files for one run would multiply the
    checkpoint IO far beyond what the caller asked for)."""
    stack, _ = morphing
    u16 = np.clip(stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)

    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        template_update_every=8, template_window=8,
    )
    ckpt = tmp_path / "run.ckpt.npz"
    mc.correct_file(
        str(src), output=str(tmp_path / "out.tif"), chunk_size=8,
        checkpoint=str(ckpt), checkpoint_every=1000,
    )
    # T=48, E=8 -> 5 interior boundaries. Old behavior: one part file
    # per boundary plus the final save. New: only the final save.
    parts = sorted(tmp_path.glob("run.ckpt.npz.part*.npz"))
    assert len(parts) == 1, [p.name for p in parts]
