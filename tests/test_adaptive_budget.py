"""Adaptive hypothesis budgets + temporal warm start (PR 13).

The contract under test: the budget ladder and the warm-start seed are
pure SEARCH optimizations — they must land on the same transforms as
the full fixed budget within PARITY.md registration tolerance, per
frame, independent of batchmates, with a scene-cut frame falling back
to the full budget automatically.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kcmc_tpu import MotionCorrector  # noqa: E402
from kcmc_tpu.models import get_model  # noqa: E402
from kcmc_tpu.ops.ransac import consensus_batch, ransac_estimate  # noqa: E402
from kcmc_tpu.utils.metrics import (  # noqa: E402
    relative_transforms,
    transform_rmse,
)
from kcmc_tpu.utils.synthetic import make_drift_stack  # noqa: E402


@pytest.fixture
def matched_pairs():
    """Clean synthetic correspondences under a known affine, with 30%
    gross outliers — the regime the ladder must not degrade."""
    rng = np.random.default_rng(3)
    N = 600
    src = rng.uniform(0, 512, (N, 2)).astype(np.float32)
    A = np.array(
        [[1.01, 0.02, 3.0], [-0.015, 0.99, -2.0], [0.0, 0.0, 1.0]],
        np.float32,
    )
    dst = (src @ A[:2, :2].T + A[:2, 2]).astype(np.float32)
    out = rng.random(N) < 0.3
    dst[out] += rng.uniform(-60.0, 60.0, (int(out.sum()), 2)).astype(
        np.float32
    )
    return src, dst, np.ones(N, bool), A


def _corner_err(Ma, Mb, side=512.0):
    pts = np.array(
        [[0, 0, 1], [side, 0, 1], [0, side, 1], [side, side, 1]], np.float32
    )
    pa = pts @ np.asarray(Ma, np.float32).T
    pb = pts @ np.asarray(Mb, np.float32).T
    pa = pa[:, :2] / pa[:, 2:3]
    pb = pb[:, :2] / pb[:, 2:3]
    return float(np.abs(pa - pb).max())


def test_ladder_matches_full_budget(matched_pairs):
    src, dst, valid, _A = matched_pairs
    model = get_model("affine")
    key = jax.random.key(11)
    full = ransac_estimate(model, src, dst, valid, key, score_cap=512)
    lad = ransac_estimate(
        model, src, dst, valid, key, score_cap=512, budget_rungs=4
    )
    # Same consensus: the ladder's winner refines on the full set, so
    # the delivered fits agree to registration tolerance (PARITY.md).
    assert _corner_err(full.transform, lad.transform) < 0.05
    assert abs(int(full.n_inliers) - int(lad.n_inliers)) <= 2


def test_good_seed_and_scene_cut_fallback(matched_pairs):
    src, dst, valid, A = matched_pairs
    model = get_model("affine")
    key = jax.random.key(11)
    full = ransac_estimate(model, src, dst, valid, key, score_cap=512)
    good = ransac_estimate(
        model, src, dst, valid, key, score_cap=512, budget_rungs=4,
        seed_transform=jnp.asarray(A), seed_ok=jnp.bool_(True),
    )
    assert _corner_err(full.transform, good.transform) < 0.05
    # Scene cut: a wildly wrong seed scores below the exit bar, the
    # ladder runs, and the true consensus still wins.
    bogus = np.array(
        [[0.2, 0.9, 400.0], [-0.9, 0.3, -300.0], [0, 0, 1]], np.float32
    )
    cut = ransac_estimate(
        model, src, dst, valid, key, score_cap=512, budget_rungs=4,
        seed_transform=jnp.asarray(bogus), seed_ok=jnp.bool_(True),
    )
    assert _corner_err(full.transform, cut.transform) < 0.05
    assert int(cut.n_inliers) >= int(full.n_inliers) - 2


def test_ladder_results_independent_of_batchmates(matched_pairs):
    """A frame's result must not depend on how long other frames in
    the batch search (the per-frame done masking) — the property that
    keeps chunked == one-shot under the ladder."""
    src, dst, valid, _A = matched_pairs
    model = get_model("affine")
    rng = np.random.default_rng(9)
    # Frame 0: clean (exits early). Frame 1: 85% outliers (searches
    # the whole ladder).
    dst_hard = dst.copy()
    hard = rng.random(len(src)) < 0.8
    dst_hard[hard] += rng.uniform(-80, 80, (int(hard.sum()), 2)).astype(
        np.float32
    )
    keys = jax.vmap(
        lambda i: jax.random.fold_in(jax.random.key(0), i)
    )(jnp.arange(2, dtype=jnp.uint32))
    together = consensus_batch(
        model,
        jnp.stack([src, src]),
        jnp.stack([dst, dst_hard]),
        jnp.stack([valid, valid]),
        keys,
        score_cap=512,
        budget_rungs=4,
    )
    alone = consensus_batch(
        model, src[None], dst[None], valid[None], keys[:1],
        score_cap=512, budget_rungs=4,
    )
    np.testing.assert_array_equal(
        np.asarray(together.transform[0]), np.asarray(alone.transform[0])
    )


def test_static_path_unchanged_by_rung_knob(matched_pairs):
    """budget_rungs=1 and =0 must take the identical static path."""
    src, dst, valid, _A = matched_pairs
    model = get_model("rigid")
    key = jax.random.key(4)
    r0 = ransac_estimate(model, src, dst, valid, key, budget_rungs=0)
    r1 = ransac_estimate(model, src, dst, valid, key, budget_rungs=1)
    np.testing.assert_array_equal(
        np.asarray(r0.transform), np.asarray(r1.transform)
    )


@pytest.mark.parametrize("warm", [False, True])
def test_pipeline_parity_with_warm_start_and_scene_cut(warm):
    """End-to-end: a drift stack with a SCENE CUT spliced in (an
    unrelated second scene) registered with and without warm_start —
    transforms must agree to registration tolerance on both sides of
    the cut (the stale cross-cut seed scores itself out)."""
    d1 = make_drift_stack(
        n_frames=10, shape=(96, 96), model="translation", max_drift=4.0,
        seed=0,
    )
    kw = dict(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=128, n_hypotheses=64, warm_start=warm,
    )
    mc = MotionCorrector(**kw)
    res = mc.correct(d1.stack.astype(np.float32))
    rmse = transform_rmse(
        res.transforms, relative_transforms(d1.transforms),
        d1.stack.shape[1:],
    )
    assert rmse < 0.06, f"warm={warm} rmse {rmse:.3f}"


@pytest.mark.slow
def test_warm_start_matches_cold_transforms():
    # slow-marked: two full corrector builds; the bench-regression CI
    # job runs this file without the tier-1 'not slow' filter.
    d = make_drift_stack(
        n_frames=12, shape=(96, 96), model="affine", max_drift=4.0, seed=1
    )
    kw = dict(
        model="affine", backend="jax", batch_size=4, max_keypoints=128,
        n_hypotheses=64,
    )
    cold = MotionCorrector(**kw).correct(d.stack.astype(np.float32))
    hot = MotionCorrector(warm_start=True, **kw).correct(
        d.stack.astype(np.float32)
    )
    err = max(
        _corner_err(a, b, side=96.0)
        for a, b in zip(cold.transforms, hot.transforms)
    )
    assert err < 0.05, f"warm-start diverged {err:.4f} px"


def test_seeded_fused_program_prewarms_through_plan_ladder():
    """The PR-13 acceptance contract: with warm_start + plan buckets,
    warmup() builds the seeded fused program and the retrace sentinel
    convicts ZERO post-warm-up compiles — budget rungs are static
    in-program and the seed rides the compiled signature."""
    from kcmc_tpu.analysis import sanitize
    from kcmc_tpu.plans.runtime import predict_compile_keys

    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        max_keypoints=64, n_hypotheses=32, plan_buckets=(64,),
        warm_start=True,
    )
    mc.warmup()
    stack = np.random.default_rng(0).random((16, 64, 64)).astype(np.float32)
    with sanitize.retrace_sentinel(
        predicted=predict_compile_keys(mc.config)
    ):
        res = mc.correct(stack)
    assert res.transforms.shape == (16, 3, 3)


def test_warm_start_rejects_piecewise():
    with pytest.raises(ValueError, match="warm_start"):
        MotionCorrector(model="piecewise", warm_start=True)


def test_match_precision_variants_identical():
    """int8 / bf16 / float32 Hamming matrices are EXACT — identical to
    the XOR+popcount oracle bit for bit."""
    from kcmc_tpu.ops.match import hamming_matrix, hamming_matrix_mxu

    rng = np.random.default_rng(0)
    q = rng.integers(0, 2**32, (64, 16), dtype=np.uint32)
    r = rng.integers(0, 2**32, (48, 16), dtype=np.uint32)
    qv = rng.random(64) < 0.9
    rv = rng.random(48) < 0.9
    oracle = np.asarray(hamming_matrix(q, r, qv, rv)).astype(np.uint32)
    for prec in ("float32", "bf16", "int8"):
        got = np.asarray(
            hamming_matrix_mxu(q, r, qv, rv, precision=prec)
        ).astype(np.uint32)
        np.testing.assert_array_equal(got, oracle, err_msg=prec)


@pytest.mark.slow
def test_match_precision_pipeline_parity():
    # slow-marked: three full corrector builds; the bench-regression CI
    # job runs this file without the tier-1 'not slow' filter.
    """A full registration run agrees across match_precision settings
    within PARITY.md tolerance (int8/bf16 exactly; float32 re-routes
    descriptor quantization, so tolerance-level)."""
    d = make_drift_stack(
        n_frames=8, shape=(96, 96), model="affine", max_drift=3.0, seed=2
    )
    kw = dict(
        model="affine", backend="jax", batch_size=4, max_keypoints=128,
        n_hypotheses=64,
    )
    ref = MotionCorrector(match_precision="bf16", **kw).correct(
        d.stack.astype(np.float32)
    )
    i8 = MotionCorrector(match_precision="int8", **kw).correct(
        d.stack.astype(np.float32)
    )
    np.testing.assert_array_equal(ref.transforms, i8.transforms)
    f32 = MotionCorrector(match_precision="float32", **kw).correct(
        d.stack.astype(np.float32)
    )
    err = max(
        _corner_err(a, b, side=96.0)
        for a, b in zip(ref.transforms, f32.transforms)
    )
    assert err < 0.1, f"float32 reference route diverged {err:.4f} px"
