"""Warp-kernel selection policy and pallas-path pipeline equivalence."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic


def test_pallas_warp_pipeline_matches_jnp():
    """Forcing the Pallas translation warp must not change results
    (interpret mode on CPU)."""
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(128, 128), model="translation", seed=51
    )
    r_jnp = MotionCorrector(
        model="translation", backend="jax", batch_size=4, warp="jnp"
    ).correct(data.stack)
    r_pl = MotionCorrector(
        model="translation", backend="jax", batch_size=4, warp="pallas"
    ).correct(data.stack)
    np.testing.assert_allclose(r_pl.transforms, r_jnp.transforms, atol=1e-6)
    np.testing.assert_allclose(r_pl.corrected, r_jnp.corrected, atol=1e-5)


def test_pallas_rejected_for_non_translation():
    # Validated at config time — covers the piecewise/3D paths too, where
    # the warp policy is otherwise never consulted.
    for model in ("affine", "piecewise"):
        with pytest.raises(ValueError, match="pallas"):
            MotionCorrector(model=model, backend="jax", batch_size=2, warp="pallas")


def test_auto_on_cpu_uses_jnp():
    """auto must fall back to the gather warp on CPU (no accelerator)."""
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.ops.warp import warp_batch_with_ok

    b = JaxBackend(CorrectorConfig(model="translation", warp="auto"))
    assert b._resolve_batch_warp() is warp_batch_with_ok


def test_warp_ok_flag_surfaces():
    """Frames a bounded gather-free kernel zeroes must be flagged.

    rescue_warp=False keeps the raw zero-and-flag contract visible (the
    default rescues flagged frames through the exact warp instead —
    tests/test_rescue_warp.py).
    """
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(128, 128), model="rigid", max_drift=4.0, seed=2
    )
    # max_shear_px=0 makes any nonzero rotation exceed the bound.
    res = MotionCorrector(
        model="rigid", backend="jax", batch_size=4, warp="separable",
        max_shear_px=0, rescue_warp=False,
    ).correct(data.stack)
    ok = res.diagnostics["warp_ok"]
    assert ok.shape == (4,)
    # the rotated frames exceed a zero shear budget -> flagged + zeroed
    assert not ok[1:].any()
    assert np.all(res.corrected[~ok] == 0.0)
    # sanity: with the default bound everything is within range
    res2 = MotionCorrector(
        model="rigid", backend="jax", batch_size=4, warp="separable"
    ).correct(data.stack)
    assert np.all(res2.diagnostics["warp_ok"])
