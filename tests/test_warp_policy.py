"""Warp-kernel selection policy and pallas-path pipeline equivalence."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic


def test_pallas_warp_pipeline_matches_jnp():
    """Forcing the Pallas translation warp must not change results
    (interpret mode on CPU)."""
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(128, 128), model="translation", seed=51
    )
    r_jnp = MotionCorrector(
        model="translation", backend="jax", batch_size=4, warp="jnp"
    ).correct(data.stack)
    r_pl = MotionCorrector(
        model="translation", backend="jax", batch_size=4, warp="pallas"
    ).correct(data.stack)
    # Since the round-5 transform polish, the warped pixels feed back
    # into the transform (the polish measures residual shifts on them),
    # so the two warp implementations' float-rounding differences
    # propagate into the estimate at the ~1e-6 px level. 1e-4 still
    # fails any real kernel divergence by orders of magnitude.
    np.testing.assert_allclose(r_pl.transforms, r_jnp.transforms, atol=1e-4)
    np.testing.assert_allclose(r_pl.corrected, r_jnp.corrected, atol=1e-3)


def test_pallas_rejected_for_non_translation():
    # Validated at config time — covers the piecewise/3D paths too, where
    # the warp policy is otherwise never consulted.
    for model in ("affine", "piecewise"):
        with pytest.raises(ValueError, match="pallas"):
            MotionCorrector(model=model, backend="jax", batch_size=2, warp="pallas")


def test_auto_on_cpu_uses_jnp():
    """auto must fall back to the gather warp on CPU (no accelerator)."""
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.ops.warp import warp_batch_with_ok

    b = JaxBackend(CorrectorConfig(model="translation", warp="auto"))
    assert b._resolve_batch_warp((128, 128)) is warp_batch_with_ok


def test_warp_ok_flag_surfaces():
    """Frames a bounded gather-free kernel zeroes must be flagged.

    rescue_warp=False keeps the raw zero-and-flag contract visible (the
    default rescues flagged frames through the exact warp instead —
    tests/test_rescue_warp.py).
    """
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(128, 128), model="rigid", max_drift=4.0, seed=2
    )
    # max_shear_px=0 makes any nonzero rotation exceed the bound.
    res = MotionCorrector(
        model="rigid", backend="jax", batch_size=4, warp="separable",
        max_shear_px=0, rescue_warp=False,
    ).correct(data.stack)
    ok = res.diagnostics["warp_ok"]
    assert ok.shape == (4,)
    # the rotated frames exceed a zero shear budget -> flagged + zeroed
    assert not ok[1:].any()
    assert np.all(res.corrected[~ok] == 0.0)
    # sanity: with the default bound everything is within range
    res2 = MotionCorrector(
        model="rigid", backend="jax", batch_size=4, warp="separable"
    ).correct(data.stack)
    assert np.all(res2.diagnostics["warp_ok"])


def test_max_rotation_deg_sets_shear_bound():
    """max_rotation_deg derives the separable shear bound per shape."""
    import math

    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig

    b = JaxBackend(CorrectorConfig(model="rigid", max_rotation_deg=5.0))
    # conservative: the longer frame side sets the worst-case shear
    expect = math.ceil(math.tan(math.radians(5.0)) * 256 / 2)
    assert b._shear_bound_px((128, 256)) == expect
    # unset: the raw pixel knob wins
    b2 = JaxBackend(CorrectorConfig(model="rigid", max_shear_px=11))
    assert b2._shear_bound_px((128, 256)) == 11
    with pytest.raises(ValueError, match="max_rotation_deg"):
        CorrectorConfig(model="rigid", max_rotation_deg=60.0)


def test_out_of_bound_telemetry_warns_and_escalates():
    """A persistently out-of-bound stack must (a) warn, (b) switch the
    remaining batches to the exact warp, (c) still produce output
    identical to a pure-jnp run."""
    data = synthetic.make_drift_stack(
        n_frames=12, shape=(96, 96), model="rigid", max_drift=4.0, seed=7
    )
    kw = dict(
        model="rigid", backend="jax", batch_size=2, warp="separable",
        max_shear_px=0,  # every rotated frame exceeds the bound
        rescue_warn_fraction=0.25,
    )
    ref = MotionCorrector(model="rigid", backend="jax", batch_size=2,
                          warp="jnp").correct(data.stack)

    with pytest.warns(RuntimeWarning, match="switching the remaining"):
        res = MotionCorrector(**kw).correct(data.stack)
    rescued = np.asarray(res.diagnostics["warp_rescued"])
    assert rescued[:2].any()  # early batches hit the bounded kernel
    assert not rescued[-2:].any()  # post-escalation batches don't rescue
    # Rescued frames' photometric polish runs in its own jit (host
    # rescue path) while the reference run polishes in-program; the
    # correlation sums' float association differs, so transforms agree
    # to ~1e-4 px rather than bitwise (pre-round-5 the two paths were
    # identical because nothing fed warped pixels back).
    np.testing.assert_allclose(res.corrected, ref.corrected, atol=1e-3)
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-4)

    # escalation off: warn-only, every flagged frame rescues
    with pytest.warns(RuntimeWarning, match="persistently"):
        res2 = MotionCorrector(
            **{**kw, "rescue_escalate": False}
        ).correct(data.stack)
    rescued2 = np.asarray(res2.diagnostics["warp_rescued"])
    assert rescued2[1:].all()
    np.testing.assert_allclose(res2.corrected, ref.corrected, atol=1e-5)


def test_rescue_window_trips_on_late_onset_motion():
    """A long in-bound prefix must not dilute the telemetry: when the
    recent-window fraction exceeds the threshold, the policy trips even
    though the cumulative fraction is far below it."""
    mc = MotionCorrector(
        model="rigid", backend="jax", batch_size=8, warp="separable",
        rescue_warn_fraction=0.25,
    )
    # simulate drains: 2000 in-bound frames, then rescues on every frame
    mc._dispatch_batches(iter([]), None, lambda e: None)  # reset state
    import numpy as np

    for _ in range(250):  # 2000 clean frames
        mc._rescue_window.append((8, 0))
        mc._rescue_seen += 8
    batch = np.zeros((8, 16, 16), np.float32)
    for _ in range(40):  # 320 bad frames: cumulative 320/2320 ~ 14%
        host = {"warp_ok": np.zeros(8, bool)}  # all 8 frames out of bound
        with __import__("warnings").catch_warnings(record=True) as w:
            __import__("warnings").simplefilter("always")
            mc._rescue_flagged(host, batch, 8)
    assert mc._rescue_warned, "windowed fraction should have tripped"


def test_checkpointed_run_never_escalates(tmp_path):
    """Escalation switches warp kernels mid-stream (visible at the
    interpolation level for in-bound frames), so checkpointed streaming
    runs must stay warn-only to keep resume byte-identity."""
    import warnings

    from kcmc_tpu.io.tiff import write_stack

    data = synthetic.make_drift_stack(
        n_frames=12, shape=(96, 96), model="rigid", max_drift=4.0, seed=7
    )
    src = tmp_path / "in.tif"
    write_stack(src, np.clip(data.stack * 40000, 0, 65535).astype(np.uint16))
    mc = MotionCorrector(
        model="rigid", backend="jax", batch_size=2, warp="separable",
        max_shear_px=0, rescue_warn_fraction=0.25,
    )
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = mc.correct_file(
            str(src), output=str(tmp_path / "o.tif"),
            checkpoint=str(tmp_path / "c.npz"),
        )
    assert not res.timing["warp_escalated"]
    assert any("persistently" in str(x.message) for x in w)  # warn-only
    # every flagged frame was rescued individually
    assert np.asarray(res.diagnostics["warp_rescued"])[1:].all()


def test_translation_auto_falls_back_beyond_pallas_vmem(monkeypatch):
    """The whole-frame Pallas translation kernel VMEM-OOMs at compile
    time beyond ~512^2 (measured 20.5 MB scoped vmem at 1024^2 vs the
    16 MB limit); warp='auto' must route large frames to the ROW-STRIP
    Pallas kernel (round 5) — and frames beyond even the strip budget
    to the separable pass chain — instead of dying in the compiler."""
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.ops import pallas_warp

    assert pallas_warp.supports((512, 512))
    assert not pallas_warp.supports((1024, 1024))
    assert not pallas_warp.supports((2048, 2048))
    assert pallas_warp.supports_strips((1024, 1024))
    assert pallas_warp.supports_strips((2048, 2048))
    assert not pallas_warp.supports_strips((2048, 8192))

    backend = JaxBackend(CorrectorConfig(model="translation", warp="auto"))
    monkeypatch.setattr(JaxBackend, "_on_accelerator", staticmethod(lambda: True))
    small = backend._resolve_batch_warp((512, 512))
    large = backend._resolve_batch_warp((1024, 1024))
    huge = backend._resolve_batch_warp((2048, 8192))
    assert "warp_batch_translation" in repr(small.func)
    assert "warp_batch_translation_strips" in repr(large.func)
    assert "warp_batch_affine" in repr(huge.func)


def test_matrix_auto_routes_pallas_with_vmem_fallback(monkeypatch):
    """warp='auto' for rigid/affine/homography prefers the Pallas
    matrix kernel (bit-equal to the XLA one) and falls back to the XLA
    form where its VMEM gate rejects the shape."""
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.ops import pallas_warp_field as pwf

    monkeypatch.setattr(
        JaxBackend, "_on_accelerator", staticmethod(lambda: True)
    )
    for model in ("rigid", "affine", "homography"):
        backend = JaxBackend(CorrectorConfig(model=model, warp="auto"))
        mpx = backend._matrix_resid_px((512, 512))
        assert pwf.supports_matrix((512, 512), mpx)
        fn = backend._resolve_batch_warp((512, 512))
        assert "warp_batch_matrix_pallas" in repr(fn.func)
    backend = JaxBackend(CorrectorConfig(model="affine", warp="auto"))
    monkeypatch.setattr(pwf, "pick_strip_matrix", lambda *a, **k: None)
    fn = backend._resolve_batch_warp((512, 512))
    assert "warp_batch_matrix" in repr(fn.func)
    assert "pallas" not in repr(fn.func)


def test_piecewise_auto_routes_fused_field_warp(monkeypatch):
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.ops import pallas_warp_field as pwf

    monkeypatch.setattr(
        JaxBackend, "_on_accelerator", staticmethod(lambda: True)
    )
    backend = JaxBackend(CorrectorConfig(model="piecewise", warp="auto"))
    fn = backend._resolve_field_warp((512, 512))
    assert fn is not None and "warp_batch_field" in repr(fn.func)
    # beyond the VMEM gate: None -> the XLA flow path takes over
    monkeypatch.setattr(pwf, "pick_strip", lambda *a, **k: None)
    assert backend._resolve_field_warp((512, 512)) is None
