"""Integration suite for fleet-wide distributed tracing over the
serve path (docs/OBSERVABILITY.md "Distributed tracing").

* one routed request yields ONE stitched trace spanning client →
  server → scheduler segments, over a real socket, with the causal
  parent/child chain intact;
* span weights (dur × n) telescope against the latency plane's
  per-segment histogram sums — the two views of one request agree;
* the `trace` verb serves the live span ring; `kcmc_tpu trace` renders
  critical paths from shards and addresses;
* `metrics` carries bucket exemplars naming real trace ids, rendered
  as OpenMetrics ``# {trace_id=...}`` suffixes;
* every `# TYPE` in the exposition has a matching `# HELP` (the
  format test of the `kcmc_serve_queue_frames` satellite);
* `slo_objectives` surfaces multi-window `kcmc_slo_*` gauges, with a
  nonzero burn rate under an injected slowdown (an impossible
  threshold: every request is "slow");
* `trace=False` on the client and an unset `trace_shard_dir` disable
  every emission site (the overhead A/B's off arm).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.obs.latency import render_prometheus
from kcmc_tpu.obs.tracing import collect_spans, critical_path, stitch
from kcmc_tpu.utils.synthetic import make_drift_stack

MC_KW = dict(
    model="translation", backend="numpy", batch_size=8,
    max_keypoints=64, n_hypotheses=32,
)

LIFECYCLE_SEGMENTS = {
    "request.admission", "request.queue_wait", "request.batch_form",
    "request.dispatch", "request.device", "request.drain",
    "request.delivery", "request.total",
}


def _stack(n=16, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


def _drive(c, n=16, seed=0):
    sid = c.open_session(tenant="trace-t")
    c.submit(sid, _stack(n, seed=seed))
    seen = 0
    while seen < n:
        got = c.results(sid, timeout=60.0)
        assert got is not None
        seen += got["n"]
    c.close_session(sid)
    return sid


# -- one stitched trace over the real socket ---------------------------------


def test_one_request_yields_one_stitched_trace(tmp_path):
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    shard_dir = str(tmp_path / "spans")
    mc = MotionCorrector(trace_shard_dir=shard_dir, **MC_KW)
    with ServeServer(mc, port=0) as srv:
        with ServeClient(
            port=srv.port,
            trace_shard=str(tmp_path / "client-spans.jsonl"),
        ) as c:
            _drive(c)
            submit_ctx = None
            # last_trace tracks the most recent call; remember the
            # submit's by driving once more explicitly
            sid = c.open_session(tenant="t2")
            c.submit(sid, _stack(8, seed=1))
            submit_ctx = dict(c.last_trace)
            seen = 0
            while seen < 8:
                got = c.results(sid, timeout=60.0)
                seen += got["n"]
            c.close_session(sid)
            live = c.trace_dump()
            m = c.metrics()
    assert submit_ctx and len(submit_ctx["trace_id"]) == 32

    spans = collect_spans(
        [shard_dir, str(tmp_path / "client-spans.jsonl")]
    )
    traces = stitch(spans)
    tid = submit_ctx["trace_id"]
    assert tid in traces, sorted(traces)
    tr = traces[tid]
    names = {s["name"] for s in tr}
    # client → server → every scheduler segment, one causal trace
    assert "rpc.client" in names and "rpc.server" in names
    assert LIFECYCLE_SEGMENTS <= names, sorted(names)
    # causal chain: the client's rpc.client span is the root; the
    # server re-parents onto the wire span id
    roots = [s for s in tr if s["name"] == "rpc.client"]
    assert any(s["span_id"] == submit_ctx["span_id"] for s in roots)
    segs = [s for s in tr if s["name"] in LIFECYCLE_SEGMENTS]
    assert all(s.get("parent_id") for s in segs)
    cp = critical_path(tr)
    assert cp["dominant"] in LIFECYCLE_SEGMENTS - {"request.total"}
    assert cp["total_s"] > 0

    # the live ring (trace verb) carries the same trace
    assert any(s.get("trace_id") == tid for s in live)
    # ...and the exemplars name real traces from this run
    all_tids = {s["trace_id"] for s in spans if s.get("trace_id")}
    ex_tids = {
        ex["trace_id"]
        for rungs in (m.get("exemplars") or {}).values()
        for buckets in rungs.values()
        for ex in buckets.values()
    }
    assert ex_tids and ex_tids <= all_tids


def test_span_weights_telescope_against_segment_sums(tmp_path):
    """The spans and the latency histograms are two views of the same
    requests: per segment, sum(dur × n) over spans must equal the
    histogram's sum_s (within float rounding of the span records)."""
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    shard_dir = str(tmp_path / "spans")
    mc = MotionCorrector(trace_shard_dir=shard_dir, **MC_KW)
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port) as c:
            _drive(c, n=24)
            m = c.metrics()

    spans = collect_spans([shard_dir])
    weights: dict[str, float] = {}
    for s in spans:
        if s["name"] in LIFECYCLE_SEGMENTS:
            n = int((s.get("args") or {}).get("n", 1))
            weights[s["name"]] = weights.get(s["name"], 0.0) + (
                s["dur_s"] * max(1, n)
            )
    totals = m["plane"]["totals"]
    for seg in LIFECYCLE_SEGMENTS:
        hist_sum = totals[seg]["sum_s"]
        assert weights.get(seg, 0.0) == pytest.approx(
            hist_sum, rel=0.02, abs=2e-3
        ), (seg, weights.get(seg), hist_sum)


def test_tracing_unarmed_emits_nothing(tmp_path):
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(**MC_KW)  # no trace_shard_dir
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port, trace=False) as c:
            _drive(c)
            assert c.last_trace is None
            assert c.trace_dump() == []
            m = c.metrics()
    assert not m.get("exemplars")


def test_concurrent_traced_streams_stay_distinct(tmp_path):
    """Two threads submitting traced requests concurrently: every
    emitted span belongs to a trace one of the clients minted — no
    cross-talk, no unparented segments."""
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    shard_dir = str(tmp_path / "spans")
    mc = MotionCorrector(trace_shard_dir=shard_dir, **MC_KW)
    minted: set[str] = set()
    lock = threading.Lock()
    with ServeServer(mc, port=0) as srv:
        def drive(i):
            with ServeClient(port=srv.port) as c:
                sid = c.open_session(tenant=f"t{i}")
                c.submit(sid, _stack(12, seed=i))
                with lock:
                    minted.add(c.last_trace["trace_id"])
                seen = 0
                while seen < 12:
                    seen += c.results(sid, timeout=60.0)["n"]
                c.close_session(sid)

        ts = [threading.Thread(target=drive, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
    assert len(minted) == 2
    spans = collect_spans([shard_dir])
    seg_tids = {
        s["trace_id"]
        for s in spans
        if s["name"] in LIFECYCLE_SEGMENTS and s.get("trace_id")
    }
    assert seg_tids <= minted and seg_tids


# -- exposition: exemplars + HELP/TYPE format --------------------------------


def test_prometheus_exposition_exemplars_and_help_format(tmp_path):
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(
        trace_shard_dir=str(tmp_path / "spans"), **MC_KW
    )
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port) as c:
            sid = c.open_session(tenant="fmt")
            c.submit(sid, _stack(8))
            seen = 0
            while seen < 8:
                seen += c.results(sid, timeout=60.0)["n"]
            m = c.metrics()  # session open: queues gauge populated
            c.close_session(sid)
    text = render_prometheus(m)
    # at least one bucket line carries an OpenMetrics exemplar
    ex_lines = [
        ln for ln in text.splitlines()
        if "_bucket{" in ln and '# {trace_id="' in ln
    ]
    assert ex_lines, text
    trace_id = ex_lines[0].split('trace_id="')[1].split('"')[0]
    assert len(trace_id) == 32
    # the queue gauge rides with its HELP line
    assert "# TYPE kcmc_serve_queue_frames gauge" in text
    assert "# HELP kcmc_serve_queue_frames" in text
    # format contract: EVERY # TYPE has a matching # HELP
    types = {
        ln.split()[2]
        for ln in text.splitlines()
        if ln.startswith("# TYPE")
    }
    helps = {
        ln.split()[2]
        for ln in text.splitlines()
        if ln.startswith("# HELP")
    }
    assert types and types == helps, types ^ helps
    # empty payloads still render (the pre-plane contract)
    assert render_prometheus({}) == "\n"


# -- SLO objectives over the serve path --------------------------------------


def test_slo_gauges_burn_under_injected_slowdown(tmp_path):
    """An impossible latency objective (1 µs threshold) makes every
    real request a budget burn: the `metrics` slo section and the
    exposition must show a nonzero multi-window burn rate."""
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(
        slo_objectives="full:0.000001:0.99;avail:0.999", **MC_KW
    )
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port) as c:
            _drive(c)
            m = c.metrics()
    slo = m.get("slo")
    assert slo, sorted(m)
    names = {o["name"] for o in slo["objectives"]}
    assert names == {"latency_full_lt_1e-06s", "availability"}
    burns = slo["burn_rates"]["latency_full_lt_1e-06s"]
    assert burns["5m"] > 1.0, burns  # every request burned budget
    text = render_prometheus(m)
    burn_lines = [
        ln for ln in text.splitlines()
        if ln.startswith("kcmc_slo_burn_rate{")
    ]
    assert len(burn_lines) >= 4  # one per window per objective
    assert any(
        'window="5m"' in ln and not ln.rstrip().endswith(" 0")
        for ln in burn_lines
    ), burn_lines
    assert any(
        ln.startswith("kcmc_slo_target") for ln in text.splitlines()
    )


def test_slo_spec_validated_at_config_time():
    with pytest.raises(ValueError, match="slo_objectives"):
        MotionCorrector(slo_objectives="full:nope", **MC_KW)


def test_trace_shard_cap_validated_at_config_time():
    with pytest.raises(ValueError):
        MotionCorrector(trace_shard_cap=0, **MC_KW)


# -- the trace CLI -----------------------------------------------------------


def test_trace_cli_renders_shards_and_live_address(tmp_path, capsys):
    import json as _json

    from kcmc_tpu.__main__ import main as cli_main
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    shard_dir = str(tmp_path / "spans")
    mc = MotionCorrector(trace_shard_dir=shard_dir, **MC_KW)
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port) as c:
            _drive(c)
        # live address source (the trace verb) while the server is up
        rc = cli_main(
            ["trace", f"127.0.0.1:{srv.port}", "--json"]
        )
    assert rc == 0
    live = _json.loads(capsys.readouterr().out)
    assert live["kind"] == "kcmc_trace" and live["n_traces"] >= 1

    chrome = str(tmp_path / "trace.json")
    rc = cli_main(["trace", shard_dir, "--chrome", chrome])
    out = capsys.readouterr().out
    assert rc == 0
    assert "critical" in out or "dominant" in out, out
    events = _json.load(open(chrome))["traceEvents"]
    assert any(e.get("ph") == "X" for e in events)


# -- fleet: the trace survives a kill-and-migrate ----------------------------


@pytest.mark.slow
def test_trace_survives_kill_and_migrate(tmp_path):
    """THE fleet tracing acceptance: SIGKILL the bound replica while a
    traced session is mid-stream. The router migrates the session to
    the survivor, the replayed frames carry the SAME trace context,
    and the stitched trace ends up spanning BOTH replica processes
    plus a `fleet.migrate` link span on the router."""
    import os
    import signal
    import time

    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.serve.client import ServeClient, ServeError
    from kcmc_tpu.serve.fleet import spawn_replica
    from kcmc_tpu.serve.journal import journal_path, load_session_journal
    from kcmc_tpu.serve.router import FleetRouter

    jdir = str(tmp_path / "journals")
    shard_dir = str(tmp_path / "spans")
    os.makedirs(jdir, exist_ok=True)
    replicas = [
        spawn_replica(
            [
                "--port", "0", "--backend", "numpy",
                "--batch-size", "8", "--max-keypoints", "64",
                "--hypotheses", "32",
                "--journal-dir", jdir, "--journal-every", "4",
                "--trace-shards", shard_dir,
            ],
            env={"JAX_PLATFORMS": "cpu"},
        )
        for _ in range(2)
    ]
    cfg = CorrectorConfig(trace_shard_dir=shard_dir)
    router = FleetRouter(
        replicas, port=0, config=cfg, journal_dir=jdir
    ).start()
    # One traced submit for the WHOLE stream: the kill lands while the
    # victim is mid-batch, so the un-journaled tail is replayed to the
    # survivor by the router with the ORIGINAL trace context — that is
    # the continuation under test. (A second client submit would mint
    # a fresh trace id by design.)
    stack = _stack(64, seed=7)
    n = len(stack)
    try:
        with ServeClient(port=router.port) as c:
            sid = c.open_session(tenant="trace", session_id="T1")
            c.submit(sid, stack)
            tid = c.last_trace["trace_id"]
            jp = journal_path(jdir, sid)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 60.0:
                if os.path.exists(jp):
                    got = load_session_journal(jp)
                    if got and 4 <= int(got[0]["done"]) < n:
                        break
                time.sleep(0.02)
            else:
                raise AssertionError("journal never became durable")
            victim_rid = router.stats()["sessions"][sid]
            victim = next(r for r in replicas if r.rid == victim_rid)
            os.kill(victim.proc.pid, signal.SIGKILL)
            victim.proc.wait(timeout=30)
            delivered = 0
            while delivered < n:
                try:
                    span = c.results(sid, timeout=60.0)
                except ServeError as e:
                    # a span delivered to the dropped connection is
                    # reported, not lost — the error carries it
                    span = (getattr(e, "info", None) or {}).get("span")
                    if span is None:
                        raise
                assert span is not None
                delivered = int(span["first_frame"]) + int(span["n"])
            out = c.close_session(sid)
        assert out["frames"] == n
        assert router.stats()["migrations_total"] >= 1
    finally:
        router.stop(stop_owned=True)

    spans = [
        s for s in collect_spans([shard_dir]) if s.get("trace_id") == tid
    ]
    assert spans, "the trace vanished in the migration"
    seg_pids = {
        s["pid"] for s in spans if s["name"] in LIFECYCLE_SEGMENTS
    }
    assert len(seg_pids) >= 2, (
        f"one stitched trace must span both replicas, saw pids "
        f"{seg_pids}"
    )
    links = [s for s in spans if s["name"] == "fleet.migrate"]
    assert links, "no fleet.migrate link span on the migrated trace"
    assert links[0].get("args", {}).get("from") == victim_rid
