"""Iterative template refinement: mean-template re-registration must
improve accuracy on noisy stacks and compose with the streaming path."""

import numpy as np

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse
from kcmc_tpu.utils.synthetic import make_drift_stack


def test_refinement_improves_noisy_registration():
    data = make_drift_stack(
        n_frames=24, shape=(128, 128), model="translation", seed=2, noise=0.1
    )
    rel = relative_transforms(data.transforms)
    plain = MotionCorrector(model="translation").correct(data.stack)
    refined = MotionCorrector(
        model="translation", template_iters=2, template_window=24
    ).correct(data.stack)
    e_plain = transform_rmse(plain.transforms, rel, (128, 128))
    e_ref = transform_rmse(refined.transforms, rel, (128, 128))
    assert e_ref < e_plain  # sqrt(N) template noise advantage
    assert e_ref < 0.3


def test_refinement_timing_stage_reported():
    data = make_drift_stack(n_frames=8, shape=(96, 96), model="translation", seed=0)
    res = MotionCorrector(
        model="translation", template_iters=1, template_window=8
    ).correct(data.stack)
    assert "refine_template" in res.timing["stages_s"]


def test_refinement_streaming_path(tmp_path):
    from kcmc_tpu.io.tiff import TiffWriter

    data = make_drift_stack(
        n_frames=12, shape=(96, 96), model="translation", seed=1, noise=0.05
    )
    src = tmp_path / "src.tif"
    w = TiffWriter(src)
    for fr in data.stack:
        w.append(fr.astype(np.float32))
    w.close()

    mc = MotionCorrector(model="translation", template_iters=1, template_window=12)
    res = mc.correct_file(str(src))
    rel = relative_transforms(data.transforms)
    assert transform_rmse(res.transforms, rel, (96, 96)) < 0.3
    assert "refine_template" in res.timing["stages_s"]
