"""Fused Pallas field warp (interpret mode) vs the gather oracle.

The kernel under test replaces upsample_field + warp_batch_flow in the
piecewise path (jax_backend._resolve_field_warp). Its contract: match
one-shot bilinear sampling of the bilinearly-upsampled field to
O(|grad u|²) — ~30x tighter than the naive two-pass split the XLA flow
warp uses (test_warp_field.py allows 0.2 max there; the fused kernel
holds ~0.005) — with the warp family's bounded-kernel semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.pallas_warp_field import (
    pick_strip,
    supports,
    warp_batch_field,
)
from kcmc_tpu.ops.piecewise import upsample_field
from kcmc_tpu.ops.warp import warp_frame_flow
from kcmc_tpu.utils import synthetic


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(7)
    return synthetic.render_scene(rng, (192, 192), n_blobs=90).astype(
        np.float32
    )


def _oracle(frames, fields):
    shape = frames.shape[1:]
    flows = jax.vmap(lambda f: upsample_field(f, shape))(fields)
    return np.asarray(jax.vmap(warp_frame_flow)(frames, flows))


def test_matches_gather_oracle(img):
    H, W = img.shape
    rng = np.random.default_rng(1)
    fields = []
    for t in [(0.0, 0.0), (4.7, -3.1), (-9.4, 6.2)]:
        f = rng.uniform(-2.5, 2.5, size=(8, 8, 2)).astype(np.float32)
        fields.append(f + np.asarray(t, np.float32))
    fields = jnp.asarray(np.stack(fields))
    frames = jnp.asarray(np.stack([img] * 3))
    ref = _oracle(frames, fields)
    out, ok = warp_batch_field(
        frames, fields, max_px=6, interpret=True, with_ok=True
    )
    assert np.all(np.asarray(ok))
    d = np.abs(np.asarray(out) - ref)
    # consumer-phase-corrected split: O(|grad u|²) from one-shot
    # bilinear (measured 3.2e-3 max on this workload; the naive split
    # the XLA path uses measures ~0.1 here)
    assert d.mean() < 2e-4, f"mean diff {d.mean():.6f}"
    assert d.max() < 0.02, f"max diff {d.max():.4f}"


def test_constant_field_is_exact_translation(img):
    # A constant field is a pure (fractional) translation: both passes
    # collapse to single bilinear taps — exact up to float association.
    frames = jnp.asarray(img[None])
    f = jnp.broadcast_to(
        jnp.asarray([1.3, -2.6], jnp.float32), (1, 8, 8, 2)
    )
    ref = _oracle(frames, f)
    out = np.asarray(warp_batch_field(frames, f, max_px=6, interpret=True))
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_strip_path_and_odd_shapes():
    # Non-divisible height, non-square frame, odd grid, forced strips.
    rng = np.random.default_rng(3)
    H, W = 200, 160
    img = synthetic.render_scene(rng, (H, W), n_blobs=80).astype(np.float32)
    f = rng.uniform(-2.0, 2.0, size=(2, 6, 5, 2)).astype(np.float32)
    f[1] += np.asarray([7.3, -5.1], np.float32)
    frames = jnp.asarray(np.stack([img, img]))
    fields = jnp.asarray(f)
    ref = _oracle(frames, fields)
    out = np.asarray(
        warp_batch_field(frames, fields, max_px=6, strip=128, interpret=True)
    )
    d = np.abs(out - ref)
    assert d.mean() < 2e-4, f"mean diff {d.mean():.6f}"
    assert d.max() < 0.02, f"max diff {d.max():.4f}"


def test_residual_beyond_bound_zeroes_and_flags(img):
    f = np.zeros((1, 8, 8, 2), np.float32)
    f[0, :4] = 10.0
    f[0, 4:] = -10.0  # zero mean, residual 10 px >> max_px
    out, ok = warp_batch_field(
        jnp.asarray(img[None]), jnp.asarray(f), max_px=4,
        interpret=True, with_ok=True,
    )
    assert not bool(np.asarray(ok)[0])
    assert np.all(np.asarray(out) == 0.0)


def test_translation_beyond_pad_zeroes_and_flags(img):
    f = np.full((1, 8, 8, 2), 300.0, np.float32)  # > PAD window
    out, ok = warp_batch_field(
        jnp.asarray(img[None]), jnp.asarray(f), max_px=4,
        interpret=True, with_ok=True,
    )
    assert not bool(np.asarray(ok)[0])
    assert np.all(np.asarray(out) == 0.0)


def test_out_of_frame_samples_zeroed(img):
    # Constant +20 px x-shift: the rightmost 20 columns sample beyond
    # the frame and must be zero, matching the gather oracle's policy.
    frames = jnp.asarray(img[None])
    f = jnp.broadcast_to(jnp.asarray([20.0, 0.0], jnp.float32), (1, 8, 8, 2))
    out = np.asarray(warp_batch_field(frames, f, max_px=6, interpret=True))
    assert np.all(out[:, :, -20:] == 0.0)
    ref = _oracle(frames, f)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_supports_and_pick_strip():
    assert supports((512, 512))
    assert pick_strip((512, 512)) == 256  # measured-fastest (DESIGN.md)
    assert pick_strip((192, 192)) == 192  # whole frame below 256 rows


def _hom(theta_deg, tx, ty, g, h, sc=1.0, c=95.5):
    th = np.deg2rad(theta_deg)
    R = np.array(
        [[sc * np.cos(th), -sc * np.sin(th), 0],
         [sc * np.sin(th), sc * np.cos(th), 0], [0, 0, 1.0]]
    )
    C = np.array([[1, 0, c], [0, 1, c], [0, 0, 1.0]])
    Ci = np.array([[1, 0, -c], [0, 1, -c], [0, 0, 1.0]])
    T = np.array([[1, 0, tx], [0, 1, ty], [0, 0, 1.0]])
    M = (C @ R @ Ci @ T).astype(np.float64)
    M[2, 0] = g
    M[2, 1] = h
    return M.astype(np.float32)


def test_matrix_pallas_bit_equals_xla(img):
    """The Pallas matrix warp computes the identical f32 math to
    ops/warp_field.warp_batch_matrix — outputs must be bit-equal, so
    routing between them can never change results."""
    from kcmc_tpu.ops.pallas_warp_field import warp_batch_matrix_pallas
    from kcmc_tpu.ops.warp_field import warp_batch_matrix

    cases = [
        _hom(0.0, 0.0, 0.0, 0.0, 0.0),
        _hom(0.0, 5.2, -3.8, 2e-5, -1.5e-5),
        _hom(1.2, -4.1, 2.6, -2e-5, 2e-5),
        _hom(-0.8, 30.3, -17.7, 0.0, 0.0, sc=1.01),
    ]
    frames = jnp.asarray(np.stack([img] * len(cases)))
    Ms = jnp.asarray(np.stack(cases))
    ref, ok_ref = warp_batch_matrix(frames, Ms, max_px=12, with_ok=True)
    out, ok = warp_batch_matrix_pallas(
        frames, Ms, max_px=12, interpret=True, with_ok=True
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # forced strip path identical too
    out2 = warp_batch_matrix_pallas(
        frames, Ms, max_px=12, strip=128, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))


def test_matrix_pallas_over_bound_zeroes_and_flags(img):
    from kcmc_tpu.ops.pallas_warp_field import warp_batch_matrix_pallas

    frames = jnp.asarray(img[None])
    big = jnp.asarray(_hom(8.0, 0.0, 0.0, 0.0, 0.0)[None])  # >> 4 px resid
    out, ok = warp_batch_matrix_pallas(
        frames, big, max_px=4, interpret=True, with_ok=True
    )
    assert not bool(np.asarray(ok)[0])
    assert np.all(np.asarray(out) == 0.0)


def test_fast_apply_matrix_kernel_route_and_fallback(img):
    """fast_apply_matrix: kernel route within tolerance of the gather
    warp; envelope-exceeding transforms take the exact fallback so
    every input still applies."""
    from kcmc_tpu.ops.warp import fast_apply_matrix, warp_batch

    frames = jnp.asarray(np.stack([img] * 3))
    Ms = np.stack([
        _hom(0.0, 3.2, -1.8, 0.0, 0.0),
        _hom(0.6, -2.1, 4.4, 0.0, 0.0),
        _hom(25.0, 0.0, 0.0, 0.0, 0.0),  # far beyond the residual bound
    ])
    out = fast_apply_matrix(frames, jnp.asarray(Ms), force_kernel=True)
    ref = np.asarray(warp_batch(frames, jnp.asarray(Ms)))
    d = np.abs(out - ref)
    assert d.max() < 5e-3, f"max {d.max():.5f}"
    # the fallback frame (index 2) is gather-exact
    np.testing.assert_allclose(out[2], ref[2], atol=1e-5)


def test_fast_apply_fields_kernel_route_and_fallback():
    from kcmc_tpu.ops.warp import fast_apply_fields
    from kcmc_tpu.ops.piecewise import upsample_field
    from kcmc_tpu.ops.warp import warp_frame_flow

    rng = np.random.default_rng(11)
    H = W = 192
    img = synthetic.render_scene(rng, (H, W), n_blobs=80).astype(np.float32)
    frames = jnp.asarray(np.stack([img] * 2))
    f = rng.uniform(-2.0, 2.0, size=(2, 8, 8, 2)).astype(np.float32)
    f[1, :4] = 30.0  # beyond max_px: fallback frame
    f[1, 4:] = -30.0
    fields = jnp.asarray(f)
    out = fast_apply_fields(frames, fields, force_kernel=True)
    flows = jax.vmap(lambda ff: upsample_field(ff, (H, W)))(fields)
    ref = np.asarray(jax.vmap(warp_frame_flow)(frames, flows))
    assert np.abs(out[0] - ref[0]).max() < 5e-3
    np.testing.assert_allclose(out[1], ref[1], atol=1e-5)
