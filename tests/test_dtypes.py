"""Integer-dtype (microscopy uint16 etc.) ingest and output restoration."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse
from kcmc_tpu.utils.synthetic import make_drift_stack


@pytest.fixture(scope="module")
def uint16_data():
    data = make_drift_stack(n_frames=6, shape=(128, 128), model="translation", seed=1)
    stack16 = np.clip(np.rint(data.stack * 60000.0), 0, 65535).astype(np.uint16)
    return data, stack16


def test_uint16_stack_registers_at_full_accuracy(uint16_data):
    """Raw-scale integer input must register as well as float input —
    the detection threshold is contrast-relative."""
    data, stack16 = uint16_data
    mc = MotionCorrector(model="translation", backend="jax")
    res = mc.correct(stack16)
    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), (128, 128)
    )
    assert rmse < 0.25
    assert res.corrected.dtype == np.float32  # default output dtype


def test_output_dtype_input_restores_uint16(uint16_data):
    _, stack16 = uint16_data
    mc = MotionCorrector(model="translation", backend="jax")
    res = mc.correct(stack16, output_dtype="input")
    assert res.corrected.dtype == np.uint16
    # Values are resampled blends of the inputs: same range, rounded.
    assert res.corrected.max() <= 65535
    valid = res.corrected[np.asarray(res.diagnostics["warp_ok"], bool)]
    assert valid.max() > 30000  # content survived the round trip


def test_output_dtype_explicit(uint16_data):
    _, stack16 = uint16_data
    mc = MotionCorrector(model="translation", backend="jax")
    res = mc.correct(stack16, output_dtype=np.float64)
    assert res.corrected.dtype == np.float64


def test_correct_file_preserves_source_dtype(tmp_path, uint16_data):
    from kcmc_tpu.io import TiffStack
    from kcmc_tpu.io.tiff import TiffWriter

    _, stack16 = uint16_data
    src = tmp_path / "src16.tif"
    out = tmp_path / "out16.tif"
    w = TiffWriter(src)
    for fr in stack16:
        w.append(fr)
    w.close()

    mc = MotionCorrector(model="translation", backend="jax")
    mc.correct_file(str(src), output=str(out))
    with TiffStack(out) as ts:
        assert ts.dtype == np.uint16
        frames = np.asarray(ts.read(0, len(ts)))
    assert frames.shape == stack16.shape
    assert frames.max() > 30000


def test_int32_output_boundary_does_not_wrap():
    """ADVICE r2: float32(2**31-1) == 2**31.0, so clipping int32 targets
    against iinfo.max in float32 wrapped boundary values to INT32_MIN on
    the final astype. The clip bounds must be exactly representable."""
    from kcmc_tpu.corrector import _cast_output
    from kcmc_tpu.utils.dtypes import int_clip_bounds

    arr = np.array([2.2e9, -2.2e9, 1234.6], np.float32)
    out = _cast_output(arr, np.dtype(np.int32))
    assert out.dtype == np.int32
    assert out[0] > 0, f"positive saturation wrapped: {out[0]}"
    assert out[1] < 0, f"negative saturation wrapped: {out[1]}"
    assert out[2] == 1235

    lo, hi = int_clip_bounds(np.dtype(np.int32), np.float32)
    assert int(hi) <= np.iinfo(np.int32).max
    assert int(lo) >= np.iinfo(np.int32).min
    lo64, hi64 = int_clip_bounds(np.dtype(np.int64), np.float64)
    assert int(hi64) <= np.iinfo(np.int64).max
    assert int(lo64) >= np.iinfo(np.int64).min


def test_device_cast_int32_boundary_does_not_wrap():
    import jax.numpy as jnp

    from kcmc_tpu.backends.jax_backend import _cast_corrected

    out = np.asarray(
        _cast_corrected(jnp.asarray([2.2e9, -2.2e9], jnp.float32), "int32")
    )
    assert out[0] > 0 and out[1] < 0


def test_plugin_backend_without_native_dtype_flag_gets_float32():
    """ADVICE r2: out-of-tree backends written against the original
    float32 seam must not silently receive integer batches."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.backends import _np_kernels  # noqa: F401 (import check)
    from kcmc_tpu.backends.numpy_backend import NumpyBackend

    seen = []

    class LegacyBackend(NumpyBackend):
        # Simulate a plugin predating the native-dtype seam.
        accepts_native_dtype = False

        def process_batch(self, frames, ref, idx):
            seen.append(np.asarray(frames).dtype)
            return super().process_batch(frames, ref, idx)

    mc = MotionCorrector(model="translation", backend="numpy", batch_size=4)
    mc.backend = LegacyBackend(mc.config)
    stack = (np.random.default_rng(0).uniform(0, 1000, (4, 64, 64))).astype(
        np.uint16
    )
    mc.correct(stack)
    assert seen and all(dt == np.float32 for dt in seen), seen
