"""Integer-dtype (microscopy uint16 etc.) ingest and output restoration."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse
from kcmc_tpu.utils.synthetic import make_drift_stack


@pytest.fixture(scope="module")
def uint16_data():
    data = make_drift_stack(n_frames=6, shape=(128, 128), model="translation", seed=1)
    stack16 = np.clip(np.rint(data.stack * 60000.0), 0, 65535).astype(np.uint16)
    return data, stack16


def test_uint16_stack_registers_at_full_accuracy(uint16_data):
    """Raw-scale integer input must register as well as float input —
    the detection threshold is contrast-relative."""
    data, stack16 = uint16_data
    mc = MotionCorrector(model="translation", backend="jax")
    res = mc.correct(stack16)
    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), (128, 128)
    )
    assert rmse < 0.25
    assert res.corrected.dtype == np.float32  # default output dtype


def test_output_dtype_input_restores_uint16(uint16_data):
    _, stack16 = uint16_data
    mc = MotionCorrector(model="translation", backend="jax")
    res = mc.correct(stack16, output_dtype="input")
    assert res.corrected.dtype == np.uint16
    # Values are resampled blends of the inputs: same range, rounded.
    assert res.corrected.max() <= 65535
    valid = res.corrected[np.asarray(res.diagnostics["warp_ok"], bool)]
    assert valid.max() > 30000  # content survived the round trip


def test_output_dtype_explicit(uint16_data):
    _, stack16 = uint16_data
    mc = MotionCorrector(model="translation", backend="jax")
    res = mc.correct(stack16, output_dtype=np.float64)
    assert res.corrected.dtype == np.float64


def test_correct_file_preserves_source_dtype(tmp_path, uint16_data):
    from kcmc_tpu.io import TiffStack
    from kcmc_tpu.io.tiff import TiffWriter

    _, stack16 = uint16_data
    src = tmp_path / "src16.tif"
    out = tmp_path / "out16.tif"
    w = TiffWriter(src)
    for fr in stack16:
        w.append(fr)
    w.close()

    mc = MotionCorrector(model="translation", backend="jax")
    mc.correct_file(str(src), output=str(out))
    with TiffStack(out) as ts:
        assert ts.dtype == np.uint16
        frames = np.asarray(ts.read(0, len(ts)))
    assert frames.shape == stack16.shape
    assert frames.max() > 30000
