"""Stack I/O: writer round-trips, native-vs-Python decoder parity,
chunked prefetch loader."""

import numpy as np
import pytest

from kcmc_tpu.io import ChunkedStackLoader, TiffStack, read_stack, write_stack
from kcmc_tpu.io.tiff import _PyTiffParser, _get_native


@pytest.fixture(scope="module")
def stacks():
    rng = np.random.default_rng(0)
    return {
        "uint8": rng.integers(0, 255, size=(5, 37, 53), dtype=np.uint8),
        "uint16": rng.integers(0, 65535, size=(4, 64, 48), dtype=np.uint16),
        "int16": rng.integers(-3000, 3000, size=(3, 33, 65), dtype=np.int16),
        "float32": rng.normal(size=(4, 40, 40)).astype(np.float32),
    }


@pytest.mark.parametrize("compression", ["none", "deflate", "packbits"])
@pytest.mark.parametrize("key", ["uint8", "uint16", "int16", "float32"])
def test_write_read_roundtrip(tmp_path, stacks, key, compression):
    path = tmp_path / f"{key}_{compression}.tif"
    write_stack(path, stacks[key], compression=compression)
    out = read_stack(path)
    assert out.dtype == stacks[key].dtype
    np.testing.assert_array_equal(out, stacks[key])


@pytest.mark.parametrize("compression", ["none", "deflate", "packbits"])
def test_native_matches_python_parser(tmp_path, stacks, compression):
    if _get_native() is None:
        pytest.skip("no native toolchain")
    path = tmp_path / f"parity_{compression}.tif"
    write_stack(path, stacks["uint16"], compression=compression)
    ts = TiffStack(path)
    assert ts.backend == "native"
    got = ts.read()
    ts.close()
    py = _PyTiffParser(str(path))
    ref = np.stack([py.read_page(i) for i in range(len(py.pages))])
    py.close()
    np.testing.assert_array_equal(got, ref)


def test_page_range_and_getitem(tmp_path, stacks):
    path = tmp_path / "range.tif"
    write_stack(path, stacks["uint16"])
    with TiffStack(path) as ts:
        assert ts.shape == stacks["uint16"].shape
        np.testing.assert_array_equal(ts.read(1, 3), stacks["uint16"][1:3])
        np.testing.assert_array_equal(ts[2], stacks["uint16"][2])
        np.testing.assert_array_equal(ts[-1], stacks["uint16"][-1])
        np.testing.assert_array_equal(ts[1:4], stacks["uint16"][1:4])


def test_lzw_decode_oracle():
    """LZW bitstreams from a known-good encoder decode correctly (both
    decoders), including table growth past the 9->10 bit width bump."""
    from kcmc_tpu.io.tiff import _lzw_decode_py

    rng = np.random.default_rng(7)
    # Low-entropy data so LZW builds a deep table.
    data = rng.integers(0, 4, size=20000, dtype=np.uint8).tobytes()
    encoded = _lzw_encode_reference(data)
    assert _lzw_decode_py(encoded, len(data)) == data

    if _get_native() is not None:
        # Native parity via a hand-built LZW TIFF.
        import struct

        H, W = 100, 200
        img = np.frombuffer(data, np.uint8)[: H * W].reshape(H, W)
        enc = _lzw_encode_reference(img.tobytes())
        path = "/tmp/kcmc_lzw_test.tif"
        with open(path, "wb") as f:
            f.write(b"II\x2a\x00")
            f.write(struct.pack("<I", 0))
            strip_off = f.tell()
            f.write(enc)
            if f.tell() % 2:
                f.write(b"\0")
            ifd = f.tell()
            f.seek(4)
            f.write(struct.pack("<I", ifd))
            f.seek(ifd)
            entries = [
                (256, 4, 1, W), (257, 4, 1, H), (258, 3, 1, 8), (259, 3, 1, 5),
                (262, 3, 1, 1), (273, 4, 1, strip_off), (277, 3, 1, 1),
                (278, 4, 1, H), (279, 4, 1, len(enc)), (339, 3, 1, 1),
            ]
            f.write(struct.pack("<H", len(entries)))
            for tag, type_, count, value in entries:
                f.write(struct.pack("<HHII", tag, type_, count, value))
            f.write(struct.pack("<I", 0))
        out = read_stack(path)
        np.testing.assert_array_equal(out[0], img)


def _lzw_encode_reference(data: bytes) -> bytes:
    """Minimal TIFF-variant LZW encoder (MSB-first, early change) used
    only to generate test bitstreams."""
    out = bytearray()
    bitbuf, bits = 0, 0
    width = 9

    def put(code):
        nonlocal bitbuf, bits
        bitbuf = (bitbuf << width) | code
        bits += width
        while bits >= 8:
            out.append((bitbuf >> (bits - 8)) & 0xFF)
            bits -= 8

    table = {bytes([i]): i for i in range(256)}
    next_code = 258
    put(256)  # Clear
    w = b""
    for b in data:
        c = bytes([b])
        if w + c in table:
            w = w + c
            continue
        put(table[w])
        table[w + c] = next_code
        next_code += 1
        # Early change, encoder side: the decoder (which lags one table
        # entry behind) bumps width after ITS table reaches 511/1023/2047,
        # so the encoder bumps at 512/1024/2048.
        if next_code == 512:
            width = 10
        elif next_code == 1024:
            width = 11
        elif next_code == 2048:
            width = 12
        elif next_code == 4093:
            put(256)
            table = {bytes([i]): i for i in range(256)}
            next_code = 258
            width = 9
        w = c
    if w:
        put(table[w])
    put(257)  # EOI
    if bits:
        out.append((bitbuf << (8 - bits)) & 0xFF)
    return bytes(out)


def test_chunked_loader_prefetch(tmp_path, stacks):
    path = tmp_path / "chunks.tif"
    write_stack(path, stacks["uint8"])
    got = []
    with ChunkedStackLoader(path, chunk_size=2) as loader:
        for lo, hi, frames in loader:
            got.append((lo, hi, frames))
    assert [(lo, hi) for lo, hi, _ in got] == [(0, 2), (2, 4), (4, 5)]
    np.testing.assert_array_equal(
        np.concatenate([f for _, _, f in got]), stacks["uint8"]
    )


def test_chunked_loader_ndarray_source(stacks):
    arr = stacks["float32"]
    chunks = list(ChunkedStackLoader(arr, chunk_size=3))
    np.testing.assert_array_equal(
        np.concatenate([f for _, _, f in chunks]), arr
    )


def test_bigtiff_write_read_roundtrip(tmp_path):
    """BigTIFF (64-bit offsets) written by TiffWriter reads back exactly
    through both the NumPy reader and (when built) the native decoder."""
    from kcmc_tpu.io import TiffStack, write_stack

    rng = np.random.default_rng(8)
    stack = (rng.random((5, 64, 96)) * 60000).astype(np.uint16)
    p = tmp_path / "big.tif"
    write_stack(p, stack, bigtiff=True)
    assert p.read_bytes()[:4] == b"II\x2b\x00"
    with TiffStack(p) as ts:
        assert len(ts) == 5 and ts.dtype == np.uint16
        np.testing.assert_array_equal(ts.read(0, 5), stack)
    # numpy fallback decoder explicitly
    from kcmc_tpu.io.tiff import _PyTiffParser

    py = _PyTiffParser(str(p))
    got = np.stack([py.read_page(i) for i in range(5)])
    np.testing.assert_array_equal(got, stack)


def test_bigtiff_resume_state(tmp_path):
    """Writer checkpoint/resume round-trips in BigTIFF mode too."""
    from kcmc_tpu.io import TiffStack
    from kcmc_tpu.io.tiff import TiffWriter

    rng = np.random.default_rng(9)
    frames = (rng.random((4, 32, 48)) * 60000).astype(np.uint16)
    p = tmp_path / "b.tif"
    w = TiffWriter(p, bigtiff=True)
    w.append(frames[0])
    w.append(frames[1])
    state = w.checkpoint_state()
    w.append(frames[2])  # torn page: simulated kill after checkpoint
    w.close()
    w2 = TiffWriter.resume(p, state)
    assert w2.bigtiff and w2.n_pages == 2
    w2.append(frames[2])
    w2.append(frames[3])
    w2.close()
    with TiffStack(p) as ts:
        np.testing.assert_array_equal(ts.read(0, 4), frames)


@pytest.mark.parametrize("comp", ["none", "deflate", "packbits"])
def test_append_batch_matches_per_page(tmp_path, comp):
    """append_batch (native parallel deflate when available) must write
    a byte-identical file to per-page appends — resume byte-identity
    must not depend on which encoder ran."""
    from kcmc_tpu.io.tiff import TiffWriter

    rng = np.random.default_rng(3)
    stack = (rng.random((6, 64, 96)) * 60000).astype(np.uint16)
    a, b = tmp_path / "a.tif", tmp_path / "b.tif"
    with TiffWriter(a, compression=comp) as w:
        for fr in stack:
            w.append(fr)
    with TiffWriter(b, compression=comp) as w:
        w.append_batch(stack)
    assert a.read_bytes() == b.read_bytes()

    from kcmc_tpu.io import TiffStack

    with TiffStack(b) as ts:
        np.testing.assert_array_equal(ts.read(0, 6), stack)


def test_append_batch_bigtiff(tmp_path):
    from kcmc_tpu.io import TiffStack
    from kcmc_tpu.io.tiff import TiffWriter

    rng = np.random.default_rng(4)
    stack = (rng.random((4, 48, 64)) * 60000).astype(np.uint16)
    p = tmp_path / "b.tif"
    with TiffWriter(p, compression="deflate", bigtiff=True) as w:
        w.append_batch(stack)
    with TiffStack(p) as ts:
        np.testing.assert_array_equal(ts.read(0, 4), stack)


def test_deflate_checkpoint_records_encoder_and_pins_python(tmp_path):
    """ADVICE r2: resume byte-identity for deflate streams holds only
    under the same zlib build. The checkpoint records the encoder; a
    stream recorded as Python-zlib pins the resumed writer to the Python
    path, and an unreproducible encoder downgrades with a warning."""
    import warnings

    from kcmc_tpu.io.tiff import TiffWriter, _deflate_encoder_id

    rng = np.random.default_rng(0)
    frames = rng.integers(0, 1000, (4, 32, 32), dtype=np.uint16)

    p = tmp_path / "a.tif"
    w = TiffWriter(p, compression="deflate")
    w.append_batch(frames[:2])
    state = w.checkpoint_state()
    w.close()
    assert "encoder" in state and state["encoder"].startswith("py:")

    # Recorded as Python-only: the resumed writer must pin to Python
    # zlib even if the native encoder is available.
    st_py = dict(state, encoder=_deflate_encoder_id(pin_python=True))
    w2 = TiffWriter.resume(p, st_py, compression="deflate")
    assert w2._pin_python_deflate
    w2.append_batch(frames[2:])
    w2.close()
    got = read_stack(p)
    np.testing.assert_array_equal(got, frames)

    # Unreproducible encoder: resume still works, with a warning.
    st_alien = dict(state, encoder="py:0.0-zlib-ng")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        w3 = TiffWriter.resume(p, st_alien, compression="deflate")
        w3.close()
    assert any("byte-identical" in str(r.message) for r in rec)

    # Uncompressed streams carry no encoder key (nothing to pin).
    p2 = tmp_path / "b.tif"
    w4 = TiffWriter(p2)
    w4.append(frames[0])
    assert "encoder" not in w4.checkpoint_state()
    w4.close()
