"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

Validates the SURVEY.md §2 parallelism contract: frame batches sharded
over the mesh, reference descriptors all-gathered, results identical to
the single-device path.
"""

import jax
import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.parallel import make_mesh
from kcmc_tpu.utils import synthetic

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


@pytest.fixture(scope="module")
def data():
    return synthetic.make_drift_stack(
        n_frames=16, shape=(128, 128), model="translation", max_drift=6.0, seed=31
    )


def test_sharded_matches_single_device(data):
    mesh = make_mesh(8)
    r1 = MotionCorrector(model="translation", backend="jax", batch_size=8).correct(data.stack)
    r8 = MotionCorrector(
        model="translation", backend="jax", batch_size=8, mesh=mesh
    ).correct(data.stack)
    # Same algorithm, same keys (folded from global frame index) => the
    # sharded program must reproduce the single-device transforms.
    np.testing.assert_allclose(r8.transforms, r1.transforms, atol=1e-4)
    np.testing.assert_allclose(r8.corrected, r1.corrected, atol=1e-4)


def test_sharded_mesh_sizes(data):
    for n in (2, 4):
        mesh = make_mesh(n)
        res = MotionCorrector(
            model="translation", backend="jax", batch_size=2 * n, mesh=mesh
        ).correct(data.stack[: 2 * n])
        assert res.transforms.shape == (2 * n, 3, 3)
        assert np.isfinite(res.transforms).all()


def test_graft_entry_dryrun():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_graft_entry_single():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = fn(*args)
    out = jax.tree.map(np.asarray, out)
    assert out["transform"].shape[0] == args[0].shape[0]
    assert np.isfinite(out["corrected"]).all()


def test_shard_host_local_frames_single_process():
    """Single-process degenerate case: local frames == global batch."""
    import numpy as np
    from kcmc_tpu.parallel import make_mesh
    from kcmc_tpu.parallel import shard_host_local_frames

    mesh = make_mesh(4)
    frames = np.random.default_rng(0).random((8, 16, 16)).astype(np.float32)
    arr = shard_host_local_frames(frames, mesh)
    assert arr.shape == (8, 16, 16)
    np.testing.assert_allclose(np.asarray(arr), frames)


def test_sharded_piecewise_matches_single_device():
    """The dense-field (config 3) pipeline under shard_map must
    reproduce the single-device fields exactly."""
    data = synthetic.make_piecewise_stack(
        n_frames=8, shape=(128, 128), max_disp=4.0, seed=33
    )
    r1 = MotionCorrector(
        model="piecewise", backend="jax", batch_size=8
    ).correct(data.stack)
    r8 = MotionCorrector(
        model="piecewise", backend="jax", batch_size=8, mesh=make_mesh(8)
    ).correct(data.stack)
    np.testing.assert_allclose(r8.fields, r1.fields, atol=1e-4)
    np.testing.assert_allclose(r8.corrected, r1.corrected, atol=1e-4)


def test_sharded_rigid3d_matches_single_device():
    """The volumetric (config 5) pipeline under shard_map must
    reproduce the single-device transforms."""
    data = synthetic.make_drift_stack_3d(
        n_frames=8, shape=(16, 64, 64), max_drift=2.0, seed=34
    )
    r1 = MotionCorrector(
        model="rigid3d", backend="jax", batch_size=8
    ).correct(data.stack)
    r8 = MotionCorrector(
        model="rigid3d", backend="jax", batch_size=8, mesh=make_mesh(8)
    ).correct(data.stack)
    np.testing.assert_allclose(r8.transforms, r1.transforms, atol=1e-4)
    np.testing.assert_allclose(r8.corrected, r1.corrected, atol=1e-4)


def test_mesh_keypoint_padding(data):
    """Round 6 replaces the old hard K % n_devices == 0 constructor
    error with mesh padding: the prepared reference's keypoint arrays
    gain masked (valid=False) rows up to the next device-count
    multiple, so ANY max_keypoints (including octave-merged totals
    like 1032 on a 7-device mesh) shards — and results still match the
    single-device path."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.parallel import make_mesh

    # the old traps now construct fine (1032 % 7 = 3; 100 % 8 = 4)
    MotionCorrector(
        model="similarity", backend="jax", mesh=make_mesh(7),
        n_octaves=3, max_keypoints=1024,
    )
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        mesh=make_mesh(8), max_keypoints=100,
    )
    # the padded reference: K rounded up to the mesh, pad rows masked
    ref = mc.backend.prepare_reference(
        np.asarray(data.stack[0], np.float32)
    )
    assert ref["xy"].shape[0] == 104  # 100 -> next multiple of 8
    assert not np.asarray(ref["valid"])[100:].any()
    r8 = mc.correct(data.stack)
    r1 = MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        max_keypoints=100,
    ).correct(data.stack)
    np.testing.assert_allclose(r8.transforms, r1.transforms, atol=1e-4)


def test_numpy_backend_rejects_banded_config():
    """ADVICE r4: the numpy oracle has no banded-matching mirror; a
    match_radius config must refuse rather than silently run the dense
    matcher with different semantics."""
    import pytest

    from kcmc_tpu import MotionCorrector

    with pytest.raises(ValueError, match="banded"):
        MotionCorrector(
            model="translation", backend="numpy", match_radius=32.0
        )
