"""Gather-free flow/homography warps vs the jnp gather implementations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.warp import warp_batch, warp_frame_flow
from kcmc_tpu.ops.warp_field import warp_batch_flow, warp_batch_homography
from kcmc_tpu.utils import synthetic


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(7)
    return synthetic.render_scene(rng, (192, 192), n_blobs=90).astype(np.float32)


def _bilerp_field(coarse, shape):
    """Bilinearly upsample a coarse (gh, gw, 2) field to a dense one."""
    gh, gw, _ = coarse.shape
    H, W = shape
    yi = np.linspace(0, gh - 1, H)
    xi = np.linspace(0, gw - 1, W)
    y0 = np.clip(yi.astype(int), 0, gh - 2)
    x0 = np.clip(xi.astype(int), 0, gw - 2)
    fy = (yi - y0)[:, None, None]
    fx = (xi - x0)[None, :, None]
    c00 = coarse[y0][:, x0]
    c01 = coarse[y0][:, x0 + 1]
    c10 = coarse[y0 + 1][:, x0]
    c11 = coarse[y0 + 1][:, x0 + 1]
    return (
        c00 * (1 - fy) * (1 - fx)
        + c01 * (1 - fy) * fx
        + c10 * fy * (1 - fx)
        + c11 * fy * fx
    ).astype(np.float32)


def test_flow_warp_matches_gather(img):
    H, W = img.shape
    rng = np.random.default_rng(1)
    flows = []
    for t in [(0, 0), (4.7, -3.1), (-9.4, 6.2)]:
        coarse = rng.uniform(-2.5, 2.5, size=(5, 5, 2)).astype(np.float32)
        flows.append(_bilerp_field(coarse, (H, W)) + np.asarray(t, np.float32))
    flows = jnp.asarray(np.stack(flows))
    frames = jnp.asarray(np.stack([img] * 3))
    ref = np.asarray(jax.vmap(warp_frame_flow)(frames, flows))
    # joint mode: exact 2D bilinear
    exact = np.asarray(warp_batch_flow(frames, flows, max_px=6, joint=True))
    np.testing.assert_allclose(exact, ref, atol=2e-4)
    # default two-pass split: O(|u| * |grad u|) from one-shot bilinear
    fast = np.asarray(warp_batch_flow(frames, flows, max_px=6))
    d = np.abs(fast - ref)
    assert d.mean() < 2e-3, f"mean diff {d.mean():.5f}"
    assert d.max() < 0.2, f"max diff {d.max():.4f}"


def test_flow_residual_out_of_bounds_zeroes(img):
    H, W = img.shape
    flow = np.zeros((1, H, W, 2), np.float32)
    flow[0, : H // 2] = 10.0  # residual after mean removal >> bound
    flow[0, H // 2 :] = -10.0
    out = np.asarray(warp_batch_flow(jnp.asarray(img[None]), jnp.asarray(flow), max_px=4))
    assert np.all(out == 0.0)


def _hom(theta_deg, tx, ty, g, h, c=95.5):
    th = np.deg2rad(theta_deg)
    R = np.array(
        [[np.cos(th), -np.sin(th), 0], [np.sin(th), np.cos(th), 0], [0, 0, 1.0]]
    )
    C = np.array([[1, 0, c], [0, 1, c], [0, 0, 1.0]])
    Ci = np.array([[1, 0, -c], [0, 1, -c], [0, 0, 1.0]])
    T = np.array([[1, 0, tx], [0, 1, ty], [0, 0, 1.0]])
    M = (C @ R @ Ci @ T).astype(np.float64)
    M[2, 0] = g
    M[2, 1] = h
    return M.astype(np.float32)


def test_homography_warp_close_to_gather(img):
    cases = [
        _hom(0.0, 0.0, 0.0, 0.0, 0.0),
        _hom(0.0, 5.2, -3.8, 2e-5, -1.5e-5),
        _hom(1.2, -4.1, 2.6, -2e-5, 2e-5),
    ]
    frames = jnp.asarray(np.stack([img] * len(cases)))
    Ms = jnp.asarray(np.stack(cases))
    fast = np.asarray(warp_batch_homography(frames, Ms, shear_px=8, max_px=4))
    ref = np.asarray(warp_batch(frames, Ms))
    d = np.abs(fast - ref)[:, 16:-16, 16:-16]
    assert d.mean() < 5e-3, f"mean interior diff {d.mean():.4f}"
    assert d.max() < 0.15, f"max interior diff {d.max():.4f}"


def test_homography_pipeline_auto_matches_jnp(img):
    """On CPU, auto falls back to the gather warp; force comparison of the
    two homography paths directly at the pipeline level instead."""
    from kcmc_tpu import MotionCorrector

    data = synthetic.make_drift_stack(
        n_frames=4, shape=(160, 160), model="homography", max_drift=5.0, seed=4
    )
    res = MotionCorrector(model="homography", backend="jax", batch_size=4).correct(
        data.stack
    )
    fast = np.asarray(
        warp_batch_homography(
            jnp.asarray(data.stack, jnp.float32),
            jnp.asarray(res.transforms),
            shear_px=8,
            max_px=4,
        )
    )
    d = np.abs(fast - res.corrected)[:, 16:-16, 16:-16]
    assert d.mean() < 5e-3


def test_rigid3d_warp_close_to_gather():
    from kcmc_tpu.ops.warp import warp_volume
    from kcmc_tpu.ops.warp_field import warp_batch_rigid3d
    from kcmc_tpu.utils.synthetic import make_drift_stack_3d

    data = make_drift_stack_3d(n_frames=3, shape=(16, 64, 64), seed=5)
    vols = jnp.asarray(data.stack)
    Ms = jnp.asarray(data.transforms)
    fast, ok = warp_batch_rigid3d(vols, Ms, max_px=6, with_ok=True)
    assert np.all(np.asarray(ok))
    ref = np.stack([np.asarray(warp_volume(vols[i], Ms[i])) for i in range(3)])
    d = np.abs(np.asarray(fast) - ref)[:, 2:-2, 8:-8, 8:-8]
    assert d.mean() < 5e-3, f"mean interior diff {d.mean():.4f}"
    assert d.max() < 0.2, f"max interior diff {d.max():.4f}"


def test_rigid3d_warp_out_of_bounds_zeroes():
    from kcmc_tpu.ops.warp_field import warp_batch_rigid3d

    rng = np.random.default_rng(0)
    vol = jnp.asarray(rng.random((8, 32, 32), dtype=np.float32)[None])
    M = np.eye(4, dtype=np.float32)
    th = 0.6  # ~34 deg: residual far beyond bound
    M[0, 0] = M[1, 1] = np.cos(th)
    M[0, 1] = -np.sin(th)
    M[1, 0] = np.sin(th)
    out, ok = warp_batch_rigid3d(vol, jnp.asarray(M[None]), max_px=2, with_ok=True)
    assert not bool(np.asarray(ok)[0])
    assert np.all(np.asarray(out) == 0.0)


def _matrix_cases():
    c = 95.5  # (192 - 1) / 2
    out = []
    M = np.eye(3, dtype=np.float32)
    M[0, 2], M[1, 2] = 3.3, -2.7
    out.append(M)
    th = 0.03
    co, si = np.cos(th), np.sin(th)
    M = np.eye(3, dtype=np.float32)
    M[:2, :2] = [[co, -si], [si, co]]
    M[:2, 2] = [3.3 + c - co * c + si * c, -2.7 + c - si * c - co * c]
    out.append(M)
    M2 = M.copy()
    M2[0, 0] *= 1.015
    M2[1, 1] *= 0.99
    out.append(M2)
    M3 = M2.copy()
    M3[2, 0], M3[2, 1] = 2e-5, -1.5e-5
    out.append(M3)
    return out


def test_matrix_warp_matches_gather(img):
    """The round-5 single-interpolation kernel must match one-shot
    bilinear (the gather warp) to ~1e-3 pixel VALUES — two orders
    tighter than the 4-pass separable chain's bound above. This is the
    property the photometric polish depends on: the polish converges
    to the warp's photometric optimum, so warp artifact becomes
    transform error (measured 0.055 px for homography pre-kernel)."""
    from kcmc_tpu.ops.warp_field import warp_batch_matrix

    cases = _matrix_cases()
    frames = jnp.asarray(np.stack([img] * len(cases)))
    Ms = jnp.asarray(np.stack(cases))
    fast, ok = warp_batch_matrix(frames, Ms, max_px=12, with_ok=True)
    assert np.asarray(ok).all()
    ref = np.asarray(warp_batch(frames, Ms))
    d = np.abs(np.asarray(fast) - ref)[:, 16:-16, 16:-16]
    # measured (2026-08-01, 512² scene): max 0.0016, rms 5e-5 —
    # bounds at ~3x measured
    assert d.max() < 5e-3, f"max interior diff {d.max():.5f}"
    assert np.sqrt((d**2).mean()) < 3e-4


def test_matrix_warp_out_of_bounds_zeroes(img):
    from kcmc_tpu.ops.warp_field import warp_batch_matrix

    th = 0.25  # ~14 deg: corner residual ~ 33 px >> max_px
    co, si = np.cos(th), np.sin(th)
    c = 95.5
    M = np.eye(3, dtype=np.float32)
    M[:2, :2] = [[co, -si], [si, co]]
    M[:2, 2] = [c - co * c + si * c, c - si * c - co * c]
    out, ok = warp_batch_matrix(
        jnp.asarray(img)[None], jnp.asarray(M)[None], max_px=12, with_ok=True
    )
    assert not np.asarray(ok)[0]
    assert np.all(np.asarray(out) == 0.0)


def test_matrix_warp_translation_exact(img):
    """Pure translation goes through the kernel's canvas + fractional
    pass only — bit-near the gather warp everywhere (no consumer
    correction involved: uy is constant)."""
    from kcmc_tpu.ops.warp_field import warp_batch_matrix

    M = np.eye(3, dtype=np.float32)
    M[0, 2], M[1, 2] = -7.36, 11.84
    out = warp_batch_matrix(jnp.asarray(img)[None], jnp.asarray(M)[None], max_px=12)
    ref = np.asarray(warp_batch(jnp.asarray(img)[None], jnp.asarray(M)[None]))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)
