"""The shared fixed-capacity dispatch primitive (ops/dispatch.py):
stability, and the runtime key clamp that keeps a corrupted key from
scrambling the whole packed sort (ADVICE r5)."""

import jax.numpy as jnp
import numpy as np

from kcmc_tpu.ops.dispatch import segment_by_key, stable_argsort_small_keys


def test_stable_argsort_matches_numpy_stable(rng):
    keys = rng.integers(0, 7, size=100).astype(np.int32)
    order, sk = stable_argsort_small_keys(jnp.asarray(keys), 7)
    np.testing.assert_array_equal(
        np.asarray(order), np.argsort(keys, kind="stable")
    )
    np.testing.assert_array_equal(np.asarray(sk), np.sort(keys))


def test_out_of_range_keys_clamp_instead_of_corrupting():
    """A negative key would shift into the index bits (or the sign bit)
    and scramble EVERY item's order; the clamp keeps the damage local —
    the result is exactly the stable argsort of the clamped keys."""
    keys = np.array([-5, 0, 3, 99, 2, -1, 3], np.int32)
    order, sk = stable_argsort_small_keys(jnp.asarray(keys), 4)
    clamped = np.clip(keys, 0, 4)
    np.testing.assert_array_equal(
        np.asarray(order), np.argsort(clamped, kind="stable")
    )
    np.testing.assert_array_equal(np.asarray(sk), np.sort(clamped))


def test_segment_by_key_basic_grouping_and_overflow():
    keys = np.array([1, 0, 1, 2, 1, 0, 1], np.int32)
    idx, ok = segment_by_key(jnp.asarray(keys), 3, cap=3)
    idx, ok = np.asarray(idx), np.asarray(ok)
    np.testing.assert_array_equal(idx[0][ok[0]], [1, 5])
    # stable within the group; overflow drops the LAST items
    np.testing.assert_array_equal(idx[1][ok[1]], [0, 2, 4])
    np.testing.assert_array_equal(idx[2][ok[2]], [3])


def test_segment_by_key_out_of_range_ids_stay_local():
    """ids > n_groups clamp to the drop sentinel; a negative id clamps
    into group 0 (wrong for that item, documented) — but OTHER items'
    grouping must be untouched either way."""
    keys = np.array([1, -3, 0, 1, 7, 2, 1], np.int32)
    idx, ok = segment_by_key(jnp.asarray(keys), 3, cap=4)
    idx, ok = np.asarray(idx), np.asarray(ok)
    np.testing.assert_array_equal(idx[1][ok[1]], [0, 3, 6])
    np.testing.assert_array_equal(idx[2][ok[2]], [5])
    # the -3 joins group 0 (clamped), the 7 is dropped entirely
    np.testing.assert_array_equal(idx[0][ok[0]], [1, 2])
    kept = np.concatenate([idx[g][ok[g]] for g in range(3)])
    assert 4 not in kept
