"""Unit suite for the request-latency histogram layer (obs/latency.py).

Pins the properties the telemetry plane is built on:

* deterministic bucket edges (a pure function of the index — the
  cross-process merge contract);
* EXACT mergeability: associative, commutative, split-independent
  (bit-identical dicts), round-trippable through JSON;
* quantile accuracy: estimates within the documented ~9% relative
  bound of exact percentiles on known distributions;
* concurrent-record safety through `SegmentLatencies` (run under
  `--sanitize` in CI);
* the shared summary schema and the Prometheus text exposition.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from kcmc_tpu.obs.latency import (
    _EDGES_NS,
    PER_OCTAVE,
    T0_NS,
    LatencyHistogram,
    SegmentLatencies,
    merge_histograms,
    render_prometheus,
)

SUMMARY_KEYS = {"count", "sum_s", "p50_s", "p90_s", "p99_s", "max_s"}


# -- bucket-edge determinism -------------------------------------------------


def test_edges_are_deterministic_integer_geometric_ladder():
    # recomputing the ladder from the scheme constants reproduces it
    # exactly — the property that makes cross-process merges line up
    recomputed = tuple(
        round(T0_NS * 2.0 ** (i / PER_OCTAVE)) for i in range(len(_EDGES_NS))
    )
    assert recomputed == _EDGES_NS
    assert all(isinstance(e, int) for e in _EDGES_NS)
    assert all(b > a for a, b in zip(_EDGES_NS, _EDGES_NS[1:]))
    assert _EDGES_NS[0] == T0_NS
    assert _EDGES_NS[PER_OCTAVE] == 2 * T0_NS  # one octave doubles


def test_record_is_order_independent_bit_identical():
    rng = np.random.default_rng(7)
    vals = rng.lognormal(-7, 2.0, 500)
    h1, h2 = LatencyHistogram(), LatencyHistogram()
    for v in vals:
        h1.record(v)
    for v in reversed(vals):
        h2.record(v)
    assert h1.to_dict() == h2.to_dict()


def test_to_from_dict_round_trip_and_scheme_guard():
    h = LatencyHistogram()
    for v in (1e-7, 3e-4, 0.5, 2.0, 500.0):  # incl. under/overflow
        h.record(v)
    d = h.to_dict()
    assert LatencyHistogram.from_dict(json.loads(json.dumps(d))).to_dict() == d
    bad = dict(d, scheme={"t0_ns": 1, "per_octave": 1, "octaves": 1})
    with pytest.raises(ValueError, match="scheme"):
        LatencyHistogram.from_dict(bad)


# -- exact mergeability ------------------------------------------------------


def _hist_of(vals) -> LatencyHistogram:
    h = LatencyHistogram()
    for v in vals:
        h.record(float(v))
    return h


def test_merge_equals_single_stream_bit_identical():
    rng = np.random.default_rng(0)
    vals = rng.lognormal(-6, 1.5, 3000)
    merged = merge_histograms(
        _hist_of(vals[:1000]), _hist_of(vals[1000:1700]),
        _hist_of(vals[1700:]),
    )
    assert merged.to_dict() == _hist_of(vals).to_dict()


def test_merge_associative_and_commutative():
    rng = np.random.default_rng(1)
    parts = [
        _hist_of(rng.lognormal(-6 + i, 1.0, 200)) for i in range(3)
    ]
    a, b, c = parts
    ab_c = merge_histograms(merge_histograms(a, b), c).to_dict()
    a_bc = merge_histograms(a, merge_histograms(b, c)).to_dict()
    cba = merge_histograms(c, b, a).to_dict()
    assert ab_c == a_bc == cba


def test_merge_with_empty_is_identity():
    h = _hist_of([0.01, 0.02])
    assert merge_histograms(h, LatencyHistogram()).to_dict() == h.to_dict()
    assert merge_histograms(LatencyHistogram()).count == 0


# -- quantile accuracy -------------------------------------------------------

# documented bound: geometric-midpoint estimate within 2^(1/8)-1 of the
# exact value (plus a hair for edge rounding)
REL_BOUND = 2 ** (1 / 8) - 1 + 0.01


@pytest.mark.parametrize(
    "dist",
    [
        lambda rng: rng.lognormal(-6, 1.5, 5000),
        lambda rng: rng.uniform(1e-4, 5e-2, 5000),
        lambda rng: rng.exponential(3e-3, 5000),
    ],
)
def test_quantile_accuracy_bound_vs_exact(dist):
    rng = np.random.default_rng(42)
    vals = dist(rng)
    h = _hist_of(vals)
    for q in (50, 90, 99):
        exact = float(np.percentile(vals, q))
        est = h.quantile(q)
        assert est is not None
        assert abs(est - exact) / exact <= REL_BOUND, (q, est, exact)


def test_quantile_edge_cases():
    assert LatencyHistogram().quantile(50) is None
    h = _hist_of([0.25])  # single sample: every quantile is ~it
    for q in (1, 50, 99):
        est = h.quantile(q)
        assert abs(est - 0.25) / 0.25 <= REL_BOUND
    # estimates are clamped to the observed max (p99 can never exceed
    # the largest recorded value)
    h2 = _hist_of([1e-3] * 99 + [7.0])
    assert h2.quantile(100) <= 7.0 + 1e-9
    # negative/zero durations clamp into the first bucket, not a crash
    h3 = LatencyHistogram()
    h3.record(-1.0)
    h3.record(0.0)
    assert h3.count == 2 and h3.sum_ns == 0


def test_summary_schema_is_the_shared_one():
    s = _hist_of([1e-3, 2e-3, 3e-3]).summary()
    assert set(s) == SUMMARY_KEYS
    assert s["count"] == 3
    assert s["max_s"] == pytest.approx(3e-3, rel=1e-6)
    empty = LatencyHistogram().summary()
    assert set(empty) == SUMMARY_KEYS
    assert empty["p99_s"] is None and empty["count"] == 0


# -- SegmentLatencies (concurrent recorder) ----------------------------------


def test_concurrent_observe_loses_nothing():
    """8 threads × 2000 records through the one recorder lock: total
    counts and integer sums must be exact (runs under --sanitize in
    the CI observability lane — the lock is the contract)."""
    lat = SegmentLatencies()
    N, T = 2000, 8

    def worker(i):
        for k in range(N):
            lat.observe(
                "request.total", 1e-4 * ((i + k) % 13 + 1),
                rung="full" if i % 2 else "degraded",
            )

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert lat.count == N * T
    rep = lat.report()
    rungs = rep["segments"]["request.total"]
    assert rungs["full"]["count"] + rungs["degraded"]["count"] == N * T
    assert rep["totals"]["request.total"]["count"] == N * T


def test_merge_from_and_segment_total_are_exact():
    a, b = SegmentLatencies(), SegmentLatencies()
    a.observe("request.device", 0.01, n=3)
    a.observe("request.device", 0.02, rung="degraded")
    b.observe("request.device", 0.04)
    b.observe("request.total", 0.05)
    plane = SegmentLatencies()
    plane.merge_from(a)
    plane.merge_from(b)
    assert plane.hist_dicts()["request.device"]["full"]["count"] == 4
    tot = plane.segment_total("request.device")
    assert tot.count == 5  # both rungs merged
    # bit-identity vs recording everything into one recorder
    one = SegmentLatencies()
    one.observe("request.device", 0.01, n=3)
    one.observe("request.device", 0.02, rung="degraded")
    one.observe("request.device", 0.04)
    one.observe("request.total", 0.05)
    assert plane.hist_dicts() == one.hist_dicts()


# -- Prometheus exposition ---------------------------------------------------


def _fake_metrics():
    lat = SegmentLatencies()
    for v in (1e-4, 5e-4, 2e-3, 2e-3, 0.5):
        lat.observe("request.total", v)
    lat.observe("request.queue_wait", 3e-4, n=2, rung="degraded")
    return {
        "plane": {"histograms": lat.hist_dicts()},
        "counters": {"frames_done": 42, "rejected_frames": 0},
        "gauges": {
            "sessions_open": 2,
            "loop_beat_age_s": 0.25,
            "queues": {"s0001": 3, 'we"ird': 1},
        },
    }


def test_render_prometheus_format_and_cumulative_buckets():
    text = render_prometheus(_fake_metrics())
    lines = text.strip().splitlines()
    assert text.endswith("\n")
    # exposition shape: TYPE lines precede their series
    assert "# TYPE kcmc_request_latency_seconds histogram" in lines
    assert "# TYPE kcmc_serve_frames_done_total counter" in lines
    assert "kcmc_serve_frames_done_total 42" in lines
    assert "kcmc_serve_sessions_open 2" in lines
    assert 'kcmc_serve_queue_frames{session="s0001"} 3' in lines
    assert 'session="we\\"ird"' in text  # label escaping
    # cumulative bucket counts are monotone and +Inf == count per series
    series: dict[str, list[tuple[float | None, int]]] = {}
    for ln in lines:
        if ln.startswith("kcmc_request_latency_seconds_bucket"):
            labels = ln[ln.index("{") + 1 : ln.index("}")]
            le = [
                kv.split("=")[1].strip('"')
                for kv in labels.split(",")
                if kv.startswith("le=")
            ][0]
            key = ",".join(
                kv for kv in labels.split(",") if not kv.startswith("le=")
            )
            series.setdefault(key, []).append(
                (None if le == "+Inf" else float(le), int(ln.split()[-1]))
            )
    assert series, "no bucket series rendered"
    for key, buckets in series.items():
        counts = [c for _, c in buckets]
        assert counts == sorted(counts), (key, buckets)
        assert buckets[-1][0] is None, f"{key} missing +Inf"
        count_line = [
            ln
            for ln in lines
            if ln.startswith(f"kcmc_request_latency_seconds_count{{{key}}}")
        ]
        assert count_line and int(count_line[0].split()[-1]) == counts[-1]


def test_render_prometheus_empty_payload():
    text = render_prometheus({})
    assert text == "\n"
    # and a payload with only counters still renders
    text = render_prometheus({"counters": {"frames_done": 0}})
    assert "kcmc_serve_frames_done_total 0" in text
