"""Cloud-ingest/egress resilience (io/objectstore.py): the emulated
object store, hedged/retried/verified range reads, the `object` fault
surface, and crash-resumable sharded egress with the durable
high-water-mark manifest — the chaos contract is byte-identity: a
fault-storm run and a kill+resume run must both produce exactly the
chunk set of an uninterrupted run, with zero lost or duplicated
frames."""

import hashlib
import time
import warnings

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import ChunkedStackLoader, open_stack, put_stack
from kcmc_tpu.io import objectstore
from kcmc_tpu.io.formats import make_writer, resume_writer
from kcmc_tpu.io.objectstore import (
    MANIFEST_KEY,
    PREV_MANIFEST_KEY,
    _HEDGE_WARMUP,
    EmulatedObjectStore,
    ObjectIntegrityError,
    ObjectNotFound,
    ObjectStack,
    ObjectStoreThrottled,
    ObjectStoreWriter,
    client_for_url,
    load_manifest,
    reset_url_state,
    stats_snapshot,
)
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.faults import FaultPlan, RetryPolicy, classify_transient
from kcmc_tpu.utils.metrics import (
    RobustnessReport,
    relative_transforms,
    transform_rmse,
)

SHAPE = (128, 128)
T = 24
# near-zero backoff: these tests exercise retry LOGIC, not the sleeps
FAST = RetryPolicy(seed=0, backoff_s=1e-4, backoff_max_s=2e-4)
FAST_CFG = dict(retry_backoff_s=1e-4, retry_backoff_max_s=2e-4)


@pytest.fixture(scope="module")
def drift():
    return synthetic.make_drift_stack(
        n_frames=T, shape=SHAPE, model="translation", max_drift=5.0, seed=7
    )


@pytest.fixture(scope="module")
def arr(drift):
    return np.clip(drift.stack * 40000, 0, 65535).astype(np.uint16)


@pytest.fixture(autouse=True)
def _fresh_url_state():
    # hedge histograms / counters are module-global per URL; isolate
    # tests from each other's latency history.  Joining the lazy hedge
    # pool keeps kcmc-objget workers from outliving the test (the
    # --sanitize leak checker would flag them).
    reset_url_state()
    yield
    objectstore._shutdown_hedge_pool(wait=True)
    reset_url_state()


def _fast(url, **arm):
    return ObjectStack(url).arm(retry=FAST, **arm)


def _chunkset(client, prefix=""):
    """{key: sha} of a stack's data objects + current manifest — the
    byte-identity unit (the .prev generation is a rewind artifact)."""
    return {
        k: hashlib.sha256(client.get(k)).hexdigest()
        for k in client.list(prefix)
        if not k.endswith(PREV_MANIFEST_KEY)
    }


# -- emulator + layout -----------------------------------------------------


def test_roundtrip_and_ranged_reads(tmp_path):
    rng = np.random.default_rng(0)
    stack = rng.integers(0, 60000, (50, 8, 9), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=7)
    with open_stack(url) as ts:
        assert len(ts) == 50
        assert ts.frame_shape == (8, 9)
        assert ts.dtype == np.uint16
        np.testing.assert_array_equal(ts.read(0, 50), stack)
        # spans crossing chunk boundaries, single frames, tails
        np.testing.assert_array_equal(ts.read(3, 23), stack[3:23])
        np.testing.assert_array_equal(ts.read(6, 8), stack[6:8])
        np.testing.assert_array_equal(ts.read(49, 50), stack[49:50])
    # raw layout: sub-chunk spans move as genuine range requests (one
    # GET per touched chunk, not per frame)
    snap = stats_snapshot(url)
    assert snap["gets"] >= 4


def test_deflate_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    stack = rng.integers(0, 60000, (30, 8, 9), dtype=np.uint16)
    url = f"emu://{tmp_path}/bz"
    put_stack(url, stack, chunk_frames=7, compression="deflate")
    with open_stack(url) as ts:
        assert ts.compression == "deflate"
        np.testing.assert_array_equal(ts.read(5, 26), stack[5:26])


def test_multipart_staging_invisible_until_complete(tmp_path):
    store = EmulatedObjectStore(tmp_path / "b")
    uid = store.multipart_begin("big")
    store.multipart_put_part("big", uid, 0, b"aaaa")
    store.multipart_put_part("big", uid, 1, b"bbbb")
    # staged parts are not listable objects — a kill here leaves no
    # torn "big"
    assert store.list("") == []
    with pytest.raises(ObjectNotFound):
        store.head("big")
    etag = store.multipart_complete("big", uid, 2)
    assert store.get("big") == b"aaaabbbb"
    assert etag == hashlib.sha256(b"aaaabbbb").hexdigest()
    # a missing part fails complete instead of assembling garbage
    uid2 = store.multipart_begin("torn")
    store.multipart_put_part("torn", uid2, 0, b"x")
    with pytest.raises(OSError, match="missing part"):
        store.multipart_complete("torn", uid2, 2)
    store.multipart_abort("torn", uid2)
    assert store.list("") == ["big"]


def test_unregistered_scheme_points_at_the_seam(tmp_path):
    with pytest.raises(ValueError, match="register_scheme"):
        client_for_url("s3://bucket/stack")


# -- fault surface: drop / stall / truncate / flip / throttle --------------


def test_drop_is_retried_and_counted(tmp_path, request):
    rng = np.random.default_rng(2)
    stack = rng.integers(0, 60000, (40, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=8)
    rep = RobustnessReport()
    plan = FaultPlan.from_spec("object:step=2:drop", seed=1)
    ts = _fast(url, fault_plan=plan, report=rep)
    np.testing.assert_array_equal(ts.read(0, 40), stack)
    assert rep.io_retries == 1
    assert stats_snapshot(url)["retries"] == 1


def test_throttle_retried_and_advises_once(tmp_path):
    rng = np.random.default_rng(3)
    stack = rng.integers(0, 60000, (40, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=8)
    rep = RobustnessReport()
    plan = FaultPlan.from_spec("object:step=1:throttle", seed=1)
    ts = _fast(url, fault_plan=plan, report=rep)
    with pytest.warns(RuntimeWarning, match="object-store path degrading"):
        np.testing.assert_array_equal(ts.read(0, 40), stack)
    assert stats_snapshot(url)["throttled"] == 1
    # once per run: further reads must not re-warn
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ts.read(0, 8)
    # the exception class itself classifies transient (OSError family)
    assert classify_transient(ObjectStoreThrottled("429"))


def test_bitflip_in_flight_refetches(tmp_path):
    """A flipped body whose STORED copy is intact is wire corruption:
    refetch, never quarantine."""
    rng = np.random.default_rng(4)
    stack = rng.integers(0, 60000, (40, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=8)
    rep = RobustnessReport()
    # step=2: ops 0/1 are the constructor's manifest GET+HEAD draw-free
    # ops; the armed plan sees the first whole-chunk GET at index 0, so
    # read a middle span whose second GET (index 1... ) — simplest: hit
    # every chunk and let the clause land on one of the 5 GETs
    plan = FaultPlan.from_spec("object:step=2:flip", seed=1)
    ts = _fast(url, fault_plan=plan, report=rep)
    np.testing.assert_array_equal(ts.read(0, 40), stack)
    snap = stats_snapshot(url)
    assert snap["refetched"] == 1
    assert rep.quarantined_parts == []  # stored copy was fine


def test_truncated_body_retried_on_ranged_get(tmp_path):
    rng = np.random.default_rng(5)
    stack = rng.integers(0, 60000, (40, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=8)
    plan = FaultPlan.from_spec("object:step=0:truncate", seed=1)
    ts = _fast(url, fault_plan=plan)
    # sub-chunk span -> ranged GET; the exact-length check catches the
    # short body and retries
    np.testing.assert_array_equal(ts.read(3, 5), stack[3:5])
    assert stats_snapshot(url)["retries"] == 1


def test_stall_capped_by_per_attempt_deadline(tmp_path):
    rng = np.random.default_rng(6)
    stack = rng.integers(0, 60000, (16, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=4)
    plan = FaultPlan.from_spec("object:step=1:stall=30", seed=1)
    ts = ObjectStack(url).arm(
        fault_plan=plan,
        retry=RetryPolicy(seed=0, backoff_s=1e-4, deadline_s=0.05),
    )
    t0 = time.perf_counter()
    np.testing.assert_array_equal(ts.read(0, 8), stack[:8])
    # the wedged GET cost one deadline, not the 30 s stall
    assert time.perf_counter() - t0 < 2.0


def test_at_rest_corruption_quarantines_and_aborts(tmp_path):
    rng = np.random.default_rng(7)
    stack = rng.integers(0, 60000, (40, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=8)
    client = client_for_url(url)
    body = bytearray(client.get("chunk-00000001"))
    body[4] ^= 0xFF
    client.put("chunk-00000001", bytes(body))
    rep = RobustnessReport()
    ts = _fast(url, report=rep)
    with pytest.raises(ObjectIntegrityError, match="quarantined"):
        ts.read(0, 40)
    assert client.list("chunk-00000001.corrupt") == [
        "chunk-00000001.corrupt"
    ]
    assert len(rep.quarantined_parts) == 1


# -- hedged reads ----------------------------------------------------------


def test_hedge_fires_past_p95_and_first_wins(tmp_path):
    rng = np.random.default_rng(8)
    stack = rng.integers(0, 60000, (64, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=4)
    ts = ObjectStack(url).arm(
        retry=RetryPolicy(seed=0, backoff_s=1e-4, deadline_s=10.0),
        hedge_ms=30.0,
    )
    # warm the live histogram with fast reads — hedging is disabled
    # until p95 means something
    for i in range(_HEDGE_WARMUP + 2):
        ts.read(i % 60, i % 60 + 1)
    assert stats_snapshot(url)["hedged"] == 0
    # stall the next primary GET below the deadline but way past p95:
    # the hedge fires, finishes first, and the read returns without
    # waiting out the stalled primary
    ts._client.fault_plan = FaultPlan.from_spec(
        "object:step=0:stall=1.5", seed=1
    )
    t0 = time.perf_counter()
    np.testing.assert_array_equal(ts.read(0, 4), stack[0:4])
    assert time.perf_counter() - t0 < 1.4
    snap = stats_snapshot(url)
    assert snap["hedged"] >= 1
    assert snap["hedge_wins"] >= 1
    assert snap["p95_ms"] is not None


def test_hedge_disabled_at_zero(tmp_path):
    rng = np.random.default_rng(9)
    stack = rng.integers(0, 60000, (64, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    put_stack(url, stack, chunk_frames=4)
    ts = _fast(url, hedge_ms=0.0)
    for i in range(_HEDGE_WARMUP + 4):
        ts.read(i % 60, i % 60 + 1)
    assert ts._hedge_threshold() is None
    assert stats_snapshot(url)["hedged"] == 0


# -- manifest durability ---------------------------------------------------


def test_corrupt_manifest_quarantined_prev_generation_used(tmp_path):
    rng = np.random.default_rng(10)
    stack = rng.integers(0, 60000, (20, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    w = ObjectStoreWriter(url, 20, (6, 5), np.uint16, chunk_frames=8)
    w.append_batch(stack)
    w.close()
    client = client_for_url(url)
    good = load_manifest(client)
    # mangle the CURRENT generation on disk; the prev generation (one
    # chunk behind) must take over, quarantining the torn one
    client.put(MANIFEST_KEY, client.get(MANIFEST_KEY)[:-20] + b"garbage!")
    rep = RobustnessReport()
    man = load_manifest(client, report=rep)
    assert man["format"] == good["format"]
    assert man["n_frames"] < good["n_frames"]  # rewound, not guessed
    assert len(rep.quarantined_parts) == 1
    assert client.list(MANIFEST_KEY + ".corrupt") == [
        MANIFEST_KEY + ".corrupt"
    ]
    # both generations gone -> ObjectNotFound, never a fabricated stack
    client.delete(MANIFEST_KEY)
    client.delete(PREV_MANIFEST_KEY)
    with pytest.raises(ObjectNotFound):
        load_manifest(client)


def test_torn_multipart_upload_retries_to_clean_copy(tmp_path):
    """An injected truncate on a multipart part mangles the assembled
    object; the writer's etag-verify catches it and re-uploads — the
    durable copy is never the torn one."""
    rng = np.random.default_rng(11)
    stack = rng.integers(0, 60000, (8, 32, 32), dtype=np.uint16)
    url = f"emu://{tmp_path}/b"
    plan = FaultPlan.from_spec("object:step=1:truncate", seed=1)
    w = ObjectStoreWriter(
        url, 8, (32, 32), np.uint16, chunk_frames=8,
        part_bytes=4096,  # 16 KiB chunk -> 4 multipart parts
        fault_plan=plan, retry=FAST,
    )
    w.append_batch(stack)
    w.close()
    assert stats_snapshot(url)["retries"] >= 1
    with open_stack(url) as ts:
        np.testing.assert_array_equal(ts.read(0, 8), stack)


def test_writer_resume_reuploads_only_past_high_water_mark(tmp_path):
    rng = np.random.default_rng(12)
    stack = rng.integers(0, 60000, (50, 8, 9), dtype=np.uint16)
    url = f"emu://{tmp_path}/out"
    w = make_writer(url, 50, (8, 9), np.uint16,
                    object_opts={"chunk_frames": 7})
    w.append_batch(stack[:10])
    state = w.checkpoint_state()  # flushes the 3-frame partial tail
    assert state == {"format": "object", "n_pages": 10,
                     "zlib": state["zlib"]}
    # abandon w (the kill); resume from the durable manifest
    w2 = resume_writer(url, state, object_opts={"chunk_frames": 7})
    assert w2.n_pages == 10
    puts_before = stats_snapshot(url)["puts"]
    w2.append_batch(stack[10:50])
    w2.close()
    # uninterrupted twin
    url2 = f"emu://{tmp_path}/ref"
    w3 = make_writer(url2, 50, (8, 9), np.uint16,
                     object_opts={"chunk_frames": 7})
    w3.append_batch(stack)
    w3.close()
    c1, c2 = client_for_url(url), client_for_url(url2)
    assert _chunkset(c1) == _chunkset(c2)
    # the resume re-uploaded the tail chunk + later chunks, NOT the
    # full chunks already below the high-water mark
    resumed_puts = stats_snapshot(url)["puts"] - puts_before
    full_puts = stats_snapshot(url2)["puts"]
    assert resumed_puts < full_puts
    with open_stack(url) as ts:
        np.testing.assert_array_equal(ts.read(0, 50), stack)


def test_writer_resume_refuses_store_behind_cursor(tmp_path):
    rng = np.random.default_rng(13)
    stack = rng.integers(0, 60000, (20, 6, 5), dtype=np.uint16)
    url = f"emu://{tmp_path}/out"
    w = ObjectStoreWriter(url, 20, (6, 5), np.uint16, chunk_frames=8)
    w.append_batch(stack[:8])
    state = w.checkpoint_state()
    # corrupt a durable chunk below the cursor: the frames are gone, so
    # resume must refuse (OSError -> the corrector restarts from
    # scratch) and quarantine the evidence
    client = client_for_url(url)
    client.put("chunk-00000000", b"not the chunk")
    rep = RobustnessReport()
    with pytest.raises(OSError, match="corrupt at resume"):
        ObjectStoreWriter.resume(
            url, state, object_opts={"report": rep, "retry": FAST}
        )
    assert rep.quarantined_parts
    # a manifest behind the checkpoint cursor is equally unresumable
    url2 = f"emu://{tmp_path}/out2"
    w2 = ObjectStoreWriter(url2, 20, (6, 5), np.uint16, chunk_frames=8)
    w2.append_batch(stack[:8])
    w2.checkpoint_state()
    with pytest.raises(OSError, match="behind the checkpoint cursor"):
        ObjectStoreWriter.resume(url2, {"format": "object", "n_pages": 16})


# -- end-to-end: correct_file over the emulator ----------------------------


@pytest.fixture()
def bucket(tmp_path, arr):
    url = f"emu://{tmp_path}/in"
    put_stack(url, arr, chunk_frames=8)
    return url


def _mk(**kw):
    return MotionCorrector(
        model="translation", backend="jax", batch_size=8, **kw
    )


@pytest.mark.slow
def test_correct_file_emulated_ingest_parity(bucket, drift):
    res = _mk().correct_file(bucket, chunk_size=8)
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15
    obj = res.timing["feeder"]["object"]["ingest"]
    assert obj["gets"] > 0 and obj["retries"] == 0


@pytest.mark.slow
def test_correct_file_pooled_ingest_parity(bucket, drift):
    """io_workers >= 2 routes the emu source through the thread-flavor
    decode pool (per-worker clients via the URL respec) with the same
    results."""
    res = _mk(io_workers=2).correct_file(bucket, chunk_size=8)
    ft = res.timing["feeder"]
    assert ft["mode"] == "thread"
    assert ft["chunks"] > 0
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15


@pytest.mark.slow
def test_fault_storm_zero_loss_byte_identity(tmp_path, bucket):
    """THE chaos contract: drop + stall + flip + truncate + throttle
    across a full emulated ingest->egress run completes with zero lost
    or duplicated frames — the output chunk set is byte-identical to
    the fault-free run's."""
    clean_out = f"emu://{tmp_path}/out-clean"
    _mk(**FAST_CFG).correct_file(bucket, output=clean_out, chunk_size=8)
    reset_url_state()
    storm = (
        "object:step=3:drop, object:step=5:stall=0.2, object:step=7:flip, "
        "object:step=9:truncate, object:step=11:throttle"
    )
    storm_out = f"emu://{tmp_path}/out-storm"
    res = _mk(
        fault_plan=storm, object_timeout_s=2.0, **FAST_CFG
    ).correct_file(bucket, output=storm_out, chunk_size=8)
    assert res.robustness["faults_injected"] > 0
    c = client_for_url(f"emu://{tmp_path}")
    clean = {
        k.split("/", 1)[1]: v for k, v in _chunkset(c, "out-clean").items()
    }
    storm = {
        k.split("/", 1)[1]: v for k, v in _chunkset(c, "out-storm").items()
    }
    assert clean == storm


@pytest.mark.slow
def test_kill_resume_egress_byte_identity(tmp_path, bucket):
    """Kill mid-run -> restart -> resume: the writer re-uploads only
    past the durable high-water mark and the final chunk set is
    byte-identical to an uninterrupted run."""
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        object_chunk_frames=8,
    )
    ref_out = f"emu://{tmp_path}/ref"
    mk().correct_file(bucket, output=ref_out, chunk_size=8)

    calls = {"n": 0}
    orig = ChunkedStackLoader._read

    def poisoned(self, lo, hi):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("simulated kill")
        return orig(self, lo, hi)

    out = f"emu://{tmp_path}/out"
    ckpt = tmp_path / "run.ckpt.npz"
    ChunkedStackLoader._read = poisoned
    try:
        with pytest.raises(RuntimeError, match="simulated kill"):
            mk().correct_file(
                bucket, output=out, chunk_size=8,
                checkpoint=str(ckpt), checkpoint_every=8,
            )
    finally:
        ChunkedStackLoader._read = orig
    puts_before = stats_snapshot(out)["puts"]
    res = mk().correct_file(
        bucket, output=out, chunk_size=8, checkpoint=str(ckpt)
    )
    assert res.timing["restored_frames"] > 0
    resumed_puts = stats_snapshot(out)["puts"] - puts_before
    c = client_for_url(f"emu://{tmp_path}")
    ref = {k.split("/", 1)[1]: v for k, v in _chunkset(c, "ref").items()}
    got = {k.split("/", 1)[1]: v for k, v in _chunkset(c, "out").items()}
    assert ref == got
    assert resumed_puts < stats_snapshot(ref_out)["puts"]
