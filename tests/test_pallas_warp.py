"""Pallas translation-warp kernel vs the jnp gather warp (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.pallas_warp import warp_batch_translation, warp_frame_translation
from kcmc_tpu.ops.warp import warp_frame
from kcmc_tpu.utils import synthetic


def _mat(tx, ty):
    return jnp.asarray(
        np.array([[1, 0, tx], [0, 1, ty], [0, 0, 1]], dtype=np.float32)
    )


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(5)
    return jnp.asarray(synthetic.render_scene(rng, (96, 96), n_blobs=40))


@pytest.mark.parametrize(
    "tx,ty",
    [(0.0, 0.0), (3.0, -2.0), (2.5, 1.25), (-7.75, 4.5), (0.5, 0.5), (-20.25, 30.5)],
)
def test_matches_gather_warp(img, tx, ty):
    ref = np.asarray(warp_frame(img, _mat(tx, ty)))
    out = np.asarray(
        warp_frame_translation(img, jnp.asarray([tx, ty], jnp.float32), interpret=True)
    )
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_batch(img):
    frames = jnp.stack([img, img * 0.5, img + 0.1])
    mats = jnp.stack([_mat(1.5, -2.0), _mat(0.0, 0.0), _mat(-3.25, 4.0)])
    out = np.asarray(warp_batch_translation(frames, mats, interpret=True))
    for i in range(3):
        ref = np.asarray(warp_frame(frames[i], mats[i]))
        np.testing.assert_allclose(out[i], ref, atol=1e-5)


def test_strip_kernel_matches_whole_frame():
    """The round-5 row-strip variant (large-frame route) must agree
    with the whole-frame kernel wherever both run — same exactness
    window, same out-of-bounds semantics — including shifts near ±PAD
    and a height that does not divide the strip size."""
    import jax

    from kcmc_tpu.ops.pallas_warp import (
        PAD,
        _STRIP_ROWS,
        supports_strips,
        warp_batch_translation_strips,
    )

    assert supports_strips((1024, 1024)) and supports_strips((2048, 2048))
    rng = np.random.default_rng(9)
    H = _STRIP_ROWS + 40  # ragged final strip
    img = jnp.asarray(synthetic.render_scene(rng, (H, 160), n_blobs=60))
    shifts = [
        (0.0, 0.0), (3.4, -2.6), (-30.25, 17.5),
        (PAD - 1.5, -(PAD - 1.5)),  # near the exactness window edge
        (PAD + 40.0, 0.0),  # beyond it: frame must zero, ok False
    ]
    frames = jnp.stack([img] * len(shifts))
    Ms = jnp.stack([_mat(tx, ty) for tx, ty in shifts])
    ref, ok_ref = warp_batch_translation(frames, Ms, interpret=True, with_ok=True)
    out, ok = warp_batch_translation_strips(
        frames, Ms, interpret=True, with_ok=True
    )
    np.testing.assert_array_equal(np.asarray(ok), np.asarray(ok_ref))
    assert not np.asarray(ok)[-1]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # and against the gather warp directly
    gat = np.asarray(jax.vmap(warp_frame)(frames[:4], Ms[:4]))
    np.testing.assert_allclose(np.asarray(out)[:4], gat, atol=1e-4)
