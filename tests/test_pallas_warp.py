"""Pallas translation-warp kernel vs the jnp gather warp (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.pallas_warp import warp_batch_translation, warp_frame_translation
from kcmc_tpu.ops.warp import warp_frame
from kcmc_tpu.utils import synthetic


def _mat(tx, ty):
    return jnp.asarray(
        np.array([[1, 0, tx], [0, 1, ty], [0, 0, 1]], dtype=np.float32)
    )


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(5)
    return jnp.asarray(synthetic.render_scene(rng, (96, 96), n_blobs=40))


@pytest.mark.parametrize(
    "tx,ty",
    [(0.0, 0.0), (3.0, -2.0), (2.5, 1.25), (-7.75, 4.5), (0.5, 0.5), (-20.25, 30.5)],
)
def test_matches_gather_warp(img, tx, ty):
    ref = np.asarray(warp_frame(img, _mat(tx, ty)))
    out = np.asarray(
        warp_frame_translation(img, jnp.asarray([tx, ty], jnp.float32), interpret=True)
    )
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_batch(img):
    frames = jnp.stack([img, img * 0.5, img + 0.1])
    mats = jnp.stack([_mat(1.5, -2.0), _mat(0.0, 0.0), _mat(-3.25, 4.0)])
    out = np.asarray(warp_batch_translation(frames, mats, interpret=True))
    for i in range(3):
        ref = np.asarray(warp_frame(frames[i], mats[i]))
        np.testing.assert_allclose(out[i], ref, atol=1e-5)
