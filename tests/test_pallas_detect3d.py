"""Fused 3D structure-tensor kernel vs the jnp shift-and-add path
(interpret mode on CPU): dense field parity and keypoint-level parity
through the shared selection stage."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.detect3d import (
    _maxpool3_same,
    detect_keypoints_3d_batch,
    harris_response_3d,
)
from kcmc_tpu.ops.pallas_detect3d import response_fields_3d, supports
from kcmc_tpu.utils.synthetic import make_drift_stack_3d


@pytest.fixture(
    scope="module",
    params=["zero_background", "camera_offset"],
)
def vols(request):
    """Blob stacks decay to ~0 at the faces; the camera-offset variant
    (background 100 +- noise, as real microscopy data has) exercises
    the volume-border gradient masking — an unmasked kernel inflates
    the border response ~2x and passes only the zero-background case."""
    data = make_drift_stack_3d(n_frames=2, shape=(16, 96, 96), seed=1)
    stack = np.asarray(data.stack, np.float32)
    if request.param == "camera_offset":
        rng = np.random.default_rng(7)
        stack = stack * 50.0 + 100.0 + rng.normal(0, 2.0, stack.shape)
    return jnp.asarray(stack.astype(np.float32))


def test_dense_fields_match_jnp_path(vols):
    resp_p, nms_p = jax.tree.map(
        np.asarray, response_fields_3d(vols, interpret=True)
    )
    resp_j = np.asarray(jax.vmap(harris_response_3d)(vols))
    nms_j = np.where(
        resp_j >= np.asarray(jax.vmap(_maxpool3_same)(resp_j)),
        resp_j,
        -np.inf,
    )
    scale = np.abs(resp_j).max()
    assert np.abs(resp_p - resp_j).max() <= 1e-5 * scale
    # NMS winners agree except float near-ties (boundary ring is
    # border-excluded by the selection stage anyway).
    interior = np.s_[:, 2:-2, 2:-2, 2:-2]
    agree = (
        np.isfinite(nms_p[interior]) == np.isfinite(nms_j[interior])
    ).mean()
    assert agree > 0.999


def test_keypoints_match_jnp_path(vols):
    kw = dict(max_keypoints=128, threshold=1e-4, border=6)
    kj = detect_keypoints_3d_batch(vols, **kw, use_pallas=False)
    kp = detect_keypoints_3d_batch(vols, **kw, use_pallas=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(kj.valid), np.asarray(kp.valid))
    both = np.asarray(kj.valid & kp.valid)
    assert np.abs(np.asarray(kj.xy) - np.asarray(kp.xy))[both].max() < 1e-3


def test_supports_bounds():
    assert supports((32, 256, 256))
    assert not supports((32, 256, 4096))  # slab would overflow VMEM
    assert not supports((32, 256, 256), window_sigma=3.0)  # halo