"""Warp tests: inverse-warp semantics, round trips, flow warps, 3D."""

import jax
import jax.numpy as jnp
import numpy as np

from kcmc_tpu.ops import warp
from kcmc_tpu.utils import synthetic


def _scene(shape=(96, 96), seed=0):
    rng = np.random.default_rng(seed)
    return synthetic.render_scene(rng, shape, n_blobs=40)


def test_warp_identity():
    img = jnp.asarray(_scene())
    out = warp.warp_frame(img, jnp.eye(3))
    np.testing.assert_allclose(np.asarray(out), np.asarray(img), atol=1e-6)


def test_warp_undoes_synthetic_drift():
    """Warping a drifted frame by its gt transform recovers the scene."""
    data = synthetic.make_drift_stack(n_frames=4, shape=(128, 128), model="affine", noise=0.0, seed=2)
    t = 3
    corrected = warp.warp_frame(jnp.asarray(data.stack[t]), jnp.asarray(data.transforms[t]))
    mask = np.asarray(warp.coverage_mask((128, 128), jnp.asarray(data.transforms[t])))
    # interior comparison: double interpolation softens edges slightly
    m = 16
    err = np.abs(np.asarray(corrected) - data.reference)[m:-m, m:-m]
    assert err[mask[m:-m, m:-m]].mean() < 0.02


def test_warp_matches_numpy_oracle():
    img = _scene()
    M = np.array([[1.01, 0.02, 3.0], [-0.01, 0.99, -2.0], [0, 0, 1]], dtype=np.float32)
    out = np.asarray(warp.warp_frame(jnp.asarray(img), jnp.asarray(M)))
    H, W = img.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2]
    sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2]
    oracle = synthetic._bilinear(img, sx, sy)
    np.testing.assert_allclose(out, oracle, atol=1e-5)


def test_flow_warp_equals_matrix_warp_for_translation():
    img = jnp.asarray(_scene())
    M = jnp.asarray(np.array([[1, 0, 4.0], [0, 1, -6.0], [0, 0, 1]], dtype=np.float32))
    flow = jnp.broadcast_to(jnp.asarray(np.array([4.0, -6.0], np.float32)), (96, 96, 2))
    a = warp.warp_frame(img, M)
    b = warp.warp_frame_flow(img, flow)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_warp_vmap_over_frames():
    data = synthetic.make_drift_stack(n_frames=3, shape=(64, 64), model="translation")
    out = jax.vmap(warp.warp_frame)(jnp.asarray(data.stack), jnp.asarray(data.transforms))
    assert out.shape == (3, 64, 64)


def test_warp_volume_identity_and_shift():
    vol = jnp.asarray(np.random.default_rng(0).uniform(size=(8, 16, 16)).astype(np.float32))
    out = warp.warp_volume(vol, jnp.eye(4))
    np.testing.assert_allclose(np.asarray(out), np.asarray(vol), atol=1e-6)
    # integer z-shift: corrected(z) = frame(z+1)
    M = jnp.eye(4).at[2, 3].set(1.0)
    out = np.asarray(warp.warp_volume(vol, M))
    np.testing.assert_allclose(out[:-1], np.asarray(vol)[1:], atol=1e-6)
    assert (out[-1] == 0).all()  # out-of-bounds fill
