"""Double-buffered H2D staging (config.upload_overlap, PR 18).

Contracts under test:

* outputs are BYTE-identical with overlap on vs off — the staged slot
  holds exactly the arrays the inline dispatch path builds, only WHEN
  the bytes move changes — including across an uneven tail batch and
  a rolling-template run;
* `timing["pipeline"]` reports the seam (`upload_overlap`,
  `upload_waits`) and consumer time blocked on a not-yet-finished slot
  lands in the `upload_wait` stall;
* the staging worker is invisible at the API surface: no kcmc-upload
  thread survives a run (the worker shuts down at the final flush) and
  the cross-thread slot handoff runs sanitize-clean;
* backends without the `stage_upload` seam (numpy) silently take the
  inline path.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic

SHAPE = (64, 64)
T = 30  # 30 = 3*8 + 6: the tail batch rides the staged-slot path too


@pytest.fixture(scope="module")
def data():
    return synthetic.make_drift_stack(
        n_frames=T, shape=SHAPE, model="translation", max_drift=4.0,
        seed=11,
    )


def mk(**kw):
    return MotionCorrector(
        model="translation", backend="jax", batch_size=8, **kw
    )


def test_overlap_byte_identical_across_uneven_tail(data):
    on = mk().correct(data.stack)
    off = mk(upload_overlap=False).correct(data.stack)
    np.testing.assert_array_equal(on.corrected, off.corrected)
    np.testing.assert_array_equal(on.transforms, off.transforms)


def test_overlap_byte_identical_with_rolling_templates(data):
    kw = dict(template_update_every=10, template_window=6)
    on = mk(**kw).correct(data.stack)
    off = mk(upload_overlap=False, **kw).correct(data.stack)
    np.testing.assert_array_equal(on.corrected, off.corrected)
    np.testing.assert_array_equal(on.transforms, off.transforms)


def test_overlap_byte_identical_native_uint16_upload(data):
    """The staged slot carries the NATIVE-dtype upload (uint16 crosses
    at half the float32 bytes and widens on device), same as inline."""
    u16 = np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    on = mk().correct(u16)
    off = mk(upload_overlap=False).correct(u16)
    np.testing.assert_array_equal(on.corrected, off.corrected)
    np.testing.assert_array_equal(on.transforms, off.transforms)


def test_pipeline_reports_overlap_and_waits(data):
    res = mk().correct(data.stack)
    pipe = res.timing["pipeline"]
    assert pipe["upload_overlap"] is True
    # every staged slot after the first batch is waited on (possibly
    # for ~0s when staging already finished)
    assert pipe["upload_waits"] >= 1
    assert "upload_wait" in res.timing["stalls_s"]
    assert res.timing["stalls_s"]["upload_wait"] >= 0.0


def test_overlap_off_stays_inline(data):
    res = mk(upload_overlap=False).correct(data.stack)
    pipe = res.timing["pipeline"]
    assert pipe["upload_overlap"] is False
    assert pipe["upload_waits"] == 0
    assert "upload_wait" not in res.timing["stalls_s"]


def test_numpy_backend_has_no_staging_seam(data):
    res = MotionCorrector(
        model="translation", backend="numpy", batch_size=8
    ).correct(data.stack)
    pipe = res.timing["pipeline"]
    assert pipe["upload_overlap"] is False
    assert pipe["upload_waits"] == 0


def test_upload_worker_joined_after_run(data):
    mk().correct(data.stack)
    alive = [
        t.name
        for t in threading.enumerate()
        if t.name.startswith("kcmc-upload")
    ]
    assert alive == []


def test_slot_handoff_sanitize_clean(data):
    """The staged-slot handoff (consumer waits on the worker's future,
    the staged buffer rides the in-flight entry until drain) under the
    runtime sanitizer: zero violations, zero leaked threads."""
    from kcmc_tpu.analysis import sanitize

    owned = not sanitize.active()
    if owned:
        sanitize.enable(watchdog_s=5.0, static=False)
    try:
        before = sanitize.leak_snapshot()
        res = mk().correct(data.stack)
        assert res.timing["pipeline"]["upload_overlap"] is True
        assert sanitize.take_violations() == []
        assert sanitize.check_leaks(before, grace_s=2.0) == []
    finally:
        if owned:
            sanitize.disable()
