"""TRUE multi-host execution: two OS processes, TCP-coordinated.

tests/test_distributed.py exercises the sharded program on one process
with 8 virtual devices; this spawns TWO jax.distributed processes (4
virtual CPU devices each) that form one global 8-device mesh — the same
topology as a 2-host TPU pod slice over DCN. Each process feeds only
its host-local half of the batch (`shard_host_local_frames`) and its
half of the keypoint-sharded reference; the all-gather then crosses
process boundaries for real, and each host's transform shards must
match a single-device run.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import numpy as np

pid = int(sys.argv[1]); port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax
jax.config.update("jax_platforms", "cpu")

from kcmc_tpu.parallel import initialize_multihost, make_mesh, shard_host_local_frames
initialize_multihost(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from kcmc_tpu.backends.jax_backend import JaxBackend
from kcmc_tpu.config import CorrectorConfig
from kcmc_tpu.parallel.mesh import FRAME_AXIS
from kcmc_tpu.utils import synthetic

B, K, SHAPE = 8, 64, (96, 96)
data = synthetic.make_drift_stack(
    n_frames=B, shape=SHAPE, model="translation", max_drift=5.0, seed=41
)
cfg = CorrectorConfig(model="translation", max_keypoints=K, batch_size=B)

# single-device truth, computed independently on this host
single = JaxBackend(cfg)
ref = single.prepare_reference(np.asarray(data.stack[0], np.float32))
truth = single.process_batch(
    np.asarray(data.stack, np.float32), ref, np.arange(B, dtype=np.uint32)
)

# global mesh across both processes; host-local halves of everything
mesh = make_mesh()
sharded_backend = JaxBackend(cfg, mesh=mesh)
fn = sharded_backend._get_batch_fn(SHAPE)

lo, hi = pid * (B // 2), (pid + 1) * (B // 2)
frames = shard_host_local_frames(
    np.asarray(data.stack[lo:hi], np.float32), mesh
)
idx = shard_host_local_frames(np.arange(lo, hi, dtype=np.uint32), mesh)

klo, khi = pid * (K // 2), (pid + 1) * (K // 2)
sh = NamedSharding(mesh, P(FRAME_AXIS))
ref_sharded = {
    k: jax.make_array_from_process_local_data(
        sh, np.asarray(ref[k])[klo:khi]
    )
    for k in ("xy", "desc", "valid")
}
# the reference FRAME is replicated over the mesh (both hosts hold it)
rep = NamedSharding(mesh, P())
ref_frame = jax.make_array_from_process_local_data(
    rep, np.asarray(ref["frame"], np.float32)
)

out = fn(
    frames, ref_sharded["xy"], ref_sharded["desc"], ref_sharded["valid"],
    ref_frame, idx,
)

# every host checks ITS addressable transform shards against the truth
got = np.concatenate(
    [np.asarray(s.data) for s in out["transform"].addressable_shards]
)
want = truth["transform"][lo:hi]
err = np.abs(got - want).max()
assert err < 1e-4, f"process {pid}: transform mismatch {err}"
print(f"process {pid}: OK, max|dT|={err:.2e}", flush=True)
"""


@pytest.mark.skipif(
    os.environ.get("KCMC_SKIP_MULTIHOST") == "1",
    reason="multihost spawn disabled",
)
def test_two_process_multihost_matches_single_device(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = [p.communicate(timeout=600) for p in procs]
    if any(
        "Multiprocess computations aren't implemented on the CPU backend"
        in err
        for _p, (_out, err) in zip(procs, outs)
    ):
        # jaxlib's CPU client in this image cannot EXECUTE multiprocess
        # computations at all (the single-device truth computation
        # inside the worker already trips it) — a platform limitation,
        # not a kcmc regression. Any other failure still fails below.
        pytest.skip(
            "jaxlib CPU backend does not implement multiprocess "
            "computations in this image"
        )
    for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"process {pid} failed:\nSTDOUT:\n{out}\nSTDERR:\n{err[-3000:]}"
        )
        assert f"process {pid}: OK" in out
