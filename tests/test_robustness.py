"""Edge cases: degenerate inputs must degrade gracefully, never NaN/crash."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic


def test_blank_frames_yield_identity():
    """Featureless frames have no matches: identity transform, finite
    outputs, zero inliers."""
    stack = np.zeros((4, 96, 96), np.float32)
    res = MotionCorrector(model="translation", backend="jax", batch_size=2).correct(stack)
    assert np.isfinite(res.transforms).all()
    np.testing.assert_allclose(res.transforms, np.eye(3)[None].repeat(4, 0), atol=1e-5)
    assert np.isfinite(res.corrected).all()


def test_noise_only_frames_finite():
    """Pure noise: matches are garbage but everything stays finite."""
    rng = np.random.default_rng(0)
    stack = rng.random((4, 96, 96), dtype=np.float32)
    res = MotionCorrector(model="affine", backend="jax", batch_size=2).correct(stack)
    assert np.isfinite(res.transforms).all()
    assert np.isfinite(res.corrected).all()
    assert np.isfinite(res.diagnostics["rms_residual"]).all()


def test_non_multiple_of_eight_frame_size():
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(107, 93), model="translation", max_drift=3.0, seed=1
    )
    res = MotionCorrector(model="translation", backend="jax", batch_size=2).correct(
        data.stack
    )
    assert res.corrected.shape == data.stack.shape
    assert np.isfinite(res.transforms).all()


def test_uint16_input_stack():
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(128, 128), model="translation", max_drift=4.0, seed=2
    )
    u16 = (data.stack * 60000).astype(np.uint16)
    res = MotionCorrector(model="translation", backend="jax", batch_size=2).correct(u16)
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), (128, 128)
    )
    assert rmse < 0.75


def test_batch_size_larger_than_stack():
    data = synthetic.make_drift_stack(
        n_frames=3, shape=(96, 96), model="translation", max_drift=2.0, seed=3
    )
    res = MotionCorrector(model="translation", backend="jax", batch_size=16).correct(
        data.stack
    )
    assert res.corrected.shape[0] == 3


def test_small_max_keypoints():
    data = synthetic.make_drift_stack(
        n_frames=3, shape=(96, 96), model="translation", max_drift=2.0, seed=4
    )
    res = MotionCorrector(
        model="translation", backend="jax", batch_size=3, max_keypoints=24
    ).correct(data.stack)
    assert np.isfinite(res.transforms).all()


def test_single_frame_stack():
    data = synthetic.make_drift_stack(
        n_frames=1, shape=(96, 96), model="translation", seed=5
    )
    res = MotionCorrector(model="translation", backend="jax", batch_size=4).correct(
        data.stack
    )
    assert res.corrected.shape[0] == 1
    np.testing.assert_allclose(res.transforms[0], np.eye(3), atol=1e-4)


def test_bad_reference_index_raises():
    stack = np.zeros((3, 64, 64), np.float32)
    with pytest.raises(ValueError, match="out of range"):
        MotionCorrector(model="translation", reference=7).correct(stack)


def test_wrong_rank_stack_raises():
    with pytest.raises(ValueError, match="stack must be"):
        MotionCorrector(model="translation").correct(np.zeros((64, 64), np.float32))


def test_device_stack_and_device_outputs_match_host_path():
    import jax.numpy as jnp

    data = synthetic.make_drift_stack(
        n_frames=5, shape=(96, 96), model="translation", max_drift=3.0, seed=6
    )
    mc = MotionCorrector(model="translation", backend="jax", batch_size=2)
    host = mc.correct(data.stack)
    dev = mc.correct(jnp.asarray(data.stack), device_outputs=True)
    assert not isinstance(dev.corrected, np.ndarray)  # stayed on device
    np.testing.assert_allclose(np.asarray(dev.transforms), host.transforms, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dev.corrected), host.corrected, atol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(dev.diagnostics["n_inliers"]), host.diagnostics["n_inliers"]
    )


def test_background_offset_does_not_kill_detection():
    """A constant background offset (camera counts) creates border-ring
    response spikes under SAME-conv gradients; the detection threshold
    is relative to the border-EXCLUDED peak so interior keypoints
    survive. Regression: the whole-volume peak killed 3D registration
    entirely (2 keypoints, 55 px RMSE)."""
    import numpy as np

    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse
    from kcmc_tpu.utils.synthetic import make_drift_stack, make_drift_stack_3d

    d3 = make_drift_stack_3d(n_frames=4, shape=(16, 96, 96), seed=0)
    stack = np.asarray(d3.stack, np.float32) * 50.0 + 100.0
    res = MotionCorrector(model="rigid3d", batch_size=2).correct(stack)
    rmse = transform_rmse(
        res.transforms, relative_transforms(d3.transforms), (16, 96, 96)
    )
    assert rmse < 0.5
    assert np.asarray(res.diagnostics["n_keypoints"]).mean() > 20

    d2 = make_drift_stack(n_frames=4, shape=(128, 128), model="translation", seed=0)
    res2 = MotionCorrector(model="translation").correct(
        np.asarray(d2.stack, np.float32) * 50.0 + 100.0
    )
    rmse2 = transform_rmse(
        res2.transforms, relative_transforms(d2.transforms), (128, 128)
    )
    assert rmse2 < 0.2


def test_nan_frame_degrades_gracefully():
    """A frame of NaNs (dead camera, flat-field artifact) must not
    crash or poison its neighbors: the bad frame yields ~no inliers
    (visible in diagnostics) while every other frame registers."""
    data = synthetic.make_drift_stack(
        n_frames=6, shape=(128, 128), model="translation", seed=19
    )
    stack = np.array(data.stack)
    stack[3] = np.nan
    res = MotionCorrector(
        model="translation", backend="jax", batch_size=3
    ).correct(stack)
    n_in = np.asarray(res.diagnostics["n_inliers"])
    assert n_in[3] <= 3  # the NaN frame finds no consensus...
    good = [0, 1, 2, 4, 5]
    assert (n_in[good] > 10).all()  # ...and the rest are untouched
    assert np.isfinite(np.asarray(res.transforms)[good]).all()


def test_similarity_zoom_envelope():
    """Zoom-robustness envelope (VERDICT r2 #6): single-scale BRIEF
    matching holds far beyond the ±3% the synthetic similarity config
    exercises. Measured on the TPU (2026-07-31, 256^2 scene, 5 zoomed
    frames + small drift): RMSE 0.02-0.09 px through ±20% zoom with
    graceful match decay (126 -> ~55 median), <=0.26 px at ±25-30%, and
    collapse only at 0.70/1.40 where ~20 surviving matches let RANSAC
    latch onto a false consensus. This test pins the ±10%/±20% points.
    """
    import warnings

    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils import synthetic
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

    rng = np.random.default_rng(0)
    shape = (256, 256)
    scene = synthetic.render_scene(rng, shape)
    cx, cy = (shape[1] - 1) / 2.0, (shape[0] - 1) / 2.0

    def stack_at_scale(s, n=4):
        mats = np.tile(np.eye(3, dtype=np.float32), (n, 1, 1))
        frames = [scene]
        for t in range(1, n):
            L = np.float32(s) * np.eye(2, dtype=np.float32)
            mats[t, :2, :2] = L
            mats[t, :2, 2] = rng.uniform(-3, 3, 2).astype(np.float32) + np.array(
                [cx, cy], np.float32
            ) - L @ np.array([cx, cy], np.float32)
            frames.append(synthetic._warp_scene(scene, mats[t]))
        st = np.stack(frames) + rng.normal(0, 0.01, (n,) + shape).astype(
            np.float32
        )
        return st.astype(np.float32), mats

    mc = MotionCorrector(model="similarity", backend="jax", batch_size=4)
    for s, rmse_bound, match_floor in (
        (0.90, 0.15, 60),
        (1.10, 0.15, 60),
        (0.80, 0.25, 30),
        (1.20, 0.25, 30),
    ):
        st, mats = stack_at_scale(s)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            res = mc.correct(st)
        nm = np.asarray(res.diagnostics["n_matches"])[1:]
        rmse = transform_rmse(
            res.transforms, relative_transforms(mats), shape
        )
        assert rmse < rmse_bound, f"zoom {s}: RMSE {rmse:.3f}"
        assert nm.min() >= match_floor, f"zoom {s}: matches {nm}"


def test_nonfinite_input_pixels():
    """Dead/hot sensor pixels (NaN rows, Inf columns): estimation must
    stay accurate WITHOUT sanitization (NaN kills its own local Harris
    response; RANSAC shrugs off the lost keypoints), and with
    `sanitize_input=True` the corrected output is fully finite with the
    same registration accuracy — on both backends (parity)."""
    import warnings

    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils import synthetic
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

    data = synthetic.make_drift_stack(
        n_frames=6, shape=(160, 160), model="translation", max_drift=5.0, seed=3
    )
    stack = np.array(data.stack)
    stack[2, 40:42, :] = np.nan
    stack[3, :, 80] = np.inf
    stack[4, 100:104, 100:104] = np.nan
    rel = relative_transforms(data.transforms)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        # default: estimation robust, garbage pixels stay visible
        res = MotionCorrector(
            model="translation", backend="jax", batch_size=3
        ).correct(stack)
        assert np.isfinite(res.transforms).all()
        assert transform_rmse(res.transforms, rel, (160, 160)) < 0.3

        # sanitize_input: fully finite output, same accuracy, both backends
        for backend in ("jax", "numpy"):
            res = MotionCorrector(
                model="translation", backend=backend, batch_size=3,
                sanitize_input=True,
            ).correct(stack)
            assert np.isfinite(res.corrected).all(), backend
            rmse = transform_rmse(res.transforms, rel, (160, 160))
            assert rmse < 0.3, f"{backend} sanitized RMSE {rmse:.3f}"


def test_rescue_warp_honors_sanitize_input():
    """The exact-warp rescue path re-warps RAW host frames; with
    sanitize_input=True it must re-apply sanitization or the fully-
    finite-output guarantee breaks exactly for out-of-bound frames."""
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig

    rng = np.random.default_rng(0)
    frames = rng.uniform(size=(2, 64, 64)).astype(np.float32)
    frames[0, 10:12, :] = np.nan
    M = np.tile(np.eye(3, dtype=np.float32), (2, 1, 1))
    M[:, 0, 2] = 3.5  # subpixel shift: bilinear blend spreads any NaN
    out = {"transform": M}

    be = JaxBackend(CorrectorConfig(model="translation", sanitize_input=True))
    got = be.rescue_warp(frames, out)
    assert np.isfinite(got).all()

    be_raw = JaxBackend(CorrectorConfig(model="translation"))
    got_raw = be_raw.rescue_warp(frames, out)
    assert not np.isfinite(got_raw).all()  # default: garbage stays visible
