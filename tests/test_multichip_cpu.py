"""Multi-chip production-path suite on the 8-device virtual CPU mesh.

Round-6 contract (ISSUE 5): multi-chip is a first-class config surface —
`CorrectorConfig.mesh_devices` / KCMC_DEVICES / `--devices` resolve a
1-D frame-axis mesh at backend construction; uneven frame batches and
non-divisible reference keypoint counts are mesh-padded instead of
erroring; sharded runs match the single-device path within the
documented float32 tolerance (the sharded program is the same algorithm
with the same global-index RANSAC keys — residual deltas come from XLA
tiling f32 reductions differently per shard, bounded well under the
1e-4 px pin here); and checkpoint resume is mesh-shape neutral.

Run under `XLA_FLAGS=--xla_force_host_platform_device_count=8` (the
repo conftest forces this; the CI `multichip` job sets it explicitly).
"""

import jax
import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.config import CorrectorConfig
from kcmc_tpu.utils import synthetic

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

SHAPE = (96, 96)


@pytest.fixture(scope="module")
def data():
    return synthetic.make_drift_stack(
        n_frames=14, shape=SHAPE, model="translation", max_drift=4.0,
        seed=3,
    )


# -- config / CLI / env surface (no sharded compiles: cheap) -------------


def test_mesh_devices_config_resolves():
    mc = MotionCorrector(
        model="translation", backend="jax", mesh_devices=8
    )
    assert mc.backend.mesh is not None
    assert mc.backend.mesh.devices.size == 8
    # -1 = all visible devices
    mc_all = MotionCorrector(
        model="translation", backend="jax", mesh_devices=-1
    )
    assert mc_all.backend.mesh.devices.size == len(jax.devices())
    # 0 (default) = single-chip
    assert MotionCorrector(model="translation").backend.mesh is None


def test_kcmc_devices_env_resolves(monkeypatch):
    monkeypatch.setenv("KCMC_DEVICES", "4")
    mc = MotionCorrector(model="translation", backend="jax")
    assert mc.backend.mesh.devices.size == 4
    # explicit config wins over the environment
    mc2 = MotionCorrector(
        model="translation", backend="jax", mesh_devices=2
    )
    assert mc2.backend.mesh.devices.size == 2
    monkeypatch.setenv("KCMC_DEVICES", "all")
    mc3 = MotionCorrector(model="translation", backend="jax")
    assert mc3.backend.mesh.devices.size == len(jax.devices())
    monkeypatch.setenv("KCMC_DEVICES", "0")
    assert MotionCorrector(model="translation").backend.mesh is None


def test_mesh_devices_validation():
    with pytest.raises(ValueError, match="mesh_devices"):
        CorrectorConfig(mesh_devices=-2)
    # oversubscription fails loudly at construction, not mid-run
    with pytest.raises(ValueError, match="devices"):
        MotionCorrector(
            model="translation", backend="jax",
            mesh_devices=len(jax.devices()) + 1,
        )


def test_kcmc_devices_env_failures_name_the_var(monkeypatch):
    """A stale or mistyped KCMC_DEVICES must fail with an error that
    points at the env var — the traceback alone has to make the shell
    export findable (the value came from the environment, not from
    anything in the failing run's code)."""
    monkeypatch.setenv("KCMC_DEVICES", "eight")
    with pytest.raises(ValueError, match="KCMC_DEVICES"):
        MotionCorrector(model="translation", backend="jax")
    monkeypatch.setenv("KCMC_DEVICES", str(len(jax.devices()) + 1))
    with pytest.raises(ValueError, match="KCMC_DEVICES"):
        MotionCorrector(model="translation", backend="jax")
    # explicit config errors stay env-free
    monkeypatch.delenv("KCMC_DEVICES")
    with pytest.raises(ValueError) as e:
        MotionCorrector(
            model="translation", backend="jax",
            mesh_devices=len(jax.devices()) + 1,
        )
    assert "KCMC_DEVICES" not in str(e.value)


def test_cli_explicit_devices_zero_forces_single_chip(monkeypatch):
    """`--devices 0` is the CLI's single-chip escape hatch: it clears
    the ambient KCMC_DEVICES opt-in so "explicit wins over env" holds
    for 0 too; an absent --devices leaves the env var in charge."""
    import os

    from kcmc_tpu.__main__ import _parse_reference_and_overrides

    class A:
        reference = "0"
        batch_size = 0
        max_keypoints = 0
        hypotheses = 0
        warp = ""
        quality = False
        devices = 0

    monkeypatch.setenv("KCMC_DEVICES", "8")
    _ref, overrides = _parse_reference_and_overrides(A())
    assert overrides["mesh_devices"] == 0
    assert "KCMC_DEVICES" not in os.environ
    mc = MotionCorrector(model="translation", backend="jax", **overrides)
    assert mc.backend.mesh is None

    monkeypatch.setenv("KCMC_DEVICES", "4")
    A.devices = None  # flag not passed: env stays authoritative
    _ref, overrides = _parse_reference_and_overrides(A())
    assert "mesh_devices" not in overrides
    assert os.environ["KCMC_DEVICES"] == "4"


def test_numpy_backend_ignores_mesh_devices(data):
    """The no-op mirror: one config must run on either backend — the
    degradation ladder fails a SHARDED jax batch over to numpy without
    a config scrub."""
    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=7,
        mesh_devices=8,
    )
    assert mc.backend.mesh is None
    info = mc.backend.runtime_info()
    assert info["mesh_devices_ignored"] == 8
    res = mc.correct(data.stack[:4])
    assert res.transforms.shape == (4, 3, 3)


def test_cli_devices_maps_to_mesh_devices():
    from kcmc_tpu.__main__ import _parse_reference_and_overrides

    class A:
        reference = "0"
        batch_size = 0
        max_keypoints = 0
        hypotheses = 0
        warp = ""
        quality = False
        devices = 4

    _ref, overrides = _parse_reference_and_overrides(A())
    assert overrides["mesh_devices"] == 4


# -- sharded execution parity -------------------------------------------


def test_sharded_batch_uneven_tail_and_k_padding(data):
    """The full `correct` over a mesh with batch_size % 8 != 0 AND
    max_keypoints % 8 != 0 — the two pre-round-6 hard errors — must
    match the single-device path within the documented tolerance,
    including the 2-frame tail batch (14 = 2*6 + 2)."""
    mk = lambda **kw: MotionCorrector(
        model="translation", backend="jax", batch_size=6,
        max_keypoints=100, **kw,
    )
    r1 = mk().correct(data.stack)
    r8 = mk(mesh_devices=8).correct(data.stack)
    np.testing.assert_allclose(r8.transforms, r1.transforms, atol=1e-4)
    np.testing.assert_allclose(r8.corrected, r1.corrected, atol=1e-4)
    for k in ("n_inliers", "n_matches"):
        np.testing.assert_array_equal(
            np.asarray(r8.diagnostics[k]), np.asarray(r1.diagnostics[k])
        )


@pytest.mark.slow
def test_sharded_rolling_template_uneven_everything(tmp_path):
    """Rolling template updates + streaming writeback over the mesh
    with non-divisible batch and K: the mesh-resident update seam
    (all-gathered tail blend + on-device reference re-extraction) must
    track the single-device rolling run within float32 blend
    tolerance."""
    from kcmc_tpu.io.tiff import write_stack

    data = synthetic.make_drift_stack(
        n_frames=24, shape=SHAPE, model="translation", max_drift=4.0,
        seed=9,
    )
    u16 = np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)
    mk = lambda **kw: MotionCorrector(
        model="translation", backend="jax", batch_size=6,
        max_keypoints=100, template_update_every=12, template_window=6,
        **kw,
    )
    r1 = mk().correct_file(str(src), output=str(tmp_path / "o1.tif"))
    r8 = mk(mesh_devices=8).correct_file(
        str(src), output=str(tmp_path / "o8.tif")
    )
    np.testing.assert_allclose(r8.transforms, r1.transforms, atol=1e-4)
    # the zero-stall path stayed engaged under the mesh
    assert r8.timing["pipeline"]["device_templates"] is True
    assert r8.timing["pipeline"]["template_updates"] == 1


class _PoisonAfter:
    def __init__(self, allow):
        self.allow = allow
        self.calls = 0

    def __call__(self, orig, loader, lo, hi):
        self.calls += 1
        if self.calls > self.allow:
            raise RuntimeError("simulated kill")
        return orig(loader, lo, hi)


@pytest.mark.slow
def test_resume_across_mesh_shapes(tmp_path, monkeypatch):
    """Mesh-shape-neutral checkpoints: a streaming run checkpointed on
    a 4-chip mesh resumes on an 8-chip mesh (mesh_devices is pinned out
    of the resume signature) and completes with transforms matching an
    uninterrupted run to registration tolerance. Byte-identity of the
    output file is only contractual on the SAME mesh shape — across
    shapes the agreement is float32-tight."""
    from kcmc_tpu.io import ChunkedStackLoader
    from kcmc_tpu.io.tiff import write_stack
    from kcmc_tpu.utils.checkpoint import load_stream_checkpoint

    data = synthetic.make_drift_stack(
        n_frames=32, shape=SHAPE, model="translation", max_drift=4.0,
        seed=5,
    )
    u16 = np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)
    orig = ChunkedStackLoader._read

    def run(output, devices, checkpoint=None, poison=None):
        mc = MotionCorrector(
            model="translation", backend="jax", batch_size=8,
            mesh_devices=devices,
        )
        if poison is not None:
            monkeypatch.setattr(
                ChunkedStackLoader, "_read",
                lambda self, lo, hi: poison(orig, self, lo, hi),
            )
        else:
            monkeypatch.setattr(ChunkedStackLoader, "_read", orig)
        return mc.correct_file(
            str(src), output=str(output), chunk_size=8,
            checkpoint=checkpoint and str(checkpoint),
            checkpoint_every=8,
        )

    ref = run(tmp_path / "ref.tif", devices=8)  # uninterrupted 8-chip

    ckpt = tmp_path / "run.ckpt.npz"
    out = tmp_path / "out.tif"
    with pytest.raises(RuntimeError, match="simulated kill"):
        run(out, devices=4, checkpoint=ckpt, poison=_PoisonAfter(3))
    meta, _ = load_stream_checkpoint(str(ckpt))
    assert 0 < meta["done"] < 32

    res = run(out, devices=8, checkpoint=ckpt)  # resume on MORE chips
    assert res.timing["restored_frames"] == meta["done"]
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-4)


# -- pipelined collectives (PR 18) ---------------------------------------


def test_ring_all_gather_matches_monolithic_gather():
    """The chunked ppermute ring is value-identical to the monolithic
    tiled all_gather — shards concatenated in axis-index order —
    including non-uniform chunk bounds (K % chunks != 0) and chunk
    counts clamped to the local row count."""
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from kcmc_tpu.parallel import sharded as sh

    mesh = Mesh(np.array(jax.devices()[:8]), ("i",))
    x = np.arange(8 * 6 * 3, dtype=np.float32).reshape(48, 3)

    def run(fn):
        f = jax.jit(
            sh.shard_map(fn, mesh=mesh, in_specs=(P("i"),), out_specs=P())
        )
        return np.asarray(f(x))

    mono = run(lambda v: jax.lax.all_gather(v, "i", tiled=True))
    for chunks in (1, 4, 64):  # uniform / uneven bounds / clamped to K
        ring = run(lambda v, c=chunks: sh.ring_all_gather(v, "i", 8, c))
        np.testing.assert_array_equal(ring, mono)


def test_collective_chunks_full_run_parity(data):
    """`collective_chunks` routes the reference gathers through the
    ring; the full sharded run must match the monolithic-gather mesh
    run within the documented float32 tolerance (same algorithm, same
    gathered values — only the collective's schedule changes)."""
    mk = lambda **kw: MotionCorrector(
        model="translation", backend="jax", batch_size=6,
        max_keypoints=100, mesh_devices=8, **kw,
    )
    mono = mk().correct(data.stack)
    ring = mk(collective_chunks=4).correct(data.stack)
    np.testing.assert_allclose(ring.transforms, mono.transforms, atol=1e-5)
    np.testing.assert_allclose(ring.corrected, mono.corrected, atol=1e-4)
