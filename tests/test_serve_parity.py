"""Cross-stream batching parity: the serving scheduler must produce the
SAME per-frame outputs as one-shot `correct()` runs of the same frames.

The acceptance contract (ISSUE 6): two concurrent sessions through the
scheduler, on the numpy and CPU-jax backends, match two sequential
one-shot runs within 1e-4 — including an uneven interleave and a
session that closes mid-window. Parity holds structurally (per-frame
registration keyed by the session-local global index, per-entry
references) so the observed deltas are 0 or float32 reduction-order
noise.
"""

from __future__ import annotations

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.serve.scheduler import StreamScheduler
from kcmc_tpu.utils.synthetic import make_drift_stack

TOL = 1e-4
BASE_KW = dict(
    model="translation", batch_size=8, max_keypoints=64, n_hypotheses=32,
)


def _stack(n, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


def _assert_close(res, truth):
    assert np.abs(res.transforms - truth.transforms).max() < TOL
    for key in ("n_inliers", "n_matches"):
        np.testing.assert_array_equal(
            res.diagnostics[key], truth.diagnostics[key]
        )


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_two_concurrent_sessions_match_sequential_oneshot(backend):
    s1, s2 = _stack(20, seed=0), _stack(14, seed=1)
    truth1 = MotionCorrector(backend=backend, **BASE_KW).correct(s1)
    truth2 = MotionCorrector(backend=backend, **BASE_KW).correct(s2)

    mc = MotionCorrector(backend=backend, **BASE_KW)
    sched = StreamScheduler(mc).start()
    try:
        a = sched.open_session(tenant="A")
        b = sched.open_session(tenant="B")
        # uneven interleave: submit sizes unrelated to the batch size,
        # alternating between streams
        sched.submit(a.sid, s1[:7])
        sched.submit(b.sid, s2[:3])
        sched.submit(a.sid, s1[7:9])
        sched.submit(b.sid, s2[3:14])
        sched.submit(a.sid, s1[9:20])
        ra = sched.close_session(a.sid, timeout=180)
        rb = sched.close_session(b.sid, timeout=180)
    finally:
        sched.stop()
    _assert_close(ra, truth1)
    _assert_close(rb, truth2)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_session_closing_mid_window_leaves_other_stream_exact(backend):
    """One session closes while the other's batches are still flowing
    through the shared window — the survivor's outputs must stay
    exact, and the closer's partial stream must equal a one-shot run
    of exactly the frames it submitted."""
    s1, s2 = _stack(9, seed=2), _stack(24, seed=3)
    truth1 = MotionCorrector(backend=backend, **BASE_KW).correct(s1)
    truth2 = MotionCorrector(backend=backend, **BASE_KW).correct(s2)

    mc = MotionCorrector(backend=backend, **BASE_KW)
    sched = StreamScheduler(mc).start()
    try:
        a = sched.open_session(tenant="closer")
        b = sched.open_session(tenant="survivor")
        sched.submit(b.sid, s2[:16])
        sched.submit(a.sid, s1)  # 9 frames: one full batch + a padded tail
        ra = sched.close_session(a.sid, timeout=180)  # closes mid-traffic
        sched.submit(b.sid, s2[16:])
        rb = sched.close_session(b.sid, timeout=180)
    finally:
        sched.stop()
    assert ra.timing["n_frames"] == 9
    _assert_close(ra, truth1)
    _assert_close(rb, truth2)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_rolling_template_stream_matches_oneshot(backend):
    """Rolling-template sessions: boundary updates land at the same
    absolute frame indices as a one-shot run, with the same averaging
    window, regardless of how the stream was sliced into submits."""
    stack = _stack(32, seed=4)
    truth = MotionCorrector(
        backend=backend, template_update_every=16, **BASE_KW
    ).correct(stack)

    mc = MotionCorrector(backend=backend, template_update_every=16, **BASE_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="roll")
        for lo in range(0, 32, 5):  # submit size coprime with E and B
            sched.submit(s.sid, stack[lo : lo + 5])
        res = sched.close_session(s.sid, timeout=180)
    finally:
        sched.stop()
    assert np.abs(res.transforms - truth.transforms).max() < TOL


def test_corrected_pixels_match_oneshot_jax():
    stack = _stack(12, seed=5)
    truth = MotionCorrector(backend="jax", **BASE_KW).correct(stack)
    mc = MotionCorrector(backend="jax", **BASE_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="pix", emit_frames=True)
        sched.submit(s.sid, stack)
        res = sched.close_session(s.sid, timeout=180)
    finally:
        sched.stop()
    assert res.corrected.shape == truth.corrected.shape
    assert np.abs(res.corrected - truth.corrected).max() < TOL


def test_explicit_reference_matches_oneshot_numpy():
    stack = _stack(10, seed=6)
    ref = stack[3]
    truth = MotionCorrector(
        backend="numpy", reference=ref, **BASE_KW
    ).correct(stack)
    mc = MotionCorrector(backend="numpy", **BASE_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="ref", reference=ref)
        sched.submit(s.sid, stack)
        res = sched.close_session(s.sid, timeout=180)
    finally:
        sched.stop()
    assert np.abs(res.transforms - truth.transforms).max() < TOL
