"""smooth_trajectory: stabilization semantics.

The contract under test: S_t = M_t @ inv(smooth(M)_t) removes motion
faster than ~sigma frames while following slower motion; an
already-smooth trajectory is left untouched (S == I); fields stabilize
by temporal high-pass.
"""

import numpy as np
import pytest

from kcmc_tpu import smooth_trajectory


def _translation(tx, ty):
    M = np.eye(3, dtype=np.float64)
    M[0, 2], M[1, 2] = tx, ty
    return M


def _jittery_pan(T=240, seed=0):
    """Correction warps for a slow sinusoid pan + white jitter.

    A drifting scene's correction warp carries -path (it removes the
    motion); the smooth pan component is what stabilization must KEEP.
    """
    rng = np.random.default_rng(seed)
    t = np.arange(T)
    pan_x = 30.0 * np.sin(2 * np.pi * t / T)
    pan_y = 10.0 * np.cos(2 * np.pi * t / T)
    jit = rng.normal(0, 1.5, size=(T, 2))
    Ms = np.stack(
        [
            _translation(-(pan_x[i] + jit[i, 0]), -(pan_y[i] + jit[i, 1]))
            for i in range(T)
        ]
    )
    return Ms, np.stack([pan_x, pan_y], -1), jit


def test_removes_jitter_keeps_pan():
    Ms, pan, jit = _jittery_pan()
    S = smooth_trajectory(Ms, sigma=12.0)
    # The stabilizing warp removes s_txy from the frame's position.
    s_txy = -S[:, :2, 2]
    pos_before = pan + jit
    pos_after = pos_before - s_txy
    interior = np.s_[30:-30]
    # (a) Only jitter-scale warps are applied — the 30-px pan stays in
    # the footage (full registration would put the whole pan in S).
    assert np.abs(s_txy).max() < 8.0
    # (b) The stabilized path still FOLLOWS the pan (a few px of
    # low-pass leak at this sigma/period ratio is expected; 21 px rms
    # would mean the pan was removed).
    dev = pos_after[interior] - pan[interior]
    assert np.sqrt((dev**2).mean()) < 3.0
    # (c) Frame-to-frame shake collapses.
    before = np.sqrt(np.diff(pos_before[interior], axis=0) ** 2).mean()
    after = np.sqrt(np.diff(pos_after[interior], axis=0) ** 2).mean()
    assert after < 0.35 * before


def test_smooth_trajectory_is_untouched():
    Ms, _, _ = _jittery_pan()
    # Strip the jitter: a smooth path must produce S == I.
    t = np.arange(len(Ms))
    smooth = np.stack(
        [
            _translation(
                -30.0 * np.sin(2 * np.pi * i / len(Ms)),
                -10.0 * np.cos(2 * np.pi * i / len(Ms)),
            )
            for i in t
        ]
    )
    S = smooth_trajectory(smooth, sigma=8.0)
    # A smooth path is (near-)untouched: the only deviation is the
    # curvature leak (1 - gain) * amplitude ~ 0.64 px at this
    # sigma/period/amplitude — crucially sub-px against a 30-px path,
    # and ZERO extra at the boundary (odd reflection; plain reflect
    # kinked the endpoint ~5 px).
    assert np.abs(S - np.eye(3)).max() < 0.8
    assert np.abs(S[[0, -1]] - np.eye(3)).max() < 0.1


def test_homography_renormalized():
    rng = np.random.default_rng(1)
    T = 60
    Ms = np.tile(np.eye(3), (T, 1, 1))
    Ms[:, 0, 2] = rng.normal(0, 2, T)
    Ms[:, 2, 0] = 1e-5 * rng.normal(0, 1, T)
    Ms[:, 2, 2] = 1.0
    S = smooth_trajectory(Ms, sigma=5.0)
    assert S.shape == (T, 3, 3)
    assert np.all(np.isfinite(S))
    # Stabilizers stay near identity-scale (renormalized smooth inverse).
    assert np.abs(S[:, 2, 2] - 1.0).max() < 1e-3


def test_rigid3d_shape():
    rng = np.random.default_rng(2)
    T = 40
    Ms = np.tile(np.eye(4), (T, 1, 1))
    Ms[:, :3, 3] = rng.normal(0, 1, (T, 3))
    S = smooth_trajectory(Ms, sigma=6.0)
    assert S.shape == (T, 4, 4)
    np.testing.assert_allclose(
        S[:, 3], np.tile([0.0, 0, 0, 1], (T, 1)), atol=1e-12
    )


def test_fields_highpass():
    rng = np.random.default_rng(3)
    T = 120
    t = np.arange(T, dtype=np.float64)
    slow = np.sin(2 * np.pi * t / T)[:, None, None, None] * np.ones((1, 4, 4, 2))
    fast = rng.normal(0, 0.5, (T, 4, 4, 2))
    S = smooth_trajectory(fields=slow + fast, sigma=10.0)
    assert S.shape == (T, 4, 4, 2)
    interior = np.s_[20:-20]
    # slow term suppressed, fast term kept
    resid = S[interior] - fast[interior]
    assert np.sqrt((resid**2).mean()) < 0.25 * np.sqrt((fast**2).mean())


def test_single_frame_and_validation():
    S = smooth_trajectory(np.eye(3)[None], sigma=5.0)
    np.testing.assert_allclose(S, np.eye(3)[None], atol=1e-12)
    with pytest.raises(ValueError):
        smooth_trajectory()
    with pytest.raises(ValueError):
        smooth_trajectory(np.eye(3)[None], fields=np.zeros((1, 2, 2, 2)))
    with pytest.raises(ValueError):
        smooth_trajectory(np.eye(3)[None], sigma=0.0)
    with pytest.raises(ValueError):
        smooth_trajectory(np.zeros((4, 2, 3)))


def test_interpolate_failed_linear_gap():
    from kcmc_tpu import interpolate_failed

    T = 10
    Ms = np.stack([_translation(2.0 * t, -t) for t in range(T)])
    good = np.ones(T, bool)
    good[[3, 4, 7]] = False
    garbage = Ms.copy()
    garbage[[3, 4, 7]] = np.eye(3)  # what a blank frame really returns
    fixed = interpolate_failed(garbage, good)
    # Linear drift: interpolation recovers the exact transforms.
    np.testing.assert_allclose(fixed, Ms, atol=1e-9)
    # Good frames pass through bit-unchanged.
    np.testing.assert_array_equal(fixed[good], garbage[good])


def test_interpolate_failed_end_runs_copy_nearest():
    from kcmc_tpu import interpolate_failed

    Ms = np.stack([_translation(t, 0.0) for t in range(6)])
    good = np.array([False, False, True, True, True, False])
    bad = Ms.copy()
    bad[[0, 1, 5]] = np.eye(3)
    fixed = interpolate_failed(bad, good)
    np.testing.assert_allclose(fixed[0], Ms[2])
    np.testing.assert_allclose(fixed[1], Ms[2])
    np.testing.assert_allclose(fixed[5], Ms[4])


def test_interpolate_failed_validation():
    from kcmc_tpu import interpolate_failed

    Ms = np.tile(np.eye(3), (4, 1, 1))
    with pytest.raises(ValueError, match="no good frames"):
        interpolate_failed(Ms, np.zeros(4, bool))
    with pytest.raises(ValueError, match="good mask"):
        interpolate_failed(Ms, np.ones(3, bool))
    np.testing.assert_array_equal(
        interpolate_failed(Ms, np.ones(4, bool)), Ms
    )


def test_interpolate_failed_single_survivor():
    """One good frame: every failed frame copies it (np.interp clamps
    to the lone sample on both sides) — finite, never identity."""
    from kcmc_tpu import interpolate_failed

    T = 7
    Ms = np.stack([_translation(3.0 * t, t) for t in range(T)])
    good = np.zeros(T, bool)
    good[3] = True
    bad = Ms.copy()
    bad[~good] = np.eye(3)
    fixed = interpolate_failed(bad, good)
    assert np.isfinite(fixed).all()
    for t in range(T):
        np.testing.assert_allclose(fixed[t], Ms[3])
    # survivor passes through bit-unchanged
    np.testing.assert_array_equal(fixed[3], bad[3])


def test_interpolate_failed_ends_and_interior_homography():
    """Failed runs at BOTH ends plus an interior gap, projective
    family: output stays finite, renormalized (M[2,2] == 1), ends copy
    the nearest good frame, and dtype is preserved."""
    from kcmc_tpu import interpolate_failed

    T = 9
    Ms = np.stack(
        [_translation(1.5 * t, -0.5 * t) for t in range(T)]
    ).astype(np.float32)
    Ms[:, 2, 0] = 1e-4  # mild projective row
    good = np.ones(T, bool)
    good[[0, 1, 4, 7, 8]] = False
    bad = Ms.copy()
    bad[~good] = np.eye(3, dtype=np.float32)
    fixed = interpolate_failed(bad, good)
    assert fixed.dtype == np.float32
    assert np.isfinite(fixed).all()
    np.testing.assert_allclose(fixed[:, 2, 2], 1.0, atol=1e-7)
    np.testing.assert_allclose(fixed[0], fixed[1], atol=1e-6)
    np.testing.assert_allclose(fixed[0], Ms[2], atol=1e-3)
    np.testing.assert_allclose(fixed[8], Ms[6], atol=1e-3)
    np.testing.assert_allclose(fixed[4], Ms[4], atol=1e-3)  # interior gap


def test_interpolate_failed_pipeline_recipe():
    """The documented repair: a blank (artifact) frame mid-drift gets
    its motion back from the neighbors instead of identity."""
    from kcmc_tpu import MotionCorrector, interpolate_failed
    from kcmc_tpu.utils import synthetic
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

    data = synthetic.make_drift_stack(
        n_frames=10, shape=(96, 96), model="translation", max_drift=6.0,
        seed=9,
    )
    stack = np.array(data.stack)
    stack[5] = 0.0  # shutter blank
    res = MotionCorrector(
        model="translation", backend="jax", batch_size=5
    ).correct(stack)
    good = np.asarray(res.diagnostics["n_inliers"]) >= 10
    assert not good[5] and good.sum() == 9
    fixed = interpolate_failed(res.transforms, good)
    gt = relative_transforms(data.transforms)
    # The blank frame's repaired transform lands near the true motion
    # (identity would be ~ the full accumulated drift off).
    err_fixed = np.abs(fixed[5, :2, 2] - gt[5, :2, 2]).max()
    err_identity = np.abs(res.transforms[5, :2, 2] - gt[5, :2, 2]).max()
    assert err_fixed < 2.0 and err_fixed < 0.5 * err_identity
    rmse = transform_rmse(fixed, gt, (96, 96))
    assert rmse < 1.0


def test_apply_correction_integration():
    """Stabilizers feed apply_correction like any other transforms."""
    from kcmc_tpu import apply_correction

    rng = np.random.default_rng(4)
    stack = rng.uniform(size=(6, 32, 32)).astype(np.float32)
    Ms = np.tile(np.eye(3, dtype=np.float32), (6, 1, 1))
    Ms[:, 0, 2] = rng.normal(0, 1.0, 6)
    out = apply_correction(stack, smooth_trajectory(Ms, sigma=2.0))
    assert out.shape == stack.shape and np.isfinite(out).all()
