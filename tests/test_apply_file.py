"""Registration-only streaming, apply_correction_file, and the
apply/stabilize CLI commands (the file-scale two-pass workflows)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector, apply_correction, apply_correction_file
from kcmc_tpu.io import read_stack, write_stack
from kcmc_tpu.utils import synthetic

SHAPE = (96, 96)


def _make_input(tmp_path, n_frames=6, model="translation"):
    data = synthetic.make_drift_stack(
        n_frames=n_frames, shape=SHAPE, model=model, max_drift=4.0, seed=11
    )
    path = tmp_path / "in.tif"
    write_stack(path, data.stack)
    return data, path


def test_emit_frames_false_registers_without_frames(tmp_path):
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    full = mc.correct_file(path)
    reg = mc.correct_file(path, emit_frames=False)
    assert reg.corrected.shape[0] == 0
    np.testing.assert_allclose(reg.transforms, full.transforms, atol=1e-6)
    # Diagnostics still flow (minus the pixel-level rescue rewrite).
    assert "n_inliers" in reg.diagnostics
    np.testing.assert_array_equal(
        reg.diagnostics["n_inliers"], full.diagnostics["n_inliers"]
    )


def test_emit_frames_false_quality_metrics_still_computed(tmp_path):
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=3, quality_metrics=True
    )
    reg = mc.correct_file(path, emit_frames=False)
    assert reg.corrected.shape[0] == 0
    assert (reg.diagnostics["template_corr"] > 0.5).all()


def test_emit_frames_false_rejects_output(tmp_path):
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    with pytest.raises(ValueError, match="registration-only"):
        mc.correct_file(path, output=str(tmp_path / "o.tif"), emit_frames=False)


def test_emit_frames_false_numpy_backend(tmp_path):
    """Backends without the emit_frames seam drop frames in the
    orchestrator — same transforms, empty corrected."""
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(model="translation", backend="numpy", batch_size=3)
    reg = mc.correct_file(path, emit_frames=False)
    full = mc.correct_file(path)
    assert reg.corrected.shape[0] == 0
    np.testing.assert_allclose(reg.transforms, full.transforms, atol=1e-6)


def test_emit_frames_false_nans_unrescued_quality(tmp_path):
    """Registration-only runs cannot rescue out-of-bound frames, so
    their template_corr (measured on a bounded-kernel-zeroed frame)
    must come back NaN, not as a silently-wrong score."""
    from kcmc_tpu.utils.synthetic import render_scene, _warp_scene

    rng = np.random.default_rng(5)
    scene = render_scene(rng, (256, 256), n_blobs=220)
    shifts = [(0.0, 0.0), (140.0, -20.0), (3.0, 2.0)]  # 140 > the ±128 bound
    mats = np.tile(np.eye(3, dtype=np.float32), (len(shifts), 1, 1))
    mats[:, :2, 2] = shifts
    stack = np.stack([_warp_scene(scene, m) for m in mats]).astype(np.float32)
    path = tmp_path / "big.tif"
    write_stack(path, stack)
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=3, warp="pallas",
        quality_metrics=True, rescue_warp=True,
    )
    reg = mc.correct_file(path, emit_frames=False)
    ok = np.asarray(reg.diagnostics["warp_ok"], bool)
    corr = np.asarray(reg.diagnostics["template_corr"])
    assert not ok[1] and ok[0] and ok[2]
    assert np.isnan(corr[1]) and np.isfinite(corr[[0, 2]]).all()
    # The full (rescuing) run reports a real score for the same frame.
    full = mc.correct_file(path)
    assert np.isfinite(full.diagnostics["template_corr"]).all()


def test_apply_correction_file_matches_in_memory(tmp_path):
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    res = mc.correct_file(path, emit_frames=False)
    out = tmp_path / "applied.tif"
    apply_correction_file(
        path, str(out), transforms=res.transforms, chunk_size=4
    )
    want = apply_correction(
        data.stack, res.transforms, output_dtype=data.stack.dtype
    )
    np.testing.assert_array_equal(read_stack(out), want)


def test_apply_correction_file_validation(tmp_path):
    data, path = _make_input(tmp_path)
    out = str(tmp_path / "o.tif")
    with pytest.raises(ValueError, match="exactly one"):
        apply_correction_file(path, out)
    with pytest.raises(ValueError, match="pages"):
        apply_correction_file(
            path, out, transforms=np.tile(np.eye(3), (3, 1, 1))
        )


def _run_cli(args, timeout=600):
    script = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import kcmc_tpu.__main__ as m; import sys; sys.exit(m.main(%r))"
    )
    return subprocess.run(
        [sys.executable, "-c", script % (args,)],
        capture_output=True, text=True, timeout=timeout,
    )


def test_cli_register_then_apply(tmp_path):
    """correct without -o (registration-only) -> apply to a second
    channel: the multi-channel file workflow end to end."""
    data, path = _make_input(tmp_path)
    # A "functional channel": different contrast, same motion.
    func = tmp_path / "func.tif"
    write_stack(func, (data.stack * 0.5 + 7.0).astype(np.float32))
    tpath = tmp_path / "reg.npz"
    out = _run_cli([
        "correct", str(path), "--transforms", str(tpath),
        "--model", "translation", "--batch-size", "3",
    ])
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout.strip().splitlines()[-1])["output"] is None

    opath = tmp_path / "func_corr.tif"
    out = _run_cli(["apply", str(func), str(tpath), "-o", str(opath)])
    assert out.returncode == 0, out.stderr
    got = read_stack(opath)
    want = apply_correction(
        (data.stack * 0.5 + 7.0).astype(np.float32),
        np.load(tpath)["transforms"],
        output_dtype="float32",
    )
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_cli_stabilize_piecewise_fields(tmp_path):
    """The fields branch of stabilize: piecewise registration feeds the
    temporal high-pass + streaming field apply."""
    from kcmc_tpu.utils.synthetic import make_piecewise_stack

    data = make_piecewise_stack(n_frames=8, shape=(96, 96), seed=2)
    path = tmp_path / "pw.tif"
    write_stack(path, data.stack)
    opath = tmp_path / "stab.tif"
    out = _run_cli([
        "stabilize", str(path), "-o", str(opath), "--sigma", "2",
        "--model", "piecewise", "--batch-size", "4",
    ])
    assert out.returncode == 0, out.stderr
    got = read_stack(opath)
    assert got.shape == data.stack.shape and np.isfinite(got).all()


def test_cli_apply_rejects_wrong_npz(tmp_path):
    data, path = _make_input(tmp_path)
    bad = tmp_path / "bad.npz"
    np.savez(bad, unrelated=np.zeros(3))
    out = _run_cli(["apply", str(path), str(bad), "-o", str(tmp_path / "o.tif")])
    assert out.returncode != 0
    assert "neither 'transforms' nor 'fields'" in out.stderr


def test_cli_stabilize(tmp_path):
    data, path = _make_input(tmp_path, n_frames=12)
    opath = tmp_path / "stab.tif"
    out = _run_cli([
        "stabilize", str(path), "-o", str(opath), "--sigma", "3",
        "--batch-size", "4",
    ])
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["sigma_frames"] == 3.0
    got = read_stack(opath)
    assert got.shape == data.stack.shape
    # Stabilized footage shakes less than the raw footage.
    shake = lambda s: np.abs(np.diff(np.asarray(s, np.float32), axis=0)).mean()
    assert shake(got) < shake(data.stack)
