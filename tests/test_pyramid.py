"""ORB scale pyramid (ops/pyramid.py + backend wiring): multi-octave
detection extends the zoom envelope from ±25% to ~2x.

The headline contract (VERDICT r3 item 2): similarity drift with
1.5-2x zoom — where the single-scale envelope test documents collapse —
is recovered with n_octaves=3, cross-backend, without touching the
flagship single-scale configs (n_octaves=1 default).
"""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.ops.pyramid import (
    octave_sizes,
    per_octave_k,
    resize_matrix,
)
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (256, 256)


def _zoom_stack(rng, scene, s, n=4, drift=3.0):
    """Frames showing `scene` scaled by s (about the center) plus small
    random drift — the same construction as the single-scale envelope
    test in test_robustness.py."""
    cy, cx = (SHAPE[0] - 1) / 2.0, (SHAPE[1] - 1) / 2.0
    mats = np.tile(np.eye(3, dtype=np.float32), (n, 1, 1))
    frames = [scene]
    for t in range(1, n):
        L = np.float32(s) * np.eye(2, dtype=np.float32)
        mats[t, :2, :2] = L
        mats[t, :2, 2] = rng.uniform(-drift, drift, 2).astype(
            np.float32
        ) + np.array([cx, cy], np.float32) - L @ np.array([cx, cy], np.float32)
        frames.append(synthetic._warp_scene(scene, mats[t]))
    st = np.stack(frames) + rng.normal(0, 0.01, (n,) + SHAPE).astype(np.float32)
    return st.astype(np.float32), mats


def test_resize_matrix_properties():
    # rows are a partition of unity (interpolation preserves constants)
    for n_in, n_out in ((256, 172), (256, 256), (100, 64), (64, 100)):
        m = resize_matrix(n_in, n_out)
        assert m.shape == (n_out, n_in)
        np.testing.assert_allclose(m.sum(axis=1), 1.0, atol=1e-6)
    # identity at equal size
    np.testing.assert_allclose(resize_matrix(64, 64), np.eye(64), atol=1e-6)
    # constant image stays constant; linear ramp stays linear (interior)
    m = resize_matrix(256, 172)
    ramp = np.arange(256, dtype=np.float32)
    out = m @ ramp
    centers = (np.arange(172) + 0.5) * (256 / 172) - 0.5
    np.testing.assert_allclose(out[5:-5], centers[5:-5], atol=1e-3)


def test_octave_geometry():
    sizes = octave_sizes((512, 512), 3, 1.5)
    assert sizes[0] == (512, 512)
    assert all(h % 8 == 0 and w % 8 == 0 for h, w in sizes)
    assert sizes[1][0] < sizes[0][0] > sizes[2][0]
    ks = per_octave_k(1024, 3)
    assert len(ks) == 3 and all(k % 8 == 0 for k in ks)


@pytest.mark.parametrize(
    "zoom,n_octaves,octave_scale,n_blobs",
    [
        (1.5, 3, 1.5, 220),
        # 2x zoom only shows the scene's central quarter, so corner-
        # evaluated RMSE extrapolates from quarter-confined matches —
        # the denser scene and the sqrt(2) spacing (whose powers hit 2x
        # exactly) keep the fit's lever-arm error under the bound.
        (2.0, 4, 2**0.5, 500),
        (0.67, 3, 1.5, 220),
    ],
)
def test_pyramid_recovers_large_zoom(zoom, n_octaves, octave_scale, n_blobs):
    """1.5-2x zoom at <0.1 px with the pyramid + coarse-to-fine refine —
    the regime where the single-scale run is documented
    (test_robustness envelope) to collapse to a false consensus."""
    import warnings

    rng = np.random.default_rng(3)
    scene = synthetic.render_scene(rng, SHAPE, n_blobs=n_blobs)
    st, mats = _zoom_stack(rng, scene, zoom)
    rel = relative_transforms(mats)

    mc = MotionCorrector(
        model="similarity", backend="jax", batch_size=4,
        n_octaves=n_octaves, octave_scale=octave_scale, max_keypoints=1024,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = mc.correct(st)
    err = transform_rmse(res.transforms, rel, SHAPE)
    # Bounds pinned per-regime (VERDICT r4 item 10 — the documented 2x
    # tail must not shelter a regression in the solid <= 1.5x regime).
    # Measured 2026-08-01 with the transform polish + bf16-compose pin
    # (DESIGN.md "The 2x-zoom TPU tail"): 1.5x 0.012, 2x 0.018,
    # 0.67x 0.008 px on BOTH platforms. ~3x headroom per regime; the
    # old 2x platform tail (0.34 px) fails loudly now.
    bound = 0.06 if zoom == 2.0 else 0.04
    assert err < bound, err
    # the recovered zoom itself is right (scale of the linear part)
    got_s = np.sqrt(np.abs(np.linalg.det(np.asarray(res.transforms)[1:, :2, :2])))
    np.testing.assert_allclose(got_s, zoom, rtol=0.01)


def test_single_scale_fails_where_pyramid_succeeds():
    """Contrast case: the same 1.5x-zoom stack through the default
    single-scale config must NOT reach pyramid accuracy — otherwise the
    pyramid is dead weight and the envelope documentation is stale."""
    import warnings

    rng = np.random.default_rng(3)
    scene = synthetic.render_scene(rng, SHAPE, n_blobs=220)
    st, mats = _zoom_stack(rng, scene, 1.5)
    rel = relative_transforms(mats)
    mc = MotionCorrector(model="similarity", backend="jax", batch_size=4)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = mc.correct(st)
    err = transform_rmse(res.transforms, rel, SHAPE)
    assert err > 0.5, err


def test_pyramid_cross_backend_parity():
    """jax and numpy backends agree on the multi-scale config (same
    resize constants, same octave layout, same coordinate mapping)."""
    import warnings

    rng = np.random.default_rng(5)
    scene = synthetic.render_scene(rng, SHAPE, n_blobs=220)
    st, mats = _zoom_stack(rng, scene, 1.4, n=3)
    rel = relative_transforms(mats)
    kw = dict(
        model="similarity", batch_size=4, n_octaves=3,
        octave_scale=1.5, max_keypoints=768,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rj = MotionCorrector(backend="jax", **kw).correct(st)
        rn = MotionCorrector(backend="numpy", **kw).correct(st)
    ej = transform_rmse(rj.transforms, rel, SHAPE)
    en = transform_rmse(rn.transforms, rel, SHAPE)
    assert ej < 0.1 and en < 0.1, (ej, en)


def test_flagship_configs_unaffected():
    """n_octaves=1 (default) goes through the unchanged single-scale
    stage — identical results to a pre-pyramid run."""
    data = synthetic.make_drift_stack(
        n_frames=6, shape=SHAPE, model="translation", max_drift=5.0, seed=0
    )
    rel = relative_transforms(data.transforms)
    res = MotionCorrector(model="translation", backend="jax", batch_size=3).correct(
        data.stack
    )
    assert transform_rmse(res.transforms, rel, SHAPE) < 0.1


def test_config_validation():
    with pytest.raises(ValueError, match="n_octaves"):
        MotionCorrector(n_octaves=0)
    with pytest.raises(ValueError, match="octave_scale"):
        MotionCorrector(n_octaves=2, octave_scale=1.0)
    with pytest.raises(ValueError, match="2D"):
        MotionCorrector(model="rigid3d", n_octaves=2)
