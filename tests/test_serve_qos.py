"""Deadline-aware serve scheduling (ISSUE 20; docs/SERVING.md
"Latency QoS").

The contract under test, end to end:

* **partial-window parity** — a deadline-forced partial dispatch,
  padded to the smallest covering batch-ladder rung, produces
  transforms equal (<= 1e-4, the `test_serve_parity.py` tolerance) to
  a one-shot run of the same frames, and the dispatch records a
  `deadline_forced` why;
* **bounded starvation** — every batch-class session a latency
  preemption skips gains aging credit; one at
  `serve_latency_starvation_limit` takes the slot unconditionally
  (deterministic white-box property, no scheduler thread);
* **predictive admission** — a submit whose predicted wait exceeds
  its deadline is rejected 429-style with a `predicted_wait_s` hint;
  a COLD plane (no device history) never rejects;
* **journal round-trip** — a migrated/resumed latency session keeps
  its class, session-default deadline, hit/miss scorecard, and the
  ORIGINAL absolute deadlines of outstanding frames;
* **per-class observability** — SLO objectives carry `qos_class`
  (Prometheus label included), the fleet wait hint folds per-class
  rungs, and the report's "Deadline QoS" table renders an em dash
  (never crashes) on pre-QoS artifacts.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.obs.latency import LatencyHistogram
from kcmc_tpu.plans.buckets import batch_ladder, route_batch
from kcmc_tpu.serve.scheduler import OverloadedError, StreamScheduler
from kcmc_tpu.utils.synthetic import make_drift_stack

TOL = 1e-4
MC_KW = dict(
    model="translation", backend="numpy", batch_size=8,
    max_keypoints=64, n_hypotheses=32,
)
# Effectively uncached horizon model: every pick recomputes from the
# live histograms, so a test's warm-up history is visible immediately
# (the 1s default would let picks read a stale cold-plane cache).
FRESH = dict(serve_latency_horizon_refresh_s=0.001)


def _stack(n=24, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


def _wait_done(sched, sess, n, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        with sched._lock:
            if sess.done >= n:
                return
        time.sleep(0.02)
    raise AssertionError(f"session never drained {n} frames")


def _whitebox_sched(**cfg):
    """A scheduler that admits sessions but never dispatches: the
    `_running` flag flips without the loop thread, so `_pick_locked`
    can be driven deterministically from the test."""
    mc = MotionCorrector(**cfg, **MC_KW)
    sched = StreamScheduler(mc)
    sched._running = True
    return sched


# -- the batch-bucket ladder (plans/buckets.py) -----------------------------


def test_batch_ladder_and_route():
    assert batch_ladder(8) == (1, 2, 4, 8)
    assert batch_ladder(12) == (1, 2, 4, 8, 12)
    assert batch_ladder(1) == (1,)
    with pytest.raises(ValueError, match="batch_size"):
        batch_ladder(0)
    ladder = batch_ladder(8)
    assert route_batch(1, ladder) == 1
    assert route_batch(3, ladder) == 4  # smallest covering rung
    assert route_batch(8, ladder) == 8
    assert route_batch(9, ladder) is None  # caller splits the window
    assert route_batch(0, ladder) is None


# -- class plumbing ---------------------------------------------------------


def test_qos_class_validated_and_exposed():
    sched = _whitebox_sched()
    try:
        with pytest.raises(ValueError, match="qos_class"):
            sched.open_session(qos_class="bogus")
        with pytest.raises(ValueError, match="deadline_ms"):
            sched.open_session(deadline_ms=-1.0)
        s = sched.open_session(
            qos_class="latency", deadline_ms=250.0, session_id="L"
        )
        b = sched.open_session(session_id="B")
        assert s.qos_class == "latency" and s.deadline_ms == 250.0
        assert b.qos_class == "batch" and b.deadline_ms is None
        assert s.snapshot()["qos_class"] == "latency"
        st = sched.stats()["deadline_qos"]
        assert st["qos_classes"] == {"L": "latency", "B": "batch"}
        assert set(st["dispatch_why"]) == {
            "dispatch.why.full_window", "dispatch.why.deadline_forced",
            "dispatch.why.preempted", "dispatch.why.fill_floor",
            "dispatch.why.flush",
        }
    finally:
        sched._running = False
        sched.stop()


# -- partial-window dispatch parity -----------------------------------------


def test_deadline_forced_partial_dispatch_is_parity_exact():
    """The headline contract: trickled latency-class submits with
    already-blown deadlines dispatch as rung-padded partials
    (deadline_forced), and the stream's transforms equal a one-shot
    run — padding rung and batch slicing never leak into results."""
    stack = _stack(24, seed=5)
    truth = MotionCorrector(**MC_KW).correct(stack)

    mc = MotionCorrector(
        serve_latency_admission=False, **FRESH, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        # Warm the horizon model: a full batch-class run gives the
        # plane batch_form/dispatch/device history, so the latency
        # picks below see horizon > 0 (deadline_forced, not the
        # cold-plane flush).
        warm = sched.open_session(tenant="warm")
        sched.submit(warm.sid, stack, first=0)
        res_w = sched.close_session(warm.sid, timeout=120)
        # batch stream that never touched a deadline: payload stays
        # byte-identical to pre-QoS (no deadline_qos section)
        assert "deadline_qos" not in res_w.timing

        s = sched.open_session(tenant="lat", qos_class="latency")
        for i in range(0, len(stack), 3):
            # 1ms deadline is blown by pick time: every 3-frame chunk
            # is a forced partial on the 4-rung
            sched.submit(s.sid, stack[i:i + 3], first=i, deadline_ms=1.0)
            _wait_done(sched, s, i + 3)
        res = sched.close_session(s.sid, timeout=120)
        st = sched.stats()
    finally:
        sched.stop()
    assert res.timing["n_frames"] == len(stack)
    assert np.abs(res.transforms - truth.transforms).max() < TOL
    dq = st["deadline_qos"]
    assert dq["dispatch_why"]["dispatch.why.deadline_forced"] >= 1
    # the stream's close payload carries its class + scorecard
    assert res.timing["deadline_qos"]["qos_class"] == "latency"
    scored = (
        res.timing["deadline_qos"]["deadline_hits"]
        + res.timing["deadline_qos"]["deadline_misses"]
    )
    assert scored == len(stack)  # every deadline-stamped frame scored


def test_take_batch_pads_to_target_rung():
    """take_batch(target=rung) pads to the rung, not the full window —
    the compiled-program-per-rung contract the prewarm relies on."""
    sched = _whitebox_sched()
    try:
        stack = _stack(8, seed=6)
        s = sched.open_session(
            qos_class="latency", reference=stack[0], session_id="P"
        )
        sched._prepare_references()
        sched.submit("P", stack[:3], first=0, deadline_ms=1.0)
        with sched._lock:
            rung = route_batch(3, batch_ladder(8))
            taken = s.take_batch(8, target=rung)
        assert taken is not None
        n_valid, frames = taken[0], taken[1]
        assert n_valid == 3
        assert frames.shape[0] == rung == 4
    finally:
        sched._running = False
        sched.stop()


# -- class-aware preemption + bounded starvation ----------------------------


def test_preemption_starvation_bound_is_exact():
    """Deterministic white-box property: two latency preemptions age a
    skipped batch session to the limit; the third pick is the
    starvation grant — the batch session takes the slot, its credit
    resets, and the counters record exactly 2 preemptions + 1 grant."""
    stack = _stack(24, seed=7)
    sched = _whitebox_sched(serve_latency_starvation_limit=2)
    try:
        lat = sched.open_session(
            tenant="lat", reference=stack[0], qos_class="latency",
            session_id="L",
        )
        bat = sched.open_session(
            tenant="bat", reference=stack[0], session_id="B"
        )
        sched._prepare_references()
        # cold plane: predictive admission must NEVER reject (no
        # device history yet), even with a 1ms deadline
        sched.submit("L", stack, first=0, deadline_ms=1.0)
        sched.submit("B", stack[:8], first=0)
        with sched._lock:
            s1, t1, _, why1 = sched._pick_locked()
            assert s1 is lat and t1[0] == 8
            assert why1 == "preempted"  # full window, but B was skipped
            assert sched._starve_credit["B"] == 1
            s2, _, _, why2 = sched._pick_locked()
            assert s2 is lat and why2 == "preempted"
            assert sched._starve_credit["B"] == 2
            # credit hit the limit: the batch session takes this slot
            # even though the latency session still has a full window
            s3, t3, _, why3 = sched._pick_locked()
            assert s3 is bat and t3[0] == 8
            assert why3 == "full_window"
            assert sched._starve_credit["B"] == 0  # aging restarts
        dq = sched.stats()["deadline_qos"]
        assert dq["preemptions"] == 2
        assert dq["starvation_grants"] == 1
        assert lat.preempted_dispatches == 2
    finally:
        sched._running = False
        sched.stop()


# -- predictive admission ---------------------------------------------------


def test_predictive_admission_rejects_with_hint():
    stack = _stack(24, seed=8)
    mc = MotionCorrector(**FRESH, **MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        warm = sched.open_session(tenant="warm")
        sched.submit(warm.sid, stack, first=0)
        sched.close_session(warm.sid, timeout=120)

        s = sched.open_session(tenant="lat", qos_class="latency")
        # a 1-microsecond deadline is unmeetable against any warm
        # horizon: predictive admission rejects up front with the hint
        with pytest.raises(OverloadedError) as ei:
            sched.submit(s.sid, stack[:4], first=0, deadline_ms=0.001)
        assert ei.value.predicted_wait_s is not None
        assert ei.value.predicted_wait_s > 0
        # the hint matches the scheduler's own model (queue would have
        # been 4 frames deep)
        with sched._lock:
            want = sched._predicted_wait_locked(s, 4)
        assert want == pytest.approx(
            ei.value.predicted_wait_s, rel=0.5
        )
        with sched._lock:
            assert s.backlog() == 0  # nothing admitted
            assert s.submitted == 0
        dq = sched.stats()["deadline_qos"]
        assert dq["rejected_deadline_submits"] == 1
        # the same frames WITHOUT a deadline admit fine
        d = sched.submit(s.sid, stack[:4], first=0)
        assert d["accepted"] == 4
        res = sched.close_session(s.sid, timeout=120)
        assert res.timing["n_frames"] == 4
    finally:
        sched.stop()


# -- journal round-trip: class + outstanding deadlines ----------------------


def test_journal_roundtrip_preserves_class_and_deadlines(tmp_path):
    from kcmc_tpu.serve.journal import journal_path

    stack = _stack(24, seed=9)
    truth = MotionCorrector(**MC_KW).correct(stack)

    mc = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4,
        **MC_KW,
    )
    sched = StreamScheduler(mc).start()
    s = sched.open_session(
        tenant="q", session_id="Q1", qos_class="latency",
        deadline_ms=250.0,
    )
    sched.submit(s.sid, stack[:14], first=0, deadline_ms=60000.0)
    _wait_done(sched, s, 14)
    # 6 more frames with far-future deadlines: the warm plane's slack
    # check DEFERS them (deadline affords fill time), so they are
    # still pending — with live absolute deadlines — at stop()
    sched.submit(s.sid, stack[14:20], first=14, deadline_ms=60000.0)
    with sched._lock:
        orig_deadlines = dict(s._outstanding_deadlines())
        hits0, misses0 = s.deadline_hits, s.deadline_misses
    assert hits0 + misses0 == 14  # 60s deadlines: all scored by now
    sched.stop()
    assert os.path.exists(journal_path(str(tmp_path), "Q1"))

    mc2 = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4,
        **MC_KW,
    )
    sched2 = StreamScheduler(mc2).start()
    try:
        sess, cursor, resumed = sched2.resume_session("Q1")
        assert resumed and cursor == 14
        # class, session default, and scorecard survive the seam
        assert sess.qos_class == "latency"
        assert sess.deadline_ms == 250.0
        assert (sess.deadline_hits, sess.deadline_misses) == (
            hits0, misses0
        )
        # outstanding frames keep their ORIGINAL absolute deadlines —
        # a migrated stream's budget keeps burning, it never resets
        assert set(sess._replay_deadlines) == set(
            int(k) for k in orig_deadlines
        )
        for k, v in orig_deadlines.items():
            assert sess._replay_deadlines[int(k)] == pytest.approx(
                v, abs=1e-6
            )
        sched2.submit("Q1", stack[cursor:], first=cursor)
        with sched2._lock:
            # the replayed frames consumed their restored deadlines
            assert not sess._replay_deadlines
        res = sched2.close_session("Q1", timeout=120)
    finally:
        sched2.stop()
    assert res.timing["n_frames"] == 24
    assert np.abs(res.transforms - truth.transforms).max() < TOL
    assert res.timing["deadline_qos"]["qos_class"] == "latency"


# -- per-class SLOs (obs/slo.py) --------------------------------------------


def test_slo_objectives_carry_qos_class():
    from kcmc_tpu.obs.slo import parse_objectives

    objs = parse_objectives("latency:0.25:0.99;full:2:0.95;avail:0.999")
    by = {o.name: o for o in objs}
    lat = by["latency_latency_lt_0.25s"]
    ful = by["latency_full_lt_2s"]
    assert lat.qos_class == "latency"
    assert ful.qos_class == "batch"  # full rung measures batch traffic
    assert by["availability"].qos_class is None
    assert lat.describe()["qos_class"] == "latency"
    assert "qos_class" not in by["availability"].describe()


def test_slo_prometheus_has_per_class_labels():
    from kcmc_tpu.obs.slo import render_slo_prometheus

    slo = {
        "objectives": [
            {
                "name": "latency_latency_lt_0.25s", "kind": "latency",
                "rung": "latency", "threshold_s": 0.25, "target": 0.99,
                "qos_class": "latency",
            },
            {
                "name": "latency_batch_lt_2s", "kind": "latency",
                "rung": "batch", "threshold_s": 2.0, "target": 0.95,
                "qos_class": "batch",
            },
            {"name": "availability", "kind": "availability",
             "target": 0.999},
        ],
        "burn_rates": {}, "alerts": [],
    }
    text = "\n".join(render_slo_prometheus(slo))
    assert 'qos_class="latency"' in text
    assert 'qos_class="batch"' in text
    # availability carries no class label (pre-QoS scrape compatible)
    avail = [
        ln for ln in text.splitlines()
        if 'objective="availability"' in ln
    ]
    assert avail and all("qos_class" not in ln for ln in avail)


# -- fleet per-class wait hint (serve/fleet.py) -----------------------------


def _hist_dict(value_s, count):
    h = LatencyHistogram()
    h.record(value_s, n=count)
    return h.to_dict()


def test_fleet_predicted_wait_is_class_scoped():
    from kcmc_tpu.serve.fleet import predicted_wait_s

    metrics = {
        "plane": {
            "histograms": {
                "request.total": {
                    "latency": _hist_dict(0.01, 20),   # fast class
                    "full": _hist_dict(1.0, 20),       # slow batch
                    "degraded": _hist_dict(2.0, 4),
                },
            },
            "totals": {"request.total": {"p50_s": 0.5}},
        },
    }
    w_lat = predicted_wait_s(metrics, 0, 8, qos_class="latency")
    w_bat = predicted_wait_s(metrics, 0, 8, qos_class="batch")
    w_any = predicted_wait_s(metrics, 0, 8)
    assert w_lat is not None and w_bat is not None
    assert w_lat < w_bat  # the latency rung's history, not the fold
    assert w_bat > 0.5  # full+degraded fold dominates the blind total
    assert w_any == pytest.approx(0.5)  # class-blind: totals p50
    # a class with no history falls back to the class-blind total
    del metrics["plane"]["histograms"]["request.total"]["latency"]
    assert predicted_wait_s(
        metrics, 0, 8, qos_class="latency"
    ) == pytest.approx(0.5)
    # pre-QoS payload (no histograms at all): same fallback
    assert predicted_wait_s(
        {"plane": {"totals": {"request.total": {"p50_s": 0.5}}}},
        4, 8, qos_class="latency",
    ) == pytest.approx(0.75)
    # no history anywhere: None (never reject blind)
    assert predicted_wait_s({}, 0, 8, qos_class="latency") is None


# -- report surface (obs/report.py) -----------------------------------------


def test_report_deadline_qos_table_renders_and_degrades():
    from kcmc_tpu.obs.report import _deadline_qos_table

    # pre-QoS artifacts (missing / malformed section): em dash, never
    # a crash
    for timing in (None, {}, {"deadline_qos": None},
                   {"deadline_qos": "bogus"}, "not-a-dict"):
        lines = _deadline_qos_table(timing)
        assert len(lines) == 1 and "—" in lines[0]
    lines = _deadline_qos_table({
        "deadline_qos": {
            "qos_class": "latency", "deadline_hits": 9,
            "deadline_misses": 1, "preempted_dispatches": 3,
        }
    })
    body = "\n".join(lines)
    assert "class=latency" in body
    assert "hit_rate=90.0%" in body
    assert "preempted_dispatches=3" in body
