"""File-to-file streaming correction + the CLI."""

import json
import subprocess
import sys

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import read_stack, write_stack
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (128, 128)


def _make_input(tmp_path, n_frames=6):
    data = synthetic.make_drift_stack(
        n_frames=n_frames, shape=SHAPE, model="translation", max_drift=5.0, seed=3
    )
    path = tmp_path / "in.tif"
    write_stack(path, data.stack, compression="deflate")
    return data, path


def test_correct_file_matches_in_memory(tmp_path):
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    res_mem = mc.correct(data.stack)
    res_file = mc.correct_file(path)
    np.testing.assert_allclose(res_file.transforms, res_mem.transforms, atol=1e-6)
    np.testing.assert_allclose(res_file.corrected, res_mem.corrected, atol=1e-5)


def test_correct_file_streams_output(tmp_path):
    data, path = _make_input(tmp_path)
    out_path = tmp_path / "out.tif"
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    res = mc.correct_file(path, output=str(out_path), compression="deflate")
    assert res.corrected.shape[0] == 0  # frames went to disk
    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), SHAPE
    )
    assert rmse < 0.6
    written = read_stack(out_path)
    assert written.shape == data.stack.shape
    ref = mc.correct(data.stack)
    np.testing.assert_allclose(written, ref.corrected, atol=1e-5)


def test_cli_info_and_correct(tmp_path):
    data, path = _make_input(tmp_path)
    env_script = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import kcmc_tpu.__main__ as m; import sys; sys.exit(m.main(%r))"
    )
    out = subprocess.run(
        [sys.executable, "-c", env_script % (["info", str(path)],)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    info = json.loads(out.stdout.strip().splitlines()[-1])
    assert info["n_frames"] == 6
    assert info["frame_shape"] == [128, 128]

    tpath = tmp_path / "t.npz"
    opath = tmp_path / "corr.tif"
    args = [
        "correct", str(path), "-o", str(opath), "--transforms", str(tpath),
        "--model", "translation", "--batch-size", "3", "--quality",
    ]
    out = subprocess.run(
        [sys.executable, "-c", env_script % (args,)],
        capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["output"] == str(opath)
    assert 0.5 < summary["template_corr_mean"] <= 1.0
    saved = np.load(tpath)
    assert saved["transforms"].shape == (6, 3, 3)
    assert read_stack(opath).shape == data.stack.shape


class _PoisonAfter:
    """Makes ChunkedStackLoader._read raise after `allow` successful
    chunk reads — a deterministic stand-in for a mid-run kill."""

    def __init__(self, allow):
        self.allow = allow
        self.calls = 0

    def __call__(self, orig, loader, lo, hi):
        self.calls += 1
        if self.calls > self.allow:
            raise RuntimeError("simulated kill")
        return orig(loader, lo, hi)


def test_streaming_resume_byte_identical(tmp_path, monkeypatch):
    """Kill-and-rerun via checkpoint= must resume after the last
    checkpointed frame and produce a byte-identical output TIFF."""
    from kcmc_tpu.io import ChunkedStackLoader
    from kcmc_tpu.io.tiff import write_stack
    from kcmc_tpu.utils.checkpoint import load_stream_checkpoint

    data = synthetic.make_drift_stack(
        n_frames=24, shape=(96, 96), model="translation", seed=11
    )
    u16 = np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)

    orig = ChunkedStackLoader._read

    def run(output, checkpoint=None, poison=None):
        mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
        if poison is not None:
            monkeypatch.setattr(
                ChunkedStackLoader, "_read",
                lambda self, lo, hi: poison(orig, self, lo, hi),
            )
        else:
            monkeypatch.setattr(ChunkedStackLoader, "_read", orig)
        return mc.correct_file(
            str(src), output=str(output), chunk_size=8,
            checkpoint=checkpoint and str(checkpoint),
            checkpoint_every=8,
        )

    ref = run(tmp_path / "ref.tif")  # uninterrupted, no checkpoint

    ckpt = tmp_path / "run.ckpt.npz"
    out = tmp_path / "out.tif"
    with pytest.raises(RuntimeError, match="simulated kill"):
        run(out, checkpoint=ckpt, poison=_PoisonAfter(2))
    meta, _segments = load_stream_checkpoint(str(ckpt))
    assert 0 < meta["done"] < 24  # partial progress checkpointed

    res = run(out, checkpoint=ckpt)  # resume to completion
    assert res.timing["restored_frames"] == meta["done"]
    assert (tmp_path / "ref.tif").read_bytes() == out.read_bytes()
    # transforms/diagnostics identical to the uninterrupted run
    np.testing.assert_array_equal(res.transforms.shape, (24, 3, 3))
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-6)

    # idempotent: re-running a completed job restores everything
    res2 = run(out, checkpoint=ckpt)
    assert res2.timing["restored_frames"] == 24
    np.testing.assert_allclose(res2.transforms, ref.transforms, atol=1e-6)
    assert (tmp_path / "ref.tif").read_bytes() == out.read_bytes()


def test_streaming_checkpoint_stale_config_restarts(tmp_path):
    """A checkpoint written under different settings must be ignored."""
    from kcmc_tpu.io.tiff import write_stack

    data = synthetic.make_drift_stack(
        n_frames=8, shape=(96, 96), model="translation", seed=12
    )
    src = tmp_path / "in.tif"
    write_stack(src, np.clip(data.stack * 40000, 0, 65535).astype(np.uint16))
    ckpt = tmp_path / "c.npz"
    out = tmp_path / "o.tif"
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    mc.correct_file(str(src), output=str(out), checkpoint=str(ckpt))
    # different config: stale checkpoint ignored, full restart still works
    mc2 = MotionCorrector(
        model="translation", backend="jax", batch_size=4, n_hypotheses=64
    )
    res = mc2.correct_file(str(src), output=str(out), checkpoint=str(ckpt))
    assert res.timing["restored_frames"] == 0
    assert res.transforms.shape == (8, 3, 3)


def test_streaming_checkpoint_requires_output(tmp_path):
    mc = MotionCorrector(model="translation", backend="jax")
    with pytest.raises(ValueError, match="checkpoint requires output"):
        mc.correct_file(str(tmp_path / "x.tif"), checkpoint=str(tmp_path / "c.npz"))


def test_streaming_checkpoint_replaced_input_restarts(tmp_path):
    """A completed checkpoint must not serve stale results when the
    input file is replaced by a different same-shape stack."""
    from kcmc_tpu.io.tiff import write_stack

    def make(seed):
        data = synthetic.make_drift_stack(
            n_frames=8, shape=(96, 96), model="translation", seed=seed
        )
        return np.clip(data.stack * 40000, 0, 65535).astype(np.uint16), data

    src = tmp_path / "in.tif"
    ckpt = tmp_path / "c.npz"
    out = tmp_path / "o.tif"
    u16a, _ = make(1)
    write_stack(src, u16a)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    ra = mc.correct_file(str(src), output=str(out), checkpoint=str(ckpt))

    u16b, _ = make(2)  # same shape/dtype/frames, different content
    write_stack(src, u16b)
    rb = mc.correct_file(str(src), output=str(out), checkpoint=str(ckpt))
    assert rb.timing["restored_frames"] == 0  # checkpoint invalidated
    # and the results genuinely reflect the new stack
    assert not np.allclose(ra.transforms, rb.transforms)


def test_stall_watchdog_exits_and_resume_completes(tmp_path):
    """A frozen device wait must turn into exit(3) (stall_abort), and a
    rerun with the same checkpoint must finish the job."""
    import subprocess

    data = synthetic.make_drift_stack(
        n_frames=32, shape=(96, 96), model="translation", seed=23
    )
    src = tmp_path / "in.tif"
    write_stack(src, np.clip(data.stack * 40000, 0, 65535).astype(np.uint16))

    # Child wedges the loader after 5 chunks (like the observed tunnel
    # hang: blocked forever, no exception), with a 30 s stall budget
    # (well past the CPU compile, so progress starts before the freeze).
    # 5 chunks = 20 frames in: with depth-3 pipelined dispatch at least
    # two batches have drained and checkpointed before the freeze.
    script = f"""
import time
import numpy as np
import jax; jax.config.update('jax_platforms', 'cpu')
from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import ChunkedStackLoader

orig = ChunkedStackLoader._read
calls = {{}}
def wedge(self, lo, hi):
    calls['n'] = calls.get('n', 0) + 1
    if calls['n'] > 5:
        time.sleep(3600)  # simulated wedged link
    return orig(self, lo, hi)
ChunkedStackLoader._read = wedge
mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
mc.correct_file({str(src)!r}, output={str(tmp_path / 'out.tif')!r},
                chunk_size=4, checkpoint={str(tmp_path / 'c.npz')!r},
                checkpoint_every=4, stall_abort=30.0)
"""
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 3, (out.returncode, out.stderr[-1500:])
    assert "STALL" in out.stderr
    assert (tmp_path / "c.npz").exists()  # progress was checkpointed

    # rerun (no wedge): resumes from the checkpoint and completes
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    res = mc.correct_file(
        str(src), output=str(tmp_path / "out.tif"), chunk_size=4,
        checkpoint=str(tmp_path / "c.npz"), checkpoint_every=4,
    )
    assert res.timing["restored_frames"] > 0
    assert res.transforms.shape == (32, 3, 3)


def test_streaming_sharded_mesh_resume_byte_identical(tmp_path, monkeypatch):
    """VERDICT r2 #4: the streaming path under a device mesh. A sharded
    `correct_file` run (frames data-parallel over an 8-device mesh,
    reference all-gathered) with a mid-run kill + checkpoint resume
    must produce the byte-identical output TIFF of a sharded
    UNINTERRUPTED run (the resume contract), and match a single-device
    run to registration precision. RANSAC keys fold global frame
    indices, so estimation is device-count-independent; since the
    round-5 photometric polish, its f32 correlation reductions may
    TILE differently between the unsharded and per-shard programs, so
    cross-device-count agreement is ~1e-6-px-tight rather than bitwise
    (pinned at 1e-4 here)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from kcmc_tpu.io import ChunkedStackLoader
    from kcmc_tpu.io.tiff import write_stack
    from kcmc_tpu.parallel import make_mesh
    from kcmc_tpu.utils.checkpoint import load_stream_checkpoint

    data = synthetic.make_drift_stack(
        n_frames=40, shape=(96, 96), model="translation", seed=11
    )
    u16 = np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)

    orig = ChunkedStackLoader._read

    def run(output, mesh=None, checkpoint=None, poison=None):
        mc = MotionCorrector(
            model="translation", backend="jax", batch_size=8, mesh=mesh
        )
        if poison is not None:
            monkeypatch.setattr(
                ChunkedStackLoader, "_read",
                lambda self, lo, hi: poison(orig, self, lo, hi),
            )
        else:
            monkeypatch.setattr(ChunkedStackLoader, "_read", orig)
        return mc.correct_file(
            str(src), output=str(output), chunk_size=8,
            compression="deflate",
            checkpoint=checkpoint and str(checkpoint),
            checkpoint_every=8,
        )

    ref = run(tmp_path / "ref.tif")  # single-device, uninterrupted

    mesh = make_mesh(8)
    ref_sharded = run(tmp_path / "ref_sharded.tif", mesh=mesh)

    ckpt = tmp_path / "run.ckpt.npz"
    out = tmp_path / "out.tif"
    # allow 3 chunk reads: with batch==chunk==8 and dispatch depth 3,
    # the first drain (and so the first checkpoint) happens at the 3rd
    # dispatch; the kill then fires on the 4th read.
    with pytest.raises(RuntimeError, match="simulated kill"):
        run(out, mesh=mesh, checkpoint=ckpt, poison=_PoisonAfter(3))
    meta, _segments = load_stream_checkpoint(str(ckpt))
    assert 0 < meta["done"] < 40  # partial progress checkpointed

    res = run(out, mesh=mesh, checkpoint=ckpt)  # sharded resume
    assert res.timing["restored_frames"] == meta["done"]
    # resume contract: byte-identical to the uninterrupted SHARDED run
    assert (tmp_path / "ref_sharded.tif").read_bytes() == out.read_bytes()
    np.testing.assert_allclose(
        res.transforms, ref_sharded.transforms, atol=1e-6
    )
    # device-count invariance: registration-precision-tight vs the
    # single-device run (see docstring)
    np.testing.assert_allclose(res.transforms, ref.transforms, atol=1e-4)
