"""File-to-file streaming correction + the CLI."""

import json
import subprocess
import sys

import numpy as np

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import read_stack, write_stack
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (128, 128)


def _make_input(tmp_path, n_frames=6):
    data = synthetic.make_drift_stack(
        n_frames=n_frames, shape=SHAPE, model="translation", max_drift=5.0, seed=3
    )
    path = tmp_path / "in.tif"
    write_stack(path, data.stack, compression="deflate")
    return data, path


def test_correct_file_matches_in_memory(tmp_path):
    data, path = _make_input(tmp_path)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    res_mem = mc.correct(data.stack)
    res_file = mc.correct_file(path)
    np.testing.assert_allclose(res_file.transforms, res_mem.transforms, atol=1e-6)
    np.testing.assert_allclose(res_file.corrected, res_mem.corrected, atol=1e-5)


def test_correct_file_streams_output(tmp_path):
    data, path = _make_input(tmp_path)
    out_path = tmp_path / "out.tif"
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    res = mc.correct_file(path, output=str(out_path), compression="deflate")
    assert res.corrected.shape[0] == 0  # frames went to disk
    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), SHAPE
    )
    assert rmse < 0.6
    written = read_stack(out_path)
    assert written.shape == data.stack.shape
    ref = mc.correct(data.stack)
    np.testing.assert_allclose(written, ref.corrected, atol=1e-5)


def test_cli_info_and_correct(tmp_path):
    data, path = _make_input(tmp_path)
    env_script = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import kcmc_tpu.__main__ as m; import sys; sys.exit(m.main(%r))"
    )
    out = subprocess.run(
        [sys.executable, "-c", env_script % (["info", str(path)],)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    info = json.loads(out.stdout.strip().splitlines()[-1])
    assert info["n_frames"] == 6
    assert info["frame_shape"] == [128, 128]

    tpath = tmp_path / "t.npz"
    opath = tmp_path / "corr.tif"
    args = [
        "correct", str(path), "-o", str(opath), "--transforms", str(tpath),
        "--model", "translation", "--batch-size", "3", "--quality",
    ]
    out = subprocess.run(
        [sys.executable, "-c", env_script % (args,)],
        capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["output"] == str(opath)
    assert 0.5 < summary["template_corr_mean"] <= 1.0
    saved = np.load(tpath)
    assert saved["transforms"].shape == (6, 3, 3)
    assert read_stack(opath).shape == data.stack.shape
