"""Pallas patch-extraction kernel vs the XLA dynamic_slice gather
(interpret mode on CPU), and the pallas descriptor path end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from kcmc_tpu.ops.pallas_patch import ELEMENT_INDEXING, extract_patches

# The slab / 3D descriptor layouts place per-keypoint blocks with
# element-indexed BlockSpecs (`pl.Element`); jaxlib builds that predate
# the API (this dev image's 0.4.37) cannot run them even in interpret
# mode, so their equivalence tests skip there (they run on the TPU
# image and any jax with pallas element indexing).
needs_element_indexing = pytest.mark.skipif(
    not ELEMENT_INDEXING,
    reason="this jax/pallas build lacks pl.Element (element-indexed "
    "BlockSpecs)",
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    B, H, W, K, PAD = 3, 96, 96, 40, 16
    padded = jnp.asarray(
        rng.random((B, H + 2 * PAD, W + 2 * PAD), dtype=np.float32)
    )
    oy = jnp.asarray(rng.integers(0, H, size=(B, K)), dtype=jnp.int32)
    ox = jnp.asarray(rng.integers(0, W, size=(B, K)), dtype=jnp.int32)
    return padded, oy, ox


@pytest.mark.parametrize("P", [28, 32])
def test_matches_xla_gather(data, P):
    padded, oy, ox = data
    out = np.asarray(extract_patches(padded, oy, ox, P, interpret=True))

    def per(img, ys, xs):
        return jax.vmap(lambda y, x: lax.dynamic_slice(img, (y, x), (P, P)))(ys, xs)

    ref = np.asarray(jax.vmap(per)(padded, oy, ox))
    np.testing.assert_array_equal(out, ref)


def test_keypoint_padding(data):
    """K not divisible by the kernel's block size is padded internally."""
    padded, oy, ox = data
    out = np.asarray(extract_patches(padded, oy[:, :13], ox[:, :13], 28, interpret=True))
    ref = np.asarray(extract_patches(padded, oy, ox, 28, interpret=True))[:, :13]
    np.testing.assert_array_equal(out, ref)


@pytest.mark.parametrize("oriented", [False, True])
def test_describe_batch_pallas_path_matches_vmap(oriented):
    """The pallas descriptor route must produce the same bits as the
    per-frame XLA route (interpret mode off-TPU)."""
    from kcmc_tpu.ops.describe import describe_keypoints_batch
    from kcmc_tpu.ops.detect import detect_keypoints
    from kcmc_tpu.utils import synthetic

    rng = np.random.default_rng(4)
    frames = jnp.asarray(
        np.stack(
            [synthetic.render_scene(rng, (128, 128), n_blobs=60) for _ in range(3)]
        ).astype(np.float32)
    )
    kps = jax.vmap(lambda f: detect_keypoints(f, max_keypoints=64))(frames)
    ref = describe_keypoints_batch(frames, kps, oriented=oriented, use_pallas=False)
    out = describe_keypoints_batch(
        frames, kps, oriented=oriented, use_pallas=True, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@needs_element_indexing
def test_describe3d_batch_pallas_path_matches_vmap():
    """The plane-flattened 3D Pallas descriptor route must produce the
    same bits as the per-volume XLA route (interpret mode off-TPU)."""
    from kcmc_tpu.ops.describe3d import describe_keypoints_3d_batch
    from kcmc_tpu.ops.detect3d import detect_keypoints_3d
    from kcmc_tpu.utils.synthetic import make_drift_stack_3d

    data = make_drift_stack_3d(n_frames=3, shape=(16, 96, 96), seed=2)
    vols = jnp.asarray(data.stack, jnp.float32)
    kps = jax.vmap(
        lambda v: detect_keypoints_3d(v, max_keypoints=48, border=4)
    )(vols)
    ref = describe_keypoints_3d_batch(vols, kps, use_pallas=False)
    out = describe_keypoints_3d_batch(
        vols, kps, use_pallas=True, interpret=True
    )
    xor = (np.asarray(out) ^ np.asarray(ref)).view(np.uint8)
    diff = int(np.unpackbits(xor).sum())
    bits = 32 * ref.shape[-1] * ref.shape[0] * ref.shape[1]
    # split-precision selection + blend order: only exact-tie bits may flip
    assert diff <= bits * 1e-3


def test_smem_batch_chunking_matches_unchunked(data, monkeypatch):
    """Large B x K runs split the batch to fit scalar prefetch in SMEM
    (batch 64 x K=2048 overflows the 1 MB space otherwise); the split
    must be output-identical to one call.

    The budget is read at TRACE time (extract_patches is jitted), so the
    jit cache must be cleared after shrinking it — otherwise the second
    call is a cache hit of the unchunked executable and the test proves
    nothing.
    """
    import kcmc_tpu.ops.pallas_patch as pp

    padded, oy, ox = data
    ref = np.asarray(pp.extract_patches(padded, oy, ox, 16, interpret=True))
    try:
        # shrink the budget so even this tiny case must chunk per-frame
        monkeypatch.setattr(pp, "_SMEM_SCALAR_BUDGET", 8)
        assert pp._smem_batch_limit(2, oy.shape[1], pp._KB) == 1
        jax.clear_caches()
        got = np.asarray(
            pp.extract_patches(padded, oy, ox, 16, interpret=True)
        )
    finally:
        monkeypatch.undo()
        jax.clear_caches()  # don't leak tiny-budget traces to other tests
    np.testing.assert_array_equal(got, ref)


@needs_element_indexing
def test_slab_variant_matches_whole_frame_kernel():
    """The per-keypoint Element-indexed slab layout (the automatic
    fallback when a frame is too large for the resident-frame kernel's
    VMEM budget) is bit-identical to the whole-frame kernel on the same
    inputs, including the ORB moment outputs."""
    import jax.numpy as jnp

    from kcmc_tpu.ops import pallas_patch as pp

    rng = np.random.default_rng(0)
    B, H, W, K, P = 3, 96, 112, 24, 32
    r1 = (P - 2) // 2 + 1
    padded = jnp.asarray(
        rng.uniform(size=(B, H + 2 * r1, W + 2 * r1)).astype(np.float32)
    )
    Hp, Wp = padded.shape[1:]
    oy = jnp.asarray(rng.integers(0, Hp - P + 1, (B, K)), jnp.int32)
    ox = jnp.asarray(rng.integers(0, Wp - P + 1, (B, K)), jnp.int32)
    fx = jnp.asarray(rng.uniform(size=(B, K, 1)).astype(np.float32))
    fy = jnp.asarray(rng.uniform(size=(B, K, 1)).astype(np.float32))

    ref = pp.extract_blended_planes(
        padded, oy, ox, fx, fy, P, with_moments=True, interpret=True
    )
    got = pp._extract_blended_planes_slab(
        padded, oy, ox, fx, fy, P, with_moments=True, interpret=True
    )
    for name, a, b in zip(("pb", "m10", "m01"), ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)

    # VMEM gate boundaries: judged sizes use the resident-frame kernel,
    # 2048^2 does not (it would scoped-vmem OOM at compile time).
    assert pp.supports((512, 512), 32)
    assert pp.supports((1024, 1024), 32)
    assert not pp.supports((2048, 2048), 32)


def test_banded_extraction_matches_oracle_any_density():
    """The round-5 row-banded large-frame route must be exact for ANY
    keypoint density pattern — the aligned-runs dispatch has no
    per-band capacity, so a fully clustered scene (every keypoint in
    one band: the microscopy tissue-in-top-quarter case) extracts
    identically to a uniform one."""
    from kcmc_tpu.ops.pallas_patch import extract_blended
    from kcmc_tpu.utils import synthetic

    rng = np.random.default_rng(4)
    H = W = 768
    P = 32
    from kcmc_tpu.ops.pallas_patch import _extract_blended_planes_banded

    frames = np.stack(
        [synthetic.render_scene(rng, (H, W), n_blobs=300) for _ in range(2)]
    )
    r1 = (P - 2) // 2 + 1
    padded = jnp.asarray(
        np.pad(frames, ((0, 0), (r1, r1), (r1, r1)), mode="edge")
    )
    K = 64
    for ymax in (H // 4, H - r1 - 2):  # clustered, then uniform
        xy = np.stack([
            np.stack(
                [rng.uniform(r1 + 2, W - r1 - 2, K),
                 rng.uniform(r1 + 2, ymax, K)], -1,
            )
            for _ in range(2)
        ]).astype(np.float32)
        oy = jnp.asarray(np.floor(xy[..., 1]).astype(np.int32) + 1)
        ox = jnp.asarray(np.floor(xy[..., 0]).astype(np.int32) + 1)
        fx = jnp.asarray((xy[..., 0] % 1.0)[..., None].astype(np.float32))
        fy = jnp.asarray((xy[..., 1] % 1.0)[..., None].astype(np.float32))
        got = np.asarray(_extract_blended_planes_banded(
            padded, oy, ox, fx, fy, P, NB=4, interpret=True
        ))
        want = np.asarray(extract_blended(padded, jnp.asarray(xy), P,
                                          interpret=True))
        np.testing.assert_array_equal(got, want)


def test_narrow_slab_wrap_boundary_p65():
    """ADVICE r5 wrap-safety: P=65 is the narrow-slab layout's exact
    lane-window boundary — worst-case residual rx = 63 plus the 65-lane
    patch fills the 128-lane window with zero slack (63 + 65 = 128).
    Exercise origins that land rx on 63 through BOTH pre-shifted copies
    (ox % 128 == 63 -> copy 0, ox % 128 == 127 -> copy 1) and check
    the blended patches against a plain NumPy bilinear oracle; one more
    lane (P=66) must be refused by the _frame_fits_2copy gate."""
    from kcmc_tpu.ops import pallas_patch as pp

    P = 65
    r1 = (P - 2) // 2 + 1  # 32: the describe padding convention
    H, W = 96, 176
    Hp, Wp = H + 2 * r1, W + 2 * r1
    # the test must actually exercise the narrow-slab (2-copy) path
    assert pp._frame_fits_2copy(Hp, Wp, P)
    assert not pp._frame_fits_2copy(Hp, Wp, P + 1)

    rng = np.random.default_rng(7)
    padded = jnp.asarray(rng.random((2, Hp, Wp), dtype=np.float32))
    # rx = 63 via copy 0 (ox=63) and copy 1 (ox=127), plus aligned and
    # interior controls; oy exercises the row roll alongside
    ox = jnp.asarray([[63, 127, 0, 40], [127, 63, 95, 7]], jnp.int32)
    oy = jnp.asarray([[0, 31, 63, 95], [95, 8, 17, 2]], jnp.int32)
    fx = jnp.asarray(
        rng.random((2, 4, 1), dtype=np.float32), jnp.float32
    )
    fy = jnp.asarray(
        rng.random((2, 4, 1), dtype=np.float32), jnp.float32
    )

    got = np.asarray(
        pp.extract_blended_planes(padded, oy, ox, fx, fy, P, interpret=True)
    )

    pn = np.asarray(padded)
    fxn, fyn = np.asarray(fx), np.asarray(fy)
    for b in range(2):
        for k in range(4):
            y0, x0 = int(oy[b, k]), int(ox[b, k])
            p = pn[b, y0 : y0 + P, x0 : x0 + P]
            # same separable grouping (and f32 arithmetic) as the kernel
            yb = (1.0 - fyn[b, k, 0]) * p[:-1] + fyn[b, k, 0] * p[1:]
            want = (
                (1.0 - fxn[b, k, 0]) * yb[:, :-1]
                + fxn[b, k, 0] * yb[:, 1:]
            )
            np.testing.assert_allclose(
                got[b, k], want.astype(np.float32), atol=1e-6,
                err_msg=f"b={b} k={k} origin=({y0},{x0})",
            )
