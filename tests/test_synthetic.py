"""Tests for synthetic workload generation and evaluation metrics."""

import numpy as np

from kcmc_tpu.utils import metrics, synthetic


def test_drift_stack_shapes():
    data = synthetic.make_drift_stack(n_frames=4, shape=(64, 64), model="translation")
    assert data.stack.shape == (4, 64, 64)
    assert data.transforms.shape == (4, 3, 3)
    assert np.isfinite(data.stack).all()
    # frame 0 drift is small but transforms are exact homogeneous matrices
    np.testing.assert_allclose(data.transforms[:, 2, 2], 1.0)


def test_drift_stack_translation_consistency():
    """The generated frame must equal the scene shifted by the gt transform."""
    data = synthetic.make_drift_stack(n_frames=3, shape=(96, 96), model="translation", noise=0.0, seed=3)
    t = data.transforms[2][:2, 2]
    # Sample the scene at integer grid minus drift and compare interior.
    H, W = data.stack.shape[1:]
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    expected = synthetic._bilinear(data.reference, xs - t[0], ys - t[1])
    m = 20
    np.testing.assert_allclose(
        data.stack[2][m:-m, m:-m], expected[m:-m, m:-m], atol=1e-4
    )


def test_piecewise_stack_shapes():
    data = synthetic.make_piecewise_stack(n_frames=3, shape=(64, 64), grid=(8, 8))
    assert data.stack.shape == (3, 64, 64)
    assert data.fields.shape == (3, 8, 8, 2)


def test_3d_stack_shapes():
    data = synthetic.make_drift_stack_3d(n_frames=2, shape=(16, 48, 48))
    assert data.stack.shape == (2, 16, 48, 48)
    assert data.transforms.shape == (2, 4, 4)
    R = data.transforms[1][:3, :3]
    np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-5)


def test_transform_rmse_zero_for_identical():
    T = np.tile(np.eye(3, dtype=np.float32), (5, 1, 1))
    assert metrics.transform_rmse(T, T, (64, 64)) == 0.0


def test_transform_rmse_translation_units():
    """A pure 3-4 translation error must give RMSE = 5 px exactly."""
    gt = np.tile(np.eye(3, dtype=np.float32), (2, 1, 1))
    est = gt.copy()
    est[:, 0, 2] = 3.0
    est[:, 1, 2] = 4.0
    assert abs(metrics.transform_rmse(est, gt, (64, 64)) - 5.0) < 1e-5


def test_stage_timer():
    t = metrics.StageTimer()
    with t.stage("detect"):
        pass
    with t.stage("detect"):
        pass
    with t.stage("warp"):
        pass
    rep = t.report(n_frames=10)
    assert set(rep["stages_s"]) == {"detect", "warp"}
    assert t.counts["detect"] == 2
    assert rep["frames_per_sec"] > 0
