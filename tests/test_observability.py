"""Observability subsystem: golden-schema tests for the Chrome-trace
export and the per-frame records JSONL, the run manifest, heartbeat
lifecycle, StageTimer's stage_counts/mean reporting, and the advisory
warning-routing seam (kcmc_tpu/obs; ISSUE 4)."""

import io
import json
import logging
import threading
import time
import warnings

import numpy as np
import pytest

from kcmc_tpu.config import CorrectorConfig
from kcmc_tpu.obs import log as obs_log
from kcmc_tpu.obs.heartbeat import Heartbeat
from kcmc_tpu.obs.manifest import build_manifest, config_digest
from kcmc_tpu.obs.records import (
    REQUIRED_RECORD_KEYS,
    FrameRecordStream,
    read_jsonl,
    records_from_batch,
)
from kcmc_tpu.obs.trace import Tracer
from kcmc_tpu.utils.metrics import StageTimer


@pytest.fixture(autouse=True)
def _restore_logging():
    """Routing state is process-global; tests that configure CLI
    logging must not leak it into later pytest.warns-based suites."""
    yield
    obs_log.reset_cli_logging()


def _small_run(tmp_path, n_frames=12, **obs_kw):
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    data = make_drift_stack(
        n_frames=n_frames, shape=(64, 64), model="translation",
        max_drift=4.0, seed=0,
    )
    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=4, **obs_kw
    )
    return mc.correct(data.stack)


# -- Chrome trace export ----------------------------------------------------


def test_trace_export_schema(tmp_path):
    trace_path = str(tmp_path / "trace.json")
    _small_run(tmp_path, trace_path=trace_path)
    trace = json.loads((tmp_path / "trace.json").read_text())
    evs = trace["traceEvents"]
    assert len(evs) > 0
    # the golden schema: every event carries ts/dur/ph/tid (and pid/name)
    for ev in evs:
        assert {"ts", "dur", "ph", "tid", "pid", "name"} <= set(ev), ev
        assert ev["ph"] in ("X", "i", "C", "M")
        assert ev["ts"] >= 0 and ev["dur"] >= 0
    names = {e["name"] for e in evs}
    # stage spans, dispatch-seam spans, progress counters, thread names
    assert "prepare_reference" in names
    assert "register_batches" in names
    assert "dispatch_batch" in names
    assert any(e["ph"] == "C" and e["name"] == "frames_done" for e in evs)
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in evs)
    # complete spans nest inside the run: durations are microseconds
    reg = next(e for e in evs if e["name"] == "register_batches")
    disp = [e for e in evs if e["name"] == "dispatch_batch"]
    assert len(disp) == 3  # 12 frames / batch 4
    assert sum(d["dur"] for d in disp) <= reg["dur"] * 1.05
    # manifest + final timing ride in the metadata
    assert trace["metadata"]["manifest"]["kind"] == "kcmc_run_manifest"
    assert "stages_s" in trace["metadata"]["timing"]


def test_tracer_threads_and_counters():
    tr = Tracer()
    with tr.span("main_work", cat="stage"):
        pass

    def worker():
        with tr.span("worker_work", cat="writer"):
            pass

    t = threading.Thread(target=worker, name="bg-worker")
    t.start()
    t.join()
    tr.counter("frames_done", {"frames": 7})
    tr.instant("checkpoint_save", args={"done": 4})
    evs = tr.events()
    tids = {e["tid"] for e in evs if e["ph"] == "X"}
    assert len(tids) == 2  # two threads, two tracks
    meta = {
        e["args"]["name"] for e in evs if e["ph"] == "M"
    }
    assert "bg-worker" in meta
    # everything serializes to strict JSON
    json.dumps(tr.to_json())


# -- frame records JSONL ----------------------------------------------------


def test_frame_records_schema(tmp_path):
    rec_path = str(tmp_path / "frames.jsonl")
    res = _small_run(
        tmp_path, n_frames=12, frame_records_path=rec_path,
        quality_metrics=True,
    )
    lines = (tmp_path / "frames.jsonl").read_text().splitlines()
    objs = [json.loads(line) for line in lines]  # every line: valid JSON
    header, records, summary = objs[0], objs[1:-1], objs[-1]
    assert header["kind"] == "kcmc_frame_records"
    assert header["manifest"]["config_sha256"]
    assert summary["kind"] == "kcmc_run_summary"
    assert summary["frames"] == 12
    assert "stages_s" in summary["timing"]
    assert len(records) == 12
    for i, rec in enumerate(records):
        assert set(REQUIRED_RECORD_KEYS) <= set(rec), rec
        assert rec["frame"] == i  # frame order, one record per frame
        assert rec["model"] == "translation"
        assert rec["inlier_ratio"] is not None
        assert rec["rms_residual_px"] is not None
        assert "template_corr" in rec  # quality_metrics ran
    # records agree with the in-memory diagnostics
    assert [r["n_inliers"] for r in records] == [
        int(v) for v in res.diagnostics["n_inliers"]
    ]


def test_records_nan_becomes_null():
    recs = records_from_batch(
        0,
        {
            "n_keypoints": np.array([5]),
            "n_matches": np.array([0]),
            "n_inliers": np.array([0]),
            "rms_residual": np.array([np.nan]),
            "template_corr": np.array([np.nan]),
        },
        model="affine",
    )
    assert recs[0]["rms_residual_px"] is None
    assert recs[0]["template_corr"] is None
    json.dumps(recs, allow_nan=False)  # strict-JSON clean


def test_records_stream_backpressure_and_torn_tail(tmp_path):
    path = str(tmp_path / "r.jsonl")
    stream = FrameRecordStream(path, manifest={"m": 1}, depth=2)
    for lo in range(0, 64, 4):
        stream.append(
            records_from_batch(
                lo, {"n_inliers": np.arange(lo, lo + 4)}, model="t"
            )
        )
    stream.close(summary={"frames": 64})
    header, records, summary = read_jsonl(path)
    assert header["manifest"] == {"m": 1}
    assert [r["frame"] for r in records] == list(range(64))  # ordered
    assert summary["frames"] == 64
    # torn tail line (killed run) parses without the summary
    txt = (tmp_path / "r.jsonl").read_text().splitlines()
    (tmp_path / "torn.jsonl").write_text(
        "\n".join(txt[:-1]) + '\n{"frame": 99, "n_in'
    )
    _, records2, summary2 = read_jsonl(str(tmp_path / "torn.jsonl"))
    assert summary2 is None
    assert len(records2) == 64


def test_records_stream_resume_appends_not_truncates(tmp_path):
    """A checkpoint-resumed run must keep the killed run's records up
    to the resume cursor (they ARE the post-mortem), prune the tail the
    replay re-emits (drains outrun checkpoint saves), and append; a
    fresh run over the same path truncates as before."""
    path = str(tmp_path / "r.jsonl")
    first = FrameRecordStream(path, manifest={"m": 1})
    first.append(
        records_from_batch(0, {"n_inliers": np.arange(8)}, model="t")
    )
    first.close()  # killed run: no summary line
    # simulate the kill tearing the last line mid-write
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"frame": 8, "n_in')
    # checkpoint saved at frame 4 but frames 0..7 had drained: the
    # resumed run replays 4..11, so stale records 4..7 must be pruned
    resumed = FrameRecordStream(path, manifest={"m": 1})
    resumed.mark_resume(4)
    resumed.append(
        records_from_batch(4, {"n_inliers": np.arange(8)}, model="t")
    )
    resumed.close(summary={"frames": 12})
    header, records, summary = read_jsonl(path)
    assert header["manifest"] == {"m": 1}
    # one record per frame, no duplicates across the resume seam
    assert [r["frame"] for r in records] == list(range(12))
    assert summary["frames"] == 12
    raw = [json.loads(line) for line in open(path)]  # torn line pruned
    assert any(o.get("kind") == "kcmc_run_resume" for o in raw)
    # without mark_resume the same path truncates (fresh run semantics)
    fresh = FrameRecordStream(path, manifest={"m": 2})
    fresh.append(
        records_from_batch(0, {"n_inliers": np.arange(2)}, model="t")
    )
    fresh.close(summary={"frames": 2})
    header3, records3, _ = read_jsonl(path)
    assert header3["manifest"] == {"m": 2}
    assert len(records3) == 2


# -- manifest ---------------------------------------------------------------


def test_manifest_contents_and_config_hash():
    cfg = CorrectorConfig(model="affine")
    man = build_manifest(config=cfg, backend_name="numpy")
    assert man["kind"] == "kcmc_run_manifest"
    assert man["backend"] == "numpy"
    assert man["config"]["model"] == "affine"
    assert man["versions"]["kcmc_tpu"]
    assert man["versions"]["python"]
    json.dumps(man)  # JSON-safe throughout
    # the digest is deterministic and config-sensitive
    _, d1 = config_digest(cfg)
    _, d2 = config_digest(CorrectorConfig(model="affine"))
    _, d3 = config_digest(CorrectorConfig(model="rigid"))
    assert d1 == d2 != d3
    assert man["config_sha256"] == d1


def test_manifest_records_backend_runtime():
    from kcmc_tpu.backends import get_backend

    be = get_backend("numpy", CorrectorConfig())
    man = build_manifest(config=be.config, backend=be, backend_name="numpy")
    assert man["backend_runtime"]["backend"] == "numpy"
    assert man["backend_runtime"]["numpy"] == np.__version__


# -- heartbeat --------------------------------------------------------------


def test_heartbeat_lifecycle_no_thread_leak():
    before = threading.active_count()
    got = []
    hb = Heartbeat(0.02, lambda: "beat", emit=got.append)
    hb.start()
    hb.start()  # idempotent: no second thread
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.01)
    hb.stop()
    assert got and got[0] == "beat"
    assert hb.beats >= 1
    assert not hb.running
    hb.stop()  # idempotent
    assert threading.active_count() == before


def test_heartbeat_sampler_failure_mutes_not_raises():
    got = []

    def bad_sample():
        raise RuntimeError("boom")

    hb = Heartbeat(0.01, bad_sample, emit=got.append)
    with hb:
        time.sleep(0.1)
    assert not hb.running
    assert len(got) == 1  # one diagnostic, then muted
    assert "boom" in got[0]


def test_heartbeat_rejects_bad_interval():
    with pytest.raises(ValueError, match="positive"):
        Heartbeat(0.0, lambda: "x")
    with pytest.raises(ValueError, match="heartbeat_s"):
        CorrectorConfig(heartbeat_s=-1.0)


def test_heartbeat_during_run_emits_progress(tmp_path, monkeypatch):
    got = []
    import kcmc_tpu.obs.heartbeat as hb_mod

    monkeypatch.setattr(hb_mod, "_default_emit", got.append)
    _small_run(tmp_path, n_frames=12, heartbeat_s=0.01)
    assert got  # beat at least once during the run
    assert any("frames" in m and "fps" in m for m in got)
    # run teardown joined the heartbeat thread
    assert not any(
        t.name == "kcmc-heartbeat" for t in threading.enumerate()
    )


# -- StageTimer reporting (satellite: counts were collected, never
#    reported) --------------------------------------------------------------


def test_stage_timer_reports_counts_and_means():
    t = StageTimer()
    for _ in range(3):
        with t.stage("detect"):
            time.sleep(0.001)
    with t.stage("warp"):
        pass
    rep = t.report(n_frames=4)
    assert rep["stage_counts"] == {"detect": 3, "warp": 1}
    assert set(rep["stage_mean_s"]) == {"detect", "warp"}
    assert rep["stage_mean_s"]["detect"] == pytest.approx(
        rep["stages_s"]["detect"] / 3
    )


def test_stage_timer_emits_spans_into_tracer():
    t = StageTimer()
    t.tracer = Tracer()
    with t.stage("detect"):
        pass
    with t.stall("drain_sync"):
        pass
    t.add_stall("writer_backpressure", 0.25)
    evs = t.tracer.events()
    by_name = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert by_name["detect"]["cat"] == "stage"
    assert by_name["drain_sync"]["cat"] == "stall"
    # back-dated add_stall span carries the reported duration
    assert by_name["writer_backpressure"]["dur"] == pytest.approx(
        0.25e6, rel=0.01
    )


# -- advisory routing (satellite: logger + --verbose/--quiet) ---------------


def test_advise_defaults_to_warnings():
    with pytest.warns(RuntimeWarning, match="hello"):
        obs_log.advise("hello")


def test_advise_routes_to_logger_when_cli_configured():
    stream = io.StringIO()
    obs_log.setup_cli_logging(stream=stream)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning would raise
        obs_log.advise("routed message")
    assert "routed message" in stream.getvalue()
    assert "WARNING" in stream.getvalue()
    obs_log.reset_cli_logging()
    with pytest.warns(RuntimeWarning, match="back to warnings"):
        obs_log.advise("back to warnings")


def test_cli_logging_levels():
    stream = io.StringIO()
    logger = obs_log.setup_cli_logging(verbose=1, stream=stream)
    assert logger.level == logging.INFO
    logger = obs_log.setup_cli_logging(quiet=1, stream=stream)
    assert logger.level == logging.ERROR
    assert len(
        [h for h in logger.handlers if getattr(h, "_kcmc_cli_handler", False)]
    ) == 1  # replaced, not stacked


def test_ladder_warnings_still_warn_in_library_mode():
    """The chaos suite's pytest.warns contracts ride the advise()
    default path; spot-check one end to end."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    data = make_drift_stack(
        n_frames=8, shape=(64, 64), model="translation", seed=0
    )
    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=4,
        fault_plan="device:step=1:transient", retry_attempts=1,
        failover_backend=None,
    )
    with pytest.warns(RuntimeWarning, match="marking its"):
        res = mc.correct(data.stack)
    assert res.robustness["failed_frames"] == 4


# -- disabled-by-default cost: no obs objects are constructed ---------------


def test_observability_off_constructs_nothing(tmp_path):
    res = _small_run(tmp_path, n_frames=8)
    assert res.transforms is not None
    # telemetry handle is cleared after every run, enabled or not
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    mc = MotionCorrector(model="translation", backend="numpy", batch_size=4)
    mc.correct(make_drift_stack(n_frames=4, shape=(64, 64), seed=0).stack)
    assert mc._telemetry is None
    rec_path = tmp_path / "r.jsonl"
    mc2 = MotionCorrector(
        model="translation", backend="numpy", batch_size=4,
        frame_records_path=str(rec_path),
    )
    mc2.correct(make_drift_stack(n_frames=4, shape=(64, 64), seed=0).stack)
    assert mc2._telemetry is None  # @_telemetry_scope cleared it
    assert rec_path.exists()
