"""Bins-first oriented descriptor machinery (round 5).

The production contract — descriptors from the sorted bins-first route
equal the jnp oracle up to bf16 tie level — is covered by
test_pallas_patch/test_detect_describe_match; these tests pin the new
pieces directly: frame-level moments vs the conv definition, the
aligned-run sort, and the dynamic-block selection matmul.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kcmc_tpu.ops.describe import (
    _MOMENT_KERNELS,
    _aligned_runs,
    _moments_at_keypoints,
)
from kcmc_tpu.ops.pallas_patch import binned_select_rows, moment_maps


def test_moment_maps_match_conv():
    rng = np.random.default_rng(0)
    p = jnp.asarray(
        rng.normal(size=(2, 224, 200)).astype(np.float32)
    ).astype(jnp.bfloat16)
    m10, m01 = moment_maps(p, interpret=True)
    kern = jnp.asarray(_MOMENT_KERNELS, p.dtype)
    maps = lax.conv_general_dilated(
        p[:, None], kern, (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32,
    )
    np.testing.assert_allclose(
        np.asarray(m10), np.asarray(maps[:, 0]), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(m01), np.asarray(maps[:, 1]), atol=1e-4
    )


def test_moments_at_keypoints_match_patch_moments():
    # conv-fallback route vs the in-patch oracle definition
    from kcmc_tpu.ops.describe import _extract_patches, _moment_angles
    from kcmc_tpu.ops.patterns import ROT_RADIUS

    rng = np.random.default_rng(1)
    r = ROT_RADIUS
    img = rng.normal(size=(160, 160)).astype(np.float32)
    imgq = jnp.asarray(img).astype(jnp.bfloat16).astype(jnp.float32)
    xy = jnp.asarray(
        rng.uniform(20, 140, size=(64, 2)).astype(np.float32)
    )
    padded = jnp.pad(
        jnp.asarray(img).astype(jnp.bfloat16)[None],
        ((0, 0), (r + 1, r + 1), (r + 1, r + 1)), mode="edge",
    )
    m10, m01 = _moments_at_keypoints(
        padded, xy[None], r, use_pallas=False
    )
    ang_new = np.arctan2(np.asarray(m01)[0], np.asarray(m10)[0])
    raw, _ = _extract_patches(imgq, xy, r)
    ang_old = np.asarray(_moment_angles(raw, xy, r))
    # identical pixels, different summation order: tie-level only
    d = np.abs(np.angle(np.exp(1j * (ang_new - ang_old))))
    assert d.max() < 1e-4, f"max angle diff {d.max():.2e}"


def test_aligned_runs_structure():
    keys = jnp.asarray([2, 0, 2, 5, 0, 2, 9, 0], jnp.int32)  # 9 = drop
    n_groups, align = 6, 4
    src, astarts, aends = _aligned_runs(keys, n_groups, align)
    src = np.asarray(src)
    astarts, aends = np.asarray(astarts), np.asarray(aends)
    N = keys.shape[0]
    # group 0: items 1, 4, 7 (stable order), aligned run of 4
    assert astarts[0] == 0 and aends[0] == 4
    assert list(src[:4]) == [1, 4, 7, N]
    # group 2: items 0, 2, 5
    assert astarts[2] == 4 and aends[2] == 8
    assert list(src[4:8]) == [0, 2, 5, N]
    # group 5: item 3; empty groups have zero-length runs
    assert astarts[5] == 8 and aends[5] == 12 and src[8] == 3
    assert astarts[1] == aends[1] == 4
    # dropped key (9) appears nowhere
    assert 6 not in src[: aends[5]]
    # padding slots carry the sentinel
    assert (src[aends[5]:] == N).all()


def test_binned_select_rows_uses_each_blocks_matrix():
    rng = np.random.default_rng(3)
    B, Kp, L, V, align, nb = 2, 64, 96, 128, 16, 3
    flat = jnp.asarray(
        rng.normal(size=(B, Kp, L)).astype(np.float32)
    ).astype(jnp.bfloat16)
    sel = jnp.asarray(
        rng.normal(size=(nb, L, V)).astype(np.float32)
    ).astype(jnp.bfloat16)
    # per-block bins; frame 1 includes the padding sentinel nb (clamped)
    ibin = jnp.asarray([[0, 0, 2, 1], [1, 2, nb, 0]], jnp.int32)
    out = np.asarray(
        binned_select_rows(flat, ibin, sel, align, interpret=True)
    )
    f = np.asarray(flat, np.float32)
    s = np.asarray(sel, np.float32)
    ib = np.asarray(ibin)
    for b in range(B):
        for blk in range(Kp // align):
            ref = (
                f[b, blk * align : (blk + 1) * align]
                @ s[min(ib[b, blk], nb - 1)]
            )
            got = out[b, blk * align : (blk + 1) * align].astype(np.float32)
            np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.5)


def test_bins_first_route_matches_oracle_at_large_k():
    """End-to-end bins-first route (K >= _BINS_FIRST_MIN_K) vs the jnp
    oracle: tie-level bit mismatch only. Synthetic keypoints (detect
    not needed) keep the interpret-mode cost to one frame."""
    from kcmc_tpu.ops.describe import (
        _BINS_FIRST_MIN_K,
        describe_keypoints_batch,
    )
    from kcmc_tpu.ops.detect import Keypoints
    from kcmc_tpu.utils import synthetic

    rng = np.random.default_rng(9)
    H = W = 256
    K = _BINS_FIRST_MIN_K
    img = synthetic.render_scene(rng, (H, W), n_blobs=200).astype(np.float32)
    frames = jnp.asarray(img[None])
    xy = rng.uniform(20, W - 20, size=(1, K, 2)).astype(np.float32)
    valid = np.ones((1, K), bool)
    valid[0, -64:] = False
    kps = Keypoints(
        xy=jnp.asarray(xy),
        score=jnp.asarray(np.linspace(1, 0.1, K, dtype=np.float32)[None]),
        valid=jnp.asarray(valid),
    )
    d_new = describe_keypoints_batch(
        frames, kps, oriented=True, use_pallas=True, interpret=True
    )
    d_ref = describe_keypoints_batch(
        frames, kps, oriented=True, use_pallas=False
    )
    dn = np.ascontiguousarray(np.asarray(d_new))
    dr = np.ascontiguousarray(np.asarray(d_ref))
    assert np.all(dn[~valid] == 0)
    x = np.ascontiguousarray(dn ^ dr)
    bits = np.unpackbits(x.view(np.uint8), axis=-1).reshape(1, K, -1).sum(-1)
    frac = bits[valid].mean()
    # tie-level contract (bf16 quantization ties; on-chip record 0.169)
    assert frac < 1.5, f"avg bit mismatch {frac:.3f}"
    # drops (all-zero rows among valid) only from bin-capacity overflow:
    # random orientations at K=2048, cap=2x share => none expected
    dropped = (dn[valid] == 0).all(-1).sum()
    assert dropped == 0, f"{dropped} dropped descriptors"


def test_backmap_scatter_matches_gather(rng):
    """The sorted-layout back-map's two routes must agree exactly: the
    packed-sort inverse-permutation GATHER (common K) and the drop-mode
    word SCATTER it falls back to when K exceeds the lossless 32-bit
    pack (> ~32768, where raising used to abandon the run entirely)."""
    from kcmc_tpu.ops.describe import _backmap_words

    B, K, NW, Kp = 3, 40, 4, 64
    words = jnp.asarray(
        rng.integers(0, 2**32, size=(B, Kp, NW), dtype=np.uint32)
    )
    src = np.full((B, Kp), K, np.int32)  # padding sentinel everywhere
    for b in range(B):
        pos = rng.choice(Kp, size=K, replace=False)
        src[b, pos] = rng.permutation(K)
    g = np.asarray(_backmap_words(words, jnp.asarray(src), K))
    s = np.asarray(
        _backmap_words(words, jnp.asarray(src), K, force_scatter=True)
    )
    assert g.shape == (B, K, NW)
    np.testing.assert_array_equal(g, s)
    # spot-check the permutation semantics directly
    wnp = np.asarray(words)
    for b in range(B):
        for slot in range(Kp):
            k = src[b, slot]
            if k < K:
                np.testing.assert_array_equal(g[b, k], wnp[b, slot])
