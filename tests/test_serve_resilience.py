"""Serve-plane fault tolerance (ISSUE 14; docs/ROBUSTNESS.md
"Serve-plane failures").

The contract under test, end to end:

* **submit idempotency** — frames carry monotonic indices; replayed
  submits (client reconnect retries) are deduplicated at admission
  with outputs parity-equal to a clean run, and gaps are rejected;
* **durable journals + crash resume** — a server killed with SIGKILL
  mid-stream restarts over the same `--journal-dir` and resumes every
  journaled session from its last durable frame, with resumed outputs
  parity-equal (<= 1e-4, the `test_serve_parity.py` tolerance) to an
  uninterrupted run; corrupt journals quarantine instead of crashing;
* **backend supervision** — a FATAL injected device error quarantines
  the backend (rebuilt off the request path) and fails the batch over
  without dropping any session;
* **client resilience** — every read has a deadline (no forever-block
  on a half-open socket), transport drops/stalls are absorbed by
  reconnect + idempotent replay, and a dead server surfaces as
  ServeError(code=503), distinct from a drained stream (None);
* **graceful drain + staleness** — a stopping scheduler journals every
  open session first, and idle clients are reaped (journaled, not
  dropped).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.serve.journal import (
    SessionJournal,
    journal_path,
    load_session_journal,
)
from kcmc_tpu.serve.scheduler import StreamScheduler
from kcmc_tpu.utils.faults import (
    FatalFaultError,
    FaultPlan,
    TransientFaultError,
)
from kcmc_tpu.utils.synthetic import make_drift_stack

TOL = 1e-4
MC_KW = dict(
    model="translation", backend="numpy", batch_size=8,
    max_keypoints=64, n_hypotheses=32,
)


def _stack(n=24, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


def _wait_done(sched, sess, n, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        with sched._lock:
            if sess.done >= n:
                return
        time.sleep(0.02)
    raise AssertionError(f"session never drained {n} frames")


# -- fault-plan grammar: the serve surfaces ---------------------------------


def test_serve_surfaces_parse_and_fire():
    plan = FaultPlan.from_spec(
        "transport:step=1:raise, scheduler:stall=0.5, journal:times=2"
    )
    # transport raises only at its step ("raise" aliases fatal)
    plan.maybe_fail("transport", 0)
    with pytest.raises(FatalFaultError):
        plan.maybe_fail("transport", 1)
    # stall clauses never raise; they are consumed via take_stall
    assert plan.take_stall("scheduler") == 0.5
    assert plan.take_stall("scheduler") == 0.0  # spent
    # journal clause fires per attempt until its budget is spent
    for _ in range(2):
        with pytest.raises(TransientFaultError):
            plan.maybe_fail("journal", plan.op_index("journal"))
    plan.maybe_fail("journal", plan.op_index("journal"))
    assert plan.injected == 4


def test_stall_key_is_surface_restricted():
    with pytest.raises(ValueError, match="stall"):
        FaultPlan.from_spec("device:stall=1.0")
    with pytest.raises(ValueError, match="positive"):
        FaultPlan.from_spec("transport:stall=0")


# -- submit idempotency -----------------------------------------------------


def test_duplicate_submit_dedup_parity():
    """Replayed overlapping submits (reconnect retries) must be
    invisible: outputs equal a one-shot run of the logical stream."""
    stack = _stack(20, seed=1)
    truth = MotionCorrector(**MC_KW).correct(stack)

    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="dup")
        sched.submit(s.sid, stack[:8], first=0)
        # full replay of the first submit (a retry whose first attempt
        # actually landed): dropped wholesale
        d = sched.submit(s.sid, stack[:8], first=0)
        assert d["accepted"] == 0 and d["deduped"] == 8
        # partial overlap: only the new tail is admitted
        d = sched.submit(s.sid, stack[4:14], first=4)
        assert d["accepted"] == 6 and d["deduped"] == 4
        assert d["next"] == 14
        sched.submit(s.sid, stack[14:], first=14)
        res = sched.close_session(s.sid, timeout=120)
    finally:
        sched.stop()
    assert res.timing["n_frames"] == 20
    assert np.abs(res.transforms - truth.transforms).max() < TOL
    assert res.timing["robustness"]["deduped_frames"] == 12


def test_submit_gap_rejected():
    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="gap")
        sched.submit(s.sid, _stack(4), first=0)
        with pytest.raises(ValueError, match="gap"):
            sched.submit(s.sid, _stack(4), first=9)
    finally:
        sched.stop()


# -- journal round-trip + crash resume (in-process) -------------------------


def test_scheduler_stop_journals_and_resume_is_parity_exact(tmp_path):
    """The graceful-drain half of the resume contract: stop() journals
    every open session; a NEW scheduler over the same directory resumes
    it and the combined outputs equal an uninterrupted run."""
    stack = _stack(24, seed=2)
    truth = MotionCorrector(**MC_KW).correct(stack)

    mc = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    s = sched.open_session(tenant="t", session_id="J1")
    sched.submit(s.sid, stack[:14], first=0)
    _wait_done(sched, s, 14)
    sched.stop()  # graceful drain: journals, then fails the open stream
    assert os.path.exists(journal_path(str(tmp_path), "J1"))

    mc2 = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4, **MC_KW
    )
    sched2 = StreamScheduler(mc2).start()
    try:
        sess, cursor, resumed = sched2.resume_session("J1")
        assert resumed and cursor == 14
        # client replays from BEFORE the cursor: dedup absorbs it
        d = sched2.submit("J1", stack[10:], first=10)
        assert d["deduped"] == 4 and d["accepted"] == 10
        res = sched2.close_session("J1", timeout=120)
        st = sched2.stats()
    finally:
        sched2.stop()
    assert res.timing["n_frames"] == 24
    assert np.abs(res.transforms - truth.transforms).max() < TOL
    rb = res.timing["robustness"]
    assert rb["resumed_from_frame"] == 14
    assert st["resilience"]["sessions_resumed"] == 1
    # clean close discards the journal: no duplicate resurrection
    assert not os.path.exists(journal_path(str(tmp_path), "J1"))


def test_rolling_template_journal_resume_parity(tmp_path):
    """Resume across a template boundary: the journaled rolling state
    (template source, boundary, blend tail) must reproduce the
    uninterrupted boundary updates exactly."""
    stack = _stack(32, seed=3)
    truth = MotionCorrector(
        template_update_every=16, **MC_KW
    ).correct(stack)

    mc = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4,
        template_update_every=16, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    s = sched.open_session(
        tenant="roll", session_id="R1", template_update_every=16
    )
    sched.submit(s.sid, stack[:21], first=0)  # past the first boundary
    _wait_done(sched, s, 21)
    sched.stop()

    mc2 = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4,
        template_update_every=16, **MC_KW
    )
    sched2 = StreamScheduler(mc2).start()
    try:
        _sess, cursor, resumed = sched2.resume_session("R1")
        assert resumed
        sched2.submit("R1", stack[cursor:], first=cursor)
        res = sched2.close_session("R1", timeout=120)
    finally:
        sched2.stop()
    assert res.timing["n_frames"] == 32
    assert np.abs(res.transforms - truth.transforms).max() < TOL


def test_corrupt_journal_quarantines_and_rewinds(tmp_path):
    """Checkpoint-grade corruption handling: a corrupt part rewinds a
    static-reference journal to its last good prefix, a rolling-
    template journal refuses the rewind, and a corrupt meta record is
    quarantined — the serving plane never crashes over any of them."""
    j = SessionJournal(str(tmp_path), "C1", every=1)
    ref = {"ref_frame": np.zeros((4, 4), np.float32)}
    base = {"sid": "C1", "config": "x", "tail_lens": []}
    def seg(v):
        return [{
            "transform": np.eye(3, dtype=np.float32)[None],
            "n_inliers": np.array([v]),
        }]
    assert j.save(dict(base, done=1), seg(3), ref)
    assert j.save(dict(base, done=2), seg(5), ref)
    path = journal_path(str(tmp_path), "C1")
    meta, segments, _arrays = load_session_journal(path)
    assert meta["done"] == 2 and len(segments) == 2

    # corrupt the SECOND part: quarantined + rewound to cursor 1
    part1 = f"{path}.part00001.npz"
    with open(part1, "r+b") as f:
        f.truncate(os.path.getsize(part1) // 2)
    with pytest.warns(RuntimeWarning, match="corrupt"):
        got = load_session_journal(path)
    assert got is not None
    meta, segments, _arrays = got
    assert meta["done"] == 1 and len(segments) == 1
    assert os.path.exists(part1 + ".corrupt")

    # a ROLLING journal with a corrupt part refuses the rewind (the
    # stored template matches only the final cursor)
    jr = SessionJournal(str(tmp_path), "C2", every=1)
    tmpl = {"template": np.zeros((4, 4), np.float32)}
    rbase = {"sid": "C2", "config": "x", "tail_lens": []}
    assert jr.save(dict(rbase, done=1), seg(1), tmpl)
    assert jr.save(dict(rbase, done=2), seg(2), tmpl)
    rpath = journal_path(str(tmp_path), "C2")
    rpart1 = f"{rpath}.part00001.npz"
    with open(rpart1, "r+b") as f:
        f.truncate(os.path.getsize(rpart1) // 2)
    with pytest.warns(RuntimeWarning, match="corrupt"):
        assert load_session_journal(rpath) is None

    # corrupt the META record: quarantined, stream unresumable
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.warns(RuntimeWarning, match="corrupt"):
        assert load_session_journal(path) is None
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)

    # a scheduler resume over the quarantined journal reports "no
    # journal", it does not crash the plane
    mc = MotionCorrector(serve_journal_dir=str(tmp_path), **MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        with pytest.raises(KeyError, match="no journal"):
            sched.resume_session("C1")
    finally:
        sched.stop()


def test_resume_config_mismatch_rejected(tmp_path):
    mc = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    s = sched.open_session(session_id="M1")
    sched.submit(s.sid, _stack(12), first=0)
    _wait_done(sched, s, 12)
    sched.stop()

    kw = dict(MC_KW, n_hypotheses=64)  # SIG-AFFECTING change
    mc2 = MotionCorrector(serve_journal_dir=str(tmp_path), **kw)
    sched2 = StreamScheduler(mc2).start()
    try:
        with pytest.raises(ValueError, match="incompatible"):
            sched2.resume_session("M1")
    finally:
        sched2.stop()


def test_journal_write_failure_never_fails_the_stream(tmp_path):
    """An injected journal fault degrades durability (counted,
    advised), never the stream."""
    stack = _stack(16, seed=4)
    truth = MotionCorrector(**MC_KW).correct(stack)
    mc = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4,
        fault_plan="journal:always", **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="jf")
        with pytest.warns(RuntimeWarning, match="journal write"):
            sched.submit(s.sid, stack, first=0)
            res = sched.close_session(s.sid, timeout=120)
    finally:
        sched.stop()
    assert res.timing["n_frames"] == 16
    assert np.abs(res.transforms - truth.transforms).max() < TOL
    assert res.timing["robustness"]["journal_failures"] >= 1


# -- backend supervision ----------------------------------------------------


@pytest.mark.parametrize("spec", ["device:step=1:fatal"])
def test_fatal_device_error_fails_over_without_dropping_session(spec):
    """The acceptance case: a FATAL injected device error quarantines
    the backend and recovers the batch on the failover rung — zero
    dropped sessions, outputs within tolerance."""
    stack = _stack(24, seed=5)
    truth = MotionCorrector(backend="jax", **{
        k: v for k, v in MC_KW.items() if k != "backend"
    }).correct(stack)

    kw = {k: v for k, v in MC_KW.items() if k != "backend"}
    mc = MotionCorrector(backend="jax", fault_plan=spec, **kw)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="sup")
        with pytest.warns(RuntimeWarning, match="quarantined"):
            sched.submit(s.sid, stack, first=0)
            res = sched.close_session(s.sid, timeout=180)
        st = sched.stats()
    finally:
        sched.stop()
    assert res.timing["n_frames"] == 24
    rb = res.timing["robustness"]
    assert rb["backend_failovers"] >= 1
    assert rb["failed_frames"] == 0
    assert st["supervisor"]["backend_rebuilds"] >= 1
    assert np.abs(res.transforms - truth.transforms).max() < TOL


def test_transient_strikes_quarantine_and_rebuild():
    """Repeated transient dispatch failures cross the strike threshold
    and trigger a rebuild; the ladder's retry rung still recovers every
    batch, so nothing is lost meanwhile."""
    stack = _stack(24, seed=6)
    truth = MotionCorrector(**MC_KW).correct(stack)
    mc = MotionCorrector(
        fault_plan="device:times=2:transient",
        retry_backoff_s=0.001, serve_backend_strikes=2, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="strikes")
        sched.submit(s.sid, stack, first=0)
        res = sched.close_session(s.sid, timeout=120)
    finally:
        sched.stop()
    assert res.timing["n_frames"] == 24
    assert res.timing["robustness"]["device_retries"] >= 1
    assert np.abs(res.transforms - truth.transforms).max() < TOL


# -- scheduler-queue wedge surface ------------------------------------------


def test_scheduler_stall_and_error_injection_survive():
    """A scheduler stall clause wedges one iteration (visible in the
    wedge gauge ordering: the loop still beats afterwards) and a
    raising clause lands in the loop's error backstop — the plane keeps
    serving either way."""
    stack = _stack(12, seed=7)
    mc = MotionCorrector(
        fault_plan="scheduler:stall=0.2, scheduler:raise", **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="wedge")
        sched.submit(s.sid, stack, first=0)
        res = sched.close_session(s.sid, timeout=120)
        st = sched.stats()
    finally:
        sched.stop()
    assert res.timing["n_frames"] == 12
    assert st["supervisor"]["loop_beat_age_s"] >= 0.0
    assert sched.fault_plan.injected == 2


# -- staleness reap ---------------------------------------------------------


def test_stale_session_reaped_journaled_and_resumable(tmp_path):
    stack = _stack(12, seed=8)
    mc = MotionCorrector(
        serve_journal_dir=str(tmp_path), serve_journal_every=4,
        serve_session_timeout_s=0.3, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="idle", session_id="Z1")
        sched.submit(s.sid, stack, first=0)
        _wait_done(sched, s, 12)
        # the reap fires on the scheduler thread once the client has
        # been idle past the timeout — poll the counter
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0:
            with sched._lock:
                if sched._stats["sessions_reaped"]:
                    break
            time.sleep(0.05)
        st = sched.stats()
        assert st["resilience"]["sessions_reaped"] == 1
        assert st["sessions_open"] == 0
        # journaled, not dropped: the reaped stream resumes
        assert os.path.exists(journal_path(str(tmp_path), "Z1"))
        _sess, cursor, resumed = sched.resume_session("Z1")
        assert resumed and cursor == 12
        res = sched.close_session("Z1", timeout=120)
        assert res.timing["n_frames"] == 12
    finally:
        sched.stop()


# -- heartbeat narration ----------------------------------------------------


def test_aggregate_sampler_renders_resilience():
    from kcmc_tpu.obs.heartbeat import aggregate_sampler

    line = aggregate_sampler(lambda: {
        "sessions": [
            {"name": "t/s1", "frames": 10, "fps": 2.0, "idle_s": 40.0}
        ],
        "robustness": {"backend_failovers": 2, "journal_saves": 3},
        "stale": {"t/s1": 40.0},
        "loop_beat_age_s": 45.0,
    })()
    assert "robustness backend_failovers=2 journal_saves=3" in line
    assert "stale t/s1=40s" in line
    assert "SCHEDULER WEDGED 45s" in line


def test_aggregate_sampler_quiet_when_healthy():
    from kcmc_tpu.obs.heartbeat import aggregate_sampler

    line = aggregate_sampler(lambda: {
        "sessions": [{"name": "t/s1", "frames": 10, "fps": 2.0}],
        "robustness": {"backend_failovers": 0},
        "loop_beat_age_s": 0.01,
    })()
    assert "robustness" not in line
    assert "WEDGED" not in line


# -- transport resilience (real sockets) ------------------------------------


def _client(port, **kw):
    from kcmc_tpu.serve.client import ServeClient

    return ServeClient(port=port, **kw)


def test_transport_drop_and_stall_absorbed_by_reconnect():
    """A dropped connection and a stalled (half-open) reply are both
    absorbed: the client reconnects with backoff and replays the
    idempotent request; submits never double-process (dedup)."""
    from kcmc_tpu.serve.server import ServeServer

    stack = _stack(16, seed=9)
    truth = MotionCorrector(**MC_KW).correct(stack)
    stall_s = 3.0
    mc = MotionCorrector(
        # message 1 (the first submit) is dropped mid-request; message 3
        # stalls past the client read deadline (half-open socket:
        # io_timeout=1 -> deadline 2s < 3s stall)
        fault_plan=f"transport:step=1:raise, transport:step=3:stall={stall_s}",
        **MC_KW,
    )
    server = ServeServer(mc, port=0)
    with server:
        c = _client(
            server.port, io_timeout=1.0, reconnect_backoff_s=0.05
        )
        sid = c.open_session(tenant="net", session_id="N1")
        c.submit(sid, stack[:8])   # dropped -> reconnect -> replayed
        t_stall = time.monotonic()
        c.submit(sid, stack[8:])   # stalled reply -> timeout -> replayed
        out = c.close_session(sid, timeout=120)
        st = c.stats()
        c.close()
        # let the stalled handler wake and tear its connection down (the
        # sanitizer's socket-leak checker runs at test end)
        time.sleep(max(0.0, t_stall + stall_s + 0.5 - time.monotonic()))
    assert out["frames"] == 16
    assert np.abs(out["transforms"] - truth.transforms).max() < TOL
    # each replayed submit was deduplicated, never double-processed
    assert st["frames_done"] == 16


def test_results_distinguishes_server_gone_from_drained():
    from kcmc_tpu.serve.client import ServeClient, ServeError
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(**MC_KW)
    server = ServeServer(mc, port=0)
    with server:
        c = ServeClient(
            port=server.port, io_timeout=1.0,
            reconnect_attempts=2, reconnect_backoff_s=0.05,
        )
        sid = c.open_session(tenant="gone")
        c.submit(sid, _stack(4))
        got = c.results(sid, timeout=30.0)
        assert got is not None and got["n"] == 4
        c.close_session(sid, timeout=60)
        # stream drained: None, not an error
        assert c.results(sid, timeout=5.0) is None
        # drop our socket so the post-shutdown poll must RECONNECT (a
        # lingering handler thread of the stopped server could otherwise
        # still answer on the old connection); close() is terminal, so
        # the keep-the-client-usable drop is disconnect()
        c.disconnect()
    # server gone: a coded transport error, not a hang and not None
    with pytest.raises(ServeError) as ei:
        c.results(sid, timeout=5.0)
    assert ei.value.code == 503
    c.close()


# -- the kill -9 canary (real process, real SIGKILL) ------------------------


def _spawn_serve(tmp_path, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kcmc_tpu", "serve",
            "--port", "0", "--backend", "numpy",
            "--batch-size", "8", "--max-keypoints", "64",
            "--hypotheses", "32",
            "--journal-dir", str(tmp_path / "journals"),
            "--journal-every", "4",
            *extra,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=env,
    )
    ready = json.loads(proc.stdout.readline())
    assert ready["serving"] is True
    return proc, ready["port"]


@pytest.mark.slow
def test_kill9_mid_stream_restart_resumes_with_zero_gaps(tmp_path):
    """THE acceptance canary: SIGKILL a serving process mid-stream,
    restart it over the same journal dir, resume, and the full-stream
    outputs are parity-equal to an uninterrupted run — no frame gaps,
    no duplicates."""
    stack = _stack(24, seed=10)
    truth = MotionCorrector(**MC_KW).correct(stack)

    proc, port = _spawn_serve(tmp_path)
    try:
        c = _client(port, io_timeout=5.0, reconnect_attempts=2)
        sid = c.open_session(tenant="k9", session_id="K1")
        c.submit(sid, stack[:16])
        # wait until the journal has durable frames
        jp = journal_path(str(tmp_path / "journals"), "K1")
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0:
            if os.path.exists(jp):
                loaded = load_session_journal(jp)
                if loaded is not None and int(loaded[0]["done"]) >= 4:
                    break
            time.sleep(0.05)
        else:
            raise AssertionError("journal never became durable")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        c.close()
    finally:
        if proc.poll() is None:
            proc.kill()

    proc2, port2 = _spawn_serve(tmp_path)
    try:
        c2 = _client(port2, io_timeout=5.0)
        cursor = c2.resume_session("K1")
        assert cursor >= 4
        # re-submit from the cursor: no gaps (the server rejects them),
        # overlap is impossible (we start exactly at the cursor)
        c2.submit("K1", stack[cursor:])
        out = c2.close_session("K1", timeout=180)
        st = c2.stats()
        c2.shutdown()
        c2.close()
    finally:
        try:
            proc2.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc2.kill()

    assert out["frames"] == 24
    assert np.abs(out["transforms"] - truth.transforms).max() < TOL
    assert st["resilience"]["sessions_resumed"] == 1
