"""Behavioral tests: detection finds real corners; descriptors match across
translated frames; KNN matching recovers the ground-truth shift."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.describe import N_WORDS, describe_keypoints
from kcmc_tpu.ops.detect import detect_keypoints
from kcmc_tpu.ops.match import hamming_matrix, knn_match, popcount_u32
from kcmc_tpu.utils import synthetic


@pytest.fixture(scope="module")
def scene():
    rng = np.random.default_rng(42)
    return synthetic.render_scene(rng, (160, 160), n_blobs=60)


def test_detect_finds_blob_peaks(scene):
    kps = detect_keypoints(jnp.asarray(scene), max_keypoints=128)
    assert kps.xy.shape == (128, 2)
    n_valid = int(kps.valid.sum())
    assert n_valid > 20, f"expected plenty of corners, got {n_valid}"
    # all valid keypoints inside the border
    xy = np.asarray(kps.xy)[np.asarray(kps.valid)]
    assert (xy >= 15).all() and (xy <= 160 - 15).all()
    # scores sorted descending
    sc = np.asarray(kps.score)[np.asarray(kps.valid)]
    assert (np.diff(sc) <= 1e-6).all()


def test_detect_subpixel_tracks_shift(scene):
    """Shifting the image by a fraction of a pixel must move detections."""
    shift = 0.4
    H, W = scene.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    shifted = synthetic._bilinear(scene, xs - shift, ys)
    k0 = detect_keypoints(jnp.asarray(scene), max_keypoints=64)
    k1 = detect_keypoints(jnp.asarray(shifted), max_keypoints=64)
    xy0 = np.asarray(k0.xy)[np.asarray(k0.valid)]
    xy1 = np.asarray(k1.xy)[np.asarray(k1.valid)]
    # match nearest keypoints between the two sets
    d = np.linalg.norm(xy0[:, None] - xy1[None, :], axis=-1)
    nn = d.argmin(1)
    close = d[np.arange(len(xy0)), nn] < 1.5
    dx = (xy1[nn[close], 0] - xy0[close, 0]).mean()
    assert abs(dx - shift) < 0.15, f"mean dx {dx}, want ~{shift}"


def test_describe_shapes_and_masking(scene):
    kps = detect_keypoints(jnp.asarray(scene), max_keypoints=64)
    desc = describe_keypoints(jnp.asarray(scene), kps)
    assert desc.shape == (64, N_WORDS)
    assert desc.dtype == jnp.uint32
    invalid = ~np.asarray(kps.valid)
    assert (np.asarray(desc)[invalid] == 0).all()


def test_popcount():
    x = jnp.asarray(np.array([0, 1, 3, 0xFFFFFFFF, 0xAAAAAAAA], dtype=np.uint32))
    np.testing.assert_array_equal(np.asarray(popcount_u32(x)), [0, 1, 2, 32, 16])


def test_match_recovers_translation(scene):
    """detect+describe+match across a shifted frame: displacement of valid
    matches equals the shift."""
    t = np.array([5.0, -3.0], dtype=np.float32)
    H, W = scene.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    moved = synthetic._bilinear(scene, xs - t[0], ys - t[1])

    kr = detect_keypoints(jnp.asarray(scene), max_keypoints=128)
    dr = describe_keypoints(jnp.asarray(scene), kr)
    kq = detect_keypoints(jnp.asarray(moved), max_keypoints=128)
    dq = describe_keypoints(jnp.asarray(moved), kq)

    m = knn_match(dq, dr, kq.valid, kr.valid)
    n_valid = int(m.valid.sum())
    assert n_valid > 15, f"too few matches: {n_valid}"
    q_xy = np.asarray(kq.xy)
    r_xy = np.asarray(kr.xy)[np.asarray(m.idx)]
    disp = (q_xy - r_xy)[np.asarray(m.valid)]
    med = np.median(disp, axis=0)
    np.testing.assert_allclose(med, t, atol=0.3)


def test_match_masks_invalid():
    """Zero/invalid descriptors must never produce valid matches."""
    q = jnp.zeros((16, N_WORDS), dtype=jnp.uint32)
    r = jnp.zeros((16, N_WORDS), dtype=jnp.uint32)
    v = jnp.zeros(16, dtype=bool)
    m = knn_match(q, r, v, v)
    assert not bool(m.valid.any())


def test_hamming_matrix_identity():
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.integers(0, 2**32, size=(8, N_WORDS), dtype=np.uint32))
    v = jnp.ones(8, dtype=bool)
    D = np.asarray(hamming_matrix(d, d, v, v))
    assert (np.diag(D) == 0).all()
    assert (D == D.T).all()


def test_detect_describe_vmap_over_frames(scene):
    """The per-frame ops must vmap over a frame batch (pipeline contract)."""
    stack = jnp.stack([jnp.asarray(scene)] * 3)
    kps = jax.vmap(lambda f: detect_keypoints(f, max_keypoints=32))(stack)
    assert kps.xy.shape == (3, 32, 2)
    descs = jax.vmap(describe_keypoints)(stack, kps)
    assert descs.shape == (3, 32, N_WORDS)
    np.testing.assert_array_equal(np.asarray(descs[0]), np.asarray(descs[2]))


def test_mxu_match_exactly_equals_xor_topk_oracle():
    """The MXU ±1-matmul + min/argmin match must reproduce the direct
    XOR+popcount+top_k formulation bit-for-bit: same distance matrix,
    same best index (ties -> lowest index), same runner-up value, same
    validity under ratio/mutual/cap — including masked slots and
    duplicate descriptors (forced distance ties)."""
    import jax.numpy as jnp
    from jax import lax

    from kcmc_tpu.ops.describe import N_BITS
    from kcmc_tpu.ops.match import (
        Matches,
        hamming_matrix,
        hamming_matrix_mxu,
        knn_match,
    )

    rng = np.random.default_rng(11)
    Kq, Kr, W = 96, 80, 8
    q = rng.integers(0, 2**32, (Kq, W), dtype=np.uint32)
    r = rng.integers(0, 2**32, (Kr, W), dtype=np.uint32)
    # force exact-duplicate descriptors (distance-0 ties) and shared rows
    q[10] = q[11] = r[5]
    r[6] = r[5]
    q[-1] = q[0]
    qv = rng.uniform(size=Kq) < 0.9
    rv = rng.uniform(size=Kr) < 0.9
    qj, rj = jnp.asarray(q), jnp.asarray(r)
    qvj, rvj = jnp.asarray(qv), jnp.asarray(rv)

    D_xor = np.asarray(hamming_matrix(qj, rj, qvj, rvj))
    D_mxu = np.asarray(hamming_matrix_mxu(qj, rj, qvj, rvj))
    np.testing.assert_array_equal(D_xor, D_mxu)

    def oracle(ratio=0.85, max_dist=80, mutual=True):
        Di = jnp.asarray(D_xor).astype(jnp.int32)
        neg2, idx2 = lax.top_k(-Di, 2)
        best, second, idx = -neg2[:, 0], -neg2[:, 1], idx2[:, 0]
        ok = (best < max_dist) & (
            best.astype(jnp.float32) < ratio * second.astype(jnp.float32)
        )
        if mutual:
            rev = jnp.argmin(Di, axis=0)
            ok = ok & (rev[idx] == jnp.arange(Kq))
        ok = ok & qvj & (best < jnp.int32(N_BITS + 1))
        return Matches(idx.astype(jnp.int32), best, second, ok)

    for mutual in (True, False):
        for ratio, max_dist in ((0.85, 80), (1.0, 257)):
            got = knn_match(
                qj, rj, qvj, rvj, ratio=ratio, max_dist=max_dist, mutual=mutual
            )
            want = oracle(ratio=ratio, max_dist=max_dist, mutual=mutual)
            np.testing.assert_array_equal(np.asarray(got.idx), np.asarray(want.idx))
            np.testing.assert_array_equal(np.asarray(got.dist), np.asarray(want.dist))
            np.testing.assert_array_equal(
                np.asarray(got.second), np.asarray(want.second)
            )
            np.testing.assert_array_equal(
                np.asarray(got.valid), np.asarray(want.valid)
            )
