"""Behavioral tests: detection finds real corners; descriptors match across
translated frames; KNN matching recovers the ground-truth shift."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.detect import detect_keypoints
from kcmc_tpu.ops.describe import describe_keypoints, N_WORDS
from kcmc_tpu.ops.match import knn_match, popcount_u32, hamming_matrix
from kcmc_tpu.utils import synthetic


@pytest.fixture(scope="module")
def scene():
    rng = np.random.default_rng(42)
    return synthetic.render_scene(rng, (160, 160), n_blobs=60)


def test_detect_finds_blob_peaks(scene):
    kps = detect_keypoints(jnp.asarray(scene), max_keypoints=128)
    assert kps.xy.shape == (128, 2)
    n_valid = int(kps.valid.sum())
    assert n_valid > 20, f"expected plenty of corners, got {n_valid}"
    # all valid keypoints inside the border
    xy = np.asarray(kps.xy)[np.asarray(kps.valid)]
    assert (xy >= 15).all() and (xy <= 160 - 15).all()
    # scores sorted descending
    sc = np.asarray(kps.score)[np.asarray(kps.valid)]
    assert (np.diff(sc) <= 1e-6).all()


def test_detect_subpixel_tracks_shift(scene):
    """Shifting the image by a fraction of a pixel must move detections."""
    shift = 0.4
    H, W = scene.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    shifted = synthetic._bilinear(scene, xs - shift, ys)
    k0 = detect_keypoints(jnp.asarray(scene), max_keypoints=64)
    k1 = detect_keypoints(jnp.asarray(shifted), max_keypoints=64)
    xy0 = np.asarray(k0.xy)[np.asarray(k0.valid)]
    xy1 = np.asarray(k1.xy)[np.asarray(k1.valid)]
    # match nearest keypoints between the two sets
    d = np.linalg.norm(xy0[:, None] - xy1[None, :], axis=-1)
    nn = d.argmin(1)
    close = d[np.arange(len(xy0)), nn] < 1.5
    dx = (xy1[nn[close], 0] - xy0[close, 0]).mean()
    assert abs(dx - shift) < 0.15, f"mean dx {dx}, want ~{shift}"


def test_describe_shapes_and_masking(scene):
    kps = detect_keypoints(jnp.asarray(scene), max_keypoints=64)
    desc = describe_keypoints(jnp.asarray(scene), kps)
    assert desc.shape == (64, N_WORDS)
    assert desc.dtype == jnp.uint32
    invalid = ~np.asarray(kps.valid)
    assert (np.asarray(desc)[invalid] == 0).all()


def test_popcount():
    x = jnp.asarray(np.array([0, 1, 3, 0xFFFFFFFF, 0xAAAAAAAA], dtype=np.uint32))
    np.testing.assert_array_equal(np.asarray(popcount_u32(x)), [0, 1, 2, 32, 16])


def test_match_recovers_translation(scene):
    """detect+describe+match across a shifted frame: displacement of valid
    matches equals the shift."""
    t = np.array([5.0, -3.0], dtype=np.float32)
    H, W = scene.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    moved = synthetic._bilinear(scene, xs - t[0], ys - t[1])

    kr = detect_keypoints(jnp.asarray(scene), max_keypoints=128)
    dr = describe_keypoints(jnp.asarray(scene), kr)
    kq = detect_keypoints(jnp.asarray(moved), max_keypoints=128)
    dq = describe_keypoints(jnp.asarray(moved), kq)

    m = knn_match(dq, dr, kq.valid, kr.valid)
    n_valid = int(m.valid.sum())
    assert n_valid > 15, f"too few matches: {n_valid}"
    q_xy = np.asarray(kq.xy)
    r_xy = np.asarray(kr.xy)[np.asarray(m.idx)]
    disp = (q_xy - r_xy)[np.asarray(m.valid)]
    med = np.median(disp, axis=0)
    np.testing.assert_allclose(med, t, atol=0.3)


def test_match_masks_invalid():
    """Zero/invalid descriptors must never produce valid matches."""
    q = jnp.zeros((16, N_WORDS), dtype=jnp.uint32)
    r = jnp.zeros((16, N_WORDS), dtype=jnp.uint32)
    v = jnp.zeros(16, dtype=bool)
    m = knn_match(q, r, v, v)
    assert not bool(m.valid.any())


def test_hamming_matrix_identity():
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.integers(0, 2**32, size=(8, N_WORDS), dtype=np.uint32))
    v = jnp.ones(8, dtype=bool)
    D = np.asarray(hamming_matrix(d, d, v, v))
    assert (np.diag(D) == 0).all()
    assert (D == D.T).all()


def test_detect_describe_vmap_over_frames(scene):
    """The per-frame ops must vmap over a frame batch (pipeline contract)."""
    stack = jnp.stack([jnp.asarray(scene)] * 3)
    kps = jax.vmap(lambda f: detect_keypoints(f, max_keypoints=32))(stack)
    assert kps.xy.shape == (3, 32, 2)
    descs = jax.vmap(describe_keypoints)(stack, kps)
    assert descs.shape == (3, 32, N_WORDS)
    np.testing.assert_array_equal(np.asarray(descs[0]), np.asarray(descs[2]))
