"""Tile-autotune machinery (PR 13): search, stamp persistence, warm
replay, and determinism.

The contract the CI canary also asserts: tuning runs ONCE per (kernel,
shape, dtype) per cache, the winner persists as a plan stamp, and a
second run (a fresh process in production; a fresh PlanRuntime +
cleared registry here) replays the stamp with ZERO re-tunes and
identical stamp files.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from kcmc_tpu.plans import autotune
from kcmc_tpu.plans.cache import PlanCache


@pytest.fixture(autouse=True)
def _fresh_registry():
    # The compile-cache dir is process-global first-writer-wins
    # (plans/cache.enable_compile_cache): release it around each test
    # so every PlanRuntime here really stamps under ITS tmp_path.
    from kcmc_tpu.plans.cache import disable_compile_cache

    disable_compile_cache()
    autotune.reset_for_tests()
    yield
    autotune.reset_for_tests()
    disable_compile_cache()


def _runtime(tmp_path=None):
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.plans.runtime import PlanRuntime

    cfg = CorrectorConfig(
        compile_cache_dir=str(tmp_path) if tmp_path is not None else None
    )
    return PlanRuntime(cfg)


def test_search_picks_fastest_and_counts(tmp_path):
    rt = _runtime(tmp_path)
    calls = []

    def measure(c):
        calls.append(c)
        return {32: 3.0, 64: 1.0, 128: 2.0}[c]

    got = rt.tile("k", (64, 64), "float32", (32, 64, 128), 64, measure)
    assert got == 64
    assert set(calls) == {32, 64, 128}
    assert rt.stats()["autotune_tuned"] == 1


def test_infeasible_candidates_skipped_and_all_fail_falls_back(tmp_path):
    rt = _runtime(tmp_path)

    def sometimes(c):
        if c != 128:
            raise RuntimeError("VMEM OOM")
        return 1.0

    assert rt.tile("a", (8, 8), "f32", (32, 64, 128), 64, sometimes) == 128

    def never(c):
        raise RuntimeError("VMEM OOM")

    assert rt.tile("b", (8, 8), "f32", (32, 64, 128), 64, never) == 64
    s = rt.stats()
    assert s["autotune_tuned"] == 1 and s["autotune_default"] == 1


def test_stamp_roundtrip_zero_retunes_second_run(tmp_path):
    """The determinism contract: run 2 against the same cache replays
    run 1's winner with zero measure calls and identical stamps."""
    rt1 = _runtime(tmp_path)
    calls = []

    def measure(c):
        calls.append(c)
        return float(c)  # 32 wins

    w1 = rt1.tile("detect_strip", (256, 256), "float32",
                  (32, 64, 128), 64, measure)
    assert w1 == 32 and calls
    stamp_dir = os.path.join(str(tmp_path), "kcmc_plans")
    stamps1 = {
        f: open(os.path.join(stamp_dir, f)).read()
        for f in sorted(os.listdir(stamp_dir))
    }
    assert stamps1

    # "Second process": fresh registry + fresh runtime, same cache dir.
    autotune.reset_for_tests()
    rt2 = _runtime(tmp_path)
    calls2 = []
    w2 = rt2.tile("detect_strip", (256, 256), "float32",
                  (32, 64, 128), 64, lambda c: calls2.append(c) or 1.0)
    assert w2 == w1
    assert calls2 == [], "second run re-tuned instead of replaying"
    assert rt2.stats()["autotune_replayed"] == 1
    stamps2 = {
        f: open(os.path.join(stamp_dir, f)).read()
        for f in sorted(os.listdir(stamp_dir))
    }
    assert stamps2 == stamps1, "stamps changed across runs"


def test_tuple_winner_roundtrips_through_json(tmp_path):
    rt = _runtime(tmp_path)
    got = rt.tile(
        "pair", (16, 16), "f32", ((8, 128), (16, 256)), (8, 128),
        lambda c: float(sum(c)),
    )
    assert got == (8, 128)
    autotune.reset_for_tests()
    rt2 = _runtime(tmp_path)
    again = rt2.tile(
        "pair", (16, 16), "f32", ((8, 128), (16, 256)), (8, 128),
        lambda c: 0.0,
    )
    assert again == (8, 128) and isinstance(again, tuple)


def test_no_cache_tunes_in_process_only(tmp_path):
    rt = _runtime(None)  # no persistent cache
    calls = []
    w = rt.tile("x", (32, 32), "f32", (1, 2), 1,
                lambda c: calls.append(c) or float(c))
    assert w == 1 and calls
    # same process: registry serves it, no re-measure
    calls.clear()
    w2 = rt.tile("x", (32, 32), "f32", (1, 2), 1,
                 lambda c: calls.append(c) or 0.0)
    assert w2 == 1 and calls == []


def test_stamp_payload_is_audit_complete(tmp_path):
    rt = _runtime(tmp_path)
    rt.tile("k2", (64, 64), "float32", (32, 64), 64,
            lambda c: {32: 2.0, 64: 1.0}[c])
    stamp_dir = os.path.join(str(tmp_path), "kcmc_plans")
    metas = [
        json.load(open(os.path.join(stamp_dir, f)))
        for f in os.listdir(stamp_dir)
    ]
    at = [m for m in metas if m.get("kind") == "autotune"]
    assert len(at) == 1
    assert at[0]["winner"] == 64
    assert set(at[0]["timings_ms"]) == {"32", "64"}


def test_single_candidate_skips_search():
    cache = PlanCache(None)
    calls = []
    w, outcome = autotune.autotune(
        "lone", (128,), 64, lambda c: calls.append(c) or 1.0, cache=cache
    )
    assert w == 128 and outcome == "default" and calls == []


def test_backend_tile_params_off_cpu():
    """Off-accelerator the backend resolves no tilings (the kernels it
    would tune only lower on TPU) — and the batch program builds with
    the defaults."""
    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig

    be = JaxBackend(CorrectorConfig(max_keypoints=64, n_hypotheses=32))
    assert be._tile_params((64, 64)) == {}

    d = np.random.default_rng(0).random((4, 64, 64)).astype(np.float32)
    ref = be.prepare_reference(d[0])
    out = be.process_batch(d, ref, np.arange(4, dtype=np.uint32))
    assert out["transform"].shape == (4, 3, 3)
