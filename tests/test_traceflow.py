"""Trace-contract analysis (`traceflow` + `donation` passes), the
incremental check cache, and the runtime retrace sentinel.

Layers mirror test_analysis.py's contract:

* known-bad fixture per rule id — `retrace`, `dtype-flow`, `transfer`,
  `bucket-escape`, `donation` each FIRE on a minimal snippet and stay
  quiet on the fixed variant;
* the repo itself stays clean against the baseline (test_analysis.py's
  integration test covers the new passes via default_passes);
* the cache replays content-hash-matched results (module-scoped and
  program-scoped) and a cache hit is measurably cheaper than cold;
* the retrace sentinel: the static `predict_compile_keys` ladder and
  the runtime compile observations cross-validate — a warmed corrector
  records zero post-warm-up compiles on covered programs, an escaping
  shape convicts.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from kcmc_tpu.analysis.core import Finding, ModuleIndex
from kcmc_tpu.analysis.donation import DonationPass
from kcmc_tpu.analysis.traceflow import TraceFlowPass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


def messages_of(findings):
    return [f.message for f in findings]


def tf(sources):
    return TraceFlowPass().run(ModuleIndex.from_sources(sources))


def don(sources):
    return DonationPass().run(ModuleIndex.from_sources(sources))


# -- retrace -----------------------------------------------------------------


def test_retrace_fires_on_branch_over_traced_value():
    fs = tf({"kcmc_tpu/ops/bad.py": """
import jax

@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""})
    assert any(
        "trace-time branch on a traced value" in m for m in messages_of(fs)
    ), fs


def test_retrace_fires_on_range_over_traced_value():
    fs = tf({"kcmc_tpu/ops/bad.py": """
import jax

@jax.jit
def f(x, n):
    for _ in range(n):
        x = x + 1
    return x
"""})
    assert any("range() over a traced value" in m for m in messages_of(fs))


def test_retrace_quiet_on_static_and_identity_tests():
    fs = tf({"kcmc_tpu/ops/ok.py": """
import jax
import jax.numpy as jnp

@jax.jit
def f(x, y):
    if x is None:
        return y
    if x.ndim == 3:
        x = x[0]
    H, W = x.shape
    if H % 8:
        x = x[: H - H % 8]
    return jnp.where(x > 0, x, y)
"""})
    assert [f for f in fs if f.rule == "retrace"] == []


def test_retrace_follows_cross_module_call_edges_with_arg_masks():
    """A branch on a TRACED argument two modules away fires; a branch
    on a static (config-derived) argument of the same callee stays
    quiet — the mask is per call site, not per function."""
    fs = tf({
        "kcmc_tpu/ops/entry.py": """
import jax
from kcmc_tpu.ops.helper import detect

@jax.jit
def entry(frame):
    return detect(frame, thresh=0.5)
""",
        "kcmc_tpu/ops/helper.py": """
def detect(frame, thresh=0.0):
    if thresh > 0:
        frame = frame + thresh
    if frame.mean() > 0:
        return frame
    return -frame
""",
    })
    retrace = [f for f in fs if f.rule == "retrace"]
    assert len(retrace) == 1, retrace
    assert retrace[0].path == "kcmc_tpu/ops/helper.py"
    assert retrace[0].line == 5  # the frame.mean() branch, not thresh


def test_retrace_fires_on_per_call_closure_capture():
    fs = tf({"kcmc_tpu/ops/bad.py": """
import time
import jax

def make():
    scale = time.time()

    @jax.jit
    def f(x):
        return x * scale
    return f
"""})
    assert any(
        "closure over a per-call host value" in m for m in messages_of(fs)
    )


def test_retrace_quiet_on_seeded_jax_random_closure():
    fs = tf({"kcmc_tpu/ops/ok.py": """
import jax

def make(seed):
    key = jax.random.key(seed)

    @jax.jit
    def f(x):
        return x * jax.random.uniform(key)
    return f
"""})
    assert [f for f in fs if f.rule == "retrace"] == []


def test_static_argnum_candidate_fires_and_declared_static_is_quiet():
    fs = tf({"kcmc_tpu/ops/bad.py": """
import functools
import jax

@jax.jit
def f(x, flag):
    if flag:
        return x * 2
    return x

@functools.partial(jax.jit, static_argnames=("flag",))
def g(x, flag):
    if flag:
        return x * 2
    return x
"""})
    cands = [m for m in messages_of(fs) if "static-argnum candidate" in m]
    assert len(cands) == 1 and "'flag' of jit-traced 'f'" in cands[0], fs


# -- dtype-flow --------------------------------------------------------------


def test_dtype_flow_fires_on_float64_inside_traced_code():
    fs = tf({"kcmc_tpu/ops/bad.py": """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return x.astype(jnp.float64)
"""})
    assert any("explicit float64" in m for m in messages_of(fs))


def test_dtype_flow_quiet_on_host_numpy_float64_constants():
    # the polish-window pattern: a float64 NUMPY constant built at
    # trace time and cast — never a device-wide dtype
    fs = tf({"kcmc_tpu/ops/ok.py": """
import jax
import jax.numpy as jnp
import numpy as _np

@jax.jit
def f(x):
    win = _np.arange(5, dtype=_np.float64)
    return x * jnp.asarray(win, jnp.float32)
"""})
    assert [f for f in fs if f.rule == "dtype-flow"] == []


def test_dtype_flow_fires_on_bf16_accumulation_without_acc_dtype():
    fs = tf({"kcmc_tpu/ops/bad.py": """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    b = x.astype(jnp.bfloat16)
    return jnp.sum(b)

@jax.jit
def g(x):
    b = x.astype(jnp.bfloat16)
    return jnp.matmul(b, b, preferred_element_type=jnp.float32)
"""})
    hits = [m for m in messages_of(fs) if "bf16 accumulation" in m]
    assert len(hits) == 1 and "'f'" in hits[0], fs


def test_dtype_flow_fires_on_widening_upload_cast_in_window():
    fs = tf({"kcmc_tpu/backends/jax_backend.py": """
import jax.numpy as jnp

class B:
    def process_batch_async(self, frames, ref, idx):
        fj = jnp.asarray(frames, jnp.float32)
        return self.fn(fj)
"""})
    assert any(
        "host-side widening cast before upload" in m for m in messages_of(fs)
    )


def test_dtype_flow_quiet_on_native_upload_then_device_cast():
    fs = tf({"kcmc_tpu/backends/jax_backend.py": """
import jax.numpy as jnp

class B:
    def process_batch_async(self, frames, ref, idx):
        fj = jnp.asarray(frames).astype(jnp.float32)
        return self.fn(fj)
"""})
    assert [f for f in fs if f.rule == "dtype-flow"] == []


# -- transfer ----------------------------------------------------------------

WINDOW_SRC = """
import numpy as np
import jax

class B:
    def process_batch_async(self, frames, ref, idx):
        out = self.fn(frames)
        {window_line}
        return out

    def prepare_reference(self, frame):
        return np.asarray(frame)  # setup scope: amortized, quiet
"""


def test_transfer_fires_inside_window_quiet_in_setup():
    fs = tf({
        "kcmc_tpu/backends/jax_backend.py": WINDOW_SRC.format(
            window_line="host = np.asarray(out)"
        )
    })
    hits = [f for f in fs if f.rule == "transfer"]
    assert len(hits) == 1, fs
    assert "process_batch_async" in hits[0].message
    assert "per frame" in hits[0].detail or "unknown" in hits[0].detail


def test_transfer_quiet_on_declared_async_copy():
    fs = tf({
        "kcmc_tpu/backends/jax_backend.py": WINDOW_SRC.format(
            window_line="out.copy_to_host_async()"
        )
    })
    assert [f for f in fs if f.rule == "transfer"] == []


def test_transfer_fires_on_tree_map_asarray():
    fs = tf({
        "kcmc_tpu/backends/jax_backend.py": WINDOW_SRC.format(
            window_line="host = jax.tree.map(np.asarray, out)"
        )
    })
    assert any("jax.tree.map(np.asarray" in m for m in messages_of(fs))


# -- bucket-escape -----------------------------------------------------------

ESCAPE_SRC = """
import jax
import jax.numpy as jnp

@jax.jit
def _metric(x):
    return x.mean()

class B:
    def process_batch_async(self, frames, ref, idx):
        out = self.fn(frames)
        {line}
        return out
"""


def test_bucket_escape_fires_on_unaccounted_jit_dispatch():
    fs = tf({
        "kcmc_tpu/backends/jax_backend.py": ESCAPE_SRC.format(
            line="m = _metric(out)"
        )
    })
    hits = [f for f in fs if f.rule == "bucket-escape"]
    assert len(hits) == 1 and hits[0].severity == "error", fs


def test_bucket_escape_quiet_under_plan_accounting():
    fs = tf({
        "kcmc_tpu/backends/jax_backend.py": ESCAPE_SRC.format(
            line="""with self._plan.maybe_timed("quality", (8, 8), "float32"):
            m = _metric(out)"""
        )
    })
    assert [f for f in fs if f.rule == "bucket-escape"] == []


def test_bucket_escape_quiet_when_routed_and_fallback_accounted():
    fs = tf({"kcmc_tpu/backends/jax_backend.py": """
import jax

@jax.jit
def _metric(x):
    return x.mean()

class B:
    def process_batch_async(self, frames, ref, idx):
        bucket = self._plan.route(frames.shape[1:])
        if bucket is None:
            self._plan.note_route("bucket_fallback")
        out = self.fn(frames)
        m = _metric(out)
        return out
"""})
    assert [f for f in fs if f.rule == "bucket-escape"] == []


# -- roofline-vocab ----------------------------------------------------------


def test_roofline_vocab_fires_on_unknown_program_literal():
    """A plan-routed program literal with no PROGRAM_VOCAB entry would
    silently escape the roofline cost model — the rule warns at the
    routing site."""
    fs = tf({"kcmc_tpu/plans/bad.py": """
def build(rt, fn):
    return rt.maybe_timed("mystery_warp", fn)
"""})
    hits = [f for f in fs if f.rule == "roofline-vocab"]
    assert len(hits) == 1, fs
    assert "mystery_warp" in hits[0].message
    assert hits[0].severity == "warning"


def test_roofline_vocab_quiet_on_known_and_variable_names():
    """Known vocabulary entries are quiet; a name threaded through a
    variable is not a literal routing site (covered elsewhere)."""
    fs = tf({"kcmc_tpu/plans/ok.py": """
def build(rt, fn, name):
    a = rt.maybe_timed("register", fn)
    b = rt.timed("quality", a)
    return rt.maybe_timed(name, b)
"""})
    assert [f for f in fs if f.rule == "roofline-vocab"] == []


def test_roofline_vocab_ignores_modules_outside_scope():
    fs = tf({"kcmc_tpu/io/elsewhere.py": """
def build(rt, fn):
    return rt.maybe_timed("mystery_warp", fn)
"""})
    assert [f for f in fs if f.rule == "roofline-vocab"] == []


# -- donation ----------------------------------------------------------------


def test_donation_candidate_fires_on_dying_same_shape_input():
    fs = don({"kcmc_tpu/ops/bad.py": """
import jax
import jax.numpy as jnp

@jax.jit
def scale(x, y):
    return jnp.where(x > 0, x * 2.0, y)

def run(data, keep):
    tmp = jnp.asarray(data)
    out = scale(tmp, keep)
    return out, keep
"""})
    msgs = messages_of(fs)
    assert any("double-allocates 'x'" in m for m in msgs), fs
    # `keep` is returned after the call: live, never a candidate
    assert not any("double-allocates 'y'" in m for m in msgs), fs


def test_donation_quiet_on_astype_and_on_donated_jits():
    fs = don({"kcmc_tpu/ops/ok.py": """
import jax
import jax.numpy as jnp

@jax.jit
def casty(x):
    return x.astype("uint16")

@jax.jit
def already(x):
    return x + 1.0
already_j = jax.jit(already, donate_argnums=(0,))

def run(d):
    t = jnp.asarray(d)
    a = casty(t)
    u = jnp.asarray(d)
    b = already(u)
    return a, b
"""})
    assert fs == [], fs


def test_donation_contract_fires_on_undonated_register_builder():
    fs = don({"kcmc_tpu/backends/jax_backend.py": """
import jax

class B:
    def _get_batch_fn(self, shape):
        fn = self._instrument_program(
            "register", shape, self._build_batch_fn(shape)
        )
        return fn

    def _build_batch_fn(self, shape):
        def local(frames):
            return frames
        return jax.jit(local)
"""})
    assert any(
        "frame program 'register' compiles without donate_argnums" in m
        for m in messages_of(fs)
    ), fs


def test_donation_contract_satisfied_by_conditional_donate_kwarg():
    fs = don({"kcmc_tpu/backends/jax_backend.py": """
import jax

class B:
    def _get_batch_fn(self, shape):
        return self._instrument_program(
            "register", shape, self._build_batch_fn(shape)
        )

    def _build_batch_fn(self, shape):
        def local(frames):
            return frames
        return jax.jit(local, donate_argnums=self._donate_argnums())
"""})
    assert [f for f in fs if "register" in f.message] == [], fs


# -- repo integration --------------------------------------------------------


def test_new_passes_run_in_default_suite():
    from kcmc_tpu.analysis.cli import default_passes

    names = {p.name for p in default_passes()}
    assert {"traceflow", "donation"} <= names


def test_repo_traceflow_findings_all_baselined():
    """The two new passes over the working tree: every finding must be
    covered by a justified baseline entry (same gate CI applies, but
    scoped so a failure names the offending pass)."""
    from kcmc_tpu.analysis.cli import default_baseline_path
    from kcmc_tpu.analysis.core import Baseline, run_passes

    index = ModuleIndex.from_package(REPO_ROOT)
    baseline = Baseline.load(default_baseline_path())
    result = run_passes(
        index, [TraceFlowPass(), DonationPass()], baseline
    )
    assert result.new == [], [f.format() for f in result.new]
    for e in baseline.entries:
        assert e.reason.strip(), f"unjustified baseline entry: {e}"


def test_sarif_rules_table_carries_new_rule_ids():
    from kcmc_tpu.analysis.core import CheckResult
    from kcmc_tpu.analysis.sarif import to_sarif

    f = Finding(
        rule="bucket-escape",
        path="kcmc_tpu/backends/jax_backend.py",
        line=3,
        severity="error",
        message="jitted '_metric' dispatched from the window",
    )
    log = to_sarif(
        CheckResult(
            findings=[f], new=[f], baselined=[], baseline_problems=[],
            passes=["traceflow"],
        )
    )
    rules = {
        r["id"]
        for r in log["runs"][0]["tool"]["driver"]["rules"]
    }
    assert {
        "retrace", "dtype-flow", "transfer", "bucket-escape", "donation"
    } <= rules
    assert log["runs"][0]["results"][0]["ruleId"] == "bucket-escape"
    # schema sanity when jsonschema is around (full validation lives in
    # test_analysis.py)
    try:
        import jsonschema  # noqa: F401
    except ImportError:
        pass


# -- incremental check cache -------------------------------------------------


class _CountingPass:
    """Program-scoped stub: counts run() invocations."""

    name = "counting"

    def __init__(self):
        self.runs = 0

    def run(self, index):
        self.runs += 1
        return [
            Finding(
                rule="counting", path=m.path, line=1,
                severity="warning", message=f"saw {m.path}",
            )
            for m in index
        ]


class _ModulePass(_CountingPass):
    """Module-scoped stub: records which module paths it analyzed."""

    name = "permodule"
    cache_scope = "module"

    def __init__(self):
        super().__init__()
        self.paths: list[str] = []

    def run(self, index):
        self.paths.extend(m.path for m in index)
        return super().run(index)


def _fake_repo(tmp_path, extra=""):
    pkg = tmp_path / "kcmc_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text("A = 1\n")
    (pkg / "b.py").write_text(f"B = 2\n{extra}")
    return str(tmp_path)


def test_cache_replays_program_scoped_results(tmp_path):
    from kcmc_tpu.analysis.cache import CheckCache
    from kcmc_tpu.analysis.core import run_passes

    root = _fake_repo(tmp_path)
    p = _CountingPass()
    idx = ModuleIndex.from_package(root)
    r1 = run_passes(idx, [p], cache=CheckCache(root))
    r2 = run_passes(idx, [p], cache=CheckCache(root))
    assert p.runs == 1  # second run replayed from cache
    assert [f.message for f in r1.findings] == [
        f.message for f in r2.findings
    ]
    # an edit invalidates: the pass runs again
    (tmp_path / "kcmc_tpu" / "b.py").write_text("B = 3\n")
    idx2 = ModuleIndex.from_package(root)
    run_passes(idx2, [p], cache=CheckCache(root))
    assert p.runs == 2


def test_cache_module_scope_reanalyzes_only_changed_modules(tmp_path):
    from kcmc_tpu.analysis.cache import CheckCache
    from kcmc_tpu.analysis.core import run_passes

    root = _fake_repo(tmp_path)
    p = _ModulePass()
    run_passes(
        ModuleIndex.from_package(root), [p], cache=CheckCache(root)
    )
    assert sorted(p.paths) == [
        "kcmc_tpu/__init__.py", "kcmc_tpu/a.py", "kcmc_tpu/b.py",
    ]
    p.paths.clear()
    (tmp_path / "kcmc_tpu" / "b.py").write_text("B = 4\n")
    r = run_passes(
        ModuleIndex.from_package(root), [p], cache=CheckCache(root)
    )
    assert p.paths == ["kcmc_tpu/b.py"]  # a.py replayed from cache
    assert {f.path for f in r.findings if f.rule == "counting"} == {
        "kcmc_tpu/__init__.py", "kcmc_tpu/a.py", "kcmc_tpu/b.py",
    }


def test_cache_hit_is_faster_than_cold_on_the_real_repo(tmp_path):
    """The headline contract: a repeat `kcmc check` replays instead of
    re-deriving. Cold runs the full nine-pass suite (seconds); the hit
    is file IO (tens of ms). Asserted at a conservative 3x."""
    import shutil

    from kcmc_tpu.analysis.cli import default_passes, run_check

    # isolate the cache: copy nothing, point the cache at a scratch
    # root by running against the real repo but a scratch cache dir
    cache_dir = os.path.join(REPO_ROOT, ".kcmc_check_cache")
    had = os.path.isdir(cache_dir)
    backup = None
    if had:
        backup = str(tmp_path / "cache_backup")
        shutil.move(cache_dir, backup)
    try:
        t0 = time.perf_counter()
        r1 = run_check(REPO_ROOT, passes=default_passes(), use_cache=True)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        r2 = run_check(REPO_ROOT, passes=default_passes(), use_cache=True)
        hit = time.perf_counter() - t0
        assert len(r1.findings) == len(r2.findings)
        assert hit * 3 < cold, (cold, hit)
    finally:
        if os.path.isdir(cache_dir):
            shutil.rmtree(cache_dir)
        if backup is not None:
            shutil.move(backup, cache_dir)


def test_cache_ignores_corrupt_files(tmp_path):
    from kcmc_tpu.analysis.cache import CheckCache
    from kcmc_tpu.analysis.core import run_passes

    root = _fake_repo(tmp_path)
    cache_dir = tmp_path / ".kcmc_check_cache"
    cache_dir.mkdir()
    (cache_dir / "results.json").write_text("{not json")
    p = _CountingPass()
    r = run_passes(
        ModuleIndex.from_package(root), [p], cache=CheckCache(root)
    )
    assert p.runs == 1 and len(r.findings) == 3


# -- retrace sentinel (unit: no jax) ----------------------------------------


def test_sentinel_convicts_covered_compile_after_arm():
    from kcmc_tpu.analysis import sanitize

    with sanitize.retrace_sentinel(
        covered=("register",), predicted={("register", (64, 64), "float32")}
    ):
        # warm-up builds never convict
        sanitize.note_compile(
            "register", (64, 64), "float32", during_build=True
        )
        # uncovered programs never convict
        sanitize.note_compile("quality", (50, 70), "float32")
        assert sanitize.take_violations() == []
        sanitize.note_compile("register", (80, 80), "float32")
    v = sanitize.take_violations()
    assert len(v) == 1 and "escaped the plan_buckets ladder" in v[0], v
    assert sanitize.take_violations() == []  # drained


def test_sentinel_disarmed_is_free():
    from kcmc_tpu.analysis import sanitize

    sanitize.note_compile("register", (64, 64), "float32")
    assert sanitize.take_violations() == []
    assert sanitize.sentinel_stats() == {"armed": False}


def test_predict_compile_keys_matches_ladder():
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.plans.runtime import predict_compile_keys

    cfg = CorrectorConfig(plan_buckets=(64, (96, 128)))
    keys = predict_compile_keys(cfg, dtypes=("float32", "uint16"))
    assert ("register", (64, 64), "uint16") in keys
    assert ("register", (96, 128), "float32") in keys
    assert ("reference", (64, 64), "float32") in keys
    # reference/apply warm float32 only — uint16 batches cast on device
    assert ("reference", (64, 64), "uint16") not in keys
    assert ("apply", (96, 128), "float32") in keys


# -- retrace sentinel (integration: warmed corrector) ------------------------


@pytest.mark.slow
def test_warmed_corrector_records_zero_postwarmup_compiles():
    """The acceptance contract: static prediction == runtime
    observation. A warmed corrector serving in-bucket traffic compiles
    NOTHING after warm-up; an out-of-ladder shape convicts. Runs in
    the CI sanitize job (which takes tests/test_sanitize.py and this
    module without the tier-1 slow filter)."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.analysis import sanitize
    from kcmc_tpu.plans.runtime import predict_compile_keys

    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=8,
        max_keypoints=64, n_hypotheses=32, plan_buckets=(64,),
    )
    mc.warmup()
    plan = mc.backend._plan
    pred = predict_compile_keys(mc.config)
    seen = {
        (p, s, dt)
        for (p, s, dt, _rung) in plan.compile_counts
        if p in ("reference", "register", "apply")
    }
    assert seen == pred, (seen, pred)  # ladder == observation, exactly

    rng = np.random.default_rng(0)
    stack = (rng.random((16, 64, 64)) * 1000).astype(np.float32)
    with sanitize.retrace_sentinel(predicted=pred, label="warmed"):
        mc.correct(stack)
    assert sanitize.take_violations() == []

    off = (rng.random((16, 80, 80)) * 1000).astype(np.float32)
    with sanitize.retrace_sentinel(predicted=pred, label="warmed"):
        mc.correct(off)
    v = sanitize.take_violations()
    assert v and all("escaped the plan_buckets ladder" in m for m in v), v


# -- donation runtime guard --------------------------------------------------


@pytest.mark.slow
def test_register_donation_preserves_caller_device_arrays():
    """The donating register program must never invalidate a
    caller-owned device array (the defensive-copy guard), and donating
    vs non-donating configs agree bitwise."""
    import jax.numpy as jnp

    from kcmc_tpu import MotionCorrector

    rng = np.random.default_rng(1)
    stack = (rng.random((8, 48, 48)) * 1000).astype(np.float32)
    kw = dict(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=32, n_hypotheses=16,
    )
    mc_d = MotionCorrector(**kw)
    mc_n = MotionCorrector(donate_buffers=False, **kw)
    ref = mc_d.backend.prepare_reference(stack[0])
    idx = np.arange(4, dtype=np.uint32)

    dev = jnp.asarray(stack[:4])
    out_d = mc_d.backend.process_batch(dev, ref, idx)
    np.asarray(dev)  # raises if the guard failed and dev was donated

    ref_n = mc_n.backend.prepare_reference(stack[0])
    out_n = mc_n.backend.process_batch(stack[:4], ref_n, idx)
    np.testing.assert_allclose(
        out_d["transform"], out_n["transform"], atol=1e-5
    )


def test_retrace_respects_static_argnums_integers():
    fs = tf({"kcmc_tpu/ops/ok.py": """
import functools
import jax

@functools.partial(jax.jit, static_argnums=(1,))
def f(x, n):
    if n:
        return x[:n]
    return x
"""})
    assert [f for f in fs if f.rule == "retrace"] == [], fs


def test_dtype_flow_quiet_on_np_float64_scalar_constructor():
    fs = tf({"kcmc_tpu/ops/ok.py": """
import jax
import numpy as np

@jax.jit
def f(x):
    scale = np.float64(0.5)
    return x * float(scale)
"""})
    assert [f for f in fs if f.rule == "dtype-flow"] == [], fs


def test_donation_quiet_when_buffer_read_earlier_in_a_loop():
    """A read at a LOWER line than the call, inside the same loop, is a
    next-iteration read — never a donation candidate."""
    fs = don({"kcmc_tpu/ops/ok.py": """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x + 1.0

def run(data, k):
    buf = jnp.asarray(data)
    total = 0.0
    for _ in range(k):
        total = total + buf.sum()
        out = step(buf)
    return out, total
"""})
    assert fs == [], fs
