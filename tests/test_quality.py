"""Template-correlation quality metrics (diagnostics["template_corr"])."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils.synthetic import make_drift_stack


@pytest.mark.parametrize("backend", ["jax", "numpy"])
def test_template_corr_reported_and_high_when_registered(backend):
    data = make_drift_stack(n_frames=6, shape=(128, 128), model="translation", seed=0)
    mc = MotionCorrector(
        model="translation", backend=backend, quality_metrics=True
    )
    res = mc.correct(data.stack)
    corr = np.asarray(res.diagnostics["template_corr"])
    assert corr.shape == (6,)
    # registered frames must correlate strongly with the reference
    assert corr.min() > 0.8
    # and the metric is genuinely informative: raw drifted frames less so
    from kcmc_tpu.backends.numpy_backend import template_corr_np

    raw = template_corr_np(
        np.asarray(data.stack[1:], np.float32),
        np.asarray(data.stack[0], np.float32),
    )
    assert corr[1:].mean() > raw.mean()


def test_template_corr_absent_by_default():
    data = make_drift_stack(n_frames=4, shape=(96, 96), model="translation", seed=0)
    res = MotionCorrector(model="translation").correct(data.stack)
    assert "template_corr" not in res.diagnostics


@pytest.mark.parametrize("backend", ["jax", "numpy"])
def test_template_corr_offset_background_invariance(backend):
    """Masked correlation: exact registration on offset-background data
    scores ~1.0 even for large drifts (the old full-frame metric read
    the warp's out-of-coverage zeros against the offset background and
    sank with drift size)."""
    data = make_drift_stack(
        n_frames=6, shape=(128, 128), model="translation",
        max_drift=25.0, seed=4,
    )
    stack = np.asarray(data.stack, np.float32) + 500.0  # background offset
    mc = MotionCorrector(
        model="translation", backend=backend, quality_metrics=True,
    )
    res = mc.correct(stack)
    corr = np.asarray(res.diagnostics["template_corr"])
    cov = np.asarray(res.diagnostics["coverage"])
    assert corr.shape == (6,) and cov.shape == (6,)
    # Coverage below 1 for the drifted frames proves the mask is real...
    assert cov[1:].max() < 1.0
    assert cov.min() > 0.5
    # ...and the in-coverage correlation stays high regardless of drift.
    assert corr.min() > 0.9


def test_template_corr_piecewise_and_homography_masks():
    """The mask derivation covers every model family's output form
    (field for piecewise, 3x3 matrix for homography)."""
    from kcmc_tpu.utils.synthetic import make_piecewise_stack

    data = make_piecewise_stack(n_frames=3, shape=(128, 128), seed=2)
    res = MotionCorrector(
        model="piecewise", quality_metrics=True, batch_size=3
    ).correct(data.stack)
    corr = np.asarray(res.diagnostics["template_corr"])
    assert corr.shape == (3,) and corr.min() > 0.7

    data = make_drift_stack(
        n_frames=3, shape=(128, 128), model="homography", seed=2
    )
    res = MotionCorrector(
        model="homography", quality_metrics=True, batch_size=3
    ).correct(data.stack)
    corr = np.asarray(res.diagnostics["template_corr"])
    assert corr.shape == (3,) and corr.min() > 0.7


def test_crispness_improves_after_correction():
    """Crispness of the mean image — the standard stack-level
    correction-quality score — must rise after registration (residual
    motion blurs the temporal mean), be scale-invariant, and accept 3D
    stacks."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils import synthetic
    from kcmc_tpu.utils.metrics import crispness

    data = synthetic.make_drift_stack(
        n_frames=10, shape=(128, 128), model="translation", max_drift=8.0,
        seed=4,
    )
    before = crispness(data.stack)
    res = MotionCorrector(model="translation", backend="jax", batch_size=5).correct(
        data.stack
    )
    after = crispness(res.corrected)
    assert after > before * 1.1, f"crispness {before:.3f} -> {after:.3f}"
    # scale invariance: same stack, 1000x intensity
    np.testing.assert_allclose(
        crispness(data.stack * 1000.0), before, rtol=1e-4
    )
    # 3D stacks accepted, including degenerate single-plane volumes
    d3 = synthetic.make_drift_stack_3d(n_frames=3, shape=(8, 48, 48), seed=2)
    assert crispness(d3.stack) > 0.0
    assert crispness(d3.stack[:, :1]) > 0.0
    # a bare mean image is ambiguous by shape: rejected explicitly
    import pytest

    with pytest.raises(ValueError, match="stack"):
        crispness(np.zeros((64, 64), np.float32))
