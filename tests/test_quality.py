"""Template-correlation quality metrics (diagnostics["template_corr"])."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils.synthetic import make_drift_stack


@pytest.mark.parametrize("backend", ["jax", "numpy"])
def test_template_corr_reported_and_high_when_registered(backend):
    data = make_drift_stack(n_frames=6, shape=(128, 128), model="translation", seed=0)
    mc = MotionCorrector(
        model="translation", backend=backend, quality_metrics=True
    )
    res = mc.correct(data.stack)
    corr = np.asarray(res.diagnostics["template_corr"])
    assert corr.shape == (6,)
    # registered frames must correlate strongly with the reference
    assert corr.min() > 0.8
    # and the metric is genuinely informative: raw drifted frames less so
    from kcmc_tpu.backends.numpy_backend import template_corr_np

    raw = template_corr_np(
        np.asarray(data.stack[1:], np.float32),
        np.asarray(data.stack[0], np.float32),
    )
    assert corr[1:].mean() > raw.mean()


def test_template_corr_absent_by_default():
    data = make_drift_stack(n_frames=4, shape=(96, 96), model="translation", seed=0)
    res = MotionCorrector(model="translation").correct(data.stack)
    assert "template_corr" not in res.diagnostics
