"""Exact-warp rescue: frames whose motion exceeds a gather-free
kernel's static bound must be re-resampled exactly, not returned as
zeros. Exercised via the Pallas translation warp's +-128 px bound
(interpret mode piggybacks on warp='pallas' off-TPU)."""

import numpy as np

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic


def _big_shift_stack(shifts):
    rng = np.random.default_rng(5)
    scene = synthetic.render_scene(rng, (256, 256), n_blobs=220)
    mats = np.tile(np.eye(3, dtype=np.float32), (len(shifts), 1, 1))
    mats[:, :2, 2] = shifts
    stack = np.stack(
        [synthetic._warp_scene(scene, m) for m in mats]
    ).astype(np.float32)
    return stack, mats


class _FlagEveryOtherBackend:
    """Wraps the jax backend, forcing warp_ok False on odd frames so the
    rescue path is exercised deterministically on any platform."""

    def __init__(self, inner):
        self.inner = inner
        self.config = inner.config

    def prepare_reference(self, ref):
        return self.inner.prepare_reference(ref)

    def process_batch(self, frames, ref, idx):
        out = self.inner.process_batch(frames, ref, idx)
        ok = np.asarray(out["warp_ok"], bool).copy()
        ok[1::2] = False
        corrected = np.array(out["corrected"])
        corrected[1::2] = 0.0
        out["warp_ok"] = ok
        out["corrected"] = corrected
        return out

    def rescue_warp(self, frames, out):
        return self.inner.rescue_warp(frames, out)


def test_flagged_frames_are_rescued_exactly():
    shifts = np.array(
        [[0, 0], [10.5, -7.2], [30.1, 22.4], [55.0, -41.3]], np.float32
    )
    stack, mats = _big_shift_stack(shifts)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    mc.backend = _FlagEveryOtherBackend(mc.backend)
    res = mc.correct(stack)
    assert np.asarray(res.diagnostics["warp_rescued"])[1::2].all()
    assert np.asarray(res.diagnostics["warp_ok"]).all()
    # Rescued frames must align with the reference frame, not be zeros.
    interior = np.s_[64:-64, 64:-64]
    for t in (1, 3):
        err = np.abs(res.corrected[t][interior] - stack[0][interior])
        assert np.median(err) < 0.05


def test_rescue_disabled_keeps_zeroed_frames():
    shifts = np.array([[0, 0], [20.0, 10.0]], np.float32)
    stack, _ = _big_shift_stack(shifts)
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=2, rescue_warp=False
    )
    mc.backend = _FlagEveryOtherBackend(mc.backend)
    res = mc.correct(stack)
    assert not np.asarray(res.diagnostics["warp_ok"])[1]
    assert np.abs(res.corrected[1]).max() == 0.0


def test_rescue_noop_when_all_ok():
    data = synthetic.make_drift_stack(
        n_frames=6, shape=(96, 96), model="translation", seed=0
    )
    res = MotionCorrector(model="translation", batch_size=4).correct(data.stack)
    assert "warp_rescued" in res.diagnostics
    assert not np.asarray(res.diagnostics["warp_rescued"]).any()
