"""Guards on bench.py — the judged artifact the driver runs every round.

A syntax error or a drifted JSON schema in bench.py would silently cost
the round's benchmark record, so the contract is asserted here.
"""

import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def test_bench_module_compiles_and_has_cli():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    for flag in ("--frames", "--size", "--model", "--batch", "--all"):
        assert flag in out.stdout


def test_judged_json_line_parses():
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    line = bench.judged_json_line("translation", 512, 3210.4)
    rec = json.loads(line)
    assert rec["metric"] == "registration_throughput_translation_512x512"
    assert rec["value"] == 3210.4
    assert rec["unit"] == "frames/sec/chip"
    assert rec["vs_baseline"] == round(3210.4 / 200.0, 3)
    assert "\n" not in line


def test_judged_json_line_carries_variance_payload():
    """VERDICT r2 #7: the artifact must record every sweep time and the
    --all per-config rows, so round-over-round drift is attributable to
    noise vs regression instead of a single best-of-three number."""
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    sweeps = [3101.2, 2980.5, 3055.9]
    configs = {
        "affine": {"fps": 1745.0, "rmse_px": 0.051, "sweeps_fps": [1745.0, 1700.1, 1688.8]},
    }
    line = bench.judged_json_line(
        "translation", 512, max(sweeps), sweeps_fps=sweeps, configs=configs
    )
    assert "\n" not in line
    rec = json.loads(line)
    # Contract keys unchanged...
    assert rec["value"] == max(sweeps)
    assert rec["unit"] == "frames/sec/chip"
    # ...variance payload present and parseable.
    assert rec["sweeps_fps"] == sweeps
    assert rec["configs"]["affine"]["fps"] == 1745.0
    assert rec["configs"]["affine"]["sweeps_fps"][1] == 1700.1


def test_bench_cli_has_multichip_flags():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "--multichip" in out.stdout
    assert "--devices" in out.stdout


def test_multichip_judged_json_line_contract():
    """The --multichip judged line: one parseable JSON line carrying
    per-config 1-chip/mesh fps and the scaling efficiency vs 1 chip."""
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    configs = {
        "translation": {
            "fps_1chip": 4000.0, "fps_mesh": 28000.0,
            "efficiency": 0.875, "rmse_px": 0.013,
            "sweeps_fps": [28000.0, 27500.0, 28100.0],
        },
        "homography": {
            "fps_1chip": 1360.0, "fps_mesh": 9100.0,
            "efficiency": 0.836, "rmse_px": 0.026, "sweeps_fps": None,
        },
    }
    line = bench.multichip_judged_json_line(512, 8, configs)
    assert "\n" not in line
    rec = json.loads(line)
    assert rec["metric"] == "multichip_scaling_translation_512x512"
    assert rec["value"] == 28000.0
    assert rec["unit"] == "frames/sec/mesh"
    assert rec["n_devices"] == 8
    # vs_baseline keeps per-chip semantics: value / (200 * n_devices)
    assert rec["vs_baseline"] == round(28000.0 / (200.0 * 8), 3)
    assert rec["efficiency"] == 0.875
    assert rec["configs"]["homography"]["efficiency"] == 0.836


def test_scaling_row_efficiency_math():
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    row = bench._scaling_row(
        {"fps": 100.0, "rmse_px": 0.05, "sweeps_fps": [100.0]},
        {"fps": 640.0, "rmse_px": 0.05, "sweeps_fps": [640.0]},
        8,
    )
    assert row["efficiency"] == 0.8
    assert row["fps_1chip"] == 100.0 and row["fps_mesh"] == 640.0


def test_bench_cli_has_coldstart_flags():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "--coldstart" in out.stdout
    assert "--plans" in out.stdout


def test_coldstart_judged_json_line_contract():
    """The --coldstart judged line: one parseable JSON line with the
    warm first-frame latency as the value, per-config cold/warm/speedup
    rows, and vs_baseline = best speedup / 5 (the >=5x warm target)."""
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    rows = {
        "translation": {
            "cold_s": 130.0, "warm_s": 10.0, "speedup": 13.0,
            "compile_s_cold": 120.0, "compile_s_warm": 2.0,
            "run1_stamp_misses": 2, "run2_stamp_misses": 0,
            "run2_stamp_hits": 2,
        },
        "piecewise": {
            "cold_s": 28.5, "warm_s": 3.8, "speedup": 7.5,
            "compile_s_cold": 24.6, "compile_s_warm": 1.3,
            "run1_stamp_misses": 2, "run2_stamp_misses": 0,
            "run2_stamp_hits": 2,
        },
    }
    line = bench.coldstart_judged_json_line("translation", 512, rows)
    assert "\n" not in line
    rec = json.loads(line)
    assert rec["metric"] == "coldstart_first_frame_translation_512x512"
    assert rec["value"] == 10.0
    assert rec["unit"] == "seconds"
    assert rec["speedup"] == 13.0
    assert rec["vs_baseline"] == round(13.0 / 5.0, 3)
    assert rec["configs"]["piecewise"]["run2_stamp_misses"] == 0


def test_bench_cli_has_hostfed_flags():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "--hostfed" in out.stdout
    assert "--io-workers" in out.stdout


def test_hostfed_judged_json_line_contract():
    """The --hostfed judged line: one parseable JSON line with host-fed
    streaming fps as the value, the device-resident ratio, the
    GIL-bound-fallback single-vs-pooled speedup, and byte identity."""
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    rows = {
        "device": {"fps": 4000.0, "rmse_px": 0.013},
        "hostfed": {
            "fps": 3600.0, "ingest_fps": 5200.0, "rmse_px": 0.013,
            "stall_fractions": {"prefetch_wait": 0.02},
            "feeder": {"mode": "process", "workers": 8},
        },
        "pyfallback_single": {
            "fps": 230.0, "ingest_fps": 233.0, "rmse_px": 0.013,
            "stall_fractions": {"prefetch_wait": 0.9}, "feeder": None,
        },
        "pyfallback_pooled": {
            "fps": 1500.0, "ingest_fps": 1700.0, "rmse_px": 0.013,
            "stall_fractions": {"prefetch_wait": 0.2},
            "feeder": {"mode": "process", "workers": 8},
        },
        "byte_identical": True,
        "speedup_vs_single": 6.522,
        "ingest_speedup_vs_single": 7.296,
        "pool": {"workers": 8, "mesh_devices": 0},
    }
    line = bench.hostfed_judged_json_line(512, rows)
    assert "\n" not in line
    rec = json.loads(line)
    assert rec["metric"] == "hostfed_streaming_translation_512x512"
    assert rec["value"] == 3600.0
    assert rec["unit"] == "frames/sec"
    assert rec["vs_baseline"] == round(3600.0 / 200.0, 3)
    assert rec["hostfed_vs_device"] == 0.9
    assert rec["speedup_vs_single"] == 6.522
    assert rec["byte_identical"] is True
    assert rec["configs"]["pyfallback_pooled"]["feeder"]["workers"] == 8
    assert "byte_identical" not in rec["configs"]


def test_bench_cli_has_regress_flags():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "--regress" in out.stdout
    assert "--against" in out.stdout


def test_regress_gate_passes_and_fails_on_doctored_reference(
    tmp_path, monkeypatch, capsys
):
    """The regression gate's pass/fail logic, without real compute: a
    stubbed run beats the reference (exit 0), then the reference is
    doctored so the same numbers read as a >5% fps regression and as an
    rmse regression (exit 1, failures named)."""
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    monkeypatch.setattr(
        bench, "run_bench_device",
        lambda frames, size, model, batch, **kw: {
            "fps": 100.0, "rmse_px": 0.10, "n_frames": frames,
            "seconds": 1.0, "sweeps_fps": [100.0],
        },
    )
    ref = tmp_path / "ref.json"
    ref.write_text(json.dumps({"configs": {
        "translation": {"fps": 98.0, "rmse_px": 0.10},
        "homography": {"fps": 50.0, "rmse_px": 0.2},
        "piecewise": {"fps": 100.0, "rmse_px": 0.102},
    }}))
    rc = bench.run_bench_regress(str(ref), True, 64, 64, 16)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and rec["value"] == 1 and rec["failures"] == []

    ref.write_text(json.dumps({"configs": {
        "translation": {"fps": 120.0, "rmse_px": 0.10},   # fps regression
        "homography": {"fps": 50.0, "rmse_px": 0.08},     # rmse regression
        "piecewise": {"fps": 100.0, "rmse_px": 0.10},     # exactly on ref: ok
    }}))
    rc = bench.run_bench_regress(str(ref), True, 64, 64, 16)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and rec["value"] == 0
    assert any("translation: fps" in f for f in rec["failures"])
    assert any("homography: rmse" in f for f in rec["failures"])
    assert len(rec["failures"]) == 2, rec["failures"]
