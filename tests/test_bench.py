"""Guards on bench.py — the judged artifact the driver runs every round.

A syntax error or a drifted JSON schema in bench.py would silently cost
the round's benchmark record, so the contract is asserted here.
"""

import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def test_bench_module_compiles_and_has_cli():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    for flag in ("--frames", "--size", "--model", "--batch", "--all"):
        assert flag in out.stdout


def test_judged_json_line_parses():
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    line = bench.judged_json_line("translation", 512, 3210.4)
    rec = json.loads(line)
    assert rec["metric"] == "registration_throughput_translation_512x512"
    assert rec["value"] == 3210.4
    assert rec["unit"] == "frames/sec/chip"
    assert rec["vs_baseline"] == round(3210.4 / 200.0, 3)
    assert "\n" not in line


def test_judged_json_line_carries_variance_payload():
    """VERDICT r2 #7: the artifact must record every sweep time and the
    --all per-config rows, so round-over-round drift is attributable to
    noise vs regression instead of a single best-of-three number."""
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    sweeps = [3101.2, 2980.5, 3055.9]
    configs = {
        "affine": {"fps": 1745.0, "rmse_px": 0.051, "sweeps_fps": [1745.0, 1700.1, 1688.8]},
    }
    line = bench.judged_json_line(
        "translation", 512, max(sweeps), sweeps_fps=sweeps, configs=configs
    )
    assert "\n" not in line
    rec = json.loads(line)
    # Contract keys unchanged...
    assert rec["value"] == max(sweeps)
    assert rec["unit"] == "frames/sec/chip"
    # ...variance payload present and parseable.
    assert rec["sweeps_fps"] == sweeps
    assert rec["configs"]["affine"]["fps"] == 1745.0
    assert rec["configs"]["affine"]["sweeps_fps"][1] == 1700.1
