"""Guards on bench.py — the judged artifact the driver runs every round.

A syntax error or a drifted JSON schema in bench.py would silently cost
the round's benchmark record, so the contract is asserted here.
"""

import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def test_bench_module_compiles_and_has_cli():
    out = subprocess.run(
        [sys.executable, _BENCH, "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    for flag in ("--frames", "--size", "--model", "--batch", "--all"):
        assert flag in out.stdout


def test_judged_json_line_parses():
    sys.path.insert(0, os.path.dirname(_BENCH))
    import bench

    line = bench.judged_json_line("translation", 512, 3210.4)
    rec = json.loads(line)
    assert rec["metric"] == "registration_throughput_translation_512x512"
    assert rec["value"] == 3210.4
    assert rec["unit"] == "frames/sec/chip"
    assert rec["vs_baseline"] == round(3210.4 / 200.0, 3)
    assert "\n" not in line
