"""ResumableCorrector: chunked checkpoint/resume correctness."""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.checkpoint import ResumableCorrector


@pytest.fixture(scope="module")
def data():
    return synthetic.make_drift_stack(
        n_frames=10, shape=(128, 128), model="translation", max_drift=6.0, seed=41
    )


def test_resume_matches_direct(tmp_path, data):
    """Chunked+checkpointed processing must equal one-shot processing."""
    mc = MotionCorrector(model="translation", backend="jax", batch_size=5)
    direct = mc.correct(data.stack)

    rc = ResumableCorrector(
        MotionCorrector(model="translation", backend="jax", batch_size=5),
        str(tmp_path / "run.ckpt.npz"),
        chunk_frames=4,
    )
    resumed = rc.correct(data.stack)
    np.testing.assert_allclose(resumed.transforms, direct.transforms, atol=1e-5)
    np.testing.assert_allclose(resumed.corrected, direct.corrected, atol=1e-4)


def test_resume_restores_from_checkpoint(tmp_path, data):
    """A partial run's checkpoint must be picked up, not recomputed."""
    path = str(tmp_path / "run2.ckpt.npz")
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    rc = ResumableCorrector(mc, path, chunk_frames=4)

    # Simulate an interrupted run: process only the first chunk by
    # running on a truncated stack... then full stack resumes.
    class Boom(RuntimeError):
        pass

    orig = mc.correct
    calls = {"n": 0}

    def bombing_correct(stack, **kw):
        if calls["n"] >= 1:
            raise Boom()
        calls["n"] += 1
        return orig(stack, **kw)

    mc.correct = bombing_correct
    with pytest.raises(Boom):
        rc.correct(data.stack)
    mc.correct = orig

    res = rc.correct(data.stack)
    assert res.timing["restored_frames"] == 4  # first chunk came from disk
    direct = MotionCorrector(model="translation", backend="jax", batch_size=4).correct(
        data.stack
    )
    np.testing.assert_allclose(res.transforms, direct.transforms, atol=1e-5)


def test_stale_checkpoint_is_discarded(tmp_path, data):
    path = str(tmp_path / "run3.ckpt.npz")
    rc1 = ResumableCorrector(
        MotionCorrector(model="translation", backend="jax", batch_size=4),
        path,
        chunk_frames=4,
    )
    rc1.correct(data.stack)
    # different config => checkpoint invalid => full recompute, same result
    rc2 = ResumableCorrector(
        MotionCorrector(model="translation", backend="jax", batch_size=4, n_hypotheses=64),
        path,
        chunk_frames=4,
    )
    res = rc2.correct(data.stack)
    assert res.timing["restored_frames"] == 0
    assert res.transforms.shape == (10, 3, 3)


def test_resume_manager_rejects_rolling_templates(tmp_path):
    """ResumableCorrector restarts each chunk from the initial template,
    so rolling updates would silently diverge from a one-shot run — the
    constructor must refuse and point at correct_file(checkpoint=)."""
    import pytest

    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.checkpoint import ResumableCorrector

    mc = MotionCorrector(
        model="translation", backend="jax", template_update_every=8
    )
    with pytest.raises(ValueError, match="template_update_every"):
        ResumableCorrector(mc, str(tmp_path / "c.npz"))
