"""Test configuration: force an 8-device virtual CPU mesh.

Tests never require real TPU hardware; multi-chip sharding is exercised
on fake CPU devices per SURVEY.md §4. Must run before `jax` is first
imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# NOTE: do NOT enable JAX_COMPILATION_CACHE_DIR here — the persistent
# compilation cache hangs indefinitely in this image when armed at
# import time via the env var against the axon TPU tunnel (verified in
# round 3; enabling it AFTER import on the CPU backend works — that is
# what kcmc_tpu/plans/cache.enable_compile_cache does, and the plans
# tests that need it opt in against a tmpdir and disable it after).
# KCMC_COMPILE_CACHE is popped too so an operator's ambient cache dir
# never leaks compile-cache state into the suite.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
os.environ.pop("KCMC_COMPILE_CACHE", None)

# The image's TPU-tunnel plugin ("axon", registered by sitecustomize)
# force-sets jax_platforms="axon,cpu" via jax.config, which overrides the
# env var above and makes every backend init dial the (single-tenant) TPU
# tunnel — hanging tests whenever the chip is busy or wedged. Tests are
# CPU-only by design (SURVEY.md §4), so pin the config back.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _isolate_cli_logging():
    """Keep `advise()` routing order-independent across tests.

    Any test that drives `__main__.main()` in-process flips the module
    to logger routing with a handler bound to pytest's captured stderr;
    without this reset, later `pytest.warns` contracts fail and the
    handler writes to a closed stream.
    """
    yield
    from kcmc_tpu.obs import log as obs_log

    if obs_log.cli_logging_active():
        obs_log.reset_cli_logging()
