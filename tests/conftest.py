"""Test configuration: force an 8-device virtual CPU mesh.

Tests never require real TPU hardware; multi-chip sharding is exercised
on fake CPU devices per SURVEY.md §4. Must run before `jax` is first
imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# NOTE: do NOT enable JAX_COMPILATION_CACHE_DIR here — the persistent
# compilation cache hangs indefinitely in this image when armed at
# import time via the env var against the axon TPU tunnel (verified in
# round 3; enabling it AFTER import on the CPU backend works — that is
# what kcmc_tpu/plans/cache.enable_compile_cache does, and the plans
# tests that need it opt in against a tmpdir and disable it after).
# KCMC_COMPILE_CACHE is popped too so an operator's ambient cache dir
# never leaks compile-cache state into the suite.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
os.environ.pop("KCMC_COMPILE_CACHE", None)

# The image's TPU-tunnel plugin ("axon", registered by sitecustomize)
# force-sets jax_platforms="axon,cpu" via jax.config, which overrides the
# env var above and makes every backend init dial the (single-tenant) TPU
# tunnel — hanging tests whenever the chip is busy or wedged. Tests are
# CPU-only by design (SURVEY.md §4), so pin the config back.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--sanitize",
        action="store_true",
        default=False,
        help="run under the kcmc runtime concurrency sanitizer: "
        "instrumented locks validated against the static lock-order "
        "graph, a deadlock watchdog, and a per-test leak check "
        "(threads/sockets/telemetry claims); also via KCMC_SANITIZE=1 "
        "(docs/ANALYSIS.md)",
    )


def pytest_configure(config):
    from kcmc_tpu.analysis import sanitize

    # env first: `kcmc sanitize --strict --watchdog 3 pytest …` carries
    # its options through KCMC_SANITIZE_*; a bare --sanitize falls back
    # to defaults (enable is idempotent, so the order is safe)
    if not sanitize.maybe_enable_from_env() and config.getoption(
        "--sanitize"
    ):
        sanitize.enable()


@pytest.fixture(autouse=True)
def _sanitize_guard(request):
    """Per-test sanitizer gate (no-op unless --sanitize/KCMC_SANITIZE):
    any lock-order violation or deadlock suspect recorded during the
    test, and any leaked thread/socket/telemetry-path-claim still live
    after it, fails the test that caused it."""
    from kcmc_tpu.analysis import sanitize

    if not sanitize.active():
        yield
        return
    sanitize.take_violations()  # a prior test's report must not bleed in
    before = sanitize.leak_snapshot()
    yield
    problems = sanitize.take_violations() + sanitize.check_leaks(before)
    if problems:
        pytest.fail(
            "sanitizer caught:\n" + "\n".join(f"- {p}" for p in problems),
            pytrace=False,
        )


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _isolate_cli_logging():
    """Keep `advise()` routing order-independent across tests.

    Any test that drives `__main__.main()` in-process flips the module
    to logger routing with a handler bound to pytest's captured stderr;
    without this reset, later `pytest.warns` contracts fail and the
    handler writes to a closed stream.
    """
    yield
    from kcmc_tpu.obs import log as obs_log

    if obs_log.cli_logging_active():
        obs_log.reset_cli_logging()
