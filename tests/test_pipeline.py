"""End-to-end pipeline tests: the judged workloads at reduced scale.

Config 1 (translation drift) is the minimum end-to-end slice from
SURVEY.md §7: synthetic drift stack -> full pipeline -> recovered
transforms within sub-pixel RMSE of ground truth.
"""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse


SHAPE = (160, 160)


@pytest.fixture(scope="module")
def translation_data():
    return synthetic.make_drift_stack(
        n_frames=12, shape=SHAPE, model="translation", max_drift=8.0, seed=11
    )


def test_translation_drift_recovery(translation_data):
    data = translation_data
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    assert res.corrected.shape == data.stack.shape
    assert res.transforms.shape == (12, 3, 3)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 0.5, f"transform RMSE {rmse:.3f} px"
    # diagnostics present and sane
    assert (res.diagnostics["n_inliers"] > 10).all()
    assert res.frames_per_sec is not None


def test_corrected_frames_align_with_reference(translation_data):
    data = translation_data
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    # After correction every frame should match frame 0's scene content.
    m = 24
    ref = data.stack[0][m:-m, m:-m]
    for t in (5, 11):
        err = np.abs(res.corrected[t][m:-m, m:-m] - ref)
        assert err.mean() < 0.05, f"frame {t} mean abs err {err.mean():.4f}"


def test_rigid_drift_recovery():
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="rigid", max_drift=6.0, seed=5
    )
    mc = MotionCorrector(model="rigid", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 0.7, f"rigid RMSE {rmse:.3f} px"


def test_affine_drift_recovery():
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="affine", max_drift=6.0, seed=6
    )
    mc = MotionCorrector(model="affine", backend="jax", batch_size=4, n_hypotheses=192)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 1.0, f"affine RMSE {rmse:.3f} px"


def test_similarity_drift_recovery():
    """Similarity (4-DoF) family: zoom drift + rotation + translation
    recovered, including the scale component specifically."""
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="similarity", max_drift=6.0, seed=9
    )
    mc = MotionCorrector(model="similarity", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 0.7, f"similarity RMSE {rmse:.3f} px"
    # recovered per-frame scale must track the ground-truth zoom walk
    got_s = np.linalg.det(np.asarray(res.transforms)[:, :2, :2]) ** 0.5
    rel = relative_transforms(data.transforms)
    want_s = np.linalg.det(rel[:, :2, :2]) ** 0.5
    np.testing.assert_allclose(got_s, want_s, atol=5e-3)


def test_homography_drift_recovery():
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="homography", max_drift=6.0, seed=7
    )
    mc = MotionCorrector(model="homography", backend="jax", batch_size=4, n_hypotheses=192)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 1.2, f"homography RMSE {rmse:.3f} px"


def test_reference_selectors(translation_data):
    data = translation_data
    mc = MotionCorrector(model="translation", backend="jax", reference="mean", batch_size=4)
    res = mc.correct(data.stack[:4])
    assert res.transforms.shape == (4, 3, 3)
    mc2 = MotionCorrector(
        model="translation", backend="jax", reference=data.reference, batch_size=4
    )
    res2 = mc2.correct(data.stack[:4])
    rmse = transform_rmse(res2.transforms, data.transforms[:4], SHAPE)
    assert rmse < 0.5


def test_batch_boundaries_dont_change_results(translation_data):
    """Chunking must be invisible: same transforms for any batch size."""
    data = translation_data
    r1 = MotionCorrector(model="translation", backend="jax", batch_size=3).correct(data.stack[:7])
    r2 = MotionCorrector(model="translation", backend="jax", batch_size=7).correct(data.stack[:7])
    np.testing.assert_allclose(r1.transforms, r2.transforms, atol=1e-5)


def test_input_validation():
    mc = MotionCorrector(model="translation", backend="jax")
    with pytest.raises(ValueError, match="stack must be"):
        mc.correct(np.zeros((4, 4)))
    with pytest.raises(ValueError, match="rigid3d"):
        mc.correct(np.zeros((2, 4, 8, 8), np.float32))
    with pytest.raises(ValueError, match="unknown backend"):
        MotionCorrector(model="translation", backend="cuda")
    with pytest.raises(ValueError, match="reference index"):
        MotionCorrector(model="translation", reference=99).correct(
            np.zeros((3, 64, 64), np.float32)
        )


def test_affine_nominal_2k_matches_scale():
    """Config 2 at its nominal scale (~2k matches/frame, BASELINE.json
    configs[1]): a dense sharp scene with K=4096 keypoints, a finer
    Harris window (the detector's density ceiling — 1.5 caps maxima at
    ~2.6k on 512^2) and a 4-px candidate tile must yield >=1800
    SURVIVING matches per frame (measured ~2.5k) and recover the drift
    to sub-pixel RMSE."""
    data = synthetic.make_drift_stack(
        n_frames=2, shape=(512, 512), model="affine", max_drift=6.0,
        seed=33, n_blobs=12000, sigma_range=(0.7, 1.4),
    )
    mc = MotionCorrector(
        model="affine", backend="jax", batch_size=2, max_keypoints=4096,
        nms_size=3, harris_window_sigma=1.2, cand_tile=4,
    )
    res = mc.correct(data.stack)
    n_kp = np.asarray(res.diagnostics["n_keypoints"])
    n_matches = np.asarray(res.diagnostics["n_matches"])
    n_inliers = np.asarray(res.diagnostics["n_inliers"])
    assert n_kp.min() > 3800, f"dense scene should near-fill K=4096: {n_kp}"
    assert n_matches[1:].min() >= 1800, f"nominal-scale matching: {n_matches}"
    # matches must be real correspondences, not ratio-test leakage
    assert n_inliers[1:].min() >= 1600, f"consensus inliers: {n_inliers}"
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, (512, 512))
    assert rmse < 0.5, f"affine@2k RMSE {rmse:.3f}"


def test_affine_nominal_2k_cross_backend_parity():
    """Config 2's high-K regime agrees across backends (the judged
    metric is CPU-parity RMSE; the detector knobs and MXU matcher must
    not perturb it). Small frame keeps the NumPy per-frame loop fast."""
    data = synthetic.make_drift_stack(
        n_frames=3, shape=(256, 256), model="affine", max_drift=5.0,
        seed=34, n_blobs=3000, sigma_range=(0.7, 1.4),
    )
    kw = dict(
        model="affine", batch_size=3, max_keypoints=1024, nms_size=3,
        harris_window_sigma=1.2, cand_tile=4,
    )
    rj = MotionCorrector(backend="jax", **kw).correct(data.stack)
    rn = MotionCorrector(backend="numpy", **kw).correct(data.stack)
    rel = relative_transforms(data.transforms)
    rmse_j = transform_rmse(rj.transforms, rel, (256, 256))
    rmse_n = transform_rmse(rn.transforms, rel, (256, 256))
    cross = transform_rmse(rj.transforms, rn.transforms, (256, 256))
    assert rmse_j < 0.3, f"jax high-K RMSE {rmse_j:.3f}"
    assert rmse_n < 0.3, f"numpy high-K RMSE {rmse_n:.3f}"
    assert cross < 0.25, f"cross-backend high-K RMSE {cross:.3f}"


def test_piecewise_residual_passes_improve_field():
    """Multi-pass refinement (default field_passes=3) must not be worse than a single pass on
    a seeded stack — the residual pass exists to cut the membership-
    averaging bias (deterministic: same keys, same data)."""
    data = synthetic.make_piecewise_stack(
        n_frames=6, shape=(192, 192), max_disp=5.0, seed=15
    )
    from kcmc_tpu.utils.metrics import field_rmse

    gt = data.fields - data.fields[0]
    errs = {}
    for passes in (1, 2):
        res = MotionCorrector(
            model="piecewise", backend="jax", batch_size=6,
            field_passes=passes,
        ).correct(data.stack)
        errs[passes] = field_rmse(res.fields, gt)
    assert errs[2] <= errs[1] + 1e-3, errs
    import pytest

    with pytest.raises(ValueError, match="field_passes"):
        MotionCorrector(model="piecewise", field_passes=0)


def test_piecewise_refine_hypotheses_budget():
    """The refine-pass hypothesis budget (round 5): 0 must fall back to
    the full patch_hypotheses budget exactly (same PRNG stream, same
    results), and the small default budget must hold accuracy — the
    refine passes fit a 2x-threshold-gated residual where even 8
    hypotheses find consensus (CorrectorConfig.refine_hypotheses)."""
    data = synthetic.make_piecewise_stack(
        n_frames=6, shape=(192, 192), max_disp=5.0, seed=21
    )
    from kcmc_tpu.utils.metrics import field_rmse

    gt = data.fields - data.fields[0]

    def run(**kw):
        res = MotionCorrector(
            model="piecewise", backend="jax", batch_size=6, **kw
        ).correct(data.stack)
        return res.fields, field_rmse(res.fields, gt)

    f_full, e_full = run(refine_hypotheses=0)
    f_same, _ = run(refine_hypotheses=32)  # == patch_hypotheses default
    np.testing.assert_array_equal(np.asarray(f_full), np.asarray(f_same))
    _, e_small = run(refine_hypotheses=8)  # the shipping default
    # gated-residual consensus: the small budget may differ at RANSAC
    # sampling level but must not cost measurable field accuracy
    assert e_small <= e_full * 1.1 + 1e-3, (e_small, e_full)
    import pytest

    with pytest.raises(ValueError, match="refine_hypotheses"):
        MotionCorrector(model="piecewise", refine_hypotheses=-1)


def test_apply_correction_multichannel_and_valid_region():
    """Register the structural channel, apply to the functional channel
    (multi-channel microscopy workflow), then crop to the common valid
    region."""
    from kcmc_tpu import apply_correction, common_valid_region

    data = synthetic.make_drift_stack(
        n_frames=6, shape=(128, 128), model="translation", max_drift=8.0,
        seed=27,
    )
    # "functional channel": same motion, different contrast
    functional = (np.asarray(data.stack) ** 2 + 0.1).astype(np.float32)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=3)
    res = mc.correct(data.stack)

    corr_func = apply_correction(functional, res.transforms, batch_size=4)
    assert corr_func.shape == functional.shape
    # applying the structural transforms aligns the functional channel:
    # compare against directly correcting the functional channel's pixels
    direct = apply_correction(functional, relative_transforms(data.transforms))
    m = 20
    err = np.abs(corr_func[:, m:-m, m:-m] - direct[:, m:-m, m:-m])
    assert err.mean() < 0.02, err.mean()

    ys, xs = common_valid_region(res.transforms, (128, 128))
    # the drifted stack can't be fully covered: the crop shrinks
    assert (ys.stop - ys.start) < 128 or (xs.stop - xs.start) < 128
    cropped = res.corrected[:, ys, xs]
    assert (np.abs(cropped).sum(axis=(1, 2)) > 0).all()
    # inside the common region, every frame matches the reference scene
    ref = np.asarray(data.stack[0])[ys, xs]
    for t in range(6):
        d = np.abs(cropped[t] - ref)
        assert d.mean() < 0.05, (t, d.mean())

    # uint16 output dtype path + argument validation
    u16 = apply_correction(
        functional, res.transforms, output_dtype=np.uint16
    )
    assert u16.dtype == np.uint16
    with pytest.raises(ValueError, match="exactly one"):
        apply_correction(functional)
    with pytest.raises(ValueError, match="frames but"):
        apply_correction(functional[:3], res.transforms)


def test_common_valid_region_inscribed_and_3d():
    """Every pixel of the returned crop must be covered by EVERY
    transform — including rotations, where the common region is a
    rotated polygon and a bounding box would lie."""
    import jax.numpy as jnp

    from kcmc_tpu import common_valid_region
    from kcmc_tpu.ops.warp import coverage_mask, coverage_mask_3d

    def rot(th, c=31.5):
        M = np.eye(3, dtype=np.float32)
        M[:2, :2] = [[np.cos(th), -np.sin(th)], [np.sin(th), np.cos(th)]]
        M[:2, 2] = [c - M[0, 0] * c - M[0, 1] * c, c - M[1, 0] * c - M[1, 1] * c]
        return M

    Ms = np.stack([rot(0.2), rot(-0.2), np.eye(3, dtype=np.float32)])
    ys, xs = common_valid_region(Ms, (64, 64))
    assert ys.stop - ys.start > 10 and xs.stop - xs.start > 10
    for M in Ms:
        cov = np.asarray(coverage_mask((64, 64), jnp.asarray(M)))
        assert cov[ys, xs].all(), "crop contains uncovered pixels"

    # 3D: z-translation plus in-plane rotation
    M3 = np.eye(4, dtype=np.float32)
    M3[2, 3] = 1.7
    M3b = np.eye(4, dtype=np.float32)
    M3b[:2, :2] = [[np.cos(0.1), -np.sin(0.1)], [np.sin(0.1), np.cos(0.1)]]
    zs, ys, xs = common_valid_region(np.stack([M3, M3b]), (8, 32, 32))
    for M in (M3, M3b):
        cov = np.asarray(coverage_mask_3d((8, 32, 32), jnp.asarray(M)))
        assert cov[zs, ys, xs].all()
    with pytest.raises(ValueError, match="need shape"):
        common_valid_region(np.stack([M3]), (32, 32))


def test_common_valid_region_edge_semantics():
    """Disjoint coverage raises instead of returning an unsafe crop;
    z-dependent shear shrinks the z-run until a true rectangle exists;
    4D stacks reject fields=."""
    import jax.numpy as jnp

    from kcmc_tpu import apply_correction, common_valid_region
    from kcmc_tpu.ops.warp import coverage_mask_3d

    # opposite full-frame drifts: zero common coverage -> error
    A = np.eye(3, dtype=np.float32); A[0, 2] = 70.0
    B = np.eye(3, dtype=np.float32); B[0, 2] = -70.0
    with pytest.raises(ValueError, match="no region is covered"):
        common_valid_region(np.stack([A, B]), (64, 64))

    # x-shear in z makes per-plane bands disjoint across the full run;
    # the result must still be genuinely covered (run shrinks)
    S = np.eye(4, dtype=np.float32); S[0, 2] = 4.0
    S2 = np.eye(4, dtype=np.float32); S2[0, 2] = 4.0; S2[0, 3] = -28.0
    zs, ys, xs = common_valid_region(np.stack([S, S2]), (8, 32, 32))
    for M in (S, S2):
        cov = np.asarray(coverage_mask_3d((8, 32, 32), jnp.asarray(M)))
        assert cov[zs, ys, xs].all()

    with pytest.raises(ValueError, match="2D"):
        apply_correction(
            np.zeros((2, 4, 8, 8), np.float32),
            fields=np.zeros((2, 2, 2, 2), np.float32),
        )


def test_largest_true_rect_matches_bruteforce():
    """The vectorized histogram/pointer-jump rectangle equals the brute-
    force maximum area on random masks (ADVICE r2: the Python stack
    sweep was interpreter-bound on large frames)."""
    from kcmc_tpu.corrector import _largest_true_rect

    rng = np.random.default_rng(7)

    def brute(mask):
        H, W = mask.shape
        best = 0
        for y0 in range(H):
            for y1 in range(y0 + 1, H + 1):
                col = mask[y0:y1].all(axis=0)
                run = best_run = 0
                for v in col:
                    run = run + 1 if v else 0
                    best_run = max(best_run, run)
                best = max(best, (y1 - y0) * best_run)
        return best

    for trial in range(12):
        H = int(rng.integers(1, 14))
        W = int(rng.integers(1, 14))
        mask = rng.uniform(size=(H, W)) < rng.uniform(0.2, 0.9)
        got = _largest_true_rect(mask)
        want = brute(mask)
        if got is None:
            assert want == 0, f"trial {trial}: missed a rectangle"
            continue
        ys, xs = got
        assert mask[ys, xs].all(), f"trial {trial}: rect not all-True"
        area = (ys.stop - ys.start) * (xs.stop - xs.start)
        assert area == want, f"trial {trial}: {area} != brute {want}"


def test_largest_true_rect_large_mask_fast():
    """2048^2 mask in well under a second (was seconds of interpreter
    time with the per-row Python stack)."""
    import time

    from kcmc_tpu.corrector import _largest_true_rect

    yy, xx = np.mgrid[0:1024, 0:1024]
    mask = (yy - 500) ** 2 + (xx - 520) ** 2 < 480**2  # inscribed disc
    t0 = time.perf_counter()
    ys, xs = _largest_true_rect(mask)
    dt = time.perf_counter() - t0
    assert mask[ys, xs].all()
    # inscribed square of a radius-480 disc has side ~679
    assert (ys.stop - ys.start) * (xs.stop - xs.start) > 600 * 600
    assert dt < 1.0, f"largest-rect took {dt:.2f}s"


def test_correlation_polish_symmetry_and_recovery():
    """The polish's two claims: exactly zero correction on identical
    images (the two-way symmetric scoring — one-sided windowed
    correlation had 0.07 px of vertex bias), and recovery of a small
    known shift on shifted ones."""
    import jax
    import jax.numpy as jnp

    from kcmc_tpu.ops.piecewise import correlation_polish
    from kcmc_tpu.utils import synthetic

    rng = np.random.default_rng(5)
    scene = synthetic.render_scene(rng, (256, 256), n_blobs=300)
    t = jnp.asarray(scene)
    # identical: zero correction
    d0 = np.asarray(correlation_polish(t[None], t, (8, 8)))
    assert np.abs(d0).max() == 0.0
    # integer-shifted by (+1, 0): correction must be ~(-1, 0)
    shifted = jnp.asarray(np.roll(scene, 1, axis=1))  # content moved +x
    d1 = np.asarray(correlation_polish(shifted[None], t, (8, 8)))[0]
    interior = d1[1:-1, 1:-1]
    np.testing.assert_allclose(interior[..., 0], 1.0, atol=0.2)
    np.testing.assert_allclose(interior[..., 1], 0.0, atol=0.2)
