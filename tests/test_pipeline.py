"""End-to-end pipeline tests: the judged workloads at reduced scale.

Config 1 (translation drift) is the minimum end-to-end slice from
SURVEY.md §7: synthetic drift stack -> full pipeline -> recovered
transforms within sub-pixel RMSE of ground truth.
"""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse


SHAPE = (160, 160)


@pytest.fixture(scope="module")
def translation_data():
    return synthetic.make_drift_stack(
        n_frames=12, shape=SHAPE, model="translation", max_drift=8.0, seed=11
    )


def test_translation_drift_recovery(translation_data):
    data = translation_data
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    assert res.corrected.shape == data.stack.shape
    assert res.transforms.shape == (12, 3, 3)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 0.5, f"transform RMSE {rmse:.3f} px"
    # diagnostics present and sane
    assert (res.diagnostics["n_inliers"] > 10).all()
    assert res.frames_per_sec is not None


def test_corrected_frames_align_with_reference(translation_data):
    data = translation_data
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    # After correction every frame should match frame 0's scene content.
    m = 24
    ref = data.stack[0][m:-m, m:-m]
    for t in (5, 11):
        err = np.abs(res.corrected[t][m:-m, m:-m] - ref)
        assert err.mean() < 0.05, f"frame {t} mean abs err {err.mean():.4f}"


def test_rigid_drift_recovery():
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="rigid", max_drift=6.0, seed=5
    )
    mc = MotionCorrector(model="rigid", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 0.7, f"rigid RMSE {rmse:.3f} px"


def test_affine_drift_recovery():
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="affine", max_drift=6.0, seed=6
    )
    mc = MotionCorrector(model="affine", backend="jax", batch_size=4, n_hypotheses=192)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 1.0, f"affine RMSE {rmse:.3f} px"


def test_similarity_drift_recovery():
    """Similarity (4-DoF) family: zoom drift + rotation + translation
    recovered, including the scale component specifically."""
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="similarity", max_drift=6.0, seed=9
    )
    mc = MotionCorrector(model="similarity", backend="jax", batch_size=4)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 0.7, f"similarity RMSE {rmse:.3f} px"
    # recovered per-frame scale must track the ground-truth zoom walk
    got_s = np.linalg.det(np.asarray(res.transforms)[:, :2, :2]) ** 0.5
    rel = relative_transforms(data.transforms)
    want_s = np.linalg.det(rel[:, :2, :2]) ** 0.5
    np.testing.assert_allclose(got_s, want_s, atol=5e-3)


def test_homography_drift_recovery():
    data = synthetic.make_drift_stack(
        n_frames=8, shape=SHAPE, model="homography", max_drift=6.0, seed=7
    )
    mc = MotionCorrector(model="homography", backend="jax", batch_size=4, n_hypotheses=192)
    res = mc.correct(data.stack)
    rmse = transform_rmse(res.transforms, relative_transforms(data.transforms), SHAPE)
    assert rmse < 1.2, f"homography RMSE {rmse:.3f} px"


def test_reference_selectors(translation_data):
    data = translation_data
    mc = MotionCorrector(model="translation", backend="jax", reference="mean", batch_size=4)
    res = mc.correct(data.stack[:4])
    assert res.transforms.shape == (4, 3, 3)
    mc2 = MotionCorrector(
        model="translation", backend="jax", reference=data.reference, batch_size=4
    )
    res2 = mc2.correct(data.stack[:4])
    rmse = transform_rmse(res2.transforms, data.transforms[:4], SHAPE)
    assert rmse < 0.5


def test_batch_boundaries_dont_change_results(translation_data):
    """Chunking must be invisible: same transforms for any batch size."""
    data = translation_data
    r1 = MotionCorrector(model="translation", backend="jax", batch_size=3).correct(data.stack[:7])
    r2 = MotionCorrector(model="translation", backend="jax", batch_size=7).correct(data.stack[:7])
    np.testing.assert_allclose(r1.transforms, r2.transforms, atol=1e-5)


def test_input_validation():
    mc = MotionCorrector(model="translation", backend="jax")
    with pytest.raises(ValueError, match="stack must be"):
        mc.correct(np.zeros((4, 4)))
    with pytest.raises(ValueError, match="rigid3d"):
        mc.correct(np.zeros((2, 4, 8, 8), np.float32))
    with pytest.raises(ValueError, match="unknown backend"):
        MotionCorrector(model="translation", backend="cuda")
    with pytest.raises(ValueError, match="reference index"):
        MotionCorrector(model="translation", reference=99).correct(
            np.zeros((3, 64, 64), np.float32)
        )


def test_affine_nominal_2k_matches_scale():
    """Config 2 at its nominal scale (~2k matches/frame): a dense scene
    with max_keypoints=2048 must yield >1k surviving matches per frame
    and recover the drift to sub-pixel RMSE (BASELINE.json configs[1])."""
    data = synthetic.make_drift_stack(
        n_frames=2, shape=(512, 512), model="affine", max_drift=6.0,
        seed=33, n_blobs=6000,
    )
    mc = MotionCorrector(
        model="affine", backend="jax", batch_size=2, max_keypoints=2048
    )
    res = mc.correct(data.stack)
    n_kp = np.asarray(res.diagnostics["n_keypoints"])
    n_matches = np.asarray(res.diagnostics["n_matches"])
    assert n_kp.min() > 1800, f"dense scene should near-fill K=2048: {n_kp}"
    assert n_matches[1:].min() > 1000, f"nominal-scale matching: {n_matches}"
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, (512, 512))
    assert rmse < 0.5, f"affine@2k RMSE {rmse:.3f}"


def test_piecewise_residual_passes_improve_field():
    """field_passes=2 (default) must not be worse than a single pass on
    a seeded stack — the residual pass exists to cut the membership-
    averaging bias (deterministic: same keys, same data)."""
    data = synthetic.make_piecewise_stack(
        n_frames=6, shape=(192, 192), max_disp=5.0, seed=15
    )
    from kcmc_tpu.utils.metrics import field_rmse

    gt = data.fields - data.fields[0]
    errs = {}
    for passes in (1, 2):
        res = MotionCorrector(
            model="piecewise", backend="jax", batch_size=6,
            field_passes=passes,
        ).correct(data.stack)
        errs[passes] = field_rmse(res.fields, gt)
    assert errs[2] <= errs[1] + 1e-3, errs
    import pytest

    with pytest.raises(ValueError, match="field_passes"):
        MotionCorrector(model="piecewise", field_passes=0)
