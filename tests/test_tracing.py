"""Unit suite for the distributed-tracing substrate (obs/tracing.py)
and the SLO burn-rate engine (obs/slo.py).

Covered here (socket-free; the serve integration lives in
test_serve_trace.py):

* id minting: W3C-width hex ids, span-id uniqueness under concurrent
  sessions (run under the concurrency sanitizer — must be clean);
* context propagation: child_context advances the causal tree,
  valid_context rejects wire garbage;
* span shards: header + append + bounded cap + dropped counter,
  torn-tail recovery after a real SIGKILL mid-write and after a
  deterministic truncation;
* collection: collect_spans over files/dirs/lists, stitch,
  critical_path, slowest, chrome_trace;
* exemplars: bucketing parity with LatencyHistogram, last-wins,
  bounded, fleet merge, top_exemplar;
* SLO: objective-spec parsing errors, multi-window burn under an
  injected slowdown (fake clock), page/ticket AND-gating, heartbeat
  line, Prometheus rendering;
* `kcmc_tpu report` critical-path rendering from shards, and the
  "—" row on pre-tracing artifacts.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from kcmc_tpu.obs.tracing import (
    ExemplarStore,
    SpanShard,
    child_context,
    chrome_trace,
    collect_spans,
    critical_path,
    mint_span_id,
    mint_trace_id,
    new_context,
    read_span_shard,
    slowest,
    stitch,
    top_exemplar,
    valid_context,
)


# -- ids + context -----------------------------------------------------------


def test_mint_ids_are_hex_and_right_width():
    t, s = mint_trace_id(), mint_span_id()
    assert len(t) == 32 and int(t, 16) >= 0
    assert len(s) == 16 and int(s, 16) >= 0
    ctx = new_context()
    assert set(ctx) == {"trace_id", "span_id"}


def test_span_ids_unique_across_concurrent_sessions():
    """Concurrent sessions minting ids and emitting to one shared
    shard must never collide (os.urandom: no shared counter to race
    on) — and the shard's lock discipline must be sanitizer-clean."""
    from kcmc_tpu.analysis import sanitize

    owned = not sanitize.active()
    if owned:
        sanitize.enable(watchdog_s=5.0, static=False)
    try:
        shard = SpanShard()
        minted: list[list[str]] = [[] for _ in range(8)]

        def mint(slot: int) -> None:
            for _ in range(200):
                ctx = new_context()
                minted[slot].append(ctx["span_id"])
                shard.complete(
                    "request.total", time.time(), 1e-4,
                    trace_id=ctx["trace_id"], span_id=ctx["span_id"],
                )

        ts = [
            threading.Thread(target=mint, args=(i,)) for i in range(8)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        ids = [s for slot in minted for s in slot]
        assert len(ids) == 8 * 200
        assert len(set(ids)) == len(ids), "span-id collision"
        violations = sanitize.take_violations()
        assert not violations, violations
    finally:
        if owned:
            sanitize.disable()


def test_child_context_advances_the_tree():
    root = new_context()
    ch = child_context(root)
    assert ch["trace_id"] == root["trace_id"]
    assert ch["parent_id"] == root["span_id"]
    assert ch["span_id"] != root["span_id"]
    assert child_context(None) is None
    assert child_context({}) is None


@pytest.mark.parametrize(
    "garbage",
    [None, 7, "abc", [], {"trace_id": ""}, {"trace_id": 12},
     {"span_id": "deadbeef"}],
)
def test_valid_context_rejects_wire_garbage(garbage):
    assert valid_context(garbage) is None


def test_valid_context_strips_non_string_optionals():
    got = valid_context(
        {"trace_id": "t" * 32, "span_id": 5, "parent_id": "p" * 16,
         "junk": 1}
    )
    assert got == {"trace_id": "t" * 32, "parent_id": "p" * 16}


# -- span shards -------------------------------------------------------------


def test_shard_header_roundtrip_and_ring(tmp_path):
    p = str(tmp_path / "spans.jsonl")
    sh = SpanShard(p, cap=16)
    ctx = new_context()
    sh.complete(
        "request.device", time.time(), 0.01,
        trace_id=ctx["trace_id"], args={"n": 4},
    )
    sh.close()
    with open(p) as f:
        header = json.loads(f.readline())
    assert header["kind"] == "kcmc_span_shard"
    spans = read_span_shard(p)
    assert len(spans) == 1
    s = spans[0]
    assert s["name"] == "request.device"
    assert s["trace_id"] == ctx["trace_id"]
    assert s["args"] == {"n": 4}
    assert s["pid"] == os.getpid()
    # the in-memory ring serves the live `trace` verb
    assert sh.tail()[0]["name"] == "request.device"


def test_shard_bounded_cap_counts_drops(tmp_path):
    p = str(tmp_path / "spans.jsonl")
    sh = SpanShard(p, cap=5)
    for _ in range(9):
        sh.complete("request.total", time.time(), 1e-3,
                    trace_id=mint_trace_id())
    sh.close()
    assert len(read_span_shard(p)) == 5  # file capped
    assert sh.dropped == 4  # overflow counted, never torn
    assert len(sh.tail()) == 5  # ring ages out oldest


def test_shard_torn_tail_truncation_recovery(tmp_path):
    p = str(tmp_path / "spans.jsonl")
    sh = SpanShard(p)
    for i in range(4):
        sh.complete("request.total", time.time(), 1e-3,
                    trace_id=mint_trace_id(), args={"i": i})
    sh.close()
    # tear the final line mid-object, the kill -9 disk state
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-9])
    spans = read_span_shard(p)
    assert [s["args"]["i"] for s in spans] == [0, 1, 2]
    # an unparseable HEADER is a hard error (not a span shard at all)
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"kind": "kcmc_span_shard"')
    with pytest.raises(ValueError):
        read_span_shard(str(bad))


def test_shard_survives_real_sigkill_mid_write(tmp_path):
    """A child process SIGKILLed while appending spans leaves a shard
    the reader recovers without error — every complete line parses."""
    p = str(tmp_path / "spans.jsonl")
    child = subprocess.Popen(
        [
            sys.executable, "-c",
            "import sys, time\n"
            "from kcmc_tpu.obs.tracing import SpanShard, mint_trace_id\n"
            f"sh = SpanShard({p!r}, cap=1_000_000)\n"
            "print('armed', flush=True)\n"
            "while True:\n"
            "    sh.complete('request.total', time.time(), 1e-4,\n"
            "                trace_id=mint_trace_id())\n",
        ],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert child.stdout.readline().strip() == "armed"
        deadline = time.monotonic() + 30
        while os.path.getsize(p) < 4096 and time.monotonic() < deadline:
            time.sleep(0.01)
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=30)
    spans = read_span_shard(p)  # must not raise
    assert len(spans) >= 1
    assert all(len(s["trace_id"]) == 32 for s in spans)


def test_shard_write_failure_never_raises(tmp_path):
    p = str(tmp_path / "spans.jsonl")
    sh = SpanShard(p)
    sh._fh.close()  # simulate the disk yanked mid-run
    sh.complete("request.total", time.time(), 1e-3,
                trace_id=mint_trace_id())  # must swallow, not raise
    assert sh.tail()  # the ring still works
    sh.close()


# -- collection / stitching --------------------------------------------------


def _one_trace(shard, dur_device=0.03, n=4):
    client = new_context()
    shard.complete("rpc.client", time.time(), dur_device + 0.01,
                   trace_id=client["trace_id"],
                   span_id=client["span_id"])
    ch = child_context(client)
    shard.complete("request.device", time.time(), dur_device,
                   trace_id=ch["trace_id"], parent_id=ch["parent_id"],
                   args={"n": n})
    shard.complete("request.queue_wait", time.time(), 0.001,
                   trace_id=ch["trace_id"], parent_id=ch["parent_id"],
                   args={"n": n})
    shard.complete("request.total", time.time(),
                   dur_device + 0.002, trace_id=ch["trace_id"],
                   parent_id=ch["parent_id"], args={"n": n})
    return client["trace_id"]


def test_collect_stitch_critical_path_slowest(tmp_path):
    a = SpanShard(str(tmp_path / "a.jsonl"))
    tid_fast = _one_trace(a, dur_device=0.01)
    tid_slow = _one_trace(a, dur_device=0.5)
    a.close()
    # files, dirs, and already-loaded lists all collect
    spans = collect_spans([str(tmp_path)])
    assert spans == collect_spans([str(tmp_path / "a.jsonl")])
    assert spans == collect_spans([spans])
    traces = stitch(spans)
    assert set(traces) == {tid_fast, tid_slow}
    cp = critical_path(traces[tid_slow])
    assert cp["dominant"] == "request.device"
    # span weight = dur * n telescopes against per-frame histograms
    assert cp["segments"]["request.device"] == pytest.approx(
        0.5 * 4, rel=1e-6
    )
    rows = slowest(traces, n=1)
    assert rows[0]["trace_id"] == tid_slow
    # untraced spans stitch to no trace
    assert stitch([{"name": "x", "dur_s": 1.0}]) == {}


def test_chrome_trace_export(tmp_path):
    sh = SpanShard()
    _one_trace(sh)
    out = chrome_trace(sh.tail())
    events = out["traceEvents"]
    names = {e["name"] for e in events}
    assert "request.device" in names and "process_name" in names
    x = [e for e in events if e["ph"] == "X"]
    assert all(e["ts"] > 0 and "trace_id" in e["args"] for e in x)
    json.dumps(out)  # must be serializable as written


# -- exemplars ---------------------------------------------------------------


def test_exemplar_store_buckets_like_the_histogram():
    from kcmc_tpu.obs.latency import LatencyHistogram

    store = ExemplarStore()
    h = LatencyHistogram()
    for v, tid in [(0.001, "a" * 32), (0.25, "b" * 32)]:
        store.note("request.total", v, tid)
        h.record(v)
    exp = store.export()
    buckets = exp["request.total"]["full"]
    # the exemplar bucket indices are exactly the histogram's
    assert set(buckets) == set(h.to_dict()["counts"])
    top = top_exemplar(exp, "request.total")
    assert top["trace_id"] == "b" * 32
    assert top_exemplar(exp, "request.device") is None
    assert top_exemplar({}, "request.total") is None


def test_exemplar_store_last_wins_and_bounded():
    store = ExemplarStore(cap=3)
    store.note("request.total", 0.01, "old" + "0" * 29)
    store.note("request.total", 0.0101, "new" + "0" * 29)  # same bucket
    exp = store.export()
    (only,) = exp["request.total"]["full"].values()
    assert only["trace_id"].startswith("new")
    for i in range(5):  # distinct buckets overflow the cap
        store.note("request.device", 10.0 ** (-i), f"{i}" * 32)
    total = sum(
        len(b)
        for rungs in store.export().values()
        for b in rungs.values()
    )
    assert total <= 3
    store.note("request.total", 0.01, None)  # untraced: no-op


def test_exemplar_merge_exports_last_wins():
    a = {"request.total": {"full": {"9": {"trace_id": "a" * 32,
                                          "value_s": 0.1}}}}
    b = {"request.total": {"full": {"9": {"trace_id": "b" * 32,
                                          "value_s": 0.2}}}}
    merged = ExemplarStore.merge_exports([a, b, None, "junk"])
    assert merged["request.total"]["full"]["9"]["trace_id"] == "b" * 32


# -- SLO engine --------------------------------------------------------------


def _hists(good: int, bad: int) -> dict:
    """A plane.histograms dict with `good` fast and `bad` slow
    request.total observations on the full rung."""
    from kcmc_tpu.obs.latency import LatencyHistogram

    h = LatencyHistogram()
    if good:
        h.record(0.01, n=good)
    if bad:
        h.record(5.0, n=bad)
    return {"request.total": {"full": h.to_dict()}}


def test_parse_objectives_spec_grammar():
    from kcmc_tpu.obs.slo import parse_objectives

    objs = parse_objectives("full:0.25:0.99;avail:0.999; ")
    assert [o.kind for o in objs] == ["latency", "availability"]
    assert objs[0].rung == "full" and objs[0].threshold_s == 0.25
    assert objs[1].target == 0.999
    assert parse_objectives("") == []
    for bad in ["full:0.25", "avail:2", "full:-1:0.9", "full:0.2:1.5",
                "avail:0.9:0.9", "full:x:0.9"]:
        with pytest.raises(ValueError):
            parse_objectives(bad)


def test_slo_burn_nonzero_under_injected_slowdown():
    from kcmc_tpu.obs.slo import PAGE_BURN, SLOEngine, WINDOWS

    clock = [0.0]
    eng = SLOEngine("full:0.25:0.99;avail:0.999",
                    now=lambda: clock[0])
    # healthy hour: all requests fast, zero burn
    eng.tick(_hists(good=1000, bad=0), {"frames_done": 1000})
    clock[0] = 3600.0
    eng.tick(_hists(good=2000, bad=0), {"frames_done": 2000})
    burns = eng.burn_rates()
    assert burns["latency_full_lt_0.25s"]["5m"] == 0.0
    assert eng.alerts() == []
    # injected slowdown: from here every new request is slow — the
    # cumulative bad count grows while good stalls, so the bad
    # fraction of every window's delta is 1.0 against a 1% budget
    clock[0] += 300.0
    eng.tick(_hists(good=2000, bad=700), {"frames_done": 2700})
    clock[0] += 3600.0
    eng.tick(_hists(good=2000, bad=1400), {"frames_done": 3400})
    burns = eng.burn_rates()
    for w in WINDOWS:
        assert burns["latency_full_lt_0.25s"][w] > 1.0, (w, burns)
    assert burns["latency_full_lt_0.25s"]["5m"] >= PAGE_BURN
    alerts = eng.alerts()
    assert any(a.startswith("PAGE slo=latency_full") for a in alerts)
    hb = eng.heartbeat()
    assert hb.startswith("slo burn 5m=") and "ALERTS=" in hb


def test_slo_page_requires_both_fast_windows():
    """The multi-window AND: a 5-minute blip must not page when the
    1-hour window is still healthy."""
    from kcmc_tpu.obs.slo import SLOEngine

    clock = [0.0]
    eng = SLOEngine("full:0.25:0.99", now=lambda: clock[0])
    # a long healthy hour dilutes the 1h window
    eng.tick(_hists(good=100_000, bad=0), {})
    clock[0] = 3600.0
    eng.tick(_hists(good=200_000, bad=0), {})
    # short sharp blip: 100% bad for one 5m sample
    clock[0] += 300.0
    eng.tick(_hists(good=200_000, bad=300), {})
    burns = eng.burn_rates()["latency_full_lt_0.25s"]
    assert burns["5m"] > burns["1h"]
    assert eng.alerts() == [], burns


def test_slo_availability_objective_counts_rejections():
    from kcmc_tpu.obs.slo import SLOEngine

    clock = [0.0]
    eng = SLOEngine("avail:0.999", now=lambda: clock[0])
    eng.tick({}, {"frames_done": 0, "rejected_frames": 0})
    for i in range(1, 5):  # cumulative: 10% of frames rejected
        clock[0] += 300.0
        eng.tick({}, {"frames_done": 900 * i,
                      "rejected_frames": 100 * i})
    burns = eng.burn_rates()["availability"]
    assert burns["5m"] == pytest.approx(100.0, rel=0.01)  # 10%/0.1%


def test_render_slo_prometheus_lines_and_absence():
    from kcmc_tpu.obs.slo import SLOEngine, render_slo_prometheus

    assert render_slo_prometheus(None) == []
    assert render_slo_prometheus({}) == []
    eng = SLOEngine("full:0.25:0.99")
    eng.tick(_hists(good=10, bad=0), {})
    lines = render_slo_prometheus(eng.gauges())
    text = "\n".join(lines)
    assert 'kcmc_slo_burn_rate{objective="latency_full_lt_0.25s"' in text
    assert 'window="5m"' in text and 'window="3d"' in text
    # the full rung measures batch-class traffic: every line of the
    # objective carries the per-class label (docs/SERVING.md
    # "Latency QoS")
    assert (
        'kcmc_slo_target{objective="latency_full_lt_0.25s"'
        ',qos_class="batch"} 0.99'
    ) in text
    assert "kcmc_slo_alerts 0" in text
    # every TYPE has a HELP (the exposition format contract)
    types = {l.split()[2] for l in lines if l.startswith("# TYPE")}
    helps = {l.split()[2] for l in lines if l.startswith("# HELP")}
    assert types == helps


# -- report rendering --------------------------------------------------------


def test_report_renders_critical_path_from_shards(tmp_path):
    from kcmc_tpu.obs.report import load_run, render_report, _json_summary

    sh = SpanShard(str(tmp_path / "spans.jsonl"))
    for _ in range(3):
        _one_trace(sh, dur_device=0.1)
    sh.close()
    for src in (str(tmp_path / "spans.jsonl"), str(tmp_path)):
        run = load_run(src)
        text = render_report(run)
        assert "Critical path (3 traced requests" in text
        assert "request.device" in text and "slowest:" in text
        cp = _json_summary(run, top=5)["critical_path"]
        assert cp["dominant"] == {"request.device": 3}
        assert len(cp["slowest"]) == 3


def test_report_critical_path_dash_on_pre_tracing_artifacts(tmp_path):
    from kcmc_tpu.obs.report import load_run, render_report, _json_summary

    p = tmp_path / "frames.jsonl"
    p.write_text(
        json.dumps({"kind": "kcmc_frame_records", "version": 1}) + "\n"
        + json.dumps({"frame": 0, "n_inliers": 10}) + "\n"
    )
    run = load_run(str(p))
    text = render_report(run)
    assert "Critical path: —" in text  # present, dashed, no crash
    assert _json_summary(run, top=5)["critical_path"] is None
