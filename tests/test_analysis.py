"""`kcmc check` — the AST invariant checker (kcmc_tpu/analysis).

Two layers:

* known-bad fixtures per pass: each rule must FIRE on a minimal
  snippet exhibiting the violation (and stay quiet on the fixed
  variant) — the demonstrability contract of docs/ANALYSIS.md;
* the repo itself: a full `run_check` over the working tree must be
  clean against the checked-in baseline (no new findings, no stale or
  unjustified baseline entries) — the same gate CI applies.
"""

from __future__ import annotations

import json
import os

from kcmc_tpu.analysis.config_registry import ConfigRegistryPass
from kcmc_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    Finding,
    ModuleIndex,
    run_passes,
)
from kcmc_tpu.analysis.concurrency import RacePass, ThreadRootsPass
from kcmc_tpu.analysis.jit_purity import JitPurityPass
from kcmc_tpu.analysis.lifecycle import ResourceLifecyclePass
from kcmc_tpu.analysis.lock_discipline import LockDisciplinePass
from kcmc_tpu.analysis.span_registry import SpanRegistryPass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


def messages_of(findings):
    return [f.message for f in findings]


# -- pass 1: config-registry ----------------------------------------------

CONFIG_TMPL = """
import dataclasses

@dataclasses.dataclass(frozen=True)
class CorrectorConfig:
    model: str = "translation"
    batch_size: int = 32
    {extra_field}
    def __post_init__(self):
        {post_init}

SIG_NEUTRAL_FIELDS = frozenset({{"batch_size"}})
SIG_AFFECTING_FIELDS = frozenset({{{affecting}}})

def _validate_field_classification():
    pass
"""


def config_index(
    extra_field="", post_init="_validate_field_classification()",
    affecting='"model"', docs='`model` `batch_size` `mystery`',
):
    src = CONFIG_TMPL.format(
        extra_field=extra_field, post_init=post_init, affecting=affecting
    )
    return ModuleIndex.from_sources(
        {"kcmc_tpu/config.py": src}, docs={"docs/API.md": docs}
    )


def test_config_pass_clean_fixture():
    findings = ConfigRegistryPass().run(config_index())
    assert findings == []


def test_config_pass_fires_on_unclassified_field():
    idx = config_index(extra_field="mystery: int = 0")
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "'mystery' is classified in neither" in m
        for m in messages_of(findings)
    ), findings


def test_config_pass_fires_on_double_classification():
    idx = config_index(affecting='"model", "batch_size"')
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "BOTH signature registries" in m for m in messages_of(findings)
    )


def test_config_pass_fires_on_ghost_registry_entry():
    idx = config_index(affecting='"model", "removed_field"')
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "lists 'removed_field'" in m for m in messages_of(findings)
    )


def test_config_pass_fires_on_missing_validator_call():
    idx = config_index(post_init="pass")
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "_validate_field_classification" in m
        for m in messages_of(findings)
    )


def test_config_pass_fires_on_undocumented_field():
    idx = config_index(docs="`model` only")
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "'batch_size' is not documented" in m
        for m in messages_of(findings)
    )


# -- pass 2: jit-purity ----------------------------------------------------

JIT_BAD = """
import jax
import numpy as np

def helper(x):
    host = np.asarray(x)          # host sync inside traced code
    print("tracing", host)
    return x * 2

@jax.jit
def traced(x):
    y = helper(x)
    y.block_until_ready()
    return float(y)
"""

JIT_CLEAN = """
import jax
import jax.numpy as jnp

def helper(x):
    return jnp.asarray(x) * 2

@jax.jit
def traced(x):
    return helper(x) + 1

def host_driver(x):
    # host-side code may sync freely: not reachable from a jit root
    import numpy as np
    return np.asarray(traced(x))
"""


def test_jit_purity_fires_on_host_sync_inside_jit():
    idx = ModuleIndex.from_sources(
        {"kcmc_tpu/backends/jax_backend.py": JIT_BAD}
    )
    findings = JitPurityPass().run(idx)
    msgs = messages_of(findings)
    assert any("np.asarray" in m for m in msgs), findings
    assert any("block_until_ready" in m for m in msgs)
    assert any("print" in m for m in msgs)
    assert any("float()" in m for m in msgs)


def test_jit_purity_quiet_on_clean_module_and_host_code():
    idx = ModuleIndex.from_sources(
        {"kcmc_tpu/backends/jax_backend.py": JIT_CLEAN}
    )
    assert JitPurityPass().run(idx) == []


def test_jit_purity_follows_jit_call_sites_not_just_decorators():
    src = """
import jax, time

def impure(x):
    return x + time.time()

fn = jax.jit(impure)
"""
    idx = ModuleIndex.from_sources({"kcmc_tpu/plans/plan.py": src})
    findings = JitPurityPass().run(idx)
    assert any("time.time" in m for m in messages_of(findings))


def test_jit_purity_ignores_modules_outside_scope():
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/reader.py": JIT_BAD})
    assert JitPurityPass().run(idx) == []


# -- pass 3: lock/thread discipline ---------------------------------------

LOCK_CYCLE = """
import threading

class Pool:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""

LOCK_CYCLE_VIA_CALL = """
import threading

class Pool:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            self.grab_b()

    def grab_b(self):
        with self._b:
            pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""

DAEMON_XLA = """
import threading

class Warmer:
    def start(self):
        threading.Thread(
            target=self._warm, name="warm", daemon=True
        ).start()

    def _warm(self):
        from kcmc_tpu.backends import get_backend
        get_backend("jax", None)
"""

DAEMON_OK = """
import threading

class Warmer:
    def start(self):
        self._t = threading.Thread(target=self._warm, daemon=False)
        self._t.start()

    def _warm(self):
        from kcmc_tpu.backends import get_backend
        get_backend("jax", None)

    def tick(self):
        threading.Thread(target=self._log, daemon=True).start()

    def _log(self):
        print("alive")
"""

RACE_BAD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(
            target=self._run, name="w", daemon=False
        )

    def _run(self):
        self._n = self._n + 1      # worker write, no lock

    def stop(self):
        self._t.join()

    def reset(self):
        self._n = 0                # client write, no lock
"""


def test_lock_order_cycle_fires():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/pool.py": LOCK_CYCLE})
    findings = LockDisciplinePass().run(idx)
    assert any(
        f.rule == "lock-order" and "cycle" in f.message for f in findings
    ), findings


def test_lock_order_cycle_through_method_call_fires():
    idx = ModuleIndex.from_sources(
        {"kcmc_tpu/serve/pool.py": LOCK_CYCLE_VIA_CALL}
    )
    findings = LockDisciplinePass().run(idx)
    assert any(f.rule == "lock-order" for f in findings), findings


def test_lock_order_quiet_on_consistent_nesting():
    src = LOCK_CYCLE.replace(
        "with self._b:\n            with self._a:",
        "with self._a:\n            with self._b:",
    )
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/pool.py": src})
    assert not [
        f for f in LockDisciplinePass().run(idx) if f.rule == "lock-order"
    ]


def test_daemon_xla_thread_fires():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/warm.py": DAEMON_XLA})
    findings = LockDisciplinePass().run(idx)
    hits = [f for f in findings if f.rule == "daemon-xla"]
    assert hits and "get_backend" in hits[0].message, findings


def test_daemon_xla_quiet_on_non_daemon_and_non_xla_threads():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/warm.py": DAEMON_OK})
    assert not [
        f for f in LockDisciplinePass().run(idx) if f.rule == "daemon-xla"
    ]


# -- pass 5+6: whole-program concurrency (thread-roots, race) --------------


def test_race_fires_on_unsynchronized_cross_thread_write():
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": RACE_BAD})
    findings = RacePass().run(idx)
    hits = [f for f in findings if f.rule == "race"]
    assert hits and "Counter._n" in hits[0].message, findings
    assert hits[0].severity == "error"


def test_race_quiet_when_both_sides_locked():
    src = RACE_BAD.replace(
        "self._n = self._n + 1      # worker write, no lock",
        "with self._lock:\n            self._n = self._n + 1",
    ).replace(
        "self._n = 0                # client write, no lock",
        "with self._lock:\n            self._n = 0",
    )
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": src})
    assert not [f for f in RacePass().run(idx) if f.rule == "race"]


CALLER_HELD = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(
            target=self._run, name="w", daemon=False
        )

    def _run(self):
        with self._lock:
            self._n = self._n + 1

    def _write(self):
        self._n = 0    # unlocked HERE — the caller holds the lock

    def stop(self):
        self._t.join()

    def reset(self):
        with self._lock:
            self._write()
"""


def test_race_sees_caller_held_locks_across_calls():
    """Happens-before propagation: a callee invoked under the caller's
    lock inherits it — the serving plane's convention."""
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": CALLER_HELD})
    assert not [f for f in RacePass().run(idx) if f.rule == "race"]
    # drop the caller's lock and the same write becomes a race
    bad = CALLER_HELD.replace(
        "with self._lock:\n            self._write()", "self._write()"
    )
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": bad})
    assert [f for f in RacePass().run(idx) if f.rule == "race"]


RACE_CROSS_MODULE = {
    "kcmc_tpu/serve/plane.py": """
import threading

from kcmc_tpu.serve.stream import Stream

class Plane:
    def __init__(self):
        self._lock = threading.RLock()
        self._streams = {}
        self._thread = threading.Thread(
            target=self._loop, name="plane", daemon=False
        )

    def _loop(self):
        for s in list(self._streams.values()):
            s.account(1)

    def stop(self):
        self._thread.join()

    def submit(self, sid, n):
        with self._lock:
            s = self._streams.get(sid)
            s.enqueue(n)

    def open(self, sid):
        with self._lock:
            self._streams[sid] = Stream(self._lock, sid)
""",
    "kcmc_tpu/serve/stream.py": """
import threading

class Stream:
    def __init__(self, lock, sid):
        self._cond = threading.Condition(lock)
        self.sid = sid
        self.queued = 0
        self.done = 0

    def enqueue(self, n):
        self.queued = self.queued + n

    def account(self, n):
        self.done = self.done + n
""",
}


def test_race_resolves_constructor_lock_aliasing_cross_module():
    """Stream._cond IS Plane._lock (constructor-parameter aliasing
    through the call site): enqueue under the plane lock is quiet;
    the scheduler-thread account() with no lock fires."""
    from kcmc_tpu.analysis.concurrency import RacePass as RP

    findings = RP().run(ModuleIndex.from_sources(RACE_CROSS_MODULE))
    msgs = messages_of(findings)
    # 'done' is written by the loop thread with no lock and read by
    # nobody else -> no pair; 'queued' is written under the plane lock
    # from submit (client) but the loop thread reads nothing of it...
    # make the conflict explicit: account() also touches queued
    assert not any("Stream.queued" in m for m in msgs), findings
    bad = dict(RACE_CROSS_MODULE)
    bad["kcmc_tpu/serve/stream.py"] = bad["kcmc_tpu/serve/stream.py"].replace(
        "self.done = self.done + n",
        "self.done = self.done + n\n        self.queued = self.queued - n",
    )
    findings = RP().run(ModuleIndex.from_sources(bad))
    assert any(
        "Stream.queued" in m for m in messages_of(findings)
    ), findings


def test_race_exempts_construction_context():
    """Writes reached through a constructor (including methods the
    ctor calls) are building an unpublished object — exempt."""
    src = RACE_BAD.replace(
        "def reset(self):\n        self._n = 0                # client write, no lock",
        "def reset(self):\n        pass",
    )
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": src})
    # the only unlocked client-side write was in __init__ -> no pair
    assert not [f for f in RacePass().run(idx) if f.rule == "race"]


def test_thread_roots_flags_unnamed_and_lambda_threads():
    src = """
import threading

def work():
    pass

def spawn():
    threading.Thread(target=work, daemon=True).start()
    threading.Thread(target=lambda: None, name="x", daemon=True).start()
"""
    findings = ThreadRootsPass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/spawner.py": src})
    )
    msgs = messages_of(findings)
    assert any("without a name=" in m for m in msgs), findings
    assert any("lambda target" in m for m in msgs), findings


def test_thread_roots_quiet_on_named_resolvable_threads():
    src = """
import threading

def work():
    pass

def spawn():
    t = threading.Thread(target=work, name="kcmc-w", daemon=True)
    t.start()
    t.join()
"""
    assert ThreadRootsPass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/spawner.py": src})
    ) == []


# -- pass 7: resource lifecycle --------------------------------------------


def test_lifecycle_flags_unjoined_thread_and_unreleased_executor():
    src = """
import threading
from concurrent.futures import ThreadPoolExecutor

def leak_thread():
    t = threading.Thread(target=print, name="t", daemon=False)
    t.start()

def leak_pool():
    ex = ThreadPoolExecutor(2)
    ex.submit(print)
"""
    findings = ResourceLifecyclePass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/leaky.py": src})
    )
    msgs = messages_of(findings)
    assert any("'t' acquired from threading.Thread" in m for m in msgs)
    assert any(
        "'ex' acquired from ThreadPoolExecutor" in m for m in msgs
    ), findings


def test_lifecycle_quiet_on_finally_with_and_escape():
    src = """
import threading
from concurrent.futures import ThreadPoolExecutor

def joined():
    t = threading.Thread(target=print, name="t", daemon=False)
    t.start()
    try:
        pass
    finally:
        t.join()

def managed():
    with ThreadPoolExecutor(2) as ex:
        ex.submit(print)

def transferred():
    t = threading.Thread(target=print, name="t", daemon=False)
    return t
"""
    assert ResourceLifecyclePass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/clean.py": src})
    ) == []


def test_lifecycle_happy_path_release_is_a_warning():
    src = """
from concurrent.futures import ThreadPoolExecutor

def risky():
    ex = ThreadPoolExecutor(2)
    ex.submit(print)
    ex.shutdown()
"""
    findings = ResourceLifecyclePass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/risky.py": src})
    )
    assert [f.severity for f in findings] == ["warning"], findings
    assert "happy path" in findings[0].message


def test_lifecycle_self_attr_needs_a_releasing_method():
    src = """
from concurrent.futures import ThreadPoolExecutor

class Owner:
    def start(self):
        self._ex = ThreadPoolExecutor(2)
"""
    findings = ResourceLifecyclePass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/owner.py": src})
    )
    assert any(
        "never released by Owner" in f.message for f in findings
    ), findings
    fixed = src + "\n    def close(self):\n        self._ex.shutdown()\n"
    assert ResourceLifecyclePass().run(
        ModuleIndex.from_sources({"kcmc_tpu/io/owner.py": fixed})
    ) == []


# -- pass 4: span-registry -------------------------------------------------

REGISTRY_SRC = """
SPAN_NAMES = frozenset({"good_span", "good_stall"})
TIMING_KEYS = frozenset({"stages_s", "total_s"})
"""

SPAN_BAD = """
def run(tracer, timer, timing):
    with tracer.span("rogue_span"):
        pass
    with timer.stall("good_stall"):
        pass
    timing["rogue_key"] = 1.0
    return timing.get("stages_s")
"""


def span_index(producer=SPAN_BAD):
    return ModuleIndex.from_sources(
        {
            "kcmc_tpu/obs/registry.py": REGISTRY_SRC,
            "kcmc_tpu/corrector.py": producer
            + "\nX = ('good_span',)\n",  # keep good_span non-stale
        }
    )


def test_span_registry_fires_on_unregistered_span_and_key():
    findings = SpanRegistryPass().run(span_index())
    msgs = messages_of(findings)
    assert any("'rogue_span'" in m for m in msgs), findings
    assert any("'rogue_key'" in m for m in msgs)
    # registered names at emission sites stay quiet
    assert not any("good_stall" in m and "not in" in m for m in msgs)


def test_span_registry_flags_stale_registry_entry():
    idx = ModuleIndex.from_sources(
        {
            "kcmc_tpu/obs/registry.py": REGISTRY_SRC,
            "kcmc_tpu/corrector.py": "def f(timer):\n"
            "    with timer.stall('good_stall'):\n        pass\n",
        }
    )
    findings = SpanRegistryPass().run(idx)
    assert any(
        "'good_span'" in f.message and "no emission site" in f.message
        for f in findings
    )


def test_span_registry_resolves_union_registries():
    reg = (
        "A = frozenset({'a_span'})\n"
        "B = frozenset({'b_span'})\n"
        "SPAN_NAMES = A | B\n"
        "TIMING_KEYS = frozenset()\n"
    )
    idx = ModuleIndex.from_sources(
        {
            "kcmc_tpu/obs/registry.py": reg,
            "kcmc_tpu/x.py": "def f(t):\n"
            "    t.instant('a_span'); t.instant('b_span')\n",
        }
    )
    assert not [
        f
        for f in SpanRegistryPass().run(idx)
        if "not in SPAN_NAMES" in f.message
    ]


# -- baseline mechanics ----------------------------------------------------


def test_baseline_splits_and_reports_stale_and_unjustified():
    f1 = Finding("r", "a.py", 3, "error", "msg one")
    f2 = Finding("r", "a.py", 9, "error", "msg two")
    bl = Baseline(
        [
            BaselineEntry("r", "a.py", "msg one", "justified"),
            BaselineEntry("r", "a.py", "gone finding", "was fixed"),
            BaselineEntry("r", "b.py", "whatever", ""),  # no reason
        ]
    )
    new, accepted = bl.split([f1, f2])
    assert [f.message for f in new] == ["msg two"]
    assert [f.message for f in accepted] == ["msg one"]
    problems = bl.problems()
    assert any("no justification" in f.message for f in problems)
    assert any("stale baseline entry" in f.message for f in problems)


def test_baseline_keys_ignore_line_numbers():
    f = Finding("r", "a.py", 123, "error", "stable message")
    e = BaselineEntry("r", "a.py", "stable message", "ok")
    assert e.matches(f)
    f2 = Finding("r", "a.py", 456, "error", "stable message")
    assert e.matches(f2)


def test_run_passes_exit_semantics():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/warm.py": DAEMON_XLA})
    res = run_passes(idx, [LockDisciplinePass()])
    assert res.exit_code == 1
    bl = Baseline(
        [
            BaselineEntry(
                "daemon-xla",
                "kcmc_tpu/serve/warm.py",
                "daemon thread 'warm' reaches jax compile/dispatch",
                "fixture",
            )
        ]
    )
    res2 = run_passes(idx, [LockDisciplinePass()], bl)
    assert res2.exit_code == 0 and res2.baselined


# -- the repo itself is clean vs the checked-in baseline -------------------


def test_repo_is_clean_against_baseline():
    from kcmc_tpu.analysis.cli import run_check

    res = run_check(REPO_ROOT)
    assert res.new == [], "NEW findings:\n" + "\n".join(
        f.format() for f in res.new
    )
    blocking = [
        f for f in res.baseline_problems if f.severity == "error"
    ]
    assert blocking == [], "\n".join(f.format() for f in blocking)
    assert res.exit_code == 0
    # the nine passes all ran
    assert set(res.passes) == {
        "config-registry",
        "jit-purity",
        "lock-discipline",
        "span-registry",
        "thread-roots",
        "race",
        "resource-lifecycle",
        "traceflow",
        "donation",
    }


def test_cli_json_roundtrip_and_report_rendering(tmp_path, capsys):
    from kcmc_tpu.analysis.cli import main as check_main
    from kcmc_tpu.obs.report import main as report_main

    rc = check_main(["--root", REPO_ROOT, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload["kind"] == "kcmc_check" and payload["ok"] is True

    art = tmp_path / "check.json"
    art.write_text(out)
    rc = report_main(str(art))
    rendered = capsys.readouterr().out
    assert rc == 0 and rendered.startswith("kcmc check:")
    assert "OK" in rendered


def test_cli_fails_on_injected_bad_snippet(tmp_path, capsys):
    """The CI negative contract: a deliberately-bad snippet anywhere in
    the package must flip `kcmc check` to a nonzero exit."""
    import shutil

    from kcmc_tpu.analysis.cli import main as check_main

    root = tmp_path / "repo"
    shutil.copytree(
        os.path.join(REPO_ROOT, "kcmc_tpu"),
        root / "kcmc_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    (root / "docs").mkdir()
    shutil.copy(
        os.path.join(REPO_ROOT, "docs", "API.md"), root / "docs" / "API.md"
    )
    assert check_main(["--root", str(root)]) == 0
    capsys.readouterr()
    bad = root / "kcmc_tpu" / "serve" / "scheduler.py"
    bad.write_text(bad.read_text() + "\n\n" + DAEMON_XLA)
    assert check_main(["--root", str(root)]) == 1
    assert "daemon-xla" in capsys.readouterr().out


# -- SARIF export -----------------------------------------------------------

# The load-bearing subset of the SARIF 2.1.0 schema: required
# top-level shape, run/tool/driver/rules, and result anatomy. The full
# OASIS schema is ~400 KB; this subset pins every property GitHub's
# code-scanning ingest requires.
SARIF_SUBSET_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "$schema": {"type": "string"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["id"],
                                        },
                                    },
                                },
                            }
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["ruleId", "message"],
                            "properties": {
                                "level": {
                                    "enum": [
                                        "none", "note", "warning", "error",
                                    ]
                                },
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                },
                                "locations": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["physicalLocation"],
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "required": [
                                                    "artifactLocation"
                                                ],
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "required": ["uri"],
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type": "integer",
                                                                "minimum": 1,
                                                            }
                                                        },
                                                    },
                                                },
                                            }
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


def _validate_sarif(payload: dict) -> None:
    try:
        import jsonschema
    except ImportError:
        # structural fallback: the same required-property walk by hand
        assert payload["version"] == "2.1.0"
        run = payload["runs"][0]
        assert run["tool"]["driver"]["name"]
        for r in run["results"]:
            assert r["ruleId"] and r["message"]["text"]
            loc = r["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"]
            assert loc["region"]["startLine"] >= 1
        return
    jsonschema.validate(payload, SARIF_SUBSET_SCHEMA)


def test_sarif_export_validates_and_carries_findings():
    from kcmc_tpu.analysis.sarif import to_sarif

    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": RACE_BAD})
    res = run_passes(idx, [RacePass()])
    payload = to_sarif(res)
    _validate_sarif(payload)
    results = payload["runs"][0]["results"]
    assert any(r["ruleId"] == "race" for r in results)
    race = next(r for r in results if r["ruleId"] == "race")
    assert race["level"] == "error"
    uri = race["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
    assert uri == "kcmc_tpu/io/counter.py"
    # baselined findings do NOT annotate PRs
    bl = Baseline(
        [
            BaselineEntry(
                "race", "kcmc_tpu/io/counter.py",
                "possible data race on 'Counter._n'", "fixture",
            )
        ]
    )
    res2 = run_passes(idx, [RacePass()], bl)
    assert to_sarif(res2)["runs"][0]["results"] == []


def test_cli_sarif_of_repo_is_schema_valid(tmp_path, capsys):
    from kcmc_tpu.analysis.cli import main as check_main

    out = tmp_path / "check.sarif"
    rc = check_main(["--root", REPO_ROOT, "--sarif", str(out)])
    capsys.readouterr()
    assert rc == 0
    payload = json.loads(out.read_text())
    _validate_sarif(payload)
    # repo is clean vs baseline -> no PR annotations
    assert payload["runs"][0]["results"] == []


# -- --prune-baseline --------------------------------------------------------


def test_prune_baseline_drops_stale_keeps_live(tmp_path, capsys):
    from kcmc_tpu.analysis.cli import main as check_main

    root = tmp_path / "repo"
    (root / "kcmc_tpu").mkdir(parents=True)
    (root / "kcmc_tpu" / "warm.py").write_text(DAEMON_XLA)
    bl = tmp_path / "bl.json"
    bl.write_text(
        json.dumps(
            {
                "kind": "kcmc_check_baseline",
                "entries": [
                    {
                        "rule": "daemon-xla",
                        "path": "kcmc_tpu/warm.py",
                        "match": "daemon thread 'warm' reaches jax "
                        "compile/dispatch",
                        "reason": "fixture",
                    },
                    {
                        "rule": "config-registry",
                        "path": "kcmc_tpu/config.py",
                        "match": "config module not found",
                        "reason": "fixture package has no config module",
                    },
                    {
                        "rule": "span-registry",
                        "path": "kcmc_tpu/obs/registry.py",
                        "match": "canonical registry not found",
                        "reason": "fixture package has no registry",
                    },
                    {
                        "rule": "race",
                        "path": "kcmc_tpu/gone.py",
                        "match": "possible data race on 'Gone.x'",
                        "reason": "was fixed long ago",
                    },
                ],
            }
        )
    )
    rc = check_main(
        ["--root", str(root), "--baseline", str(bl), "--prune-baseline"]
    )
    err = capsys.readouterr().err
    assert "pruned 1 stale baseline entry" in err
    assert rc == 0
    data = json.loads(bl.read_text())
    assert [e["rule"] for e in data["entries"]] == [
        "daemon-xla", "config-registry", "span-registry"
    ]
    # pruning is idempotent
    rc = check_main(
        ["--root", str(root), "--baseline", str(bl), "--prune-baseline"]
    )
    assert "pruned 0 stale baseline entries" in capsys.readouterr().err
    assert rc == 0


def test_write_baseline_roundtrip(tmp_path, capsys):
    from kcmc_tpu.analysis.cli import main as check_main

    # a package with one daemon-xla finding and no baseline
    root = tmp_path / "repo"
    (root / "kcmc_tpu").mkdir(parents=True)
    (root / "kcmc_tpu" / "warm.py").write_text(DAEMON_XLA)
    bl = tmp_path / "bl.json"
    # missing explicit baseline path -> usage error
    assert check_main(["--root", str(root), "--baseline", str(bl)]) == 2
    bl.write_text(
        json.dumps({"kind": "kcmc_check_baseline", "entries": []})
    )
    assert check_main(["--root", str(root), "--baseline", str(bl)]) == 1
    capsys.readouterr()
    check_main(
        ["--root", str(root), "--baseline", str(bl), "--write-baseline"]
    )
    data = json.loads(bl.read_text())
    rules = {e["rule"] for e in data["entries"]}
    assert "daemon-xla" in rules, data
    # written entries carry placeholder reasons — the reviewer
    # contract is the FILL-ME-IN marker
    assert all("FILL-ME-IN" in e["reason"] for e in data["entries"])
    # rewriting keeps still-firing justified entries and drops none
    for e in data["entries"]:
        e["reason"] = "justified for the test"
    bl.write_text(json.dumps({"kind": "kcmc_check_baseline",
                              "entries": data["entries"]}))
    check_main(
        ["--root", str(root), "--baseline", str(bl), "--write-baseline"]
    )
    again = json.loads(bl.read_text())
    assert {e["match"] for e in again["entries"]} == {
        e["match"] for e in data["entries"]
    }
    assert all(
        e["reason"] == "justified for the test" for e in again["entries"]
    )
