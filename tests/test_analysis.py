"""`kcmc check` — the AST invariant checker (kcmc_tpu/analysis).

Two layers:

* known-bad fixtures per pass: each rule must FIRE on a minimal
  snippet exhibiting the violation (and stay quiet on the fixed
  variant) — the demonstrability contract of docs/ANALYSIS.md;
* the repo itself: a full `run_check` over the working tree must be
  clean against the checked-in baseline (no new findings, no stale or
  unjustified baseline entries) — the same gate CI applies.
"""

from __future__ import annotations

import json
import os

from kcmc_tpu.analysis.config_registry import ConfigRegistryPass
from kcmc_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    Finding,
    ModuleIndex,
    run_passes,
)
from kcmc_tpu.analysis.jit_purity import JitPurityPass
from kcmc_tpu.analysis.lock_discipline import LockDisciplinePass
from kcmc_tpu.analysis.span_registry import SpanRegistryPass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


def messages_of(findings):
    return [f.message for f in findings]


# -- pass 1: config-registry ----------------------------------------------

CONFIG_TMPL = """
import dataclasses

@dataclasses.dataclass(frozen=True)
class CorrectorConfig:
    model: str = "translation"
    batch_size: int = 32
    {extra_field}
    def __post_init__(self):
        {post_init}

SIG_NEUTRAL_FIELDS = frozenset({{"batch_size"}})
SIG_AFFECTING_FIELDS = frozenset({{{affecting}}})

def _validate_field_classification():
    pass
"""


def config_index(
    extra_field="", post_init="_validate_field_classification()",
    affecting='"model"', docs='`model` `batch_size` `mystery`',
):
    src = CONFIG_TMPL.format(
        extra_field=extra_field, post_init=post_init, affecting=affecting
    )
    return ModuleIndex.from_sources(
        {"kcmc_tpu/config.py": src}, docs={"docs/API.md": docs}
    )


def test_config_pass_clean_fixture():
    findings = ConfigRegistryPass().run(config_index())
    assert findings == []


def test_config_pass_fires_on_unclassified_field():
    idx = config_index(extra_field="mystery: int = 0")
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "'mystery' is classified in neither" in m
        for m in messages_of(findings)
    ), findings


def test_config_pass_fires_on_double_classification():
    idx = config_index(affecting='"model", "batch_size"')
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "BOTH signature registries" in m for m in messages_of(findings)
    )


def test_config_pass_fires_on_ghost_registry_entry():
    idx = config_index(affecting='"model", "removed_field"')
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "lists 'removed_field'" in m for m in messages_of(findings)
    )


def test_config_pass_fires_on_missing_validator_call():
    idx = config_index(post_init="pass")
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "_validate_field_classification" in m
        for m in messages_of(findings)
    )


def test_config_pass_fires_on_undocumented_field():
    idx = config_index(docs="`model` only")
    findings = ConfigRegistryPass().run(idx)
    assert any(
        "'batch_size' is not documented" in m
        for m in messages_of(findings)
    )


# -- pass 2: jit-purity ----------------------------------------------------

JIT_BAD = """
import jax
import numpy as np

def helper(x):
    host = np.asarray(x)          # host sync inside traced code
    print("tracing", host)
    return x * 2

@jax.jit
def traced(x):
    y = helper(x)
    y.block_until_ready()
    return float(y)
"""

JIT_CLEAN = """
import jax
import jax.numpy as jnp

def helper(x):
    return jnp.asarray(x) * 2

@jax.jit
def traced(x):
    return helper(x) + 1

def host_driver(x):
    # host-side code may sync freely: not reachable from a jit root
    import numpy as np
    return np.asarray(traced(x))
"""


def test_jit_purity_fires_on_host_sync_inside_jit():
    idx = ModuleIndex.from_sources(
        {"kcmc_tpu/backends/jax_backend.py": JIT_BAD}
    )
    findings = JitPurityPass().run(idx)
    msgs = messages_of(findings)
    assert any("np.asarray" in m for m in msgs), findings
    assert any("block_until_ready" in m for m in msgs)
    assert any("print" in m for m in msgs)
    assert any("float()" in m for m in msgs)


def test_jit_purity_quiet_on_clean_module_and_host_code():
    idx = ModuleIndex.from_sources(
        {"kcmc_tpu/backends/jax_backend.py": JIT_CLEAN}
    )
    assert JitPurityPass().run(idx) == []


def test_jit_purity_follows_jit_call_sites_not_just_decorators():
    src = """
import jax, time

def impure(x):
    return x + time.time()

fn = jax.jit(impure)
"""
    idx = ModuleIndex.from_sources({"kcmc_tpu/plans/plan.py": src})
    findings = JitPurityPass().run(idx)
    assert any("time.time" in m for m in messages_of(findings))


def test_jit_purity_ignores_modules_outside_scope():
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/reader.py": JIT_BAD})
    assert JitPurityPass().run(idx) == []


# -- pass 3: lock/thread discipline ---------------------------------------

LOCK_CYCLE = """
import threading

class Pool:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""

LOCK_CYCLE_VIA_CALL = """
import threading

class Pool:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            self.grab_b()

    def grab_b(self):
        with self._b:
            pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""

DAEMON_XLA = """
import threading

class Warmer:
    def start(self):
        threading.Thread(
            target=self._warm, name="warm", daemon=True
        ).start()

    def _warm(self):
        from kcmc_tpu.backends import get_backend
        get_backend("jax", None)
"""

DAEMON_OK = """
import threading

class Warmer:
    def start(self):
        self._t = threading.Thread(target=self._warm, daemon=False)
        self._t.start()

    def _warm(self):
        from kcmc_tpu.backends import get_backend
        get_backend("jax", None)

    def tick(self):
        threading.Thread(target=self._log, daemon=True).start()

    def _log(self):
        print("alive")
"""

SHARED_WRITE = """
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._run, daemon=False)

    def _run(self):
        self._n = self._n + 1      # worker write, no lock

    def reset(self):
        self._n = 0                # consumer write, no lock
"""


def test_lock_order_cycle_fires():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/pool.py": LOCK_CYCLE})
    findings = LockDisciplinePass().run(idx)
    assert any(
        f.rule == "lock-order" and "cycle" in f.message for f in findings
    ), findings


def test_lock_order_cycle_through_method_call_fires():
    idx = ModuleIndex.from_sources(
        {"kcmc_tpu/serve/pool.py": LOCK_CYCLE_VIA_CALL}
    )
    findings = LockDisciplinePass().run(idx)
    assert any(f.rule == "lock-order" for f in findings), findings


def test_lock_order_quiet_on_consistent_nesting():
    src = LOCK_CYCLE.replace(
        "with self._b:\n            with self._a:",
        "with self._a:\n            with self._b:",
    )
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/pool.py": src})
    assert not [
        f for f in LockDisciplinePass().run(idx) if f.rule == "lock-order"
    ]


def test_daemon_xla_thread_fires():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/warm.py": DAEMON_XLA})
    findings = LockDisciplinePass().run(idx)
    hits = [f for f in findings if f.rule == "daemon-xla"]
    assert hits and "get_backend" in hits[0].message, findings


def test_daemon_xla_quiet_on_non_daemon_and_non_xla_threads():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/warm.py": DAEMON_OK})
    assert not [
        f for f in LockDisciplinePass().run(idx) if f.rule == "daemon-xla"
    ]


def test_shared_write_without_lock_fires():
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": SHARED_WRITE})
    findings = LockDisciplinePass().run(idx)
    hits = [f for f in findings if f.rule == "shared-write"]
    assert hits and "self._n" in hits[0].message, findings


def test_shared_write_quiet_when_locked():
    src = SHARED_WRITE.replace(
        "self._n = self._n + 1      # worker write, no lock",
        "with self._lock:\n            self._n = self._n + 1",
    ).replace(
        "self._n = 0                # consumer write, no lock",
        "with self._lock:\n            self._n = 0",
    )
    idx = ModuleIndex.from_sources({"kcmc_tpu/io/counter.py": src})
    assert not [
        f for f in LockDisciplinePass().run(idx) if f.rule == "shared-write"
    ]


# -- pass 4: span-registry -------------------------------------------------

REGISTRY_SRC = """
SPAN_NAMES = frozenset({"good_span", "good_stall"})
TIMING_KEYS = frozenset({"stages_s", "total_s"})
"""

SPAN_BAD = """
def run(tracer, timer, timing):
    with tracer.span("rogue_span"):
        pass
    with timer.stall("good_stall"):
        pass
    timing["rogue_key"] = 1.0
    return timing.get("stages_s")
"""


def span_index(producer=SPAN_BAD):
    return ModuleIndex.from_sources(
        {
            "kcmc_tpu/obs/registry.py": REGISTRY_SRC,
            "kcmc_tpu/corrector.py": producer
            + "\nX = ('good_span',)\n",  # keep good_span non-stale
        }
    )


def test_span_registry_fires_on_unregistered_span_and_key():
    findings = SpanRegistryPass().run(span_index())
    msgs = messages_of(findings)
    assert any("'rogue_span'" in m for m in msgs), findings
    assert any("'rogue_key'" in m for m in msgs)
    # registered names at emission sites stay quiet
    assert not any("good_stall" in m and "not in" in m for m in msgs)


def test_span_registry_flags_stale_registry_entry():
    idx = ModuleIndex.from_sources(
        {
            "kcmc_tpu/obs/registry.py": REGISTRY_SRC,
            "kcmc_tpu/corrector.py": "def f(timer):\n"
            "    with timer.stall('good_stall'):\n        pass\n",
        }
    )
    findings = SpanRegistryPass().run(idx)
    assert any(
        "'good_span'" in f.message and "no emission site" in f.message
        for f in findings
    )


def test_span_registry_resolves_union_registries():
    reg = (
        "A = frozenset({'a_span'})\n"
        "B = frozenset({'b_span'})\n"
        "SPAN_NAMES = A | B\n"
        "TIMING_KEYS = frozenset()\n"
    )
    idx = ModuleIndex.from_sources(
        {
            "kcmc_tpu/obs/registry.py": reg,
            "kcmc_tpu/x.py": "def f(t):\n"
            "    t.instant('a_span'); t.instant('b_span')\n",
        }
    )
    assert not [
        f
        for f in SpanRegistryPass().run(idx)
        if "not in SPAN_NAMES" in f.message
    ]


# -- baseline mechanics ----------------------------------------------------


def test_baseline_splits_and_reports_stale_and_unjustified():
    f1 = Finding("r", "a.py", 3, "error", "msg one")
    f2 = Finding("r", "a.py", 9, "error", "msg two")
    bl = Baseline(
        [
            BaselineEntry("r", "a.py", "msg one", "justified"),
            BaselineEntry("r", "a.py", "gone finding", "was fixed"),
            BaselineEntry("r", "b.py", "whatever", ""),  # no reason
        ]
    )
    new, accepted = bl.split([f1, f2])
    assert [f.message for f in new] == ["msg two"]
    assert [f.message for f in accepted] == ["msg one"]
    problems = bl.problems()
    assert any("no justification" in f.message for f in problems)
    assert any("stale baseline entry" in f.message for f in problems)


def test_baseline_keys_ignore_line_numbers():
    f = Finding("r", "a.py", 123, "error", "stable message")
    e = BaselineEntry("r", "a.py", "stable message", "ok")
    assert e.matches(f)
    f2 = Finding("r", "a.py", 456, "error", "stable message")
    assert e.matches(f2)


def test_run_passes_exit_semantics():
    idx = ModuleIndex.from_sources({"kcmc_tpu/serve/warm.py": DAEMON_XLA})
    res = run_passes(idx, [LockDisciplinePass()])
    assert res.exit_code == 1
    bl = Baseline(
        [
            BaselineEntry(
                "daemon-xla",
                "kcmc_tpu/serve/warm.py",
                "daemon thread 'warm' reaches jax compile/dispatch",
                "fixture",
            )
        ]
    )
    res2 = run_passes(idx, [LockDisciplinePass()], bl)
    assert res2.exit_code == 0 and res2.baselined


# -- the repo itself is clean vs the checked-in baseline -------------------


def test_repo_is_clean_against_baseline():
    from kcmc_tpu.analysis.cli import run_check

    res = run_check(REPO_ROOT)
    assert res.new == [], "NEW findings:\n" + "\n".join(
        f.format() for f in res.new
    )
    blocking = [
        f for f in res.baseline_problems if f.severity == "error"
    ]
    assert blocking == [], "\n".join(f.format() for f in blocking)
    assert res.exit_code == 0
    # the four passes all ran
    assert set(res.passes) == {
        "config-registry",
        "jit-purity",
        "lock-discipline",
        "span-registry",
    }


def test_cli_json_roundtrip_and_report_rendering(tmp_path, capsys):
    from kcmc_tpu.analysis.cli import main as check_main
    from kcmc_tpu.obs.report import main as report_main

    rc = check_main(["--root", REPO_ROOT, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload["kind"] == "kcmc_check" and payload["ok"] is True

    art = tmp_path / "check.json"
    art.write_text(out)
    rc = report_main(str(art))
    rendered = capsys.readouterr().out
    assert rc == 0 and rendered.startswith("kcmc check:")
    assert "OK" in rendered


def test_cli_fails_on_injected_bad_snippet(tmp_path, capsys):
    """The CI negative contract: a deliberately-bad snippet anywhere in
    the package must flip `kcmc check` to a nonzero exit."""
    import shutil

    from kcmc_tpu.analysis.cli import main as check_main

    root = tmp_path / "repo"
    shutil.copytree(
        os.path.join(REPO_ROOT, "kcmc_tpu"),
        root / "kcmc_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    (root / "docs").mkdir()
    shutil.copy(
        os.path.join(REPO_ROOT, "docs", "API.md"), root / "docs" / "API.md"
    )
    assert check_main(["--root", str(root)]) == 0
    capsys.readouterr()
    bad = root / "kcmc_tpu" / "serve" / "scheduler.py"
    bad.write_text(bad.read_text() + "\n\n" + DAEMON_XLA)
    assert check_main(["--root", str(root)]) == 1
    assert "daemon-xla" in capsys.readouterr().out


def test_write_baseline_roundtrip(tmp_path, capsys):
    from kcmc_tpu.analysis.cli import main as check_main

    # a package with one daemon-xla finding and no baseline
    root = tmp_path / "repo"
    (root / "kcmc_tpu").mkdir(parents=True)
    (root / "kcmc_tpu" / "warm.py").write_text(DAEMON_XLA)
    bl = tmp_path / "bl.json"
    # missing explicit baseline path -> usage error
    assert check_main(["--root", str(root), "--baseline", str(bl)]) == 2
    bl.write_text(
        json.dumps({"kind": "kcmc_check_baseline", "entries": []})
    )
    assert check_main(["--root", str(root), "--baseline", str(bl)]) == 1
    capsys.readouterr()
    check_main(
        ["--root", str(root), "--baseline", str(bl), "--write-baseline"]
    )
    data = json.loads(bl.read_text())
    rules = {e["rule"] for e in data["entries"]}
    assert "daemon-xla" in rules, data
    # written entries carry placeholder reasons — the reviewer
    # contract is the FILL-ME-IN marker
    assert all("FILL-ME-IN" in e["reason"] for e in data["entries"])
    # rewriting keeps still-firing justified entries and drops none
    for e in data["entries"]:
        e["reason"] = "justified for the test"
    bl.write_text(json.dumps({"kind": "kcmc_check_baseline",
                              "entries": data["entries"]}))
    check_main(
        ["--root", str(root), "--baseline", str(bl), "--write-baseline"]
    )
    again = json.loads(bl.read_text())
    assert {e["match"] for e in again["entries"]} == {
        e["match"] for e in data["entries"]
    }
    assert all(
        e["reason"] == "justified for the test" for e in again["entries"]
    )
