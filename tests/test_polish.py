"""Photometric transform polish (ops/polish.py): the round-5 mechanism
that breaks the matrix models' keypoint-localization noise floor.

Bounds are pinned to ~2x the measured delivered accuracy (same policy
as test_parity.py), so a regression to the pre-polish floor fails
loudly.
"""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (256, 256)


def _stack(model, seed=11):
    return synthetic.make_drift_stack(
        n_frames=6, shape=SHAPE, model=model, max_drift=6.0, seed=seed
    )


@pytest.mark.parametrize(
    "model,bound_on",
    [
        # measured 2026-07-31 (256², seed 11): polish=1 lands
        # translation 0.013, homography 0.011, affine 0.006 px; the
        # unpolished floor is 0.03-0.08 px. bound_on ~= 2x delivered;
        # the 0.75x contrast assertion below requires polish to beat
        # the unpolished run — if it ever stops helping, this fails.
        ("translation", 0.030),
        ("homography", 0.035),
        ("affine", 0.020),
    ],
)
def test_polish_beats_keypoint_floor(model, bound_on):
    data = _stack(model)
    rel = relative_transforms(data.transforms)
    r_off = MotionCorrector(
        model=model, backend="jax", batch_size=3, transform_polish=0
    ).correct(data.stack)
    r_on = MotionCorrector(
        model=model, backend="jax", batch_size=3, transform_polish=1
    ).correct(data.stack)
    e_off = transform_rmse(r_off.transforms, rel, SHAPE)
    e_on = transform_rmse(r_on.transforms, rel, SHAPE)
    assert e_on < bound_on, f"{model} polished RMSE {e_on:.4f}"
    # the polish must measurably beat the keypoint-only estimate
    assert e_on < 0.75 * e_off, f"{model}: polish {e_on:.4f} vs off {e_off:.4f}"


def test_polish_zero_passes_is_identity():
    """transform_polish=0 must reproduce the pre-polish pipeline
    exactly (the knob gates the whole mechanism)."""
    data = _stack("translation")
    r0 = MotionCorrector(
        model="translation", backend="jax", batch_size=3, transform_polish=0
    ).correct(data.stack)
    # keypoint-only floor on this workload, measured 2026-07-31: ~0.03
    # px. This pins the UNPOLISHED path so the contrast test above
    # keeps meaning something.
    rel = relative_transforms(data.transforms)
    e0 = transform_rmse(r0.transforms, rel, SHAPE)
    assert 0.005 < e0 < 0.15, e0


def test_measure_shifts_matches_piecewise_polish():
    """ops/piecewise.correlation_polish is exactly -measure_shifts.d —
    the round-5 refactor must not have changed the piecewise path."""
    import jax.numpy as jnp

    from kcmc_tpu.ops.piecewise import correlation_polish
    from kcmc_tpu.ops.polish import measure_shifts

    rng = np.random.default_rng(7)
    template = synthetic.render_scene(rng, (128, 128), n_blobs=60)
    corrected = np.stack([
        np.roll(template, (0, 1), axis=(0, 1)),
        template + rng.normal(0, 0.01, template.shape).astype(np.float32),
    ])
    # exact=True selects the per-region estimator the piecewise polish
    # is pinned to (the default is the matrix polish's bandwidth-
    # restructured formulation — a deliberately different estimator)
    d, sig = measure_shifts(
        jnp.asarray(corrected), jnp.asarray(template), (4, 4), exact=True
    )
    delta = correlation_polish(jnp.asarray(corrected), jnp.asarray(template), (4, 4))
    np.testing.assert_array_equal(np.asarray(delta), -np.asarray(d))
    assert np.asarray(sig).any()
    # and the two estimators agree to sub-pixel scale on a plain shift
    d2, _ = measure_shifts(
        jnp.asarray(corrected), jnp.asarray(template), (4, 4)
    )
    assert np.abs(np.asarray(d2) - np.asarray(d)).max() < 0.1


def test_polish_coverage_gate_blocks_zoom_borders():
    """A strong zoom leaves a third of the warped frame outside the
    source coverage; regions whose window sees that zero border must
    be gated out of the fit (they correlate template content against
    synthetic black — measured to corrupt the 1.5x-zoom recovery by
    ~0.2 px before the gate)."""
    import jax.numpy as jnp

    from kcmc_tpu.ops.polish import polish_transforms

    rng = np.random.default_rng(3)
    template = synthetic.render_scene(rng, SHAPE, n_blobs=200)
    # identity-corrected frame, but claim a 1.5x-zoom transform: every
    # border region's window coverage drops below the gate, leaving
    # too few regions for a similarity update -> transform unchanged.
    s = 1.5
    c = (SHAPE[0] - 1) / 2.0
    M = np.array(
        [[s, 0, c - s * c], [0, s, c - s * c], [0, 0, 1]], np.float32
    )
    corrected = np.where(
        np.hypot(*np.mgrid[0:SHAPE[0], 0:SHAPE[1]] - c) < SHAPE[0] / 3,
        template, 0.0,
    ).astype(np.float32)[None]
    out = polish_transforms(
        jnp.asarray(corrected), jnp.asarray(template),
        jnp.asarray(M[None]), "homography",
    )
    # homography needs >= 8 significant regions; the gate leaves at
    # most the 4 central ones -> no update
    np.testing.assert_array_equal(np.asarray(out)[0], M)


def test_polish_cross_backend_parity():
    """The numpy mirror implements the same measurement and fit: the
    two backends' polished transforms agree far tighter than their
    independent RANSAC draws ever did."""
    data = _stack("affine", seed=5)
    rj = MotionCorrector(
        model="affine", backend="jax", batch_size=3
    ).correct(data.stack)
    rn = MotionCorrector(
        model="affine", backend="numpy", batch_size=3
    ).correct(data.stack)
    cross = transform_rmse(rj.transforms, rn.transforms, SHAPE)
    assert cross < 0.01, cross


def test_rescued_frames_get_polished():
    """Frames that exceed a bounded warp kernel's motion bound skip the
    in-program polish (their warped output is zeroed); the host rescue
    path must apply the same polish so exported transforms and pixels
    match the unbounded-warp reference run."""
    import warnings

    data = synthetic.make_drift_stack(
        n_frames=6, shape=(192, 192), model="rigid",
        max_drift=4.0, seed=13,
    )
    ref = MotionCorrector(
        model="rigid", backend="jax", batch_size=3, warp="jnp"
    ).correct(data.stack)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = MotionCorrector(
            model="rigid", backend="jax", batch_size=3,
            # every rotated frame exceeds a zero shear bound
            warp="separable", max_shear_px=0, rescue_escalate=False,
        ).correct(data.stack)
    assert np.asarray(res.diagnostics["warp_rescued"]).any()
    cross = transform_rmse(res.transforms, ref.transforms, (192, 192))
    assert cross < 0.005, cross
