"""Chaos suite: deterministic fault injection, retry/backoff, and the
graceful-degradation ladder (retry -> numpy failover -> mark-failed +
interpolate_failed rescue), plus checkpoint corrupt-part quarantine."""

import traceback
import warnings

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.faults import (
    FatalFaultError,
    FaultPlan,
    RetryPolicy,
    TransientFaultError,
    classify_transient,
)
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (96, 96)
# near-zero backoff: chaos tests exercise the retry LOGIC, not the sleeps
FAST_RETRY = dict(retry_backoff_s=1e-4, retry_backoff_max_s=2e-4)


@pytest.fixture(scope="module")
def data():
    return synthetic.make_drift_stack(
        n_frames=12, shape=SHAPE, model="translation", max_drift=4.0, seed=7
    )


# -- spec grammar / plan mechanics ----------------------------------------


def test_fault_spec_grammar():
    plan = FaultPlan.from_spec(
        "io_read:step=3:raise, device:step=7:transient:times=2, "
        "checkpoint:corrupt_part=1"
    )
    io, dev, ck = plan.clauses
    assert (io.surface, io.step, io.action, io.times) == ("io_read", 3, "fatal", 1)
    assert (dev.surface, dev.step, dev.action, dev.times) == (
        "device", 7, "transient", 2,
    )
    assert (ck.surface, ck.corrupt_part) == ("checkpoint", 1)


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault surface"):
        FaultPlan.from_spec("gpu:step=1")
    with pytest.raises(ValueError, match="unknown fault-clause key"):
        FaultPlan.from_spec("device:wat=1")
    with pytest.raises(ValueError, match="corrupt_part"):
        FaultPlan.from_spec("checkpoint:step=1")
    with pytest.raises(ValueError, match="checkpoint surface only"):
        FaultPlan.from_spec("device:corrupt_part=0")
    with pytest.raises(ValueError, match="no clauses"):
        FaultPlan.from_spec("  ,  ")


def test_fault_plan_step_and_times_semantics():
    plan = FaultPlan.from_spec("device:step=1:times=2")
    plan.maybe_fail("device", 0)  # wrong step: no fault
    with pytest.raises(TransientFaultError):
        plan.maybe_fail("device", 1)
    with pytest.raises(TransientFaultError):
        plan.maybe_fail("device", 1)
    plan.maybe_fail("device", 1)  # clause spent after times=2 attempts
    assert plan.injected == 2
    assert [plan.op_index("device") for _ in range(3)] == [0, 1, 2]
    assert plan.op_index("io_read") == 0  # per-surface counters


def test_object_surface_grammar():
    plan = FaultPlan.from_spec(
        "object:step=0:drop, object:step=1:truncate, object:step=2:flip, "
        "object:step=3:throttle, object:step=4:stall=0.25"
    )
    drop, trunc, flip, thr, stall = plan.clauses
    # drop is an alias of transient: a dropped connection classifies
    # transient and retries like any wire fault
    assert (drop.surface, drop.action) == ("object", "transient")
    assert trunc.action == "truncate"
    assert flip.action == "flip"
    assert thr.action == "throttle"
    assert stall.stall == pytest.approx(0.25)


def test_object_actions_rejected_on_other_surfaces():
    for act in ("truncate", "flip", "throttle"):
        with pytest.raises(ValueError, match="object surface only"):
            FaultPlan.from_spec(f"io_read:step=0:{act}")


def test_take_action_consumes_matching_clause():
    plan = FaultPlan.from_spec("object:step=1:flip:times=2")
    assert plan.take_action("object", 0) is None  # wrong step
    assert plan.take_action("object", 1) == "flip"
    assert plan.take_action("object", 1) == "flip"
    assert plan.take_action("object", 1) is None  # spent after times=2
    assert plan.injected == 2
    # raising clauses stay on the maybe_fail path: take_action returns
    # their action string for the client to raise itself
    plan2 = FaultPlan.from_spec("object:step=0:drop")
    assert plan2.take_action("object", 0) == "transient"


def test_object_stall_rides_take_stall():
    plan = FaultPlan.from_spec("object:step=2:stall=0.5")
    assert plan.take_stall("object", 1) == 0.0
    assert plan.take_stall("object", 2) == pytest.approx(0.5)
    assert plan.take_stall("object", 2) == 0.0  # consumed


def test_default_io_retry_policy_single_construction_point():
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.utils.faults import default_io_retry_policy

    # cfg-less: standalone readers get the stock policy shape
    p = default_io_retry_policy(None)
    assert p.attempts == 3 and p.deadline_s is None
    # cfg-driven: knobs + the per-attempt deadline cap flow through,
    # and the seed offset keeps per-surface jitter streams distinct
    cfg = CorrectorConfig(
        retry_attempts=5, retry_backoff_s=0.01, retry_backoff_max_s=0.1,
        retry_jitter=0.5, seed=11, object_timeout_s=7.5,
    )
    p2 = default_io_retry_policy(cfg, seed_offset=2)
    assert (p2.attempts, p2.backoff_s, p2.backoff_max_s) == (5, 0.01, 0.1)
    assert p2.seed == 13 and p2.deadline_s == 7.5
    # retry disabled -> None, same contract the corrector relied on
    assert default_io_retry_policy(cfg.replace(retry_attempts=1)) is None


def test_config_validates_fault_plan_eagerly():
    with pytest.raises(ValueError, match="unknown fault surface"):
        MotionCorrector(model="translation", fault_plan="nope:1")


def test_classify_transient_split():
    assert classify_transient(TransientFaultError("x"))
    assert classify_transient(OSError("read failed"))
    assert classify_transient(TimeoutError("slow nfs"))
    assert classify_transient(ConnectionResetError("peer"))
    assert not classify_transient(FatalFaultError("x"))
    assert not classify_transient(ValueError("bad shape"))
    assert not classify_transient(KeyboardInterrupt())
    # permanent OS conditions are NOT retried: a deleted input or
    # revoked credentials cannot be outlived by backoff
    assert not classify_transient(FileNotFoundError("gone.tif"))
    assert not classify_transient(PermissionError("revoked"))
    assert not classify_transient(IsADirectoryError("dir"))

    class FakeXla(Exception):
        pass

    # declared device types are transient only with a status marker
    assert classify_transient(FakeXla("UNAVAILABLE: link down"), (FakeXla,))
    assert classify_transient(FakeXla("RESOURCE_EXHAUSTED: hbm"), (FakeXla,))
    assert not classify_transient(FakeXla("rank mismatch"), (FakeXla,))


def test_retry_policy_backoff_bounds():
    p = RetryPolicy(attempts=5, backoff_s=0.1, backoff_max_s=0.5,
                    jitter=0.5, seed=1)
    for k in range(6):
        base = min(0.1 * 2.0 ** k, 0.5)
        d = p.delay(k)
        assert 0.5 * base <= d <= 1.5 * base
    p0 = RetryPolicy(jitter=0.0, backoff_s=0.1, backoff_max_s=10.0)
    assert p0.delay(0) == pytest.approx(0.1)
    assert p0.delay(3) == pytest.approx(0.8)


# -- device surface: retry / failover / mark-failed ladder -----------------


@pytest.mark.slow
def test_transient_device_fault_absorbed_bit_identical(data):
    kw = dict(model="translation", backend="jax", batch_size=4, **FAST_RETRY)
    clean = MotionCorrector(**kw).correct(data.stack)
    mc = MotionCorrector(**kw, fault_plan="device:step=1:transient:times=2")
    res = mc.correct(data.stack)
    np.testing.assert_array_equal(res.transforms, clean.transforms)
    np.testing.assert_array_equal(res.corrected, clean.corrected)
    rb = res.robustness
    assert rb["device_retries"] == 2
    assert rb["faults_injected"] == 2
    assert rb["backend_failovers"] == 0
    assert rb["failed_frames"] == 0


@pytest.mark.slow
def test_device_fatal_fault_aborts(data):
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        fault_plan="device:step=0:fatal", **FAST_RETRY,
    )
    with pytest.raises(FatalFaultError):
        mc.correct(data.stack)


@pytest.mark.slow
def test_permanent_device_failure_fails_over_to_numpy(data):
    kw = dict(model="translation", backend="jax", batch_size=4, **FAST_RETRY)
    mc = MotionCorrector(**kw, fault_plan="device:step=1:always:transient")
    with pytest.warns(RuntimeWarning, match="failover backend"):
        res = mc.correct(data.stack)
    rb = res.robustness
    assert rb["device_retries"] == 2  # retries exhausted first
    assert rb["backend_failovers"] == 1
    assert rb["failed_frames"] == 0
    assert "frames_failed" not in res.diagnostics
    # the failed-over batch still registers (numpy = same algorithm)
    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), SHAPE
    )
    assert rmse < 0.6
    assert np.isfinite(res.corrected).all()


@pytest.mark.slow
def test_exhausted_ladder_marks_frames_failed_and_rescues(data):
    kw = dict(model="translation", backend="jax", batch_size=4, **FAST_RETRY)
    clean = MotionCorrector(**kw).correct(data.stack)
    mc = MotionCorrector(
        **kw,
        fault_plan="device:step=1:always:transient, failover:always:transient",
    )
    with pytest.warns(RuntimeWarning, match="marking its"):
        res = mc.correct(data.stack)
    rb = res.robustness
    assert rb["failed_frames"] == 4
    assert rb["rescued_frames"] == 4
    mask = res.diagnostics["frames_failed"]
    assert mask.shape == (12,)
    assert mask[4:8].all() and mask.sum() == 4
    assert (np.asarray(res.diagnostics["n_inliers"])[4:8] == 0).all()
    # failed frames were never registered: warp_ok stays False (rolling
    # templates must not blend them) and they are not "rescued" frames
    assert not res.diagnostics["warp_ok"][4:8].any()
    assert res.diagnostics["warp_ok"][~mask].all()
    assert not res.diagnostics["warp_rescued"].any()
    # good frames are untouched bit-for-bit; failed frames' transforms
    # are trajectory-interpolated (finite, near the drift path)
    np.testing.assert_array_equal(res.transforms[~mask], clean.transforms[~mask])
    assert np.isfinite(res.transforms).all()
    rmse = transform_rmse(
        res.transforms, relative_transforms(data.transforms), SHAPE
    )
    assert rmse < 3.0  # interpolation across the gap stays near the walk


@pytest.mark.slow
def test_retry_disabled_transient_raises(data):
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        retry_attempts=1, failover_backend=None, degrade_mark_failed=False,
        fault_plan="device:step=0:transient",
    )
    with pytest.raises(TransientFaultError):
        mc.correct(data.stack)


@pytest.mark.slow
def test_env_var_activates_fault_plan(monkeypatch, data):
    monkeypatch.setenv("KCMC_FAULT_PLAN", "device:step=0:fatal")
    mc = MotionCorrector(model="translation", backend="jax", batch_size=4)
    with pytest.raises(FatalFaultError):
        mc.correct(data.stack)


@pytest.mark.slow
def test_happy_path_reports_clean(data):
    res = MotionCorrector(
        model="translation", backend="jax", batch_size=4
    ).correct(data.stack)
    rb = res.robustness
    assert rb is not None
    assert rb["io_retries"] == 0
    assert rb["device_retries"] == 0
    assert rb["backend_failovers"] == 0
    assert rb["failed_frames"] == 0
    assert rb["quarantined_parts"] == []
    assert "frames_failed" not in res.diagnostics


# -- io_read surface -------------------------------------------------------


def _write_tiff(tmp_path, data, name="in.tif"):
    from kcmc_tpu.io.tiff import write_stack

    path = tmp_path / name
    write_stack(path, data.stack)
    return path


@pytest.mark.slow
def test_io_read_fault_retried(tmp_path, data):
    src = _write_tiff(tmp_path, data)
    kw = dict(model="translation", backend="jax", batch_size=4, **FAST_RETRY)
    clean = MotionCorrector(**kw).correct_file(str(src))
    mc = MotionCorrector(**kw, fault_plan="io_read:step=0:transient:times=2")
    res = mc.correct_file(str(src))
    np.testing.assert_array_equal(res.transforms, clean.transforms)
    assert res.robustness["io_retries"] == 2


@pytest.mark.slow
def test_io_read_fatal_fault_aborts(tmp_path, data):
    src = _write_tiff(tmp_path, data)
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        fault_plan="io_read:step=0:raise", **FAST_RETRY,
    )
    with pytest.raises(FatalFaultError):
        mc.correct_file(str(src))


def test_loader_decode_error_keeps_producer_traceback():
    from kcmc_tpu.io import ChunkedStackLoader

    class BadSource:
        def __len__(self):
            return 4

        def __getitem__(self, s):
            raise ValueError("decode exploded")

    with pytest.raises(ValueError, match="decode exploded") as ei:
        list(iter(ChunkedStackLoader(BadSource(), chunk_size=2)))
    names = [
        f.name for f in traceback.extract_tb(ei.value.__traceback__)
    ]
    assert "_read_raw" in names  # producer-side frames preserved


# -- checkpoint surface ----------------------------------------------------


def test_corrupt_checkpoint_meta_warns_quarantines_restarts(tmp_path):
    from kcmc_tpu.utils.checkpoint import load_stream_checkpoint

    p = tmp_path / "run.ckpt.npz"
    p.write_bytes(b"definitely not an npz")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        assert load_stream_checkpoint(str(p)) is None
    assert (tmp_path / "run.ckpt.npz.corrupt").exists()
    assert not p.exists()
    # absent checkpoint stays silent (fresh run, nothing to report)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert load_stream_checkpoint(str(tmp_path / "absent.npz")) is None


@pytest.mark.slow
def test_checkpoint_corrupt_part_quarantined_and_resumed(tmp_path):
    """A resume over a checkpoint with one corrupted part must
    quarantine the part, rewind to the last good chunk, recompute only
    the lost frames, and end byte-identical to the uninterrupted run."""
    from kcmc_tpu.io.tiff import write_stack

    data = synthetic.make_drift_stack(
        n_frames=32, shape=SHAPE, model="translation", max_drift=4.0, seed=11
    )
    u16 = np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, u16)
    out = tmp_path / "out.tif"
    ckpt = tmp_path / "run.ckpt.npz"
    kw = dict(model="translation", backend="jax", batch_size=4, **FAST_RETRY)

    res_a = MotionCorrector(**kw).correct_file(
        str(src), output=str(out), checkpoint=str(ckpt),
        checkpoint_every=8, chunk_size=8,
    )
    clean_bytes = out.read_bytes()
    part1 = tmp_path / "run.ckpt.npz.part00001.npz"
    assert part1.exists()  # saves at frames 8/16/24 + the final save

    mc = MotionCorrector(**kw, fault_plan="checkpoint:corrupt_part=1")
    with pytest.warns(RuntimeWarning, match="last good chunk"):
        res_b = mc.correct_file(
            str(src), output=str(out), checkpoint=str(ckpt),
            checkpoint_every=8, chunk_size=8,
        )
    rb = res_b.robustness
    assert len(rb["quarantined_parts"]) == 1
    assert (tmp_path / "run.ckpt.npz.part00001.npz.corrupt").exists()
    # rewound to the part-0 save point (frame 8), recomputed the rest
    assert res_b.timing["restored_frames"] == 8
    np.testing.assert_array_equal(res_b.transforms, res_a.transforms)
    assert out.read_bytes() == clean_bytes


@pytest.mark.slow
def test_failed_frames_persist_across_checkpoint_resume(tmp_path, data):
    """Frames the ladder marked failed before a kill must keep their
    failed status (and the interpolate_failed rescue) when the run is
    restored from the checkpoint."""
    src = _write_tiff(tmp_path, data)
    out = tmp_path / "out.tif"
    ckpt = tmp_path / "run.ckpt.npz"
    kw = dict(model="translation", backend="jax", batch_size=4, **FAST_RETRY)
    args = dict(
        output=str(out), checkpoint=str(ckpt), checkpoint_every=4,
        chunk_size=4,
    )
    mc = MotionCorrector(
        **kw,
        fault_plan="device:step=1:always:transient, failover:always:transient",
    )
    with pytest.warns(RuntimeWarning, match="marking its"):
        res1 = mc.correct_file(str(src), **args)
    assert res1.robustness["failed_frames"] == 4

    # Rerun with identical arguments and no faults: everything restores
    # from the checkpoint, and the failed-frame record must survive.
    res2 = MotionCorrector(**kw).correct_file(str(src), **args)
    assert res2.timing["restored_frames"] == 12
    assert res2.robustness["failed_frames"] == 4
    assert res2.robustness["rescued_frames"] == 4
    np.testing.assert_array_equal(
        res2.diagnostics["frames_failed"], res1.diagnostics["frames_failed"]
    )
    np.testing.assert_array_equal(res2.transforms, res1.transforms)


@pytest.mark.slow
def test_cli_inject_faults_reports_robustness(tmp_path, data):
    import json
    import subprocess
    import sys

    src = _write_tiff(tmp_path, data)
    tpath = tmp_path / "t.npz"
    env_script = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import warnings; warnings.simplefilter('ignore');"
        "import kcmc_tpu.__main__ as m; import sys; sys.exit(m.main(%r))"
    )
    args = [
        "correct", str(src), "--transforms", str(tpath),
        "--model", "translation", "--batch-size", "4",
        "--inject-faults", "device:step=1:transient:times=1",
    ]
    proc = subprocess.run(
        [sys.executable, "-c", env_script % (args,)],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["robustness"]["device_retries"] == 1
    assert summary["robustness"]["faults_injected"] == 1
    saved = np.load(tpath)
    rb = json.loads(str(saved["robustness"]))
    assert rb["device_retries"] == 1
