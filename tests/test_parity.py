"""Cross-backend parity: the judged accuracy metric (BASELINE.md).

Both backends implement the same algorithm with the same pattern
constants; the recovered transforms must agree to registration accuracy
(transform-RMSE level — RANSAC sampling differs by PRNG, so parity is
statistical, not bitwise).
"""

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (160, 160)


@pytest.mark.parametrize(
    "model", ["translation", "rigid", "similarity", "affine", "homography"]
)
def test_jax_numpy_transform_parity(model):
    data = synthetic.make_drift_stack(
        n_frames=6, shape=SHAPE, model=model, max_drift=6.0, seed=21
    )
    rj = MotionCorrector(model=model, backend="jax", batch_size=3).correct(data.stack)
    rn = MotionCorrector(model=model, backend="numpy", batch_size=3).correct(data.stack)
    rel = relative_transforms(data.transforms)
    rmse_j = transform_rmse(rj.transforms, rel, SHAPE)
    rmse_n = transform_rmse(rn.transforms, rel, SHAPE)
    cross = transform_rmse(rj.transforms, rn.transforms, SHAPE)
    # ABSOLUTE bounds pinned to ~2x the delivered accuracy (VERDICT r2
    # #3: a self-scaling bound lets a correlated regression in both
    # backends inflate its own tolerance). Measured at these seeds
    # with the round-5 photometric transform polish (2026-07-31):
    # per-backend ground-truth RMSE 0.012-0.020 px (homography worst),
    # cross-backend 0.0001-0.0098 px — the polish is deterministic
    # (unlike the backends' independent RANSAC draws), so both
    # backends now converge to nearly the same photometric optimum.
    # 0.05/0.03 keep ~2.5-3x headroom while failing any regression to
    # the pre-polish keypoint-noise floor (0.05-0.14 px).
    assert rmse_j < 0.05, f"jax {model} RMSE {rmse_j:.3f}"
    assert rmse_n < 0.05, f"numpy {model} RMSE {rmse_n:.3f}"
    assert cross < 0.03, f"cross-backend {model} RMSE {cross:.3f}"


def test_descriptor_bit_parity():
    """Descriptors from the two backends agree bit-for-bit on shared
    keypoints (same pattern constants, same sampling math)."""
    import jax.numpy as jnp

    from kcmc_tpu.backends import _np_kernels as K
    from kcmc_tpu.ops.describe import describe_keypoints
    from kcmc_tpu.ops.detect import detect_keypoints

    rng = np.random.default_rng(3)
    img = synthetic.render_scene(rng, (128, 128), n_blobs=50)

    kj = detect_keypoints(jnp.asarray(img), max_keypoints=64)
    xyn, scoren, validn = K.detect_keypoints(img, max_keypoints=64)

    # keypoint sets must match (same response, same NMS, same top-k)
    nj = int(np.asarray(kj.valid).sum())
    nn = int(validn.sum())
    assert nj == nn
    np.testing.assert_allclose(
        np.asarray(kj.xy)[: nj], xyn[: nn], atol=1e-3
    )

    dj = np.asarray(describe_keypoints(jnp.asarray(img), kj, oriented=False))
    dn = K.describe_keypoints(img, xyn, validn, oriented=False)
    mismatch_bits = np.unpackbits(
        (dj[:nj] ^ dn[:nj]).view(np.uint8)
    ).sum() / max(nj, 1)
    assert mismatch_bits < 4, f"avg descriptor bit mismatch {mismatch_bits:.2f}"


def test_rigid3d_parity():
    """Config 5 cross-backend parity: volumetric rigid registration on
    the numpy backend's 3D pipeline vs the jax backend."""
    data = synthetic.make_drift_stack_3d(
        n_frames=4, shape=(24, 96, 96), max_drift=3.0, seed=13
    )
    shape = data.stack.shape[1:]
    rj = MotionCorrector(model="rigid3d", backend="jax", batch_size=2).correct(data.stack)
    rn = MotionCorrector(model="rigid3d", backend="numpy", batch_size=2).correct(data.stack)
    rel = relative_transforms(data.transforms)
    rmse_j = transform_rmse(rj.transforms, rel, shape)
    rmse_n = transform_rmse(rn.transforms, rel, shape)
    cross = transform_rmse(rj.transforms, rn.transforms, shape)
    # Absolute bounds at ~2-3x delivered (measured 2026-07-31: both
    # backends 0.089 px, cross 0.000) — see the 2D parity test's note.
    assert rmse_j < 0.3, f"jax rigid3d RMSE {rmse_j:.3f}"
    assert rmse_n < 0.3, f"numpy rigid3d RMSE {rmse_n:.3f}"
    assert cross < 0.25, f"cross-backend rigid3d RMSE {cross:.3f}"


def test_descriptor_bit_parity_3d():
    """3D descriptors agree closely across backends on shared keypoints."""
    import jax.numpy as jnp

    from kcmc_tpu.backends import _np_kernels as K
    from kcmc_tpu.ops.describe3d import describe_keypoints_3d
    from kcmc_tpu.ops.detect3d import detect_keypoints_3d

    rng = np.random.default_rng(5)
    vol = synthetic.render_scene(rng, (20, 80, 80), n_blobs=60)

    kj = detect_keypoints_3d(jnp.asarray(vol), max_keypoints=48, border=10)
    xyzn, scoren, validn = K.detect_keypoints_3d(vol, max_keypoints=48, border=10)

    nj = int(np.asarray(kj.valid).sum())
    nn = int(validn.sum())
    assert abs(nj - nn) <= 2, f"keypoint count mismatch: jax {nj} vs numpy {nn}"
    n = min(nj, nn)
    np.testing.assert_allclose(np.asarray(kj.xy)[:n], xyzn[:n], atol=2e-2)

    dj = np.asarray(describe_keypoints_3d(jnp.asarray(vol), kj, blur_sigma=2.0))
    dn = K.describe_keypoints_3d(vol, xyzn, validn, blur_sigma=2.0)
    mismatch_bits = np.unpackbits(
        (dj[:n] ^ dn[:n]).view(np.uint8)
    ).sum() / max(n, 1)
    assert mismatch_bits < 8, f"avg 3D descriptor bit mismatch {mismatch_bits:.2f}"


def test_piecewise_parity_and_recovery():
    data = synthetic.make_piecewise_stack(
        n_frames=4, shape=(160, 160), grid=(8, 8), max_disp=5.0, seed=9
    )
    from kcmc_tpu.utils.metrics import field_rmse

    rj = MotionCorrector(model="piecewise", backend="jax", batch_size=2).correct(data.stack)
    rn = MotionCorrector(model="piecewise", backend="numpy", batch_size=2).correct(data.stack)
    assert rj.fields.shape == (4, 8, 8, 2)
    # frame 0 is the reference: gt fields are absolute, est fields are
    # relative to frame 0's field — compare field differences.
    gt_rel = data.fields - data.fields[0]
    ej = field_rmse(rj.fields, gt_rel)
    en = field_rmse(rn.fields, gt_rel)
    cross = field_rmse(rj.fields, rn.fields)
    # Absolute bounds (measured 2026-07-31, round 4, with the
    # correlation polish: both backends 0.26 px field RMSE on this
    # 160²/5px-disp workload, cross 0.011 px). 0.4 fails a ~1.5x
    # ground-truth regression; the cross bound keeps ~5x headroom for
    # patch-level RANSAC noise while staying 3x tighter than before.
    assert ej < 0.4, f"jax piecewise field RMSE {ej:.3f}"
    assert en < 0.4, f"numpy piecewise field RMSE {en:.3f}"
    assert cross < 0.05, f"cross-backend field RMSE {cross:.3f}"
