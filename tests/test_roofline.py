"""First-order roofline attribution (analysis/roofline.py, PR 18).

Contracts under test:

* the peaks catalogue is total per platform class and every resource
  the judge can name has an operator-facing label;
* `stage_costs` covers the full stage vocabulary (the
  `utils.profiling.stage_breakdown` rows plus the upload/download
  transfer pseudo-stages), scales linearly in batch, and prices the
  piecewise hypothesis field and the pyramid octaves;
* `judge` names a binding resource with a fraction of peak in (0, 1]
  (clamped at the roof), prices matrix-class work against the compute
  peak and pixel work against the vector peak, and only prices the
  interconnect when gather bytes are declared;
* `achieved_rates` (the --profile columns) skips unmeasured and
  non-positive stages instead of emitting garbage rates;
* `PROGRAM_VOCAB` covers every program literal the plan machinery
  routes today (the static half of the traceflow `roofline-vocab`
  rule, which keeps the table total going forward).
"""

from __future__ import annotations

import pytest

from kcmc_tpu.analysis.roofline import (
    PEAKS,
    PROGRAM_VOCAB,
    RESOURCE_NAMES,
    achieved_rates,
    detect_platform,
    judge,
    stage_costs,
    total_costs,
)

STAGES = (
    "upload", "detect", "describe", "match", "consensus",
    "full (+warp)", "download",
)


def test_peaks_table_total_and_labeled():
    for platform, row in PEAKS.items():
        assert row["label"], platform
        for res in RESOURCE_NAMES:
            assert res in row, (platform, res)
        assert row["compute"] > 0 and row["vector"] > 0
        assert row["memory"] > 0 and row["link"] > 0


def test_stage_costs_cover_the_stage_vocabulary():
    costs = stage_costs("translation", (64, 64), 8)
    assert set(costs) == set(STAGES)
    for stage, c in costs.items():
        for key in ("flops", "mem_bytes", "link_bytes"):
            assert c[key] >= 0.0, (stage, key)
    # transfers are the only link crossings in the model
    assert costs["upload"]["link_bytes"] > 0
    assert costs["download"]["link_bytes"] > 0
    assert costs["match"]["link_bytes"] == 0.0


def test_stage_costs_scale_linearly_with_batch():
    t1 = total_costs(stage_costs("affine", (128, 128), 8))
    t2 = total_costs(stage_costs("affine", (128, 128), 16))
    for key in ("flops", "mem_bytes", "link_bytes"):
        assert t2[key] == pytest.approx(2.0 * t1[key], rel=1e-9)


def test_stage_costs_price_piecewise_field_and_pyramid_octaves():
    base = stage_costs("affine", (128, 128), 8)
    piece = stage_costs("piecewise", (128, 128), 8)
    assert piece["consensus"]["flops"] > base["consensus"]["flops"]
    pyr = stage_costs("similarity", (128, 128), 8, n_octaves=3)
    flat = stage_costs("similarity", (128, 128), 8, n_octaves=1)
    assert pyr["detect"]["flops"] > flat["detect"]["flops"]


def test_registration_only_drops_download_frames():
    full = stage_costs("translation", (64, 64), 8)
    reg = stage_costs("translation", (64, 64), 8, emit_frames=False)
    assert reg["download"]["link_bytes"] < full["download"]["link_bytes"]


def test_judge_names_a_binding_resource():
    costs = stage_costs("affine", (512, 512), 32, max_keypoints=1024)
    v = judge(costs, measured_s=0.5, platform="tpu-v5e")
    assert v["binding"] in RESOURCE_NAMES
    assert v["binding_label"] == RESOURCE_NAMES[v["binding"]]
    assert 0.0 < v["fraction_of_peak"] <= 1.0
    assert v["platform_label"] == PEAKS["tpu-v5e"]["label"]
    assert set(v["time_at_peak_s"]) <= set(RESOURCE_NAMES)


def test_judge_fraction_clamps_at_the_roof():
    costs = {"detect": {"flops": 1e15, "mem_bytes": 0.0, "link_bytes": 0.0}}
    v = judge(costs, measured_s=1e-9, platform="cpu")
    assert v["fraction_of_peak"] == 1.0


def test_judge_classifies_synthetic_bound_shapes():
    mem = {"detect": {"flops": 1.0, "mem_bytes": 1e12, "link_bytes": 0.0}}
    assert judge(mem, 100.0, "cpu")["binding"] == "memory"
    # match/consensus flops price against the compute (MXU) peak,
    # everything else against the vector peak
    mxu = {"match": {"flops": 1e15, "mem_bytes": 0.0, "link_bytes": 0.0}}
    assert judge(mxu, 100.0, "tpu-v5e")["binding"] == "compute"
    vec = {"detect": {"flops": 1e15, "mem_bytes": 0.0, "link_bytes": 0.0}}
    assert judge(vec, 100.0, "tpu-v5e")["binding"] == "vector"
    staged = {"upload": {"flops": 0.0, "mem_bytes": 0.0, "link_bytes": 1e12}}
    assert judge(staged, 100.0, "tpu-v5e")["binding"] == "link"


def test_judge_interconnect_needs_declared_gather_bytes():
    costs = {"detect": {"flops": 1.0, "mem_bytes": 1.0, "link_bytes": 0.0}}
    a = judge(costs, 1.0, "tpu-v5e")
    assert "interconnect" not in a["time_at_peak_s"]
    b = judge(costs, 1.0, "tpu-v5e", n_devices=4, gathered_bytes=1e12)
    assert "interconnect" in b["time_at_peak_s"]
    assert b["binding"] == "interconnect"
    # platforms without an interconnect row never price it
    c = judge(costs, 1.0, "cpu", n_devices=4, gathered_bytes=1e12)
    assert "interconnect" not in c["time_at_peak_s"]


def test_judge_divides_sharded_work_not_the_host_link():
    costs = {
        "detect": {"flops": 1e12, "mem_bytes": 1e10, "link_bytes": 1e9}
    }
    one = judge(costs, 1.0, "tpu-v5e")["time_at_peak_s"]
    eight = judge(costs, 1.0, "tpu-v5e", n_devices=8)["time_at_peak_s"]
    assert eight["vector"] == pytest.approx(one["vector"] / 8, rel=1e-3)
    assert eight["link"] == pytest.approx(one["link"], rel=1e-9)


def test_achieved_rates_skip_unmeasured_stages():
    costs = stage_costs("translation", (64, 64), 8)
    rates = achieved_rates(
        costs,
        {"detect": 0.01, "describe": -0.002, "match": 0.0, "nosuch": 0.5},
    )
    assert set(rates) == {"detect"}
    assert rates["detect"]["achieved_gflops"] > 0
    assert rates["detect"]["achieved_gbs"] > 0


def test_detect_platform_is_cpu_on_this_host():
    assert detect_platform() == "cpu"
    assert detect_platform() in PEAKS


def test_program_vocab_covers_the_plan_programs():
    for prog in (
        "register", "reference", "reference_pyramid", "update_reference",
        "quality", "cast", "apply",
    ):
        assert prog in PROGRAM_VOCAB, prog
        assert PROGRAM_VOCAB[prog]
