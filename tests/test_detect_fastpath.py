"""Tile-aligned fast-path equivalence for fixed-K keypoint selection
(ADVICE r5): ops/detect._select_keypoints claims its round-5
tile-level masking fast path produces IDENTICAL results to the general
pixel-masked path — same tile maxima, same argmax tie rule, same peak.
These tests enforce the claim mechanically through the `_force_general`
seam, in 2D and 3D, for aligned and deliberately misaligned geometry."""

import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.detect import _select_keypoints
from kcmc_tpu.ops.detect3d import _select_keypoints_3d


def _fields_2d(rng, H, W):
    resp = rng.random((H, W), dtype=np.float32)
    mask = rng.random((H, W)) < 1 / 16  # sparse "local maxima"
    nms = np.where(mask, resp, -np.inf).astype(np.float32)
    ox = rng.uniform(-0.5, 0.5, (H, W)).astype(np.float32)
    oy = rng.uniform(-0.5, 0.5, (H, W)).astype(np.float32)
    return jnp.asarray(nms), jnp.asarray(ox), jnp.asarray(oy)


def _assert_same_keypoints(a, b):
    np.testing.assert_array_equal(np.asarray(a.valid), np.asarray(b.valid))
    np.testing.assert_array_equal(np.asarray(a.xy), np.asarray(b.xy))
    np.testing.assert_array_equal(np.asarray(a.score), np.asarray(b.score))


@pytest.mark.parametrize(
    "hw,border,tile",
    [
        ((128, 128), 16, 8),  # aligned everywhere: fast path engages
        ((128, 96), 8, 8),  # aligned, non-square
        ((128, 128), 16, 4),  # aligned at a finer candidate tile
    ],
)
def test_2d_fast_path_identical_to_general(rng, hw, border, tile):
    nms, ox, oy = _fields_2d(rng, *hw)
    fast = _select_keypoints(nms, ox, oy, 64, 1e-4, border, cand_tile=tile)
    gen = _select_keypoints(
        nms, ox, oy, 64, 1e-4, border, cand_tile=tile, _force_general=True
    )
    _assert_same_keypoints(fast, gen)


@pytest.mark.parametrize(
    "hw,border",
    [
        ((128, 128), 10),  # misaligned border -> general path anyway
        ((120, 104), 16),  # misaligned frame size
    ],
)
def test_2d_misaligned_geometry_consistent_and_border_respected(
    rng, hw, border
):
    nms, ox, oy = _fields_2d(rng, *hw)
    a = _select_keypoints(nms, ox, oy, 64, 1e-4, border)
    b = _select_keypoints(nms, ox, oy, 64, 1e-4, border, _force_general=True)
    _assert_same_keypoints(a, b)
    v = np.asarray(a.valid)
    assert v.any()
    xy = np.asarray(a.xy)[v]
    H, W = hw
    # integer peak positions respect the border; subpixel offsets move
    # at most 0.5 px
    assert (xy[:, 0] >= border - 0.5).all() and (xy[:, 0] < W - border).all()
    assert (xy[:, 1] >= border - 0.5).all() and (xy[:, 1] < H - border).all()


def _fields_3d(rng, D, H, W):
    resp = rng.random((D, H, W), dtype=np.float32)
    mask = rng.random((D, H, W)) < 1 / 32
    nms = np.where(mask, resp, -np.inf).astype(np.float32)
    return jnp.asarray(resp), jnp.asarray(nms)


@pytest.mark.parametrize(
    "shape,border",
    [
        ((16, 64, 64), 8),  # aligned: fast path engages
        ((16, 64, 48), 8),
    ],
)
def test_3d_fast_path_identical_to_general(rng, shape, border):
    resp, nms = _fields_3d(rng, *shape)
    fast = _select_keypoints_3d(resp, nms, 48, 1e-4, border)
    gen = _select_keypoints_3d(
        resp, nms, 48, 1e-4, border, _force_general=True
    )
    _assert_same_keypoints(fast, gen)


def test_3d_misaligned_border_consistent(rng):
    resp, nms = _fields_3d(rng, 16, 64, 64)
    a = _select_keypoints_3d(resp, nms, 48, 1e-4, 6)
    b = _select_keypoints_3d(resp, nms, 48, 1e-4, 6, _force_general=True)
    _assert_same_keypoints(a, b)
