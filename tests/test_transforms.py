"""Unit tests for transform models: recover known transforms from point sets."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.models import MODELS, apply_transform, get_model


def random_points(rng, n, ndim, scale=100.0):
    return rng.uniform(10, scale, size=(n, ndim)).astype(np.float32)


def make_gt(name, rng):
    """A ground-truth matrix for each model family."""
    if name == "translation":
        M = np.eye(3, dtype=np.float32)
        M[:2, 2] = rng.uniform(-20, 20, 2)
    elif name == "rigid":
        th = rng.uniform(-0.5, 0.5)
        c, s = np.cos(th), np.sin(th)
        M = np.array([[c, -s, 5.0], [s, c, -3.0], [0, 0, 1]], dtype=np.float32)
    elif name == "similarity":
        th = rng.uniform(-0.5, 0.5)
        s = rng.uniform(0.8, 1.2)
        c, sn = s * np.cos(th), s * np.sin(th)
        M = np.array(
            [[c, -sn, 7.0], [sn, c, -4.0], [0, 0, 1]], dtype=np.float32
        )
    elif name == "affine":
        M = np.eye(3, dtype=np.float32)
        M[:2, :2] += rng.uniform(-0.2, 0.2, (2, 2))
        M[:2, 2] = rng.uniform(-10, 10, 2)
    elif name == "homography":
        M = np.eye(3, dtype=np.float32)
        M[:2, :2] += rng.uniform(-0.1, 0.1, (2, 2))
        M[:2, 2] = rng.uniform(-10, 10, 2)
        M[2, :2] = rng.uniform(-1e-4, 1e-4, 2)
    elif name == "rigid3d":
        ax = rng.normal(size=3)
        ax /= np.linalg.norm(ax)
        th = rng.uniform(-0.4, 0.4)
        K = np.array(
            [[0, -ax[2], ax[1]], [ax[2], 0, -ax[0]], [-ax[1], ax[0], 0]], dtype=np.float64
        )
        R = np.eye(3) + np.sin(th) * K + (1 - np.cos(th)) * K @ K
        M = np.eye(4, dtype=np.float32)
        M[:3, :3] = R.astype(np.float32)
        M[:3, 3] = rng.uniform(-5, 5, 3)
    else:
        raise ValueError(name)
    return M


@pytest.mark.parametrize("name", sorted(MODELS))
def test_exact_recovery(name, rng):
    """solve() on noiseless correspondences recovers the transform."""
    model = get_model(name)
    src = random_points(rng, 64, model.ndim)
    M_gt = make_gt(name, rng)
    dst = np.asarray(apply_transform(jnp.asarray(M_gt), jnp.asarray(src)))
    w = np.ones(64, dtype=np.float32)
    M = model.solve(jnp.asarray(src), jnp.asarray(dst), jnp.asarray(w))
    resid = model.residual(M, jnp.asarray(src), jnp.asarray(dst))
    assert float(jnp.max(resid)) < 1e-3, f"{name}: max sq-resid {float(jnp.max(resid))}"


@pytest.mark.parametrize("name", sorted(MODELS))
def test_weights_ignore_outliers(name, rng):
    """Zero-weighted gross outliers must not perturb the solve."""
    model = get_model(name)
    src = random_points(rng, 64, model.ndim)
    M_gt = make_gt(name, rng)
    dst = np.array(apply_transform(jnp.asarray(M_gt), jnp.asarray(src)))
    dst[::4] += 500.0  # corrupt 25% of points
    w = np.ones(64, dtype=np.float32)
    w[::4] = 0.0
    M = model.solve(jnp.asarray(src), jnp.asarray(dst), jnp.asarray(w))
    resid = model.residual(M, jnp.asarray(src), jnp.asarray(dst))
    inlier_resid = np.asarray(resid)[w > 0]
    assert inlier_resid.max() < 1e-3


@pytest.mark.parametrize("name", sorted(MODELS))
def test_minimal_sample_solve(name, rng):
    """Solving from exactly min_samples points reproduces those points."""
    model = get_model(name)
    n = model.min_samples
    src = random_points(rng, n, model.ndim)
    M_gt = make_gt(name, rng)
    dst = np.asarray(apply_transform(jnp.asarray(M_gt), jnp.asarray(src)))
    w = np.ones(n, dtype=np.float32)
    M = model.solve(jnp.asarray(src), jnp.asarray(dst), jnp.asarray(w))
    resid = model.residual(M, jnp.asarray(src), jnp.asarray(dst))
    assert float(jnp.max(resid)) < 1e-2


@pytest.mark.parametrize("name", sorted(MODELS))
def test_degenerate_inputs_are_finite(name):
    """All-zero weights / coincident points must yield a finite matrix."""
    model = get_model(name)
    src = jnp.ones((8, model.ndim), dtype=jnp.float32)
    dst = jnp.ones((8, model.ndim), dtype=jnp.float32)
    w = jnp.zeros(8, dtype=jnp.float32)
    M = model.solve(src, dst, w)
    # Must fall back to the identity, not a finite collapse map (which
    # could spuriously win the RANSAC inlier vote).
    np.testing.assert_allclose(np.asarray(M), np.eye(model.mat_size), atol=1e-6)
    M2 = model.solve(src, dst, jnp.ones(8, dtype=jnp.float32))
    assert bool(jnp.all(jnp.isfinite(M2)))
    if name == "rigid":
        # coincident points with real weight mass: rotation undefined
        np.testing.assert_allclose(
            np.asarray(M2)[:2, :2], np.eye(2), atol=1e-6
        )


@pytest.mark.parametrize("name", sorted(MODELS))
def test_solve_is_vmappable_and_jittable(name, rng):
    """The solve must compile and batch over leading axes (frames x hyps)."""
    model = get_model(name)
    B = 5
    srcs, dsts = [], []
    for _ in range(B):
        src = random_points(rng, 16, model.ndim)
        M_gt = make_gt(name, rng)
        dst = np.asarray(apply_transform(jnp.asarray(M_gt), jnp.asarray(src)))
        srcs.append(src)
        dsts.append(dst)
    src_b = jnp.asarray(np.stack(srcs))
    dst_b = jnp.asarray(np.stack(dsts))
    w_b = jnp.ones((B, 16), dtype=jnp.float32)
    solve_b = jax.jit(jax.vmap(model.solve))
    M_b = solve_b(src_b, dst_b, w_b)
    assert M_b.shape == (B, model.mat_size, model.mat_size)
    resid = jax.vmap(model.residual)(M_b, src_b, dst_b)
    assert float(jnp.max(resid)) < 1e-2


def test_homography_projective_divide():
    """apply_transform performs the w-divide for true projective maps."""
    H = jnp.array([[1.0, 0, 0], [0, 1.0, 0], [0.001, 0, 1.0]], dtype=jnp.float32)
    pts = jnp.array([[100.0, 50.0]], dtype=jnp.float32)
    out = apply_transform(H, pts)
    np.testing.assert_allclose(np.asarray(out), [[100 / 1.1, 50 / 1.1]], rtol=1e-5)


def test_degenerate_samples_rejected_statistically():
    """Random collinear minimal samples at image-scale coordinates must
    (almost always) hit the singularity guards and return identity —
    not a finite collapsing map that could win the RANSAC vote. The
    guards are relative-threshold float tests, so a few noise-level
    escapes per hundred are tolerated (RANSAC's vote absorbs them); an
    absolute-epsilon guard fails this test wholesale (~60% escapes)."""
    rng = np.random.default_rng(1)
    aff = get_model("affine")
    hom = get_model("homography")
    N = 100
    lins, quads = [], []
    for _ in range(N):
        q0 = rng.uniform(0, 512, 2)
        d = rng.uniform(-1, 1, 2)
        d /= np.linalg.norm(d)
        lin = np.stack(
            [q0, q0 + rng.uniform(10, 100) * d, q0 + rng.uniform(100, 300) * d]
        ).astype(np.float32)
        lins.append(lin)
        quads.append(
            np.concatenate([lin, rng.uniform(0, 512, (1, 2)).astype(np.float32)])
        )
    lins = jnp.asarray(np.stack(lins))
    quads = jnp.asarray(np.stack(quads))
    w3 = jnp.ones((N, 3), jnp.float32)
    w4 = jnp.ones((N, 4), jnp.float32)
    Ma = np.asarray(
        jax.vmap(lambda s, w: aff.solve(s, s + 5.0, w))(lins, w3)
    )
    Mh = np.asarray(
        jax.vmap(lambda s, w: hom.solve(s, s + 5.0, w))(quads, w4)
    )
    esc_a = sum(not np.allclose(M, np.eye(3), atol=1e-5) for M in Ma)
    esc_h = sum(not np.allclose(M, np.eye(3), atol=1e-5) for M in Mh)
    assert esc_a <= 5, f"{esc_a}/{N} collinear affine samples escaped"
    assert esc_h <= 5, f"{esc_h}/{N} degenerate homography samples escaped"
    # refine path (LU): a singular system yields inf/nan which _guard
    # replaces with the identity — output is always finite
    M2 = aff.resolved_refine_solve(lins[0], lins[0] + 5.0, w3[0])
    assert bool(jnp.all(jnp.isfinite(M2)))


def test_rigid3d_degenerate_outputs_are_isometries():
    """Collinear 3D minimal samples leave the rotation about the line
    unconstrained; the QCP solver may return any consistent rigid
    motion. The safety property (unlike affine/homography, which gate
    on singular determinants) is that every output is a PROPER
    ISOMETRY — it cannot collapse points into spurious inlier mass, so
    RANSAC's vote disposes of it."""
    rng = np.random.default_rng(3)
    r3 = get_model("rigid3d")
    N = 100
    lins = []
    for _ in range(N):
        q0 = rng.uniform(0, 256, 3)
        d = rng.uniform(-1, 1, 3)
        d /= np.linalg.norm(d)
        lins.append(
            np.stack(
                [q0, q0 + rng.uniform(10, 80) * d, q0 + rng.uniform(80, 200) * d]
            ).astype(np.float32)
        )
    lins = jnp.asarray(np.stack(lins))
    w = jnp.ones((N, 3), jnp.float32)
    Ms = np.asarray(jax.vmap(lambda s, ww: r3.solve(s, s + 5.0, ww))(lins, w))
    for M in Ms:
        R = M[:3, :3]
        np.testing.assert_allclose(R @ R.T, np.eye(3), atol=1e-4)
        assert np.linalg.det(R) > 0.9  # proper (no reflection/collapse)
