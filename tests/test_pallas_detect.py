"""Fused Pallas detection kernel vs the jnp conv path (interpret mode
on CPU): dense field parity, keypoint-level parity through the shared
selection stage, the free-ride smooth output, and ragged frame sizes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.detect import (
    _maxpool_same,
    _subpixel_fields,
    detect_keypoints_batch,
    gaussian_blur,
    harris_response,
)
from kcmc_tpu.ops.pallas_detect import response_fields
from kcmc_tpu.utils import synthetic


def _frames(shape, n=2, seed=3):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        np.stack(
            [synthetic.render_scene(rng, shape, n_blobs=80) for _ in range(n)]
        ).astype(np.float32)
    )


@pytest.mark.parametrize("shape", [(128, 128), (96, 160), (100, 84)])
def test_dense_fields_match_jnp_path(shape):
    frames = _frames(shape)
    nms_p, ox_p, oy_p = jax.tree.map(
        np.asarray, response_fields(frames, interpret=True)
    )
    resp = np.asarray(jax.vmap(harris_response)(frames))
    mp = np.asarray(jax.vmap(lambda r: _maxpool_same(r, 5))(resp))
    nms_j = np.where(resp >= mp, resp, -np.inf)
    ox_j, oy_j = jax.vmap(_subpixel_fields)(jnp.asarray(resp))

    # Interior: the kernel's zero-extended boundary handling differs
    # from the jnp path only on the 1-px frame edge (border-excluded).
    interior = np.s_[:, 2:-2, 2:-2]
    scale = np.abs(resp).max()
    fin_p = np.isfinite(nms_p[interior])
    fin_j = np.isfinite(nms_j[interior])
    np.testing.assert_array_equal(fin_p, fin_j)
    both = fin_p & fin_j
    assert (
        np.abs(nms_p[interior][both] - nms_j[interior][both]).max()
        <= 1e-5 * scale
    )
    np.testing.assert_allclose(
        ox_p[interior], np.asarray(ox_j)[interior], atol=1e-3
    )
    np.testing.assert_allclose(
        oy_p[interior], np.asarray(oy_j)[interior], atol=1e-3
    )


@pytest.mark.parametrize("shape", [(128, 128), (150, 108)])
def test_keypoints_match_jnp_path(shape):
    frames = _frames(shape)
    kw = dict(
        max_keypoints=128, threshold=1e-4, nms_size=5, border=16,
        harris_k=0.04,
    )
    kj = detect_keypoints_batch(frames, **kw, use_pallas=False)
    kp = detect_keypoints_batch(frames, **kw, use_pallas=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(kj.valid), np.asarray(kp.valid))
    both = np.asarray(kj.valid & kp.valid)
    assert np.abs(np.asarray(kj.xy) - np.asarray(kp.xy))[both].max() < 1e-3


def test_smooth_output_matches_gaussian_blur():
    frames = _frames((128, 128))
    _, smooth = detect_keypoints_batch(
        frames, max_keypoints=64, use_pallas=True, smooth_sigma=2.0,
        interpret=True,
    )
    ref = jax.vmap(lambda f: gaussian_blur(f, 2.0))(frames)
    np.testing.assert_allclose(
        np.asarray(smooth), np.asarray(ref), atol=1e-5
    )


def test_smooth_output_jnp_fallback():
    """The smooth ride-along also works on the non-Pallas route."""
    frames = _frames((96, 96))
    kps_a, smooth = detect_keypoints_batch(
        frames, max_keypoints=64, use_pallas=False, smooth_sigma=2.0
    )
    kps_b = detect_keypoints_batch(frames, max_keypoints=64, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(kps_a.xy), np.asarray(kps_b.xy))
    ref = jax.vmap(lambda f: gaussian_blur(f, 2.0))(frames)
    np.testing.assert_allclose(np.asarray(smooth), np.asarray(ref), atol=1e-6)


def test_unsupported_configs_fall_back_to_jnp():
    """Configs beyond the kernel's halo/VMEM budget must take the jnp
    route (same results as use_pallas=False), not raise."""
    frames = _frames((96, 96))
    kw = dict(max_keypoints=64, threshold=1e-4, border=16, harris_k=0.04)
    # nms_size=19: reach 2+5+9+1 = 17 > halo 16.
    a = detect_keypoints_batch(frames, **kw, nms_size=19, use_pallas=True)
    b = detect_keypoints_batch(frames, **kw, nms_size=19, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(a.xy), np.asarray(b.xy))
    # smooth_sigma beyond the halo likewise falls back.
    a2, s2 = detect_keypoints_batch(
        frames, **kw, nms_size=5, use_pallas=True, smooth_sigma=6.0
    )
    ref = jax.vmap(lambda f: gaussian_blur(f, 6.0))(frames)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(ref), atol=1e-6)


def test_wide_frames_rejected_by_supports():
    from kcmc_tpu.ops.pallas_detect import supports

    assert supports((512, 512))
    assert not supports((8, 8192))  # scratch slabs would overflow VMEM
    assert not supports((512, 512), nms_size=19)  # halo
    assert not supports((512, 512), smooth_sigma=0.0)  # degenerate blur


def test_paneled_fields_match_whole_frame_kernel():
    """Column-paneled wide-frame wrapper == whole-frame kernel, exactly,
    away from the true frame edge band (zeros-as-content there); the
    smooth free-ride is exactly identical everywhere."""
    from kcmc_tpu.ops.pallas_detect import (
        _reach,
        response_fields,
        response_fields_paneled,
    )

    frames = _frames((64, 300))
    whole = response_fields(frames, smooth_sigma=2.0, interpret=True)
    # max_panel_w=160 -> 128-wide cores -> 3 panels at W=300.
    paneled = response_fields_paneled(
        frames, smooth_sigma=2.0, max_panel_w=160, interpret=True
    )
    r = _reach(5, 1.5, 2.0)
    band = np.s_[:, :, r:-r]
    for w, p in zip(whole[:3], paneled[:3]):
        np.testing.assert_array_equal(np.asarray(w)[band], np.asarray(p)[band])
    np.testing.assert_array_equal(
        np.asarray(whole[3]), np.asarray(paneled[3])
    )


def test_wide_frame_detect_uses_paneled_path():
    """W past the strip kernel's lane budget: detect_keypoints_batch
    takes the paneled Pallas route and agrees with the jnp path."""
    from kcmc_tpu.ops.pallas_detect import supports, supports_paneled

    # Guard the premise: this width really is beyond the whole-frame
    # kernel and inside the paneled gate — otherwise the comparison
    # below would vacuously run the jnp path twice.
    assert not supports((48, 2100))
    assert supports_paneled(border=16)
    frames = _frames((48, 2100), n=1)
    kw = dict(max_keypoints=96, threshold=1e-4, border=16, harris_k=0.04)
    kj = detect_keypoints_batch(frames, **kw, use_pallas=False)
    kp = detect_keypoints_batch(frames, **kw, use_pallas=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(kj.valid), np.asarray(kp.valid))
    both = np.asarray(kj.valid & kp.valid)
    assert np.abs(np.asarray(kj.xy) - np.asarray(kp.xy))[both].max() < 1e-3


def test_supports_paneled_gates():
    from kcmc_tpu.ops.pallas_detect import supports_paneled

    assert supports_paneled(border=16)
    assert not supports_paneled(border=4)  # frame-edge band exposed
    assert not supports_paneled(nms_size=19, border=16)  # halo
    assert not supports_paneled(smooth_sigma=0.0, border=16)


def test_describe_accepts_precomputed_smooth():
    """Threading detect's smooth into describe changes nothing."""
    from kcmc_tpu.ops.describe import describe_keypoints_batch

    frames = _frames((128, 128))
    kps, smooth = detect_keypoints_batch(
        frames, max_keypoints=64, use_pallas=True, smooth_sigma=2.0,
        interpret=True,
    )
    a = describe_keypoints_batch(
        frames, kps, oriented=False, use_pallas=True, interpret=True,
        smooth=smooth,
    )
    b = describe_keypoints_batch(
        frames, kps, oriented=False, use_pallas=True, interpret=True
    )
    # smooth from the fused kernel differs from gaussian_blur by float
    # summation order only; descriptor bits compare blurred values with
    # a strict <, so equal bits everywhere except exact ties.
    bits = 32 * a.shape[-1] * a.shape[0] * a.shape[1]
    xor = (np.asarray(a) ^ np.asarray(b)).view(np.uint8)
    diff = int(np.unpackbits(xor).sum())  # popcount; numpy<2 compatible
    assert diff <= bits * 1e-3
