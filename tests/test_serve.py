"""Serving-layer unit + end-to-end suite.

Covers the serve satellites and transport:

* admission control — degrade-before-reject ordering, 429 rejection,
  hysteresis restore, decision counters in stats;
* weighted round-robin fairness schedule;
* the aggregate heartbeat sampler (multi-session frames/fps + queue
  depths + admission counters);
* collision-safe RunTelemetry artifact paths for concurrent runs in
  one process (two simultaneous sessions never share a records file);
* AsyncBatchWriter idempotent, cross-thread close surfacing a pending
  worker error exactly once;
* server-side session writers torn down from the scheduler thread;
* the real-socket transport: two concurrent clients, stats over the
  wire, clean shutdown.

Cross-stream BATCHING parity lives in tests/test_serve_parity.py.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io.async_writer import AsyncBatchWriter
from kcmc_tpu.serve.scheduler import OverloadedError, StreamScheduler
from kcmc_tpu.utils.synthetic import make_drift_stack

MC_KW = dict(
    model="translation", backend="numpy", batch_size=8,
    max_keypoints=64, n_hypotheses=32,
)


def _stack(n=16, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


@pytest.fixture
def sched():
    mc = MotionCorrector(**MC_KW)
    s = StreamScheduler(mc).start()
    yield s
    s.stop()


# -- admission control + QoS ------------------------------------------------


def test_degrade_engages_before_rejection():
    mc = MotionCorrector(
        serve_queue_depth=12, serve_degrade_watermark=0.5, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        stack = _stack(16)
        s = sched.open_session(tenant="hot")
        with pytest.warns(RuntimeWarning, match="degraded consensus"):
            dec = sched.submit(s.sid, stack[:12])  # past the 50% watermark
        assert dec["degraded"] is True
        # a submit that would exceed the bound outright is the LAST
        # resort: rejected 429-style, with the decision counted
        with pytest.raises(OverloadedError) as ei:
            sched.submit(s.sid, stack[:13])
        assert ei.value.code == 429
        st = sched.stats()
        assert st["admission"]["degrade_events"] == 1
        assert st["admission"]["rejected_submits"] == 1
        assert st["admission"]["rejected_frames"] == 13
        # (degraded_active is the LIVE flag — the scheduler may already
        # have drained past the hysteresis restore point by now, so the
        # engage itself is asserted via the decision + event counter.)
        res = sched.close_session(s.sid, timeout=120)
        assert res.timing["n_frames"] == 12
        # the degraded dispatches were counted
        assert sched.stats()["admission"]["degraded_batches"] >= 1
    finally:
        sched.stop()


def test_invalid_submit_past_watermark_does_not_degrade():
    # A mis-shaped submit is a CLIENT error: it must be rejected without
    # flipping the session's QoS state (no phantom degrade events).
    mc = MotionCorrector(
        serve_queue_depth=12, serve_degrade_watermark=0.5, **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="t")
        sched.submit(s.sid, _stack(2))  # pins the stream's frame shape
        with pytest.raises(ValueError, match="frames are"):
            sched.submit(s.sid, _stack(8, shape=(32, 32)))
        assert s.degraded is False
        assert sched.stats()["admission"]["degrade_events"] == 0
        sched.close_session(s.sid, timeout=120)
    finally:
        sched.stop()


def test_results_after_close_delivers_then_exhausts():
    # A results poll racing (or following) a close_session must deliver
    # whatever was never fetched, then read "exhausted" — never
    # "no open session".
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(**MC_KW)
    server = ServeServer(mc, port=0)
    with server:
        sess = server.scheduler.open_session(tenant="t")
        server.scheduler.submit(sess.sid, _stack(4))
        server.scheduler.close_session(sess.sid, timeout=120)
        resp = server.handle_op({"op": "results", "session": sess.sid})
        assert resp["ok"] and resp["n"] == 4  # the undelivered span
        resp = server.handle_op({"op": "results", "session": sess.sid})
        assert resp == {"ok": True, "exhausted": True}
        with pytest.raises(KeyError):  # a never-opened id still errors
            server.handle_op({"op": "results", "session": "nope"})


def test_failed_open_releases_telemetry_claims(tmp_path):
    # A rejected open_session (bad reference) must not leak artifact-
    # path claims in the RunTelemetry registry, and the id stays usable.
    from kcmc_tpu.obs import run as obs_run

    mc = MotionCorrector(
        frame_records_path=str(tmp_path / "fr.jsonl"), **MC_KW
    )
    sched = StreamScheduler(mc).start()
    try:
        before = set(obs_run._ACTIVE_PATHS)
        with pytest.raises(ValueError, match="2-D"):
            sched.open_session(
                reference=np.zeros((2, 8, 8), np.float32),
                session_id="job-1",
            )
        assert set(obs_run._ACTIVE_PATHS) == before
        s = sched.open_session(
            reference=_stack(1)[0], session_id="job-1"
        )
        sched.submit(s.sid, _stack(4))
        sched.close_session(s.sid, timeout=120)
        assert set(obs_run._ACTIVE_PATHS) == before  # released on finish
    finally:
        sched.stop()


def test_close_session_retry_after_reap_returns_result(sched):
    # A close_session that timed out client-side must be retryable: the
    # reaped session's final result is retained, not lost.
    s = sched.open_session(tenant="t")
    sched.submit(s.sid, _stack(6))
    first = sched.close_session(s.sid, timeout=120)
    # let the scheduler reap the closed session from its schedule
    for _ in range(200):
        if not sched.stats()["sessions_open"]:
            break
        import time

        time.sleep(0.02)
    retry = sched.close_session(s.sid, timeout=10)
    assert retry is first  # the SAME finalized CorrectionResult
    np.testing.assert_array_equal(retry.transforms, first.transforms)


def test_degraded_restores_after_drain(sched):
    # watermark 1.0 => degradation disabled; manual flag restores once
    # the backlog empties past the hysteresis point
    s = sched.open_session(tenant="t")
    s.degraded = True
    sched.submit(s.sid, _stack(4))
    sched.close_session(s.sid, timeout=120)
    assert s.degraded is False  # hysteresis restore ran on drain


def test_degraded_backend_keeps_reference_knobs():
    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc)
    db = sched._get_degraded_backend()
    cfg, dcfg = mc.config, db.config
    assert dcfg.n_hypotheses < cfg.n_hypotheses
    # reference preparation must be identical on both backends so a
    # session's prepared reference stays valid across the QoS flip
    for knob in (
        "max_keypoints", "detect_threshold", "nms_size", "border",
        "n_octaves", "blur_sigma", "oriented", "harris_window_sigma",
        "cand_tile",
    ):
        assert getattr(dcfg, knob) == getattr(cfg, knob), knob


def test_unknown_session_errors(sched):
    with pytest.raises(KeyError):
        sched.submit("nope", _stack(2))


# -- fairness ----------------------------------------------------------------


def test_weighted_round_robin_schedule(sched):
    a = sched.open_session(tenant="A", weight=1, session_id="a")
    b = sched.open_session(tenant="B", weight=3, session_id="b")
    with sched._lock:
        order = list(sched._order)
    assert order.count("a") == 1 and order.count("b") == 3
    # interleaved, not clustered: 'a' is not adjacent to itself and the
    # first cycle position alternates tenants where possible
    assert order[0] in ("a", "b") and set(order) == {"a", "b"}
    sched.close_session(a.sid, timeout=60)
    sched.close_session(b.sid, timeout=60)


def test_two_sessions_interleave_probed_by_occupancy(sched):
    # Both sessions' frames flow through one scheduler; occupancy
    # accounts valid frames over B-padded batches.
    a = sched.open_session(tenant="A")
    b = sched.open_session(tenant="B")
    sched.submit(a.sid, _stack(8, seed=0))
    sched.submit(b.sid, _stack(8, seed=1))
    sched.close_session(a.sid, timeout=120)
    sched.close_session(b.sid, timeout=120)
    st = sched.stats()
    assert st["frames_done"] == 16
    assert st["batch_occupancy"] == 1.0  # 8-frame submits, B=8


# -- aggregate heartbeat -----------------------------------------------------


def test_aggregate_sampler_formats_sessions_queues_admission():
    from kcmc_tpu.obs.heartbeat import aggregate_sampler

    sample = aggregate_sampler(lambda: {
        "sessions": [
            {"name": "A/s1", "frames": 40, "fps": 10.0},
            {"name": "B/s2", "frames": 8, "fps": 2.5},
        ],
        "queues": {"s1": 3, "s2": 9},
        "admission": {"rejected": 13, "degraded": 2},
        "extra": "occupancy=0.85 inflight=2",
    })
    line = sample()
    assert "2 session(s), 48 frames total, 12.5 fps" in line
    assert "A/s1=40@10.0fps" in line and "B/s2=8@2.5fps" in line
    assert "queued s1=3 s2=9" in line
    assert "degraded=2" in line and "rejected=13" in line
    assert "occupancy=0.85" in line


def test_aggregate_sampler_idle_and_quiet_admission():
    from kcmc_tpu.obs.heartbeat import aggregate_sampler

    line = aggregate_sampler(lambda: {"sessions": []})()
    assert "0 sessions (idle)" in line
    # all-zero admission counters stay out of the line
    line = aggregate_sampler(lambda: {
        "sessions": [{"name": "s", "frames": 1, "fps": 1.0}],
        "admission": {"rejected": 0, "degraded": 0},
    })()
    assert "admission" not in line


def test_scheduler_snapshot_feeds_sampler(sched):
    from kcmc_tpu.obs.heartbeat import aggregate_sampler

    s = sched.open_session(tenant="T")
    sched.submit(s.sid, _stack(8))
    sched.close_session(s.sid, timeout=120)
    line = aggregate_sampler(sched.snapshot)()
    assert "occupancy=" in line and "inflight=" in line


# -- collision-safe telemetry paths (satellite) ------------------------------


def test_concurrent_sessions_get_distinct_record_files(tmp_path):
    """Two simultaneous sessions configured with the SAME artifact path
    must never interleave writes into one file: EVERY serve session
    derives a session-id filename (so sequential sessions of a
    long-lived server don't overwrite each other either); each file
    stays valid JSONL with only its own session's frames."""
    records = tmp_path / "frames.jsonl"
    mc = MotionCorrector(frame_records_path=str(records), **MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        a = sched.open_session(tenant="A", session_id="sess-a")
        b = sched.open_session(tenant="B", session_id="sess-b")
        paths = {
            a.telemetry.frame_records_path,
            b.telemetry.frame_records_path,
        }
        assert len(paths) == 2, "both sessions claimed one records file"
        assert paths == {
            str(tmp_path / "frames.sess-a.jsonl"),
            str(tmp_path / "frames.sess-b.jsonl"),
        }
        sched.submit(a.sid, _stack(8, seed=0))
        sched.submit(b.sid, _stack(8, seed=1))
        sched.close_session(a.sid, timeout=120)
        sched.close_session(b.sid, timeout=120)
        for sess in (a, b):
            path = sess.telemetry.frame_records_path
            lines = [
                json.loads(ln)
                for ln in open(path, encoding="utf-8")
                if ln.strip()
            ]
            header = lines[0]
            assert header["kind"] == "kcmc_frame_records"
            assert header["manifest"]["run_id"] == sess.sid
            recs = [o for o in lines if "kind" not in o]
            assert [r["frame"] for r in recs] == list(range(8))
    finally:
        sched.stop()


def test_sequential_runs_reuse_configured_path(tmp_path):
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.obs.run import RunTelemetry

    path = tmp_path / "t.jsonl"
    cfg = CorrectorConfig(frame_records_path=str(path))
    t1 = RunTelemetry.begin(cfg, backend_name="numpy")
    assert t1.frame_records_path == str(path)
    t1.finish({})
    t2 = RunTelemetry.begin(cfg, backend_name="numpy")
    # claim released at finish: the NEXT run gets the verbatim path
    assert t2.frame_records_path == str(path)
    t2.finish({})


def test_concurrent_trace_paths_derive_and_release(tmp_path):
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.obs.run import RunTelemetry

    path = tmp_path / "trace.json"
    cfg = CorrectorConfig(trace_path=str(path))
    t1 = RunTelemetry.begin(cfg, backend_name="numpy", run_id="one")
    t2 = RunTelemetry.begin(cfg, backend_name="numpy", run_id="two")
    assert t1.trace_path == str(path)
    assert t2.trace_path == str(tmp_path / "trace.two.json")
    t1.finish({})
    t2.finish({})
    for p in (t1.trace_path, t2.trace_path):
        trace = json.load(open(p))
        assert trace["metadata"]["manifest"]["kind"] == "kcmc_run_manifest"
    # both released: a fresh run reclaims the configured path
    t3 = RunTelemetry.begin(cfg, backend_name="numpy")
    assert t3.trace_path == str(path)
    t3.finish({})


# -- AsyncBatchWriter close semantics (satellite) ----------------------------


class _ListWriter:
    def __init__(self, fail_on=None):
        self.batches = []
        self.closed = 0
        self.fail_on = fail_on
        self.n_pages = 0

    def append_batch(self, frames, n_threads=0):
        if self.fail_on is not None and len(self.batches) == self.fail_on:
            raise OSError("disk full")
        self.batches.append(np.asarray(frames))
        self.n_pages += len(frames)

    def checkpoint_state(self):
        return {"n": self.n_pages}

    def close(self):
        self.closed += 1


def test_async_writer_close_idempotent_and_cross_thread():
    inner = _ListWriter()
    w = AsyncBatchWriter(inner, depth=2)
    w.append_batch(np.zeros((2, 4, 4), np.float32))
    results = []

    def closer():
        try:
            w.close()
            results.append("ok")
        except BaseException as e:  # pragma: no cover - failure detail
            results.append(e)

    threads = [threading.Thread(target=closer) for _ in range(4)]
    for t in threads:
        t.start()
    w.close()  # creator thread too
    for t in threads:
        t.join()
    assert results == ["ok"] * 4
    assert inner.closed == 1  # teardown ran exactly once
    assert inner.n_pages == 2


def test_async_writer_close_surfaces_worker_error_exactly_once():
    inner = _ListWriter(fail_on=0)
    w = AsyncBatchWriter(inner, depth=4)
    w.append_batch(np.zeros((1, 4, 4), np.float32))
    w._thread.join(timeout=10.0)  # let the failure land
    errors, oks = [], []

    def closer():
        try:
            w.close()
            oks.append(1)
        except OSError as e:
            errors.append(e)

    threads = [threading.Thread(target=closer) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(errors) == 1, "worker error must surface exactly once"
    assert len(oks) == 5
    assert inner.closed == 1


def test_async_writer_append_after_close_raises():
    w = AsyncBatchWriter(_ListWriter(), depth=1)
    w.close()
    with pytest.raises(ValueError, match="closed"):
        w.append_batch(np.zeros((1, 4, 4), np.float32))


# -- server-side writers torn down from the scheduler thread ----------------


def test_session_writer_closed_by_scheduler_thread(tmp_path):
    out = tmp_path / "served.tif"
    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(
            tenant="w", output=str(out), expected_frames=8,
            output_dtype="float32",
        )
        sched.submit(s.sid, _stack(8))
        res = sched.close_session(s.sid, timeout=120)
        assert res.timing["n_frames"] == 8
        from kcmc_tpu.io import read_stack

        frames = read_stack(str(out))
        assert frames.shape == (8, 48, 48)
    finally:
        sched.stop()


def test_session_output_requires_expected_frames(sched):
    with pytest.raises(ValueError, match="expected_frames"):
        sched.open_session(tenant="w", output="x.tif")


# -- real-socket transport ---------------------------------------------------


def test_socket_two_clients_parity_stats_and_shutdown(tmp_path):
    from kcmc_tpu.serve.client import ServeClient, ServeError
    from kcmc_tpu.serve.server import ServeServer

    s1 = _stack(12, seed=0)
    s2 = _stack(10, seed=1)
    truth1 = MotionCorrector(**MC_KW).correct(s1)
    truth2 = MotionCorrector(**MC_KW).correct(s2)

    mc = MotionCorrector(**MC_KW)
    with ServeServer(mc, port=0) as srv:
        got = {}

        def drive(name, stack, truth):
            with ServeClient(port=srv.port) as c:
                sid = c.open_session(tenant=name)
                for lo in range(0, len(stack), 5):
                    c.submit(sid, stack[lo : lo + 5])
                got[name] = c.close_session(sid)

        ta = threading.Thread(target=drive, args=("A", s1, truth1))
        tb = threading.Thread(target=drive, args=("B", s2, truth2))
        ta.start(), tb.start()
        ta.join(120), tb.join(120)
        assert np.abs(got["A"]["transforms"] - truth1.transforms).max() < 1e-4
        assert np.abs(got["B"]["transforms"] - truth2.transforms).max() < 1e-4
        assert "n_inliers" in got["A"]["diagnostics"]
        with ServeClient(port=srv.port) as c:
            st = c.stats()
            assert st["frames_done"] == 22
            assert st["admission"]["accepted_frames"] == 22
            with pytest.raises(ServeError, match="no open session"):
                c.submit("ghost", s1[:1])
            final = c.shutdown()
            assert final["frames_done"] == 22
        assert srv.wait(timeout=10.0), "shutdown op must release wait()"


def test_socket_incremental_results():
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    stack = _stack(12)
    mc = MotionCorrector(**MC_KW)
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port) as c:
            sid = c.open_session(tenant="inc")
            c.submit(sid, stack)
            seen = 0
            while seen < 12:
                span = c.results(sid, timeout=60.0)
                assert span is not None
                assert span["first_frame"] == seen
                seen += span["n"]
                assert "transform" in span
            final = c.close_session(sid)
            assert final["frames"] == 12
            c.shutdown()
