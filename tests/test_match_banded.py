"""Spatially-banded matcher (ops/match_banded.py): banded == dense for
in-radius motion, graceful degradation beyond the radius / capacity,
and the end-to-end pipeline contract with `match_radius` set.

The banded matcher's claim (module docstring): recall loss vs the dense
matcher comes only from bucket-capacity overflow, never from geometry —
every reference keypoint within R of a query is in its tile's candidate
window.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu.ops.match import knn_match
from kcmc_tpu.ops.match_banded import (
    BandedGeometry,
    banded_match,
    build_banded_ref,
    make_geometry,
)

SHAPE = (256, 256)
K = 512


def _scene(rng, k=K, lo=16, hi=240):
    xy = rng.uniform(lo, hi, size=(k, 2)).astype(np.float32)
    desc = rng.integers(0, 2**32, size=(k, 8), dtype=np.uint32)
    return xy, desc


def _noisy(desc, rng, n_and=3):
    """Flip a sparse random subset of bits (AND of uniform masks)."""
    flip = rng.integers(0, 2**32, size=desc.shape, dtype=np.uint32)
    for _ in range(n_and):
        flip &= rng.integers(0, 2**32, size=desc.shape, dtype=np.uint32)
    return desc ^ flip


def _run_banded(geom, ref_xy, ref_desc, q_xy, q_desc, valid=None, **kw):
    v = np.ones(len(ref_xy), bool) if valid is None else valid
    bref = build_banded_ref(
        geom, jnp.asarray(ref_xy), jnp.asarray(ref_desc), jnp.asarray(v)
    )
    return banded_match(
        geom, bref, jnp.asarray(q_desc), jnp.asarray(q_xy),
        jnp.asarray(np.ones(len(q_xy), bool)), **kw
    )


@pytest.mark.parametrize("radius,tile", [(16.0, 64), (32.0, 64), (12.0, 32)])
def test_banded_equals_dense_within_radius(rng, radius, tile):
    """Drift below the radius: banded reproduces every dense match (the
    dense matcher is the oracle; capacity slack is generous here)."""
    # scene margins keep every drifted query inside the image (out-of-
    # image keypoints are dropped by design, and can't occur in real use)
    ref_xy, ref_desc = _scene(rng, lo=40, hi=215)
    drift = np.array([0.55, -0.35], np.float32) * radius  # |drift| < R
    q_xy = ref_xy + drift
    q_desc = _noisy(ref_desc, rng)
    valid = np.ones(K, bool)

    dense = knn_match(
        jnp.asarray(q_desc), jnp.asarray(ref_desc),
        jnp.asarray(valid), jnp.asarray(valid),
    )
    # slack=6: capacity comfortably above any cluster in this uniform
    # scene, so the zero-loss claim is purely about window geometry.
    # (Bounded overflow loss at tight slack is the documented contract,
    # covered by test_capacity_overflow_drops_gracefully.)
    geom = make_geometry(SHAPE, radius, K, K, tile=tile, slack=6.0)
    band = _run_banded(geom, ref_xy, ref_desc, q_xy, q_desc)

    dv, bv = np.asarray(dense.valid), np.asarray(band.valid)
    di, bi = np.asarray(dense.idx), np.asarray(band.idx)
    # Every dense match whose pair is within the radius must be found
    # with the same reference index. (Banded may validly find MORE: its
    # ratio/mutual competitors are restricted to the motion envelope.)
    in_rad = dv & (np.linalg.norm(ref_xy[di] - q_xy, axis=1) < radius)
    assert in_rad.sum() > 0.9 * K
    assert (bv & in_rad).sum() == in_rad.sum()
    assert (bi[in_rad] == di[in_rad]).all()
    # Distances for shared matches are identical (same Hamming math).
    both = dv & bv & (bi == di)
    np.testing.assert_array_equal(
        np.asarray(band.dist)[both], np.asarray(dense.dist)[both]
    )


def test_drift_beyond_radius_degrades_visibly(rng):
    """Motion past the radius loses matches (valid=False) rather than
    mis-matching: the failure mode is a visible n_matches collapse."""
    ref_xy, ref_desc = _scene(rng)
    geom = make_geometry(SHAPE, 16.0, K, K, slack=3.0)
    q_desc = _noisy(ref_desc, rng)

    near = _run_banded(geom, ref_xy, ref_desc, ref_xy + 8.0, q_desc)
    far = _run_banded(geom, ref_xy, ref_desc, ref_xy + 90.0, q_desc)
    n_near = int(np.asarray(near.valid).sum())
    n_far = int(np.asarray(far.valid).sum())
    assert n_near > 0.9 * K
    assert n_far < 0.05 * K
    # and the far matches that DID validate are within the candidate
    # window's geometric reach (per-axis: query anywhere in its tile to
    # a candidate anywhere in the padded window)
    fi = np.asarray(far.idx)[np.asarray(far.valid)]
    if len(fi):
        d = np.abs(ref_xy[fi] - (ref_xy + 90.0)[np.asarray(far.valid)])
        reach = geom.tile + (geom.n_win * geom.sub - geom.tile) / 2
        assert (d <= reach).all()


def test_capacity_overflow_drops_gracefully(rng):
    """Keypoints crammed into one bucket beyond capacity: excess slots
    are dropped (valid=False), never aliased to wrong matches."""
    k = 256
    xy = rng.uniform(100, 110, size=(k, 2)).astype(np.float32)  # one cell
    desc = rng.integers(0, 2**32, size=(k, 8), dtype=np.uint32)
    geom = make_geometry(SHAPE, 16.0, k, k, slack=1.0)
    assert geom.csub < k  # the premise: bucket can't hold them all
    band = _run_banded(geom, xy, desc, xy, desc)
    bv = np.asarray(band.valid)
    bi = np.asarray(band.idx)
    # the surviving matches are all correct identity matches
    assert (bi[bv] == np.arange(k)[bv]).all()
    assert 0 < bv.sum() < k


def test_border_keypoints_match(rng):
    """Tiles at the image border have clipped candidate windows; the
    keypoints there must still match (window clamps, not wraps)."""
    k = 128
    # keypoints hugging all four borders
    # +4-px drift below must keep every query in the image
    edge = np.concatenate([
        np.stack([np.linspace(2, 249, k // 4), np.full(k // 4, 3.0)], -1),
        np.stack([np.linspace(2, 249, k // 4), np.full(k // 4, 248.0)], -1),
        np.stack([np.full(k // 4, 3.0), np.linspace(2, 249, k // 4)], -1),
        np.stack([np.full(k // 4, 248.0), np.linspace(2, 249, k // 4)], -1),
    ]).astype(np.float32)
    desc = np.asarray(
        np.random.default_rng(7).integers(0, 2**32, size=(k, 8)), np.uint32
    )
    geom = make_geometry(SHAPE, 16.0, k, k, slack=4.0)
    band = _run_banded(geom, edge, desc, edge + 4.0, desc)
    bv = np.asarray(band.valid)
    bi = np.asarray(band.idx)
    assert bv.sum() > 0.9 * k
    assert (bi[bv] == np.arange(k)[bv]).all()


def test_mutual_rejects_cross_tile_claims(rng):
    """A reference keypoint claimed by a better query in a DIFFERENT
    tile must reject the worse query's claim — the reverse pass spans
    every tile whose window contains the keypoint's sub-bucket."""
    # two queries near a tile boundary, one ref keypoint between them
    ref_xy = np.array([[63.0, 40.0]], np.float32)
    ref_desc = np.asarray([[0xDEADBEEF] * 8], np.uint32)
    # query 0 (tile 0) has the exact descriptor; query 1 (tile 1) has a
    # 1-bit-off copy — without cross-tile mutual both would claim ref 0.
    q_xy = np.array([[60.0, 40.0], [66.0, 40.0]], np.float32)
    q_desc = np.asarray(
        [[0xDEADBEEF] * 8, [0xDEADBEEE] + [0xDEADBEEF] * 7], np.uint32
    )
    geom = make_geometry(SHAPE, 16.0, 2, 1, tile=64, slack=8.0)
    bref = build_banded_ref(
        geom, jnp.asarray(ref_xy), jnp.asarray(ref_desc),
        jnp.asarray(np.ones(1, bool)),
    )
    band = banded_match(
        geom, bref, jnp.asarray(q_desc), jnp.asarray(q_xy),
        jnp.asarray(np.ones(2, bool)), ratio=1.0, mutual=True,
    )
    bv = np.asarray(band.valid)
    assert bv[0] and not bv[1]  # exact copy wins, cross-tile loser rejected


def test_window_covers_radius_property():
    """Geometry invariant: for every tile, the candidate window covers
    the full ±R envelope of every point in the tile."""
    for radius in (8.0, 16.0, 24.0, 32.0, 48.0):
        for tile in (32, 64, 128):
            g = make_geometry((512, 512), radius, 1024, 1024, tile=tile)
            pad = g.n_win * g.sub - tile  # total padding, px
            assert pad >= 2 * radius - 1e-6, (radius, tile, g)


def test_banded_under_vmap(rng):
    """The per-frame matcher must vmap over a batch (it runs inside the
    backend's vmapped tail)."""
    ref_xy, ref_desc = _scene(rng)
    valid = np.ones(K, bool)
    geom = make_geometry(SHAPE, 16.0, K, K, slack=3.0)
    bref = build_banded_ref(
        geom, jnp.asarray(ref_xy), jnp.asarray(ref_desc), jnp.asarray(valid)
    )
    B = 3
    drifts = np.array([[4.0, 2.0], [-6.0, 5.0], [0.0, -8.0]], np.float32)
    q_xy = np.stack([ref_xy + d for d in drifts])
    q_desc = np.stack([_noisy(ref_desc, rng) for _ in range(B)])

    fn = jax.vmap(
        lambda qd, qx: banded_match(
            geom, bref, qd, qx, jnp.asarray(valid)
        )
    )
    out = fn(jnp.asarray(q_desc), jnp.asarray(q_xy))
    assert out.valid.shape == (B, K)
    for b in range(B):
        bv = np.asarray(out.valid[b])
        bi = np.asarray(out.idx[b])
        assert bv.sum() > 0.9 * K
        assert (bi[bv] == np.arange(K)[bv]).all()


def test_pipeline_with_match_radius(rng):
    """End-to-end: MotionCorrector(match_radius=...) recovers the same
    drift as the dense path on a synthetic stack."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse
    from kcmc_tpu.utils.synthetic import make_drift_stack

    data = make_drift_stack(
        n_frames=10, shape=(256, 256), model="affine", max_drift=8.0, seed=3
    )
    rel = relative_transforms(data.transforms)

    dense = MotionCorrector(model="affine", backend="jax", batch_size=5)
    band = MotionCorrector(
        model="affine", backend="jax", batch_size=5, match_radius=24.0
    )
    r_dense = dense.correct(data.stack)
    r_band = band.correct(data.stack)
    e_dense = transform_rmse(r_dense.transforms, rel, (256, 256))
    e_band = transform_rmse(r_band.transforms, rel, (256, 256))
    assert e_band < 0.25
    assert e_band < 2.0 * e_dense + 0.02
    # the banded run found a comparable number of matches
    nm_d = np.asarray(r_dense.diagnostics["n_matches"])
    nm_b = np.asarray(r_band.diagnostics["n_matches"])
    assert (nm_b > 0.9 * nm_d).all()


def test_config_validation():
    from kcmc_tpu import MotionCorrector

    with pytest.raises(ValueError, match="match_radius"):
        MotionCorrector(match_radius=-1.0)
    with pytest.raises(ValueError, match="match_radius"):
        MotionCorrector(model="rigid3d", match_radius=8.0)
    with pytest.raises(ValueError, match="match_slack"):
        MotionCorrector(match_radius=8.0, match_slack=0.5)
    with pytest.raises(ValueError, match="match_tile"):
        MotionCorrector(match_radius=8.0, match_tile=8)


def test_zero_descriptor_never_matches(rng):
    """All-zero descriptors are the invalid sentinel (masked slots,
    bin-capacity-dropped keypoints, flat patches): both matchers must
    reject them even when the validity flag says True — a zero query's
    distance to a low-popcount reference would otherwise pass every
    test as a spurious correspondence."""
    k = 64
    xy = rng.uniform(20, 230, size=(k, 2)).astype(np.float32)
    desc = rng.integers(0, 2**32, size=(k, 8), dtype=np.uint32)
    desc[0] = 0  # query 0: zero descriptor, valid=True
    ref_desc = desc.copy()
    ref_desc[1] = 0  # ref 1: zero descriptor, valid=True
    valid = np.ones(k, bool)

    dense = knn_match(
        jnp.asarray(desc), jnp.asarray(ref_desc),
        jnp.asarray(valid), jnp.asarray(valid),
    )
    assert not bool(np.asarray(dense.valid)[0])
    assert 1 not in np.asarray(dense.idx)[np.asarray(dense.valid)]

    geom = make_geometry((256, 256), 16.0, k, k, slack=6.0)
    band = _run_banded(geom, xy, ref_desc, xy, desc)
    assert not bool(np.asarray(band.valid)[0])
    assert 1 not in np.asarray(band.idx)[np.asarray(band.valid)]


def test_mutual_packing_beyond_8k_keypoints(rng):
    """The reverse-pass packed key must hold (distance, query index)
    for K past 8192 — the scale regime the banded matcher exists for
    (a fixed 8192 multiplier would corrupt the mutual test there)."""
    K_big = 12288
    xy = rng.uniform(16, 496, size=(K_big, 2)).astype(np.float32)
    desc = rng.integers(0, 2**32, size=(K_big, 8), dtype=np.uint32)
    valid = np.ones(K_big, bool)
    geom = make_geometry((512, 512), 12.0, K_big, K_big, slack=3.0)
    bref = build_banded_ref(
        geom, jnp.asarray(xy), jnp.asarray(desc), jnp.asarray(valid)
    )
    band = banded_match(
        geom, bref, jnp.asarray(desc), jnp.asarray(xy + 4.0),
        jnp.asarray(valid), mutual=True,
    )
    bv = np.asarray(band.valid)
    bi = np.asarray(band.idx)
    # identity descriptors, small drift: high-K indices must survive the
    # mutual test and map to themselves
    assert bv.sum() > 0.8 * K_big
    assert (bi[bv] == np.arange(K_big)[bv]).all()
    assert bv[8192:].sum() > 0.8 * (K_big - 8192)
