"""`kcmc_tpu report` + CLI observability flags: round-trip on a
synthetic run, both artifact flavors (frame-records JSONL and
transforms npz), verbosity flags, and post-mortem artifacts from a
failed run."""

import json

import numpy as np
import pytest

from kcmc_tpu.__main__ import main as cli_main
from kcmc_tpu.obs import log as obs_log
from kcmc_tpu.obs.report import load_run, render_report


@pytest.fixture(autouse=True)
def _restore_logging():
    # cli_main configures process-global advisory routing; undo so
    # later pytest.warns-based suites keep their contracts
    yield
    obs_log.reset_cli_logging()


@pytest.fixture
def smoke_tif(tmp_path):
    from kcmc_tpu.io.tiff import write_stack
    from kcmc_tpu.utils.synthetic import make_drift_stack

    data = make_drift_stack(
        n_frames=16, shape=(64, 64), model="translation", max_drift=4.0,
        seed=0,
    )
    path = tmp_path / "smoke.tif"
    write_stack(
        str(path), np.clip(data.stack * 40000, 0, 65535).astype(np.uint16)
    )
    return str(path)


def _run_correct(tmp_path, smoke_tif, *extra):
    args = [
        "correct", smoke_tif, "--backend", "numpy", "--batch-size", "8",
        "--transforms", str(tmp_path / "t.npz"),
        "--trace", str(tmp_path / "t.json"),
        "--frame-records", str(tmp_path / "f.jsonl"),
        *extra,
    ]
    assert cli_main(args) == 0


def test_cli_correct_produces_valid_artifacts(tmp_path, smoke_tif, capsys):
    _run_correct(tmp_path, smoke_tif, "--quality")
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # satellite: the CLI summary now carries stage counts + means
    assert summary["stages"]["register_batches"]["count"] >= 1
    assert summary["stages"]["register_batches"]["mean_s"] > 0
    # trace: Perfetto-loadable, schema-complete
    trace = json.loads((tmp_path / "t.json").read_text())
    for ev in trace["traceEvents"]:
        assert {"ts", "dur", "ph", "tid"} <= set(ev)
    assert trace["metadata"]["manifest"]["backend"] == "numpy"
    # records: one per frame with ratio + residual (acceptance)
    lines = [
        json.loads(line)
        for line in (tmp_path / "f.jsonl").read_text().splitlines()
    ]
    recs = [o for o in lines if "frame" in o and "kind" not in o]
    assert len(recs) == 16
    assert all(
        r["inlier_ratio"] is not None and r["rms_residual_px"] is not None
        for r in recs
    )


def test_report_roundtrip_jsonl_and_npz(tmp_path, smoke_tif, capsys):
    _run_correct(tmp_path, smoke_tif, "--quality")
    capsys.readouterr()

    assert cli_main(["report", str(tmp_path / "f.jsonl"), "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "kcmc run report" in out
    assert "Stages:" in out and "register_batches" in out
    assert "Frame quality percentiles:" in out
    assert "inlier_ratio" in out and "residual_px" in out
    assert "Worst 3 frames" in out

    assert cli_main(["report", str(tmp_path / "t.npz")]) == 0
    out_npz = capsys.readouterr().out
    assert "Frame quality percentiles:" in out_npz
    assert "Robustness ladder:" in out_npz

    assert cli_main(["report", str(tmp_path / "f.jsonl"), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["n_frames"] == 16
    assert js["metrics"]["inlier_ratio"]["p50"] > 0
    assert js["timing"]["stages_s"]


def test_chaos_run_records_stay_complete(tmp_path, smoke_tif, capsys):
    # chaos run: a transient device fault is retried away; the frame
    # records still cover every frame and the summary line carries the
    # robustness counters
    _run_correct(
        tmp_path, smoke_tif,
        "--inject-faults", "device:step=1:transient",
    )
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["robustness"]["device_retries"] >= 1
    _, records, jsum = _read_jsonl(tmp_path / "f.jsonl")
    assert len(records) == 16
    assert jsum["robustness"]["device_retries"] >= 1


def test_failover_frames_flagged_in_records(tmp_path):
    # retries exhausted -> numpy failover: the recovered frames carry
    # the per-frame `failover` flag in the record stream
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    data = make_drift_stack(
        n_frames=12, shape=(64, 64), model="translation", seed=0
    )
    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=4,
        frame_records_path=str(tmp_path / "fo.jsonl"),
        fault_plan="device:step=1:transient", retry_attempts=1,
        failover_backend="jax",
    )
    with pytest.warns(RuntimeWarning, match="failover backend"):
        res = mc.correct(data.stack)
    assert res.robustness["backend_failovers"] == 1
    assert res.robustness["failover_frames"] == 4
    _, records, _ = _read_jsonl(tmp_path / "fo.jsonl")
    flagged = [r["frame"] for r in records if r["failover"]]
    assert flagged == [4, 5, 6, 7]  # the failed batch's frames


def _read_jsonl(path):
    from kcmc_tpu.obs.records import read_jsonl

    return read_jsonl(str(path))


def test_report_on_incomplete_records(tmp_path):
    # a killed run leaves no summary line; report degrades gracefully
    (tmp_path / "dead.jsonl").write_text(
        json.dumps({"kind": "kcmc_frame_records", "version": 1})
        + "\n"
        + json.dumps(
            {
                "frame": 0, "model": "translation", "n_keypoints": 9,
                "n_matches": 8, "n_inliers": 7, "inlier_ratio": 0.875,
                "rms_residual_px": 0.2, "warp_ok": True, "failed": False,
                "failover": False, "escalated": False,
            }
        )
        + "\n"
    )
    run = load_run(str(tmp_path / "dead.jsonl"))
    assert run["incomplete"]
    text = render_report(run)
    assert "did not close cleanly" in text
    assert "Frame quality percentiles:" in text


def test_failed_run_flushes_postmortem_artifacts(tmp_path):
    """A run that dies mid-stream still leaves a readable trace and
    records file with the error recorded (the post-mortem use case)."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    data = make_drift_stack(
        n_frames=16, shape=(64, 64), model="translation", seed=0
    )
    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=4,
        trace_path=str(tmp_path / "post.json"),
        frame_records_path=str(tmp_path / "post.jsonl"),
        # fatal injected fault: no retries, no failover, no mark-failed
        fault_plan="device:step=2:raise",
        retry_attempts=1, failover_backend=None, degrade_mark_failed=False,
    )
    with pytest.raises(Exception, match="injected"):
        mc.correct(data.stack)
    trace = json.loads((tmp_path / "post.json").read_text())
    assert "error" in trace["metadata"]
    _, records, summary = _read_jsonl(tmp_path / "post.jsonl")
    assert summary is not None and "error" in summary
    assert len(records) >= 4  # batches drained before the fault


def test_verbose_flag_routes_advisories(tmp_path, smoke_tif, capsys):
    # -v runs INFO-level logging; CLI mode routes advise() to stderr
    # logging instead of warnings (stdout stays pure JSON)
    assert (
        cli_main(["-v", "correct", smoke_tif, "--backend", "numpy",
                  "--batch-size", "8"])
        == 0
    )
    out = capsys.readouterr().out
    json.loads(out.strip().splitlines()[-1])  # machine-readable stdout
    assert obs_log.cli_logging_active()
