"""RANSAC consensus tests: outlier rejection, exact recovery, vmap over frames."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from test_transforms import make_gt, random_points

from kcmc_tpu.models import apply_transform, get_model
from kcmc_tpu.ops.ransac import ransac_estimate


def corrupt(dst, rng, frac):
    """Replace a fraction of correspondences with gross outliers."""
    dst = np.array(dst)
    n = len(dst)
    k = int(frac * n)
    idx = rng.choice(n, k, replace=False)
    dst[idx] = rng.uniform(0, 200, size=(k, dst.shape[1])).astype(np.float32)
    return dst, idx


@pytest.mark.parametrize("name", ["translation", "rigid", "affine", "homography", "rigid3d"])
def test_ransac_rejects_outliers(name, rng):
    model = get_model(name)
    src = random_points(rng, 128, model.ndim)
    M_gt = make_gt(name, rng)
    dst_clean = np.asarray(apply_transform(jnp.asarray(M_gt), jnp.asarray(src)))
    dst, out_idx = corrupt(dst_clean, rng, frac=0.4)
    # small noise on inliers
    dst = dst + rng.normal(0, 0.05, dst.shape).astype(np.float32)

    res = ransac_estimate(
        model,
        jnp.asarray(src),
        jnp.asarray(dst),
        jnp.ones(128, dtype=bool),
        jax.random.key(0),
        n_hypotheses=128,
        threshold=2.0,
    )
    resid = model.residual(res.transform, jnp.asarray(src), jnp.asarray(dst_clean))
    rms = float(jnp.sqrt(jnp.mean(resid)))
    assert rms < 0.2, f"{name}: rms vs clean dst {rms}"
    assert int(res.n_inliers) > 60
    # the gross outliers must be flagged as outliers
    inl = np.asarray(res.inlier_mask)
    assert not inl[out_idx].any()


def test_ransac_respects_valid_mask(rng):
    """Invalid matches must be ignored even if geometrically consistent."""
    model = get_model("translation")
    src = random_points(rng, 64, 2)
    # valid half moves by (5, 5); invalid half by (-20, -20)
    dst = src.copy()
    dst[:32] += 5.0
    dst[32:] -= 20.0
    valid = np.zeros(64, dtype=bool)
    valid[:32] = True
    res = ransac_estimate(
        model, jnp.asarray(src), jnp.asarray(dst), jnp.asarray(valid), jax.random.key(1)
    )
    np.testing.assert_allclose(np.asarray(res.transform)[:2, 2], [5.0, 5.0], atol=1e-3)
    assert int(res.n_inliers) == 32


def test_ransac_no_valid_matches_gives_identity():
    model = get_model("affine")
    src = jnp.zeros((32, 2))
    dst = jnp.zeros((32, 2))
    valid = jnp.zeros(32, dtype=bool)
    res = ransac_estimate(model, src, dst, valid, jax.random.key(0))
    np.testing.assert_allclose(np.asarray(res.transform), np.eye(3), atol=1e-6)
    assert int(res.n_inliers) == 0


def test_ransac_vmaps_over_frames(rng):
    """(frames x hypotheses) batching — the BASELINE north-star structure."""
    model = get_model("rigid")
    F, N = 4, 64
    srcs = np.stack([random_points(rng, N, 2) for _ in range(F)])
    gts = np.stack([make_gt("rigid", rng) for _ in range(F)])
    dsts = np.stack(
        [np.asarray(apply_transform(jnp.asarray(gts[i]), jnp.asarray(srcs[i]))) for i in range(F)]
    )
    keys = jax.random.split(jax.random.key(7), F)
    fn = jax.jit(
        jax.vmap(
            lambda s, d, k: ransac_estimate(
                model, s, d, jnp.ones(N, dtype=bool), k, n_hypotheses=64
            )
        )
    )
    res = fn(jnp.asarray(srcs), jnp.asarray(dsts), keys)
    assert res.transform.shape == (F, 3, 3)
    for i in range(F):
        np.testing.assert_allclose(np.asarray(res.transform[i]), gts[i], atol=5e-2)


def test_ransac_deterministic(rng):
    """Same key => identical result (cross-backend reproducibility contract)."""
    model = get_model("translation")
    src = random_points(rng, 64, 2)
    dst = src + np.array([3.0, -2.0], np.float32)
    a = ransac_estimate(model, jnp.asarray(src), jnp.asarray(dst), jnp.ones(64, bool), jax.random.key(5))
    b = ransac_estimate(model, jnp.asarray(src), jnp.asarray(dst), jnp.ones(64, bool), jax.random.key(5))
    np.testing.assert_array_equal(np.asarray(a.transform), np.asarray(b.transform))


def test_score_cap_sparse_frame_still_recovers():
    """score_cap's strided scoring subset can hold fewer valid matches
    than the model's minimal sample on sparse frames; the mixed
    hypothesis pool (first eighth sampled from the FULL set) must keep
    such frames recoverable (review finding, round 5)."""
    import jax
    import jax.numpy as jnp

    from kcmc_tpu.models.transforms import get_model
    from kcmc_tpu.ops.ransac import ransac_estimate

    rng = np.random.default_rng(3)
    model = get_model("affine")
    N = 4096
    # 12 valid matches clustered in slots the stride-4 subset mostly
    # misses: put them at consecutive odd-ish slots
    idxs = 4 * np.arange(12) + 1  # never hit by [::4]
    M_true = np.array(
        [[1.01, 0.004, 3.2], [-0.004, 0.99, -2.1], [0, 0, 1]], np.float32
    )
    src = rng.uniform(20, 480, (N, 2)).astype(np.float32)
    dst = (src @ M_true[:2, :2].T) + M_true[:2, 2]
    dst += rng.normal(0, 0.05, dst.shape).astype(np.float32)
    valid = np.zeros(N, bool)
    valid[idxs] = True
    res = ransac_estimate(
        model, jnp.asarray(src), jnp.asarray(dst.astype(np.float32)),
        jnp.asarray(valid), jax.random.key(0),
        n_hypotheses=128, threshold=2.0, score_cap=1024,
    )
    assert int(res.n_inliers) >= 10
    got = np.asarray(res.transform)
    corners = np.array([[0, 0], [511, 0], [0, 511], [511, 511]], np.float32)
    err = np.abs(
        (corners @ got[:2, :2].T + got[:2, 2])
        - (corners @ M_true[:2, :2].T + M_true[:2, 2])
    ).max()
    assert err < 1.0, err


def test_every_model_guards_degenerate_duplicated_samples():
    """ADVICE r5: _sample_indices can return the SAME valid match
    `min_samples` times (fewer valid matches than the minimal set), so
    every solver carries a mechanical obligation — a duplicated-point
    system must come back as the identity guard (a non-collapsing map),
    never NaN and never a finite map that collapses the plane onto the
    dst point (which would spuriously out-score honest hypotheses)."""
    from kcmc_tpu.models import MODELS

    for name, model in MODELS.items():
        d = model.ndim
        p = np.full((model.min_samples, d), 3.25, np.float32)
        w = np.ones(model.min_samples, np.float32)
        for label, dst in (("coincident", p), ("shifted", p + 2.0)):
            for solver in (model.solve, model.resolved_refine_solve):
                M = np.asarray(
                    solver(jnp.asarray(p), jnp.asarray(dst), jnp.asarray(w))
                )
                assert np.isfinite(M).all(), (name, label)
                lin = M[:d, :d]
                det = float(np.linalg.det(lin))
                assert abs(det) > 0.5, (name, label, M)
                if label == "coincident":
                    # no motion information at all: the guard identity
                    np.testing.assert_allclose(
                        M, np.eye(d + 1), atol=1e-5,
                        err_msg=f"{name}/{label}",
                    )
                else:
                    # a repeated point moved by a constant: identity
                    # (the guard) or a pure shift (translation's — and
                    # a centroid-matching rigid refine's — legitimate
                    # exact fit) are both fine; what is FORBIDDEN is a
                    # collapsing/shearing linear part
                    np.testing.assert_allclose(
                        lin @ lin.T, np.eye(d), atol=1e-3,
                        err_msg=f"{name}/{label}",
                    )
