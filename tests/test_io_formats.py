"""Pluggable streaming ingest (io/formats.py): Zarr / HDF5 / npy / raw
/ array sources stream through the same machinery as TIFF — prefetch,
checkpoint-resume, registration-only passes (SURVEY.md §1 stack-I/O
layer)."""

import json
import os
import zlib

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.io import ChunkedStackLoader, ZarrStack, open_stack
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE = (128, 128)
T = 24


@pytest.fixture(scope="module")
def drift():
    return synthetic.make_drift_stack(
        n_frames=T, shape=SHAPE, model="translation", max_drift=5.0, seed=7
    )


def _u16(stack):
    return np.clip(stack * 40000, 0, 65535).astype(np.uint16)


def _write_zarr(path, arr, chunks=(8, 64, 64), compress=True, sep="."):
    """Hand-rolled Zarr v2 store — no zarr dependency, which is the
    point: the built-in reader must handle stores other tools wrote."""
    os.makedirs(path)
    meta = {
        "zarr_format": 2,
        "shape": list(arr.shape),
        "chunks": list(chunks),
        "dtype": arr.dtype.str,
        "compressor": {"id": "zlib", "level": 1} if compress else None,
        "fill_value": 0,
        "order": "C",
        "filters": None,
        "dimension_separator": sep,
    }
    with open(os.path.join(path, ".zarray"), "w") as f:
        json.dump(meta, f)
    grid = [-(-s // c) for s, c in zip(arr.shape, chunks)]
    for idx in np.ndindex(*grid):
        block = np.zeros(chunks, arr.dtype)
        sl = tuple(
            slice(i * c, min((i + 1) * c, s))
            for i, c, s in zip(idx, chunks, arr.shape)
        )
        view = arr[sl]
        block[tuple(slice(0, v) for v in view.shape)] = view
        buf = block.tobytes()
        if compress:
            buf = zlib.compress(buf, 1)
        dst = os.path.join(path, sep.join(map(str, idx)))
        os.makedirs(os.path.dirname(dst), exist_ok=True)  # "/"-separated
        with open(dst, "wb") as f:
            f.write(buf)


@pytest.mark.parametrize("compress,sep", [(True, "."), (False, "/")])
def test_zarr_reader_roundtrip(tmp_path, drift, compress, sep):
    arr = _u16(drift.stack)
    path = tmp_path / "stack.zarr"
    _write_zarr(str(path), arr, compress=compress, sep=sep)
    with open_stack(str(path)) as ts:
        assert len(ts) == T
        assert ts.frame_shape == SHAPE
        assert ts.dtype == np.uint16
        np.testing.assert_array_equal(ts.read(0, T), arr)
        np.testing.assert_array_equal(ts.read(5, 11), arr[5:11])


def test_zarr_correct_file_end_to_end(tmp_path, drift):
    arr = _u16(drift.stack)
    zpath = tmp_path / "in.zarr"
    _write_zarr(str(zpath), arr)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=8)
    res = mc.correct_file(str(zpath), chunk_size=8)
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15


def test_zarr_checkpoint_resume_byte_identical(tmp_path, drift):
    """Kill+resume over a zarr source produces the same output TIFF as
    an uninterrupted run — the streaming machinery is format-blind."""
    arr = _u16(drift.stack)
    zpath = tmp_path / "in.zarr"
    _write_zarr(str(zpath), arr)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=4
    )
    ref_out = tmp_path / "ref.tif"
    mk().correct_file(str(zpath), output=str(ref_out), chunk_size=8)

    calls = {"n": 0}
    orig = ChunkedStackLoader._read

    def poisoned(self, lo, hi):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("simulated kill")
        return orig(self, lo, hi)

    out = tmp_path / "out.tif"
    ckpt = tmp_path / "run.ckpt.npz"
    ChunkedStackLoader._read = poisoned
    try:
        with pytest.raises(RuntimeError, match="simulated kill"):
            mk().correct_file(
                str(zpath), output=str(out), chunk_size=8,
                checkpoint=str(ckpt), checkpoint_every=8,
            )
    finally:
        ChunkedStackLoader._read = orig
    res = mk().correct_file(
        str(zpath), output=str(out), chunk_size=8, checkpoint=str(ckpt),
    )
    assert res.timing["restored_frames"] > 0
    assert ref_out.read_bytes() == out.read_bytes()


def test_hdf5_source(tmp_path, drift):
    h5py = pytest.importorskip("h5py")
    arr = _u16(drift.stack)
    path = tmp_path / "in.h5"
    with h5py.File(path, "w") as f:
        f.create_dataset("data/stack", data=arr, chunks=(4,) + SHAPE)
    with open_stack(str(path)) as ts:  # auto-discovered single dataset
        assert ts.frame_shape == SHAPE
        np.testing.assert_array_equal(ts.read(3, 9), arr[3:9])
    res = MotionCorrector(
        model="translation", backend="jax", batch_size=8
    ).correct_file(str(path), chunk_size=8)
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15


def test_npy_and_raw_sources(tmp_path, drift):
    arr = _u16(drift.stack)
    npy = tmp_path / "in.npy"
    np.save(npy, arr)
    with open_stack(str(npy)) as ts:
        np.testing.assert_array_equal(ts.read(0, 5), arr[:5])

    raw = tmp_path / "in.raw"
    arr.tofile(raw)
    with open_stack(
        str(raw), shape=arr.shape, dtype=np.uint16
    ) as ts:
        assert ts.dtype == np.uint16
        np.testing.assert_array_equal(ts.read(10, T), arr[10:])

    res = MotionCorrector(
        model="translation", backend="jax", batch_size=8
    ).correct_file(
        str(raw), chunk_size=8,
        reader_options=dict(shape=arr.shape, dtype=np.uint16),
    )
    assert res.transforms.shape == (T, 3, 3)


def test_array_source_streams(drift):
    """An in-memory array goes through the same streaming path."""
    res = MotionCorrector(
        model="translation", backend="jax", batch_size=8
    ).correct_file(_u16(drift.stack), chunk_size=8)
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15


def test_checkpoint_needs_path_source(drift):
    with pytest.raises(ValueError, match="file-path source"):
        MotionCorrector(model="translation", backend="jax").correct_file(
            _u16(drift.stack), output="x.tif", checkpoint="c.npz"
        )


def test_unknown_format_message(tmp_path):
    p = tmp_path / "stack.xyz"
    p.write_bytes(b"??")
    with pytest.raises(ValueError, match="unrecognized stack format"):
        open_stack(str(p))


def test_mini_zarr_rejects_exotic_compressor(tmp_path, drift):
    try:
        import zarr  # noqa: F401

        pytest.skip("zarr installed: the full reader handles blosc")
    except ImportError:
        pass
    arr = _u16(drift.stack)
    path = tmp_path / "b.zarr"
    _write_zarr(str(path), arr, compress=False)
    meta = json.loads((path / ".zarray").read_text())
    meta["compressor"] = {"id": "blosc", "cname": "zstd"}
    (path / ".zarray").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="blosc"):
        ZarrStack(str(path))


def _store_bytes(path):
    """Every chunk + metadata byte of a zarr directory store, keyed by
    entry name (byte-identity comparison helper)."""
    return {
        name: (path / name).read_bytes() for name in os.listdir(path)
    }


@pytest.mark.parametrize("compression", ["none", "deflate"])
def test_zarr_egress_roundtrip(tmp_path, drift, compression):
    """Round-5 write side (VERDICT r4 item 8): zarr-in -> zarr-out with
    no TIFF transcoding; the output store reads back through the same
    ingest protocol with the corrected pixels."""
    arr = _u16(drift.stack)
    zin = tmp_path / "in.zarr"
    _write_zarr(str(zin), arr)
    zout = tmp_path / "out.zarr"
    mc = MotionCorrector(model="translation", backend="jax", batch_size=8)
    res = mc.correct_file(
        str(zin), output=str(zout), chunk_size=8, output_dtype="input",
        compression=compression,
    )
    with open_stack(str(zout)) as ts:
        assert len(ts) == T
        assert ts.dtype == np.uint16
        got = ts.read(0, T)
    # output-file runs keep corrected out of memory; an in-memory run
    # of the same deterministic pipeline is the pixel oracle
    mem = MotionCorrector(
        model="translation", backend="jax", batch_size=8
    ).correct_file(str(zin), chunk_size=8, output_dtype="input")
    np.testing.assert_array_equal(got, mem.corrected)
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15


def test_zarr_egress_checkpoint_resume_byte_identical(tmp_path, drift):
    """Kill+resume with a ZARR output: every chunk file and the
    .zarray metadata must match an uninterrupted run byte for byte
    (the zarr writer has no offset chain, so this must hold exactly)."""
    arr = _u16(drift.stack)
    zin = tmp_path / "in.zarr"
    _write_zarr(str(zin), arr)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=4
    )
    ref_out = tmp_path / "ref.zarr"
    mk().correct_file(
        str(zin), output=str(ref_out), chunk_size=8, output_dtype="input",
        compression="deflate",
    )

    calls = {"n": 0}
    orig = ChunkedStackLoader._read

    def poisoned(self, lo, hi):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("simulated kill")
        return orig(self, lo, hi)

    out = tmp_path / "out.zarr"
    ckpt = tmp_path / "run.ckpt.npz"
    ChunkedStackLoader._read = poisoned
    try:
        with pytest.raises(RuntimeError, match="simulated kill"):
            mk().correct_file(
                str(zin), output=str(out), chunk_size=8,
                checkpoint=str(ckpt), checkpoint_every=8,
                output_dtype="input", compression="deflate",
            )
    finally:
        ChunkedStackLoader._read = orig
    res = mk().correct_file(
        str(zin), output=str(out), chunk_size=8, checkpoint=str(ckpt),
        output_dtype="input", compression="deflate",
    )
    assert res.timing["restored_frames"] > 0
    assert _store_bytes(ref_out) == _store_bytes(out)


def test_zarr_egress_apply_file(tmp_path, drift):
    """apply_correction_file writes .zarr outputs through the same
    factory seam."""
    from kcmc_tpu import apply_correction_file

    arr = _u16(drift.stack)
    zin = tmp_path / "in.zarr"
    _write_zarr(str(zin), arr)
    mc = MotionCorrector(model="translation", backend="jax", batch_size=8)
    res = mc.correct_file(str(zin), chunk_size=8)
    zout = tmp_path / "applied.zarr"
    apply_correction_file(
        str(zin), str(zout), transforms=res.transforms, chunk_size=8
    )
    with open_stack(str(zout)) as ts:
        assert len(ts) == T and ts.dtype == np.uint16


def test_hdf5_egress_roundtrip(tmp_path, drift):
    """h5-in -> h5-out with no transcoding: the contiguous early-alloc
    HDF5 writer (round 5) reads back through the ingest protocol with
    the corrected pixels."""
    h5py = pytest.importorskip("h5py")
    arr = _u16(drift.stack)
    hin = tmp_path / "in.h5"
    with h5py.File(hin, "w") as f:
        f.create_dataset("stack", data=arr)
    hout = tmp_path / "out.h5"
    mc = MotionCorrector(model="translation", backend="jax", batch_size=8)
    res = mc.correct_file(
        str(hin), output=str(hout), chunk_size=8, output_dtype="input",
    )
    with open_stack(str(hout)) as ts:
        assert len(ts) == T
        assert ts.dtype == np.uint16
        got = ts.read(0, T)
    mem = MotionCorrector(
        model="translation", backend="jax", batch_size=8
    ).correct_file(str(hin), chunk_size=8, output_dtype="input")
    np.testing.assert_array_equal(got, mem.corrected)
    err = transform_rmse(
        res.transforms, relative_transforms(drift.transforms), SHAPE
    )
    assert err < 0.15


def test_hdf5_egress_checkpoint_resume(tmp_path, drift):
    """Kill+resume with an HDF5 output: the contiguous layout's resume
    must reproduce an uninterrupted run's DATASET exactly. (Whole-file
    byte identity does not hold for HDF5 — object headers embed
    creation timestamps — so the contract is dataset bytes, which is
    what any reader consumes.)"""
    pytest.importorskip("h5py")
    arr = _u16(drift.stack)
    hin = tmp_path / "in.h5"
    import h5py

    with h5py.File(hin, "w") as f:
        f.create_dataset("stack", data=arr)
    mk = lambda: MotionCorrector(
        model="translation", backend="jax", batch_size=4
    )
    ref_out = tmp_path / "ref.h5"
    mk().correct_file(
        str(hin), output=str(ref_out), chunk_size=8, output_dtype="input",
    )

    calls = {"n": 0}
    orig = ChunkedStackLoader._read

    def poisoned(self, lo, hi):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("simulated kill")
        return orig(self, lo, hi)

    out = tmp_path / "out.h5"
    ckpt = tmp_path / "run.ckpt.npz"
    ChunkedStackLoader._read = poisoned
    try:
        with pytest.raises(RuntimeError, match="simulated kill"):
            mk().correct_file(
                str(hin), output=str(out), chunk_size=8,
                checkpoint=str(ckpt), checkpoint_every=8,
                output_dtype="input",
            )
    finally:
        ChunkedStackLoader._read = orig
    res = mk().correct_file(
        str(hin), output=str(out), chunk_size=8, checkpoint=str(ckpt),
        output_dtype="input",
    )
    assert res.timing["restored_frames"] > 0
    with h5py.File(ref_out, "r") as fr, h5py.File(out, "r") as fo:
        a, b = fr["data"][...], fo["data"][...]
    np.testing.assert_array_equal(a, b)


def test_hdf5_egress_refuses_compression(tmp_path):
    pytest.importorskip("h5py")
    from kcmc_tpu.io.formats import HDF5Writer

    with pytest.raises(ValueError, match="zarr"):
        HDF5Writer(
            tmp_path / "o.h5", 4, SHAPE, np.uint16, compression="deflate"
        )
