"""Integration suite for the request-latency telemetry plane.

Serve-side (docs/OBSERVABILITY.md "Request latency"):

* two concurrent streams → the `metrics` verb reports per-segment
  p50/p99 for EVERY lifecycle segment, over a real socket;
* per-frame segment durations telescope: the segment sums equal the
  end-to-end sum (≈ wall time per request);
* merging the per-session histograms reproduces the plane-wide
  rollup bit for bit (the fleet-aggregation contract);
* the latency section schema is ONE schema across the `metrics`
  verb, `close_session` timing, and `kcmc_tpu report --json`;
* `kcmc_tpu top --once` renders a live server; `kcmc_tpu metrics
  --text` renders exposition from a live server and a dumped
  snapshot;
* journal.save / journal.resume are DURATION spans (tracer) and
  latency segments;
* one-shot `correct` records the shared dispatch/device/drain subset;
* `latency_telemetry=False` disables every record site.
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.obs.latency import LatencyHistogram, merge_histograms
from kcmc_tpu.serve.scheduler import StreamScheduler
from kcmc_tpu.utils.synthetic import make_drift_stack

MC_KW = dict(
    model="translation", backend="numpy", batch_size=8,
    max_keypoints=64, n_hypotheses=32,
)

SUMMARY_KEYS = {"count", "sum_s", "p50_s", "p90_s", "p99_s", "max_s"}
LIFECYCLE_SEGMENTS = {
    "request.admission", "request.queue_wait", "request.batch_form",
    "request.dispatch", "request.device", "request.drain",
    "request.delivery", "request.total",
}


def _stack(n=16, seed=0, shape=(48, 48)):
    d = make_drift_stack(
        n_frames=n, shape=shape, model="translation", max_drift=3.0,
        seed=seed,
    )
    return d.stack.astype(np.float32)


def _drain_fully(sess, total):
    seen = 0
    while seen < total:
        got = sess.fetch(timeout=60)
        assert got is not None
        seen += got["n"]
    return seen


def _wait_idle(sched, total, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = sched.stats()
        if (
            st["frames_done"] >= total
            and st["inflight_batches"] == 0
            and not any(st["queues"].values())
        ):
            return st
        time.sleep(0.02)
    raise AssertionError("scheduler never went idle")


# -- two concurrent streams over the real socket -----------------------------


def test_metrics_verb_reports_every_segment_two_streams():
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(**MC_KW)
    with ServeServer(mc, port=0) as srv:
        def drive(i):
            with ServeClient(port=srv.port) as c:
                sid = c.open_session(tenant=f"t{i}")
                stack = _stack(12, seed=i)
                for lo in range(0, 12, 5):
                    c.submit(sid, stack[lo : lo + 5])
                seen = 0
                while seen < 12:
                    span = c.results(sid, timeout=60.0)
                    assert span is not None
                    seen += span["n"]
                c.close_session(sid)

        ts = [threading.Thread(target=drive, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        with ServeClient(port=srv.port) as c:
            m = c.metrics()
    assert m["schema"] == "kcmc_metrics/1"
    assert m["latency_telemetry"] is True
    segs = m["plane"]["segments"]
    assert LIFECYCLE_SEGMENTS <= set(segs), sorted(segs)
    for seg in LIFECYCLE_SEGMENTS:
        for rung, s in segs[seg].items():
            assert set(s) == SUMMARY_KEYS, (seg, rung)
            assert s["count"] > 0 and s["p50_s"] is not None
            assert s["p99_s"] >= s["p50_s"] - 1e-9
    # both streams' frames flowed through the plane rollup
    assert m["plane"]["totals"]["request.total"]["count"] == 24
    assert m["counters"]["frames_done"] == 24


def test_segment_sums_telescope_to_end_to_end_and_wall_time():
    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        t0 = time.perf_counter()
        s = sched.open_session(tenant="w")
        sched.submit(s.sid, _stack(16))
        _drain_fully(s, 16)
        res = sched.close_session(s.sid, timeout=120)
        wall = time.perf_counter() - t0
    finally:
        sched.stop()
    totals = res.timing["latency"]["totals"]
    parts = sum(
        totals[seg]["sum_s"]
        for seg in LIFECYCLE_SEGMENTS
        if seg != "request.total"
    )
    e2e = totals["request.total"]["sum_s"]
    # per-frame segments tile [submit call, fetch] exactly — the sums
    # agree to histogram ns truncation + summary rounding
    assert parts == pytest.approx(e2e, rel=0.02, abs=1e-3), (parts, e2e)
    # and no request outlives the run
    assert totals["request.total"]["max_s"] <= wall + 0.05
    assert totals["request.total"]["count"] == 16


def test_cross_session_merge_is_bit_identical_to_plane_rollup():
    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        a = sched.open_session(tenant="A")
        b = sched.open_session(tenant="B")
        sched.submit(a.sid, _stack(8, seed=0))
        sched.submit(b.sid, _stack(8, seed=1))
        _drain_fully(a, 8)
        _drain_fully(b, 8)
        _wait_idle(sched, 16)
        m = sched.metrics()
        # quiesced: merging the two sessions' exported histograms must
        # reproduce the plane rollup EXACTLY (the fleet aggregator's
        # contract — integer state, no float drift)
        sessions = m["sessions"]
        assert set(sessions) == {a.sid, b.sid}
        merged: dict = {}
        for sid in sorted(sessions):
            for seg, rungs in sessions[sid]["histograms"].items():
                for rung, d in rungs.items():
                    h = LatencyHistogram.from_dict(d)
                    key = (seg, rung)
                    merged[key] = (
                        h if key not in merged
                        else merge_histograms(merged[key], h)
                    )
        plane = m["plane"]["histograms"]
        rebuilt = {}
        for (seg, rung), h in merged.items():
            rebuilt.setdefault(seg, {})[rung] = h.to_dict()
        assert rebuilt == plane
        # closing folds the sessions into the rollup without changing it
        ra = sched.close_session(a.sid, timeout=120)
        sched.close_session(b.sid, timeout=120)
        m2 = sched.metrics()
        assert m2["plane"]["histograms"] == plane
        # one schema: the close timing's latency section carries the
        # same summary keys as the metrics verb
        for seg, rungs in ra.timing["latency"]["segments"].items():
            for rung, s in rungs.items():
                assert set(s) == SUMMARY_KEYS, (seg, rung)
    finally:
        sched.stop()


def test_heartbeat_snapshot_carries_latency_pulse():
    from kcmc_tpu.obs.heartbeat import aggregate_sampler

    mc = MotionCorrector(**MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="hb")
        sched.submit(s.sid, _stack(8))
        _drain_fully(s, 8)
        snap = sched.snapshot()
        assert "latency" in snap
        assert snap["latency"]["p99_ms"] >= snap["latency"]["p50_ms"]
        line = aggregate_sampler(sched.snapshot)()
        assert "latency p50=" in line and "p99=" in line
        sched.close_session(s.sid, timeout=120)
    finally:
        sched.stop()


# -- CLI surfaces ------------------------------------------------------------


def test_top_once_and_metrics_cli_live_and_snapshot(tmp_path, capsys):
    from kcmc_tpu.__main__ import main as cli_main
    from kcmc_tpu.obs.top import main as top_main
    from kcmc_tpu.serve.client import ServeClient
    from kcmc_tpu.serve.server import ServeServer

    mc = MotionCorrector(**MC_KW)
    with ServeServer(mc, port=0) as srv:
        with ServeClient(port=srv.port) as c:
            sid = c.open_session(tenant="cli")
            c.submit(sid, _stack(8))
            seen = 0
            while seen < 8:
                span = c.results(sid, timeout=60.0)
                assert span is not None
                seen += span["n"]
            snap_path = tmp_path / "metrics.json"
            snap_path.write_text(json.dumps(c.metrics()))
        addr = f"127.0.0.1:{srv.port}"
        # live one-shot dashboard render
        rc = top_main(
            argparse.Namespace(addr=addr, interval=2.0, once=True)
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "kcmc_tpu top" in out
        assert "request.total" in out
        assert "cli" in out  # the session row
        # live scrape, text exposition
        assert cli_main(["metrics", addr, "--text"]) == 0
        text = capsys.readouterr().out
        assert "kcmc_request_latency_seconds_bucket" in text
        assert "kcmc_serve_frames_done_total" in text
    # snapshot re-render (no server needed)
    assert cli_main(["metrics", str(snap_path), "--text"]) == 0
    text = capsys.readouterr().out
    assert "kcmc_request_latency_seconds_count" in text
    # JSON passthrough keeps the schema
    assert cli_main(["metrics", str(snap_path)]) == 0
    m = json.loads(capsys.readouterr().out)
    assert m["schema"] == "kcmc_metrics/1"


def test_top_once_unreachable_exits_nonzero(capsys):
    from kcmc_tpu.obs.top import main as top_main

    rc = top_main(
        argparse.Namespace(addr="127.0.0.1:1", interval=2.0, once=True)
    )
    assert rc == 1


# -- journal durability spans ------------------------------------------------


def test_journal_save_and_resume_are_duration_spans(tmp_path):
    trace = tmp_path / "t.json"
    kw = dict(
        MC_KW, serve_journal_dir=str(tmp_path / "j"),
        serve_journal_every=4, trace_path=str(trace),
    )
    mc = MotionCorrector(**kw)
    sched = StreamScheduler(mc).start()
    sid = None
    try:
        s = sched.open_session(tenant="jr")
        sid = s.sid
        sched.submit(s.sid, _stack(12))
        _drain_fully(s, 12)
        _wait_idle(sched, 12)
        # journal.save landed as a latency segment...
        assert "journal.save" in s.lat.report()["segments"]
        # ...and as DURATION spans on the session trace (the old
        # instants carried dur 0 and hid the write cost)
        evs = [
            e
            for e in s.telemetry.tracer.events()
            if e["name"] == "journal.save"
        ]
        assert evs and all(e["ph"] == "X" for e in evs)
        assert any(e["dur"] > 0 for e in evs)
    finally:
        sched.stop()  # journals the still-open session (keep_journal)
    # restart: resume_session rehydrates and records journal.resume
    mc2 = MotionCorrector(**kw)
    sched2 = StreamScheduler(mc2).start()
    try:
        sess, cursor, resumed = sched2.resume_session(sid)
        assert resumed and cursor == 12
        rep = sess.lat.report()["segments"]
        assert "journal.resume" in rep
        assert rep["journal.resume"]["full"]["count"] == 1
        evs = [
            e
            for e in sess.telemetry.tracer.events()
            if e["name"] == "journal.resume"
        ]
        assert evs and evs[0]["ph"] == "X"
        # the plane rollup sees it too (metrics verb surface)
        assert "journal.resume" in sched2.metrics()["plane"]["segments"]
        sched2.close_session(sid, timeout=120)
    finally:
        sched2.stop()


# -- one shared vocabulary: one-shot runs + report ---------------------------


def test_one_shot_correct_records_shared_subset(tmp_path):
    records = tmp_path / "fr.jsonl"
    mc = MotionCorrector(frame_records_path=str(records), **MC_KW)
    res = mc.correct(_stack(16))
    lat = res.timing["latency"]
    # sync backends (numpy) execute inside the dispatch call: that
    # interval is request.device, and request.dispatch is skipped so
    # the kernel time is never double-counted (async backends record
    # all three — the CI observability smoke covers the jax path)
    assert {"request.device", "request.drain"} <= set(lat["segments"])
    assert "request.dispatch" not in lat["segments"]
    for seg, rungs in lat["segments"].items():
        assert seg in LIFECYCLE_SEGMENTS
        for s in rungs.values():
            assert set(s) == SUMMARY_KEYS
            assert s["count"] == 16 or s["count"] > 0
    # report --json surfaces the section with the SAME schema
    from kcmc_tpu.obs.report import _json_summary, load_run, render_report

    run = load_run(str(records))
    summary = _json_summary(run, top=5)
    assert summary["latency"] == lat
    text = render_report(run)
    assert "Request latency" in text
    assert "request.device" in text


def test_report_renders_dash_on_pre_plane_artifacts():
    # artifacts from before this PR carry no latency section: the
    # renderer must skip gracefully and --json must carry None
    from kcmc_tpu.obs.report import _json_summary, render_report

    run = {
        "source": "old.jsonl",
        "records": [],
        "timing": {
            "stages_s": {"warp": 1.0},
            "stage_counts": {"warp": 1},
            "stage_mean_s": {"warp": 1.0},
            "total_s": 1.0,
        },
    }
    text = render_report(run)
    assert "Request latency" not in text  # no crash, no empty table
    assert _json_summary(run, top=5)["latency"] is None
    # and a partial section with missing stats renders the em dash
    run["timing"]["latency"] = {
        "segments": {"request.total": {"full": {"count": 1}}},
        "totals": {},
    }
    text = render_report(run)
    assert "Request latency" in text and "—" in text


def test_latency_telemetry_off_disables_every_site():
    mc = MotionCorrector(latency_telemetry=False, **MC_KW)
    sched = StreamScheduler(mc).start()
    try:
        s = sched.open_session(tenant="off")
        assert s.lat is None
        sched.submit(s.sid, _stack(8))
        res = sched.close_session(s.sid, timeout=120)
        assert "latency" not in res.timing
        m = sched.metrics()
        assert m["latency_telemetry"] is False
        assert m["plane"]["segments"] == {}
        assert m["counters"]["frames_done"] == 8  # health surface intact
    finally:
        sched.stop()
