"""Execution plans (kcmc_tpu/plans): bucket-padding parity, AOT
warm-up, persistent plan stamps, and the obs/serve surfaces.

The load-bearing contract is PARITY: a 2D matrix-model input routed
through a padding bucket must produce the same results as the
unbucketed path — detection masked to the valid extent is candidate-
for-candidate identical (zero pad + SAME-zero-padding convolutions
leave every response value in the valid region bit-equal), and the
post-warp valid-coverage mask restores out-of-bounds-is-zero exactly,
so with the photometric polish off the parity is BITWISE; the tests
assert 1e-4 to leave float headroom across BLAS builds. The polish
measures over the bucket canvas (valid-extent-gated regions), so
polish-on runs agree to the partition-noise level instead — asserted
against ground truth, not bit-parity (docs/PERFORMANCE.md "Cold-start
anatomy" documents the semantic).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from kcmc_tpu.config import CorrectorConfig
from kcmc_tpu.corrector import MotionCorrector
from kcmc_tpu.plans.buckets import normalize_buckets, route_shape
from kcmc_tpu.utils.synthetic import make_drift_stack


@pytest.fixture
def drift_stack():
    data = make_drift_stack(
        n_frames=10, shape=(50, 70), model="translation", max_drift=6.0,
        seed=0,
    )
    return np.asarray(data.stack, np.float32)


def _correct(stack, output_dtype="float32", **kw):
    defaults = dict(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=100, transform_polish=0,
    )
    defaults.update(kw)
    return MotionCorrector(**defaults).correct(
        stack, output_dtype=output_dtype
    )


# -- bucket policy ---------------------------------------------------------


def test_normalize_buckets_canonical():
    assert normalize_buckets(None) == ()
    assert normalize_buckets(()) == ()
    assert normalize_buckets(512) == ((512, 512),)
    # ladder of squares + a rectangle, area-sorted, deduplicated
    got = normalize_buckets((512, (480, 640), 512, 1024))
    assert got == ((512, 512), (480, 640), (1024, 1024))
    assert normalize_buckets([64]) == ((64, 64),)


def test_normalize_buckets_rejects_garbage():
    with pytest.raises(ValueError):
        normalize_buckets((8,))  # below the 32x32 floor
    with pytest.raises(ValueError):
        normalize_buckets(("512",))
    with pytest.raises(ValueError):
        normalize_buckets(((64, 64, 64),))


def test_route_shape_smallest_cover():
    buckets = normalize_buckets((64, (64, 80), 128))
    assert route_shape((50, 70), buckets) == (64, 80)
    assert route_shape((64, 64), buckets) == (64, 64)
    assert route_shape((100, 100), buckets) == (128, 128)
    assert route_shape((500, 500), buckets) is None
    assert route_shape((50,), buckets) is None


def test_config_normalizes_and_validates():
    c = CorrectorConfig(plan_buckets=[64, (64, 80)])
    assert c.plan_buckets == ((64, 64), (64, 80))
    assert hash(c) is not None  # stays hashable (jit cache key)
    with pytest.raises(ValueError):
        CorrectorConfig(compile_cache_dir="")
    with pytest.raises(ValueError):
        CorrectorConfig(plan_buckets=(16,))


# -- bucket-padding parity -------------------------------------------------


def test_padded_route_parity_translation(drift_stack):
    """Odd (50, 70) frames through a (64, 80) bucket: transforms,
    corrected pixels, and the detection diagnostics all match the
    unbucketed path (uneven tail batch 10 % 4 != 0 and non-aligned
    K=100 included); quality metrics are computed at the true shape."""
    kw = dict(quality_metrics=True)
    plain = _correct(drift_stack, **kw)
    routed = _correct(drift_stack, plan_buckets=((64, 80),), **kw)
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=1e-4
    )
    np.testing.assert_allclose(routed.corrected, plain.corrected, atol=1e-4)
    for k in ("n_keypoints", "n_matches", "n_inliers"):
        np.testing.assert_array_equal(
            routed.diagnostics[k], plain.diagnostics[k]
        )
    np.testing.assert_allclose(
        routed.diagnostics["template_corr"],
        plain.diagnostics["template_corr"],
        atol=1e-5,
    )
    np.testing.assert_allclose(
        routed.diagnostics["coverage"], plain.diagnostics["coverage"],
        atol=1e-5,
    )
    assert routed.timing["plan_cache"]["bucket_padded"] > 0


def test_exact_bucket_shape_counts_exact(drift_stack):
    """A shape that IS a bucket routes with no padding; results match
    the plain program and the exact-hit counter records it."""
    data = make_drift_stack(
        n_frames=6, shape=(64, 80), model="translation", max_drift=5.0,
        seed=2,
    )
    stack = np.asarray(data.stack, np.float32)
    plain = _correct(stack)
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=100, transform_polish=0, plan_buckets=((64, 80),),
    )
    routed = mc.correct(stack)
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=1e-4
    )
    stats = mc.backend.plan_cache_stats()
    assert stats["bucket_exact"] > 0
    assert stats["bucket_padded"] == 0


def test_unroutable_shape_counts_fallback(drift_stack):
    """No covering bucket: the run falls back to an exact-shape compile
    (results untouched) and counts the miss."""
    plain = _correct(drift_stack)
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=100, transform_polish=0, plan_buckets=(32,),
    )
    routed = mc.correct(drift_stack)
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=1e-4
    )
    assert mc.backend.plan_cache_stats()["bucket_fallback"] > 0


def test_rolling_template_parity_through_buckets():
    """Rolling template updates (device-resident path) compose with
    bucket routing: blends happen at the true shape, re-extraction
    routes through the bucket."""
    data = make_drift_stack(
        n_frames=12, shape=(50, 70), model="translation", max_drift=5.0,
        seed=4,
    )
    stack = np.asarray(data.stack, np.float32)
    kw = dict(template_update_every=5, template_window=4)
    plain = _correct(stack, **kw)
    routed = _correct(stack, plan_buckets=((64, 80),), **kw)
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=1e-4
    )


def test_uint16_native_dtype_parity():
    """Native-dtype (uint16) uploads through a padding bucket: the
    zero pad is valid uint16; outputs cast identically."""
    data = make_drift_stack(
        n_frames=6, shape=(50, 70), model="translation", max_drift=5.0,
        seed=5,
    )
    stack = np.clip(np.asarray(data.stack) * 40000, 0, 65535).astype(
        np.uint16
    )
    plain = _correct(stack, output_dtype="input")
    routed = _correct(
        stack, plan_buckets=((64, 80),), output_dtype="input"
    )
    np.testing.assert_array_equal(routed.corrected, plain.corrected)
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=1e-4
    )


def test_polish_on_padded_route_hits_same_accuracy():
    """With the photometric polish ON, padded-route regions are
    measured over the bucket canvas (valid-extent-gated), so bit-parity
    is not the contract — landing on the same accuracy plateau is:
    both routes must beat the unpolished floor and agree closely."""
    from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

    data = make_drift_stack(
        n_frames=8, shape=(100, 120), model="affine", max_drift=5.0, seed=1
    )
    stack = np.asarray(data.stack, np.float32)
    truth = relative_transforms(data.transforms)
    kw = dict(model="affine", max_keypoints=128, transform_polish=1)
    plain = _correct(stack, **kw)
    routed = _correct(stack, plan_buckets=(128,), **kw)
    shape = (100, 120)
    rmse_plain = transform_rmse(plain.transforms, truth, shape)
    rmse_routed = transform_rmse(routed.transforms, truth, shape)
    unpolished = _correct(stack, **dict(kw, transform_polish=0))
    rmse_unpolished = transform_rmse(unpolished.transforms, truth, shape)
    assert rmse_routed < 0.6 * rmse_unpolished  # polish still engages
    assert abs(rmse_routed - rmse_plain) < 0.02  # same plateau
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=0.1
    )


def test_numpy_backend_ignores_buckets(drift_stack):
    """The numpy oracle accepts and ignores plan_buckets (failover from
    a bucketed jax run needs no config scrub) — results identical."""
    plain = _correct(drift_stack, backend="numpy")
    routed = _correct(
        drift_stack, backend="numpy", plan_buckets=((64, 80),)
    )
    np.testing.assert_array_equal(routed.transforms, plain.transforms)
    info = MotionCorrector(
        model="translation", backend="numpy", plan_buckets=(64,)
    ).backend.runtime_info()
    assert info["plan_buckets_ignored"] == [[64, 64]]


def test_mesh_bucketed_parity(drift_stack):
    """Bucket routing composes with mesh sharding (valid_hw rides
    replicated through shard_map; exports disabled on mesh)."""
    plain = _correct(drift_stack)
    routed = _correct(
        drift_stack, plan_buckets=((64, 80),), mesh_devices=2
    )
    np.testing.assert_allclose(
        routed.transforms, plain.transforms, atol=1e-4
    )


# -- warm-up / persistent plan cache ---------------------------------------


@pytest.fixture
def compile_cache(tmp_path):
    """A tmpdir persistent compile cache, force-disabled afterwards so
    the process-global jax config never points at a deleted dir."""
    from kcmc_tpu.plans.cache import disable_compile_cache

    yield str(tmp_path / "cache")
    disable_compile_cache()


def test_warmup_builds_and_second_backend_hits_stamps(compile_cache):
    common = dict(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=64, plan_buckets=(48,),
        compile_cache_dir=compile_cache,
    )
    mc1 = MotionCorrector(**common)
    w1 = mc1.warmup()
    assert w1["programs_built"] >= 2  # reference + register (+ apply)
    assert w1["stamp_misses"] >= 1
    assert w1["persistent"] is True
    # A FRESH backend (same config): every program is stamped, so the
    # rebuild reports hits only — the cross-process warm-start contract
    # (jit objects are new, the persistent caches are not).
    mc2 = MotionCorrector(**common)
    w2 = mc2.warmup()
    assert w2["stamp_misses"] == 0
    assert w2["stamp_hits"] == w1["stamp_hits"] + w1["stamp_misses"]
    # stamps live under the cache dir
    import os

    assert os.path.isdir(os.path.join(compile_cache, "kcmc_plans"))


def test_export_bridge_serves_warm_batches(compile_cache, drift_stack):
    """A warm-start backend (fresh jit objects, populated caches)
    serves its batches through the deserialized exported program (the
    jit swap engages later, after a few steady calls) — multi-batch
    results match the plain path bitwise-ish throughout."""
    common = dict(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=100, transform_polish=0,
        plan_buckets=((64, 80),), compile_cache_dir=compile_cache,
    )
    import glob
    import os
    import time

    mc_cold = MotionCorrector(**common)
    cold = mc_cold.correct(drift_stack)  # builds + exports (background)
    # The export threads run in the background — wait for the blobs
    # (reference + register) so the warm run deterministically takes
    # the bridge path instead of racing to a plain rebuild.
    deadline = time.monotonic() + 120
    exports = os.path.join(compile_cache, "kcmc_exports", "*.bin")
    while len(glob.glob(exports)) < 2 and time.monotonic() < deadline:
        time.sleep(0.2)
    assert len(glob.glob(exports)) >= 2, "export blobs never landed"
    # Fresh backend = a new process's state as far as jit caches go;
    # the exported blobs + stamps persist.
    mc_warm = MotionCorrector(**common)
    warm = mc_warm.correct(drift_stack)  # 3 batches: bridge then swap
    np.testing.assert_allclose(
        warm.transforms, cold.transforms, atol=1e-5
    )
    np.testing.assert_allclose(warm.corrected, cold.corrected, atol=1e-4)
    pc = warm.timing["plan_cache"]
    assert pc["stamp_misses"] == 0 and pc["stamp_hits"] >= 2


def test_warmup_requires_buckets():
    mc = MotionCorrector(model="translation", backend="jax")
    with pytest.raises(ValueError, match="bucket"):
        mc.warmup()


def test_warmed_correct_runs_and_reports(compile_cache, drift_stack):
    """After warmup, a correction at an odd covered shape dispatches
    with zero stamp misses and reports plan stats in timing."""
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=100, transform_polish=0,
        plan_buckets=((64, 80),), compile_cache_dir=compile_cache,
    )
    mc.warmup()
    res = mc.correct(drift_stack)
    pc = res.timing["plan_cache"]
    assert pc["enabled"] and pc["persistent"]
    assert pc["bucket_padded"] > 0
    assert pc["stamp_misses"] >= 1  # this process built them fresh
    plain = _correct(drift_stack)
    np.testing.assert_allclose(
        res.transforms, plain.transforms, atol=1e-4
    )


def test_trace_carries_plan_spans(tmp_path, drift_stack):
    """A traced run records jit_compile spans (cat="plan") and the
    plan_cache snapshot rides in the trace metadata timing."""
    trace = tmp_path / "t.json"
    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=96,  # fresh K: forces a compile inside the traced run
        transform_polish=0, plan_buckets=((64, 80),),
        trace_path=str(trace),
    )
    mc.correct(drift_stack)
    data = json.loads(trace.read_text())
    names = {e["name"] for e in data["traceEvents"]}
    assert "jit_compile" in names
    assert data["metadata"]["timing"]["plan_cache"]["programs_compiled"] > 0


def test_report_renders_plan_section(tmp_path, drift_stack, capsys):
    """`kcmc_tpu report` on a --transforms npz of a plans run shows the
    warm-up / compile-cache section."""
    from kcmc_tpu.__main__ import main

    stack_path = tmp_path / "stack.tif"
    from kcmc_tpu.io.tiff import write_stack

    write_stack(
        str(stack_path),
        np.clip(drift_stack * 40000, 0, 65535).astype(np.uint16),
    )
    npz = tmp_path / "reg.npz"
    rc = main([
        "correct", str(stack_path), "--transforms", str(npz),
        "--batch-size", "4", "--max-keypoints", "100",
        "--transform-polish", "0", "--buckets", "64x80",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["plan_cache"]["bucket_padded"] > 0
    rc = main(["report", str(npz)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Warm-up / compile cache" in out


def test_warmup_cli(tmp_path, capsys):
    from kcmc_tpu.__main__ import main
    from kcmc_tpu.plans.cache import disable_compile_cache

    try:
        rc = main([
            "warmup", "--buckets", "48", "--batch-size", "4",
            "--max-keypoints", "64",
            "--compile-cache", str(tmp_path / "cache"),
        ])
        assert rc == 0
        stats = json.loads(capsys.readouterr().out.strip())
        assert stats["programs_built"] >= 2
        assert stats["persistent"] is True
        assert stats["buckets"] == [[48, 48]]
    finally:
        disable_compile_cache()


def test_serve_stats_carry_plan_cache():
    from kcmc_tpu.serve.scheduler import StreamScheduler

    mc = MotionCorrector(
        model="translation", backend="jax", batch_size=4,
        max_keypoints=64, plan_buckets=(48,),
    )
    sched = StreamScheduler(mc)
    stats = sched.stats()  # works unstarted: pure snapshot
    assert stats["plan_cache"]["enabled"] is True
    assert stats["plan_cache"]["buckets"] == [[48, 48]]


def test_compile_cache_dir_is_resume_signature_neutral():
    from kcmc_tpu.corrector import _ROBUSTNESS_SIG_NEUTRAL

    assert "compile_cache_dir" in _ROBUSTNESS_SIG_NEUTRAL
    assert "plan_buckets" not in _ROBUSTNESS_SIG_NEUTRAL
