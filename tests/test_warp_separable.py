"""Separable (shear/scale multi-pass) warp vs the gather warp."""

import jax.numpy as jnp
import numpy as np
import pytest

from kcmc_tpu import MotionCorrector
from kcmc_tpu.ops.warp import warp_batch
from kcmc_tpu.ops.warp_separable import warp_batch_affine
from kcmc_tpu.utils import synthetic


def _mat(theta_deg=0.0, sx=1.0, sy=1.0, tx=0.0, ty=0.0, c=127.5):
    th = np.deg2rad(theta_deg)
    R = np.array(
        [
            [np.cos(th) * sx, -np.sin(th) * sy, 0],
            [np.sin(th) * sx, np.cos(th) * sy, 0],
            [0, 0, 1.0],
        ]
    )
    C = np.array([[1, 0, c], [0, 1, c], [0, 0, 1.0]])
    Ci = np.array([[1, 0, -c], [0, 1, -c], [0, 0, 1.0]])
    T = np.array([[1, 0, tx], [0, 1, ty], [0, 0, 1.0]])
    return (C @ R @ Ci @ T).astype(np.float32)


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(3)
    return synthetic.render_scene(rng, (256, 256), n_blobs=120).astype(np.float32)


def test_exact_for_axis_aligned(img):
    """Translation and scale (no shear) are one 1D resample per axis —
    identical to 2D bilinear."""
    cases = [_mat(), _mat(tx=7.3, ty=-4.6), _mat(sx=1.02, sy=0.98)]
    frames = jnp.asarray(np.stack([img] * len(cases)))
    Ms = jnp.asarray(np.stack(cases))
    sep = np.asarray(warp_batch_affine(frames, Ms, shear_px=8))
    gat = np.asarray(warp_batch(frames, Ms))
    np.testing.assert_allclose(sep, gat, atol=2e-5)


def test_close_for_rotations(img):
    """Multi-pass interpolation differs from one-shot bilinear only at the
    interpolation-smoothing level in the interior."""
    cases = [_mat(theta_deg=1.0), _mat(theta_deg=-2.0, tx=3.2, ty=5.9),
             _mat(theta_deg=1.5, sx=1.01, sy=0.99, tx=-6.2, ty=2.4)]
    frames = jnp.asarray(np.stack([img] * len(cases)))
    Ms = jnp.asarray(np.stack(cases))
    sep = np.asarray(warp_batch_affine(frames, Ms, shear_px=8))
    gat = np.asarray(warp_batch(frames, Ms))
    d = np.abs(sep - gat)[:, 16:-16, 16:-16]
    assert d.mean() < 5e-3, f"mean interior diff {d.mean():.4f}"
    assert d.max() < 0.15, f"max interior diff {d.max():.4f}"


def test_shear_out_of_bounds_zeroes(img):
    """Rotations beyond the static shear bound must zero the frame, not
    silently mis-resample."""
    frames = jnp.asarray(img[None])
    M = jnp.asarray(_mat(theta_deg=30.0)[None])
    out = np.asarray(warp_batch_affine(frames, M, shear_px=4))
    assert np.all(out == 0.0)


def test_projective_rejected(img):
    """A projective transform is outside the affine decomposition."""
    M = _mat(theta_deg=1.0)
    M[2, 0] = 1e-4
    out = np.asarray(warp_batch_affine(jnp.asarray(img[None]), jnp.asarray(M[None])))
    assert np.all(out == 0.0)


def test_pipeline_equivalence_rigid(img):
    """Forcing the separable warp must not change recovered transforms and
    must keep corrected frames close to the gather-warp output."""
    data = synthetic.make_drift_stack(
        n_frames=4, shape=(160, 160), model="rigid", max_drift=5.0, seed=9
    )
    # transform_polish=0: with the round-5 polish on, warped pixels
    # feed back into the estimate, so the separable chain's ~0.01 px
    # interpolation artifact becomes a ~0.01 px transform offset at the
    # polish optimum — a property of the ESTIMATOR feedback, not of
    # the warp kernel this test pins (and why warp='auto' routes rigid
    # to the artifact-free matrix kernel on TPU). Without polish the
    # estimation is warp-independent and the old exact bound holds.
    r_jnp = MotionCorrector(
        model="rigid", backend="jax", batch_size=4, warp="jnp",
        transform_polish=0,
    ).correct(data.stack)
    r_sep = MotionCorrector(
        model="rigid", backend="jax", batch_size=4, warp="separable",
        transform_polish=0,
    ).correct(data.stack)
    np.testing.assert_allclose(r_sep.transforms, r_jnp.transforms, atol=1e-6)
    d = np.abs(r_sep.corrected - r_jnp.corrected)[:, 16:-16, 16:-16]
    assert d.mean() < 5e-3


def test_separable_rejected_for_unsupported_models():
    # homography is ALLOWED since round 5 (the affine+residual split
    # chain stays reachable as the zoom-unbounded projective route)
    with pytest.raises(ValueError, match="separable"):
        MotionCorrector(model="piecewise", backend="jax", warp="separable")
    MotionCorrector(model="homography", backend="jax", warp="separable")
