"""The runtime concurrency sanitizer (kcmc_tpu/analysis/sanitize.py).

Layers under test:

* instrumented locks: creation-site identity, order-edge recording,
  cycle conviction (runtime-only AND merged with static edges);
* the deadlock watchdog: a held lock with waiters past the threshold
  records a violation and dumps stacks;
* the leak checker: threads, telemetry path claims;
* regression coverage for the PR's concurrency fixes: the serve
  scheduler, session, heartbeat, and async writer run their
  cross-thread paths under the sanitizer with zero violations.

Every test arms/disarms the sanitizer itself (the suite must behave
identically with and without the global --sanitize option).
"""

from __future__ import annotations

import threading
import time

import pytest

from kcmc_tpu.analysis import sanitize


@pytest.fixture
def san():
    owned = not sanitize.active()
    if owned:
        sanitize.enable(watchdog_s=0.3, static=False)
    yield sanitize
    sanitize.take_violations()
    if owned:
        sanitize.disable()


def test_lock_order_cycle_is_a_violation(san):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    v = san.take_violations()
    assert v and "lock-order violation" in v[0], v
    assert "test_sanitize.py" in v[0]


def test_consistent_order_is_quiet(san):
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    assert san.take_violations() == []


def test_static_graph_convicts_single_executed_order(san):
    """One executed order + the static reverse edge = violation, no
    unlucky interleaving required."""
    a = threading.Lock()
    b = threading.Lock()
    # inject the static edge a→b (as static_order_edges would for a
    # written `with self._a: with self._b:` nesting)
    st = sanitize._STATE
    st.static_edges.add((a.site, b.site))
    with b:  # runtime executes ONLY the reverse order
        with a:
            pass
    v = san.take_violations()
    assert v and "lock-order violation" in v[0], v


def test_rlock_reentrancy_and_condition_alias_are_quiet(san):
    """The serving-plane shape: an RLock, a Condition built on it,
    reentrant acquisition through both — no edges, no violations."""
    lock = threading.RLock()
    cond = threading.Condition(lock)
    with lock:
        with cond:  # same identity: no self-edge
            cond.notify_all()
    assert cond.site == lock.site
    assert san.take_violations() == []


def test_condition_wait_releases_and_reacquires(san):
    lock = threading.RLock()
    cond = threading.Condition(lock)
    hits = []

    def waiter():
        with cond:
            hits.append("waiting")
            cond.wait(timeout=5.0)
            hits.append("woke")

    t = threading.Thread(target=waiter, name="kcmc-test-waiter")
    t.start()
    for _ in range(100):
        if hits:
            break
        time.sleep(0.01)
    with cond:  # must be acquirable while the waiter waits
        cond.notify_all()
    t.join(timeout=5.0)
    assert hits == ["waiting", "woke"]
    assert san.take_violations() == []


def test_watchdog_dumps_on_held_lock_with_waiters(san, capsys):
    # pin the threshold regardless of how the sanitizer was enabled
    # (a global --sanitize run uses the default 10 s)
    st = sanitize._STATE
    old_ws = st.watchdog_s
    st.stop_watchdog()
    st.watchdog_s = 0.3
    st.start_watchdog()
    lock = threading.Lock()

    def hold():
        with lock:
            time.sleep(0.9)

    t = threading.Thread(target=hold, name="kcmc-test-holder")
    t.start()
    time.sleep(0.1)

    def want():
        with lock:
            pass

    t2 = threading.Thread(target=want, name="kcmc-test-waiter")
    t2.start()
    t.join(timeout=5.0)
    t2.join(timeout=5.0)
    v = san.take_violations()
    st.stop_watchdog()
    st.watchdog_s = old_ws
    st.start_watchdog()
    assert any("deadlock suspect" in x for x in v), v


def test_leak_checker_catches_thread_and_path_claim(san):
    from kcmc_tpu.obs import run as obs_run

    before = san.leak_snapshot()
    stop = threading.Event()
    t = threading.Thread(
        target=stop.wait, name="kcmc-test-leaky", daemon=True
    )
    t.start()
    claimed = obs_run._claim_path("/tmp/kcmc-sanitize-leak.jsonl", "rX")
    leaks = san.check_leaks(before, grace_s=0.1)
    try:
        assert any("kcmc-test-leaky" in x for x in leaks), leaks
        assert any("kcmc-sanitize-leak" in x for x in leaks), leaks
    finally:
        obs_run._release_path(claimed)
        stop.set()
        t.join(timeout=5.0)
    # released + joined: clean now
    assert san.check_leaks(before, grace_s=0.5) == []


def test_disable_restores_threading_factories(san):
    pass  # the fixture disables on exit; assert after it in the next test


def test_factories_are_real_when_inactive():
    if sanitize.active():
        pytest.skip("global --sanitize run")
    lock = threading.Lock()
    assert not hasattr(lock, "site")


def test_stats_shape(san):
    lock = threading.Lock()
    with lock:
        pass
    s = san.stats()
    assert s["active"] is True
    assert s["locks_instrumented"] >= 1
    assert s["acquisitions"] >= 1


def test_cli_sanitize_wraps_command(monkeypatch):
    """`kcmc sanitize pytest …` re-execs with the env armed and the
    --sanitize option appended."""
    calls = {}

    def fake_call(cmd, env=None):
        calls["cmd"], calls["env"] = cmd, env
        return 0

    import subprocess

    monkeypatch.setattr(subprocess, "call", fake_call)
    rc = sanitize.main(
        ["--watchdog", "2", "--strict", "pytest", "tests/x.py", "-q"]
    )
    assert rc == 0
    assert calls["env"]["KCMC_SANITIZE"] == "1"
    assert calls["env"]["KCMC_SANITIZE_WATCHDOG"] == "2.0"
    assert calls["env"]["KCMC_SANITIZE_STRICT"] == "1"
    # armed through the env, NOT a --sanitize flag: the option only
    # exists under this repo's conftest rootdir
    assert "--sanitize" not in calls["cmd"]
    assert "tests/x.py" in calls["cmd"]


# -- regression: the PR's concurrency fixes run clean under the sanitizer ---


def test_async_writer_cross_thread_paths_sanitize_clean(san):
    """Regression for the unlocked worker-side `_exc` write and the
    unguarded `_stats` accumulation: hammer append/stats/flush from
    several threads while the worker runs, then surface a worker error
    exactly once across two racing closers."""
    from kcmc_tpu.io.async_writer import AsyncBatchWriter

    class SlowWriter:
        n_pages = 0

        def __init__(self):
            self.batches = []
            self.fail_after = None

        def append_batch(self, frames, n_threads=0):
            time.sleep(0.001)
            if self.fail_after is not None and len(
                self.batches
            ) >= self.fail_after:
                raise RuntimeError("disk full")
            self.batches.append(frames)

        def checkpoint_state(self):
            return {"pages": len(self.batches)}

        def close(self):
            pass

    w = AsyncBatchWriter(SlowWriter(), depth=2)
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            w.stats()
            time.sleep(0.0005)

    rt = threading.Thread(target=reader, name="kcmc-test-stats")
    rt.start()
    for i in range(20):
        w.append_batch([i])
    w.flush()
    stop.set()
    rt.join(timeout=5.0)
    assert w.stats()["batches"] == 20
    w.close()

    # exactly-once error surfacing across two racing closers
    inner = SlowWriter()
    inner.fail_after = 0
    w2 = AsyncBatchWriter(inner, depth=1)
    w2.append_batch([1])
    time.sleep(0.1)
    raised = []

    def closer():
        try:
            w2.close()
        except RuntimeError as e:
            raised.append(e)

    ts = [
        threading.Thread(target=closer, name=f"kcmc-test-closer-{i}")
        for i in range(2)
    ]
    [t.start() for t in ts]
    [t.join(5.0) for t in ts]
    assert len(raised) == 1, raised
    assert san.take_violations() == []


def test_heartbeat_cross_thread_start_stop_sanitize_clean(san):
    """Regression for the unguarded `_thread` handle swap: start on
    one thread, stop on another (the serve finalize path)."""
    from kcmc_tpu.obs.heartbeat import Heartbeat

    beats = []
    hb = Heartbeat(0.01, lambda: "tick", emit=beats.append)
    hb.start()
    time.sleep(0.05)
    stopper = threading.Thread(
        target=hb.stop, name="kcmc-test-stopper"
    )
    stopper.start()
    stopper.join(timeout=5.0)
    assert not hb.running
    assert beats  # it actually beat before the cross-thread stop
    assert san.take_violations() == []


def test_scheduler_stats_under_concurrent_load_sanitize_clean(san):
    """Regression for the off-lock `_stats`/`_window` mutations and
    the outside-the-lock `backlog()` walk in stats(): drive a real
    numpy-backend scheduler with a client thread while hammering
    stats()/snapshot() from another."""
    import numpy as np

    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.serve.scheduler import StreamScheduler

    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=4,
        max_keypoints=32, n_hypotheses=16,
    )
    rng = np.random.default_rng(0)
    frames = rng.random((12, 32, 32), np.float32)
    with StreamScheduler(mc) as sched:
        stop = threading.Event()

        def prober():
            while not stop.is_set():
                st = sched.stats()
                assert st["batch_size"] == 4
                sched.snapshot()
                time.sleep(0.001)

        pt = threading.Thread(target=prober, name="kcmc-test-prober")
        pt.start()
        sess = sched.open_session(tenant="t")
        for lo in range(0, len(frames), 3):
            sched.submit(sess.sid, frames[lo:lo + 3])
        res = sched.close_session(sess.sid, timeout=60.0)
        stop.set()
        pt.join(timeout=5.0)
        assert res.transforms is not None and len(res.transforms) == 12
    assert san.take_violations() == []
