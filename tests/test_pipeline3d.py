"""End-to-end 3D volumetric rigid registration — judged config 5."""

import numpy as np

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE3D = (24, 96, 96)


def test_rigid3d_drift_recovery():
    data = synthetic.make_drift_stack_3d(
        n_frames=4, shape=SHAPE3D, max_drift=3.0, max_angle=0.02, seed=13
    )
    mc = MotionCorrector(
        model="rigid3d",
        backend="jax",
        batch_size=2,
        max_keypoints=256,
        border=10,
        inlier_threshold=2.0,
    )
    res = mc.correct(data.stack)
    assert res.corrected.shape == data.stack.shape
    assert res.transforms.shape == (4, 4, 4)
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, SHAPE3D, n_per_axis=5)
    assert rmse < 1.0, f"3D rigid RMSE {rmse:.3f} px"
    assert (res.diagnostics["n_inliers"][1:] > 8).all()


def test_3d_detection_finds_features():
    import jax.numpy as jnp

    from kcmc_tpu.ops.detect3d import detect_keypoints_3d

    rng = np.random.default_rng(0)
    vol = synthetic.render_scene(rng, (16, 64, 64), n_blobs=60)
    kps = detect_keypoints_3d(jnp.asarray(vol), max_keypoints=64, border=6)
    n = int(np.asarray(kps.valid).sum())
    assert n > 10
    xyz = np.asarray(kps.xy)[np.asarray(kps.valid)]
    assert (xyz[:, 0] <= 63).all() and (xyz[:, 2] <= 15).all()


def test_rigid3d_shallow_anisotropic_stack():
    """Microscopy z-stacks are shallow and anisotropic (few z planes,
    many xy pixels); the full pipeline must still recover the drift.
    Also covers odd, non-multiple-of-8 depths."""
    data = synthetic.make_drift_stack_3d(
        n_frames=4, shape=(12, 128, 128), max_drift=3.0, max_angle=0.015,
        seed=29,
    )
    mc = MotionCorrector(model="rigid3d", backend="jax", batch_size=2)
    res = mc.correct(data.stack)
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, (12, 128, 128), n_per_axis=5)
    assert rmse < 1.0, f"shallow-stack RMSE {rmse:.3f} px"


def test_rigid3d_z_translation_recovery():
    """Pure z-drift (focus drift — the common microscopy failure mode)
    must be recovered to subvoxel accuracy along z specifically."""
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    scene = synthetic.render_scene(rng, (20, 96, 96), n_blobs=140)
    dz = [0.0, 1.3, -2.6]
    stack = []
    for d in dz:
        M = np.eye(4, dtype=np.float32)
        M[2, 3] = d
        zs, ys, xs = np.meshgrid(
            np.arange(20, dtype=np.float32),
            np.arange(96, dtype=np.float32),
            np.arange(96, dtype=np.float32),
            indexing="ij",
        )
        pts = np.stack([xs, ys, zs], -1).reshape(-1, 3)
        sp = pts - np.array([0, 0, d], np.float32)  # inverse of +dz
        stack.append(synthetic._trilinear(scene, sp).reshape(20, 96, 96))
    stack = np.stack(stack) + rng.normal(0, 0.01, (3, 20, 96, 96)).astype(np.float32)

    res = MotionCorrector(model="rigid3d", backend="jax", batch_size=3).correct(stack)
    got_dz = np.asarray(res.transforms)[:, 2, 3]
    # transform maps ref coords -> frame coords; frame shifted +dz means
    # sampling at z + dz
    np.testing.assert_allclose(got_dz, dz, atol=0.35)


def test_cli_rigid3d_volume_depth(tmp_path):
    """Config 5 from the CLI: pages fold into D-deep volumes."""
    import json
    import subprocess
    import sys

    from kcmc_tpu.io import read_stack
    from kcmc_tpu.io.tiff import write_stack

    data = synthetic.make_drift_stack_3d(
        n_frames=3, shape=(12, 64, 64), max_drift=2.0, seed=17
    )
    pages = np.clip(
        data.stack.reshape(3 * 12, 64, 64) * 40000, 0, 65535
    ).astype(np.uint16)
    src = tmp_path / "zstack.tif"
    write_stack(src, pages)

    script = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import kcmc_tpu.__main__ as m; import sys; sys.exit(m.main(%r))"
    )
    args = [
        "correct", str(src), "-o", str(tmp_path / "out.tif"),
        "--model", "rigid3d", "--volume-depth", "12",
        "--transforms", str(tmp_path / "t.npz"), "--batch-size", "3",
    ]
    out = subprocess.run(
        [sys.executable, "-c", script % (args,)],
        capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["n_volumes"] == 3
    assert summary["volume_shape"] == [12, 64, 64]
    t = np.load(tmp_path / "t.npz")
    assert t["transforms"].shape == (3, 4, 4)
    assert read_stack(tmp_path / "out.tif").shape == (36, 64, 64)

    # depth that doesn't divide the page count fails loudly
    bad = subprocess.run(
        [sys.executable, "-c", script % ([
            "correct", str(src), "--model", "rigid3d", "--volume-depth", "7",
        ],)],
        capture_output=True, text=True, timeout=300,
    )
    assert bad.returncode != 0
    assert "whole number" in bad.stderr
