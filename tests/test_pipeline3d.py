"""End-to-end 3D volumetric rigid registration — judged config 5."""

import numpy as np

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE3D = (24, 96, 96)


def test_rigid3d_drift_recovery():
    data = synthetic.make_drift_stack_3d(
        n_frames=4, shape=SHAPE3D, max_drift=3.0, max_angle=0.02, seed=13
    )
    mc = MotionCorrector(
        model="rigid3d",
        backend="jax",
        batch_size=2,
        max_keypoints=256,
        border=10,
        inlier_threshold=2.0,
    )
    res = mc.correct(data.stack)
    assert res.corrected.shape == data.stack.shape
    assert res.transforms.shape == (4, 4, 4)
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, SHAPE3D, n_per_axis=5)
    assert rmse < 1.0, f"3D rigid RMSE {rmse:.3f} px"
    assert (res.diagnostics["n_inliers"][1:] > 8).all()


def test_3d_detection_finds_features():
    import jax.numpy as jnp

    from kcmc_tpu.ops.detect3d import detect_keypoints_3d

    rng = np.random.default_rng(0)
    vol = synthetic.render_scene(rng, (16, 64, 64), n_blobs=60)
    kps = detect_keypoints_3d(jnp.asarray(vol), max_keypoints=64, border=6)
    n = int(np.asarray(kps.valid).sum())
    assert n > 10
    xyz = np.asarray(kps.xy)[np.asarray(kps.valid)]
    assert (xyz[:, 0] <= 63).all() and (xyz[:, 2] <= 15).all()
