"""End-to-end 3D volumetric rigid registration — judged config 5."""

import numpy as np

from kcmc_tpu import MotionCorrector
from kcmc_tpu.utils import synthetic
from kcmc_tpu.utils.metrics import relative_transforms, transform_rmse

SHAPE3D = (24, 96, 96)


def test_rigid3d_drift_recovery():
    data = synthetic.make_drift_stack_3d(
        n_frames=4, shape=SHAPE3D, max_drift=3.0, max_angle=0.02, seed=13
    )
    mc = MotionCorrector(
        model="rigid3d",
        backend="jax",
        batch_size=2,
        max_keypoints=256,
        border=10,
        inlier_threshold=2.0,
    )
    res = mc.correct(data.stack)
    assert res.corrected.shape == data.stack.shape
    assert res.transforms.shape == (4, 4, 4)
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, SHAPE3D, n_per_axis=5)
    assert rmse < 1.0, f"3D rigid RMSE {rmse:.3f} px"
    assert (res.diagnostics["n_inliers"][1:] > 8).all()


def test_3d_detection_finds_features():
    import jax.numpy as jnp

    from kcmc_tpu.ops.detect3d import detect_keypoints_3d

    rng = np.random.default_rng(0)
    vol = synthetic.render_scene(rng, (16, 64, 64), n_blobs=60)
    kps = detect_keypoints_3d(jnp.asarray(vol), max_keypoints=64, border=6)
    n = int(np.asarray(kps.valid).sum())
    assert n > 10
    xyz = np.asarray(kps.xy)[np.asarray(kps.valid)]
    assert (xyz[:, 0] <= 63).all() and (xyz[:, 2] <= 15).all()


def test_rigid3d_shallow_anisotropic_stack():
    """Microscopy z-stacks are shallow and anisotropic (few z planes,
    many xy pixels); the full pipeline must still recover the drift.
    Also covers odd, non-multiple-of-8 depths."""
    data = synthetic.make_drift_stack_3d(
        n_frames=4, shape=(12, 128, 128), max_drift=3.0, max_angle=0.015,
        seed=29,
    )
    mc = MotionCorrector(model="rigid3d", backend="jax", batch_size=2)
    res = mc.correct(data.stack)
    rel = relative_transforms(data.transforms)
    rmse = transform_rmse(res.transforms, rel, (12, 128, 128), n_per_axis=5)
    assert rmse < 1.0, f"shallow-stack RMSE {rmse:.3f} px"


def test_rigid3d_z_translation_recovery():
    """Pure z-drift (focus drift — the common microscopy failure mode)
    must be recovered to subvoxel accuracy along z specifically."""
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    scene = synthetic.render_scene(rng, (20, 96, 96), n_blobs=140)
    dz = [0.0, 1.3, -2.6]
    stack = []
    for d in dz:
        M = np.eye(4, dtype=np.float32)
        M[2, 3] = d
        zs, ys, xs = np.meshgrid(
            np.arange(20, dtype=np.float32),
            np.arange(96, dtype=np.float32),
            np.arange(96, dtype=np.float32),
            indexing="ij",
        )
        pts = np.stack([xs, ys, zs], -1).reshape(-1, 3)
        sp = pts - np.array([0, 0, d], np.float32)  # inverse of +dz
        stack.append(synthetic._trilinear(scene, sp).reshape(20, 96, 96))
    stack = np.stack(stack) + rng.normal(0, 0.01, (3, 20, 96, 96)).astype(np.float32)

    res = MotionCorrector(model="rigid3d", backend="jax", batch_size=3).correct(stack)
    got_dz = np.asarray(res.transforms)[:, 2, 3]
    # transform maps ref coords -> frame coords; frame shifted +dz means
    # sampling at z + dz
    np.testing.assert_allclose(got_dz, dz, atol=0.35)
