"""Feeder suite (io/feeder.py): decode pools, sharded ordered ingest,
fault/crash/interrupt propagation, backpressure bounds, and the
correct_file byte-identity contract across feeder paths."""

import time
import zlib

import numpy as np
import pytest

from kcmc_tpu.io import ChunkedStackLoader, feeder
from kcmc_tpu.io.feeder import DecodePool
from kcmc_tpu.io.tiff import TiffStack, _PyTiffParser, write_stack


@pytest.fixture
def py_tiff(monkeypatch):
    """Pin the pure-Python TIFF decoder — the GIL-bound regime the
    process pool exists for — regardless of the host's toolchain."""
    monkeypatch.setenv("KCMC_FORCE_PY_TIFF", "1")


@pytest.fixture
def deflate_stack(tmp_path):
    rng = np.random.default_rng(0)
    stack = (rng.random((40, 32, 48)) * 60000).astype(np.uint16)
    p = tmp_path / "s.tif"
    write_stack(p, stack, compression="deflate")
    return p, stack


# -- pure helpers -----------------------------------------------------------


def test_resolve_workers_and_derive_prefetch():
    assert feeder.resolve_workers(3) == 3
    assert feeder.resolve_workers(1) == 1
    assert feeder.resolve_workers(0) >= 1
    # auto: depth x batch frames ahead, in chunks, plus one draining
    assert feeder.derive_prefetch(0, 32, 64) == max(2, -(-3 * 32 // 64) + 1)
    assert feeder.derive_prefetch(5, 32, 64) == 5
    assert feeder.derive_prefetch(0, 64, 64, depth=1) == 2


@pytest.mark.parametrize("n,procs", [(10, 3), (7, 8), (0, 2), (100, 1), (16, 4)])
def test_host_local_range_partitions(n, procs):
    ranges = [feeder.host_local_range(n, i, procs) for i in range(procs)]
    got = []
    for lo, hi in ranges:
        assert 0 <= lo <= hi <= n
        got.extend(range(lo, hi))
    assert got == list(range(n))  # disjoint, ordered, complete
    # ceil partition: every non-tail host carries the same load
    sizes = [hi - lo for lo, hi in ranges if hi > lo]
    assert all(s == sizes[0] for s in sizes[:-1])


def test_host_local_range_validates():
    with pytest.raises(ValueError):
        feeder.host_local_range(10, 3, 3)


# -- classification + spec --------------------------------------------------


def test_classify_and_spec(py_tiff, tmp_path, deflate_stack):
    p, _ = deflate_stack
    with TiffStack(p) as ts:
        assert ts.backend == "python"
        assert feeder.classify_source(ts) == "process"
        spec = feeder.source_spec(ts, p, None)
        # workers must never race to build/switch to the native decoder
        assert ("force_python", True) in spec[2]
    raw = tmp_path / "raw.tif"
    write_stack(raw, np.zeros((3, 8, 8), np.uint16))
    with TiffStack(raw) as ts:
        assert feeder.classify_source(ts) == "thread"
    with TiffStack(raw) as ts:
        assert feeder.source_spec(ts, None, None) is None


def test_force_py_env_zero_means_off(monkeypatch, deflate_stack):
    """KCMC_FORCE_PY_TIFF=0/false must NOT pin the pure-Python decoder
    (an explicit disable in a CI matrix or shell must win)."""
    from kcmc_tpu.io.tiff import _get_native

    if _get_native() is None:
        pytest.skip("no native toolchain")
    p, _ = deflate_stack
    monkeypatch.setenv("KCMC_FORCE_PY_TIFF", "0")
    with TiffStack(p) as ts:
        assert ts.backend == "native"
    monkeypatch.setenv("KCMC_FORCE_PY_TIFF", "false")
    with TiffStack(p) as ts:
        assert ts.backend == "native"


def test_classify_native_stays_legacy(deflate_stack):
    from kcmc_tpu.io.tiff import _get_native

    if _get_native() is None:
        pytest.skip("no native toolchain")
    p, _ = deflate_stack
    with TiffStack(p) as ts:
        assert ts.backend == "native"
        assert feeder.classify_source(ts) is None


# -- pooled ingest: content, ordering, bounds -------------------------------


def test_pooled_matches_legacy(py_tiff, deflate_stack):
    p, stack = deflate_stack
    stats = {}
    with ChunkedStackLoader(
        p, chunk_size=7, io_workers=2, prefetch=2, stats=stats
    ) as loader:
        got = list(loader)
    assert [(lo, hi) for lo, hi, _ in got] == [
        (i, min(i + 7, 40)) for i in range(0, 40, 7)
    ]
    np.testing.assert_array_equal(
        np.concatenate([f for _, _, f in got]), stack
    )
    assert stats["mode"] == "process" and stats["workers"] == 2
    assert stats["frames"] == 40 and stats["chunks"] == 6
    assert stats["max_inflight_chunks"] <= 2  # backpressure bound


def test_pooled_start_stop_window(py_tiff, deflate_stack):
    p, stack = deflate_stack
    with ChunkedStackLoader(
        p, chunk_size=4, start=5, stop=17, io_workers=2
    ) as loader:
        got = list(loader)
    assert [(lo, hi) for lo, hi, _ in got] == [(5, 9), (9, 13), (13, 17)]
    np.testing.assert_array_equal(
        np.concatenate([f for _, _, f in got]), stack[5:17]
    )


def test_out_of_order_completion_reassembles(py_tiff, tmp_path, monkeypatch):
    """Spans finishing in scrambled order must still yield chunks in
    order — exercised deterministically on the thread flavor (same
    process, so the decode fn can be patched with inverse delays)."""
    rng = np.random.default_rng(1)
    stack = (rng.random((24, 16, 16)) * 60000).astype(np.uint16)
    p = tmp_path / "u.tif"
    write_stack(p, stack)  # uncompressed python path -> "thread" kind

    real = feeder._decode_span

    def slow_head(spec, lo, hi):
        time.sleep(0.15 if lo < 8 else 0.0)  # head chunks finish LAST
        return real(spec, lo, hi)

    monkeypatch.setattr(feeder, "_decode_span", slow_head)
    pool = DecodePool(3, kind="thread")
    try:
        with ChunkedStackLoader(
            p, chunk_size=4, io_workers=3, pool=pool, prefetch=6
        ) as loader:
            got = list(loader)
    finally:
        pool.shutdown()
    assert [lo for lo, _, _ in got] == [0, 4, 8, 12, 16, 20]
    np.testing.assert_array_equal(
        np.concatenate([f for _, _, f in got]), stack
    )


# -- fault paths ------------------------------------------------------------


def test_worker_exception_carries_original_traceback(
    py_tiff, tmp_path, deflate_stack
):
    """A decode error inside a pool WORKER surfaces on the consumer as
    the original exception type with the worker-side traceback chained
    — not a hang, not a truncated-but-clean end of stream."""
    p, stack = deflate_stack
    # corrupt one mid-stack page's compressed strip in place (same
    # length, garbage bytes) so only the worker-side decode fails
    parser = _PyTiffParser(str(p))
    off, cnt, _rows = parser.pages[20][0]
    parser.close()
    with open(p, "r+b") as f:
        f.seek(off)
        f.write(b"\xde\xad" * (cnt // 2 + 1))
    with ChunkedStackLoader(p, chunk_size=8, io_workers=2) as loader:
        with pytest.raises(zlib.error) as ei:
            for lo, hi, frames in loader:
                np.testing.assert_array_equal(frames, stack[lo:hi])
    assert lo == 8  # pages before the corrupt chunk decoded fine
    # the worker traceback rides along (concurrent.futures chains it)
    assert "_decode_span" in "".join(str(c) for c in (ei.value.__cause__,))


def test_worker_crash_surfaces_not_hangs(py_tiff, deflate_stack):
    p, stack = deflate_stack
    pool = DecodePool(2, kind="process")
    try:
        with ChunkedStackLoader(
            p, chunk_size=8, io_workers=2, pool=pool, prefetch=1
        ) as loader:
            it = iter(loader)
            lo, hi, frames = next(it)  # workers are live now
            np.testing.assert_array_equal(frames, stack[lo:hi])
            for proc in list(pool._ex._processes.values()):
                proc.kill()
            with pytest.raises(RuntimeError, match="worker died"):
                for _ in it:
                    pass
        assert pool.broken
    finally:
        pool.shutdown(wait=False)


def test_broken_shared_pool_is_replaced(py_tiff, deflate_stack):
    p, stack = deflate_stack
    pool = feeder.shared_pool("process", 2)
    pool.broken = True  # as flagged after a crash
    fresh = feeder.shared_pool("process", 2)
    assert fresh is not pool and not fresh.broken
    with ChunkedStackLoader(p, chunk_size=16, io_workers=2) as loader:
        got = np.concatenate([f for _, _, f in loader])
    np.testing.assert_array_equal(got, stack)


def test_keyboard_interrupt_propagates(py_tiff, tmp_path, monkeypatch):
    """The PR-2 contract: an interrupt must never be swallowed into a
    clean-looking end of stream or a misattributed decode error."""
    stack = np.zeros((12, 8, 8), np.uint16)
    p = tmp_path / "k.tif"
    write_stack(p, stack)

    real = feeder._decode_span

    def interrupt_late(spec, lo, hi):
        if lo >= 8:
            raise KeyboardInterrupt
        return real(spec, lo, hi)

    monkeypatch.setattr(feeder, "_decode_span", interrupt_late)
    pool = DecodePool(2, kind="thread")
    try:
        with ChunkedStackLoader(
            p, chunk_size=4, io_workers=2, pool=pool
        ) as loader:
            with pytest.raises(KeyboardInterrupt):
                list(loader)
    finally:
        pool.shutdown(wait=False)


def test_injected_transient_fault_retries(py_tiff, deflate_stack):
    from kcmc_tpu.utils.faults import FaultPlan, RetryPolicy
    from kcmc_tpu.utils.metrics import RobustnessReport

    p, stack = deflate_stack
    plan = FaultPlan.from_spec("io_read:step=2:transient", seed=0)
    report = RobustnessReport()
    with ChunkedStackLoader(
        p,
        chunk_size=8,
        io_workers=2,
        fault_plan=plan,
        retry=RetryPolicy(
            attempts=3, backoff_s=0.01, backoff_max_s=0.02, jitter=0.0,
            seed=0,
        ),
        report=report,
    ) as loader:
        got = np.concatenate([f for _, _, f in loader])
    np.testing.assert_array_equal(got, stack)
    assert report.io_retries >= 1


def test_injected_fatal_fault_raises(py_tiff, deflate_stack):
    from kcmc_tpu.utils.faults import FatalFaultError, FaultPlan, RetryPolicy

    p, _ = deflate_stack
    plan = FaultPlan.from_spec("io_read:step=1:fatal", seed=0)
    with ChunkedStackLoader(
        p,
        chunk_size=8,
        io_workers=2,
        fault_plan=plan,
        retry=RetryPolicy(
            attempts=3, backoff_s=0.01, backoff_max_s=0.02, jitter=0.0,
            seed=0,
        ),
    ) as loader:
        with pytest.raises(FatalFaultError):
            list(loader)


# -- advisory ---------------------------------------------------------------


def test_single_core_advisory(py_tiff, deflate_stack):
    p, _ = deflate_stack
    with pytest.warns(RuntimeWarning, match="single core"):
        with ChunkedStackLoader(p, chunk_size=8, io_workers=1) as loader:
            list(loader)


def test_no_advisory_when_pool_engaged(py_tiff, deflate_stack, recwarn):
    p, _ = deflate_stack
    with ChunkedStackLoader(p, chunk_size=8, io_workers=2) as loader:
        list(loader)
    assert not [
        w for w in recwarn.list if "single core" in str(w.message)
    ]


# -- end-to-end byte identity -----------------------------------------------


def test_correct_file_pooled_byte_identical(py_tiff, tmp_path):
    """The acceptance contract: the pooled feeder changes WHEN pages
    decode, never what a run computes — corrected output files are
    byte-identical across feeder paths."""
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    d = make_drift_stack(
        n_frames=20, shape=(40, 40), model="translation", max_drift=3.0,
        seed=0,
    )
    stack = np.clip(d.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, stack, compression="deflate")
    mc = MotionCorrector(model="translation", backend="numpy", batch_size=8)
    r1 = mc.correct_file(
        src, output=str(tmp_path / "o1.tif"), n_threads=1,
        output_dtype="input",
    )
    r2 = mc.correct_file(
        src, output=str(tmp_path / "o2.tif"), n_threads=3,
        output_dtype="input",
    )
    assert (tmp_path / "o1.tif").read_bytes() == (
        tmp_path / "o2.tif"
    ).read_bytes()
    np.testing.assert_array_equal(r1.transforms, r2.transforms)
    assert r1.timing.get("feeder") is None  # legacy single-producer
    feed = r2.timing["feeder"]
    assert feed["mode"] == "process" and feed["workers"] == 3
    assert feed["frames"] == 20


def test_config_io_workers_drives_the_pool(py_tiff, tmp_path):
    from kcmc_tpu import MotionCorrector
    from kcmc_tpu.utils.synthetic import make_drift_stack

    d = make_drift_stack(
        n_frames=12, shape=(32, 32), model="translation", max_drift=2.0,
        seed=1,
    )
    stack = np.clip(d.stack * 40000, 0, 65535).astype(np.uint16)
    src = tmp_path / "in.tif"
    write_stack(src, stack, compression="deflate")
    mc = MotionCorrector(
        model="translation", backend="numpy", batch_size=4, io_workers=2,
        io_prefetch=2,
    )
    res = mc.correct_file(src, emit_frames=False)
    feed = res.timing["feeder"]
    assert feed["workers"] == 2 and feed["prefetch_chunks"] == 2


# -- config validation ------------------------------------------------------


def test_config_fields_validated_and_neutral():
    from kcmc_tpu import config as cfg_mod
    from kcmc_tpu.config import CorrectorConfig

    with pytest.raises(ValueError, match="io_workers"):
        CorrectorConfig(io_workers=-1)
    with pytest.raises(ValueError, match="io_prefetch"):
        CorrectorConfig(io_prefetch=-2)
    assert "io_workers" in cfg_mod.SIG_NEUTRAL_FIELDS
    assert "io_prefetch" in cfg_mod.SIG_NEUTRAL_FIELDS


# -- shared pool registry ---------------------------------------------------


def test_shared_pool_reuse_and_shutdown():
    a = feeder.shared_pool("thread", 2)
    assert feeder.shared_pool("thread", 2) is a
    assert feeder.shared_pool("thread", 3) is not a
    feeder.shutdown_shared_pools()
    assert feeder.shared_pool("thread", 2) is not a
    feeder.shutdown_shared_pools()


def test_minizarr_zlib_classifies_process(tmp_path):
    try:
        import zarr  # noqa: F401

        pytest.skip("zarr package present: ZarrStack bypasses _MiniZarr")
    except ImportError:
        pass
    from kcmc_tpu.io.formats import ZarrStack, ZarrWriter

    rng = np.random.default_rng(2)
    stack = (rng.random((6, 16, 16)) * 60000).astype(np.uint16)
    store = tmp_path / "s.zarr"
    w = ZarrWriter(store, 6, (16, 16), np.uint16, compression="deflate")
    w.append_batch(stack)
    w.close()
    zs = ZarrStack(store)
    assert feeder.classify_source(zs) == "process"
    with ChunkedStackLoader(
        zs, chunk_size=2, io_workers=2, source_path=store
    ) as loader:
        got = np.concatenate([f for _, _, f in loader])
    np.testing.assert_array_equal(got, stack)
