"""Incremental result cache for `kcmc check` (`.kcmc_check_cache/`).

The pass suite grew to nine passes, several of which build whole-
program graphs — seconds per run, paid on every local pre-commit check
and every CI invocation even when nothing changed. This cache keys
analysis results by CONTENT HASH so a repeat run replays findings
instead of re-deriving them:

* **module-scoped passes** (`cache_scope = "module"`: jit-purity,
  lock-discipline — each module's findings depend only on that
  module's source) cache per module: an edit re-analyzes the edited
  files only, everything else replays.
* **program-scoped passes** (the default: the ProgramGraph passes,
  traceflow, donation, config/span registries) cache against a
  fingerprint over EVERY module + doc hash — whole-program analysis
  has whole-program inputs, so the honest unit of reuse is
  all-or-nothing. The common cases (CI re-runs, repeated local checks,
  doc-only edits) hit.

The cache stores raw pass findings, NEVER gate decisions: baseline
splitting happens fresh on every run, so editing `baseline.json` needs
no invalidation. A schema bump (or any change to the pass list)
invalidates everything. `kcmc check --no-cache` bypasses; corrupt or
foreign cache files are ignored, never trusted.
"""

from __future__ import annotations

import hashlib
import json
import os

from kcmc_tpu.analysis.core import Finding, ModuleIndex

SCHEMA = 1
CACHE_DIRNAME = ".kcmc_check_cache"


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


def _index_hashes(index: ModuleIndex) -> dict[str, str]:
    out = {m.path: _sha(m.source) for m in index}
    for name, text in sorted(index.docs.items()):
        out[f"doc:{name}"] = _sha(text)
    return out


_ANALYSIS_SRC_SHA: str | None = None


def _analysis_package_sha() -> str:
    """Hash over EVERY source file of the analysis package. Passes
    share infrastructure (core.py's AST helpers, callgraph.py's
    ProgramGraph), so a module-scoped pass's cached findings must go
    stale when any of it changes — hashing only the pass's own module
    would replay results computed with old shared behavior."""
    global _ANALYSIS_SRC_SHA
    if _ANALYSIS_SRC_SHA is None:
        here = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for fn in sorted(os.listdir(here)):
            if not fn.endswith(".py"):
                continue
            try:
                with open(os.path.join(here, fn), "rb") as f:
                    h.update(fn.encode())
                    h.update(f.read())
            except OSError:
                continue
        _ANALYSIS_SRC_SHA = h.hexdigest()[:16]
    return _ANALYSIS_SRC_SHA


def _pass_version(p) -> str:
    """Version key for a pass's cached results: the analysis package's
    source hash, the pass's class name, and its declared configuration
    (`module_prefixes` — the one constructor knob the scoped passes
    take), so a narrowed instance never replays a default-scope
    instance's findings."""
    config = repr(getattr(p, "module_prefixes", None))
    return _sha(
        _analysis_package_sha() + type(p).__qualname__ + config
    )


def _sub_index(index: ModuleIndex, paths: list[str]) -> ModuleIndex:
    sub = ModuleIndex()
    for path in paths:
        mod = index.get(path)
        if mod is not None:
            sub.modules[path] = mod
    sub.docs = index.docs
    return sub


class CheckCache:
    """Per-repo findings cache under `<root>/.kcmc_check_cache/`."""

    def __init__(self, root: str):
        self.dir = os.path.join(root, CACHE_DIRNAME)
        self.path = os.path.join(self.dir, "results.json")
        self._data: dict | None = None
        self.hits = 0  # module or whole-pass replays this run
        self.misses = 0

    # -- storage ------------------------------------------------------

    def _load_file(self) -> dict:
        if self._data is None:
            try:
                with open(self.path, encoding="utf-8") as f:
                    data = json.load(f)
                if (
                    data.get("kind") == "kcmc_check_cache"
                    and data.get("schema") == SCHEMA
                ):
                    self._data = data
                else:
                    self._data = {}
            except (OSError, ValueError):
                self._data = {}
        return self._data

    def _save_file(self, data: dict) -> None:
        data["kind"] = "kcmc_check_cache"
        data["schema"] = SCHEMA
        os.makedirs(self.dir, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, self.path)
        self._data = data

    # -- the per-pass seam (called by core.run_passes) ----------------

    def findings_for(self, p, index: ModuleIndex) -> list[Finding]:
        hashes = _index_hashes(index)
        version = _pass_version(p)
        if getattr(p, "cache_scope", "program") == "module":
            return self._module_scoped(p, index, hashes, version)
        return self._program_scoped(p, index, hashes, version)

    def _program_scoped(self, p, index, hashes, version) -> list[Finding]:
        fp = _sha(
            json.dumps([version, sorted(hashes.items())], sort_keys=True)
        )
        data = self._load_file()
        entry = data.get("program", {}).get(p.name)
        if entry and entry.get("fingerprint") == fp:
            self.hits += 1
            return [Finding(**f) for f in entry["findings"]]
        self.misses += 1
        findings = p.run(index)
        data.setdefault("program", {})[p.name] = {
            "fingerprint": fp,
            "findings": [f.as_dict() for f in findings],
        }
        self._save_file(data)
        return findings

    def _module_scoped(self, p, index, hashes, version) -> list[Finding]:
        data = self._load_file()
        stored = data.get("module", {}).get(p.name, {})
        if stored.get("version") != version:
            stored = {"version": version, "modules": {}}
        mod_entries = stored.get("modules", {})
        out: list[Finding] = []
        stale: list[str] = []
        for mod in index:
            entry = mod_entries.get(mod.path)
            if entry is not None and entry.get("sha") == hashes[mod.path]:
                self.hits += 1
                out.extend(Finding(**f) for f in entry["findings"])
            else:
                stale.append(mod.path)
        if stale:
            self.misses += len(stale)
            fresh = p.run(_sub_index(index, stale))
            by_path: dict[str, list] = {path: [] for path in stale}
            for f in fresh:
                by_path.setdefault(f.path, []).append(f.as_dict())
            for path in stale:
                mod_entries[path] = {
                    "sha": hashes[path],
                    "findings": by_path.get(path, []),
                }
            out.extend(fresh)
            # drop entries for deleted modules
            live = {m.path for m in index}
            stored["modules"] = {
                k: v for k, v in mod_entries.items() if k in live
            }
            data.setdefault("module", {})[p.name] = stored
            self._save_file(data)
        return out
