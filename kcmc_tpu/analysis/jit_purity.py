"""Pass 2 — jit-boundary purity (`jit-purity`).

The zero-stall pipeline only works because every jitted program is pure
device compute: one hidden `np.asarray`/`.block_until_ready()` inside a
traced function serializes the dispatch window back to synchronous
round trips (or worse, traces a host value into the compiled program
as a constant), and unseeded host randomness or wall-clock reads make
retraces non-reproducible. Those hazards are invisible in review once
they hide two calls deep.

From every `jax.jit` / `pjit` / `shard_map` site in the configured
modules this pass walks the *locally reachable* call graph — callees
defined in the same module, resolved by bare name, plus `self.`
methods — and flags, inside traced code:

* host materialization: `np.asarray` / `np.array` / `.item()` /
  `.tolist()` / `.block_until_ready()` / `jax.device_get`;
* host scalarization: `float()` / `int()` / `bool()` on a non-constant
  argument (forces a device sync when the value is traced);
* side effects: `print`, `open`;
* nondeterminism: `time.*`, `datetime.*` ("Date"-like reads),
  `random.*` / `np.random.*` (unseeded host randomness — `jax.random`
  with an explicit key threads through the trace and is fine).

Cross-module callees are deliberately out of scope: the pass enforces
what a reader of the jitted file can verify locally; ops-module purity
is the parity suite's job.
"""

from __future__ import annotations

import ast

from kcmc_tpu.analysis.core import (
    Finding,
    FunctionTable,
    Module,
    ModuleIndex,
    attr_chain,
    enclosing_class,
    reachable_functions,
)

# Entry points that begin a traced region. Matched against the LAST
# dotted component of the call, so `jax.jit`, `functools.partial(
# jax.jit, ...)`, bare `jit` (`from jax import jit`), `pjit`, and the
# sharded.py `shard_map` shim all resolve. A non-jax `.jit` matching
# too is the right failure mode: a visible (baselineable) finding
# beats a silent false negative.
JIT_ENTRY_NAMES = frozenset({"jit", "pjit", "shard_map"})


def _is_jit_entry(chain: str) -> bool:
    return chain.rsplit(".", 1)[-1] in JIT_ENTRY_NAMES

# (dotted-suffix, severity, why) — matched against call names inside
# traced code.
HAZARD_CALLS = (
    ("np.asarray", "error", "host materialization of a traced value"),
    ("np.array", "error", "host materialization of a traced value"),
    ("numpy.asarray", "error", "host materialization of a traced value"),
    ("numpy.array", "error", "host materialization of a traced value"),
    ("jax.device_get", "error", "host transfer inside traced code"),
    ("print", "warning", "side effect inside traced code"),
    ("open", "error", "file IO inside traced code"),
)
HAZARD_METHOD_CALLS = (
    (".block_until_ready", "error", "device sync inside traced code"),
    (".item", "error", "host scalarization of a traced value"),
    (".tolist", "error", "host materialization of a traced value"),
)
HAZARD_PREFIXES = (
    ("time.", "error", "wall-clock nondeterminism inside traced code"),
    ("datetime.", "error", "Date-like nondeterminism inside traced code"),
    ("random.", "error", "unseeded host randomness inside traced code"),
    ("np.random.", "error", "unseeded host randomness inside traced code"),
    (
        "numpy.random.",
        "error",
        "unseeded host randomness inside traced code",
    ),
)
SCALARIZERS = ("float", "int", "bool")


def _jit_roots(
    mod: Module, table: FunctionTable
) -> list[tuple[ast.FunctionDef, str, int]]:
    """(traced function, how it was entered, jit-site line)."""
    roots: list[tuple[ast.FunctionDef, str, int]] = []
    seen: set[int] = set()

    def add(fn: ast.FunctionDef | None, how: str, line: int) -> None:
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            roots.append((fn, how, line))

    # Decorated defs: @jax.jit / @functools.partial(jax.jit, ...).
    for fns in table.functions.values():
        for fn in fns:
            for dec in fn.decorator_list:
                chain = attr_chain(
                    dec.func if isinstance(dec, ast.Call) else dec
                )
                inner = ""
                if (
                    isinstance(dec, ast.Call)
                    and chain.endswith("partial")
                    and dec.args
                ):
                    inner = attr_chain(dec.args[0])
                if _is_jit_entry(chain) or (
                    inner and _is_jit_entry(inner)
                ):
                    add(fn, f"@{chain}", dec.lineno)

    # Call sites: jax.jit(fn) / shard_map(fn, ...) with a locally
    # resolvable function argument (Name, or lambda traced inline).
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not _is_jit_entry(chain):
            continue
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Name):
            cands = table.functions.get(arg.id)
            add(cands[0] if cands else None, chain, node.lineno)
        elif isinstance(arg, ast.Lambda):
            # wrap the lambda body so the walker has a FunctionDef-like
            # node; ast.Lambda shares .body traversal via ast.walk
            fn = ast.FunctionDef(
                name="<lambda>",
                args=arg.args,
                body=[ast.Expr(value=arg.body)],
                decorator_list=[],
                lineno=arg.lineno,
                col_offset=arg.col_offset,
            )
            add(fn, chain, node.lineno)
    return roots


def _is_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.operand, ast.Constant)
    )


class JitPurityPass:
    name = "jit-purity"
    # Each module's findings depend only on that module's source (the
    # call graph is deliberately local), so the check cache can replay
    # unchanged modules (analysis/cache.py).
    cache_scope = "module"

    def __init__(
        self,
        module_prefixes: tuple[str, ...] = (
            "kcmc_tpu/backends/jax_backend.py",
            "kcmc_tpu/plans/",
            "kcmc_tpu/parallel/",
        ),
    ):
        self.module_prefixes = module_prefixes

    def _modules(self, index: ModuleIndex) -> list[Module]:
        out = []
        for mod in index:
            if any(mod.path.startswith(p) for p in self.module_prefixes):
                out.append(mod)
        return out

    def run(self, index: ModuleIndex) -> list[Finding]:
        out: list[Finding] = []
        for mod in self._modules(index):
            table = FunctionTable(mod.tree)
            for root, how, _site_line in _jit_roots(mod, table):
                cls = enclosing_class(mod.tree, root)
                for fn in reachable_functions(table, root, cls):
                    out.extend(
                        self._scan_traced(mod, fn, root.name, how)
                    )
        # de-dup: one finding per (message, line) — overlapping call
        # graphs from several jit roots reach the same helper
        uniq: dict[tuple, Finding] = {}
        for f in out:
            uniq.setdefault((f.path, f.line, f.message), f)
        return list(uniq.values())

    def _scan_traced(
        self, mod: Module, fn: ast.FunctionDef, root_name: str, how: str
    ) -> list[Finding]:
        out = []

        def emit(line, sev, what, why):
            out.append(
                Finding(
                    rule=self.name,
                    path=mod.path,
                    line=line,
                    severity=sev,
                    message=(
                        f"{what} inside jit-traced '{root_name}' "
                        f"(via {fn.name})"
                    ),
                    detail=f"{why}; traced through {how}",
                )
            )

        # Don't descend into nested defs here — they are separate
        # entries of the reachable set only if actually CALLED.
        nested: set[int] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            ):
                nested.update(id(sub) for sub in ast.walk(n))

        for node in ast.walk(fn):
            if id(node) in nested or not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            for suf, sev, why in HAZARD_CALLS:
                if chain == suf or chain.endswith("." + suf):
                    emit(node.lineno, sev, f"call to {suf}", why)
            for suf, sev, why in HAZARD_METHOD_CALLS:
                if chain.endswith(suf):
                    emit(node.lineno, sev, f"call to *{suf}()", why)
            for pref, sev, why in HAZARD_PREFIXES:
                if chain.startswith(pref):
                    emit(node.lineno, sev, f"call to {chain}", why)
            if (
                chain in SCALARIZERS
                and node.args
                and not _is_const(node.args[0])
            ):
                emit(
                    node.lineno,
                    "warning",
                    f"{chain}() on a non-constant expression",
                    "host scalarization syncs if the value is traced",
                )
        return out
