"""Pass 7 — resource lifecycle (`lifecycle`).

Every acquired resource must reach its release on every path. The
leak classes that matter here are the ones PRs 3–9 created: a started
`Thread` never joined, a `DecodePool`/executor never shut down, a
socket or file opened outside a `with`, a `RunTelemetry` whose
artifact-path claims outlive a failed construction. The pass knows
three ownership shapes:

* **scoped** (a local variable): the acquisition must be a context
  manager (`with …`), or the variable must be released in a `finally`
  (releases only on the straight-line path are a warning — the
  exception path leaks), or the value must ESCAPE (returned, stored on
  `self`/a global/container, passed onward) — escaped ownership is the
  receiver's problem;
* **object-held** (`self._x = …`): some method of the class must
  release `self._x` (`join`/`shutdown`/`close`/`stop`/`finish`); a
  class that acquires but can never release is an error;
* **process-lifetime** (stored in a module global): the module must
  register an `atexit` hook — the feeder's shared-pool registry is the
  canonical shape.

Threads: `daemon=True` threads are exempt from the join requirement
(they are backstops by contract — the daemon-xla pass bounds what they
may touch); a non-daemon thread that is started and neither stored nor
joined relies on the interpreter's exit join and gets a warning, not
an error (the plan-export threads use exactly that contract,
deliberately).

The runtime half of this contract is `kcmc_tpu/analysis/sanitize.py`'s
per-test leak checker — static for the shapes the AST can see, runtime
for the rest.
"""

from __future__ import annotations

import ast

from kcmc_tpu.analysis.callgraph import ProgramGraph
from kcmc_tpu.analysis.core import Finding, ModuleIndex
from kcmc_tpu.analysis.lock_discipline import _self_attr, attr_chain

# Ctor chain (exact or trailing name) -> (resource kind, release names)
STDLIB_RESOURCES = {
    "threading.Thread": ("thread", ("join",)),
    "ThreadPoolExecutor": ("executor", ("shutdown",)),
    "ProcessPoolExecutor": ("executor", ("shutdown",)),
    "socket.socket": ("socket", ("close", "detach")),
    "socket.create_connection": ("socket", ("close", "detach")),
    "open": ("file", ("close",)),
}

# Program-defined resource classes (constructed OR factory-built) and
# their release methods. Kept explicit: a class with a `close()` is not
# automatically a tracked resource — these are the ones whose leak
# takes worker threads, sockets, or artifact-path claims with it.
PROGRAM_RESOURCES = {
    "DecodePool": ("decode pool", ("shutdown",)),
    "AsyncBatchWriter": ("async writer", ("close",)),
    "Heartbeat": ("heartbeat", ("stop",)),
    "RunTelemetry": ("telemetry", ("finish", "close")),
    "FrameRecordStream": ("record stream", ("close",)),
    "StreamScheduler": ("scheduler", ("stop",)),
    "ServeServer": ("server", ("stop",)),
}

# Factory classmethods that acquire (RunTelemetry.begin returns a live
# claim-holding telemetry or None).
FACTORY_METHODS = {("RunTelemetry", "begin")}

RELEASE_NAMES = frozenset(
    n
    for _k, names in list(STDLIB_RESOURCES.values())
    + list(PROGRAM_RESOURCES.values())
    for n in names
)


def _classify_ctor(graph: ProgramGraph, path: str, cls, call: ast.Call):
    """(kind, releases, label) when `call` acquires a resource."""
    chain = attr_chain(call.func)
    last = chain.rsplit(".", 1)[-1]
    if chain in STDLIB_RESOURCES:
        kind, rel = STDLIB_RESOURCES[chain]
        return kind, rel, chain
    if last in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
        kind, rel = STDLIB_RESOURCES[last]
        return kind, rel, last
    ref = graph.resolve_in_module(path, chain, cls=cls)
    if ref is not None and ref.cls is not None:
        if ref.name == "__init__" and ref.cls in PROGRAM_RESOURCES:
            kind, rel = PROGRAM_RESOURCES[ref.cls]
            return kind, rel, ref.cls
        if (ref.cls, ref.name) in FACTORY_METHODS:
            kind, rel = PROGRAM_RESOURCES[ref.cls]
            return kind, rel, f"{ref.cls}.{ref.name}"
    return None


def _is_daemon_thread(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


class _Scope:
    """Release/escape evidence inside one function."""

    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        # var -> set of method names called on it, var -> in-finally?
        self.calls: dict[str, set[str]] = {}
        self.finally_calls: dict[str, set[str]] = {}
        self.escapes: set[str] = set()
        self.with_items: set[int] = set()  # id() of ctx exprs
        finally_nodes: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Try):
                for s in node.finalbody:
                    finally_nodes.update(id(x) for x in ast.walk(s))
            elif isinstance(node, ast.With):
                for item in node.items:
                    self.with_items.add(id(item.context_expr))
                    # `with closing(v)`-style wrappers count as release
                    if isinstance(item.context_expr, ast.Call):
                        for a in item.context_expr.args:
                            if isinstance(a, ast.Name):
                                self.escapes.add(a.id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                base = node.func.value
                if isinstance(base, ast.Name):
                    self.calls.setdefault(base.id, set()).add(node.func.attr)
                    if id(node) in finally_nodes:
                        self.finally_calls.setdefault(base.id, set()).add(
                            node.func.attr
                        )
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                self.escapes.add(node.value.id)
            if isinstance(node, ast.Yield) and isinstance(
                node.value, ast.Name
            ):
                self.escapes.add(node.value.id)
        # escapes: stored on self/global/subscript, passed to a call,
        # appended into a container
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Name
            ):
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        self.escapes.add(node.value.id)
            if isinstance(node, ast.Call):
                for a in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    if isinstance(a, ast.Name):
                        fname = attr_chain(node.func).rsplit(".", 1)[-1]
                        if fname not in RELEASE_NAMES:
                            self.escapes.add(a.id)


class ResourceLifecyclePass:
    name = "resource-lifecycle"

    def run(self, index: ModuleIndex) -> list[Finding]:
        graph = ProgramGraph.for_index(index)
        out: list[Finding] = []
        for mod in graph.index:
            table = graph.tables[mod.path]
            has_atexit = "atexit.register" in mod.source
            mod_releases = self._getattr_releases(mod.tree)
            for cname in table.classes:
                info = graph.class_info(cname, mod.path)
                if info is None:
                    continue
                releases = self._class_release_calls(info)
                for attr, names in mod_releases.items():
                    releases.setdefault(attr, set()).update(names)
                for mname, fn in info.methods.items():
                    out.extend(
                        self._check_fn(
                            graph, mod.path, cname, mname, fn,
                            releases, has_atexit,
                        )
                    )
            for (path, fname), fn in graph.module_funcs.items():
                if path == mod.path:
                    out.extend(
                        self._check_fn(
                            graph, mod.path, None, fname, fn,
                            None, has_atexit,
                        )
                    )
        # nested defs are walked from both their own scope and their
        # enclosing function — dedup identical findings
        uniq, seen = [], set()
        for f in out:
            k = (f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                uniq.append(f)
        return uniq

    @staticmethod
    def _getattr_releases(tree: ast.Module) -> dict[str, set[str]]:
        """Module-wide release evidence through `getattr` aliasing —
        the `_telemetry_scope` decorator shape: `t = getattr(self,
        "_telemetry", None)` followed by `t.close(...)` releases the
        attribute from OUTSIDE the class body."""
        out: dict[str, set[str]] = {}
        for fn in [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            aliases: dict[str, str] = {}
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and attr_chain(node.value.func) == "getattr"
                    and len(node.value.args) >= 2
                    and isinstance(node.value.args[1], ast.Constant)
                    and isinstance(node.value.args[1].value, str)
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases[t.id] = node.value.args[1].value
            if not aliases:
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in aliases
                ):
                    out.setdefault(
                        aliases[node.func.value.id], set()
                    ).add(node.func.attr)
        return out

    @staticmethod
    def _class_release_calls(info) -> dict[str, set[str]]:
        """attr -> method names called on `self.<attr>` anywhere in the
        class (including calls on items iterated OUT of the attr — the
        tracked-thread-list join pattern)."""
        rel: dict[str, set[str]] = {}
        iter_vars: dict[str, str] = {}  # loop var -> source attr
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.For) and isinstance(
                    node.target, ast.Name
                ):
                    src = node.iter
                    if isinstance(src, ast.Call):
                        src = (
                            src.func.value
                            if isinstance(src.func, ast.Attribute)
                            else src
                        )
                    attr = _self_attr(src)
                    if attr is not None:
                        iter_vars[node.target.id] = attr
                # tuple-unpack swap: `warm, self._x = self._x, []`
                if isinstance(node, ast.Assign) and isinstance(
                    node.targets[0], ast.Tuple
                ) and isinstance(node.value, ast.Tuple):
                    for t, v in zip(
                        node.targets[0].elts, node.value.elts
                    ):
                        attr = _self_attr(v)
                        if isinstance(t, ast.Name) and attr is not None:
                            iter_vars.setdefault(t.id, attr)
                if isinstance(node, ast.Assign) and isinstance(
                    node.targets[0], ast.Name
                ):
                    attr = _self_attr(node.value)
                    if attr is not None:
                        iter_vars.setdefault(node.targets[0].id, attr)
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                base = node.func.value
                attr = _self_attr(base)
                if attr is None and isinstance(base, ast.Name):
                    attr = iter_vars.get(base.id)
                if attr is not None:
                    rel.setdefault(attr, set()).add(node.func.attr)
        return rel

    def _check_fn(
        self, graph, path, cls, fname, fn, class_releases, has_atexit
    ) -> list[Finding]:
        out: list[Finding] = []
        scope = None  # built lazily — most functions acquire nothing
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            got = _classify_ctor(graph, path, cls, node)
            if got is None:
                continue
            kind, rel_names, label = got
            if kind == "thread" and _is_daemon_thread(node):
                continue  # daemon threads are backstops by contract
            if scope is None:
                scope = _Scope(fn)
            if id(node) in scope.with_items:
                continue  # context-managed: release is structural
            owner = self._owner_of(
                fn, node, graph.module_mutables.get(path, set())
            )
            if owner is None:
                # unassigned: Thread(...).start() fire-and-forget
                if kind == "thread":
                    out.append(
                        Finding(
                            rule="resource-lifecycle",
                            path=path, line=node.lineno,
                            severity="warning",
                            message=(
                                "non-daemon thread started without a "
                                "handle relies on interpreter-exit join"
                            ),
                            detail=(
                                "store and join it on the owner's stop "
                                "path, or document the exit-join contract"
                            ),
                        )
                    )
                elif kind not in ("file",):
                    out.append(
                        Finding(
                            rule="resource-lifecycle",
                            path=path, line=node.lineno,
                            severity="error",
                            message=(
                                f"{kind} acquired from {label} is "
                                "discarded without a release handle"
                            ),
                            detail=f"release via {'/'.join(rel_names)}",
                        )
                    )
                continue
            okind, oname = owner
            if okind == "escape":
                continue
            if okind == "self":
                rel = (class_releases or {}).get(oname, set())
                if not rel & set(rel_names):
                    out.append(
                        Finding(
                            rule="resource-lifecycle",
                            path=path, line=node.lineno,
                            severity="error",
                            message=(
                                f"{kind} stored on 'self.{oname}' is "
                                f"never released by {cls}"
                            ),
                            detail=(
                                f"no method of {cls} calls "
                                f"{'/'.join(rel_names)} on it"
                            ),
                        )
                    )
            elif okind == "global":
                if not has_atexit:
                    out.append(
                        Finding(
                            rule="resource-lifecycle",
                            path=path, line=node.lineno,
                            severity="warning",
                            message=(
                                f"process-lifetime {kind} in module "
                                f"global '{oname}' has no atexit "
                                "coverage"
                            ),
                            detail=(
                                "register a teardown hook so workers "
                                "and handles do not outlive the process"
                            ),
                        )
                    )
            else:  # local variable
                calls = scope.calls.get(oname, set())
                fin = scope.finally_calls.get(oname, set())
                released = calls & set(rel_names)
                released_fin = fin & set(rel_names)
                if released_fin:
                    continue
                if oname in scope.escapes:
                    continue  # ownership transferred
                if released:
                    out.append(
                        Finding(
                            rule="resource-lifecycle",
                            path=path, line=node.lineno,
                            severity="warning",
                            message=(
                                f"{kind} '{oname}' is released only on "
                                "the happy path"
                            ),
                            detail=(
                                "move the release into try/finally or "
                                "a context manager — the exception "
                                "path leaks it"
                            ),
                        )
                    )
                elif kind == "thread" and "start" not in calls:
                    continue  # constructed but never started
                else:
                    out.append(
                        Finding(
                            rule="resource-lifecycle",
                            path=path, line=node.lineno,
                            severity="error",
                            message=(
                                f"{kind} '{oname}' acquired from "
                                f"{label} is never released"
                            ),
                            detail=f"release via {'/'.join(rel_names)}",
                        )
                    )
        return out

    def _owner_of(self, fn, call: ast.Call, mutables: set[str]):
        """Where the acquisition's value lands: ("self", attr),
        ("global", name) for module-registry stores, ("local", name),
        ("escape", _) when stored into another object, or None for a
        discarded value. The call counts as the assignment's value even
        when wrapped in a conditional expression."""
        for node in ast.walk(fn):
            value = None
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.NamedExpr):
                value, targets = node.value, [node.target]
            if value is None or not any(
                sub is call for sub in ast.walk(value)
            ):
                continue
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    return ("self", attr)
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name
                ) and t.value.id in mutables:
                    return ("global", t.value.id)
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    # stored into some other object: ownership
                    # transferred to its holder
                    return ("escape", attr_chain(t))
            for t in targets:
                if isinstance(t, ast.Name):
                    return ("local", t.id)
        return None
