"""SARIF 2.1.0 export for `kcmc check --sarif` (docs/ANALYSIS.md).

Static Analysis Results Interchange Format: the one JSON dialect the
GitHub code-scanning UI ingests natively, so `kcmc check` findings
render as inline PR annotations instead of a log line someone has to
go read. The emitter is deliberately minimal-but-valid: one run, one
driver, a rule table built from the rules that actually fired, and one
result per NEW finding (baselined findings are accepted debt — they do
not annotate PRs; baseline-hygiene problems do).

The structural contract is pinned by `tests/test_analysis.py`, which
validates the output against a SARIF 2.1.0 subset schema with
jsonschema when available and by hand otherwise.
"""

from __future__ import annotations

SARIF_SCHEMA_URI = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

_RULE_HELP = {
    "config-registry": "CorrectorConfig resume-signature classification",
    "jit-purity": "no host work inside jit-traced code",
    "lock-order": "lock acquisition graph must be acyclic",
    "daemon-xla": "XLA-reaching work only on non-daemon threads",
    "span-registry": "telemetry names drawn from obs/registry.py",
    "thread-roots": "concurrent entry points named and resolvable",
    "race": "cross-root shared access needs intersecting lock sets",
    "resource-lifecycle": "acquired resources reach release on all paths",
    "retrace": "no trace-time branching/capture of per-call values",
    "dtype-flow": "no silent wide-dtype promotion or upload widening",
    "transfer": "no host transfers inside the dispatch window",
    "bucket-escape": "jit dispatch shapes stay on the plan_buckets ladder",
    "roofline-vocab": "plan-routed programs priced by the roofline model",
    "donation": "dying same-shape jit inputs should donate their buffer",
    "baseline": "baseline entries stay justified and live",
    "parse": "sources must parse",
}

_LEVELS = {"error": "error", "warning": "warning"}


def to_sarif(result) -> dict:
    """A CheckResult -> SARIF 2.1.0 log dict (new findings + baseline
    problems; baselined findings are deliberately excluded)."""
    findings = list(result.new) + list(result.baseline_problems)
    rule_ids = sorted({f.rule for f in findings} | set(_RULE_HELP))
    rule_index = {r: i for i, r in enumerate(rule_ids)}
    rules = [
        {
            "id": r,
            "name": r.replace("-", " ").title().replace(" ", ""),
            "shortDescription": {
                "text": _RULE_HELP.get(r, r)
            },
            "helpUri": (
                "https://github.com/kcmc-tpu/kcmc-tpu/blob/main/"
                "docs/ANALYSIS.md"
            ),
        }
        for r in rule_ids
    ]
    results = []
    for f in findings:
        text = f.message if not f.detail else f"{f.message} ({f.detail})"
        results.append(
            {
                "ruleId": f.rule,
                "ruleIndex": rule_index[f.rule],
                "level": _LEVELS.get(f.severity, "note"),
                "message": {"text": text},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path,
                                "uriBaseId": "%SRCROOT%",
                            },
                            "region": {"startLine": max(1, int(f.line))},
                        }
                    }
                ],
                # stable identity for annotation dedup across pushes
                "partialFingerprints": {
                    "kcmcFindingKey/v1": f.key
                },
            }
        )
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "kcmc-check",
                        "informationUri": (
                            "https://github.com/kcmc-tpu/kcmc-tpu/"
                            "blob/main/docs/ANALYSIS.md"
                        ),
                        "rules": rules,
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }
