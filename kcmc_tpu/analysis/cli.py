"""`kcmc check`: run the repo's invariant passes and gate on the
baseline (docs/ANALYSIS.md).

Exit codes: 0 = no new error-severity findings (warnings and baselined
findings never block); 1 = new errors (or unjustified baseline
entries); 2 = usage problems (missing baseline file, bad root).

The default baseline ships inside the package
(`kcmc_tpu/analysis/baseline.json`), so `kcmc check` works from any
checkout without flags; `--write-baseline` rewrites it from the
current findings with placeholder reasons for NEW entries — fill the
reasons in before committing (an empty reason is itself a finding).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def default_passes():
    from kcmc_tpu.analysis.config_registry import ConfigRegistryPass
    from kcmc_tpu.analysis.jit_purity import JitPurityPass
    from kcmc_tpu.analysis.lock_discipline import LockDisciplinePass
    from kcmc_tpu.analysis.span_registry import SpanRegistryPass

    return [
        ConfigRegistryPass(),
        JitPurityPass(),
        LockDisciplinePass(),
        SpanRegistryPass(),
    ]


def find_repo_root(start: str | None = None) -> str:
    """The directory holding the `kcmc_tpu/` package: walk up from
    this file (source checkouts), falling back to cwd."""
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.dirname(os.path.dirname(here))  # …/kcmc_tpu/analysis
    if os.path.isdir(os.path.join(cand, "kcmc_tpu")):
        return cand
    cwd = os.path.abspath(start or os.getcwd())
    if os.path.isdir(os.path.join(cwd, "kcmc_tpu")):
        return cwd
    return cand


def default_baseline_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "baseline.json"
    )


def run_check(
    root: str,
    baseline_path: str | None = None,
    passes=None,
):
    from kcmc_tpu.analysis.core import Baseline, ModuleIndex, run_passes

    index = ModuleIndex.from_package(root)
    bl_path = baseline_path or default_baseline_path()
    baseline = Baseline.load(bl_path) if os.path.exists(bl_path) else None
    return run_passes(
        index, passes if passes is not None else default_passes(), baseline
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kcmc check",
        description=(
            "AST-based repo invariant checker: config-signature "
            "registry, jit purity, lock/thread discipline, span "
            "registry (docs/ANALYSIS.md)"
        ),
    )
    ap.add_argument(
        "--root",
        default=None,
        help="repo root holding kcmc_tpu/ (default: auto-detected)",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        metavar="PATH",
        help=(
            "baseline file of accepted findings (default: the "
            "checked-in kcmc_tpu/analysis/baseline.json)"
        ),
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="machine-readable report on stdout (kind: kcmc_check)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help=(
            "rewrite the baseline from the current findings (new "
            "entries get a FILL-ME-IN reason; commit only after "
            "justifying each)"
        ),
    )
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else find_repo_root()
    if not os.path.isdir(os.path.join(root, "kcmc_tpu")):
        print(
            f"kcmc check: no kcmc_tpu/ package under {root!r}",
            file=sys.stderr,
        )
        return 2
    bl_path = args.baseline or default_baseline_path()
    if args.baseline and not os.path.exists(bl_path):
        print(
            f"kcmc check: baseline {bl_path!r} does not exist",
            file=sys.stderr,
        )
        return 2

    try:
        result = run_check(root, baseline_path=bl_path)
    except (ValueError, KeyError, OSError) as e:
        # a hand-edited baseline with bad JSON / wrong kind / missing
        # entry fields is a usage error (exit 2), not "new findings"
        print(
            f"kcmc check: cannot load baseline {bl_path!r}: {e}",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        from kcmc_tpu.analysis.core import Baseline, BaselineEntry

        old = (
            Baseline.load(bl_path) if os.path.exists(bl_path) else Baseline()
        )
        # re-match against the current findings so still-firing entries
        # survive and stale ones drop out
        old.split(result.findings)
        entries = [e for e in old.entries if e.used]
        known = {(e.rule, e.path, e.match) for e in entries}
        for f in result.new:
            key = (f.rule, f.path, f.message)
            if key not in known:
                known.add(key)
                entries.append(
                    BaselineEntry(
                        rule=f.rule,
                        path=f.path,
                        match=f.message,
                        reason="FILL-ME-IN: justify or fix",
                    )
                )
        Baseline(entries).save(bl_path)
        print(
            f"kcmc check: wrote {len(entries)} baseline entries to "
            f"{bl_path}",
            file=sys.stderr,
        )

    if args.json:
        print(json.dumps(result.as_dict()))
    else:
        for f in result.new:
            print(f.format())
        for f in result.baseline_problems:
            print(f.format())
        s = result.summary()
        print(
            f"kcmc check: {s['findings']} findings "
            f"({s['baselined']} baselined, {s['new']} new, "
            f"{s['new_errors']} new errors, "
            f"{s['stale_baseline']} stale baseline) -> "
            f"{'OK' if s['ok'] else 'FAIL'}"
        )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
