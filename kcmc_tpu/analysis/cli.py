"""`kcmc check`: run the repo's invariant passes and gate on the
baseline (docs/ANALYSIS.md).

Exit codes: 0 = no new error-severity findings (warnings and baselined
findings never block); 1 = new errors (or unjustified baseline
entries); 2 = usage problems (missing baseline file, bad root).

The default baseline ships inside the package
(`kcmc_tpu/analysis/baseline.json`), so `kcmc check` works from any
checkout without flags; `--write-baseline` rewrites it from the
current findings with placeholder reasons for NEW entries — fill the
reasons in before committing (an empty reason is itself a finding).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def default_passes():
    from kcmc_tpu.analysis.concurrency import RacePass, ThreadRootsPass
    from kcmc_tpu.analysis.config_registry import ConfigRegistryPass
    from kcmc_tpu.analysis.donation import DonationPass
    from kcmc_tpu.analysis.jit_purity import JitPurityPass
    from kcmc_tpu.analysis.lifecycle import ResourceLifecyclePass
    from kcmc_tpu.analysis.lock_discipline import LockDisciplinePass
    from kcmc_tpu.analysis.span_registry import SpanRegistryPass
    from kcmc_tpu.analysis.traceflow import TraceFlowPass

    return [
        ConfigRegistryPass(),
        JitPurityPass(),
        LockDisciplinePass(),
        SpanRegistryPass(),
        ThreadRootsPass(),
        RacePass(),
        ResourceLifecyclePass(),
        TraceFlowPass(),
        DonationPass(),
    ]


def find_repo_root(start: str | None = None) -> str:
    """The directory holding the `kcmc_tpu/` package: walk up from
    this file (source checkouts), falling back to cwd."""
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.dirname(os.path.dirname(here))  # …/kcmc_tpu/analysis
    if os.path.isdir(os.path.join(cand, "kcmc_tpu")):
        return cand
    cwd = os.path.abspath(start or os.getcwd())
    if os.path.isdir(os.path.join(cwd, "kcmc_tpu")):
        return cwd
    return cand


def default_baseline_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "baseline.json"
    )


def run_check(
    root: str,
    baseline_path: str | None = None,
    passes=None,
    use_cache: bool = True,
):
    from kcmc_tpu.analysis.cache import CheckCache
    from kcmc_tpu.analysis.core import Baseline, ModuleIndex, run_passes

    index = ModuleIndex.from_package(root)
    bl_path = baseline_path or default_baseline_path()
    baseline = Baseline.load(bl_path) if os.path.exists(bl_path) else None
    return run_passes(
        index,
        passes if passes is not None else default_passes(),
        baseline,
        cache=CheckCache(root) if use_cache else None,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kcmc check",
        description=(
            "AST-based repo invariant checker: config-signature "
            "registry, jit purity, lock/thread discipline, span "
            "registry, thread-root inventory, whole-program race "
            "detection, resource lifecycle, trace-contract flow, and "
            "the buffer-donation audit (docs/ANALYSIS.md)"
        ),
    )
    ap.add_argument(
        "--root",
        default=None,
        help="repo root holding kcmc_tpu/ (default: auto-detected)",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        metavar="PATH",
        help=(
            "baseline file of accepted findings (default: the "
            "checked-in kcmc_tpu/analysis/baseline.json)"
        ),
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="machine-readable report on stdout (kind: kcmc_check)",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help=(
            "bypass the content-hash result cache "
            "(.kcmc_check_cache/) and re-run every pass"
        ),
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help=(
            "rewrite the baseline from the current findings (new "
            "entries get a FILL-ME-IN reason; commit only after "
            "justifying each)"
        ),
    )
    ap.add_argument(
        "--prune-baseline",
        action="store_true",
        help=(
            "drop STALE baseline entries (entries whose finding no "
            "longer fires) and rewrite the file — the explicit cleanup "
            "mode behind the stale-entry warning"
        ),
    )
    ap.add_argument(
        "--sarif",
        default="",
        metavar="PATH",
        help=(
            "also write the NEW findings as a SARIF 2.1.0 log (GitHub "
            "code-scanning upload renders them as inline PR "
            "annotations); '-' for stdout"
        ),
    )
    args = ap.parse_args(argv)
    if args.json and args.sarif == "-":
        print(
            "kcmc check: --json and --sarif - both claim stdout; "
            "write the SARIF log to a file",
            file=sys.stderr,
        )
        return 2

    root = os.path.abspath(args.root) if args.root else find_repo_root()
    if not os.path.isdir(os.path.join(root, "kcmc_tpu")):
        print(
            f"kcmc check: no kcmc_tpu/ package under {root!r}",
            file=sys.stderr,
        )
        return 2
    bl_path = args.baseline or default_baseline_path()
    if args.baseline and not os.path.exists(bl_path):
        print(
            f"kcmc check: baseline {bl_path!r} does not exist",
            file=sys.stderr,
        )
        return 2

    try:
        result = run_check(
            root, baseline_path=bl_path, use_cache=not args.no_cache
        )
    except (ValueError, KeyError, OSError) as e:
        # a hand-edited baseline with bad JSON / wrong kind / missing
        # entry fields is a usage error (exit 2), not "new findings"
        print(
            f"kcmc check: cannot load baseline {bl_path!r}: {e}",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        from kcmc_tpu.analysis.core import Baseline, BaselineEntry

        old = (
            Baseline.load(bl_path) if os.path.exists(bl_path) else Baseline()
        )
        # re-match against the current findings so still-firing entries
        # survive and stale ones drop out
        old.split(result.findings)
        entries = [e for e in old.entries if e.used]
        known = {(e.rule, e.path, e.match) for e in entries}
        for f in result.new:
            key = (f.rule, f.path, f.message)
            if key not in known:
                known.add(key)
                entries.append(
                    BaselineEntry(
                        rule=f.rule,
                        path=f.path,
                        match=f.message,
                        reason="FILL-ME-IN: justify or fix",
                    )
                )
        Baseline(entries).save(bl_path)
        print(
            f"kcmc check: wrote {len(entries)} baseline entries to "
            f"{bl_path}",
            file=sys.stderr,
        )

    if args.prune_baseline:
        from kcmc_tpu.analysis.core import Baseline

        if not os.path.exists(bl_path):
            print(
                f"kcmc check: no baseline at {bl_path!r} to prune",
                file=sys.stderr,
            )
            return 2
        bl = Baseline.load(bl_path)
        bl.split(result.findings)  # marks still-firing entries used
        live = [e for e in bl.entries if e.used]
        pruned = len(bl.entries) - len(live)
        if pruned:
            Baseline(live).save(bl_path)
        print(
            f"kcmc check: pruned {pruned} stale baseline entr"
            f"{'y' if pruned == 1 else 'ies'} "
            f"({len(live)} live) in {bl_path}",
            file=sys.stderr,
        )
        if pruned:
            # the pruned file is the new truth: re-evaluate the gate so
            # a prune run reports the same exit the next plain run would
            result = run_check(
            root, baseline_path=bl_path, use_cache=not args.no_cache
        )

    if args.sarif:
        from kcmc_tpu.analysis.sarif import to_sarif

        payload = json.dumps(to_sarif(result), indent=2)
        if args.sarif == "-":
            print(payload)
        else:
            with open(args.sarif, "w", encoding="utf-8") as f:
                f.write(payload + "\n")
            print(
                f"kcmc check: wrote SARIF log to {args.sarif}",
                file=sys.stderr,
            )

    if args.json:
        print(json.dumps(result.as_dict()))
    elif args.sarif == "-":
        # stdout is the SARIF document; the summary goes to stderr
        s = result.summary()
        print(
            f"kcmc check: {s['findings']} findings ({s['new']} new) -> "
            f"{'OK' if s['ok'] else 'FAIL'}",
            file=sys.stderr,
        )
    else:
        for f in result.new:
            print(f.format())
        for f in result.baseline_problems:
            print(f.format())
        s = result.summary()
        print(
            f"kcmc check: {s['findings']} findings "
            f"({s['baselined']} baselined, {s['new']} new, "
            f"{s['new_errors']} new errors, "
            f"{s['stale_baseline']} stale baseline) -> "
            f"{'OK' if s['ok'] else 'FAIL'}"
        )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
