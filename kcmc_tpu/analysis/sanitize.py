"""Runtime concurrency sanitizer (`kcmc sanitize` / `KCMC_SANITIZE=1`
/ `pytest --sanitize`; docs/ANALYSIS.md).

The static passes reason about code; this module watches the process.
Three instruments, all designed for "run the real suite under it"
overhead (< 2x wall-clock on tier-1 — measured in docs/ANALYSIS.md):

* **lock-order recording** — `threading.Lock`/`RLock`/`Condition`
  constructed from kcmc code are wrapped; each wrapper knows its
  creation site (`file:line` — the same identity the static
  lock-order graph uses, so `self._lock = threading.Lock()` maps onto
  `_ClassModel.locks`). Acquiring lock B while holding lock A records
  the runtime edge A→B; an edge that closes a cycle against the
  union of runtime edges AND the static lock-order graph is a
  violation — one executed order plus one statically-written reverse
  order is enough to convict, no unlucky interleaving required.
  `Condition(existing_lock)` shares the wrapped lock's identity,
  exactly as the static aliasing does.

* **deadlock watchdog** — a background thread (daemon: it touches no
  XLA) scans held wrappers; a lock held past the threshold WITH
  waiters dumps every thread's stack to stderr once and records a
  violation. The fast path stays lock-free: holder/waiter info lives
  in plain attributes the watchdog reads advisorily.

* **leak checking** — `leak_snapshot()` / `check_leaks(before)`
  bracket a test: threads started and not stopped (non-daemon, or any
  `kcmc-*`-named thread; executor workers show up here too since
  their threads are non-daemon), sockets opened and not closed
  (`socket.socket` is subclass-patched while enabled), and telemetry
  artifact-path claims never released (`obs.run._ACTIVE_PATHS`).
  Process-lifetime-by-design resources are exempt: the shared decode
  pools (`kcmc-decode*`) and the process-pool manager threads they
  own.

The hot-path cost model: an uncontended acquire with no other lock
held is a thread-local list append/pop on top of the real acquire; the
sanitizer's own mutex is taken only to record a NEW edge (bounded by
the number of distinct lock pairs, not acquisitions).
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
import traceback
import weakref

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_SOCKET = None  # set at enable (socket imported lazily)

# Threads that are process-lifetime by design (docs/ANALYSIS.md):
# shared decode-pool workers and the process-pool plumbing they own.
LEAK_EXEMPT_THREADS = (
    "kcmc-decode",
    "ExecutorManagerThread",
    "QueueFeederThread",
    "QueueManagerThread",
)

_STATE: "_State | None" = None


def _norm_path(filename: str) -> str:
    """Repo-relative identity for a frame filename: the tail from the
    last `kcmc_tpu/` (or `tests/`) component, matching the static
    passes' module paths."""
    norm = filename.replace(os.sep, "/")
    for anchor in ("kcmc_tpu/", "tests/"):
        i = norm.rfind(anchor)
        if i >= 0:
            return norm[i:]
    return norm.rsplit("/", 1)[-1]


_THIS_FILE = os.path.abspath(__file__)


def _creation_site() -> tuple[str, int] | None:
    """(relpath, line) of the first frame outside this module and
    threading.py — None when the creator is not kcmc code (such locks
    stay uninstrumented)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE and not fn.endswith(
            ("threading.py",)
        ):
            norm = _norm_path(fn)
            if norm.startswith(("kcmc_tpu/", "tests/")) or (
                "kcmc" in norm or norm.startswith("test_")
            ):
                return (norm, f.f_lineno)
            return None
        f = f.f_back
    return None


class _State:
    def __init__(self, static_edges, watchdog_s: float, strict: bool):
        self.mutex = _REAL_LOCK()
        self.static_edges: set = set(static_edges or ())
        self.edges: dict = {}  # (a, b) -> description
        self.violations: list[str] = []
        self.strict = bool(strict)
        self.watchdog_s = float(watchdog_s)
        self.locks_instrumented = 0
        self.acquisitions = 0  # advisory (unlocked increments)
        self._tl = threading.local()
        self.wrappers: "weakref.WeakSet" = weakref.WeakSet()
        self.sockets: "weakref.WeakSet" = weakref.WeakSet()
        self._watchdog: threading.Thread | None = None
        self._watchdog_stop = threading.Event()
        self._dumped: set = set()

    # -- per-thread held stack --------------------------------------------

    def held(self) -> list:
        h = getattr(self._tl, "held", None)
        if h is None:
            h = self._tl.held = []
        return h

    # -- order graph -------------------------------------------------------

    def note_acquired(self, wrapper) -> None:
        held = self.held()
        self.acquisitions += 1
        if wrapper in held:  # RLock reentrancy: no new edges
            held.append(wrapper)
            return
        new = []
        for h in held:
            if h.site != wrapper.site:
                new.append((h.site, wrapper.site))
        held.append(wrapper)
        if not new:
            return
        with self.mutex:
            for edge in new:
                if edge in self.edges:
                    continue
                self.edges[edge] = (
                    f"{threading.current_thread().name}"
                )
                cycle = self._find_cycle(edge)
                if cycle is not None:
                    msg = (
                        "lock-order violation: acquiring "
                        f"{_site_label(edge[1])} while holding "
                        f"{_site_label(edge[0])} closes the cycle "
                        + " -> ".join(_site_label(s) for s in cycle)
                    )
                    self.violations.append(msg)
                    print(f"[kcmc sanitize] {msg}", file=sys.stderr)
                    if self.strict:
                        raise RuntimeError(msg)

    def note_released(self, wrapper) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is wrapper:
                del held[i]
                break

    def _find_cycle(self, new_edge):
        """A path new_edge[1] ->* new_edge[0] through runtime+static
        edges (the new edge then closes the cycle)."""
        graph: dict = {}
        for a, b in list(self.edges) + list(self.static_edges):
            graph.setdefault(a, set()).add(b)
        start, goal = new_edge[1], new_edge[0]
        stack, seen = [(start, (start,))], set()
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path + (start,)
            if node in seen:
                continue
            seen.add(node)
            for nxt in graph.get(node, ()):
                stack.append((nxt, path + (nxt,)))
        return None

    # -- watchdog ----------------------------------------------------------

    def start_watchdog(self) -> None:
        if self.watchdog_s <= 0 or self._watchdog is not None:
            return
        self._watchdog_stop.clear()
        self._watchdog = threading.Thread(
            target=self._watch, name="kcmc-sanitize-watchdog", daemon=True
        )
        self._watchdog.start()

    def stop_watchdog(self) -> None:
        self._watchdog_stop.set()
        t, self._watchdog = self._watchdog, None
        if t is not None:
            t.join(timeout=2.0)

    def _watch(self) -> None:
        while not self._watchdog_stop.wait(
            max(0.05, min(self.watchdog_s / 4.0, 1.0))
        ):
            now = time.monotonic()
            for w in list(self.wrappers):
                holder = w._holder
                if holder is None or w._waiters <= 0:
                    continue
                hname, t_acq = holder
                if now - t_acq < self.watchdog_s:
                    continue
                key = (w.site, t_acq)
                if key in self._dumped:
                    continue
                self._dumped.add(key)
                msg = (
                    "deadlock suspect: lock "
                    f"{_site_label(w.site)} held {now - t_acq:.1f}s by "
                    f"{hname} with {w._waiters} waiter(s)"
                )
                with self.mutex:
                    self.violations.append(msg)
                print(f"[kcmc sanitize] {msg}", file=sys.stderr)
                self.dump_stacks()

    @staticmethod
    def dump_stacks() -> None:
        """Every thread's current stack, attributed by thread name —
        the post-mortem a wedged serving plane never gives you."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = ["[kcmc sanitize] all-thread stack dump:"]
        for tid, frame in sorted(sys._current_frames().items()):
            out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
            out.extend(
                line.rstrip()
                for line in traceback.format_stack(frame)
            )
        print("\n".join(out), file=sys.stderr, flush=True)


def _site_label(site) -> str:
    return f"{site[0]}:{site[1]}"


# -- instrumented primitives ------------------------------------------------


class _InstrumentedLock:
    """Wraps one real Lock/RLock; shares its creation-site identity
    with any Condition built on it."""

    def __init__(self, real, site, state):
        self._real = real
        self.site = site
        self._state = state
        self._holder = None  # (thread name, t_acquired) — advisory
        self._hold_depth = 0
        self._waiters = 0  # advisory
        state.wrappers.add(self)
        state.locks_instrumented += 1

    def acquire(self, blocking=True, timeout=-1):
        st = self._state
        if blocking:
            self._waiters += 1
        try:
            ok = self._real.acquire(blocking, timeout)
        finally:
            if blocking:
                self._waiters -= 1
        if ok:
            try:
                st.note_acquired(self)
            except BaseException:
                # strict mode raises on a cycle-closing acquisition:
                # the REAL lock was already taken — undo both sides or
                # the raise leaves it held forever
                st.note_released(self)
                self._real.release()
                raise
            self._hold_depth += 1
            if self._hold_depth == 1:
                self._holder = (
                    threading.current_thread().name,
                    time.monotonic(),
                )
        return ok

    def release(self):
        self._hold_depth -= 1
        if self._hold_depth <= 0:
            self._holder = None
            self._hold_depth = 0
        self._state.note_released(self)
        self._real.release()

    def locked(self):
        f = getattr(self._real, "locked", None)
        if f is not None:
            return f()
        # RLock has no locked() on 3.10: probe non-blockingly
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # RLock protocol bits Condition uses
    def _is_owned(self):
        f = getattr(self._real, "_is_owned", None)
        if f is not None:
            return f()
        return self._real.locked()

    def _acquire_restore(self, state):
        self._real._acquire_restore(state)
        self._state.note_acquired(self)
        self._hold_depth += 1

    def _release_save(self):
        self._hold_depth = 0
        self._holder = None
        self._state.note_released(self)
        return self._real._release_save()

    def __repr__(self):
        return f"<kcmc-sanitized lock {_site_label(self.site)}>"


class _InstrumentedCondition:
    """A Condition sharing its (wrapped) lock's identity: waiting IS
    holding, exactly as the static alias model says."""

    def __init__(self, lock_wrapper, state):
        self._lock = lock_wrapper
        self._real = _REAL_CONDITION(
            lock_wrapper._real
            if isinstance(lock_wrapper, _InstrumentedLock)
            else lock_wrapper
        )
        self._state = state
        self.site = getattr(lock_wrapper, "site", None)

    # lock protocol delegates to the instrumented lock
    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout=None):
        # the real wait releases the underlying lock: suspend the
        # wrapper's held/holder accounting for the duration
        lw = self._lock
        depth = lw._hold_depth
        lw._hold_depth = 0
        lw._holder = None
        for _ in range(depth):
            self._state.note_released(lw)
        try:
            return self._real.wait(timeout)
        finally:
            for _ in range(depth):
                self._state.note_acquired(lw)
            lw._hold_depth = depth
            lw._holder = (
                threading.current_thread().name, time.monotonic()
            )

    def wait_for(self, predicate, timeout=None):
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
            else:
                waittime = None
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n=1):
        self._real.notify(n)

    def notify_all(self):
        self._real.notify_all()


# -- factories (the monkeypatch surface) ------------------------------------


def _lock_factory():
    st = _STATE
    if st is None:
        return _REAL_LOCK()
    site = _creation_site()
    if site is None:
        return _REAL_LOCK()
    return _InstrumentedLock(_REAL_LOCK(), site, st)


def _rlock_factory():
    st = _STATE
    if st is None:
        return _REAL_RLOCK()
    site = _creation_site()
    if site is None:
        return _REAL_RLOCK()
    return _InstrumentedLock(_REAL_RLOCK(), site, st)


def _condition_factory(lock=None):
    st = _STATE
    if st is None:
        return _REAL_CONDITION(lock)
    if isinstance(lock, _InstrumentedLock):
        return _InstrumentedCondition(lock, st)
    if lock is not None:
        return _REAL_CONDITION(lock)
    site = _creation_site()
    if site is None:
        return _REAL_CONDITION()
    return _InstrumentedCondition(
        _InstrumentedLock(_REAL_RLOCK(), site, st), st
    )


# -- static graph bridge -----------------------------------------------------


def static_order_edges(root: str | None = None) -> set:
    """The static lock-order graph keyed by lock DEFINITION sites —
    the same (path, line) identity runtime wrappers carry, so the
    sanitizer convicts on one executed order plus one written reverse
    order."""
    from kcmc_tpu.analysis.cli import find_repo_root
    from kcmc_tpu.analysis.core import FunctionTable, ModuleIndex
    from kcmc_tpu.analysis.lock_discipline import _ClassModel

    index = ModuleIndex.from_package(root or find_repo_root())
    edges: set = set()
    for mod in index:
        table = FunctionTable(mod.tree)
        for cls in table.classes.values():
            model = _ClassModel(mod, cls, table)
            for (outer, inner), (_line, _via) in model.order_edges().items():
                lo = model.locks.get(outer)
                li = model.locks.get(inner)
                if lo is not None and li is not None:
                    edges.add(((mod.path, lo), (mod.path, li)))
    return edges


# -- public surface ----------------------------------------------------------


def active() -> bool:
    return _STATE is not None


def enable(
    root: str | None = None,
    static: bool = True,
    watchdog_s: float = 10.0,
    strict: bool = False,
) -> None:
    """Install the sanitizer (idempotent): patch the lock factories,
    track sockets, merge the static lock-order graph, start the
    watchdog."""
    global _STATE, _REAL_SOCKET
    if _STATE is not None:
        return
    edges = set()
    if static:
        try:
            edges = static_order_edges(root)
        except Exception as e:  # static graph is an enhancement only
            print(
                f"[kcmc sanitize] static lock-order graph unavailable "
                f"({e}); runtime-only order checking",
                file=sys.stderr,
            )
    st = _State(edges, watchdog_s, strict)
    _STATE = st
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    import socket as _socket_mod

    _REAL_SOCKET = _socket_mod.socket

    class _TrackedSocket(_REAL_SOCKET):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            if _STATE is not None:
                _STATE.sockets.add(self)

    _socket_mod.socket = _TrackedSocket
    st.start_watchdog()
    atexit.register(_report_at_exit)


def disable() -> None:
    """Remove the patches (wrappers already handed out keep working —
    they delegate to real primitives)."""
    global _STATE, _REAL_SOCKET
    st = _STATE
    if st is None:
        return
    st.stop_watchdog()
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    if _REAL_SOCKET is not None:
        import socket as _socket_mod

        _socket_mod.socket = _REAL_SOCKET
        _REAL_SOCKET = None
    _STATE = None


def take_violations() -> list[str]:
    """Drain the accumulated violations (lock-order cycles, deadlock
    suspects, retrace-sentinel convictions) — the per-test gate."""
    out = _drain_sentinel()
    st = _STATE
    if st is None:
        return out
    with st.mutex:
        out, st.violations = out + st.violations, []
    return out


def stats() -> dict:
    st = _STATE
    if st is None:
        return {"active": False}
    with st.mutex:
        return {
            "active": True,
            "locks_instrumented": st.locks_instrumented,
            "acquisitions": st.acquisitions,
            "order_edges": len(st.edges),
            "static_edges": len(st.static_edges),
            "violations": len(st.violations),
        }


# -- leak checking -----------------------------------------------------------


def _thread_key(t: threading.Thread) -> tuple:
    return (t.ident, t.name)


def _shared_pool_threads() -> set[int]:
    """Thread idents owned by the process-lifetime shared decode pools
    (io/feeder.py registry): their executor manager/worker threads are
    unnamed stdlib threads, so exempt them by ownership, not name."""
    out: set[int] = set()
    feeder = sys.modules.get("kcmc_tpu.io.feeder")
    if feeder is None:
        return out
    try:
        with feeder._SHARED_LOCK:
            pools = list(feeder._SHARED.values())
    except Exception:
        return out
    for pool in pools:
        ex = getattr(pool, "_ex", None)
        mgr = getattr(ex, "_executor_manager_thread", None)
        if mgr is not None and mgr.ident is not None:
            out.add(mgr.ident)
        for t in list(getattr(ex, "_threads", ()) or ()):
            if t.ident is not None:
                out.add(t.ident)
    return out


def leak_snapshot() -> dict:
    """What is alive NOW: bracket a test with this + check_leaks."""
    snap = {
        "threads": {_thread_key(t) for t in threading.enumerate()},
        "paths": set(),
        "sockets": set(),
    }
    try:
        from kcmc_tpu.obs import run as obs_run

        with obs_run._PATHS_LOCK:
            snap["paths"] = set(obs_run._ACTIVE_PATHS)
    except Exception:
        pass
    st = _STATE
    if st is not None:
        snap["sockets"] = {
            id(s) for s in list(st.sockets) if s.fileno() != -1
        }
    return snap


def check_leaks(before: dict, grace_s: float = 2.0) -> list[str]:
    """Leaks relative to `before`: threads still running that a test
    started (after a grace join — finishing threads are not leaks),
    sockets still open, telemetry path claims never released."""
    leaks: list[str] = []
    known = before.get("threads", set())
    deadline = time.monotonic() + grace_s

    def candidates():
        out = []
        shared = _shared_pool_threads()
        for t in threading.enumerate():
            if _thread_key(t) in known or t is threading.current_thread():
                continue
            if any(t.name.startswith(p) for p in LEAK_EXEMPT_THREADS):
                continue
            if t.name == "kcmc-sanitize-watchdog" or t.ident in shared:
                continue
            if not t.daemon or t.name.startswith("kcmc-"):
                out.append(t)
        return out

    cands = candidates()
    for t in cands:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    for t in candidates():
        leaks.append(
            f"leaked thread '{t.name}' "
            f"({'non-daemon' if not t.daemon else 'daemon'}) still "
            "alive after the test (join it on the owner's stop path)"
        )
    try:
        from kcmc_tpu.obs import run as obs_run

        with obs_run._PATHS_LOCK:
            now_paths = set(obs_run._ACTIVE_PATHS)
        for p in sorted(now_paths - before.get("paths", set())):
            leaks.append(
                f"leaked telemetry path claim {p!r} (RunTelemetry "
                "finish/close never ran)"
            )
    except Exception:
        pass
    st = _STATE
    if st is not None:
        before_socks = before.get("sockets", set())
        for s in list(st.sockets):
            try:
                open_now = s.fileno() != -1
            except Exception:
                open_now = False
            if open_now and id(s) not in before_socks:
                leaks.append(
                    f"leaked socket {s!r} opened during the test and "
                    "never closed"
                )
    return leaks


# -- retrace sentinel --------------------------------------------------------
#
# The runtime half of the traceflow bucket-escape rule (docs/
# ANALYSIS.md): plans/runtime.py notes every first program build
# (jit_compile / plan_build) here. The static bucket ladder predicts
# the full compile-key set of a warmed process (plans.runtime.
# predict_compile_keys); arming the sentinel after warm-up turns any
# further compile of a COVERED program into a violation — the exact
# cross-validation the lock-order checker does for concurrency: one
# static prediction plus one runtime observation convicts, no profiler
# archaeology required. Violations drain through take_violations(), so
# `pytest --sanitize` fails the test that retraced.


class _RetraceSentinel:
    def __init__(self, covered, predicted, label):
        self.covered = frozenset(covered)
        self.predicted = frozenset(predicted or ())
        self.label = label
        self.counts: dict[tuple, int] = {}
        self.violations: list[str] = []
        self.lock = _REAL_LOCK()


_SENTINEL: "_RetraceSentinel | None" = None

DEFAULT_COVERED_PROGRAMS = ("reference", "register", "apply")


def note_compile(
    program: str,
    shape: tuple,
    dtype: str,
    rung: str = "full",
    during_build: bool = False,
) -> None:
    """Compile observation hook (called by plans/runtime.PlanRuntime on
    every first build of a program key). No-op unless a sentinel is
    armed; builds driven by ExecutionPlan (`during_build`) are the
    warm-up itself and never convict."""
    st = _SENTINEL
    if st is None:
        return
    key = (program, tuple(shape), str(dtype))
    with st.lock:
        st.counts[key] = st.counts.get(key, 0) + 1
    if during_build or program not in st.covered:
        return
    shape_s = "x".join(str(s) for s in shape)
    hint = ""
    if st.predicted:
        hint = (
            " - the static bucket ladder predicted "
            f"{len(st.predicted)} compile keys, all already warmed"
            if key not in st.predicted
            else " - a predicted key compiled AGAIN after warm-up"
        )
    msg = (
        f"retrace sentinel{f' [{st.label}]' if st.label else ''}: "
        f"program '{program}' compiled at {shape_s}/{dtype} (rung "
        f"{rung}) after warm-up{hint}; the dispatched shape escaped "
        "the plan_buckets ladder"
    )
    with st.lock:
        st.violations.append(msg)
    print(f"[kcmc sanitize] {msg}", file=sys.stderr)


def arm_retrace_sentinel(
    covered=DEFAULT_COVERED_PROGRAMS, predicted=None, label: str = ""
) -> None:
    """Arm after warm-up: from now on, any compile of a covered program
    is a violation. `predicted` (a predict_compile_keys set) only
    sharpens the message — armed-after-warm-up means the allowed count
    is zero either way."""
    global _SENTINEL
    _SENTINEL = _RetraceSentinel(covered, predicted, label)


def disarm_retrace_sentinel() -> None:
    global _SENTINEL
    _SENTINEL = None


class retrace_sentinel:
    """Context manager: `with sanitize.retrace_sentinel(...):` around
    warmed traffic. Violations recorded inside the block stay pending
    for take_violations() (the `pytest --sanitize` per-test gate), so
    the with-block arms and disarms without swallowing the report."""

    def __init__(
        self,
        covered=DEFAULT_COVERED_PROGRAMS,
        predicted=None,
        label: str = "",
    ):
        self._args = (covered, predicted, label)

    def __enter__(self):
        arm_retrace_sentinel(*self._args)
        return _SENTINEL

    def __exit__(self, *exc):
        st = _SENTINEL
        if st is not None and st.violations:
            with st.lock:
                pending, st.violations = list(st.violations), []
            _pending_sentinel_violations.extend(pending)
        disarm_retrace_sentinel()
        return False


# violations that outlive a disarmed sentinel, drained with the state's
_pending_sentinel_violations: list[str] = []


def sentinel_stats() -> dict:
    st = _SENTINEL
    if st is None:
        return {"armed": False}
    with st.lock:
        return {
            "armed": True,
            "covered": sorted(st.covered),
            "compiles": {
                f"{p}|{'x'.join(str(s) for s in shape)}|{dt}": n
                for (p, shape, dt), n in sorted(st.counts.items())
            },
            "violations": len(st.violations),
        }


def _drain_sentinel() -> list[str]:
    out = list(_pending_sentinel_violations)
    _pending_sentinel_violations.clear()
    st = _SENTINEL
    if st is not None:
        with st.lock:
            out += st.violations
            st.violations = []
    return out


# -- env / CLI entry ---------------------------------------------------------


def maybe_enable_from_env() -> bool:
    """Honor KCMC_SANITIZE=1 (options via KCMC_SANITIZE_WATCHDOG /
    KCMC_SANITIZE_STATIC / KCMC_SANITIZE_STRICT). Called from the CLI
    entry and the pytest plugin."""
    if os.environ.get("KCMC_SANITIZE", "") not in ("1", "true", "yes"):
        return False
    enable(
        static=os.environ.get("KCMC_SANITIZE_STATIC", "1") != "0",
        watchdog_s=float(os.environ.get("KCMC_SANITIZE_WATCHDOG", "10")),
        strict=os.environ.get("KCMC_SANITIZE_STRICT", "") == "1",
    )
    return True


def _report_at_exit() -> None:
    st = _STATE
    if st is None:
        return
    s = stats()
    line = (
        f"[kcmc sanitize] {s['locks_instrumented']} locks instrumented, "
        f"{s['acquisitions']} acquisitions, {s['order_edges']} order "
        f"edges ({s['static_edges']} static), "
        f"{s['violations']} violation(s)"
    )
    print(line, file=sys.stderr)
    for v in st.violations:
        print(f"[kcmc sanitize] UNRESOLVED: {v}", file=sys.stderr)


def main(argv=None) -> int:
    """`kcmc sanitize [opts] -- cmd args…`: re-exec a command with the
    sanitizer armed through the environment. pytest runs pick it up
    via the tests/conftest.py plugin; `python -m kcmc_tpu …` runs pick
    it up in the CLI entry (`maybe_enable_from_env`)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="kcmc sanitize",
        description=(
            "run a command under the runtime concurrency sanitizer "
            "(instrumented locks + lock-order validation against the "
            "static graph, deadlock watchdog, leak checking; "
            "docs/ANALYSIS.md)"
        ),
    )
    ap.add_argument(
        "--watchdog", type=float, default=10.0, metavar="SECS",
        help="dump all thread stacks when a lock is held this long "
        "with waiters (default 10)",
    )
    ap.add_argument(
        "--no-static", action="store_true",
        help="skip merging the static lock-order graph",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="raise at the acquisition that closes a lock-order cycle "
        "instead of recording it",
    )
    ap.add_argument(
        "cmd", nargs=argparse.REMAINDER,
        help="command to run (e.g. pytest tests/test_serve.py -q)",
    )
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":  # only the leading separator is ours
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (e.g. kcmc sanitize pytest tests/ -q)")
    env = dict(os.environ)
    env["KCMC_SANITIZE"] = "1"
    env["KCMC_SANITIZE_WATCHDOG"] = str(args.watchdog)
    env["KCMC_SANITIZE_STATIC"] = "0" if args.no_static else "1"
    if args.strict:
        env["KCMC_SANITIZE_STRICT"] = "1"
    if cmd[0] == "pytest":
        # KCMC_SANITIZE=1 already arms the pytest plugin through
        # maybe_enable_from_env (appending --sanitize here would both
        # mask the env options and break rootdirs whose conftest does
        # not register the flag)
        cmd = [sys.executable, "-m", "pytest"] + cmd[1:]
    import subprocess

    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    sys.exit(main())
