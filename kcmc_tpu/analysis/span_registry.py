"""Pass 4 — span/name registry (`span-registry`).

`obs/report.py` renders whatever the rest of the package recorded: if a
producer renames a trace span or a `timing[...]` key, the report (and
every dashboard built on the trace JSON) silently drops the series —
telemetry drift with no failing test. The canonical name registry
(`kcmc_tpu/obs/registry.py`) is the single source of truth; this pass
checks both directions:

* every string-literal span name at an emission site —
  `tracer.complete/span/instant/counter(...)`, `timer.stage/stall(...)`,
  `timer.add_stall(...)` — is registered;
* every string-literal `timing["key"]` store AND `timing.get("key")` /
  `timing["key"]` read (the report/CLI consumer side) is registered;
* registered span names that no emission site uses anymore are flagged
  stale (warning), so the registry can't rot into a name museum.

Dynamic names (a variable first argument) are skipped: the registry
governs the literal vocabulary, and this repo's two dynamic sites
(plan runtime's `plan_build`/`jit_compile` pick) choose between
registered literals.
"""

from __future__ import annotations

import ast

from kcmc_tpu.analysis.core import (
    Finding,
    ModuleIndex,
    attr_chain,
    str_const,
    str_set_from,
)

REGISTRY_PATH = "kcmc_tpu/obs/registry.py"
SPAN_SET_NAME = "SPAN_NAMES"
TIMING_SET_NAME = "TIMING_KEYS"

# method name -> emits a span-like name as first string arg.
# `observe` is the latency-segment recorder (obs/latency.py
# SegmentLatencies.observe) — its first argument is a lifecycle
# segment name, governed by REQUEST_SEGMENTS/JOURNAL_SPANS in the
# registry so an unregistered segment fails this pass (the CI canary
# proves it).
SPAN_EMITTERS = frozenset(
    {
        "complete", "span", "instant", "counter", "stage", "stall",
        "add_stall", "observe",
    }
)


def _eval_set(node: ast.AST, env: dict[str, set[str]]) -> set[str] | None:
    """Resolve a registry value statically: a literal frozenset/set, a
    Name bound to one earlier in the module, or a `|` union of such."""
    lit = str_set_from(node)
    if lit is not None:
        return lit
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        left = _eval_set(node.left, env)
        right = _eval_set(node.right, env)
        if left is not None and right is not None:
            return left | right
    return None


def _registry_sets(index: ModuleIndex, path: str):
    mod = index.get(path)
    if mod is None:
        return None, None, 0
    env: dict[str, set[str]] = {}
    spans = timing = None
    line = 0
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for t in stmt.targets:
            if not isinstance(t, ast.Name):
                continue
            value = _eval_set(stmt.value, env)
            if value is not None:
                env[t.id] = value
            if t.id == SPAN_SET_NAME:
                spans = value
                line = stmt.lineno
            elif t.id == TIMING_SET_NAME:
                timing = value
    return spans, timing, line


class SpanRegistryPass:
    name = "span-registry"

    def __init__(self, registry_path: str = REGISTRY_PATH):
        self.registry_path = registry_path

    def run(self, index: ModuleIndex) -> list[Finding]:
        spans, timing, _line = _registry_sets(index, self.registry_path)
        if spans is None or timing is None:
            return [
                Finding(
                    rule=self.name,
                    path=self.registry_path,
                    line=0,
                    severity="error",
                    message=(
                        f"canonical registry not found: {self.registry_path}"
                        f" must define literal {SPAN_SET_NAME} and "
                        f"{TIMING_SET_NAME} sets"
                    ),
                )
            ]
        out: list[Finding] = []
        used_spans: set[str] = set()
        for mod in index:
            if mod.path == self.registry_path:
                continue
            for node in ast.walk(mod.tree):
                # staleness accounting is deliberately string-level:
                # dynamic sites pick between registered literals (e.g.
                # plan runtime's `"plan_build" if building else
                # "jit_compile"`), so ANY occurrence of the literal in
                # a module keeps the name alive
                if (
                    isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value in spans
                ):
                    used_spans.add(node.value)
                # emission sites: obj.<emitter>("literal", ...)
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    meth = node.func.attr
                    if meth in SPAN_EMITTERS and node.args:
                        name = str_const(node.args[0])
                        if name is not None:
                            used_spans.add(name)
                            if name not in spans:
                                out.append(
                                    Finding(
                                        rule=self.name,
                                        path=mod.path,
                                        line=node.lineno,
                                        severity="error",
                                        message=(
                                            f"span name '{name}' "
                                            f"(via .{meth}) is not in "
                                            f"{SPAN_SET_NAME}"
                                        ),
                                        detail=(
                                            "register it in "
                                            f"{self.registry_path} so "
                                            "obs/report.py and trace "
                                            "consumers see it"
                                        ),
                                    )
                                )
                # timing reads: timing.get("key") / res.timing.get("key")
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and node.args
                    and attr_chain(node.func.value).split(".")[-1]
                    == "timing"
                ):
                    key = str_const(node.args[0])
                    if key is not None and key not in timing:
                        out.append(
                            self._timing_finding(mod, node.lineno, key)
                        )
                # timing stores/reads by subscript: timing["key"]
                if (
                    isinstance(node, ast.Subscript)
                    and attr_chain(node.value).split(".")[-1] == "timing"
                ):
                    key = str_const(node.slice)
                    if key is not None and key not in timing:
                        out.append(
                            self._timing_finding(mod, node.lineno, key)
                        )
        for stale in sorted(spans - used_spans):
            out.append(
                Finding(
                    rule=self.name,
                    path=self.registry_path,
                    line=0,
                    severity="warning",
                    message=(
                        f"registered span name '{stale}' has no "
                        "emission site left"
                    ),
                    detail="remove it or restore the producer",
                )
            )
        # one finding per (path, line, message)
        uniq: dict[tuple, Finding] = {}
        for f in out:
            uniq.setdefault((f.path, f.line, f.message), f)
        return list(uniq.values())

    def _timing_finding(self, mod, line: int, key: str) -> Finding:
        return Finding(
            rule=self.name,
            path=mod.path,
            line=line,
            severity="error",
            message=(
                f"timing key '{key}' is not in {TIMING_SET_NAME}"
            ),
            detail=f"register it in {self.registry_path}",
        )
