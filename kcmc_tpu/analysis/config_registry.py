"""Pass 1 — config-registry completeness (`config-registry`).

The resume-checkpoint signature pins every `CorrectorConfig` field that
is declared *signature-neutral* (`SIG_NEUTRAL_FIELDS`) to its default
before hashing; everything else restarts a resume when changed. A new
field added to the dataclass but to NEITHER registry silently lands on
whichever side `dataclasses.replace` happens to give it — corrupting
resume semantics with no test to notice. This pass makes the
classification total, validated, and documented:

* every dataclass field of `CorrectorConfig` appears in exactly one of
  `SIG_NEUTRAL_FIELDS` / `SIG_AFFECTING_FIELDS` (config.py);
* neither registry names a field that no longer exists;
* `__post_init__` calls the runtime validator
  (`_validate_field_classification`), so the invariant also holds for
  anyone vendoring a modified config;
* every field is documented in `docs/API.md` (backtick-quoted).
"""

from __future__ import annotations

import ast

from kcmc_tpu.analysis.core import (
    Finding,
    ModuleIndex,
    attr_chain,
    str_set_from,
)

NEUTRAL_NAME = "SIG_NEUTRAL_FIELDS"
AFFECTING_NAME = "SIG_AFFECTING_FIELDS"
VALIDATOR_NAME = "_validate_field_classification"


class ConfigRegistryPass:
    name = "config-registry"

    def __init__(
        self,
        config_path: str = "kcmc_tpu/config.py",
        config_class: str = "CorrectorConfig",
        api_doc: str = "docs/API.md",
    ):
        self.config_path = config_path
        self.config_class = config_class
        self.api_doc = api_doc

    def run(self, index: ModuleIndex) -> list[Finding]:
        mod = index.get(self.config_path)
        if mod is None:
            return [
                Finding(
                    rule=self.name,
                    path=self.config_path,
                    line=0,
                    severity="error",
                    message="config module not found in the index",
                )
            ]
        out: list[Finding] = []

        cls = next(
            (
                n
                for n in ast.walk(mod.tree)
                if isinstance(n, ast.ClassDef)
                and n.name == self.config_class
            ),
            None,
        )
        if cls is None:
            return [
                Finding(
                    rule=self.name,
                    path=self.config_path,
                    line=0,
                    severity="error",
                    message=f"class {self.config_class} not found",
                )
            ]

        # Dataclass fields: annotated class-body assignments. Walk only
        # the class body's direct statements (nested defs are methods).
        fields: dict[str, int] = {}
        for stmt in cls.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                fields[stmt.target.id] = stmt.lineno

        # The two registries: module-level NAME = frozenset({...}).
        registries: dict[str, tuple[set[str], int]] = {}
        for stmt in mod.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id in (
                    NEUTRAL_NAME,
                    AFFECTING_NAME,
                ):
                    members = str_set_from(value)
                    if members is None:
                        out.append(
                            Finding(
                                rule=self.name,
                                path=self.config_path,
                                line=stmt.lineno,
                                severity="error",
                                message=(
                                    f"{t.id} must be a literal frozenset "
                                    "of field-name strings (the checker "
                                    "reads it statically)"
                                ),
                            )
                        )
                        members = set()
                    registries[t.id] = (members, stmt.lineno)

        for reg in (NEUTRAL_NAME, AFFECTING_NAME):
            if reg not in registries:
                out.append(
                    Finding(
                        rule=self.name,
                        path=self.config_path,
                        line=cls.lineno,
                        severity="error",
                        message=f"registry {reg} is not defined",
                    )
                )
        neutral, _ = registries.get(NEUTRAL_NAME, (set(), 0))
        affecting, _ = registries.get(AFFECTING_NAME, (set(), 0))

        # Totality + disjointness + staleness.
        for fname, line in sorted(fields.items()):
            in_n, in_a = fname in neutral, fname in affecting
            if not in_n and not in_a:
                out.append(
                    Finding(
                        rule=self.name,
                        path=self.config_path,
                        line=line,
                        severity="error",
                        message=(
                            f"config field '{fname}' is classified in "
                            f"neither {NEUTRAL_NAME} nor {AFFECTING_NAME}"
                        ),
                        detail=(
                            "decide whether changing it mid-run must "
                            "restart a checkpoint resume"
                        ),
                    )
                )
            elif in_n and in_a:
                out.append(
                    Finding(
                        rule=self.name,
                        path=self.config_path,
                        line=line,
                        severity="error",
                        message=(
                            f"config field '{fname}' is classified in "
                            "BOTH signature registries"
                        ),
                    )
                )
        for reg_name, members in (
            (NEUTRAL_NAME, neutral),
            (AFFECTING_NAME, affecting),
        ):
            line = registries.get(reg_name, (set(), 0))[1]
            for ghost in sorted(members - set(fields)):
                out.append(
                    Finding(
                        rule=self.name,
                        path=self.config_path,
                        line=line,
                        severity="error",
                        message=(
                            f"{reg_name} lists '{ghost}', which is not "
                            f"a {self.config_class} field"
                        ),
                    )
                )

        # __post_init__ must run the validator.
        post = next(
            (
                s
                for s in cls.body
                if isinstance(s, ast.FunctionDef)
                and s.name == "__post_init__"
            ),
            None,
        )
        calls_validator = post is not None and any(
            isinstance(n, ast.Call)
            and attr_chain(n.func).endswith(VALIDATOR_NAME)
            for n in ast.walk(post)
        )
        if not calls_validator:
            out.append(
                Finding(
                    rule=self.name,
                    path=self.config_path,
                    line=post.lineno if post else cls.lineno,
                    severity="error",
                    message=(
                        f"__post_init__ does not call {VALIDATOR_NAME} "
                        "(the runtime totality check)"
                    ),
                )
            )

        # Documentation: every field backtick-quoted in docs/API.md.
        api = index.docs.get(self.api_doc)
        if api is not None:
            for fname, line in sorted(fields.items()):
                if f"`{fname}`" not in api:
                    out.append(
                        Finding(
                            rule=self.name,
                            path=self.config_path,
                            line=line,
                            severity="error",
                            message=(
                                f"config field '{fname}' is not "
                                f"documented in {self.api_doc}"
                            ),
                        )
                    )
        return out
