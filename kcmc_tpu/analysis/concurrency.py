"""Passes 5+6 — whole-program concurrency (`thread-roots`, `race`).

PR 8's `shared-write` warning saw one class in one file; the bugs that
matter in a resident serve fleet cross modules — the scheduler thread
writing `Session` state a client thread reads, the heartbeat thread
sampling `RunTelemetry` counters the drain path increments. These two
passes do the cross-module version properly:

* **thread-roots** — the inventory: every concurrent entry point in
  the package (`threading.Thread(target=…)`, executor `.submit(…)`
  tasks, `atexit` hooks, `socketserver` connection handlers, plus the
  public "client" surface of every thread-owning class), each with its
  cross-module call-graph closure. As a rule it enforces two
  attributability contracts: threads carry a `name=` (the sanitizer's
  stack dumps and leak reports are useless without one) and thread
  targets are statically resolvable (no lambda targets).

* **race** — per root, walk the reachable functions propagating the
  *held-lock set* across call edges (a callee invoked under `with
  self._lock:` inherits that lock — the serving plane's "caller holds
  the lock" convention becomes visible), recording every shared
  attribute / module-global read and write with its guarding lock set.
  Two accesses to the same attribute from different roots (or from a
  replicated root against itself), at least one a write, with DISJOINT
  lock sets, is a data-race finding. Lock identity is program-wide
  (`callgraph.ProgramGraph.lock_id`): `Condition(self._lock)` aliases
  collapse and constructor-parameter locks resolve through their call
  sites, so `Session._cond` and `StreamScheduler._lock` are the SAME
  lock to the disjointness test. Ambiguity degrades to a wildcard lock
  that intersects everything — unresolvable aliasing silences, never
  flags.

Known model limits (documented in docs/ANALYSIS.md): analysis is
type-based, not instance-based (two distinct `Session` objects share
one static identity), construction-time publication (build an object,
then publish it under a lock) is invisible, and `__init__` bodies are
exempt from self-attribute recording for exactly that reason. The
baseline carries the justified remainder.
"""

from __future__ import annotations

import ast
import dataclasses

from kcmc_tpu.analysis.callgraph import (
    EXECUTOR_CTORS,
    THREAD_CTOR,
    WILDCARD_LOCK,
    FuncRef,
    ProgramGraph,
)
from kcmc_tpu.analysis.core import Finding, ModuleIndex
from kcmc_tpu.analysis.lock_discipline import _self_attr, attr_chain

# Synchronization-object constructors: attributes holding these are
# primitives, not shared data — their cross-thread use is the point.
SYNC_CTORS = frozenset(
    {
        "threading.Event",
        "threading.Semaphore",
        "threading.BoundedSemaphore",
        "threading.Barrier",
        "threading.local",
        "queue.Queue",
        "queue.SimpleQueue",
        "queue.LifoQueue",
        "queue.PriorityQueue",
    }
)

# Container-mutating method names: `self.pending.extend(…)` is a WRITE
# to `pending` even though the attribute itself is only loaded.
MUTATORS = frozenset(
    {
        "append", "appendleft", "extend", "extendleft", "add", "discard",
        "remove", "pop", "popleft", "popitem", "clear", "update", "insert",
        "setdefault", "sort", "reverse",
    }
)


@dataclasses.dataclass(frozen=True)
class Root:
    """One concurrent entry point (see module docstring)."""

    kind: str  # thread | task | atexit | handler | client
    ref: FuncRef
    site_path: str
    line: int
    name: str | None = None
    daemon: bool = False
    # Whether several instances of this root can run at once (executor
    # tasks, connection handlers). The "client" surface is modeled as
    # ONE external thread: callers that race each other reach the
    # package through the handler root, which IS replicated.
    replicated: bool = False

    @property
    def group(self) -> str:
        """Concurrency identity: accesses from the SAME group never
        race (unless the group is replicated). Every client root is
        one group — the model's single external caller thread."""
        if self.kind == "client":
            return "client"
        return f"{self.kind}:{self.site_path}:{self.line}"

    def label(self) -> str:
        tag = self.name or self.ref.name
        return f"{self.kind}:{tag}"


@dataclasses.dataclass(frozen=True)
class Access:
    root: Root
    kind: str  # "r" | "w"
    path: str
    line: int
    locks: frozenset


def _thread_kwargs(call: ast.Call) -> dict:
    out = {"target": None, "name": None, "named": False, "daemon": False}
    for kw in call.keywords:
        if kw.arg == "target":
            out["target"] = kw.value
        elif kw.arg == "name":
            # any name= satisfies the attributability contract; only a
            # string CONSTANT also labels the root in reports
            out["named"] = True
            if isinstance(kw.value, ast.Constant):
                out["name"] = kw.value.value
        elif kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            out["daemon"] = bool(kw.value.value)
    return out


def _scopes(graph: ProgramGraph):
    """Every (path, cls, fn_name, fn_node) in the program."""
    for mod in graph.index:
        table = graph.tables[mod.path]
        for cname in table.classes:
            info = graph.class_info(cname, mod.path)
            for mname, fn in (info.methods if info else {}).items():
                yield mod.path, cname, mname, fn
        for (path, fname), fn in graph.module_funcs.items():
            if path == mod.path:
                yield mod.path, None, fname, fn


def collect_roots(
    graph: ProgramGraph,
) -> tuple[list[Root], list[Finding]]:
    """The concurrent-entry-point inventory plus its rule findings.
    Memoized on the graph — the thread-roots and race passes share one
    full-program sweep."""
    cached = getattr(graph, "_roots_cache", None)
    if cached is not None:
        return cached
    roots: list[Root] = []
    problems: list[Finding] = []
    thread_owning: set[str] = set()  # class names constructing threads/pools
    root_modules: set[str] = set()

    def resolve_target(path, cls, fn, expr) -> FuncRef | None:
        if expr is None:
            return None
        if isinstance(expr, ast.Lambda):
            return None
        chain = attr_chain(expr)
        sattr = _self_attr(expr)
        if sattr is not None and cls is not None:
            info = graph.class_info(cls, path)
            if info is not None and sattr in info.methods:
                return FuncRef(info.path, cls, sattr)
        if isinstance(expr, ast.Name) and cls is not None:
            info = graph.class_info(cls, path)
            if info is not None and expr.id in info.methods:
                return FuncRef(info.path, cls, expr.id)
        return graph.resolve_in_module(path, chain, cls=cls)

    for path, cls, fname, fn in _scopes(graph):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            last = chain.rsplit(".", 1)[-1]
            if chain == THREAD_CTOR:
                if cls is not None:
                    thread_owning.add(cls)
                root_modules.add(path)
                kw = _thread_kwargs(node)
                target = resolve_target(path, cls, fn, kw["target"])
                if isinstance(kw["target"], ast.Lambda):
                    problems.append(
                        Finding(
                            rule="thread-roots",
                            path=path,
                            line=node.lineno,
                            severity="warning",
                            message=(
                                "thread constructed with a lambda target "
                                "is invisible to concurrency analysis"
                            ),
                            detail=(
                                "give the body a named function so the "
                                "race pass can walk it"
                            ),
                        )
                    )
                if not kw["named"]:
                    problems.append(
                        Finding(
                            rule="thread-roots",
                            path=path,
                            line=node.lineno,
                            severity="warning",
                            message=(
                                "thread constructed without a name= "
                                f"in {cls + '.' if cls else ''}{fname}"
                            ),
                            detail=(
                                "the sanitizer's deadlock stack dumps and "
                                "leak reports attribute threads by name"
                            ),
                        )
                    )
                if target is not None:
                    roots.append(
                        Root(
                            kind="thread",
                            ref=target,
                            site_path=path,
                            line=node.lineno,
                            name=kw["name"],
                            daemon=kw["daemon"],
                        )
                    )
            elif chain in EXECUTOR_CTORS or last in EXECUTOR_CTORS:
                if cls is not None:
                    thread_owning.add(cls)
                root_modules.add(path)
            elif last == "submit" and node.args:
                target = resolve_target(path, cls, fn, node.args[0])
                if target is not None:
                    roots.append(
                        Root(
                            kind="task",
                            ref=target,
                            site_path=path,
                            line=node.lineno,
                            replicated=True,
                        )
                    )
            elif chain == "atexit.register" and node.args:
                target = resolve_target(path, cls, fn, node.args[0])
                if target is not None:
                    roots.append(
                        Root(
                            kind="atexit",
                            ref=target,
                            site_path=path,
                            line=node.lineno,
                        )
                    )
    # module-level atexit hooks (the feeder shared-pool teardown)
    for mod in graph.index:
        for node in mod.tree.body:
            call = (
                node.value
                if isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                else None
            )
            if call is not None and attr_chain(call.func) == "atexit.register":
                target = (
                    graph.resolve_in_module(
                        mod.path, attr_chain(call.args[0])
                    )
                    if call.args
                    else None
                )
                if target is not None:
                    roots.append(
                        Root(
                            kind="atexit",
                            ref=target,
                            site_path=mod.path,
                            line=node.lineno,
                        )
                    )
                root_modules.add(mod.path)
    # socketserver connection handlers: every connection runs handle()
    # on its own thread
    for infos in graph.classes.values():
        for info in infos:
            if any("RequestHandler" in b for b in info.base_names) and (
                "handle" in info.methods
            ):
                roots.append(
                    Root(
                        kind="handler",
                        ref=FuncRef(info.path, info.node.name, "handle"),
                        site_path=info.path,
                        line=info.node.lineno,
                        replicated=True,
                    )
                )
                root_modules.add(info.path)
    # the client surface: public methods of thread-owning classes and
    # public functions of root-hosting modules, modeled as one
    # external caller thread
    for cname in sorted(thread_owning):
        info = graph.class_info(cname)
        if info is None:
            continue
        for mname, fn in sorted(info.methods.items()):
            if mname.startswith("_") and mname not in (
                "__enter__", "__exit__",
            ):
                continue
            roots.append(
                Root(
                    kind="client",
                    ref=FuncRef(info.path, cname, mname),
                    site_path=info.path,
                    line=fn.lineno,
                )
            )
    for path in sorted(root_modules):
        for (p, fname), fn in sorted(graph.module_funcs.items()):
            if p == path and not fname.startswith("_"):
                roots.append(
                    Root(
                        kind="client",
                        ref=FuncRef(p, None, fname),
                        site_path=p,
                        line=fn.lineno,
                    )
                )
    graph._roots_cache = (roots, problems)
    return roots, problems


class ThreadRootsPass:
    """The inventory rule: see module docstring."""

    name = "thread-roots"

    def run(self, index: ModuleIndex) -> list[Finding]:
        graph = ProgramGraph.for_index(index)
        _roots, problems = collect_roots(graph)
        # nested defs are walked from both their own scope and their
        # enclosing function — dedup identical findings
        out, seen = [], set()
        for f in problems:
            k = (f.rule, f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                out.append(f)
        return out


# -- the race detector -----------------------------------------------------


class _FnWalker(ast.NodeVisitor):
    """One function body: lexical lock tracking, access recording, and
    call-edge collection (with the held set at each call site)."""

    def __init__(
        self, graph, ref: FuncRef, held: frozenset, out,
        in_ctor: bool = False,
    ):
        self.graph = graph
        self.ref = ref
        self.path = ref.path
        self.cls = ref.cls
        self.info = (
            graph.class_info(ref.cls, ref.path) if ref.cls else None
        )
        self.held: frozenset = held
        self.out = out  # _RaceCollector
        self.locals: dict[str, str] = {}  # var -> class name
        self.declared_globals: set[str] = set()
        # Construction context: `__init__` bodies AND everything
        # reached through a constructor call are building a not-yet-
        # published object — self-attribute traffic there is exempt
        # (module globals still record: registries like the telemetry
        # path claims are shared even at construction time).
        self.in_ctor = in_ctor or ref.name == "__init__"
        self.record_self = not self.in_ctor
        self.mutables = graph.module_mutables.get(ref.path, set())
        self.mod_locks = graph.module_locks.get(ref.path, {})

    # -- lock identity of a with-item --------------------------------------

    def _lock_of(self, expr) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and self.info is not None:
            if self.graph.is_lock_attr(self.info, attr):
                return self.graph.lock_id(self.info, attr)
            return None
        if isinstance(expr, ast.Name) and expr.id in self.mod_locks:
            return f"{self.path}:{expr.id}"
        return None

    def _is_sync_attr(self, attr: str) -> bool:
        info = self.info
        if info is None:
            return False
        if self.graph.is_lock_attr(info, attr):
            return True
        return attr in getattr(info, "sync_attrs", ())

    # -- recording ---------------------------------------------------------

    def _rec_attr(self, attr: str, kind: str, line: int) -> None:
        if not self.record_self or self.info is None:
            return
        if self._is_sync_attr(attr) or attr in self.info.methods:
            return
        self.out.record(
            ("attr", self.info.node.name, attr),
            kind,
            self.path,
            line,
            self.held,
        )

    def _rec_global(self, name: str, kind: str, line: int) -> None:
        self.out.record(
            ("global", self.path, name), kind, self.path, line, self.held
        )

    # -- visitors ----------------------------------------------------------

    def visit_Global(self, node: ast.Global) -> None:
        self.declared_globals.update(node.names)

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lid = self._lock_of(item.context_expr)
            if lid is not None:
                acquired.append(lid)
            else:
                self.visit(item.context_expr)
        prev = self.held
        if acquired:
            self.held = self.held | frozenset(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    visit_AsyncWith = visit_With

    def visit_Assign(self, node: ast.Assign) -> None:
        v = node.value
        if isinstance(v, ast.Call):
            ref = self.graph.resolve_in_module(
                self.path, attr_chain(v.func), cls=self.cls
            )
            if ref is not None and ref.cls and ref.name == "__init__":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.locals[t.id] = ref.cls
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self._rec_attr(attr, "w", node.lineno)
            else:
                self._rec_attr(attr, "r", node.lineno)
            return
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None:
                self._rec_attr(attr, "w", node.lineno)
                self.visit(node.slice)
                return
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in self.mutables
            ):
                self._rec_global(node.value.id, "w", node.lineno)
                self.visit(node.slice)
                return
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in self.mutables:
            if isinstance(node.ctx, ast.Load):
                self._rec_global(node.id, "r", node.lineno)
            elif node.id in self.declared_globals or isinstance(
                node.ctx, ast.Del
            ):
                self._rec_global(node.id, "w", node.lineno)

    def visit_Call(self, node: ast.Call) -> None:
        chain = attr_chain(node.func)
        parts = chain.split(".")
        # container-mutating calls: self.attr.append(…) / GLOBAL.pop(…)
        if len(parts) == 3 and parts[0] == "self":
            kind = "w" if parts[2] in MUTATORS else "r"
            self._rec_attr(parts[1], kind, node.lineno)
        elif len(parts) == 2 and parts[0] == "self":
            pass  # self.m(...) — the call edge below covers it
        elif len(parts) == 2 and parts[0] in self.mutables:
            kind = "w" if parts[1] in MUTATORS else "r"
            self._rec_global(parts[0], kind, node.lineno)
        ref = self._resolve_call(chain)
        if ref is not None:
            self.out.edge(
                ref,
                self.held,
                self.in_ctor or ref.name == "__init__",
            )
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            self.visit(kw.value)

    def _resolve_call(self, chain: str) -> FuncRef | None:
        head, _, rest = chain.partition(".")
        if head in self.locals and rest:
            info = self.graph.class_info(self.locals[head])
            m = rest.split(".")[-1]
            if info is not None and m in info.methods:
                return FuncRef(info.path, info.node.name, m)
        if not rest and self.cls is not None and self.info is not None:
            # bare name that is a sibling method (nested producer fns)
            if head in self.info.methods and (
                (self.path, head) not in self.graph.module_funcs
            ):
                return FuncRef(self.info.path, self.cls, head)
        return self.graph.resolve_in_module(
            self.path, chain, cls=self.cls
        )


class _RaceCollector:
    def __init__(self, root: Root):
        self.root = root
        self.accesses: list[tuple[tuple, Access]] = []
        self.edges: list[tuple[FuncRef, frozenset, bool]] = []

    def record(self, key, kind, path, line, held) -> None:
        self.accesses.append(
            (key, Access(self.root, kind, path, line, held))
        )

    def edge(self, ref, held, in_ctor: bool) -> None:
        self.edges.append((ref, held, in_ctor))


def _walk_root(graph: ProgramGraph, root: Root, budget: int = 4000):
    """The root's reachable closure with held-lock propagation:
    accesses list per shared-state key."""
    col = _RaceCollector(root)
    seen: set[tuple] = set()
    stack: list[tuple[FuncRef, frozenset, bool]] = [
        (root.ref, frozenset(), False)
    ]
    visits = 0
    while stack and visits < budget:
        ref, held, in_ctor = stack.pop()
        key = (ref.path, ref.cls, ref.name, held, in_ctor)
        if key in seen:
            continue
        seen.add(key)
        fn = graph.function(ref)
        if fn is None:
            continue
        visits += 1
        walker = _FnWalker(graph, ref, held, col, in_ctor=in_ctor)
        for stmt in fn.body:
            walker.visit(stmt)
        while col.edges:
            stack.append(col.edges.pop())
    return col.accesses


def _disjoint(a: frozenset, b: frozenset) -> bool:
    if WILDCARD_LOCK in a or WILDCARD_LOCK in b:
        return False
    return not (a & b)


def _annotate_sync_attrs(graph: ProgramGraph) -> None:
    """Mark attributes holding Event/Queue/… constructions so they are
    exempt from data-race recording (they ARE the synchronization)."""
    for infos in graph.classes.values():
        for info in infos:
            sync: set[str] = set()
            for fn in info.methods.values():
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign) or not isinstance(
                        node.value, ast.Call
                    ):
                        continue
                    chain = attr_chain(node.value.func)
                    if chain in SYNC_CTORS or chain.rsplit(".", 1)[
                        -1
                    ] in ("Event", "local", "SimpleQueue"):
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                sync.add(attr)
            info.sync_attrs = sync


class RacePass:
    """Cross-root disjoint-lock-set access pairs (module docstring)."""

    name = "race"

    def run(self, index: ModuleIndex) -> list[Finding]:
        graph = ProgramGraph.for_index(index)
        _annotate_sync_attrs(graph)
        # Classes that DECLARED synchronization (a lock, condition, or
        # sync primitive): these opted into the concurrency contract,
        # so a replicated root racing itself on their state reports;
        # sync-free classes only report across distinct roots (their
        # replicated-instance state is usually per-instance).
        sync_owners = {
            info.node.name
            for infos in graph.classes.values()
            for info in infos
            if info.locks or info.alias or info.param_locks
            or getattr(info, "sync_attrs", None)
        }
        roots, _problems = collect_roots(graph)
        seen_roots: set[tuple] = set()
        by_key: dict[tuple, list[Access]] = {}
        for root in roots:
            rk = (root.kind, root.ref, root.site_path, root.line)
            if rk in seen_roots:
                continue
            seen_roots.add(rk)
            for key, acc in _walk_root(graph, root):
                by_key.setdefault(key, []).append(acc)
        out: list[Finding] = []
        emitted: set[tuple] = set()
        for key in sorted(by_key, key=str):
            accs = by_key[key]
            self_race_ok = key[0] == "attr" and key[1] in sync_owners
            pair = self._conflict(accs, self_race_ok)
            if pair is None:
                continue
            if key in emitted:
                continue
            emitted.add(key)
            a, b = pair
            if key[0] == "attr":
                what = f"'{key[1]}.{key[2]}'"
            else:
                what = f"module global '{key[2]}'"
            out.append(
                Finding(
                    rule="race",
                    path=a.path,
                    line=a.line,
                    severity="error",
                    message=(
                        f"possible data race on {what}: concurrent "
                        "roots access it with disjoint lock sets"
                    ),
                    detail=(
                        f"{a.kind}@{a.path}:{a.line} from "
                        f"{a.root.label()} holds "
                        f"{sorted(a.locks) or 'no locks'} vs "
                        f"{b.kind}@{b.path}:{b.line} from "
                        f"{b.root.label()} holds "
                        f"{sorted(b.locks) or 'no locks'}"
                    ),
                )
            )
        return out

    @staticmethod
    def _conflict(accs: list[Access], self_race_ok: bool):
        """First (write, other) pair from concurrent roots with
        disjoint lock sets. Same-group pairs count only when the group
        is replicated AND the state's class declared synchronization
        (`self_race_ok`) — replicated instances of a sync-free class
        are modeled as per-instance state."""
        writes = [a for a in accs if a.kind == "w"]
        if not writes:
            return None
        for w in writes:
            for o in accs:
                if o is w:
                    continue
                if o.root.group == w.root.group and not (
                    w.root.replicated and self_race_ok
                ):
                    continue
                if _disjoint(w.locks, o.locks):
                    return (w, o) if w.line <= o.line else (o, w)
        return None
