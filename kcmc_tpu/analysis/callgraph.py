"""Cross-module program graph for the whole-program concurrency passes.

PR 8's passes were deliberately AST-local: each rule reasoned about
what a reader of ONE file could verify. The concurrency bugs that take
down a resident serve fleet are exactly the ones that scoping cannot
express — a write in `StreamScheduler` racing a read in `Session`
through the shared plane lock, a pool leaked across a module boundary.
This module relaxes the same-module restriction for the `thread-roots`
/ `race` / `resource-lifecycle` passes ONLY: it builds, from the shared
`ModuleIndex`, the program-wide tables those passes walk —

* **imports** — per-module alias resolution (`from kcmc_tpu.io import
  feeder`, `from kcmc_tpu.backends import get_backend` through one
  `__init__` re-export hop) so dotted call names resolve across files;
* **classes / functions** — program-wide registries, plus
  *unique-method CHA*: `obj.m()` resolves to class C when C is the
  only class in the package defining `m` (ambiguous names resolve
  nowhere — deliberately self-limiting);
* **attribute / local types** — `self.scheduler = StreamScheduler(…)`
  and `pool = DecodePool(…)` give `self.scheduler.submit()` /
  `pool.submit()` precise targets without CHA;
* **locks** — per-class lock inventories (reusing the PR-8
  `lock_discipline` ctor grammar) EXTENDED with cross-object aliasing:
  a `threading.Condition(lock)` built on a constructor parameter is
  resolved through every static call site of that constructor, so
  `Session._cond` IS `StreamScheduler._lock` to the race detector —
  the serving plane's one-lock design becomes statically visible.

Everything here is still stdlib-`ast` only, and resolution failures
are silent (an unresolved call contributes no edges): the passes built
on top must stay demonstrable on known-bad fixtures and quiet on code
they cannot see into.
"""

from __future__ import annotations

import ast
import dataclasses
import weakref

from kcmc_tpu.analysis.core import FunctionTable, ModuleIndex

# per-ModuleIndex memo of built graphs (see ProgramGraph.for_index)
_GRAPH_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
from kcmc_tpu.analysis.lock_discipline import (
    CONDITION_CTOR,
    LOCK_CTORS,
    _self_attr,
    attr_chain,
)

THREAD_CTOR = "threading.Thread"
EXECUTOR_CTORS = ("ThreadPoolExecutor", "ProcessPoolExecutor")

# The wildcard lock: identity statically unknown (an ambiguous
# constructor binding). It intersects every lock set, so it can never
# make two accesses "disjointly locked" — unresolved aliasing degrades
# to silence, not to false positives.
WILDCARD_LOCK = "*"

# Method names too generic for unique-method CHA: they are container /
# IO protocol vocabulary, and "exactly one program class defines it"
# is then an accident of the current codebase, not evidence of the
# receiver's type.
CHA_STOPLIST = frozenset(
    {
        "add", "append", "appendleft", "extend", "pop", "popleft",
        "clear", "update", "remove", "discard", "insert", "get", "put",
        "items", "keys", "values", "copy", "join", "wait", "set",
        "read", "write", "open", "close", "flush", "send", "recv",
        "acquire", "release", "start", "stop", "submit", "result",
        "cancel", "done", "run", "name", "format",
    }
)


@dataclasses.dataclass(frozen=True)
class FuncRef:
    """One function in the program: (module path, class or None, name)."""

    path: str
    cls: str | None
    name: str

    def label(self) -> str:
        q = f"{self.cls}.{self.name}" if self.cls else self.name
        return f"{self.path}:{q}"


def _module_to_path(index: ModuleIndex, dotted: str) -> str | None:
    """'kcmc_tpu.io.feeder' -> 'kcmc_tpu/io/feeder.py' (or the package
    __init__) when that file is in the index."""
    base = dotted.replace(".", "/")
    for cand in (f"{base}.py", f"{base}/__init__.py"):
        if index.get(cand) is not None:
            return cand
    return None


class _ClassInfo:
    def __init__(self, path: str, node: ast.ClassDef, table: FunctionTable):
        self.path = path
        self.node = node
        self.methods: dict[str, ast.FunctionDef] = dict(
            table.methods.get(node.name, {})
        )
        self.locks: dict[str, int] = {}  # attr -> def line
        self.alias: dict[str, str] = {}  # attr -> attr (Condition on self lock)
        self.param_locks: dict[str, str] = {}  # attr -> __init__ param name
        self.attr_types: dict[str, str] = {}  # attr -> class name
        self.base_names = [attr_chain(b) for b in node.bases]


class ProgramGraph:
    """Program-wide resolution tables over a ModuleIndex (see module
    docstring). Build once per check run; shared by the concurrency
    and lifecycle passes (`for_index` memoizes per index — the three
    passes run over one shared build, not three)."""

    @classmethod
    def for_index(cls, index: ModuleIndex) -> "ProgramGraph":
        cached = _GRAPH_CACHE.get(index)
        if cached is None:
            cached = _GRAPH_CACHE[index] = cls(index)
        return cached

    def __init__(self, index: ModuleIndex):
        self.index = index
        self.tables: dict[str, FunctionTable] = {}
        self.imports: dict[str, dict[str, tuple]] = {}
        self.classes: dict[str, list[_ClassInfo]] = {}
        self.module_funcs: dict[tuple[str, str], ast.FunctionDef] = {}
        self.module_locks: dict[str, dict[str, int]] = {}  # path -> name -> line
        self.module_mutables: dict[str, set[str]] = {}  # path -> global names
        self.ctor_aliases: dict[str, dict[str, str]] = {}  # path -> alias -> ctor
        for mod in index:
            table = FunctionTable(mod.tree)
            self.tables[mod.path] = table
            self.imports[mod.path] = self._imports_of(mod.tree)
            for cname, cnode in table.classes.items():
                self.classes.setdefault(cname, []).append(
                    _ClassInfo(mod.path, cnode, table)
                )
            class_nodes = set()
            for cnode in table.classes.values():
                class_nodes.update(id(n) for n in ast.walk(cnode))
            for fname, fns in table.functions.items():
                for fn in fns:
                    if id(fn) not in class_nodes:
                        self.module_funcs.setdefault((mod.path, fname), fn)
            self._module_scope(mod)
        # method name -> classes defining it (CHA); unique wins
        self.method_owners: dict[str, list[_ClassInfo]] = {}
        for infos in self.classes.values():
            for info in infos:
                for m in info.methods:
                    self.method_owners.setdefault(m, []).append(info)
        for infos in self.classes.values():
            for info in infos:
                self._class_model(info)
        # Constructor-parameter lock bindings need every class model
        # built first (the binding site names locks of the CALLING
        # class), so they run as a second phase.
        self.param_bindings: dict[tuple[str, str], set[str]] = {}
        self._bind_ctor_locks()

    # -- construction ------------------------------------------------------

    def _imports_of(self, tree: ast.Module) -> dict[str, tuple]:
        out: dict[str, tuple] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    path = _module_to_path(self.index, a.name)
                    if path is not None:
                        out[a.asname or a.name.split(".")[0]] = ("mod", path)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    sub = _module_to_path(
                        self.index, f"{node.module}.{a.name}"
                    )
                    if sub is not None:
                        out[a.asname or a.name] = ("mod", sub)
                        continue
                    path = _module_to_path(self.index, node.module)
                    if path is not None:
                        out[a.asname or a.name] = ("sym", path, a.name)
        return out

    def _module_scope(self, mod) -> None:
        """Module-level locks, ctor aliases (`_REAL_LOCK =
        threading.Lock`), and mutable containers (the shared-state
        surface of module-global registries like the feeder pool map)."""
        locks: dict[str, int] = {}
        mutables: set[str] = set()
        aliases: dict[str, str] = {}
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if not names:
                continue
            v = node.value
            if isinstance(v, (ast.Attribute, ast.Name)):
                ref = attr_chain(v)
                if ref in LOCK_CTORS or ref == CONDITION_CTOR:
                    for n in names:
                        aliases[n] = ref
                continue
            chain = attr_chain(v.func) if isinstance(v, ast.Call) else ""
            chain = aliases.get(chain, chain)
            if chain in LOCK_CTORS or chain == CONDITION_CTOR:
                for n in names:
                    locks[n] = node.lineno
            elif (
                isinstance(v, (ast.Dict, ast.Set, ast.List))
                or chain.rsplit(".", 1)[-1] in ("dict", "set", "list", "deque")
            ):
                mutables.update(names)
        self.ctor_aliases[mod.path] = aliases
        self.module_locks[mod.path] = locks
        self.module_mutables[mod.path] = mutables

    def _class_model(self, info: _ClassInfo) -> None:
        """Locks, aliases, param-locks, and attribute types of a class."""
        aliases = self.ctor_aliases.get(info.path, {})
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                v = node.value
                chain = attr_chain(v.func) if isinstance(v, ast.Call) else ""
                chain = aliases.get(chain, chain)
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if chain in LOCK_CTORS:
                        info.locks[attr] = node.lineno
                    elif chain == CONDITION_CTOR:
                        arg = v.args[0] if v.args else None
                        inner = _self_attr(arg) if arg is not None else None
                        if inner is not None:
                            info.alias[attr] = inner
                        elif isinstance(arg, ast.Name):
                            # Condition built on a parameter: identity
                            # resolves through the ctor call sites.
                            info.param_locks[attr] = arg.id
                        else:
                            info.locks[attr] = node.lineno
                    elif isinstance(v, ast.Name) and fn.name == "__init__":
                        # self._x = lock_param — plain storage of a
                        # constructor argument (type via call sites)
                        info.param_locks.setdefault(attr, v.id)
                    elif chain and "." not in chain:
                        owner = self.unique_class(chain)
                        if owner is not None:
                            info.attr_types[attr] = chain
                    elif chain:
                        ref = self.resolve_in_module(info.path, chain)
                        if ref is not None and ref.cls and ref.name == "__init__":
                            info.attr_types[attr] = ref.cls

    def _bind_ctor_locks(self) -> None:
        """Resolve param-aliased locks through every static constructor
        call site: `Session(view, self._lock, …)` from a method of
        `StreamScheduler` binds Session's `lock` parameter to
        `StreamScheduler._lock`. Conflicting bindings degrade to the
        wildcard lock. One program-wide call-site sweep feeds every
        class (re-walking per class is quadratic in repo size)."""
        need = {
            info.node.name
            for infos in self.classes.values()
            for info in infos
            if info.param_locks
        }
        if not need:
            return
        sites: dict[str, list] = {}  # cls -> [(path, call, caller_cls)]
        for mod in self.index:
            table = self.tables[mod.path]
            spans: list[tuple[str | None, ast.AST]] = [
                (cname, cnode) for cname, cnode in table.classes.items()
            ]
            class_ids = {
                id(n)
                for _c, cnode in spans
                for n in ast.walk(cnode)
            }
            spans.append((None, mod.tree))
            for caller_cls, scope in spans:
                for node in ast.walk(scope):
                    if caller_cls is None and id(node) in class_ids:
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    chain = attr_chain(node.func)
                    if chain.rsplit(".", 1)[-1] not in need:
                        continue
                    ref = self.resolve_in_module(
                        mod.path, chain, cls=caller_cls
                    )
                    if (
                        ref is not None
                        and ref.cls in need
                        and ref.name == "__init__"
                    ):
                        sites.setdefault(ref.cls, []).append(
                            (mod.path, node, caller_cls)
                        )
        for infos in self.classes.values():
            for info in infos:
                if not info.param_locks:
                    continue
                init = info.methods.get("__init__")
                if init is None:
                    continue
                params = [a.arg for a in init.args.args if a.arg != "self"]
                for site_path, call, caller_cls in sites.get(
                    info.node.name, ()
                ):
                    bound = self._match_args(params, call)
                    for attr, pname in info.param_locks.items():
                        expr = bound.get(pname)
                        lid = self._lock_expr_id(
                            site_path, caller_cls, expr
                        ) if expr is not None else None
                        key = (info.node.name, attr)
                        self.param_bindings.setdefault(key, set()).add(
                            lid if lid is not None else WILDCARD_LOCK
                        )

    @staticmethod
    def _match_args(params: list[str], call: ast.Call) -> dict[str, ast.AST]:
        bound: dict[str, ast.AST] = {}
        for i, a in enumerate(call.args):
            if i < len(params):
                bound[params[i]] = a
        for kw in call.keywords:
            if kw.arg:
                bound[kw.arg] = kw.value
        return bound

    def _lock_expr_id(
        self, path: str, cls: str | None, expr: ast.AST
    ) -> str | None:
        """The lock identity of an expression at a call site (`self._l`
        of the calling class, or a module-level lock name)."""
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            info = self.class_info(cls, path)
            if info is not None and self.is_lock_attr(info, attr):
                return self.lock_id(info, attr)
        if isinstance(expr, ast.Name) and expr.id in self.module_locks.get(
            path, {}
        ):
            return f"{path}:{expr.id}"
        return None

    # -- lookup ------------------------------------------------------------

    def class_info(self, name: str, prefer_path: str | None = None):
        infos = self.classes.get(name)
        if not infos:
            return None
        if prefer_path is not None:
            for i in infos:
                if i.path == prefer_path:
                    return i
        return infos[0]

    def unique_class(self, name: str):
        infos = self.classes.get(name)
        return infos[0] if infos and len(infos) == 1 else None

    def unique_method_owner(self, method: str):
        owners = self.method_owners.get(method)
        return owners[0] if owners and len(owners) == 1 else None

    def function(self, ref: FuncRef) -> ast.FunctionDef | None:
        if ref.cls is not None:
            info = self.class_info(ref.cls, ref.path)
            return info.methods.get(ref.name) if info is not None else None
        return self.module_funcs.get((ref.path, ref.name))

    # -- lock identity -----------------------------------------------------

    def is_lock_attr(self, info: _ClassInfo, attr: str) -> bool:
        seen: set[str] = set()
        while attr in info.alias and attr not in seen:
            seen.add(attr)
            attr = info.alias[attr]
        return (
            attr in info.locks
            or attr in info.param_locks
            or attr in info.alias
        )

    def lock_id(self, info: _ClassInfo, attr: str) -> str:
        """Canonical program-wide lock identity for `self.<attr>` of a
        class: alias chains collapse, constructor-parameter locks
        resolve through their (unique) binding, ambiguity wildcards."""
        seen: set[str] = set()
        while attr in info.alias and attr not in seen:
            seen.add(attr)
            attr = info.alias[attr]
        if attr in info.param_locks:
            bindings = self.param_bindings.get(
                (info.node.name, attr), set()
            )
            concrete = {b for b in bindings if b != WILDCARD_LOCK}
            if len(concrete) == 1 and len(bindings) == 1:
                return next(iter(concrete))
            return WILDCARD_LOCK
        return f"{info.node.name}.{attr}"

    # -- call resolution ---------------------------------------------------

    def resolve_in_module(
        self,
        path: str,
        chain: str,
        cls: str | None = None,
        fn: ast.FunctionDef | None = None,
    ) -> FuncRef | None:
        """Resolve a dotted call name seen in `path` (inside class
        `cls`, inside function `fn` for local-variable types) to a
        program FuncRef, or None. Constructor calls resolve to the
        class's `__init__` (FuncRef.cls set, name "__init__")."""
        if not chain or chain.startswith("?"):
            return None
        head, _, rest = chain.partition(".")
        # self.m() / self.attr.m()
        if head == "self" and cls is not None:
            info = self.class_info(cls, path)
            if info is None or not rest:
                return None
            m, _, tail = rest.partition(".")
            if not tail:
                if m in info.methods:
                    return FuncRef(info.path, cls, m)
                return self._cha(m)
            t = info.attr_types.get(m)
            meth = tail.split(".")[-1]
            if t is not None:
                tinfo = self.class_info(t)
                if tinfo is not None and meth in tinfo.methods:
                    return FuncRef(tinfo.path, t, meth)
                return None
            return self._cha(meth)
        imp = self.imports.get(path, {})
        # bare name: local function, local class ctor, imported symbol
        if not rest:
            if (path, head) in self.module_funcs:
                return FuncRef(path, None, head)
            local_cls = self._ctor_ref(head, path)
            if local_cls is not None:
                return local_cls
            got = imp.get(head)
            if got is not None and got[0] == "sym":
                return self._resolve_symbol(got[1], got[2])
            if fn is not None:
                return None
            return None
        # alias.something
        got = imp.get(head)
        if got is not None:
            m = rest.split(".")[-1]
            if got[0] == "mod":
                if (got[1], rest) in self.module_funcs:
                    return FuncRef(got[1], None, rest)
                ref = self._ctor_ref(rest, got[1])
                if ref is not None:
                    return ref
                return None
            # symbol alias with a trailing attr: ClassName.method or
            # ClassName(...) classmethod-ish — try the class's methods
            sym = self._resolve_symbol(got[1], got[2])
            if sym is not None and sym.cls is not None:
                info = self.class_info(sym.cls, sym.path)
                if info is not None and m in info.methods:
                    return FuncRef(sym.path, sym.cls, m)
            return None
        # ClassName.method on a locally-defined class
        table = self.tables.get(path)
        if table is not None and head in table.classes and rest:
            m = rest.split(".")[-1]
            info = self.class_info(head, path)
            if info is not None and m in info.methods:
                return FuncRef(info.path, head, m)
        # obj.m() — unique-method CHA
        return self._cha(rest.split(".")[-1])

    def _ctor_ref(self, name: str, prefer_path: str) -> FuncRef | None:
        info = self.class_info(name, prefer_path)
        if info is None:
            return None
        if self.unique_class(name) is None and info.path != prefer_path:
            return None
        init = info.methods.get("__init__")
        return FuncRef(info.path, info.node.name, "__init__") if init else None

    def _resolve_symbol(self, path: str, name: str, _depth: int = 0):
        """A symbol imported from `path`: function, class ctor, or a
        one-hop re-export through that module's own imports."""
        if (path, name) in self.module_funcs:
            return FuncRef(path, None, name)
        table = self.tables.get(path)
        if table is not None and name in table.classes:
            return self._ctor_ref(name, path)
        if _depth >= 2:
            return None
        got = self.imports.get(path, {}).get(name)
        if got is not None and got[0] == "sym":
            return self._resolve_symbol(got[1], got[2], _depth + 1)
        return None

    def _cha(self, method: str) -> FuncRef | None:
        if method in CHA_STOPLIST:
            return None
        owner = self.unique_method_owner(method)
        if owner is None:
            return None
        return FuncRef(owner.path, owner.node.name, method)
