"""Pass 9 — buffer-donation audit (`donation`; docs/ANALYSIS.md).

XLA can write a jitted program's output into the buffer of a donated
input (`donate_argnums`) when shape/dtype/layout match — for this
repo's frame programs that is the difference between holding ONE frame
batch in device memory per in-flight dispatch and holding two. Before
this pass, `donate_argnums` appeared nowhere in the repo: every warp /
register / template-blend call double-allocated its frame batch.

Two rules, both emitting `donation` findings:

* **generic candidates** — a jitted function with no
  `donate_argnums`/`donate_argnames` whose RETURN provably shares an
  input parameter's shape (the proof walks elementwise chains:
  `jnp.where`/`clip`/`rint`/arithmetic, local helper calls; any
  `.astype` breaks the chain because donation also needs the dtype to
  match), called at a site where the argument DIES (a temporary
  expression, or a local name never read after the call). That input
  buffer is reusable and currently is not.
* **frame-program contract** — the plan-accounted hot programs
  ("register" via `_instrument_program`, "apply" via the
  `maybe_timed("apply", …)` builders) return a same-shape corrected
  frame batch by DOCUMENTED contract (the static proof cannot cross
  the Pallas / `functools.partial` kernel seam — the parity suites pin
  the contract instead). Their `jax.jit(...)` constructions must carry
  a `donate_argnums` keyword; a conditional value
  (`donate_argnums=(0,) if donate else ()`) satisfies the rule — the
  decision is then visible and owned by the call site.

A candidate is an invitation, not an order: donation is only safe when
the caller OWNS the buffer (nothing else reads it afterwards). The
`update_reference` template blend is the worked rejection example — the
old template buffer stays readable by in-flight dispatch entries and
the checkpoint template history, so its finding is baselined with that
justification rather than fixed (docs/PERFORMANCE.md "Retracing &
transfer anatomy").
"""

from __future__ import annotations

import ast

from kcmc_tpu.analysis.callgraph import ProgramGraph
from kcmc_tpu.analysis.core import Finding, Module, ModuleIndex, attr_chain
from kcmc_tpu.analysis.traceflow import find_jit_roots

DEFAULT_PREFIXES = (
    "kcmc_tpu/backends/jax_backend.py",
    "kcmc_tpu/plans/",
    "kcmc_tpu/parallel/",
    "kcmc_tpu/ops/",
)

# Shape-preserving elementwise vocabulary for the same-shape proof.
ELEMENTWISE = frozenset(
    {
        "where", "clip", "rint", "abs", "minimum", "maximum", "add",
        "subtract", "multiply", "divide", "exp", "log", "sqrt",
        "negative", "floor", "ceil", "round", "nan_to_num", "sign",
        "tanh", "square", "positive",
    }
)

DONATE_KWARGS = ("donate_argnums", "donate_argnames")

# Plan programs whose leading array argument is a frame batch that an
# output matches by documented contract (module docstring).
FRAME_PROGRAMS = frozenset({"register", "apply"})


def _has_donate_kwarg(call: ast.Call) -> bool:
    return any(kw.arg in DONATE_KWARGS for kw in call.keywords)


def _donated_decorator(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and _has_donate_kwarg(dec):
            return True
    return False


# -- generic same-shape proof ------------------------------------------------


class _ShapeTokens:
    """Which parameters' shapes a function's return provably shares."""

    def __init__(self, graph: ProgramGraph, path: str):
        self.graph = graph
        self.path = path

    def donatable_params(self, fn: ast.FunctionDef, depth: int = 0) -> set:
        params = [a.arg for a in fn.args.args if a.arg != "self"]
        env: dict[str, set] = {p: {p} for p in params}
        nested: set[int] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            ):
                nested.update(id(sub) for sub in ast.walk(n))
        for _ in range(2):
            for node in ast.walk(fn):
                if id(node) in nested or not isinstance(node, ast.Assign):
                    continue
                toks = self._tokens(node.value, env, depth)
                if not toks:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        env[t.id] = env.get(t.id, set()) | toks
        out: set = set()
        for node in ast.walk(fn):
            if id(node) in nested or not isinstance(node, ast.Return):
                continue
            if node.value is not None:
                out |= self._return_tokens(node.value, env, depth)
        return out & set(params)

    def _return_tokens(self, node, env, depth) -> set:
        if isinstance(node, ast.Dict):
            out: set = set()
            for v in node.values:
                out |= self._tokens(v, env, depth)
            return out
        if isinstance(node, (ast.Tuple, ast.List)):
            out = set()
            for e in node.elts:
                out |= self._tokens(e, env, depth)
            return out
        return self._tokens(node, env, depth)

    def _tokens(self, node, env, depth) -> set:
        if isinstance(node, ast.Name):
            return set(env.get(node.id, ()))
        if isinstance(node, ast.BinOp):
            return self._tokens(node.left, env, depth) | self._tokens(
                node.right, env, depth
            )
        if isinstance(node, ast.UnaryOp):
            return self._tokens(node.operand, env, depth)
        if isinstance(node, ast.IfExp):
            return self._tokens(node.body, env, depth) | self._tokens(
                node.orelse, env, depth
            )
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1]
            if tail == "astype":  # dtype may change: donation needs both
                return set()
            if tail in ELEMENTWISE:
                args = node.args[1:] if tail == "where" else node.args
                out: set = set()
                for a in args:
                    out |= self._tokens(a, env, depth)
                return out
            if depth < 3:
                ref = self.graph.resolve_in_module(self.path, chain)
                if ref is not None and ref.cls is None:
                    target = self.graph.function(ref)
                    if target is not None:
                        inner = _ShapeTokens(
                            self.graph, ref.path
                        ).donatable_params(target, depth + 1)
                        if inner:
                            # map callee param tokens back to our args
                            params = [
                                a.arg
                                for a in target.args.args
                                if a.arg != "self"
                            ]
                            out = set()
                            for i, a in enumerate(node.args):
                                if i < len(params) and params[i] in inner:
                                    out |= self._tokens(a, env, depth)
                            for kw in node.keywords:
                                if kw.arg in inner:
                                    out |= self._tokens(kw.value, env, depth)
                            return out
            return set()
        return set()


# Dtype-scalar constructors: donating a 0-d scalar saves nothing, so
# call-site arguments built through these never make a candidate.
SCALAR_CTORS = frozenset(
    {
        "float32", "float64", "bfloat16", "float16", "int8", "int16",
        "int32", "int64", "uint8", "uint16", "uint32", "uint64",
        "bool_", "float", "int", "bool",
    }
)


def _arg_liveness(call_arg: ast.AST, call: ast.Call, host_fn) -> str | None:
    """None = the argument may be read after the call (no finding);
    otherwise a short description of why the buffer dies here."""
    node = call_arg
    alias = ""
    if isinstance(node, ast.Constant):
        return None
    if (
        isinstance(node, ast.Call)
        and attr_chain(node.func).rsplit(".", 1)[-1] in SCALAR_CTORS
    ):
        return None
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        if chain.rsplit(".", 1)[-1] in ("asarray", "array") and node.args:
            base = node.args[0]
            if isinstance(base, ast.Name):
                node = base
                alias = " (through jnp.asarray)"
            else:
                return (
                    "a temporary that may alias a live container entry - "
                    "donation requires ownership of the buffer"
                )
        else:
            return "a temporary expression"
    if isinstance(node, ast.Subscript):
        return None  # container entry stays reachable
    if not isinstance(node, ast.Name):
        return None
    name = node.id
    # A call inside a loop makes every read in that loop "after" it on
    # the next iteration, regardless of line order — count the whole
    # loop body as live territory.
    loop_scopes: list[ast.AST] = []
    for n in ast.walk(host_fn):
        if isinstance(n, (ast.For, ast.While)) and any(
            sub is call for sub in ast.walk(n)
        ):
            loop_scopes.append(n)

    def _reads(scope, after_line: int) -> bool:
        for n in ast.walk(scope):
            if (
                isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id == name
                and n.lineno > after_line
            ):
                return True
        return False

    if _reads(host_fn, call.lineno):
        return None
    if any(_reads(scope, 0) for scope in loop_scopes):
        return None
    return f"local '{name}' is never read after the call{alias}"


# -- the pass ----------------------------------------------------------------


class DonationPass:
    name = "donation"

    def __init__(self, module_prefixes: tuple[str, ...] = DEFAULT_PREFIXES):
        self.module_prefixes = module_prefixes

    def run(self, index: ModuleIndex) -> list[Finding]:
        graph = ProgramGraph.for_index(index)
        out: list[Finding] = []

        def emit(path, line, message, detail, severity="warning"):
            out.append(
                Finding(
                    rule=self.name,
                    path=path,
                    line=line,
                    severity=severity,
                    message=message,
                    detail=detail,
                )
            )

        mods = [
            m
            for m in index
            if any(m.path.startswith(p) for p in self.module_prefixes)
        ]
        for mod in mods:
            self._generic(mod, graph, mods, emit)
            self._contract(mod, graph, emit)
        uniq: dict[tuple, Finding] = {}
        for f in out:
            uniq.setdefault((f.path, f.line, f.message), f)
        return list(uniq.values())

    # -- generic candidates -------------------------------------------

    def _generic(self, mod: Module, graph, mods, emit) -> None:
        for root in find_jit_roots(mod, graph):
            fn = root.fn
            if _donated_decorator(fn):
                continue
            # jax.jit(f, donate_argnums=...) call-site form
            donated = False
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and attr_chain(node.func).rsplit(".", 1)[-1] == "jit"
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == fn.name
                    and _has_donate_kwarg(node)
                ):
                    donated = True
            if donated:
                continue
            cands = _ShapeTokens(graph, mod.path).donatable_params(fn)
            if not cands:
                continue
            params = [a.arg for a in fn.args.args if a.arg != "self"]
            for m in mods:
                self._check_call_sites(
                    m, graph, fn, mod.path, params, cands, emit
                )

    def _check_call_sites(
        self, mod, graph, jit_fn, def_path, params, cands, emit
    ) -> None:
        table = graph.tables[mod.path]
        host_fns = [
            f
            for fns in table.functions.values()
            for f in fns
            if f is not jit_fn
        ]
        for host in host_fns:
            for node in ast.walk(host):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain.rsplit(".", 1)[-1] != jit_fn.name:
                    continue
                ref = graph.resolve_in_module(mod.path, chain)
                if ref is not None and (
                    ref.path != def_path or ref.name != jit_fn.name
                ):
                    continue
                bound: dict[str, ast.AST] = {}
                for i, a in enumerate(node.args):
                    if i < len(params):
                        bound[params[i]] = a
                for kw in node.keywords:
                    if kw.arg:
                        bound[kw.arg] = kw.value
                for p in sorted(cands):
                    arg = bound.get(p)
                    if arg is None:
                        continue
                    why = _arg_liveness(arg, node, host)
                    if why is None:
                        continue
                    emit(
                        def_path,
                        jit_fn.lineno,
                        f"donation candidate: jitted '{jit_fn.name}' "
                        f"double-allocates '{p}'",
                        f"an output shares '{p}' shape/dtype and the "
                        f"{mod.path}:{node.lineno} call site's argument "
                        f"dies ({why}) - donate_argnums would let XLA "
                        "reuse the buffer",
                    )

    # -- frame-program contract ---------------------------------------

    def _contract(self, mod: Module, graph, emit) -> None:
        table = graph.tables[mod.path]
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1]
            if tail == "_instrument_program" and node.args:
                pname = (
                    node.args[0].value
                    if isinstance(node.args[0], ast.Constant)
                    else None
                )
                if pname in FRAME_PROGRAMS and len(node.args) >= 3:
                    self._check_builder(
                        mod, graph, table, pname, node.args[2], node, emit
                    )
            if tail in ("maybe_timed", "timed") and node.args:
                pname = (
                    node.args[0].value
                    if isinstance(node.args[0], ast.Constant)
                    else None
                )
                if pname in FRAME_PROGRAMS:
                    self._check_timed_block(mod, graph, pname, node, emit)

    def _check_builder(
        self, mod, graph, table, pname, build_expr, site, emit
    ) -> None:
        """The build expression of an instrumented frame program: a
        direct jax.jit(...) call, or a self-method whose body holds
        one. Every jit construction found must donate."""
        targets: list[ast.AST] = []
        if isinstance(build_expr, ast.Call):
            chain = attr_chain(build_expr.func)
            if chain.rsplit(".", 1)[-1] == "jit":
                targets = [build_expr]
            elif chain.startswith("self."):
                meth = chain.split(".")[-1]
                for cls_methods in table.methods.values():
                    fn = cls_methods.get(meth)
                    if fn is not None:
                        targets.extend(
                            n
                            for n in ast.walk(fn)
                            if isinstance(n, ast.Call)
                            and attr_chain(n.func).rsplit(".", 1)[-1]
                            == "jit"
                        )
        for t in targets:
            if not _has_donate_kwarg(t):
                emit(
                    mod.path,
                    t.lineno,
                    f"frame program '{pname}' compiles without "
                    "donate_argnums",
                    "its corrected-frame output matches the input "
                    "batch by contract; the batch buffer is "
                    "double-allocated per in-flight dispatch "
                    "(docs/PERFORMANCE.md)",
                )

    def _check_timed_block(self, mod, graph, pname, timed_call, emit) -> None:
        """Calls inside a maybe_timed(<frame program>) accounting block
        resolve to their builders; jit constructions there must donate."""
        with_node = None
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.With) and any(
                item.context_expr is timed_call
                or (
                    isinstance(item.context_expr, ast.Call)
                    and item.context_expr is timed_call
                )
                for item in node.items
            ):
                with_node = node
        # maybe_timed may be assigned to a ctx variable instead of used
        # inline; fall back to scanning the enclosing function
        scope = with_node
        if scope is None:
            for fns in graph.tables[mod.path].functions.values():
                for fn in fns:
                    if any(sub is timed_call for sub in ast.walk(fn)):
                        scope = fn
        if scope is None:
            return
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call) or node is timed_call:
                continue
            ref = graph.resolve_in_module(mod.path, attr_chain(node.func))
            if ref is None or ref.cls is not None:
                continue
            target = graph.function(ref)
            if target is None:
                continue
            for jit_call in ast.walk(target):
                if (
                    isinstance(jit_call, ast.Call)
                    and attr_chain(jit_call.func).rsplit(".", 1)[-1]
                    == "jit"
                    and not _has_donate_kwarg(jit_call)
                ):
                    emit(
                        ref.path,
                        jit_call.lineno,
                        f"frame program '{pname}' compiles without "
                        f"donate_argnums (via {ref.name})",
                        "its resampled output matches the input batch "
                        "by contract; the batch buffer is "
                        "double-allocated (docs/PERFORMANCE.md)",
                    )
