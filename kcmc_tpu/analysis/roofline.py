"""Roofline attribution (PR 18): which resource binds each config.

The bench trajectory (BENCH_r01–r05) says how fast each judged config
runs; nothing in the repo could say how fast it COULD run, or which
resource — matrix units, on-chip memory bandwidth, HBM, the host, the
interconnect — a config is actually pinned against. This module is
that missing model: a table-driven peaks catalogue per platform class,
a first-order bytes/FLOPs cost model per pipeline stage assembled from
the same shape vocabulary the traceflow pass documents
(`analysis/traceflow.BYTES_HINTS`), and a judge that combines the
model with MEASURED wall time into a binding-resource verdict and its
fraction of peak.

Three consumers share the one table (the "one table, two consumers"
satellite, plus the checker):

* ``bench.py --roofline`` — a judged per-config line naming the
  binding resource and fraction of peak;
* ``bench.py --profile`` — achieved-bytes/s and achieved-FLOP/s
  columns per measured stage;
* the traceflow pass — warns when a plan-routed program literal has
  no entry in `PROGRAM_VOCAB`, so a new jitted program can never be
  silently under-counted by this model.

Honesty notes. The cost model is FIRST-ORDER: per-stage FLOP and byte
counts are derived from config+shape with constant per-pixel /
per-keypoint factors measured once against the XLA cost analysis of
the compiled programs — trust binding-resource CLASSIFICATION and
order-of-magnitude fractions, not third-digit precision. The peaks
table carries spec-sheet class numbers; operators with calibrated
hardware should edit their row (that is why it is a table). On CPU the
"device" is the host itself, so the verdict degenerates to the useful
CPU question: host-compute-bound vs memory-bound vs staging-bound.
"""

from __future__ import annotations

import math

# -- peaks catalogue (per platform CLASS, table-driven) ---------------------
#
# Units: FLOP/s and bytes/s. `compute` is the dense-matmul peak the
# match/consensus stages can reach (MXU on TPU); `vector` the
# elementwise/VPU-class peak the detect/warp stages are bounded by;
# `memory` main-memory bandwidth (HBM on TPU, DRAM on host); `vmem`
# on-chip SRAM bandwidth (None where the model shouldn't price it);
# `link` the host<->device staging path (memcpy-class on CPU, PCIe
# class on TPU hosts); `interconnect` per-chip ICI/DCN bandwidth for
# the multi-chip gathers (None = single-chip platform class).
PEAKS: dict[str, dict] = {
    "cpu": {
        "label": "host (XLA:CPU)",
        "compute": 2.0e11,
        "vector": 1.0e11,
        "memory": 2.5e10,
        "vmem": None,
        "link": 1.2e10,
        "interconnect": None,
    },
    "tpu-v5e": {
        "label": "TPU v5e (1 chip)",
        "compute": 3.94e14,  # bf16/int8 MXU class
        "vector": 2.0e12,
        "memory": 8.1e11,  # HBM
        "vmem": 1.0e13,
        "link": 1.6e10,  # PCIe-class host link
        "interconnect": 4.5e10,  # ICI per link direction
    },
    "tpu-v4": {
        "label": "TPU v4 (1 chip)",
        "compute": 2.75e14,
        "vector": 1.6e12,
        "memory": 1.2e12,
        "vmem": 1.0e13,
        "link": 1.6e10,
        "interconnect": 5.0e10,
    },
}

# Resource key -> operator-facing name in the judged report.
RESOURCE_NAMES = {
    "compute": "MXU/compute",
    "vector": "VPU/vector",
    "memory": "HBM/memory",
    "vmem": "VMEM bandwidth",
    "link": "host link",
    "interconnect": "interconnect",
}

# -- program vocabulary (the traceflow "roofline-vocab" rule) ---------------
#
# EVERY literal program name routed through the plan machinery
# (`PlanRuntime.timed` / `maybe_timed` / the backend's
# `_instrument_program`) must have an entry here describing how the
# roofline model accounts it — the traceflow pass warns on any
# plan-routed literal missing from this table, so a new jitted program
# cannot silently escape the cost model. Values name the BYTES_HINTS
# rows (analysis/traceflow.py) and cost-model stages that price it.
PROGRAM_VOCAB: dict[str, str] = {
    "register": "full batch pipeline: frames upload (BYTES_HINTS "
    "'frames'), detect/describe/match/consensus/warp stage costs, "
    "corrected/out download ('corrected', 'out', diagnostics rows)",
    "reference": "single-frame detect+describe (B=1 detect/describe "
    "stage costs; no batch transfers)",
    "reference_pyramid": "fused pyramid detect+describe over one frame "
    "(detect/describe costs summed over octaves at B=1)",
    "update_reference": "device rolling-template blend: one "
    "H*W-sized elementwise pass over the averaging window",
    "quality": "template correlation + coverage: ~3 elementwise "
    "passes over 'corrected'",
    "cast": "round/clip/cast of 'corrected' before D2H (prices as one "
    "memory pass, halves the 'corrected' link bytes)",
    "apply": "warp-only application pass: warp stage cost plus "
    "'frames'/'corrected' transfers",
}


def detect_platform() -> str:
    """Peaks-table key for the current runtime. CPU hosts map to
    "cpu"; accelerators map to their platform class with "tpu-v5e" as
    the conservative default for unrecognized TPU generations."""
    import jax

    backend = jax.default_backend()
    if backend not in ("tpu", "axon"):
        return "cpu"
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return "tpu-v5e"
    if "v4" in kind:
        return "tpu-v4"
    return "tpu-v5e"


def _pyramid_px_factor(n_octaves: int, octave_scale: float) -> float:
    """Sum of per-octave pixel-count ratios vs the base frame."""
    return sum(
        (1.0 / float(octave_scale)) ** (2 * i) for i in range(max(1, n_octaves))
    )


def stage_costs(
    model: str,
    shape: tuple[int, int],
    batch: int,
    *,
    max_keypoints: int = 512,
    n_octaves: int = 1,
    octave_scale: float = 2.0,
    oriented: bool | None = None,
    n_hypotheses: int = 128,
    refine_iters: int = 2,
    patch_grid: tuple[int, int] = (8, 8),
    patch_hypotheses: int = 32,
    in_itemsize: int = 4,
    out_itemsize: int = 4,
    emit_frames: bool = True,
) -> dict[str, dict[str, float]]:
    """First-order bytes/FLOPs per pipeline stage for ONE batch.

    Returns {stage: {"flops", "mem_bytes", "link_bytes"}} with the
    stage keys matching `utils.profiling.stage_breakdown`'s rows
    (detect / describe / match / consensus / full (+warp)) plus the
    transfer pseudo-stages ``upload`` and ``download`` that never
    appear in a device breakdown but dominate host-fed rooflines.

    The constant factors are calibrated against XLA's cost analysis of
    the compiled 512² programs (first-order: blocking, padding, and
    fusion change them by tens of percent, not orders of magnitude).
    """
    from kcmc_tpu.ops.patterns import (
        N_BITS,
        PATCH_RADIUS,
        ROT_RADIUS,
    )

    H, W = int(shape[0]), int(shape[1])
    B = int(batch)
    px = float(B * H * W)
    K = int(max_keypoints)
    if oriented is None:
        oriented = model not in ("translation",)
    r = ROT_RADIUS if oriented else PATCH_RADIUS
    P = 2 * r + 2
    pyr = _pyramid_px_factor(n_octaves, octave_scale)

    costs: dict[str, dict[str, float]] = {}
    costs["upload"] = {
        "flops": 0.0,
        "mem_bytes": px * in_itemsize,
        "link_bytes": px * in_itemsize,
    }
    # Harris + blur + NMS + subpixel: ~12 conv/reduce passes of ~9-25
    # taps over every pixel (per octave on the pyramid path).
    costs["detect"] = {
        "flops": px * pyr * 160.0,
        "mem_bytes": px * pyr * 4 * 24.0,
        "link_bytes": 0.0,
    }
    # Patch extraction (K patches of P² pixels, bf16 slabs) + N_BITS
    # pair comparisons + orientation moments per keypoint.
    costs["describe"] = {
        "flops": B * K * (P * P * 24.0 + N_BITS * 4.0) * (1.0 if n_octaves <= 1 else 1.2),
        "mem_bytes": px * pyr * 4 * 2.0 + B * K * P * P * 2 * 2.0,
        "link_bytes": 0.0,
    }
    # Hamming matrix on the MXU: K x K_ref x N_BITS bit-MACs (int8/bf16
    # packed), plus the 2-NN selection sweep.
    costs["match"] = {
        "flops": 2.0 * B * K * K * (N_BITS / 8.0) + B * K * K * 4.0,
        "mem_bytes": B * K * K * 2.0,
        "link_bytes": 0.0,
    }
    # Blocked hypothesis solves/scores over the match set; piecewise
    # prices its global+patch field hypotheses through the same term.
    hyp = float(n_hypotheses)
    if model == "piecewise":
        gh, gw = patch_grid
        hyp = float(n_hypotheses + gh * gw * patch_hypotheses)
    costs["consensus"] = {
        "flops": B * hyp * K * 30.0 * (1.0 + refine_iters),
        "mem_bytes": B * hyp * K * 8.0,
        "link_bytes": 0.0,
    }
    # Bilinear warp + polish-free output pass.
    costs["full (+warp)"] = {
        "flops": px * 14.0,
        "mem_bytes": px * 4 * 3.0,
        "link_bytes": 0.0,
    }
    dl = px * out_itemsize if emit_frames else 0.0
    costs["download"] = {
        "flops": 0.0,
        "mem_bytes": dl + B * 64.0,
        "link_bytes": dl + B * 64.0,
    }
    return costs


def total_costs(costs: dict[str, dict[str, float]]) -> dict[str, float]:
    """Sum per-stage costs into one {"flops","mem_bytes","link_bytes"}."""
    out = {"flops": 0.0, "mem_bytes": 0.0, "link_bytes": 0.0}
    for c in costs.values():
        for k in out:
            out[k] += c.get(k, 0.0)
    return out


def judge(
    costs: dict[str, dict[str, float]],
    measured_s: float,
    platform: str,
    *,
    n_devices: int = 1,
    gathered_bytes: float = 0.0,
) -> dict:
    """Combine the cost model with a MEASURED wall time into a
    binding-resource verdict.

    For each resource the model computes the time the batch's work
    would take at that resource's table peak; the resource with the
    largest time-at-peak is the BINDING resource (the roofline's
    ridge), and `fraction_of_peak` is that time divided by the
    measured time — 1.0 means running at the model's speed of light,
    small fractions mean overhead/latency the roofline cannot
    attribute (dispatch, stalls, under-utilization).

    `gathered_bytes` (multi-chip): per-batch bytes each chip receives
    through the reference gathers, priced against the interconnect
    peak. Compute/memory terms are divided by `n_devices` (perfectly
    sharded work — optimistic, which is what a roofline is).
    """
    peaks = PEAKS[platform]
    tot = total_costs(costs)
    times: dict[str, float] = {}
    # Matrix-class work (match+consensus) runs against the compute
    # peak; elementwise pixel work against the vector peak where the
    # table distinguishes them.
    mxu_flops = sum(
        costs.get(s, {}).get("flops", 0.0) for s in ("match", "consensus")
    )
    vec_flops = tot["flops"] - mxu_flops
    n = max(1, int(n_devices))
    if peaks.get("compute"):
        times["compute"] = mxu_flops / peaks["compute"] / n
    if peaks.get("vector"):
        times["vector"] = vec_flops / peaks["vector"] / n
    if peaks.get("memory"):
        times["memory"] = tot["mem_bytes"] / peaks["memory"] / n
    if peaks.get("link"):
        times["link"] = tot["link_bytes"] / peaks["link"]
    if peaks.get("interconnect") and gathered_bytes > 0:
        times["interconnect"] = gathered_bytes / peaks["interconnect"]
    binding = max(times, key=times.get)
    bound_s = times[binding]
    measured_s = max(float(measured_s), 1e-12)
    return {
        "platform": platform,
        "platform_label": peaks["label"],
        "binding": binding,
        "binding_label": RESOURCE_NAMES[binding],
        "fraction_of_peak": round(min(bound_s / measured_s, 1.0), 4),
        "time_at_peak_s": {k: round(v, 6) for k, v in sorted(times.items())},
        "measured_s": round(measured_s, 6),
    }


def achieved_rates(
    costs: dict[str, dict[str, float]], stage_seconds: dict[str, float]
) -> dict[str, dict[str, float]]:
    """Achieved FLOP/s and bytes/s per measured stage (the --profile
    columns): model work divided by measured incremental time. Stages
    without a cost row or with non-positive time are skipped."""
    out: dict[str, dict[str, float]] = {}
    for name, secs in stage_seconds.items():
        c = costs.get(name)
        if c is None or not secs or secs <= 0:
            continue
        out[name] = {
            "achieved_gflops": round(c["flops"] / secs / 1e9, 2),
            "achieved_gbs": round(c["mem_bytes"] / secs / 1e9, 2),
        }
    return out
