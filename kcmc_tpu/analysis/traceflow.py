"""Pass 8 — trace-contract analysis (`retrace` / `dtype-flow` /
`transfer` / `bucket-escape`; docs/ANALYSIS.md).

PR 8's `jit-purity` answers "does traced code do host work?" for what a
reader of ONE file can see. The latency spread ROADMAP item 4 chases
lives one level up, in the *contract between host orchestration and the
compiled programs*: a Python branch on a traced value retraces per
call, a silent float64/bf16 promotion doubles (or corrupts) a hot
buffer, a host transfer inside the dispatch window serializes the
pipeline, and a jit entry whose argument shape escapes the
`plan_buckets` ladder compiles per caller shape forever. None of those
are visible module-locally.

This pass propagates a symbolic (traced?, dtype, placement) lattice
from every `jax.jit` / `pjit` / `shard_map` entry in the configured
modules through CROSS-MODULE call edges (the PR-11 `ProgramGraph`),
with per-call-site argument masks — a callee's parameter is traced
only when a traced value actually flows into it, so `detect_keypoints
(frame, threshold=cfg.detect_threshold)` keeps `threshold` static and
its trace-time branches legal. Four rule families:

* **retrace** — Python `if`/`while` on a value derived from traced
  array CONTENTS (`is None` identity tests and `.shape`/`.dtype`/
  `.ndim` reads are trace-time static and exempt), `range()` over a
  traced value, closures that bake per-call host values (`time.*`,
  unseeded `random.*`, `os.environ`) into the trace as constants, and
  static-argnum candidates (parameters used only at trace time).
* **dtype-flow** — explicit float64/complex128 inside traced code
  (silently float32 without x64, silently 2x bytes with it), bf16
  accumulation without an explicit accumulator dtype
  (`preferred_element_type=` / `precision=`), and host-side widening
  casts on the upload path (`jnp.asarray(frames, jnp.float32)` in the
  dispatch window doubles the host->device bytes of an integer stack —
  upload native, cast on device).
* **transfer** — device->host crossings inside the DISPATCH WINDOW
  (`np.asarray` / `np.array` / `jax.device_get` / `.item()` /
  `jax.tree.map(np.asarray, …)` in the per-batch methods), each with a
  bytes-per-frame estimate from the symbolic shape vocabulary.
  `copy_to_host_async` is the declared overlap path and never flagged;
  setup-scope methods (`prepare_reference`, `__init__`, warm-up) may
  transfer freely — that cost is amortized.
* **bucket-escape** — a jitted callable dispatched from the window
  whose argument shape is the CALLER's shape, in a function that never
  consults the bucket ladder (`plan.route` / `route_shape`) nor
  accounts the dispatch (`maybe_timed` / `timed` / `note_route`):
  every new caller shape is a fresh silent XLA compile. Cross-checked
  against `plans/buckets.py` routing so the accounted fallback path in
  `process_batch_async` stays quiet. The runtime retrace sentinel
  (analysis/sanitize.py + plans/runtime.py) is this rule's dynamic
  half: the static ladder predicts the compile-key set, the sentinel
  convicts any post-warm-up compile the prediction does not cover.

* **roofline-vocab** (PR 18) — a program literal routed through the
  plan machinery (`plan.maybe_timed("name", …)` / `plan.timed` /
  `_instrument_program("name", …)`) that has no entry in
  `analysis/roofline.PROGRAM_VOCAB`: the roofline cost model prices
  programs by name, so an unvocabularied program silently escapes
  `bench.py --roofline`'s attribution (and the --profile achieved-rate
  columns) — under-counting, not mis-counting, which is exactly the
  failure a checker must catch.

Resolution failures stay silent (an unresolvable call contributes no
edges and no findings) — the pass must be demonstrable on known-bad
fixtures and quiet on code it cannot see into.
"""

from __future__ import annotations

import ast
import dataclasses

from kcmc_tpu.analysis.callgraph import ProgramGraph
from kcmc_tpu.analysis.core import (
    Finding,
    Module,
    ModuleIndex,
    attr_chain,
)

# Modules whose jit entries seed the traced-closure walk, and whose
# dispatch-window methods the transfer/bucket rules scan.
DEFAULT_PREFIXES = (
    "kcmc_tpu/backends/jax_backend.py",
    "kcmc_tpu/plans/",
    "kcmc_tpu/parallel/",
    "kcmc_tpu/ops/",
)

# Per-batch methods: everything reachable here runs once per dispatched
# batch, so a host transfer or fresh compile is paid inside the
# latency/throughput window (vs prepare_reference/__init__/warmup,
# whose cost is amortized setup).
WINDOW_METHODS = frozenset(
    {
        "process_batch",
        "process_batch_async",
        "update_reference",
        "rescue_warp",
    }
)

JIT_ENTRY_NAMES = frozenset({"jit", "pjit", "shard_map"})

# Attribute reads on a traced value that are trace-time STATIC (shape
# metadata, not array contents).
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})

# Calls that erase tracedness: their result is a Python-level constant
# of the trace even when an argument is traced.
STATIC_FNS = frozenset({"len", "isinstance", "type", "hasattr", "getattr"})

# Closure-captured call chains that bake a PER-CALL host value into the
# trace as a constant (jax.random.key(seed) is seeded and deliberate).
CAPTURE_HAZARDS = (
    "time.",
    "datetime.",
    "random.",
    "np.random.",
    "numpy.random.",
    "os.environ",
    "os.urandom",
    "uuid.",
)

REDUCTIONS = frozenset(
    {"sum", "mean", "matmul", "dot", "einsum", "cumsum", "prod", "tensordot"}
)
ACC_KWARGS = frozenset({"preferred_element_type", "precision"})

# Bytes-per-frame vocabulary for the transfer estimates: symbolic
# shapes of the values this repo moves across the link, keyed by the
# names its dispatch-window code actually uses (jax_backend.py /
# plans/). Best-effort — an unknown name still gets a finding, just
# without the estimate.
BYTES_HINTS = {
    "frames": "H*W*itemsize(native dtype) per frame - the full batch",
    "corrected": "H*W*4 (float32) per frame - the dominant transfer",
    "out": "H*W*4 (float32) per frame plus per-frame diagnostics",
    "transform": "~36-64 B per frame",
    "transforms": "~36-64 B per frame",
    "field": "gh*gw*8 B per frame",
    "n_inliers": "4 B per frame",
    # PR-13 fused register program additions: the warm-start seed pair
    # rides host->device REPLICATED per dispatch (not per frame), and
    # the fused tail's match-count diagnostic is per frame like
    # n_inliers.
    "seed": "(d+1)^2*4 + 1 B per DISPATCH (replicated seed pair)",
    "seed_M": "(d+1)^2*4 B per DISPATCH (replicated seed matrix)",
    "seed_ok": "1 B per DISPATCH (replicated seed flag)",
    "n_matches": "4 B per frame",
    "rms_residual": "4 B per frame",
}


def _is_jit_entry(chain: str) -> bool:
    return chain.rsplit(".", 1)[-1] in JIT_ENTRY_NAMES


def _jit_static_names(
    dec_or_call: ast.AST, fn: ast.FunctionDef | None = None
) -> set[str]:
    """Statically-declared parameters of a jit decorator/call:
    static_argnames string literals, plus static_argnums integer
    literals resolved to parameter names through `fn`."""
    out: set[str] = set()
    node = dec_or_call
    if not isinstance(node, ast.Call):
        return out
    params = (
        [a.arg for a in fn.args.args if a.arg != "self"]
        if fn is not None
        else []
    )
    for kw in node.keywords:
        if kw.arg == "static_argnames":
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out.add(elt.value)
        elif kw.arg == "static_argnums":
            for elt in ast.walk(kw.value):
                if (
                    isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                    and not isinstance(elt.value, bool)
                    and 0 <= elt.value < len(params)
                ):
                    out.add(params[elt.value])
    return out


@dataclasses.dataclass(frozen=True)
class _JitRoot:
    module: Module
    fn: ast.FunctionDef
    how: str
    line: int
    static_names: frozenset
    cls: str | None


def find_jit_roots(mod: Module, graph: ProgramGraph) -> list[_JitRoot]:
    """Every traced entry of a module: @jax.jit / @partial(jax.jit, …)
    decorated defs, and jit(fn) / shard_map(fn, …) call sites whose
    function argument resolves locally."""
    table = graph.tables[mod.path]
    roots: list[_JitRoot] = []
    seen: set[int] = set()

    def cls_of(fn):
        for cname, cnode in table.classes.items():
            for sub in ast.walk(cnode):
                if sub is fn:
                    return cname
        return None

    def add(fn, how, line, statics):
        if fn is not None and id(fn) not in seen:
            seen.add(id(fn))
            roots.append(
                _JitRoot(
                    module=mod,
                    fn=fn,
                    how=how,
                    line=line,
                    static_names=frozenset(statics),
                    cls=cls_of(fn),
                )
            )

    for fns in table.functions.values():
        for fn in fns:
            for dec in fn.decorator_list:
                chain = attr_chain(
                    dec.func if isinstance(dec, ast.Call) else dec
                )
                inner = ""
                if (
                    isinstance(dec, ast.Call)
                    and chain.endswith("partial")
                    and dec.args
                ):
                    inner = attr_chain(dec.args[0])
                if _is_jit_entry(chain) or (inner and _is_jit_entry(inner)):
                    add(
                        fn, f"@{chain}", dec.lineno,
                        _jit_static_names(dec, fn),
                    )
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not _is_jit_entry(chain):
            continue
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Name):
            cands = table.functions.get(arg.id)
            target = cands[0] if cands else None
            add(
                target,
                chain,
                node.lineno,
                _jit_static_names(node, target),
            )
    return roots


# -- traced-closure interpreter ---------------------------------------------


class _ClosureScanner:
    """Walks one jit root's cross-module traced closure, propagating a
    per-function set of TRACED names (values derived from traced array
    contents) and a set of BF16 names, emitting retrace/dtype findings.

    Context-sensitive on the traced-parameter mask: each (function,
    mask) pair is scanned once; unresolvable calls bind nothing."""

    MAX_CONTEXTS = 4000

    def __init__(self, graph: ProgramGraph, emit):
        self.graph = graph
        self.emit = emit
        self._seen: set = set()

    def scan_root(self, root: _JitRoot) -> None:
        params = [a.arg for a in root.fn.args.args if a.arg != "self"]
        traced = frozenset(p for p in params if p not in root.static_names)
        self._scan(
            root.module.path, root.cls, root.fn, traced, root.fn.name, root.how
        )

    # -- tracedness of an expression ----------------------------------

    def _traced(self, node: ast.AST, env: set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self._traced(node.value, env)
        if isinstance(node, ast.Subscript):
            return self._traced(node.value, env)
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1]
            if tail in STATIC_FNS:
                return False
            # method on a traced receiver, or any traced argument
            if isinstance(node.func, ast.Attribute) and self._traced(
                node.func.value, env
            ):
                return True
            return any(
                self._traced(a, env)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]
            )
        if isinstance(node, ast.BinOp):
            return self._traced(node.left, env) or self._traced(
                node.right, env
            )
        if isinstance(node, ast.UnaryOp):
            return self._traced(node.operand, env)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` are identity tests on the
            # PYTHON value — static at trace time.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self._traced(node.left, env) or any(
                self._traced(c, env) for c in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(self._traced(v, env) for v in node.values)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._traced(e, env) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._traced(node.body, env) or self._traced(
                node.orelse, env
            )
        if isinstance(node, ast.Starred):
            return self._traced(node.value, env)
        return False

    @staticmethod
    def _is_bf16_cast(node: ast.AST) -> bool:
        """`x.astype(jnp.bfloat16)` / dtype=bfloat16 construction —
        outermost cast only (a bf16->f32 round trip is float32)."""
        if not isinstance(node, ast.Call):
            return False
        chain = attr_chain(node.func)
        if chain.endswith(".astype") and node.args:
            return attr_chain(node.args[0]).endswith("bfloat16")
        for kw in node.keywords:
            if kw.arg == "dtype" and attr_chain(kw.value).endswith(
                "bfloat16"
            ):
                return True
        if node.args and any(
            attr_chain(a).endswith("bfloat16") for a in node.args[1:]
        ):
            return chain.rsplit(".", 1)[-1] in ("asarray", "array", "full")
        return False

    def _bf16(self, node: ast.AST, bf: set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in bf
        if self._is_bf16_cast(node):
            return True
        if isinstance(node, ast.Attribute):
            return self._bf16(node.value, bf)
        if isinstance(node, ast.Subscript):
            return self._bf16(node.value, bf)
        if isinstance(node, ast.BinOp):
            return self._bf16(node.left, bf) or self._bf16(node.right, bf)
        return False

    # -- one function body --------------------------------------------

    def _scan(
        self,
        path: str,
        cls: str | None,
        fn: ast.FunctionDef,
        traced_params: frozenset,
        root_name: str,
        how: str,
    ) -> None:
        key = (id(fn), traced_params)
        if key in self._seen or len(self._seen) > self.MAX_CONTEXTS:
            return
        self._seen.add(key)
        env: set[str] = set(traced_params)
        bf16: set[str] = set()
        mod = self.graph.index.get(path)
        if mod is None:
            return

        nested_ids: set[int] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            ):
                nested_ids.update(id(sub) for sub in ast.walk(n))

        # two passes: the second sees names bound later in the body
        # (good enough for the loop-carried straggler without a fixpoint)
        for _ in range(2):
            for node in ast.walk(fn):
                if id(node) in nested_ids:
                    continue
                if isinstance(node, ast.Assign) and self._traced(
                    node.value, env
                ):
                    for t in node.targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                env.add(leaf.id)
                if isinstance(node, ast.Assign) and self._bf16(
                    node.value, bf16
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            bf16.add(t.id)

        for node in ast.walk(fn):
            if id(node) in nested_ids:
                continue
            if isinstance(node, (ast.If, ast.While)) and self._traced(
                node.test, env
            ):
                self.emit(
                    "retrace",
                    path,
                    node.lineno,
                    "error",
                    f"trace-time branch on a traced value inside "
                    f"jit-traced '{root_name}' (via {fn.name})",
                    "Python control flow on array contents re-traces "
                    "per call (or fails outright under jit) - use "
                    f"jnp.where / lax.cond; traced through {how}",
                )
            if isinstance(node, ast.IfExp) and self._traced(node.test, env):
                self.emit(
                    "retrace",
                    path,
                    node.lineno,
                    "error",
                    f"trace-time conditional expression on a traced "
                    f"value inside jit-traced '{root_name}' (via "
                    f"{fn.name})",
                    f"use jnp.where; traced through {how}",
                )
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1]
            if tail == "range" and any(
                self._traced(a, env) for a in node.args
            ):
                self.emit(
                    "retrace",
                    path,
                    node.lineno,
                    "error",
                    f"range() over a traced value inside jit-traced "
                    f"'{root_name}' (via {fn.name})",
                    "the loop bound bakes into the trace as a "
                    f"constant and re-traces per value; traced through {how}",
                )
            # Wide-dtype requests on DEVICE values only: jnp./jax.
            # constructors and .astype on traced receivers. Host numpy
            # float64 on static values (e.g. the polish window built in
            # f64 numpy and cast) is a legitimate trace-time constant.
            wide = None
            wide_call = chain.split(".", 1)[0] in ("jnp", "jax") or (
                tail == "astype"
                and isinstance(node.func, ast.Attribute)
                and self._traced(node.func.value, env)
            )
            if wide_call:
                for a in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    c = attr_chain(a)
                    s = (
                        a.value
                        if isinstance(a, ast.Constant)
                        and isinstance(a.value, str)
                        else ""
                    )
                    for w in ("float64", "complex128"):
                        if c.endswith(w) or s == w:
                            wide = w
            if ("float64" in chain or "complex128" in chain) and chain.split(
                ".", 1
            )[0] in ("jnp", "jax"):
                # jnp/jax-prefixed only: np.float64(...) on a static
                # value is the exempt host-constant pattern above
                wide = "float64" if "float64" in chain else "complex128"
            if wide is not None:
                self.emit(
                    "dtype-flow",
                    path,
                    node.lineno,
                    "error",
                    f"explicit {wide} inside jit-traced "
                    f"'{root_name}' (via {fn.name})",
                    "silently float32 without jax_enable_x64, silently "
                    f"2x bytes with it; traced through {how}",
                )
            if (
                tail in REDUCTIONS
                and node.args
                and self._bf16(node.args[0], bf16)
                and not any(kw.arg in ACC_KWARGS for kw in node.keywords)
            ):
                self.emit(
                    "dtype-flow",
                    path,
                    node.lineno,
                    "warning",
                    f"bf16 accumulation without an explicit accumulator "
                    f"dtype in '{fn.name}'",
                    f"jnp.{tail} over bfloat16 accumulates in bf16 on "
                    "TPU by default - pass preferred_element_type= (or "
                    "precision=) or document exactness; traced through "
                    + how,
                )
            # follow the call edge with the actual traced-arg mask
            self._follow(node, path, cls, fn, env, root_name, how)

        self._scan_captures(path, fn, mod, root_name, how, nested_ids)

    def _follow(self, call, path, cls, fn, env, root_name, how):
        chain = attr_chain(call.func)
        args = list(call.args)
        # jax.vmap(f)(…) / lax.map-style: resolve through the inner name
        if (
            isinstance(call.func, ast.Call)
            and attr_chain(call.func.func).rsplit(".", 1)[-1]
            in ("vmap", "checkpoint", "remat")
            and call.func.args
            and isinstance(call.func.args[0], ast.Name)
        ):
            chain = call.func.args[0].id
        if not chain or chain.startswith("?"):
            return
        ref = self.graph.resolve_in_module(path, chain, cls=cls, fn=fn)
        if ref is None or ref.name == "__init__":
            return
        target = self.graph.function(ref)
        if target is None:
            return
        params = [a.arg for a in target.args.args if a.arg != "self"]
        mask: set[str] = set()
        for i, a in enumerate(args):
            if i < len(params) and self._traced(a, env):
                mask.add(params[i])
        for kw in call.keywords:
            if kw.arg in params and self._traced(kw.value, env):
                mask.add(kw.arg)
        self._scan(ref.path, ref.cls, target, frozenset(mask), root_name, how)

    def _scan_captures(self, path, fn, mod, root_name, how, nested_ids):
        """Free names of the traced root that the ENCLOSING builder
        assigns from per-call host sources (time/random/environ):
        those values bake into the trace as constants of THIS call."""
        builder = None
        for cand in ast.walk(mod.tree):
            if isinstance(cand, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(sub is fn for sub in ast.walk(cand)) and cand is not fn:
                    builder = cand  # innermost wins (walk order is outer-first)
        if builder is None:
            return
        local = {a.arg for a in fn.args.args}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                for t in ast.walk(n):
                    if isinstance(t, ast.Name) and isinstance(
                        t.ctx, ast.Store
                    ):
                        local.add(t.id)
        free = {
            n.id
            for n in ast.walk(fn)
            if isinstance(n, ast.Name)
            and isinstance(n.ctx, ast.Load)
            and n.id not in local
        }
        for node in builder.body if hasattr(builder, "body") else ():
            for stmt in ast.walk(node):
                if not isinstance(stmt, ast.Assign):
                    continue
                names = {
                    t.id
                    for t in stmt.targets
                    if isinstance(t, ast.Name) and t.id in free
                }
                if not names:
                    continue
                for sub in ast.walk(stmt.value):
                    if not isinstance(sub, (ast.Call, ast.Attribute)):
                        continue
                    c = attr_chain(
                        sub.func if isinstance(sub, ast.Call) else sub
                    )
                    if c.startswith("jax.random."):
                        continue  # seeded by construction
                    if any(c.startswith(h) for h in CAPTURE_HAZARDS):
                        self.emit(
                            "retrace",
                            path,
                            stmt.lineno,
                            "error",
                            f"closure over a per-call host value "
                            f"'{sorted(names)[0]}' baked into jit-traced "
                            f"'{root_name}'",
                            f"assigned from {c} in {builder.name}; every "
                            "call traces a different constant - thread "
                            "it through as an argument instead",
                        )
                        break


# -- static-argnum candidates ------------------------------------------------


def _static_argnum_candidates(root: _JitRoot, emit) -> None:
    """Parameters of a jitted function used ONLY at trace time
    (range()/if-tests/shape positions) and not declared static."""
    fn = root.fn
    params = [a.arg for a in fn.args.args if a.arg != "self"]
    uses: dict[str, set[str]] = {p: set() for p in params}

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call):
            tail = attr_chain(node.func).rsplit(".", 1)[-1]
            for a in ast.walk(node):
                if isinstance(a, ast.Name) and a.id in uses:
                    uses[a.id].add(
                        "static" if tail == "range" else "value"
                    )
            # don't double-count below
            return

        def visit_If(self, node: ast.If):
            for a in ast.walk(node.test):
                if isinstance(a, ast.Name) and a.id in uses:
                    uses[a.id].add("static")
            for stmt in node.body + node.orelse:
                self.visit(stmt)

        def generic_visit(self, node):
            if isinstance(node, ast.Name) and node.id in uses:
                uses[node.id].add("value")
            super().generic_visit(node)

    for stmt in fn.body:
        V().visit(stmt)
    for p in params:
        if p in root.static_names:
            continue
        if uses[p] and uses[p] == {"static"}:
            emit(
                "retrace",
                root.module.path,
                fn.lineno,
                "warning",
                f"parameter '{p}' of jit-traced '{fn.name}' is used "
                "only at trace time - static-argnum candidate",
                "declaring it static_argnames avoids tracing a value "
                "the program never reads at runtime",
            )


# -- dispatch-window analysis (transfer / bucket-escape / upload cast) -------


def _with_contexts(fn: ast.FunctionDef) -> dict[int, bool]:
    """node id -> True when lexically inside a `with *.maybe_timed(…)`
    or `with *.timed(…)` block (plan compile accounting)."""
    out: dict[int, bool] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        accounted = any(
            isinstance(item.context_expr, ast.Call)
            and attr_chain(item.context_expr.func).rsplit(".", 1)[-1]
            in ("maybe_timed", "timed")
            for item in node.items
        )
        if accounted:
            for sub in ast.walk(node):
                out[id(sub)] = True
    return out


def _bytes_hint(node: ast.AST) -> str:
    names = [
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    ] + [
        n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)
    ] + [
        c.value
        for c in ast.walk(node)
        if isinstance(c, ast.Constant) and isinstance(c.value, str)
    ]
    for n in names:
        if n in BYTES_HINTS:
            return f"~{BYTES_HINTS[n]}"
    return "bytes-per-frame unknown (name outside the shape vocabulary)"


class _WindowScanner:
    """Transfer + bucket-escape + upload-widening rules over the
    dispatch-window methods of backend classes in the scoped modules."""

    def __init__(self, graph: ProgramGraph, emit):
        self.graph = graph
        self.emit = emit

    def scan_module(self, mod: Module) -> None:
        table = self.graph.tables[mod.path]
        # module-level jit-decorated helpers (dispatchable per shape)
        jit_helpers: set[str] = set()
        for fname, fns in table.functions.items():
            for fn in fns:
                for dec in fn.decorator_list:
                    chain = attr_chain(
                        dec.func if isinstance(dec, ast.Call) else dec
                    )
                    inner = (
                        attr_chain(dec.args[0])
                        if isinstance(dec, ast.Call)
                        and chain.endswith("partial")
                        and dec.args
                        else ""
                    )
                    if _is_jit_entry(chain) or (
                        inner and _is_jit_entry(inner)
                    ):
                        jit_helpers.add(fname)
        for cname, cnode in table.classes.items():
            for mname, mfn in table.methods.get(cname, {}).items():
                if mname in WINDOW_METHODS:
                    self._scan_window_fn(mod, cname, mfn, jit_helpers)

    def _scan_window_fn(self, mod, cls, fn, jit_helpers):
        accounted = _with_contexts(fn)
        src = mod.source
        fn_src = ast.get_source_segment(src, fn) or ""
        routes = (
            ".route(" in fn_src
            or "route_shape(" in fn_src
            or ".routable(" in fn_src
        )
        accounts_fallback = "note_route(" in fn_src

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1]

            # -- transfer: device -> host inside the window -----------
            d2h = None
            if chain in ("np.asarray", "np.array", "numpy.asarray",
                         "numpy.array"):
                d2h = chain
            elif chain == "jax.device_get" or tail == "device_get":
                d2h = "jax.device_get"
            elif tail in ("item", "tolist", "block_until_ready"):
                d2h = f"*.{tail}()"
            elif tail == "map" and chain.endswith("tree.map") and node.args:
                inner = attr_chain(node.args[0])
                if inner.endswith("asarray") or inner.endswith("array"):
                    d2h = "jax.tree.map(np.asarray, ...)"
            if d2h is not None:
                target = (
                    node.args[-1] if node.args else node.func
                )
                self.emit(
                    "transfer",
                    mod.path,
                    node.lineno,
                    "warning",
                    f"device->host transfer inside the dispatch window "
                    f"in '{fn.name}' ({d2h})",
                    f"{_bytes_hint(target)}; a synchronous copy here "
                    "serializes the dispatch window - prefer "
                    "copy_to_host_async or move the copy out of the "
                    "per-batch path",
                )

            # -- dtype-flow: host-side widening cast on the upload ----
            if (
                tail in ("asarray", "array")
                and chain.split(".", 1)[0] in ("jnp", "jax")
                and len(node.args) >= 2
                and attr_chain(node.args[1]).endswith(
                    ("float32", "float64")
                )
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in ("frames", "stack", "batch")
            ):
                self.emit(
                    "dtype-flow",
                    mod.path,
                    node.lineno,
                    "warning",
                    f"host-side widening cast before upload in "
                    f"'{fn.name}'",
                    "jnp.asarray(frames, float32) widens an integer "
                    "stack on the host side of the link - upload the "
                    "native dtype and .astype on device (halves "
                    "host->device bytes for uint16)",
                )

            # -- bucket-escape: unaccounted jit dispatch --------------
            is_dispatch = False
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in jit_helpers
            ):
                is_dispatch = True
            if is_dispatch and not accounted.get(id(node)):
                if not (routes and accounts_fallback):
                    self.emit(
                        "bucket-escape",
                        mod.path,
                        node.lineno,
                        "error",
                        f"jitted '{node.func.id}' dispatched from the "
                        f"window in '{fn.name}' outside the bucket "
                        "ladder and plan accounting",
                        "every new caller shape is a silent fresh XLA "
                        "compile - route through plan.route / wrap in "
                        "maybe_timed so the retrace sentinel and plan "
                        "stats see it",
                    )


# -- roofline program vocabulary (PR 18) -------------------------------------

# Call tails that stamp a PROGRAM NAME into the plan machinery; their
# first argument, when a string literal, must appear in
# analysis/roofline.PROGRAM_VOCAB so the roofline cost model can price
# the program.
_PROGRAM_SITES = frozenset({"maybe_timed", "timed", "_instrument_program"})


def _scan_roofline_vocab(mod: Module, emit) -> None:
    """Emit `roofline-vocab` warnings for plan-routed program literals
    missing from the roofline model's vocabulary."""
    from kcmc_tpu.analysis.roofline import PROGRAM_VOCAB

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = attr_chain(node.func).rsplit(".", 1)[-1]
        if tail not in _PROGRAM_SITES or not node.args:
            continue
        first = node.args[0]
        if not (
            isinstance(first, ast.Constant) and isinstance(first.value, str)
        ):
            continue  # name threaded through a variable: not this site
        name = first.value
        if name not in PROGRAM_VOCAB:
            emit(
                "roofline-vocab",
                mod.path,
                node.lineno,
                "warning",
                f"plan-routed program '{name}' has no "
                "analysis/roofline.PROGRAM_VOCAB entry",
                "the roofline cost model prices programs by name - an "
                "unvocabularied program silently escapes `bench.py "
                "--roofline` attribution; add a PROGRAM_VOCAB entry "
                "describing which BYTES_HINTS rows / cost-model stages "
                "account it",
            )


# -- the pass ----------------------------------------------------------------


class TraceFlowPass:
    """Rule families `retrace` / `dtype-flow` / `transfer` /
    `bucket-escape` / `roofline-vocab` (module docstring)."""

    name = "traceflow"

    def __init__(self, module_prefixes: tuple[str, ...] = DEFAULT_PREFIXES):
        self.module_prefixes = module_prefixes

    def run(self, index: ModuleIndex) -> list[Finding]:
        graph = ProgramGraph.for_index(index)
        out: list[Finding] = []

        def emit(rule, path, line, severity, message, detail=""):
            out.append(
                Finding(
                    rule=rule,
                    path=path,
                    line=line,
                    severity=severity,
                    message=message,
                    detail=detail,
                )
            )

        scanner = _ClosureScanner(graph, emit)
        windows = _WindowScanner(graph, emit)
        for mod in index:
            if not any(mod.path.startswith(p) for p in self.module_prefixes):
                continue
            for root in find_jit_roots(mod, graph):
                scanner.scan_root(root)
                _static_argnum_candidates(root, emit)
            windows.scan_module(mod)
            _scan_roofline_vocab(mod, emit)
        uniq: dict[tuple, Finding] = {}
        for f in out:
            uniq.setdefault((f.rule, f.path, f.line, f.message), f)
        return list(uniq.values())
