"""Pass 3 — lock/thread discipline (`lock-order`, `daemon-xla`).

The streaming pipeline, the async writer, the serving scheduler, and
the plan runtime together hold ~34 `threading` sites whose contracts
live in comments. Two of them are machine-checkable here:

* **lock-order** — per class, build the lock-acquisition graph: an
  edge a→b when a `with self._b:` executes (directly, or via a
  `self.m()` call) inside a `with self._a:` body. A cycle is a
  deadlock waiting for the right interleaving. `threading.Condition(
  self._lock)` aliases to the underlying lock (waiting on `_wake`
  IS holding `_lock`), and self-edges are ignored (RLock reentrancy
  is this repo's documented pattern).

* **daemon-xla** — the PR-7 rule, learned the hard way: a daemon
  thread killed mid-XLA-compile aborts interpreter teardown, so
  threads whose targets reach jax compile/export/dispatch must be
  non-daemon (and joined on stop). The `serve/scheduler.py`
  degraded-budget warm-up threads were the motivating catch.

The AST-local `shared-write` warning this pass used to carry was
SUPERSEDED by the whole-program `race` pass (`concurrency.py`), which
does the same reasoning cross-module with real lock sets and
happens-before propagation; the per-class acquisition machinery here
(`_ClassModel`) stays because the order/daemon rules and the runtime
sanitizer's static-graph merge build on it.
"""

from __future__ import annotations

import ast

from kcmc_tpu.analysis.core import (
    Finding,
    FunctionTable,
    Module,
    ModuleIndex,
    attr_chain,
)

LOCK_CTORS = ("threading.Lock", "threading.RLock")
CONDITION_CTOR = "threading.Condition"
THREAD_CTOR = "threading.Thread"

# Call names (bare or trailing attribute) that indicate the callee
# performs jax compile/export/dispatch work. Deliberately generous:
# reaching ANY of these from a daemon thread is worth a look.
XLA_REACHING_NAMES = frozenset(
    {
        "get_backend",
        "JaxBackend",
        "export_and_prime",
        "load_exported",
        "process_batch",
        "prepare_reference",
        "update_reference",
        "apply_transforms",
        "warmup",
        "block_until_ready",
        "device_put",
        "jit",
    }
)


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a `self._x` attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ClassModel:
    """Locks, lock aliases, with-nesting, writes, and threads of one
    class."""

    def __init__(self, mod: Module, cls: ast.ClassDef, table: FunctionTable):
        self.mod = mod
        self.cls = cls
        self.methods = table.methods.get(cls.name, {})
        self.locks: dict[str, int] = {}  # attr -> def line
        self.alias: dict[str, str] = {}  # condition attr -> lock attr
        self._find_locks()
        # method -> ordered list of (lock attr, line, body node)
        self.acquires: dict[str, list[tuple[str, ast.With, str]]] = {
            m: self._withs(fn) for m, fn in self.methods.items()
        }
        self.lock_closure: dict[str, set[str]] = {}
        for m in self.methods:
            self.lock_closure[m] = self._closure(m, set())

    def _find_locks(self) -> None:
        for fn in self.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                chain = (
                    attr_chain(node.value.func)
                    if isinstance(node.value, ast.Call)
                    else ""
                )
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if chain in LOCK_CTORS:
                        self.locks[attr] = node.lineno
                    elif chain == CONDITION_CTOR:
                        call = node.value
                        inner = (
                            _self_attr(call.args[0]) if call.args else None
                        )
                        if inner is not None:
                            self.alias[attr] = inner
                        else:
                            # Condition() owns a fresh lock — treat the
                            # condition attr itself as a lock.
                            self.locks[attr] = node.lineno

    def canon(self, attr: str) -> str:
        seen = set()
        while attr in self.alias and attr not in seen:
            seen.add(attr)
            attr = self.alias[attr]
        return attr

    def is_lock(self, attr: str | None) -> bool:
        if attr is None:
            return False
        c = self.canon(attr)
        return c in self.locks or attr in self.alias

    def _withs(self, fn: ast.FunctionDef) -> list:
        """All `with self._lock:` acquisitions in a method."""
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if self.is_lock(attr):
                    out.append((self.canon(attr), node, fn.name))
        return out

    def _closure(self, method: str, seen: set) -> set[str]:
        """Locks a call to `method` may acquire (transitively through
        self-calls)."""
        if method in seen:
            return set()
        seen.add(method)
        fn = self.methods.get(method)
        if fn is None:
            return set()
        locks = {a for a, _w, _m in self.acquires.get(method, [])}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee in self.methods:
                    locks |= self._closure(callee, seen)
        return locks

    # -- lock-order edges --------------------------------------------------

    def order_edges(self) -> dict[tuple[str, str], tuple[int, str]]:
        """{(outer, inner): (line, via)} across all methods."""
        edges: dict[tuple[str, str], tuple[int, str]] = {}
        for m, _fn in self.methods.items():
            for outer, with_node, _m in self.acquires.get(m, []):
                for node in ast.walk(with_node):
                    if node is with_node:
                        continue
                    if isinstance(node, ast.With):
                        for item in node.items:
                            attr = _self_attr(item.context_expr)
                            if self.is_lock(attr):
                                inner = self.canon(attr)
                                if inner != outer:
                                    edges.setdefault(
                                        (outer, inner),
                                        (node.lineno, m),
                                    )
                    elif isinstance(node, ast.Call):
                        callee = _self_attr(node.func)
                        if callee in self.methods:
                            for inner in self.lock_closure.get(
                                callee, set()
                            ):
                                if inner != outer:
                                    edges.setdefault(
                                        (outer, inner),
                                        (
                                            node.lineno,
                                            f"{m} -> self.{callee}()",
                                        ),
                                    )
        return edges

    # -- threads -----------------------------------------------------------

    def threads(self) -> list[dict]:
        """Every `threading.Thread(...)` constructed in this class."""
        out = []
        for m, fn in self.methods.items():
            for node in ast.walk(fn):
                if (
                    not isinstance(node, ast.Call)
                    or attr_chain(node.func) != THREAD_CTOR
                ):
                    continue
                info = {
                    "method": m,
                    "line": node.lineno,
                    "daemon": False,
                    "target": None,
                    "name": None,
                }
                for kw in node.keywords:
                    if kw.arg == "daemon" and isinstance(
                        kw.value, ast.Constant
                    ):
                        info["daemon"] = bool(kw.value.value)
                    elif kw.arg == "target":
                        t = _self_attr(kw.value)
                        if t is not None:
                            info["target"] = ("self", t)
                        elif isinstance(kw.value, ast.Name):
                            info["target"] = ("module", kw.value.id)
                    elif kw.arg == "name" and isinstance(
                        kw.value, ast.Constant
                    ):
                        info["name"] = kw.value.value
                out.append(info)
        return out


def _cycles(edges: dict[tuple[str, str], tuple[int, str]]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles, done = [], set()

    def dfs(node, path, on_path):
        if node in on_path:
            cycles.append(path[path.index(node):] + [node])
            return
        if node in done:
            return
        on_path.add(node)
        for nxt in sorted(graph.get(node, ())):
            dfs(nxt, path + [node], on_path)
        on_path.discard(node)
        done.add(node)

    for start in sorted(graph):
        dfs(start, [], set())
    # de-dup rotations
    uniq, seen = [], set()
    for c in cycles:
        key = frozenset(c)
        if key not in seen:
            seen.add(key)
            uniq.append(c)
    return uniq


def _reaches_xla(
    table: FunctionTable,
    cls: str | None,
    fn: ast.FunctionDef,
    _seen: set | None = None,
) -> str | None:
    """First XLA-reaching call name found in `fn`'s local closure."""
    seen = _seen if _seen is not None else set()
    if id(fn) in seen:
        return None
    seen.add(id(fn))
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        last = chain.rsplit(".", 1)[-1]
        if last in XLA_REACHING_NAMES or chain.startswith("jax."):
            return chain
        target = None
        if chain.startswith("self.") and cls is not None:
            target = table.methods.get(cls, {}).get(last)
        elif "." not in chain:
            cands = table.functions.get(chain)
            target = cands[0] if cands else None
        if target is not None:
            hit = _reaches_xla(table, cls, target, seen)
            if hit is not None:
                return hit
    return None


class LockDisciplinePass:
    name = "lock-discipline"
    # Per-class models over one module's AST: module-scoped for the
    # check cache (analysis/cache.py).
    cache_scope = "module"

    def run(self, index: ModuleIndex) -> list[Finding]:
        out: list[Finding] = []
        for mod in index:
            table = FunctionTable(mod.tree)
            class_nodes: set[int] = set()
            for cls in table.classes.values():
                class_nodes.update(id(n) for n in ast.walk(cls))
                model = _ClassModel(mod, cls, table)
                out.extend(self._check_order(mod, cls, model))
                out.extend(
                    self._check_threads(mod, cls, model, table)
                )
            out.extend(
                self._check_module_threads(mod, table, class_nodes)
            )
        return out

    def _check_module_threads(
        self, mod, table, class_nodes: set[int]
    ) -> list[Finding]:
        """daemon-xla for threads constructed OUTSIDE any class (module
        functions, scripts): target resolves by bare name only."""
        out = []
        for node in ast.walk(mod.tree):
            if (
                id(node) in class_nodes
                or not isinstance(node, ast.Call)
                or attr_chain(node.func) != THREAD_CTOR
            ):
                continue
            daemon, target, label = False, None, None
            for kw in node.keywords:
                if kw.arg == "daemon" and isinstance(
                    kw.value, ast.Constant
                ):
                    daemon = bool(kw.value.value)
                elif kw.arg == "target" and isinstance(
                    kw.value, ast.Name
                ):
                    target = kw.value.id
                elif kw.arg == "name" and isinstance(
                    kw.value, ast.Constant
                ):
                    label = kw.value.value
            if not daemon or target is None:
                continue
            fn = (table.functions.get(target) or [None])[0]
            if fn is None:
                continue
            hit = _reaches_xla(table, None, fn)
            if hit is not None:
                out.append(
                    Finding(
                        rule="daemon-xla",
                        path=mod.path,
                        line=node.lineno,
                        severity="error",
                        message=(
                            f"daemon thread '{label or target}' "
                            f"reaches jax compile/dispatch via {hit}"
                        ),
                        detail=(
                            "a daemon thread killed mid-XLA-compile "
                            "aborts interpreter teardown (PR-7 rule); "
                            "make it non-daemon and join it on stop"
                        ),
                    )
                )
        return out

    # -- lock-order --------------------------------------------------------

    def _check_order(self, mod, cls, model) -> list[Finding]:
        out = []
        edges = model.order_edges()
        for cycle in _cycles(edges):
            pretty = " -> ".join(cycle)
            first = min(
                (
                    edges[(a, b)]
                    for a, b in zip(cycle, cycle[1:])
                    if (a, b) in edges
                ),
                default=(cls.lineno, "?"),
            )
            out.append(
                Finding(
                    rule="lock-order",
                    path=mod.path,
                    line=first[0],
                    severity="error",
                    message=(
                        f"lock acquisition cycle in {cls.name}: "
                        f"{pretty}"
                    ),
                    detail=f"first edge via {first[1]}",
                )
            )
        return out

    # -- threads: daemon XLA -----------------------------------------------

    def _check_threads(self, mod, cls, model, table) -> list[Finding]:
        out = []
        threads = model.threads()
        if not threads:
            return out

        # daemon-xla rule
        for t in threads:
            if not t["daemon"] or t["target"] is None:
                continue
            kind, name = t["target"]
            fn = (
                model.methods.get(name)
                if kind == "self"
                else (table.functions.get(name) or [None])[0]
            )
            if fn is None:
                continue
            hit = _reaches_xla(
                table, cls.name if kind == "self" else None, fn
            )
            if hit is not None:
                label = t["name"] or name
                out.append(
                    Finding(
                        rule="daemon-xla",
                        path=mod.path,
                        line=t["line"],
                        severity="error",
                        message=(
                            f"daemon thread '{label}' reaches jax "
                            f"compile/dispatch via {hit}"
                        ),
                        detail=(
                            "a daemon thread killed mid-XLA-compile "
                            "aborts interpreter teardown (PR-7 rule); "
                            "make it non-daemon and join it on stop"
                        ),
                    )
                )

        return out
