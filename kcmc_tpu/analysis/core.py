"""The pass framework behind `kcmc check` (docs/ANALYSIS.md).

Seven PRs of growth left this repo's load-bearing invariants — resume-
signature neutrality, jit-boundary purity, the "XLA work only on
non-daemon threads" rule, canonical trace-span names — living in
comments. This module is the machinery that turns them into CI-enforced
contracts: a shared AST index over the package, passes that walk it and
emit `Finding`s, and a checked-in baseline of accepted findings so the
gate is "no NEW findings", never "rewrite history first".

Design constraints:

* **stdlib only** — `ast` + `json`; the checker must run in a bare CI
  venv before jax/numpy import (and never pay accelerator start-up).
* **sources in, findings out** — passes see a `ModuleIndex`, which the
  tests build from in-memory fixture snippets (`ModuleIndex
  .from_sources`) and the CLI builds from the real package tree, so
  every rule is demonstrable on a known-bad fixture.
* **stable finding keys** — baselines match on (rule, path, message
  prefix), never line numbers, so unrelated edits don't churn the
  baseline file.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os


SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation, anchored to file:line.

    `message` must be stable under unrelated edits (it participates in
    the baseline key); put volatile detail (line numbers, counts) in
    `detail`, never in `message`.
    """

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    severity: str  # "error" | "warning"
    message: str
    detail: str = ""

    @property
    def key(self) -> str:
        """Baseline identity: line numbers deliberately excluded."""
        return f"{self.rule}|{self.path}|{self.message}"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}"
        s = f"{loc}: {self.severity} [{self.rule}] {self.message}"
        if self.detail:
            s += f" ({self.detail})"
        return s

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": int(self.line),
            "severity": self.severity,
            "message": self.message,
            "detail": self.detail,
        }


@dataclasses.dataclass(frozen=True)
class Module:
    """One parsed source file of the package under analysis."""

    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.Module


class ModuleIndex:
    """Parse-once index shared by every pass.

    Holds {relpath: Module} plus the docs the passes consult (API.md
    for the config-documentation rule). Construction parses each file
    exactly once; a file with a syntax error becomes a finding, not a
    crash (`parse_errors`).
    """

    def __init__(self):
        self.modules: dict[str, Module] = {}
        self.docs: dict[str, str] = {}
        self.parse_errors: list[Finding] = []

    # -- construction ------------------------------------------------------

    @classmethod
    def from_sources(
        cls, sources: dict[str, str], docs: dict[str, str] | None = None
    ) -> "ModuleIndex":
        """Build from in-memory {relpath: source} — the test seam."""
        idx = cls()
        for path, src in sources.items():
            idx._add(path.replace(os.sep, "/"), src)
        idx.docs = dict(docs or {})
        return idx

    @classmethod
    def from_package(cls, root: str) -> "ModuleIndex":
        """Walk `root`'s `kcmc_tpu/` package tree (and `docs/`) on disk.

        `root` is the repo root — the directory holding `kcmc_tpu/`.
        """
        idx = cls()
        pkg = os.path.join(root, "kcmc_tpu")
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                try:
                    with open(full, encoding="utf-8") as f:
                        idx._add(rel, f.read())
                except OSError:
                    continue  # unreadable file: not this checker's story
        for doc in ("docs/API.md",):
            full = os.path.join(root, doc)
            if os.path.exists(full):
                with open(full, encoding="utf-8") as f:
                    idx.docs[doc] = f.read()
        return idx

    def _add(self, rel: str, source: str) -> None:
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            self.parse_errors.append(
                Finding(
                    rule="parse",
                    path=rel,
                    line=int(e.lineno or 0),
                    severity="error",
                    message=f"syntax error: {e.msg}",
                )
            )
            return
        self.modules[rel] = Module(path=rel, source=source, tree=tree)

    # -- access ------------------------------------------------------------

    def get(self, rel: str) -> Module | None:
        return self.modules.get(rel)

    def match(self, prefix: str = "", suffix: str = ".py") -> list[Module]:
        """Modules whose path starts/ends with the given affixes."""
        return [
            m
            for p, m in sorted(self.modules.items())
            if p.startswith(prefix) and p.endswith(suffix)
        ]

    def __iter__(self):
        return iter(self.modules.values())


# -- shared AST helpers (used by several passes) ---------------------------


def attr_chain(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain: `jax.experimental.pjit`
    -> "jax.experimental.pjit"; anything non-static contributes "?"."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_set_from(node: ast.AST) -> set[str] | None:
    """String members of a literal registry value:
    frozenset({...}) / {...} / (...) / [...] of string constants."""
    if isinstance(node, ast.Call) and attr_chain(node.func).endswith(
        "frozenset"
    ):
        if not node.args:
            return set()
        node = node.args[0]
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            s = str_const(elt)
            if s is None:
                return None
            out.add(s)
        return out
    return None


class FunctionTable(ast.NodeVisitor):
    """All function/method defs of a module, by name and by class.

    `functions` maps bare name -> [FunctionDef] (module-level AND
    nested — resolution by bare name is deliberately flat: the passes
    reason about *locally reachable* code, and this repo does not reuse
    a helper name with different meanings inside one module).
    `methods` maps class name -> {method name -> FunctionDef}.
    """

    def __init__(self, tree: ast.Module):
        self.functions: dict[str, list[ast.FunctionDef]] = {}
        self.methods: dict[str, dict[str, ast.FunctionDef]] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        self._class_stack: list[str] = []
        self.visit(tree)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.classes[node.name] = node
        self._class_stack.append(node.name)
        self.methods.setdefault(node.name, {})
        self.generic_visit(node)
        self._class_stack.pop()

    def _def(self, node) -> None:
        self.functions.setdefault(node.name, []).append(node)
        if self._class_stack:
            self.methods[self._class_stack[-1]].setdefault(node.name, node)
        self.generic_visit(node)

    visit_FunctionDef = _def
    visit_AsyncFunctionDef = _def


def called_names(fn: ast.FunctionDef) -> list[tuple[str, int]]:
    """Every call inside `fn` as (dotted name, line) — `self.m()` yields
    "self.m", `np.asarray()` yields "np.asarray"."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            out.append((attr_chain(node.func), node.lineno))
    return out


def reachable_functions(
    table: FunctionTable,
    root: ast.FunctionDef,
    cls: str | None = None,
    _seen: set | None = None,
) -> list[ast.FunctionDef]:
    """`root` plus the local call-graph closure: callees resolved by
    bare name among module functions, and by `self.m` among `cls`'s
    methods. Cross-module calls are out of scope by design — passes
    reason about what a reader of THIS file can verify."""
    seen = _seen if _seen is not None else set()
    if id(root) in seen:
        return []
    seen.add(id(root))
    out = [root]
    for name, _line in called_names(root):
        target: ast.FunctionDef | None = None
        if name.startswith("self.") and cls is not None:
            target = table.methods.get(cls, {}).get(name[5:])
        elif "." not in name:
            cands = table.functions.get(name)
            target = cands[0] if cands else None
        if target is not None:
            out.extend(reachable_functions(table, target, cls, seen))
    return out


def enclosing_class(tree: ast.Module, fn: ast.FunctionDef) -> str | None:
    """Name of the class a function is (transitively) defined inside."""
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        for sub in ast.walk(cls):
            if sub is fn:
                return cls.name
    return None


# -- baseline --------------------------------------------------------------


@dataclasses.dataclass
class BaselineEntry:
    rule: str
    path: str
    match: str  # message prefix
    reason: str  # one-line justification — REQUIRED
    used: bool = False

    def matches(self, f: Finding) -> bool:
        return (
            f.rule == self.rule
            and f.path == self.path
            and f.message.startswith(self.match)
        )


class Baseline:
    """The checked-in set of accepted findings.

    Every entry carries a one-line `reason` — a baseline without a
    justification is itself a finding (`baseline` rule), so accepted
    debt stays explained, not just silenced.
    """

    KIND = "kcmc_check_baseline"

    def __init__(self, entries: list[BaselineEntry] | None = None):
        self.entries = list(entries or [])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("kind") != cls.KIND:
            raise ValueError(
                f"{path} is not a {cls.KIND} file (kind="
                f"{data.get('kind')!r})"
            )
        return cls(
            [
                BaselineEntry(
                    rule=e["rule"],
                    path=e["path"],
                    match=e["match"],
                    reason=e.get("reason", ""),
                )
                for e in data.get("entries", [])
            ]
        )

    def as_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "entries": [
                {
                    "rule": e.rule,
                    "path": e.path,
                    "match": e.match,
                    "reason": e.reason,
                }
                for e in self.entries
            ],
        }

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.as_dict(), f, indent=2, sort_keys=False)
            f.write("\n")

    def split(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding]]:
        """(new, baselined). Marks entries used for staleness report."""
        new, accepted = [], []
        for f in findings:
            hit = None
            for e in self.entries:
                if e.matches(f):
                    hit = e
                    break
            if hit is None:
                new.append(f)
            else:
                hit.used = True
                accepted.append(f)
        return new, accepted

    def problems(self) -> list[Finding]:
        """Baseline hygiene findings: missing reasons, stale entries."""
        out = []
        for e in self.entries:
            if not e.reason.strip():
                out.append(
                    Finding(
                        rule="baseline",
                        path=e.path,
                        line=0,
                        severity="error",
                        message=(
                            f"baseline entry for [{e.rule}] "
                            f"{e.match!r} has no justification"
                        ),
                    )
                )
            elif not e.used:
                out.append(
                    Finding(
                        rule="baseline",
                        path=e.path,
                        line=0,
                        severity="warning",
                        message=(
                            f"stale baseline entry: [{e.rule}] "
                            f"{e.match!r} no longer fires"
                        ),
                    )
                )
        return out


# -- runner ----------------------------------------------------------------


@dataclasses.dataclass
class CheckResult:
    findings: list[Finding]  # everything the passes emitted
    new: list[Finding]  # not covered by the baseline — the CI gate
    baselined: list[Finding]
    baseline_problems: list[Finding]
    passes: list[str]

    @property
    def exit_code(self) -> int:
        blocking = [f for f in self.new if f.severity == "error"]
        blocking += [
            f for f in self.baseline_problems if f.severity == "error"
        ]
        return 1 if blocking else 0

    def summary(self) -> dict:
        return {
            "kind": "kcmc_check",
            "passes": self.passes,
            "findings": len(self.findings),
            "new": len(self.new),
            "new_errors": sum(
                1 for f in self.new if f.severity == "error"
            ),
            "baselined": len(self.baselined),
            "stale_baseline": sum(
                1
                for f in self.baseline_problems
                if f.message.startswith("stale")
            ),
            "ok": self.exit_code == 0,
        }

    def as_dict(self) -> dict:
        d = self.summary()
        d["new_findings"] = [f.as_dict() for f in self.new]
        d["baselined_findings"] = [f.as_dict() for f in self.baselined]
        d["baseline_problems"] = [
            f.as_dict() for f in self.baseline_problems
        ]
        return d


def run_passes(
    index: ModuleIndex,
    passes: list,
    baseline: Baseline | None = None,
    cache=None,
) -> CheckResult:
    """Run every pass over the shared index and gate against the
    baseline. Findings sort by (path, line, rule) for stable output.
    `cache` (analysis/cache.CheckCache) replays content-hash-matched
    results instead of re-running a pass; baseline splitting always
    happens fresh."""
    findings = list(index.parse_errors)
    names = []
    for p in passes:
        names.append(p.name)
        if cache is not None:
            findings.extend(cache.findings_for(p, index))
        else:
            findings.extend(p.run(index))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    bl = baseline or Baseline()
    new, accepted = bl.split(findings)
    return CheckResult(
        findings=findings,
        new=new,
        baselined=accepted,
        baseline_problems=bl.problems(),
        passes=names,
    )
