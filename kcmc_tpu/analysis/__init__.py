"""AST-based invariant checking for the kcmc_tpu repo itself
(`kcmc check`; docs/ANALYSIS.md).

Four repo-specific passes over a shared module index enforce the
contracts that previously lived only in comments:

* ``config-registry`` — every `CorrectorConfig` field classified as
  resume-signature neutral or affecting, validated and documented;
* ``jit-purity`` — no host sync / side effects / nondeterminism
  reachable inside jitted programs;
* ``lock-discipline`` — lock-order cycles, unlocked cross-thread
  writes, and the "XLA work only on non-daemon threads" rule;
* ``span-registry`` — every trace-span and `timing` key literal drawn
  from the canonical `obs/registry.py` vocabulary.

Stdlib-only on purpose: the checker runs before (and without) jax.
"""

from kcmc_tpu.analysis.cli import default_passes, main, run_check
from kcmc_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    CheckResult,
    Finding,
    Module,
    ModuleIndex,
    run_passes,
)

__all__ = [
    "Baseline",
    "BaselineEntry",
    "CheckResult",
    "Finding",
    "Module",
    "ModuleIndex",
    "default_passes",
    "main",
    "run_check",
    "run_passes",
]
