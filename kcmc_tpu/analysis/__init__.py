"""Static + runtime analysis for the kcmc_tpu repo itself
(`kcmc check` / `kcmc sanitize`; docs/ANALYSIS.md).

Seven repo-specific passes over a shared module index enforce the
contracts that previously lived only in comments:

* ``config-registry`` — every `CorrectorConfig` field classified as
  resume-signature neutral or affecting, validated and documented;
* ``jit-purity`` — no host sync / side effects / nondeterminism
  reachable inside jitted programs;
* ``lock-discipline`` — lock-order cycles and the "XLA work only on
  non-daemon threads" rule;
* ``span-registry`` — every trace-span and `timing` key literal drawn
  from the canonical `obs/registry.py` vocabulary;
* ``thread-roots`` — the concurrent-entry-point inventory (named,
  statically-resolvable threads) feeding the cross-module call graph;
* ``race`` — whole-program happens-before race detection: shared
  accesses from concurrent roots with disjoint lock sets, with
  program-wide lock identity (Condition/constructor-param aliasing);
* ``resource-lifecycle`` — every acquired thread/pool/socket/file/
  telemetry resource reaches its release on all paths.

The runtime half (`analysis/sanitize.py`, behind `kcmc sanitize` /
`KCMC_SANITIZE=1` / `pytest --sanitize`) instruments real locks,
validates executed acquisition order against the static lock-order
graph, watches for deadlocks, and leak-checks each test.

Stdlib-only on purpose: the checker runs before (and without) jax.
"""

from kcmc_tpu.analysis.cli import default_passes, main, run_check
from kcmc_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    CheckResult,
    Finding,
    Module,
    ModuleIndex,
    run_passes,
)

__all__ = [
    "Baseline",
    "BaselineEntry",
    "CheckResult",
    "Finding",
    "Module",
    "ModuleIndex",
    "default_passes",
    "main",
    "run_check",
    "run_passes",
]
