"""Static + runtime analysis for the kcmc_tpu repo itself
(`kcmc check` / `kcmc sanitize`; docs/ANALYSIS.md).

Nine repo-specific passes over a shared module index enforce the
contracts that previously lived only in comments:

* ``config-registry`` — every `CorrectorConfig` field classified as
  resume-signature neutral or affecting, validated and documented;
* ``jit-purity`` — no host sync / side effects / nondeterminism
  reachable inside jitted programs;
* ``lock-discipline`` — lock-order cycles and the "XLA work only on
  non-daemon threads" rule;
* ``span-registry`` — every trace-span and `timing` key literal drawn
  from the canonical `obs/registry.py` vocabulary;
* ``thread-roots`` — the concurrent-entry-point inventory (named,
  statically-resolvable threads) feeding the cross-module call graph;
* ``race`` — whole-program happens-before race detection: shared
  accesses from concurrent roots with disjoint lock sets, with
  program-wide lock identity (Condition/constructor-param aliasing);
* ``resource-lifecycle`` — every acquired thread/pool/socket/file/
  telemetry resource reaches its release on all paths;
* ``traceflow`` (rule families ``retrace`` / ``dtype-flow`` /
  ``transfer`` / ``bucket-escape``) — whole-program shape/dtype/
  placement flow from every jit entry: trace-time branching and
  per-call captures, silent wide-dtype promotion, dispatch-window
  host transfers with bytes estimates, and jit dispatches whose
  shapes escape the `plan_buckets` ladder;
* ``donation`` — jitted programs whose input buffer dies at the call
  site and matches an output's shape get donation-candidate findings
  (`donate_argnums`); the register/apply frame programs carry the
  contract as a checked keyword.

The runtime half (`analysis/sanitize.py`, behind `kcmc sanitize` /
`KCMC_SANITIZE=1` / `pytest --sanitize`) instruments real locks,
validates executed acquisition order against the static lock-order
graph, watches for deadlocks, leak-checks each test, and hosts the
RETRACE SENTINEL: per-program compile counts from plans/runtime.py
validated against the static bucket-ladder prediction — a warmed
process compiling a covered program again fails the gate.
Results are content-hash cached under `.kcmc_check_cache/`
(`analysis/cache.py`; `kcmc check --no-cache` bypasses).

Stdlib-only on purpose: the checker runs before (and without) jax.
"""

from kcmc_tpu.analysis.cli import default_passes, main, run_check
from kcmc_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    CheckResult,
    Finding,
    Module,
    ModuleIndex,
    run_passes,
)

__all__ = [
    "Baseline",
    "BaselineEntry",
    "CheckResult",
    "Finding",
    "Module",
    "ModuleIndex",
    "default_passes",
    "main",
    "run_check",
    "run_passes",
]
