"""Dtype-cast helpers shared by the host and device output paths."""

from __future__ import annotations

import numpy as np


def int_clip_bounds(int_dtype: np.dtype, float_dtype: np.dtype):
    """Clip bounds for a round-to-integer cast, exactly representable in
    the float dtype doing the math.

    float32(2**31 - 1) rounds UP to 2**31.0, so clipping int32 targets
    against np.iinfo(int32).max in float32 lets boundary values pass
    through as 2**31.0 and wrap to INT32_MIN on the final astype. Bounds
    are stepped one ulp inward whenever the float cast rounded outward,
    so clip-then-astype is always in range.
    """
    info = np.iinfo(int_dtype)
    f = np.dtype(float_dtype).type
    lo, hi = f(info.min), f(info.max)
    if int(hi) > info.max:
        hi = np.nextafter(hi, f(0))
    if int(lo) < info.min:
        lo = np.nextafter(lo, f(0))
    return lo, hi
