"""Deterministic fault injection + retry/backoff policy (robustness).

The production contract (ROADMAP north star) is a multi-hour
`correct_file` run over millions of frames that must survive transient
device errors, flaky storage reads, and corrupt checkpoints instead of
dying at frame 800k. This module provides both halves of that story:

* **FaultPlan** — a seedable, deterministic fault injector. A plan is
  parsed from a compact spec string and armed around the failure
  surfaces of a run: chunk reads (``io_read``, in
  `io.reader.ChunkedStackLoader`), per-batch device execution
  (``device``, in `MotionCorrector._dispatch_batches` AND the serve
  scheduler's dispatch path), the numpy failover rung (``failover``),
  checkpoint part load (``checkpoint``, in
  `utils.checkpoint.load_stream_checkpoint`), and — for the serve
  plane — client transport (``transport``, in the server's connection
  handler: a raising clause drops the connection, a ``stall=`` clause
  half-opens it), the scheduler loop (``scheduler``: a ``stall=``
  clause wedges one loop iteration, a raising clause exercises the
  loop's error backstop), session journaling (``journal``, in
  `serve.journal.SessionJournal.save`), and the fleet router
  (``fleet``, in `serve.router`/`serve.fleet`: a raising clause
  blackholes the router's next replica call — forward, health scrape,
  or migration `resume_session` — and a ``stall=`` clause stalls a
  health scrape past its probe budget).
  Activated via `CorrectorConfig(fault_plan=...)`, the
  ``KCMC_FAULT_PLAN`` environment variable, or the CLI's
  ``--inject-faults`` (``correct``, ``apply``, and ``serve``) — so
  chaos runs need no code changes.

* **RetryPolicy** — bounded retries with exponential backoff and
  seeded jitter, shared by the IO and device retry loops.

* **classify_transient** — the transient-vs-fatal error split the
  retry engine keys on. Transient errors (IO hiccups, device-link
  statuses like UNAVAILABLE/RESOURCE_EXHAUSTED) are retried and walked
  down the degradation ladder; fatal errors (shape/config bugs) are
  raised immediately so real defects never get papered over.

Spec grammar (see docs/ROBUSTNESS.md for the full reference)::

    plan    := clause ("," clause)*
    clause  := surface (":" token)*
    surface := io_read | device | failover | checkpoint
              | transport | scheduler | journal | fleet | object
    token   := key "=" value | action
    action  := transient (default) | fatal | raise (alias of fatal)
              | always (alias of times=inf)
              | drop (object only; alias of transient — a dropped
                connection)
              | truncate (object only: the op returns a TRUNCATED
                body instead of raising)
              | flip (object only: one bit of the returned body is
                flipped — checksum/etag-mismatch simulation)
              | throttle (object only: the op raises a 429/503-style
                ObjectStoreThrottled, retried with backoff)
    keys    := step=N          which operation of that surface fails
                               (0-based; omitted = every operation)
               times=N|inf     how many matching ATTEMPTS fail before
                               the clause is spent (default 1)
               p=F             fail each matching attempt with
                               probability F (seeded, deterministic)
               corrupt_part=N  checkpoint surface only: corrupt part
                               file N on disk before it is loaded
               stall=SECS      transport/scheduler/fleet/object
                               surfaces only: the matched operation
                               STALLS for SECS seconds instead of
                               raising (half-open socket / wedged
                               scheduler / stalled health scrape /
                               slow object GET simulation; consumed
                               via `take_stall`)

The ``object`` surface (PR 17) is armed inside the object-store
client (`io/objectstore.py` — the emulator and any real client built
on it): every GET/PUT/multipart op draws one op index, and the client
interprets the clause action itself via `take_action` — raising
actions become dropped-connection/throttle errors, ``truncate`` and
``flip`` mangle the returned/stored body so the checksum layer has
something real to catch.

Example — the chaos trifecta::

    io_read:step=3:raise, device:step=7:transient, checkpoint:corrupt_part=1

`times=` counts *attempts*, so ``device:step=7:times=2:transient``
fails the first two attempts at batch 7 and lets the third (the second
retry) succeed — the canonical "retries absorb the fault" scenario.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time

import numpy as np

SURFACES = (
    "io_read",
    "device",
    "failover",
    "checkpoint",
    # serve-plane surfaces (PR 14): client transport, the scheduler
    # loop, and per-session journal writes
    "transport",
    "scheduler",
    "journal",
    # fleet-router surface (PR 16): router-side replica calls —
    # raising = replica blackhole / migration failure, stall= =
    # health-scrape stall
    "fleet",
    # object-store surface (PR 17): client GET/PUT/multipart ops in
    # io/objectstore.py — drop/throttle raise, truncate/flip mangle
    # bodies, stall= simulates a slow ranged GET (what hedged reads
    # absorb)
    "object",
)

# Surfaces whose clauses may carry stall=SECS (wedge, don't raise).
_STALL_SURFACES = ("transport", "scheduler", "fleet", "object")

# Actions only the object-store client knows how to apply (consumed
# via `take_action`, never raised by `maybe_fail`).
_OBJECT_ACTIONS = ("truncate", "flip", "throttle")


class FaultError(RuntimeError):
    """Base class of injected faults (never raised by real failures)."""


class TransientFaultError(FaultError):
    """An injected fault the retry engine classifies as transient."""


class FatalFaultError(FaultError):
    """An injected fault the retry engine classifies as fatal."""


# Substrings marking a device-runtime error as transient. These are the
# gRPC-style status tokens the accelerator runtimes put in message text
# for link/resource conditions that a retry (or a failover) can outlive;
# compile/shape/user errors carry none of them.
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "connection reset",
    "socket closed",
    "transfer failed",
    "device or resource busy",
)


# OSError subclasses that describe a PERMANENT condition a retry cannot
# outlive — a deleted input, revoked credentials, a path that is a
# directory. Retrying these only delays the inevitable abort.
_PERMANENT_OS_ERRORS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


def classify_transient(exc: BaseException, device_error_types=()) -> bool:
    """Transient-vs-fatal error classification for the retry engine.

    Transient: injected TransientFaultError, OS-level IO errors
    (flaky storage, closed sockets — but NOT permanent conditions like
    FileNotFoundError/PermissionError), and device-runtime error types
    the executing backend declares (``backend.transient_error_types``)
    whose message carries a link/resource status marker. Everything
    else — ValueError/TypeError config bugs, injected FatalFaultError,
    KeyboardInterrupt — is fatal: retrying would only hide it.
    """
    if isinstance(exc, TransientFaultError):
        return True
    if isinstance(exc, FatalFaultError):
        return False
    if isinstance(exc, (OSError, TimeoutError)):
        # covers IOError, ConnectionError, InterruptedError, ...
        return not isinstance(exc, _PERMANENT_OS_ERRORS)
    if device_error_types and isinstance(exc, tuple(device_error_types)):
        msg = str(exc).lower()
        return any(m.lower() in msg for m in _TRANSIENT_MARKERS)
    return False


@dataclasses.dataclass
class _Clause:
    surface: str
    step: int | None = None  # operation index (None = every operation)
    times: float = 1.0  # failing attempts before the clause is spent
    action: str = "transient"  # transient | fatal
    p: float | None = None  # per-attempt probability (seeded)
    corrupt_part: int | None = None  # checkpoint surface only
    stall: float | None = None  # transport/scheduler: wedge seconds
    fired: int = 0


def _parse_clause(text: str) -> _Clause:
    tokens = [t.strip() for t in text.split(":") if t.strip()]
    if not tokens:
        raise ValueError(f"empty fault clause in {text!r}")
    surface = tokens[0]
    if surface not in SURFACES:
        raise ValueError(
            f"unknown fault surface {surface!r}; must be one of {SURFACES}"
        )
    c = _Clause(surface=surface)
    for tok in tokens[1:]:
        if "=" in tok:
            key, _, val = tok.partition("=")
            key, val = key.strip(), val.strip()
            if key == "step":
                c.step = int(val)
            elif key == "times":
                c.times = math.inf if val in ("inf", "always") else int(val)
                if c.times < 1:
                    raise ValueError(f"times must be >= 1, got {val!r}")
            elif key == "p":
                c.p = float(val)
                if not 0.0 < c.p <= 1.0:
                    raise ValueError(f"p must be in (0, 1], got {val!r}")
            elif key == "corrupt_part":
                c.corrupt_part = int(val)
            elif key == "stall":
                c.stall = float(val)
                if c.stall <= 0.0:
                    raise ValueError(
                        f"stall must be positive seconds, got {val!r}"
                    )
            else:
                raise ValueError(
                    f"unknown fault-clause key {key!r} in {text!r} "
                    "(known: step, times, p, corrupt_part, stall)"
                )
        elif tok in ("transient", "drop"):
            # "drop" reads as a dropped connection on the object
            # surface; both classify transient and retry identically
            c.action = "transient"
        elif tok in ("fatal", "raise"):
            c.action = "fatal"
        elif tok in _OBJECT_ACTIONS:
            c.action = tok
        elif tok == "always":
            c.times = math.inf
        else:
            raise ValueError(
                f"unknown fault-clause token {tok!r} in {text!r} "
                "(actions: transient/drop, fatal/raise, always, "
                "truncate, flip, throttle)"
            )
    if c.corrupt_part is not None and c.surface != "checkpoint":
        raise ValueError(
            f"corrupt_part= applies to the checkpoint surface only ({text!r})"
        )
    if c.surface == "checkpoint" and c.corrupt_part is None:
        raise ValueError(
            f"checkpoint clauses need corrupt_part=N ({text!r})"
        )
    if c.stall is not None and c.surface not in _STALL_SURFACES:
        raise ValueError(
            f"stall= applies to the {'/'.join(_STALL_SURFACES)} surfaces "
            f"only ({text!r})"
        )
    if c.action in _OBJECT_ACTIONS and c.surface != "object":
        raise ValueError(
            f"{c.action} applies to the object surface only ({text!r})"
        )
    return c


class FaultPlan:
    """A parsed, stateful fault-injection plan (one instance per run).

    Owns per-surface operation counters (`op_index`) so an operation's
    identity is stable across its retry attempts: the caller fetches
    one op index per logical operation and calls `maybe_fail` once per
    *attempt* — a clause with ``times=2`` therefore fails exactly the
    first two attempts of its step.
    """

    def __init__(self, clauses: list[_Clause], seed: int = 0):
        self.clauses = clauses
        self.injected = 0  # total faults raised/applied by this plan
        self._ops = {s: 0 for s in SURFACES}
        self._corrupted: set[int] = set()
        # One plan is shared between the main thread (device surface)
        # and the prefetch thread (io_read surface); the lock keeps the
        # fired/injected counters race-free, and each probabilistic
        # clause draws from its OWN seeded stream so which attempts
        # fail is independent of cross-thread interleaving.
        self._lock = threading.Lock()
        for i, c in enumerate(self.clauses):
            if c.p is not None:
                c._rng = np.random.default_rng([int(seed), i])

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlan":
        clauses = [
            _parse_clause(part)
            for part in str(spec).split(",")
            if part.strip()
        ]
        if not clauses:
            raise ValueError(f"fault plan spec has no clauses: {spec!r}")
        return cls(clauses, seed=seed)

    def op_index(self, surface: str) -> int:
        """Allocate the next operation index for a surface (NOT
        incremented by retries — call once per logical operation)."""
        with self._lock:
            i = self._ops[surface]
            self._ops[surface] = i + 1
            return i

    def _take_clause(self, surface: str, step: int | None, stall: bool):
        """Consume and return the first live clause matching this
        attempt (stall=True selects stall clauses, False raising ones);
        None when nothing matches. Lock held by the caller."""
        for c in self.clauses:
            if c.surface != surface or (c.stall is not None) != stall:
                continue
            if c.step is not None and step is not None and c.step != step:
                continue
            if c.fired >= c.times:
                continue
            if c.p is not None and c._rng.random() >= c.p:
                continue
            c.fired += 1
            self.injected += 1
            return c
        return None

    def maybe_fail(self, surface: str, step: int | None) -> None:
        """Raise the configured fault if a clause matches this attempt
        (stall clauses never raise — consume them via `take_stall`)."""
        with self._lock:
            c = self._take_clause(surface, step, stall=False)
            if c is None:
                return
            msg = (
                f"injected {c.action} fault: {surface}"
                f"[step={step}] attempt {c.fired}"
            )
            if c.action == "fatal":
                raise FatalFaultError(msg)
            raise TransientFaultError(msg)

    def take_stall(self, surface: str, step: int | None = None) -> float:
        """Seconds the matched operation should stall (0.0 = no stall
        clause fired). The serve plane's transport handler and
        scheduler loop consume these to simulate half-open sockets and
        wedged queues; the CALLER sleeps, so injection never blocks
        unrelated surfaces behind the plan lock."""
        with self._lock:
            c = self._take_clause(surface, step, stall=True)
            return float(c.stall) if c is not None else 0.0

    def take_action(self, surface: str, step: int | None = None) -> str | None:
        """Consume a matching non-stall clause and return its ACTION
        string instead of raising (None = nothing fired). The object
        surface consumes its clauses this way: the object-store client
        interprets the action itself (transient/fatal -> raise,
        truncate/flip -> mangle the body, throttle -> a 429-style
        error) — injection stays inside the client, so every consumer
        of the client exercises the same failure modes."""
        with self._lock:
            c = self._take_clause(surface, step, stall=False)
            return c.action if c is not None else None

    # -- checkpoint surface ------------------------------------------------

    def take_checkpoint_corruption(self, part_index: int) -> bool:
        """One-shot: should checkpoint part `part_index` be corrupted on
        disk before loading? (Consumed so a rerun within the same plan
        instance doesn't re-corrupt the recomputed part.)"""
        with self._lock:
            for c in self.clauses:
                if (
                    c.surface == "checkpoint"
                    and c.corrupt_part == part_index
                    and part_index not in self._corrupted
                ):
                    self._corrupted.add(part_index)
                    c.fired += 1
                    self.injected += 1
                    return True
            return False

    @staticmethod
    def corrupt_file(path: str) -> None:
        """Deterministically corrupt a file in place (truncate to half
        size) — the stand-in for a torn write / bad sector."""
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(size // 2, 1))
        except OSError:
            pass  # absent file: nothing to corrupt


def resolve_fault_plan(spec: str | None, seed: int = 0) -> FaultPlan | None:
    """Build the run's FaultPlan from an explicit spec or the
    ``KCMC_FAULT_PLAN`` environment variable (explicit wins)."""
    spec = spec or os.environ.get("KCMC_FAULT_PLAN") or None
    return FaultPlan.from_spec(spec, seed=seed) if spec else None


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff + seeded jitter.

    `attempts` is the TOTAL attempt budget per operation (1 = no
    retry). `delay(k)` is the sleep before retry k (0-based):
    ``backoff_s * 2**k`` clipped to `backoff_max_s`, multiplied by a
    uniform jitter in ``[1 - jitter, 1 + jitter]`` so a fleet of
    workers retrying a shared dependency doesn't thundering-herd it.

    `deadline_s` is the PER-ATTEMPT deadline cap for operations that
    can wedge rather than fail (a stalled object-store GET): clients
    that can enforce it (io/objectstore.py passes it into every
    client op) time the attempt out as a transient error, so one
    wedged request costs at most deadline_s before the retry/hedge
    machinery takes over. None = no cap (local-file reads fail fast
    on their own).
    """

    attempts: int = 3
    backoff_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.25
    seed: int = 0
    deadline_s: float | None = None  # per-attempt cap (object I/O)
    sleep: object = time.sleep  # injectable for tests

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)

    def delay(self, retry_index: int) -> float:
        base = min(self.backoff_s * (2.0 ** retry_index), self.backoff_max_s)
        if self.jitter <= 0.0:
            return base
        lo, hi = 1.0 - self.jitter, 1.0 + self.jitter
        return base * float(self._rng.uniform(lo, hi))


def default_io_retry_policy(cfg=None, seed_offset: int = 1):
    """THE ingest-surface retry policy — the single construction point
    shared by `corrector._begin_robust_run` (which hands it to
    `io/reader.py` and the feeder), and the object-store path
    (`io/objectstore.py` builds one for standalone readers/writers).
    One construction site means backoff/jitter/deadline semantics
    cannot drift between ingest surfaces.

    `cfg` is any object with the CorrectorConfig retry fields
    (duck-typed — no config import, so standalone io users can pass
    None for the defaults). Returns None when retries are disabled
    (``retry_attempts <= 1``), mirroring the corrector's contract.
    `seed_offset` keeps the io jitter stream distinct from the device
    policy's (separate instances per thread: numpy Generators are not
    thread-safe)."""
    if cfg is None:
        return RetryPolicy(seed=seed_offset)
    if int(cfg.retry_attempts) <= 1:
        return None
    return RetryPolicy(
        attempts=cfg.retry_attempts,
        backoff_s=cfg.retry_backoff_s,
        backoff_max_s=cfg.retry_backoff_max_s,
        jitter=cfg.retry_jitter,
        seed=int(cfg.seed) + seed_offset,
        deadline_s=getattr(cfg, "object_timeout_s", None),
    )
