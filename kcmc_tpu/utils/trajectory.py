"""Trajectory smoothing: stabilization-style correction.

`MotionCorrector.correct` removes ALL motion — every frame is pinned to
the reference. For stabilization workloads (handheld / stage-walk
video, long drifting acquisitions) the goal is different: remove the
high-frequency jitter but FOLLOW the intentional motion, so the output
pans/zooms smoothly instead of fighting a large accumulated drift (and
losing field of view to it). The standard decomposition (same family as
OpenCV vidstab / MeshFlow parameter smoothing): low-pass the recovered
per-frame motion trajectory, and re-apply only the residual.

    res = mc.correct(stack)                       # full registration
    stab = smooth_trajectory(res.transforms, sigma=15)
    stabilized = apply_correction(stack, stab)    # jitter-free pan

Given full-correction warps M_t (output->source maps, the repo-wide
convention) and their temporal low-pass M̃_t, the stabilizing warp is

    S_t = M_t @ inv(M̃_t)

-- undo the SMOOTHED correction after applying the full one, leaving
the smooth path in and taking the jitter out. Two invariants make this
the right composition: an already-smooth trajectory gives S_t == I
(footage untouched), and sigma -> inf recovers full registration up to
the mean pose. Matrix entries are smoothed directly (exact for the
translation family; for rotational jitter the induced scale error is
1 - cos(dtheta) ~ 1e-4 at the ~1 degree jitter scale this targets —
stabilizing warps need not be exactly rigid, they are just warps);
homographies are re-normalized to M[2,2] = 1 after smoothing.

Counterpart of a motion-correction framework's stabilization mode
(SURVEY.md §0 names video stabilization as a use of the pipeline
family; reference source unavailable — contract from BASELINE.json).
"""

from __future__ import annotations

import numpy as np


def _check_transforms(transforms) -> np.ndarray:
    """Validate a (T, 3, 3) / (T, 4, 4) trajectory (shared by every
    public entry point here)."""
    M = np.asarray(transforms)
    d = M.shape[-1] if M.ndim == 3 else 0
    if M.ndim != 3 or M.shape[-2] != d or d not in (3, 4):
        raise ValueError(
            f"transforms must be (T, 3, 3) or (T, 4, 4), got {M.shape}"
        )
    return M


def _gaussian_taps(sigma: float) -> np.ndarray:
    r = max(1, int(3.0 * sigma + 0.5))
    x = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return k / k.sum()


def _smooth_along_t(arr: np.ndarray, sigma: float) -> np.ndarray:
    """Gaussian low-pass along axis 0 with odd-reflect padding.

    Odd reflection (p[-k] = 2*p[0] - p[k]) extends the trajectory
    C^1-continuously, so a path arriving at the boundary with nonzero
    velocity is extrapolated straight through it instead of kinking
    into a mirrored V (plain "reflect" at sigma=8 over a 30-px/240-frame
    sinusoid bends the smoothed endpoint ~5 px off the true path; odd
    reflection leaves O(sigma^2 * curvature))."""
    taps = _gaussian_taps(sigma)
    r = len(taps) // 2
    T = arr.shape[0]
    flat = arr.reshape(T, -1).astype(np.float64)
    # for T == 1 there is nothing to smooth
    if T == 1:
        return arr.astype(np.float64)
    pad = np.pad(flat, ((r, r), (0, 0)), mode="reflect", reflect_type="odd")
    # One vectorized shift-accumulate per tap (len(taps) ~ 6*sigma ops)
    # instead of a Python loop over columns — a piecewise (T, gh, gw, 2)
    # field flattens to gh*gw*2 columns (2048 for a 32x32 grid), which
    # made per-column np.convolve the dominant host cost on long runs.
    out = np.zeros_like(flat)
    for k, t in enumerate(taps):
        out += t * pad[k : k + T]
    return out.reshape(arr.shape)


def smooth_trajectory(
    transforms: np.ndarray | None = None,
    fields: np.ndarray | None = None,
    sigma: float = 15.0,
) -> np.ndarray:
    """Stabilizing transforms/fields from a recovered motion trajectory.

    Pass exactly one of:

    * `transforms` — (T, 3, 3) or (T, 4, 4) full-correction warps from
      `CorrectionResult.transforms` (any matrix model, 2D or rigid3d).
      Returns same-shape stabilizing warps S_t = M_t @ inv(smooth(M)_t)
      for `apply_correction`.
    * `fields` — (T, gh, gw, 2) piecewise displacement fields from
      `CorrectionResult.fields`. Displacement fields compose additively
      (to first order in the displacement), so the stabilizing field is
      the high-pass residual F_t - smooth(F)_t. Returns same shape.

    `sigma` is the temporal Gaussian's scale IN FRAMES: motion slower
    than ~sigma frames is kept, faster is removed. Boundary handling is
    odd reflection — the path is extrapolated slope-preservingly
    through the ends instead of sliding toward the sequence mean.
    """
    if (transforms is None) == (fields is None):
        raise ValueError("pass exactly one of transforms= or fields=")
    if sigma <= 0.0:
        raise ValueError(f"sigma must be positive, got {sigma}")
    if fields is not None:
        fields = np.asarray(fields)
        if fields.ndim != 4 or fields.shape[-1] != 2:
            raise ValueError(f"fields must be (T, gh, gw, 2), got {fields.shape}")
        sm = _smooth_along_t(fields, sigma)
        return (fields - sm).astype(np.float32)

    M = _check_transforms(transforms)
    sm = _smooth_along_t(M, sigma)
    # Projective entries drift off unit scale under averaging; renorm.
    sm = sm / sm[:, -1:, -1:]
    # Smoothing preserves the affine last row exactly (constant input
    # rows stay constant under a normalized kernel); inv() is then a
    # valid warp of the same kind.
    stab = np.einsum("tij,tjk->tik", M.astype(np.float64), np.linalg.inv(sm))
    return stab.astype(np.float32)


def interpolate_failed(
    transforms: np.ndarray, good: np.ndarray
) -> np.ndarray:
    """Replace failed frames' transforms by interpolating their
    neighbors' motion.

    A frame whose registration failed — stimulation artifact, shutter
    blank, a dropped camera frame — comes back with a meaningless
    transform (a blank frame consensus-defaults to identity, which
    mid-drift re-introduces the full motion into that one frame). Real
    motion is continuous, so the standard repair interpolates the
    trajectory across the gap:

        good = res.diagnostics["n_inliers"] >= 20
        fixed = interpolate_failed(res.transforms, good)
        corrected = apply_correction(stack, fixed)   # re-warp

    `transforms`: (T, 3, 3) or (T, 4, 4); `good`: (T,) boolean mask of
    trustworthy frames. Failed runs interior to the sequence are
    linearly interpolated entry-wise between the flanking good frames
    (exact for translation; the standard small-motion approximation for
    the rotational/projective entries, with homographies renormalized);
    failed runs at the ends copy the nearest good transform. Raises if
    no frame is good. Good frames pass through bit-unchanged.
    """
    M = _check_transforms(transforms)
    good = np.asarray(good, bool)
    if good.shape != (len(M),):
        raise ValueError(
            f"good mask must be ({len(M)},), got {good.shape}"
        )
    if good.all():
        return M.copy()
    if not good.any():
        raise ValueError("no good frames to interpolate from")
    t = np.arange(len(M), dtype=np.float64)
    tg = t[good]
    flat = M.reshape(len(M), -1).astype(np.float64)
    out = flat.copy()
    for j in range(flat.shape[1]):
        # np.interp clamps beyond the first/last good frame = nearest
        # extrapolation at the ends.
        out[~good, j] = np.interp(t[~good], tg, flat[good, j])
    out = out.reshape(M.shape)
    out = out / out[:, -1:, -1:]  # homography renorm; affine rows exact
    out[good] = M[good]  # good frames bit-unchanged
    return out.astype(M.dtype, copy=False)
