"""Honest device profiling: forced-value timing + XLA trace capture.

The tunneled/async nature of accelerator runtimes makes naive timing
lie in BOTH directions: `jax.block_until_ready` can return before the
device has actually executed (measured on this image's TPU tunnel —
dispatch-only loops report physically impossible throughput), and
forcing a value per iteration pays a full host round-trip per call.
The honest protocol, used by bench.py and exposed here for users:

1. Warm up AND force a real value (np.asarray), so the runtime leaves
   any deferred-execution mode before timing starts.
2. Dispatch N iterations back to back (the device stream is in-order),
   then force ONE tiny value from the LAST iteration's output — total
   time = N * steady-state cost + one round trip, amortized away by N.

`stage_breakdown` times cumulative prefixes of the registration
pipeline (detect / +describe / +match / +consensus / +warp) with this
protocol, giving true incremental per-stage costs.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np


def honest_time(
    fn, *args, iters: int = 24, warmup: int = 1, min_warmup_s: float = 0.25
) -> float:
    """Seconds per call of jitted `fn(*args)`, forced-value protocol.

    Warmup runs at least `warmup` forced iterations AND at least
    `min_warmup_s` of forced wall time: the first timed loop after a
    fresh compile otherwise lands in the device's cold-clock window and
    reads 2-3x high (measured on this image's TPU — the inflation decays
    over ~0.5 s of sustained execution, not a fixed iteration count).

    CAVEAT for A/B kernel comparisons: even with this warmup, the FIRST
    honest_time call of a process (or after any idle gap) can read
    5-10x high at the sub-millisecond scale (measured round 3: a 65k
    sort timed 6.1 ms cold vs 0.36 ms sustained — an apparent "6x
    optimization" that was pure artifact; DESIGN.md "Large-frame
    support", negative result). Comparing two variants honestly needs
    several seconds of sustained pre-warming of BOTH, then interleaved
    repeated loops taking min-of; and only an end-to-end delta confirms
    a win.
    """
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    n = 0
    while n < max(1, warmup) or time.perf_counter() - t0 < min_warmup_s:
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]  # force real exec
        n += 1
        if n >= 1024:  # sub-microsecond fns: don't warm up forever
            break
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jnp.sum(jax.tree.leaves(out)[0]))
    return (time.perf_counter() - t0) / iters


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture an XLA profiler trace viewable in TensorBoard/Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def stage_breakdown(
    model: str = "translation",
    shape: tuple[int, int] = (512, 512),
    batch_size: int = 64,
    iters: int = 16,
    n_blobs: int | None = None,
    sigma_range: tuple | None = None,
    **config_overrides,
) -> dict[str, dict[str, float] | float]:
    """True incremental cost (ms/batch) of each 2D pipeline stage.

    Builds cumulative prefix programs of the registration pipeline and
    times each with the forced-value protocol; the difference between
    consecutive prefixes is the stage's incremental cost inside the
    fused program (stages fuse across boundaries, so isolated timings
    mislead).

    Caveat: prefix programs are their own XLA compilations, and a
    prefix can compile PATHOLOGICALLY differently from the full
    pipeline (measured: the describe-only prefix at max_keypoints=2048
    costs 6x the full program that contains it — its (B, K, 8) uint32
    descriptor output forces a layout the fused program never
    materializes). Trust the full-program row absolutely, the
    incremental rows directionally, and profile with `trace()` when a
    prefix row looks impossible.
    """
    import jax
    import jax.numpy as jnp

    from kcmc_tpu.backends.jax_backend import JaxBackend
    from kcmc_tpu.config import CorrectorConfig
    from kcmc_tpu.ops.describe import describe_keypoints_batch
    from kcmc_tpu.ops.detect import detect_keypoints_batch
    from kcmc_tpu.ops.match import knn_match
    from kcmc_tpu.models import get_model
    from kcmc_tpu.utils.synthetic import make_drift_stack

    if model in ("piecewise", "rigid3d"):
        raise ValueError(
            "stage_breakdown covers the 2D matrix-model pipeline; "
            f"got model={model!r}"
        )
    cfg = CorrectorConfig(model=model, batch_size=batch_size, **config_overrides)
    backend = JaxBackend(cfg)
    # Scene-density generator knobs (the affine@2k config's n_blobs /
    # sigma_range): the per-stage prices depend on match density, so
    # the profiled scene must be the JUDGED scene, not the default.
    gen_kw = {}
    if n_blobs is not None:
        gen_kw["n_blobs"] = n_blobs
    if sigma_range is not None:
        gen_kw["sigma_range"] = sigma_range
    data = make_drift_stack(
        n_frames=8, shape=shape, model=model, seed=0, **gen_kw
    )
    reps = (batch_size + 7) // 8
    frames = jnp.asarray(
        np.tile(data.stack, (reps, 1, 1))[:batch_size], jnp.float32
    )
    ref = backend.prepare_reference(np.asarray(data.stack[0], np.float32))
    ref = {k: jnp.asarray(v) for k, v in ref.items()}
    tmodel = get_model(cfg.model)
    oriented = cfg.resolved_oriented()
    use_pallas = backend._on_accelerator()

    def detect(frames):
        # Mirror the production path exactly, including the descriptor-
        # blur free-ride on the fused Pallas kernel (jax_backend.local).
        return detect_keypoints_batch(
            frames,
            max_keypoints=cfg.max_keypoints,
            threshold=cfg.detect_threshold,
            nms_size=cfg.nms_size,
            border=cfg.border,
            harris_k=cfg.harris_k,
            use_pallas=use_pallas,
            smooth_sigma=cfg.blur_sigma,
        )

    def p_detect(frames):
        k, smooth = detect(frames)
        return k.xy.sum() + k.score.sum() + smooth.sum()

    def p_describe(frames):
        k, smooth = detect(frames)
        d = describe_keypoints_batch(
            frames, k, oriented=oriented, blur_sigma=cfg.blur_sigma,
            use_pallas=use_pallas, smooth=smooth,
        )
        return d.sum()

    def _match(frames):
        k, smooth = detect(frames)
        d = describe_keypoints_batch(
            frames, k, oriented=oriented, blur_sigma=cfg.blur_sigma,
            use_pallas=use_pallas, smooth=smooth,
        )
        m = jax.vmap(
            lambda dd, vv: knn_match(
                dd, ref["desc"], vv, ref["valid"],
                ratio=cfg.ratio, max_dist=cfg.max_hamming, mutual=cfg.mutual,
                precision=cfg.resolved_match_precision(use_pallas),
            )
        )(d, k.valid)
        return k, m

    def p_match(frames):
        _, m = _match(frames)
        return m.dist.sum() + m.idx.sum()

    def p_consensus(frames):
        k, m = _match(frames)
        key = jax.random.key(cfg.seed)
        keys = jax.vmap(
            lambda i: jax.random.fold_in(key, i)
        )(jnp.arange(frames.shape[0], dtype=jnp.uint32))
        # consensus_batch mirrors the production fused tail (PR 13):
        # batch-level (frames × hypotheses) blocks + the budget ladder,
        # so the prefix prices what the full program actually runs.
        from kcmc_tpu.ops.ransac import consensus_batch

        res = consensus_batch(
            tmodel, ref["xy"][m.idx], k.xy, m.valid, keys,
            n_hypotheses=cfg.n_hypotheses,
            threshold=cfg.inlier_threshold,
            refine_iters=cfg.refine_iters,
            score_cap=cfg.score_cap,
            budget_rungs=cfg.budget_rungs,
            early_exit_frac=cfg.early_exit_frac,
        )
        return res.transform

    fn_full = backend._get_batch_fn(shape)

    def p_full(frames):
        return fn_full(
            frames, ref["xy"], ref["desc"], ref["valid"], ref["frame"],
            jnp.arange(frames.shape[0], dtype=jnp.uint32),
        )

    stages = [
        ("detect", p_detect),
        ("describe", p_describe),
        ("match", p_match),
        ("consensus", p_consensus),
        ("full (+warp)", p_full),
    ]
    report: dict = {}
    prev = 0.0
    for name, fn in stages:
        t = honest_time(jax.jit(fn), frames, iters=iters) * 1000.0
        report[name] = {"cumulative_ms": round(t, 2), "incremental_ms": round(t - prev, 2)}
        prev = t
    report["frames_per_sec"] = round(batch_size / (prev / 1000.0), 1)
    return report
