"""Utilities: synthetic workloads, evaluation metrics, timing, checkpointing."""
