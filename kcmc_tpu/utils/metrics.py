"""Evaluation metrics and structured per-stage timing.

The judged metrics (BASELINE.md) are (a) frames/sec/chip throughput and
(b) transform-RMSE parity vs the CPU backend. Transform error is
measured in *pixels*: the RMS displacement discrepancy that an
estimated transform induces relative to ground truth, evaluated over a
grid of control points spanning the frame — this compares transforms of
any family (translation vs homography vs field) in common units.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

import numpy as np


def control_points(shape: tuple[int, ...], n_per_axis: int = 9) -> np.ndarray:
    """A uniform grid of control points spanning a (H, W) or (D, H, W) frame.

    Returns (N, d) points in (x, y[, z]) order, inset 10% from borders.
    """
    axes = [
        np.linspace(0.1 * (s - 1), 0.9 * (s - 1), n_per_axis, dtype=np.float32)
        for s in shape
    ]
    mesh = np.meshgrid(*axes, indexing="ij")
    # mesh is in index order (y, x) / (z, y, x); flip to (x, y[, z]).
    return np.stack([m.ravel() for m in reversed(mesh)], axis=-1)


def _apply_np(M: np.ndarray, pts: np.ndarray) -> np.ndarray:
    d = pts.shape[-1]
    lin = pts @ M[:d, :d].T + M[:d, d]
    w = pts @ M[d, :d] + M[d, d]
    return lin / np.where(np.abs(w) < 1e-8, 1e-8, w)[..., None]


def transform_rmse(
    est: np.ndarray, gt: np.ndarray, shape: tuple[int, ...], n_per_axis: int = 9
) -> float:
    """RMS control-point displacement error between two stacks of transforms.

    ``est``/``gt``: (T, d+1, d+1) homogeneous matrices mapping reference
    coords -> frame coords. Error per frame = RMS over control points of
    ||est(p) - gt(p)||; returns the RMS over all frames and points (px).
    """
    pts = control_points(shape, n_per_axis)
    errs = []
    for Me, Mg in zip(np.asarray(est), np.asarray(gt)):
        diff = _apply_np(Me, pts) - _apply_np(Mg, pts)
        errs.append(np.sum(diff * diff, axis=-1))
    return float(np.sqrt(np.mean(np.stack(errs))))


def relative_transforms(gt: np.ndarray, ref_index: int = 0) -> np.ndarray:
    """Ground truth re-expressed relative to the reference frame.

    The pipeline estimates maps from *reference frame* coordinates to
    each frame; synthetic ground truth maps from the undrifted scene.
    With frame r as reference, the expected estimate is
    gt_t @ inv(gt_r) — use this as the comparison target.
    """
    inv = np.linalg.inv(gt[ref_index])
    return np.stack([M @ inv for M in np.asarray(gt)])


def field_rmse(est: np.ndarray, gt: np.ndarray) -> float:
    """RMS endpoint error between (T, gh, gw, 2) displacement fields (px)."""
    diff = np.asarray(est, np.float64) - np.asarray(gt, np.float64)
    return float(np.sqrt(np.mean(np.sum(diff * diff, axis=-1))))


def crispness(stack: np.ndarray) -> float:
    """Crispness of a stack's MEAN image: the Frobenius norm of its
    gradient field, normalized by the mean image's own Frobenius norm.

    The standard stack-level motion-correction quality score
    (NoRMCorre-style): residual motion blurs the temporal mean, so a
    better-corrected stack has a sharper mean image and a HIGHER
    crispness. Unitless and scale-invariant (the normalization divides
    out contrast).

        before = crispness(stack)
        after = crispness(res.corrected)   # expect after > before

    `stack` is (T, H, W) or (T, D, H, W) — always a STACK with a
    leading frame axis (a bare mean image would be indistinguishable
    from a (T, H, W) stack by shape). Singleton spatial axes (e.g. a
    single-plane volume) contribute no gradient term.
    """
    stack = np.asarray(stack, np.float32)
    if stack.ndim not in (3, 4):
        raise ValueError(
            f"crispness expects a (T, H, W) or (T, D, H, W) stack, "
            f"got shape {stack.shape}"
        )
    mean = stack.mean(axis=0)
    g2 = np.zeros_like(mean)
    for axis in range(mean.ndim):
        if mean.shape[axis] >= 2:
            g2 = g2 + np.gradient(mean, axis=axis) ** 2
    denom = float(np.linalg.norm(mean.ravel()))
    return float(np.sqrt(g2.ravel().sum()) / max(denom, 1e-12))


@dataclasses.dataclass
class RobustnessReport:
    """Per-run recovery telemetry (utils/faults.py's consumer side).

    Counts every rung of the degradation ladder a run touched: IO and
    device retries, batches failed over to the numpy backend, frames
    marked failed (and later rescued by `interpolate_failed` trajectory
    interpolation), and checkpoint parts quarantined on resume.
    Surfaced as ``CorrectionResult.timing["robustness"]`` (and from
    there the CLI summary line and the ``--transforms`` npz), so an
    unattended multi-hour run leaves an audit trail of everything it
    survived.
    """

    io_retries: int = 0  # chunk-read attempts beyond the first
    device_retries: int = 0  # device-batch attempts beyond the first
    backend_failovers: int = 0  # batches re-run on the failover backend
    failed_frame_indices: list = dataclasses.field(default_factory=list)
    # frames recovered on the failover backend (per-frame attribution
    # for the observability layer's FrameRecord `failover` flag)
    failover_frame_indices: list = dataclasses.field(default_factory=list)
    rescued_frames: int = 0  # failed frames trajectory-interpolated
    quarantined_parts: list = dataclasses.field(default_factory=list)
    faults_injected: int = 0  # faults a FaultPlan actually fired
    # Serve-plane counters (kcmc_tpu/serve; docs/ROBUSTNESS.md
    # "Serve-plane failures"): per-session journal durability and the
    # idempotent-submit dedup — zero on one-shot runs.
    journal_saves: int = 0  # durable session-journal snapshots written
    journal_failures: int = 0  # journal writes that failed (advised)
    deduped_frames: int = 0  # replayed submit frames dropped by dedup
    resumed_from_frame: int = -1  # journal-resume cursor (-1 = fresh)

    @property
    def failed_frames(self) -> int:
        return len(self.failed_frame_indices)

    def any(self) -> bool:
        return bool(
            self.io_retries
            or self.device_retries
            or self.backend_failovers
            or self.failed_frame_indices
            or self.rescued_frames
            or self.quarantined_parts
            or self.faults_injected
            or self.journal_saves
            or self.journal_failures
            or self.deduped_frames
            or self.resumed_from_frame >= 0
        )

    def as_dict(self) -> dict:
        """JSON-serializable summary (the timing/CLI payload)."""
        out = {
            "io_retries": int(self.io_retries),
            "device_retries": int(self.device_retries),
            "backend_failovers": int(self.backend_failovers),
            "failover_frames": len(self.failover_frame_indices),
            "failed_frames": int(self.failed_frames),
            "rescued_frames": int(self.rescued_frames),
            "quarantined_parts": [str(p) for p in self.quarantined_parts],
            "faults_injected": int(self.faults_injected),
        }
        # Serve-only keys appear only when serving touched them — the
        # one-shot payload (and everything asserting on it) is unchanged.
        if self.journal_saves or self.journal_failures:
            out["journal_saves"] = int(self.journal_saves)
            out["journal_failures"] = int(self.journal_failures)
        if self.deduped_frames:
            out["deduped_frames"] = int(self.deduped_frames)
        if self.resumed_from_frame >= 0:
            out["resumed_from_frame"] = int(self.resumed_from_frame)
        return out


@dataclasses.dataclass
class StageTimer:
    """Structured per-stage wall-clock timing (SURVEY.md §5).

    Accumulates seconds per named stage across chunks; `report(n_frames)`
    yields the frames/sec/chip numbers the driver benchmarks.

    Beyond the coarse stages, the timer carries *stall* accounting for
    the streaming pipeline: time the CONSUMER thread spent blocked on a
    seam that should overlap with device compute — waiting on the
    prefetch thread (`prefetch_wait`), synchronizing device outputs at
    drain (`drain_sync`), backpressured by the background writer
    (`writer_backpressure`), flushing it for a checkpoint
    (`writer_flush`), or updating the rolling template at a segment
    boundary (`template_update`). Stalls are a subset of the stage time
    (they happen *inside* register_batches), reported separately as
    `stalls_s`/`stall_counts` so a throughput regression is attributable
    to a specific pipeline seam instead of a single opaque total.
    """

    totals: dict = dataclasses.field(default_factory=dict)
    counts: dict = dataclasses.field(default_factory=dict)
    stalls: dict = dataclasses.field(default_factory=dict)
    stall_counts: dict = dataclasses.field(default_factory=dict)
    # Optional obs.trace.Tracer: stage/stall intervals double as spans
    # in the exported Chrome trace. None (the default) costs one
    # attribute check per interval — observability off stays free.
    tracer: object = None

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            if self.tracer is not None:
                self.tracer.complete(name, t0, dt, cat="stage")

    @contextlib.contextmanager
    def stall(self, name: str):
        """Time one blocking wait on a pipeline seam (see class doc)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.stalls[name] = self.stalls.get(name, 0.0) + dt
            self.stall_counts[name] = self.stall_counts.get(name, 0) + 1
            if self.tracer is not None:
                self.tracer.complete(name, t0, dt, cat="stall")

    def add_stall(
        self, name: str, seconds: float, count: int = 1, trace: bool = True
    ) -> None:
        """Accumulate stall seconds measured elsewhere. With `trace`
        (the default, for callers reporting a wait that JUST ended) an
        attached tracer gets a span back-dated to end now; pass
        trace=False for end-of-run aggregates whose individual waits
        were already traced at source (e.g. the background writer's
        own backpressure/flush spans) — a back-dated total would
        double-count them and park a bogus stall block at run end."""
        self.stalls[name] = self.stalls.get(name, 0.0) + float(seconds)
        self.stall_counts[name] = self.stall_counts.get(name, 0) + count
        if trace and self.tracer is not None and seconds > 0:
            self.tracer.complete(
                name, time.perf_counter() - float(seconds), float(seconds),
                cat="stall",
            )

    @property
    def total_seconds(self) -> float:
        return sum(self.totals.values())

    def report(self, n_frames: int | None = None) -> dict:
        out = {
            "stages_s": dict(self.totals),
            # stage_counts/stage_mean_s: `counts` is accumulated per
            # stage() entry but was never reported — a stage dominated
            # by many cheap entries vs few expensive ones is a
            # different problem, and only the pair disambiguates.
            "stage_counts": dict(self.counts),
            "stage_mean_s": {
                k: v / self.counts[k]
                for k, v in self.totals.items()
                if self.counts.get(k)
            },
            "total_s": self.total_seconds,
        }
        if self.stalls:
            out["stalls_s"] = dict(self.stalls)
            out["stall_counts"] = dict(self.stall_counts)
        if n_frames and self.total_seconds > 0:
            out["frames_per_sec"] = n_frames / self.total_seconds
        return out
